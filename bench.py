"""Benchmark: the BASELINE.json matrix on the batched TPU solver.

Flagship (the driver metric): 10k-pending-pod / 5k-node churn burst —
target < 1 s wall-clock (>= 10k pods/s). Prints exactly ONE JSON line:
``{"metric": ..., "value": pods_per_sec, "unit": "pods/s",
"vs_baseline": pods_per_sec / 10000, "matrix": {...}}`` where ``matrix``
carries the BASELINE comparison configs #1-#5:

1. NodeResourcesFit LeastAllocated, 100 pods / 20 nodes (+ host-oracle
   python reference on the same config -> speedup);
2. LoadAware mixed LS/BE, 2k pods / 500 nodes (usage + thresholds live);
3. ElasticQuota, 5k pods / 50 groups / 1k nodes (water-filled runtime +
   admission fused into the solve);
4. Coscheduling, 200 gangs x 32 pods, all-or-nothing at batch end;
5. Descheduler LoadAware rebalance sweep, 5k nodes / 30k pods.

State is device-resident; the timed section is solve + assignments
readback (what a scheduling round costs). Pod-shape bucketing
(models/placement.py pod_bucket) amortizes compiles across queue sizes.

Env knobs: KTPU_BENCH_NODES, KTPU_BENCH_PODS, KTPU_BENCH_REPEATS,
KTPU_BENCH_MATRIX=0 to skip the matrix (flagship only).
"""

import json
import os
import sys
import time

import numpy as np


def _timed(fn, repeats, *args):
    """(best seconds, warmup seconds, last output) with readback forced
    each run; the first (compile) call is timed separately as warmup."""
    t0 = time.time()
    out = fn(*args)
    _ = np.asarray(out[1] if isinstance(out, tuple) else out)
    warmup = time.time() - t0
    times = []
    for _i in range(repeats):
        t0 = time.time()
        out = fn(*args)
        _ = np.asarray(out[1] if isinstance(out, tuple) else out)
        times.append(time.time() - t0)
    return min(times), warmup, out


def _problem(n_nodes, n_pods, seed=1):
    from __graft_entry__ import _example_problem

    return _example_problem(n_nodes, n_pods, seed=seed)


def bench_flagship(repeats):
    import jax

    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
    from koordinator_tpu.parallel.mesh import (
        make_mesh,
        shard_node_state,
        shard_solver,
    )

    n_nodes = int(os.environ.get("KTPU_BENCH_NODES", 5000))
    n_pods = int(os.environ.get("KTPU_BENCH_PODS", 10000))
    state, pods, params = _problem(n_nodes, n_pods)

    devices = jax.devices()
    solver_name = "scan"
    if len(devices) > 1:
        mesh = make_mesh(devices)
        state = shard_node_state(state, mesh)
        solve = shard_solver(mesh)
    else:
        solve = jax.jit(
            lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig())
        )

    best, warmup, out = _timed(solve, repeats, state, pods, params)
    scan_pods_per_sec = n_pods / best
    win_fn = solve

    if (
        len(devices) == 1
        and devices[0].platform == "tpu"  # interpret mode can't win
        and os.environ.get("KTPU_BENCH_PALLAS", "1") != "0"
    ):
        # the VMEM-resident pallas kernel (single-chip): keep whichever
        # path wins; results are bit-identical (tests/test_pallas.py)
        try:
            from koordinator_tpu.ops.pallas_binpack import (
                pallas_schedule_batch,
                pallas_supported,
            )

            pallas_fn = lambda s, p, pr: pallas_schedule_batch(
                s, p, pr, SolverConfig()
            )
            if pallas_supported(params, SolverConfig()):
                p_best, p_warm, p_out = _timed(
                    pallas_fn, repeats, state, pods, params,
                )
                identical = bool(
                    (np.asarray(p_out[1]) == np.asarray(out[1])).all()
                ) and all(
                    bool((np.asarray(a) == np.asarray(b)).all())
                    for a, b in zip(p_out[0], out[0])
                )
                if not identical:
                    # a hardware divergence from the scan is a kernel bug
                    # and must be loud, not silently discarded
                    print(
                        "WARNING: pallas kernel diverged from the scan on "
                        "hardware — using the scan result",
                        file=sys.stderr,
                    )
                elif p_best < best:
                    best, warmup, out = p_best, warmup + p_warm, p_out
                    solver_name = "pallas"
                    win_fn = pallas_fn
        except Exception as e:  # kernel unavailable: keep the scan, say so
            print(f"pallas path skipped: {type(e).__name__}: {e}",
                  file=sys.stderr)

    # p99 round latency (the BASELINE metric pairs pods/s with p99
    # schedule latency): interpolated over 20+ timed rounds (fewer would
    # make "p99" just the single worst sample)
    lat_rounds = max(20, repeats)
    lats = []
    for _i in range(lat_rounds):
        t0 = time.time()
        o = win_fn(state, pods, params)
        _ = np.asarray(o[1])
        lats.append(time.time() - t0)
    p99_s = float(np.percentile(lats, 99))

    assignments = np.asarray(out[1])
    scheduled = int((assignments >= 0).sum())
    return {
        "pods_per_sec": n_pods / best,
        "scan_pods_per_sec": scan_pods_per_sec,
        "solver": solver_name,
        "p99_round_s": p99_s,
        "wall_s": best,
        "scheduled": scheduled,
        "n_nodes": n_nodes,
        "n_pods": n_pods,
        "warmup_s": warmup,
        "devices": f"{len(devices)}x{devices[0].platform}",
    }


def bench_fit_with_oracle(repeats, n_nodes=20, n_pods=100):
    """Config #1 on device AND through the pure-python host oracle — the
    measured host-oracle speedup + bit-identity check. At the 100x20
    scale a single host<->device round trip dominates; the 500x200
    variant shows the crossover."""
    import jax

    from koordinator_tpu.oracle.placement import schedule_sequential
    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch

    state, pods, params = _problem(n_nodes, n_pods)
    solve = jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig()))
    best, _warm, out = _timed(solve, repeats, state, pods, params)

    args = (
        np.asarray(state.alloc), np.asarray(state.used_req),
        np.asarray(state.usage), np.asarray(state.prod_usage),
        np.asarray(state.est_extra), np.asarray(state.prod_base),
        np.asarray(state.metric_fresh), np.asarray(state.schedulable),
        np.asarray(pods.req), np.asarray(pods.est),
        np.asarray(pods.is_prod), np.asarray(pods.is_daemonset),
        np.asarray(params.weights), np.asarray(params.thresholds),
        np.asarray(params.prod_thresholds),
    )
    t0 = time.time()
    oracle = schedule_sequential(*args)
    oracle_s = time.time() - t0
    identical = bool((np.asarray(out[1]) == np.asarray(oracle)).all())
    return {
        "pods_per_sec": n_pods / best,
        "oracle_pods_per_sec": n_pods / oracle_s,
        "speedup_vs_host_oracle": oracle_s / best,
        "identical_to_oracle": identical,
    }


def bench_loadaware(repeats):
    import jax

    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch

    state, pods, params = _problem(500, 2000, seed=2)
    solve = jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig()))
    best, _warm, _out = _timed(solve, repeats, state, pods, params)
    return {"pods_per_sec": 2000 / best, "wall_s": best}


def bench_quota(repeats):
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
    from koordinator_tpu.ops.quota import QuotaState

    n_nodes, n_pods, n_quota = 1000, 5000, 50
    state, pods, params = _problem(n_nodes, n_pods, seed=3)
    rng = np.random.default_rng(3)
    quota_id = rng.integers(0, n_quota, n_pods).astype(np.int32)
    pods = pods._replace(quota_id=jnp.asarray(quota_id))
    total = np.asarray(state.alloc).astype(np.int64).sum(axis=0)
    mn = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mx = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mn[:, ResourceName.CPU] = total[ResourceName.CPU] // (2 * n_quota)
    mn[:, ResourceName.MEMORY] = total[ResourceName.MEMORY] // (2 * n_quota)
    mx[:, ResourceName.CPU] = total[ResourceName.CPU] // 10
    mx[:, ResourceName.MEMORY] = total[ResourceName.MEMORY] // 10
    req = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    pods_req = np.asarray(pods.req).astype(np.int64)
    for q in range(n_quota):
        req[q] = pods_req[quota_id == q].sum(axis=0)
    qstate = QuotaState.build(
        min=mn, max=mx, weight=mx, allow_lent=np.ones(n_quota, bool),
        total=total, child_request=req,
    )
    solve = jax.jit(
        lambda s, p, pr, q: schedule_batch(s, p, pr, SolverConfig(), q)[1]
    )
    best, _warm, out = _timed(lambda *a: solve(*a), repeats,
                              state, pods, params, qstate)
    placed = int((np.asarray(out) >= 0).sum())
    return {"pods_per_sec": n_pods / best, "wall_s": best, "placed": placed}


def bench_gang(repeats):
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
    from koordinator_tpu.ops.gang import GangState

    n_gangs, size = 200, 32
    n_pods = n_gangs * size
    n_nodes = 1600
    state, pods, params = _problem(n_nodes, n_pods, seed=4)
    gang_id = np.repeat(np.arange(n_gangs, dtype=np.int32), size)
    pods = pods._replace(gang_id=jnp.asarray(gang_id))
    gstate = GangState.build(min_member=[size] * n_gangs)
    solve = jax.jit(
        lambda s, p, pr, g: schedule_batch(s, p, pr, SolverConfig(), None, g)[1]
    )
    best, _warm, out = _timed(lambda *a: solve(*a), repeats,
                              state, pods, params, gstate)
    committed = int(np.asarray(out[1]).sum())
    return {
        "pods_per_sec": n_pods / best,
        "wall_s": best,
        "committed": committed,
        "gangs": n_gangs,
    }


def bench_rebalance(repeats):
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.ops.rebalance import classify_nodes

    n_nodes, n_pods = 5000, 30000
    rng = np.random.default_rng(5)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, ResourceName.CPU] = 64000
    alloc[:, ResourceName.MEMORY] = 131072
    # 30k pods' usage folded onto nodes, skewed (squared uniform) so a
    # tail of nodes actually crosses the high threshold
    pod_node = (rng.random(n_pods) ** 2 * n_nodes).astype(np.int64)
    pod_cpu = rng.integers(200, 4000, n_pods)
    usage = np.zeros((n_nodes, NUM_RESOURCES), np.int64)
    np.add.at(usage[:, ResourceName.CPU], pod_node, pod_cpu)
    usage = np.minimum(usage, alloc).astype(np.int32)
    low = np.full(NUM_RESOURCES, -1, np.int32)
    high = np.full(NUM_RESOURCES, -1, np.int32)
    low[ResourceName.CPU] = 45
    high[ResourceName.CPU] = 65
    active = jnp.asarray(np.ones(n_nodes, bool))
    fn = jax.jit(
        lambda u, a: classify_nodes(
            u, a, jnp.asarray(low), jnp.asarray(high), active, active
        ).high
    )
    best, _warm, out = _timed(lambda *a: fn(*a), repeats,
                              jnp.asarray(usage), jnp.asarray(alloc))
    return {
        "sweeps_per_sec": 1.0 / best,
        "wall_ms": best * 1000,
        "nodes": n_nodes,
        "pods": n_pods,
        "overloaded": int(np.asarray(out).sum()),
    }


def main():
    repeats = max(1, int(os.environ.get("KTPU_BENCH_REPEATS", 3)))
    flagship = bench_flagship(repeats)

    matrix = {}
    if os.environ.get("KTPU_BENCH_MATRIX", "1") != "0":
        matrix["1_fit_100x20"] = bench_fit_with_oracle(repeats)
        matrix["1b_fit_500x200"] = bench_fit_with_oracle(
            repeats, n_nodes=200, n_pods=500
        )
        matrix["2_loadaware_2kx500"] = bench_loadaware(repeats)
        matrix["3_quota_5k_50q_1k"] = bench_quota(repeats)
        matrix["4_gang_200x32"] = bench_gang(repeats)
        matrix["5_rebalance_5kx30k"] = bench_rebalance(repeats)

    def _round(obj):
        if isinstance(obj, dict):
            return {k: _round(v) for k, v in obj.items()}
        if isinstance(obj, float):
            return round(obj, 3)
        return obj

    pods_per_sec = flagship["pods_per_sec"]
    result = {
        "metric": (
            f"batched placement churn ({flagship['n_pods']} pods / "
            f"{flagship['n_nodes']} nodes, {flagship['scheduled']} placed, "
            f"{flagship['devices']}, {flagship['solver']} solver, "
            f"warmup {flagship['warmup_s']:.1f}s)"
            + (" + BASELINE matrix configs 1-5" if matrix else "")
        ),
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / 10000.0, 3),
        "solver": flagship["solver"],
        "scan_pods_per_sec": round(flagship["scan_pods_per_sec"], 1),
        "p99_round_s": round(flagship["p99_round_s"], 4),
        "matrix": _round(matrix),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
