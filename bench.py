"""Benchmark: the BASELINE.json matrix on the batched TPU solver.

Flagship (the driver metric): 10k-pending-pod / 5k-node churn burst —
target < 1 s wall-clock (>= 10k pods/s). Prints exactly ONE JSON line:
``{"metric": ..., "value": pods_per_sec, "unit": "pods/s",
"vs_baseline": pods_per_sec / 10000, "matrix": {...}}`` where ``matrix``
carries the BASELINE comparison configs #1-#5. Every matrix entry
reports ``{pods_per_sec, p99_s, identical_to_oracle}``:

1. NodeResourcesFit LeastAllocated, 100 pods / 20 nodes — production
   routing (the PlacementModel host-fallback cutoff) runs this on the
   host sequential path, so the entry reports the host numbers plus the
   device-vs-oracle identity;
2. LoadAware mixed LS/BE, 2k pods / 500 nodes (usage + thresholds live);
3. ElasticQuota, 5k pods / 50 groups / 1k nodes — the in-kernel quota
   gate (pallas) vs the scan, winner kept, bit-identity enforced;
4. Coscheduling, 200 gangs x 32 pods — kernel scan + batch-end gang
   resolution vs the scan solver, winner kept, bit-identity enforced;
5. Descheduler LoadAware rebalance sweep, 5k nodes / 30k pods, checked
   against a numpy re-derivation;
6. (extra) NUMA-policy cluster, 3k pods x 1.5k nodes — in-kernel NUMA
   scoring/consumption vs the scan, bit-identity enforced;
7. (extra) 16k-node flagship leg — past the old 8192-node kernel cap
   (the packed argmax now carries the lane in 16 bits), kernel vs scan
   winner-kept with bit-identity;
8. (extra) full-features flagship leg — quota + strict gangs + NUMA +
   reservations fused in one 5k x 10k solve, oracle-identical on every
   mutated carry;
9. (extra) steady-state churn ticks — a 5k-node typed snapshot under a
   50-dirty-row/tick mutation stream, scheduled through the
   delta-staging path (ClusterDeltaTracker + StagedStateCache) vs full
   restage, tick-for-tick identical, with lower/stage/solve walls
   broken out (every other leg records the same breakdown);
11. (extra) outage-failover churn — a sidecar-backed churn run with the
   sidecar SIGKILLed mid-run under the supervised-restart + failover
   stack: ticks-to-first-degraded-solve, degraded-tick count, recovery
   wall from kill to the first post-restart remote solve, and
   tick-identical final state vs the in-process fault-free run
   (KTPU_BENCH_OUTAGE_NODES / _DIRTY / _TICKS reshape it);
14. (extra) sharded churn at 50k nodes — the node axis split 8 ways
   (2-D mesh, sharded delta staging: dirty rows scattered into their
   owning shard of a live NamedSharding'd world), tick-identical to
   the single-device run, with the full-re-shard and merge-overhead
   ratios recorded (runs in a virtual-CPU-forced child via ``--leg``);
15. (extra) shard scaling curve — one giant pod burst (32 independent
   lanes x 256 pods on a shared base) at 1/2/4/8 lane shards;
   acceptance >= 2x pods/s at 8 shards vs 1, every lane bit-identical
   to a solo single-device solve AND the host oracle, plus the
   node-axis merge-overhead ratio at the same shape;
16. (extra) multi-tenant solver pool — 16 tenant front-ends
   delta-churning separate worlds through ONE shared sidecar
   (cross-tenant lane batching, service/tenancy.py) vs 16 solo
   sidecars at equal device count: aggregate pods/s (acceptance >= 2x,
   plus the ISSUE-named ``fleet8`` 8-vs-8 checkpoint), per-tenant
   submit->bind p50/p99, device occupancy, per-tenant bit-identity to
   the solo run, and an unfair-arrival storm whose shed lands only on
   the flooding tenant (KTPU_BENCH_TENANTS / _TENANT_NODES /
   _TENANT_PODS reshape it); 14b additionally records leg 14's
   100k-node single-domain point (KTPU_BENCH_SHARD_100K=0 skips it);
plus a ``sharded`` entry: multi-device solve throughput when >1 device
is attached — the sharded PALLAS kernel (per-shard VMEM carry,
in-kernel per-pod cross-shard winner merge) vs the GSPMD scan, winner
kept with bit-identity — else the 8-device virtual-CPU dryrun, which
now records the driver's MACHINE verdict (rc + typed reason + the
MULTICHIP host-fingerprint-cache preflight) instead of grepping
stdout; its ``ok`` certifies sharded==single-device bit-identity at a
non-toy full-feature shape.

Kernel-vs-scan crossover (measured r4, one v5e chip, 3-5 reps): the
kernel wins every gang shape tried (400-6400 nodes, 1.1-1.6x) and every
NUMA shape except 1.5k nodes where the two are within the +-15%
run-to-run tunnel variance (kernel won 2 of 3 trials); at 16k nodes the
kernel is ~2x the scan. The per-config winner-keep below therefore IS
the dispatch policy, re-measured every run.

Oracle identity for the flagship and configs 2-4 and 6-8 runs at the
FULL config shape through the vectorized host oracle
(oracle/vectorized.py — the sequential reference semantics with the
node loop vectorized in int64 numpy; its own authority is the
differential sweep against the scalar transliteration in
tests/test_oracle_vectorized.py, plus the feature differentials in
tests/test_oracle_full_features.py). Config 5's check is the
independent scalar transliteration of the complete reference Balance
sweep (oracle/rebalance.py) — the ORDERED eviction sequence must match.
No reduced-shape extrapolation and no self-consistency-only entry
remains.

Env knobs: KTPU_BENCH_NODES, KTPU_BENCH_PODS, KTPU_BENCH_REPEATS,
KTPU_BENCH_MATRIX=0 to skip the matrix (flagship only),
KTPU_BENCH_SHARDED=0 to skip the sharded/dryrun entry,
KTPU_BENCH_PALLAS=0 to disable the pallas kernel legs (scan only),
KTPU_BENCH_ORACLE=0 to skip the full-shape oracle identity legs,
KTPU_BENCH_CHURN_NODES / _CHURN_DIRTY / _CHURN_TICKS to reshape the
churn-tick leg, KTPU_BENCH_SHARD_NODES / _SHARD_COUNT / _SHARD_DIRTY /
_SHARD_PENDING for the sharded churn leg, KTPU_BENCH_LANE_NODES /
_LANE_PODS / _LANE_COUNT for the shard scaling curve, and
KTPU_BENCH_STORM=0 to skip the preemption-storm leg (#19) —
KTPU_BENCH_STORM_NODES / _RPN / _ARRIVALS / _ORACLE_PODS /
_PLACE / _DRAIN_S reshape it (see bench_preemption_storm),
KTPU_BENCH_SLO=0 to skip the closed-loop SLO-convergence leg (#20) —
KTPU_BENCH_SLO_NODES / _SECONDS / _RATE / _TARGET reshape it
(see bench_slo_convergence), KTPU_BENCH_DENSITY=0 to skip the
tenant-density degradation leg (#21) — KTPU_BENCH_DENSITY_TENANTS /
_NODES / _PODS / _ROUNDS reshape it (see bench_tenant_density) — and
KTPU_BENCH_REBALANCE=0 to skip the rebalance-storm leg (#22) —
KTPU_BENCH_REBALANCE_NODES / _PPN reshape it (see
bench_rebalance_storm).
"""

import json
import math
import os
import subprocess
import sys
import tempfile
import time

import numpy as np


#: scan legs run at the measured-optimum unroll (SolverConfig note:
#: 32 is ~19% over the library default 8 on v5e; compile-time cost is
#: irrelevant here since warmup is excluded from the timed reps)
BENCH_UNROLL = 32


def _obs_jit(name, fn):
    """Route a bench-local jit through the device observatory so the
    leg's fingerprint carries real FLOPs/bytes/peak numbers (the legs
    driving PlacementModel/Scheduler are instrumented in-tree)."""
    from koordinator_tpu.obs.device import DEVICE_OBS

    return DEVICE_OBS.jit(name, fn)


def _timed(fn, repeats, *args):
    """(best seconds, warmup seconds, last output) with readback forced
    each run; the first (compile) call is timed separately as warmup."""
    t0 = time.time()
    out = fn(*args)
    _ = np.asarray(out[1] if isinstance(out, tuple) else out)
    warmup = time.time() - t0
    times = []
    for _i in range(repeats):
        t0 = time.time()
        out = fn(*args)
        _ = np.asarray(out[1] if isinstance(out, tuple) else out)
        times.append(time.time() - t0)
    return min(times), warmup, out


def _lat_stats(fn, args, rounds):
    """(best_s, p99_s) over >= 100 timed rounds: with fewer samples
    np.percentile(.., 99) interpolates at/above the second-worst sample,
    so a single tunnel hiccup still set "p99"; at 100 rounds the
    estimate sits below the worst sample."""
    lats = []
    for _i in range(rounds):
        t0 = time.time()
        out = fn(*args)
        _ = np.asarray(out[1] if isinstance(out, tuple) else out)
        lats.append(time.time() - t0)
    return float(min(lats)), float(np.percentile(lats, 99))


def _p99(fn, args, rounds):
    return _lat_stats(fn, args, rounds)[1]


#: host-build + staging walls of the most recent _problem call — every
#: leg folds these into its JSON as lower_s/stage_s beside its solve_s,
#: so staging-path wins are visible in the bench trajectory
_LAST_PROBLEM_TIMES = {"lower_s": 0.0, "stage_s": 0.0}


def _problem(n_nodes, n_pods, seed=1):
    import jax

    from __graft_entry__ import _example_problem

    t0 = time.time()
    state, pods, params = _example_problem(n_nodes, n_pods, seed=seed)
    t1 = time.time()
    jax.block_until_ready((state, pods, params))
    _LAST_PROBLEM_TIMES["lower_s"] = t1 - t0
    _LAST_PROBLEM_TIMES["stage_s"] = time.time() - t1
    return state, pods, params


def _leg_times(solve_s, lower_s=None, stage_s=None):
    """The per-leg wall breakdown every matrix entry reports."""
    return {
        "lower_s": _LAST_PROBLEM_TIMES["lower_s"] if lower_s is None
        else lower_s,
        "stage_s": _LAST_PROBLEM_TIMES["stage_s"] if stage_s is None
        else stage_s,
        "solve_s": solve_s,
    }


def _oracle_args(state, pods, params):
    from koordinator_tpu.oracle.vectorized import oracle_args

    return oracle_args(state, pods, params)


def _oracle_enabled():
    return os.environ.get("KTPU_BENCH_ORACLE", "1") != "0"


def bench_flagship(repeats):
    import jax

    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
    from koordinator_tpu.parallel.mesh import (
        make_mesh,
        shard_node_state,
        shard_solver,
    )

    n_nodes = int(os.environ.get("KTPU_BENCH_NODES", 5000))
    n_pods = int(os.environ.get("KTPU_BENCH_PODS", 10000))
    state, pods, params = _problem(n_nodes, n_pods)

    config = SolverConfig(unroll=BENCH_UNROLL)
    devices = jax.devices()
    if len(devices) > 1:
        mesh = make_mesh(devices)
        state = shard_node_state(state, mesh)
        solve = shard_solver(mesh, config)
    else:
        solve = _obs_jit("bench_flagship_scan", jax.jit(
            lambda s, p, pr: schedule_batch(s, p, pr, config)
        ))

    # the VMEM-resident pallas kernel leg runs single-chip on tpu only;
    # results must be bit-identical to the scan (tests/test_pallas.py).
    # Guard the import too: kernel unavailability must fall back to the
    # scan with a note, never abort the flagship bench.
    pallas_fn = None
    if (len(devices) == 1 and devices[0].platform == "tpu"
            and os.environ.get("KTPU_BENCH_PALLAS", "1") != "0"):
        try:
            from koordinator_tpu.ops.pallas_binpack import (
                pallas_schedule_batch,
                pallas_supported,
            )

            if pallas_supported(params, config):
                pallas_fn = lambda s, p, pr: pallas_schedule_batch(
                    s, p, pr, config
                )
        except Exception as e:
            print(f"pallas path skipped: {type(e).__name__}: {e}",
                  file=sys.stderr)

    def cmp_state_and_assign(a, b):
        return bool(
            (np.asarray(a[1]) == np.asarray(b[1])).all()
        ) and all(
            bool((np.asarray(x) == np.asarray(y)).all())
            for x, y in zip(a[0], b[0])
        )

    best, warmup, out, solver_name, win_fn, scan_best, _kvs = _pick_kernel_or_scan(
        solve, pallas_fn, repeats, (state, pods, params), cmp_state_and_assign
    )
    scan_pods_per_sec = n_pods / scan_best
    p99_s = _p99(win_fn, (state, pods, params), max(100, repeats))

    assignments = np.asarray(out[1])
    scheduled = int((assignments >= 0).sum())
    result = {
        "pods_per_sec": n_pods / best,
        "scan_pods_per_sec": scan_pods_per_sec,
        "solver": solver_name,
        "p99_round_s": p99_s,
        "wall_s": best,
        **_leg_times(best),
        "scheduled": scheduled,
        "n_nodes": n_nodes,
        "n_pods": n_pods,
        "warmup_s": warmup,
        "devices": f"{len(devices)}x{devices[0].platform}",
    }
    if _oracle_enabled():
        from koordinator_tpu.oracle.vectorized import schedule_vectorized

        t0 = time.time()
        oracle = schedule_vectorized(*_oracle_args(state, pods, params))
        result["oracle_wall_s"] = time.time() - t0
        result["identical_to_oracle"] = bool((assignments == oracle).all())
    return result


def _host_fallback_cells():
    """The production cutoff — MEASURED on this backend/link, exactly as
    the component config default (-1 = probe) resolves it at scheduler
    startup (VERDICT r4 weak #6: the cutoff used to be a hand-set
    constant)."""
    from koordinator_tpu.cmd.scheduler import SchedulerConfig
    from koordinator_tpu.models.placement import (
        measure_host_fallback_cells,
    )
    from koordinator_tpu.ops.binpack import SolverConfig

    configured = SchedulerConfig().host_fallback_cells
    if configured >= 0:
        return configured
    return measure_host_fallback_cells(SolverConfig(unroll=BENCH_UNROLL))


def bench_fit_with_oracle(repeats, n_nodes=20, n_pods=100):
    """Config #1 on device AND through the pure-python host oracle. At
    100x20 a single host<->device round trip dominates, so production
    (PlacementModel.host_fallback_cells) routes this shape to the host —
    the reported pods/s is the routed path's; identity is device==host."""
    import jax

    from koordinator_tpu.oracle.placement import schedule_sequential
    from koordinator_tpu.oracle.vectorized import schedule_vectorized
    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch

    state, pods, params = _problem(n_nodes, n_pods)
    solve = _obs_jit("bench_scan_small", jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig(unroll=BENCH_UNROLL))))
    best, _warm, out = _timed(solve, repeats, state, pods, params)

    args = _oracle_args(state, pods, params)
    t0 = time.time()
    oracle = schedule_sequential(*args)
    oracle_s = time.time() - t0
    identical = bool((np.asarray(out[1]) == np.asarray(oracle)).all())
    # the model's routing predicate uses the BUCKETED pod count
    # (models/placement.py _dispatch_solve after _pad_pods)
    from koordinator_tpu.models.placement import PlacementModel

    routed_host = (
        n_nodes * PlacementModel.pod_bucket(n_pods) <= _host_fallback_cells()
    )
    if routed_host:
        # the production host path runs the class-cached vectorized
        # oracle (models/placement.py _host_solve), not the scalar
        # transliteration — time what production actually runs
        routed_best, p99_s = _lat_stats(
            lambda *a: np.asarray(schedule_vectorized(*a)),
            args, max(100, repeats),
        )
    else:
        routed_best, p99_s = best, _p99(
            solve, (state, pods, params), max(100, repeats)
        )
    return {
        "pods_per_sec": n_pods / routed_best,
        "p99_s": p99_s,
        **_leg_times(routed_best),
        "identical_to_oracle": identical,
        "solver": "host" if routed_host else "device",
        "device_pods_per_sec": n_pods / best,
        "oracle_pods_per_sec": n_pods / oracle_s,
        "speedup_vs_host_oracle": oracle_s / routed_best,
        "fallback_cells_measured": _host_fallback_cells(),
    }


def bench_loadaware(repeats):
    import jax

    from koordinator_tpu.oracle.vectorized import schedule_vectorized
    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch

    state, pods, params = _problem(500, 2000, seed=2)
    solve = _obs_jit("bench_loadaware_scan", jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig(unroll=BENCH_UNROLL))))
    best, _warm, out = _timed(solve, repeats, state, pods, params)
    p99_s = _p99(solve, (state, pods, params), max(100, repeats))

    result = {
        "pods_per_sec": 2000 / best,
        "p99_s": p99_s,
        "wall_s": best,
        **_leg_times(best),
    }
    if _oracle_enabled():
        # full-shape identity through the vectorized host oracle
        oracle = schedule_vectorized(*_oracle_args(state, pods, params))
        result["identical_to_oracle"] = bool(
            (np.asarray(out[1]) == oracle).all()
        )
        result["oracle_check_shape"] = "full"
    return result


def _quota_problem(n_nodes, n_pods, n_quota, seed):
    import jax.numpy as jnp

    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.ops.quota import QuotaState

    state, pods, params = _problem(n_nodes, n_pods, seed=seed)
    rng = np.random.default_rng(seed)
    quota_id = rng.integers(0, n_quota, n_pods).astype(np.int32)
    pods = pods._replace(quota_id=jnp.asarray(quota_id))
    total = np.asarray(state.alloc).astype(np.int64).sum(axis=0)
    mn = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mx = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mn[:, ResourceName.CPU] = total[ResourceName.CPU] // (2 * n_quota)
    mn[:, ResourceName.MEMORY] = total[ResourceName.MEMORY] // (2 * n_quota)
    mx[:, ResourceName.CPU] = total[ResourceName.CPU] // 10
    mx[:, ResourceName.MEMORY] = total[ResourceName.MEMORY] // 10
    req = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    pods_req = np.asarray(pods.req).astype(np.int64)
    for q in range(n_quota):
        req[q] = pods_req[quota_id == q].sum(axis=0)
    qstate = QuotaState.build(
        min=mn, max=mx, weight=mx, allow_lent=np.ones(n_quota, bool),
        total=total, child_request=req,
    )
    return state, pods, params, qstate, quota_id


def _pick_kernel_or_scan(scan_fn, kernel_fn, repeats, args, compare):
    """Time both paths, enforce bit-identity, keep the winner — THE
    selection policy, shared by the flagship and the matrix configs.
    ``kernel_fn=None`` skips the kernel leg (unsupported shape/config).
    Returns (best_s, warmup_s_total, out, solver_name, win_fn,
    scan_best_s, kernel_vs_scan) where kernel_vs_scan is "identical",
    "DIVERGED", or "not_run" (kernel leg never executed)."""
    import jax

    best, warm, out = _timed(scan_fn, repeats, *args)
    scan_best = best
    name = "scan"
    win = scan_fn
    kernel_vs_scan = "not_run"
    if (kernel_fn is not None
            and jax.devices()[0].platform == "tpu"  # interpret can't win
            and os.environ.get("KTPU_BENCH_PALLAS", "1") != "0"):
        try:
            k_best, k_warm, k_out = _timed(kernel_fn, repeats, *args)
            warm += k_warm
            if not compare(out, k_out):
                # a hardware divergence from the scan is a kernel bug
                # and must be loud, not silently discarded
                kernel_vs_scan = "DIVERGED"
                print("WARNING: pallas kernel diverged from the scan on "
                      "hardware — using the scan result", file=sys.stderr)
            else:
                kernel_vs_scan = "identical"
                if k_best < best:
                    best, out, name, win = k_best, k_out, "pallas", kernel_fn
        except Exception as e:
            print(f"pallas path skipped: {type(e).__name__}: {e}",
                  file=sys.stderr)
    return best, warm, out, name, win, scan_best, kernel_vs_scan


def _cmp_tuple(a, b):
    """Elementwise bit-identity over two output tuples."""
    return all(bool((np.asarray(x) == np.asarray(y)).all())
               for x, y in zip(a, b))


def bench_quota(repeats):
    import jax

    from koordinator_tpu.oracle.vectorized import (
        VectorQuota,
        schedule_vectorized,
    )
    from koordinator_tpu.ops.binpack import SolverConfig, solve_batch
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    n_nodes, n_pods, n_quota = 1000, 5000, 50
    state, pods, params, qstate, qid = _quota_problem(
        n_nodes, n_pods, n_quota, seed=3
    )
    config = SolverConfig(unroll=BENCH_UNROLL)
    scan = _obs_jit("bench_quota_scan", jax.jit(
        lambda s, p, pr, q: solve_batch(s, p, pr, config, q).assign))
    kern = lambda s, p, pr, q: pallas_solve_batch(s, p, pr, config, q).assign
    cmp_assign = lambda a, b: bool((np.asarray(a) == np.asarray(b)).all())
    best, _warm, out, solver, win, _scan_best, _kvs = _pick_kernel_or_scan(
        scan, kern, repeats, (state, pods, params, qstate), cmp_assign
    )
    p99_s = _p99(win, (state, pods, params, qstate), max(100, repeats))
    placed = int((np.asarray(out) >= 0).sum())

    result = {
        "pods_per_sec": n_pods / best,
        "p99_s": p99_s,
        "solver": solver,
        "wall_s": best,
        "placed": placed,
        **_leg_times(best),
    }
    if _oracle_enabled():
        # full-shape oracle identity (full quota semantics incl. admission);
        # VectorQuota is built from the device QuotaState's own normalized
        # arrays so both paths see identical preconditions
        vq = VectorQuota(
            np.asarray(qstate.min), np.asarray(qstate.max),
            np.asarray(qstate.auto_min), np.asarray(qstate.weight),
            np.asarray(qstate.allow_lent), np.asarray(qstate.total),
        )
        oracle = schedule_vectorized(
            *_oracle_args(state, pods, params),
            pod_quota_id=qid,
            pod_non_preemptible=np.asarray(pods.non_preemptible),
            quota=vq,
        )
        result["identical_to_oracle"] = bool((np.asarray(out) == oracle).all())
        result["oracle_check_shape"] = "full"
    return result


def bench_gang(repeats):
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.ops.binpack import SolverConfig, solve_batch
    from koordinator_tpu.ops.gang import GangState
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    n_gangs, size = 200, 32
    n_pods = n_gangs * size
    n_nodes = 1600
    state, pods, params = _problem(n_nodes, n_pods, seed=4)
    gang_id = np.repeat(np.arange(n_gangs, dtype=np.int32), size)
    pods = pods._replace(gang_id=jnp.asarray(gang_id))
    gstate = GangState.build(min_member=[size] * n_gangs)
    config = SolverConfig(unroll=BENCH_UNROLL)
    scan = _obs_jit("bench_gang_scan", jax.jit(
        lambda s, p, pr, g: solve_batch(s, p, pr, config, None, g)[3:8]
    ))  # (assign, commit, waiting, rejected, raw_assign)
    kern = lambda s, p, pr, g: (lambda r: (r.assign, r.commit, r.waiting,
                                           r.rejected, r.raw_assign))(
        pallas_solve_batch(s, p, pr, config, None, g))

    best, _warm, out, solver, win, _scan_best, _kvs = _pick_kernel_or_scan(
        scan, kern, repeats, (state, pods, params, gstate), _cmp_tuple
    )
    p99_s = _p99(lambda *a: win(*a)[0], (state, pods, params, gstate),
                 max(100, repeats))
    committed = int(np.asarray(out[1]).sum())

    result = {
        "pods_per_sec": n_pods / best,
        "p99_s": p99_s,
        "solver": solver,
        "wall_s": best,
        "committed": committed,
        "gangs": n_gangs,
        **_leg_times(best),
    }
    if _oracle_enabled():
        from koordinator_tpu.oracle.vectorized import (
            gang_outcomes_np,
            schedule_vectorized,
        )

        # full-shape identity: gangs don't alter in-scan placement, so the
        # raw assignment sequence (already in the timed winner's output)
        # must equal the plain sequential oracle; the batch-end gang
        # resolution is re-derived in numpy from it
        raw = np.asarray(out[4])
        oracle = schedule_vectorized(*_oracle_args(state, pods, params))
        want_c, want_w, _want_rj = gang_outcomes_np(
            oracle, gang_id, np.asarray(gstate.min_member),
            np.asarray(gstate.bound_count), np.asarray(gstate.strict),
            np.asarray(gstate.group_id),
        )
        want_assign = np.where(want_c | want_w, oracle, -1)
        result["identical_to_oracle"] = bool(
            (raw == oracle).all()
            and (np.asarray(out[0]) == want_assign).all()
            and (np.asarray(out[1]) == want_c).all()
        )
        result["oracle_check_shape"] = "full"
    return result


def bench_numa(repeats):
    """Extra matrix entry: NUMA-policy cluster (topology-aligned scoring
    + consumption in-solve), kernel vs scan, identity enforced."""
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.ops.binpack import (
        NumaAux,
        SolverConfig,
        solve_batch,
    )
    from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

    n_nodes, n_pods = 1500, 3000
    state, pods, params = _problem(n_nodes, n_pods, seed=6)
    rng = np.random.default_rng(6)
    cap = np.asarray(state.alloc)
    free = (cap * rng.uniform(0.3, 1.0, cap.shape)).astype(np.int32)
    state = state._replace(numa_cap=jnp.asarray(cap),
                           numa_free=jnp.asarray(free))
    pods = pods._replace(has_numa_policy=jnp.asarray(
        rng.uniform(size=n_pods) < 0.4))
    aux = NumaAux(node_policy=jnp.asarray(rng.uniform(size=n_nodes) < 0.5))
    config = SolverConfig(unroll=BENCH_UNROLL)
    scan = _obs_jit("bench_numa_scan", jax.jit(
        lambda s, p, pr, a: (lambda r: (r.assign, r.numa_consumed,
                                        r.node_state.numa_free))(
            solve_batch(s, p, pr, config, numa=a))))
    kern = lambda s, p, pr, a: (lambda r: (r.assign, r.numa_consumed,
                                           r.node_state.numa_free))(
        pallas_solve_batch(s, p, pr, config, numa_aux=a))

    best, _warm, out, solver, win, scan_best, kvs = _pick_kernel_or_scan(
        scan, kern, repeats, (state, pods, params, aux), _cmp_tuple
    )
    p99_s = _p99(lambda *a: win(*a)[0], (state, pods, params, aux),
                 max(100, repeats))
    result = {
        "pods_per_sec": n_pods / best,
        "p99_s": p99_s,
        "kernel_vs_scan": kvs,  # "identical" | "DIVERGED" | "not_run"
        "solver": solver,
        "scan_pods_per_sec": n_pods / scan_best,
        "wall_s": best,
        "consumed": int(np.asarray(out[1]).sum()),
        **_leg_times(best),
    }
    if _oracle_enabled():
        # reference-semantics check at full shape (VERDICT r4 #2): the
        # sequential numpy oracle models the NUMA term + consumption
        from koordinator_tpu.oracle.vectorized import solve_full_vectorized

        t0 = time.time()
        oracle = solve_full_vectorized(state, pods, params, numa_aux=aux)
        result["oracle_wall_s"] = time.time() - t0
        result["identical_to_oracle"] = bool(
            (np.asarray(out[0]) == oracle["assign"]).all()
            and (np.asarray(out[2]) == oracle["numa_free"]).all()
        )
        result["oracle_check_shape"] = "full"
    return result


def bench_fit_16k(repeats):
    """Config #7: the flagship shape on a 16k-node cluster — past the
    old 8192-node kernel cap (VERDICT r3 #5). Kernel vs scan winner-kept
    with bit-identity on the full (state, assign) outputs."""
    import jax

    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
    from koordinator_tpu.ops.pallas_binpack import (
        pallas_schedule_batch,
        pallas_supported,
    )

    n_nodes, n_pods = 16000, 10000
    state, pods, params = _problem(n_nodes, n_pods, seed=7)
    config = SolverConfig(unroll=BENCH_UNROLL)
    scan = _obs_jit("bench_fit16k_scan", jax.jit(
        lambda s, p, pr: schedule_batch(s, p, pr, config)))
    kern = None
    if pallas_supported(params, config):
        kern = lambda s, p, pr: pallas_schedule_batch(s, p, pr, config)

    def cmp_state_and_assign(a, b):
        return bool(
            (np.asarray(a[1]) == np.asarray(b[1])).all()
        ) and all(
            bool((np.asarray(x) == np.asarray(y)).all())
            for x, y in zip(a[0], b[0])
        )

    best, _warm, out, solver, win, scan_best, kvs = _pick_kernel_or_scan(
        scan, kern, repeats, (state, pods, params), cmp_state_and_assign
    )
    p99_s = _p99(win, (state, pods, params), max(100, repeats))
    result = {
        "pods_per_sec": n_pods / best,
        "scan_pods_per_sec": n_pods / scan_best,
        "p99_s": p99_s,
        "solver": solver,
        "kernel_vs_scan": kvs,  # "identical" | "DIVERGED" | "not_run"
        "n_nodes": n_nodes,
        "wall_s": best,
        **_leg_times(best),
    }
    if _oracle_enabled():
        # reference-semantics identity at the full 16k-node shape
        # (VERDICT r4 #2 — was previously kernel==scan only)
        from koordinator_tpu.oracle.vectorized import schedule_vectorized

        t0 = time.time()
        oracle = schedule_vectorized(*_oracle_args(state, pods, params))
        result["oracle_wall_s"] = time.time() - t0
        result["identical_to_oracle"] = bool(
            (np.asarray(out[1]) == oracle).all()
        )
        result["oracle_check_shape"] = "full"
    return result


def bench_full_features(repeats):
    """Config #8: the flagship shape with EVERY feature enabled at once —
    ElasticQuota admission, strict gangs, NUMA scoring/consumption AND
    reservation credit/consumption fused into one solve at 5k nodes /
    10k pods — checked bit-for-bit against the sequential oracle
    (assign + node used, NUMA free, reservation free, quota used).
    VERDICT r4 #2: the flagship headline previously never exercised the
    fused feature paths at scale."""
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.ops.binpack import (
        NumaAux,
        ResvArrays,
        SolverConfig,
        solve_batch,
    )
    from koordinator_tpu.ops.gang import GangState
    from koordinator_tpu.ops.quota import QuotaState
    from koordinator_tpu.oracle.vectorized import (
        VectorQuota,
        solve_full_vectorized,
    )

    n_nodes = int(os.environ.get("KTPU_BENCH_NODES", 5000))
    n_pods = int(os.environ.get("KTPU_BENCH_PODS", 10000))
    n_quota, members = 50, 16
    # gangs cover <= 1/4 of the batch so shrunken smoke shapes
    # (KTPU_BENCH_PODS) keep a valid mix of gang and solo pods
    n_gangs = min(100, max(1, n_pods // (4 * members)))
    n_resv = min(64, n_gangs)
    state, pods, params = _problem(n_nodes, n_pods, seed=8)
    rng = np.random.default_rng(8)

    # NUMA side
    cap = np.asarray(state.alloc)
    free = (cap * rng.uniform(0.3, 1.0, cap.shape)).astype(np.int32)
    state = state._replace(numa_cap=jnp.asarray(cap),
                           numa_free=jnp.asarray(free))
    aux = NumaAux(node_policy=jnp.asarray(rng.uniform(size=n_nodes) < 0.5))

    # gang side: 100 strict gangs of 16 over the first 1600 pods; gang
    # members share their gang's pod template (one workload = one shape),
    # which also keeps the oracle's pod-shape class cache effective
    gang_id = np.full(n_pods, -1, np.int32)
    gang_id[: n_gangs * members] = np.repeat(
        np.arange(n_gangs, dtype=np.int32), members
    )
    gstate = GangState.build(min_member=[members] * n_gangs)
    req_np = np.asarray(pods.req).copy()
    est_np = np.asarray(pods.est).copy()
    for g in range(n_gangs):
        lo = g * members
        req_np[lo:lo + members] = req_np[lo]
        est_np[lo:lo + members] = est_np[lo]
    pods = pods._replace(req=jnp.asarray(req_np), est=jnp.asarray(est_np))

    # reservation side: reservation v is owned by gang v's workload and
    # matches exactly its member slice (transformer.go owner matching)
    node_of = rng.integers(0, n_nodes, n_resv).astype(np.int32)
    rfree = np.zeros((n_resv, NUM_RESOURCES), np.int32)
    rfree[:, ResourceName.CPU] = rng.integers(500, 4000, n_resv)
    rfree[:, ResourceName.MEMORY] = rng.integers(500, 4000, n_resv)
    match = np.zeros((n_pods, n_resv), bool)
    for v in range(n_resv):
        match[v * members:(v + 1) * members, v] = True
    resv = ResvArrays(
        node=jnp.asarray(node_of), free=jnp.asarray(rfree),
        allocate_once=jnp.asarray(rng.uniform(size=n_resv) < 0.5),
        match=jnp.asarray(match),
    )

    # quota side (requests registered AFTER the gang template rewrite)
    qid = rng.integers(0, n_quota, n_pods).astype(np.int32)
    total = cap.astype(np.int64).sum(axis=0)
    mn = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mx = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    for r in (ResourceName.CPU, ResourceName.MEMORY):
        mn[:, r] = total[r] // (2 * n_quota)
        mx[:, r] = total[r] // 8
    child_request = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    np.add.at(child_request, qid, req_np.astype(np.int64))
    qstate = QuotaState.build(
        min=mn, max=mx, weight=mx, allow_lent=np.ones(n_quota, bool),
        total=total, child_request=child_request,
    )
    vq = VectorQuota(
        min_=mn, max_=mx, auto_min=np.asarray(qstate.auto_min),
        weight=mx, allow_lent=np.ones(n_quota, bool), total=total,
    )

    pods = pods._replace(
        quota_id=jnp.asarray(qid),
        non_preemptible=jnp.asarray(rng.uniform(size=n_pods) < 0.3),
        gang_id=jnp.asarray(gang_id),
        has_numa_policy=jnp.asarray(rng.uniform(size=n_pods) < 0.4),
    )

    config = SolverConfig(unroll=BENCH_UNROLL)
    solve = _obs_jit("bench_full_features_scan", jax.jit(
        lambda s, p, pr, q, g: solve_batch(
            s, p, pr, config, q, g, resv=resv, numa=aux
        )))

    def pick(r):
        return (r.assign, r.node_state.used_req, r.node_state.numa_free,
                r.resv_free, r.quota_state.used)

    scan = lambda s, p, pr, q, g: pick(solve(s, p, pr, q, g))
    # the kernel covers the full feature set incl. reservations (r5):
    # the credit matmul + [R,Vp] rfree carry — winner-kept on identity
    from koordinator_tpu.ops.pallas_binpack import (
        pallas_resv_supported,
        pallas_solve_batch,
    )

    kern = None
    if pallas_resv_supported(n_resv, n_nodes):
        kern = lambda s, p, pr, q, g: pick(pallas_solve_batch(
            s, p, pr, config, q, g, numa_aux=aux, resv=resv
        ))

    best, _warm, out, solver, win, scan_best, kvs = _pick_kernel_or_scan(
        scan, kern, repeats, (state, pods, params, qstate, gstate),
        _cmp_tuple,
    )
    p99_s = _p99(lambda *a: win(*a)[0],
                 (state, pods, params, qstate, gstate), max(100, repeats))
    result = {
        "pods_per_sec": n_pods / best,
        "p99_s": p99_s,
        "solver": solver,
        "scan_pods_per_sec": n_pods / scan_best,
        "kernel_vs_scan": kvs,
        "wall_s": best,
        "placed": int((np.asarray(out[0]) >= 0).sum()),
        "features": "quota+gang+numa+reservation",
        **_leg_times(best),
    }
    if _oracle_enabled():
        t0 = time.time()
        oracle = solve_full_vectorized(
            state, pods, params,
            quota=vq, pod_quota_id=qid,
            pod_non_preemptible=np.asarray(pods.non_preemptible),
            gang_id=gang_id,
            gang_min_member=np.asarray(gstate.min_member),
            gang_bound_count=np.asarray(gstate.bound_count),
            gang_strict=np.asarray(gstate.strict),
            gang_group_id=np.asarray(gstate.group_id),
            numa_aux=aux, resv=resv,
        )
        result["oracle_wall_s"] = time.time() - t0
        result["identical_to_oracle"] = bool(
            (np.asarray(out[0]) == oracle["assign"]).all()
            and (np.asarray(out[1]) == oracle["used_req"]).all()
            and (np.asarray(out[2]) == oracle["numa_free"]).all()
            and (np.asarray(out[3]) == oracle["resv_free"]).all()
            and (np.asarray(out[4]) == vq.used).all()
        )
        result["oracle_check_shape"] = "full"
    return result


def bench_churn_tick(repeats):
    """Config #9 (PR 6): steady-state scheduling ticks over an EVOLVING
    cluster — the workload the incremental staging layer exists for.

    A 5k-node typed snapshot with ~2 assigned pods/node and full metric
    coverage takes a small per-tick mutation stream (50 nodes' metrics
    refreshed + the previous tick's binds) and schedules a 64-pod
    pending queue each tick. Run twice from identical seeds: once
    full-restage (no delta tracker: every tick re-lowers and re-uploads
    the world — the pre-PR-6 behavior) and once through the
    delta-staging path (ClusterDeltaTracker + StagedStateCache: dirty
    rows re-lowered on host, donated device scatter). Assignments must
    match tick-for-tick (``identical_to_full_restage``); the acceptance
    bar is delta ticks >= 3x full-restage ticks on wall time with the
    lower/stage/solve breakdown recorded for both paths."""
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.testing import (
        churn_tick_events,
        churn_world,
        fold_churn_binds,
    )

    n_nodes = int(os.environ.get("KTPU_BENCH_CHURN_NODES",
                                 os.environ.get("KTPU_BENCH_NODES", 5000)))
    dirty_per_tick = int(os.environ.get("KTPU_BENCH_CHURN_DIRTY", 50))
    pending_per_tick = 64
    # floor 3: ticks 0-1 are warmup-excluded, at least one must be timed
    ticks = max(3, int(os.environ.get("KTPU_BENCH_CHURN_TICKS",
                                      max(6, min(repeats * 4, 12)))))

    def run(with_tracker):
        snap, tracker = churn_world(n_nodes, with_tracker=with_tracker)
        model = PlacementModel(config=SolverConfig(unroll=BENCH_UNROLL))
        rng = np.random.default_rng(7)
        walls = []
        sums = {"lower_s": 0.0, "stage_s": 0.0, "solve_s": 0.0}
        log = []
        for t in range(ticks):
            now = 20.0 + t
            by_uid = churn_tick_events(
                snap, tracker, rng, dirty=dirty_per_tick,
                pending=pending_per_tick, t=t, now=now,
            )
            t0 = time.time()
            result = model.schedule(snap)
            wall = time.time() - t0
            if t > 1:  # ticks 0-1 pay solve + scatter compiles and the
                walls.append(wall)  # cold full stage: steady state only
                for k in sums:
                    sums[k] += model.last_timings[k]
            log.append(sorted(result.items()))
            fold_churn_binds(snap, tracker, result, by_uid, now)
        n = max(1, len(walls))
        return {
            "tick_wall_s": sum(walls) / n,
            "ticks_per_sec": n / sum(walls),
            **{k: v / n for k, v in sums.items()},
        }, log

    full, full_log = run(False)
    delta, delta_log = run(True)
    return {
        "ticks_per_sec": delta["ticks_per_sec"],
        "full_restage_ticks_per_sec": full["ticks_per_sec"],
        "speedup_vs_full_restage": (
            full["tick_wall_s"] and delta["tick_wall_s"]
            and full["tick_wall_s"] / delta["tick_wall_s"]
        ),
        "tick_wall_s": delta["tick_wall_s"],
        "full_tick_wall_s": full["tick_wall_s"],
        "lower_s": delta["lower_s"],
        "stage_s": delta["stage_s"],
        "solve_s": delta["solve_s"],
        "full_lower_s": full["lower_s"],
        "full_stage_s": full["stage_s"],
        "full_solve_s": full["solve_s"],
        "identical_to_full_restage": full_log == delta_log,
        "n_nodes": n_nodes,
        "dirty_per_tick": dirty_per_tick,
        "pending_per_tick": pending_per_tick,
        "ticks": ticks,
    }


def bench_pipelined_churn(repeats):
    """Config #13 (ISSUE 6): serial vs pipelined ROUND time over the
    bus-wired scheduler at 5k nodes.

    What "round time" measures, precisely: the host critical path of
    one scheduling round — everything the loop must finish before the
    next round may begin. The serial loop serializes stage + solve
    (blocking read-back) + epilogue + publish, so its round time is the
    sum. The pipelined loop's round time is ``submit_round``'s wall:
    retire-wait + catch-up staging + async dispatch — the solve
    compute, read-back, epilogue, and bus publish retire on the
    publisher worker during the cadence gap, and informer-dirty rows
    are prestaged mid-flight (the scheduling-cycle/binding-cycle split
    of the reference, done TPU-native). Both loops run the same seeded
    arrival stream — the pipelined one applies tick t+1's arrivals
    while tick t's solve is in flight, which is exactly the continuous
    informer traffic a live control plane sees — and placements must
    match tick for tick (``tick_identical_to_serial``), plus final
    bus-level node accounting bit-for-bit.

    Acceptance (ISSUE 6): pipelined p99 round < 10 ms at 5k nodes and
    >= 5x better than the serial round in the same record, with the
    per-stage lower/stage/solve/publish breakdown for both loops."""
    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
    from koordinator_tpu.client.bus import APIServer, Kind
    from koordinator_tpu.client.wiring import (
        snapshot_from_bus,
        wire_scheduler,
    )
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import (
        STAGED_NODE_FIELDS,
        SolverConfig,
    )
    from koordinator_tpu.scheduler import Scheduler
    from koordinator_tpu.scheduler.pipeline import TickPipeline
    from koordinator_tpu.state.cluster import lower_nodes

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    n_nodes = int(os.environ.get("KTPU_BENCH_PIPE_NODES",
                                 os.environ.get("KTPU_BENCH_NODES", 5000)))
    dirty_per_tick = int(os.environ.get("KTPU_BENCH_PIPE_DIRTY", 50))
    pending_per_tick = 64
    #: tick cadence: the gap the retire pipeline drains into (a real
    #: deployment runs 1s; 50ms is a 20x harder version of the same
    #: loop)
    interval_s = float(os.environ.get("KTPU_BENCH_PIPE_INTERVAL", 0.05))
    ticks = max(6, min(repeats * 4, 12))
    warmup = 2           # compile-warming empty rounds
    settle = 2           # first timed ticks pay one-off scatter compiles

    def build():
        rng = np.random.default_rng(42)
        bus = APIServer()
        sched = Scheduler(model=PlacementModel(
            config=SolverConfig(unroll=BENCH_UNROLL)))
        wire_scheduler(bus, sched)
        for i in range(n_nodes):
            bus.apply(Kind.NODE, f"n{i}", NodeSpec(
                name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
        for i in range(n_nodes):
            bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
                node_name=f"n{i}",
                node_usage={CPU: int(rng.integers(500, 30000)),
                            MEM: int(rng.integers(512, 65536))},
                update_time=10.0))
        for j in range(n_nodes):
            node_i = int(rng.integers(0, n_nodes))
            pod = PodSpec(
                name=f"a{j}", node_name=f"n{node_i}", assign_time=5.0,
                requests={CPU: int(rng.integers(200, 2000)),
                          MEM: int(rng.integers(128, 2048))})
            bus.apply(Kind.POD, pod.uid, pod)
        return bus, sched

    def mutations(rng, bus, t, now):
        for i in rng.choice(n_nodes, dirty_per_tick, replace=False):
            name = f"n{int(i)}"
            bus.apply(Kind.NODE_METRIC, name, NodeMetric(
                node_name=name,
                node_usage={CPU: int(rng.integers(500, 30000)),
                            MEM: int(rng.integers(512, 65536))},
                update_time=now))
        for j in range(pending_per_tick):
            pod = PodSpec(
                name=f"t{t}p{j}",
                requests={CPU: int(rng.integers(200, 1500)),
                          MEM: int(rng.integers(128, 1024))})
            bus.apply(Kind.POD, pod.uid, pod)

    def stats(samples):
        xs = sorted(samples)
        return {
            "p50_s": xs[len(xs) // 2],
            # ceil, not floor: at this leg's ~10 timed rounds a floored
            # index is the 2nd-largest sample, and the sub_10ms_p99
            # acceptance gate would silently exclude the worst round
            "p99_s": xs[min(len(xs) - 1,
                            math.ceil(0.99 * (len(xs) - 1)))],
            "mean_s": sum(xs) / len(xs),
        }

    def run_serial():
        bus, sched = build()
        rng = np.random.default_rng(7)
        rounds, log = [], []
        sums = {"lower_s": 0.0, "stage_s": 0.0, "solve_s": 0.0}
        for t in range(warmup):
            sched.schedule_pending(now=15.0 + 0.1 * t)
        for t in range(ticks):
            now = 20.0 + t
            mutations(rng, bus, t, now)
            t0 = time.perf_counter()
            out = sched.schedule_pending(now=now)
            wall = time.perf_counter() - t0
            log.append(sorted(out.items()))
            if t >= settle:
                rounds.append(wall)
                for k in sums:
                    sums[k] += sched.model.last_timings[k]
        n = max(1, len(rounds))
        return rounds, log, bus, {k: v / n for k, v in sums.items()}

    def run_pipelined(traced, toggle=None, n_ticks=None, obs_on=True,
                      obs_toggle=None):
        from koordinator_tpu.obs.device import DEVICE_OBS
        from koordinator_tpu.obs.trace import TRACER

        TRACER.set_enabled(traced)
        DEVICE_OBS.set_enabled(obs_on)
        try:
            n_ticks = ticks if n_ticks is None else n_ticks
            bus, sched = build()
            rng = np.random.default_rng(7)
            rounds, log, stage_rows = [], [], []
            holder = {}

            def on_result(out):
                log.append(sorted(out.items()))
                stage_rows.append(holder["p"].status()["last_round"])

            pipeline = TickPipeline(sched, log=lambda *a: None,
                                    on_result=on_result)
            holder["p"] = pipeline
            for t in range(warmup):
                pipeline.submit_round(now=15.0 + 0.1 * t)
                pipeline.drain("warmup")
            log.clear()
            stage_rows.clear()
            mutations(rng, bus, 0, 20.0)
            next_fire = time.perf_counter()
            for t in range(n_ticks):
                now = 20.0 + t
                if toggle is not None:
                    TRACER.set_enabled(toggle(t))
                if obs_toggle is not None:
                    DEVICE_OBS.set_enabled(obs_toggle(t))
                lag = next_fire - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                t0 = time.perf_counter()
                pipeline.submit_round(now=now)
                wall = time.perf_counter() - t0
                next_fire = t0 + interval_s
                if t >= settle:
                    rounds.append(wall)
                if t + 1 < n_ticks:
                    # the arrival stream lands MID-FLIGHT (while this
                    # tick's solve computes) — what prestage exists for
                    mutations(rng, bus, t + 1, now + 1.0)
                pipeline.prestage(now=now)
            pipeline.drain("bench")
            pipeline.stop()
        finally:
            # leg() catches a failing entry and moves on: neither the
            # process tracer nor the device observatory may stay
            # disabled for the legs (and Perfetto export) that follow
            TRACER.set_enabled(True)
            DEVICE_OBS.set_enabled(True)
        sums = {"lower_s": 0.0, "stage_s": 0.0, "solve_s": 0.0,
                "publish_s": 0.0}
        used = stage_rows[settle:]
        for row in used:
            for k in sums:
                sums[k] += row.get(k, 0.0)
        n = max(1, len(used))
        return (rounds, log, bus, {k: v / n for k, v in sums.items()},
                sched.timelines.stats())

    from koordinator_tpu.obs.trace import TRACER

    s_rounds, s_log, s_bus, s_stages = run_serial()
    # tracing-off pipelined run: the on-vs-off tick-identity half of
    # the ISSUE 7 acceptance
    o_rounds, o_log, _o_bus, _o_stages, _o_lat = run_pipelined(False)
    # the overhead measurement is PAIRED: one longer run alternating
    # tracing per tick, compared median-traced vs median-untraced.
    # Two separate runs differ by several % from scheduler noise alone
    # at ~7ms rounds — far above the <=0.02 bound being certified —
    # while alternation cancels the drift and additionally proves
    # placements don't depend on toggling tracing mid-run
    alt_ticks = max(4 * ticks, 40)
    a_rounds, a_log, _a_bus, _a_stages, _a_lat = run_pipelined(
        True, toggle=lambda t: t % 2 == 0, n_ticks=alt_ticks
    )
    # the device observatory's half of the same acceptance (ISSUE 8):
    # an observatory-off run for tick identity, then a paired
    # alternating run (observatory toggled per tick, tracer on
    # throughout) for the honest overhead tax — same methodology as the
    # tracer's, same <= 0.02 bound
    d_rounds, d_log, _d_bus, _d_stages, _d_lat = run_pipelined(
        True, obs_on=False
    )
    da_rounds, da_log, _da_bus, _da_stages, _da_lat = run_pipelined(
        # 2x the tracer run's length: the min-based estimator below
        # wants more samples per parity for its minima to converge
        True, obs_toggle=lambda t: t % 2 == 0, n_ticks=2 * alt_ticks
    )
    # tracing-on run LAST so the span ring still holds it: the Perfetto
    # artifact is exported from exactly this run
    TRACER.clear()
    p_rounds, p_log, p_bus, p_stages, p_latency = run_pipelined(True)
    spans = TRACER.events()

    def interval(e):
        return e["t0"], e["t0"] + (e["dur"] or 0.0)

    overlap_visible = any(
        ps["track"] != ds["track"]
        and interval(ps)[0] < interval(ds)[1]
        and interval(ds)[0] < interval(ps)[1]
        for ps in spans if ps["name"] == "prestage"
        for ds in spans if ds["name"] == "device_solve"
    )
    trace_path = os.environ.get(
        "KTPU_BENCH_TRACE_OUT",
        os.path.join(tempfile.gettempdir(),
                     "ktpu_trace_pipelined_churn.json"),
    )
    trace_events = 0
    try:
        exported = TRACER.chrome_trace()
        trace_events = len(exported["traceEvents"])
        with open(trace_path, "w") as f:
            json.dump(exported, f)
    except OSError as e:
        trace_path = f"unwritable: {e}"

    # serial identity only: the trace on/off half has its own key
    # (tick_identical_trace_on_off) — folding it in here would
    # misreport a tracer regression as a pipelined-vs-serial divergence
    identical = s_log == p_log
    if identical:
        got = lower_nodes(snapshot_from_bus(p_bus, now=100.0))
        want = lower_nodes(snapshot_from_bus(s_bus, now=100.0))
        identical = got.names == want.names and all(
            np.array_equal(getattr(got, f), getattr(want, f))
            for f in STAGED_NODE_FIELDS
        )
    s = stats(s_rounds)
    p = stats(p_rounds)
    o = stats(o_rounds)
    # the honest tracing tax (ISSUE 7 acceptance: <= 0.02 at 5k
    # nodes): median traced tick vs median untraced tick of the SAME
    # alternating run — a paired measurement, robust to the few-%
    # run-to-run drift two independent runs always show
    tr = [w for i, w in enumerate(a_rounds) if (i + settle) % 2 == 0]
    un = [w for i, w in enumerate(a_rounds) if (i + settle) % 2 == 1]

    def median(xs):
        return sorted(xs)[len(xs) // 2] if xs else 0.0

    trace_overhead = (
        max(0.0, (median(tr) - median(un)) / median(un))
        if median(un) else 0.0
    )
    obs_on_s = [w for i, w in enumerate(da_rounds)
                if (i + settle) % 2 == 0]
    obs_off_s = [w for i, w in enumerate(da_rounds)
                 if (i + settle) % 2 == 1]
    # min-vs-min, not median-vs-median: external load only ever ADDS
    # time, so the per-parity minima both converge to the true unloaded
    # round wall and their difference isolates the observatory's
    # systematic cost — the same spike-immunity argument behind
    # _timed()'s min(times). Medians at ~20 samples/parity were
    # measured swinging 0-8% on a loaded box for a KNOWN sub-1% cost.
    device_obs_overhead = (
        max(0.0, (min(obs_on_s) - min(obs_off_s)) / min(obs_off_s))
        if obs_off_s and min(obs_off_s) else 0.0
    )
    return {
        "round_p99_s": p["p99_s"],
        "round_p50_s": p["p50_s"],
        "serial_round_p99_s": s["p99_s"],
        "serial_round_p50_s": s["p50_s"],
        "speedup_p99": s["p99_s"] / p["p99_s"] if p["p99_s"] else 0.0,
        "sub_10ms_p99": p["p99_s"] < 0.010,
        "tick_identical_to_serial": identical,
        # ISSUE 7: tracing on vs off — identity, measured tax, and the
        # exported Perfetto artifact showing the stage/solve overlap
        # on == off == toggled-mid-run: the same seeded ticks place
        # identically no matter the tracer state (prefix compare — the
        # alternating run is longer)
        "tick_identical_trace_on_off": (
            p_log == o_log and a_log[: len(o_log)] == o_log
        ),
        "trace_overhead_ratio": trace_overhead,
        # ISSUE 8: the device observatory toggled per tick of one run —
        # paired overhead (<= 0.02 acceptance) and on==off==toggled
        # tick identity, the same proof shape as the tracer's
        "device_obs_overhead_ratio": device_obs_overhead,
        "tick_identical_device_obs_on_off": (
            p_log == d_log and da_log[: len(d_log)] == d_log
        ),
        "untraced_round_p99_s": o["p99_s"],
        "trace_artifact": trace_path,
        "trace_artifact_events": trace_events,
        "trace_overlap_visible": overlap_visible,
        # per-pod submit->bind latency from the new timelines — the
        # metric ROADMAP item 2's serving mode will regress against
        "pod_e2e_p50_s": p_latency["all"]["p50_s"],
        "pod_e2e_p99_s": p_latency["all"]["p99_s"],
        "pod_e2e_count": p_latency["all"]["count"],
        # the pipelined round's critical path vs what retired off-path
        "lower_s": p_stages["lower_s"],
        "stage_s": p_stages["stage_s"],
        "solve_s": p_stages["solve_s"],
        "publish_s": p_stages["publish_s"],
        "serial_lower_s": s_stages["lower_s"],
        "serial_stage_s": s_stages["stage_s"],
        "serial_solve_s": s_stages["solve_s"],
        "n_nodes": n_nodes,
        "dirty_per_tick": dirty_per_tick,
        "pending_per_tick": pending_per_tick,
        "ticks": ticks,
        "interval_s": interval_s,
    }


def bench_streaming_arrival(repeats):
    """Config #18 (ISSUE 14): continuous-arrival serving — the adaptive
    round trigger (batch-size watermark OR oldest-pod lane deadline,
    docs/DESIGN.md §22) vs the fixed-cadence loop, at sustained
    open-loop arrival rates.

    The serving question legs 9-13 never asked: not pods/s per tick but
    per-pod submit→bind p50/p99 while pods arrive CONTINUOUSLY (seeded
    heavy-tail trace, testing/arrivals.py; arrivals never wait for the
    scheduler). Three facets:

    - **low / mid rate arms**: the same trace served by (a) the fixed
      50ms cadence (run_loop's shape: a pod waits out the rest of the
      tick it missed) and (b) the adaptive trigger — both through the
      pipelined tick path, both placing every pod (equal throughput),
      per-pod latency from the PodTimelines ring. Acceptance: adaptive
      p99 >= 2x better at the mid rate.
    - **bit-identity**: the adaptive arm's recorded per-round arrival
      batches replayed through the plain fixed-round loop must
      reproduce final placements and node accounting bit for bit (the
      trigger changes WHEN rounds fire, never WHAT they decide).
    - **max sustainable rate**: a rate ladder on the adaptive arm; the
      highest rate where nothing sheds and the tail drains promptly is
      recorded as the shed point (DESIGN §22's definition).
    """
    import dataclasses

    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
    from koordinator_tpu.client.bus import APIServer, Kind
    from koordinator_tpu.client.wiring import (
        snapshot_from_bus,
        wire_scheduler,
    )
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import (
        STAGED_NODE_FIELDS,
        SolverConfig,
    )
    from koordinator_tpu.scheduler import Scheduler
    from koordinator_tpu.scheduler.pipeline import TickPipeline
    from koordinator_tpu.scheduler.streaming import (
        StreamingConfig,
        StreamingLoop,
    )
    from koordinator_tpu.state.cluster import lower_nodes
    from koordinator_tpu.testing.arrivals import heavy_tail_trace

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    # 500 nodes keeps the solve wall (~15-20ms on CPU) well under the
    # mid rate's queueing point on this class of box: the comparison
    # then measures the TRIGGER's queue-wait, not device saturation
    # (at 1000 nodes / 1000 pods/s both arms saturate and converge)
    n_nodes = int(os.environ.get("KTPU_BENCH_STREAM_NODES", 500))
    duration_s = float(os.environ.get("KTPU_BENCH_STREAM_SECONDS", 4.0))
    rate_low = float(os.environ.get("KTPU_BENCH_STREAM_RATE_LOW", 200.0))
    rate_mid = float(os.environ.get("KTPU_BENCH_STREAM_RATE_MID", 800.0))
    interval_s = float(os.environ.get("KTPU_BENCH_STREAM_INTERVAL", 0.05))
    cfg = StreamingConfig(
        watermark=int(os.environ.get("KTPU_BENCH_STREAM_WATERMARK", 64)),
        lane_deadline_s=(0.002, 0.010, 0.050),
    )

    def build():
        rng = np.random.default_rng(42)
        bus = APIServer()
        sched = Scheduler(model=PlacementModel(
            config=SolverConfig(unroll=BENCH_UNROLL)))
        wire_scheduler(bus, sched)
        for i in range(n_nodes):
            bus.apply(Kind.NODE, f"n{i}", NodeSpec(
                name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
            bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
                node_name=f"n{i}",
                node_usage={CPU: int(rng.integers(500, 30000)),
                            MEM: int(rng.integers(512, 65536))},
                update_time=10.0))
        return bus, sched

    warm_max = int(os.environ.get("KTPU_BENCH_STREAM_WARM_PODS", 1024))

    def warm(sched, bus):
        """Compile-warm every pod-bucket variant the stream can hit
        (quarter-pow2 buckets up to ``warm_max``): a round mid-stream
        must never pay an XLA compile, or the latency comparison
        measures the compiler. One slow pass per process — jax shares
        the compiled executables across the later builds' fresh jit
        wrappers. The warm pods are deleted afterwards so the measured
        world starts pristine (and identical across arms)."""
        from koordinator_tpu.parallel.mesh import pow2_quarter_bucket

        buckets = sorted({1} | {
            pow2_quarter_bucket(s, floor=8)
            for s in range(1, warm_max + 1)
        })
        for b, size in enumerate(buckets):
            uids = []
            for j in range(size):
                pod = PodSpec(name=f"warm{b}x{j}",
                              requests={CPU: 100, MEM: 64})
                bus.apply(Kind.POD, pod.uid, pod)
                uids.append(pod.uid)
            sched.schedule_pending(now=15.0)
            for uid in uids:
                bus.delete(Kind.POD, uid)
        sched.timelines.reset()

    def trace_for(rate, seed=23):
        return heavy_tail_trace(seed, duration_s=duration_s,
                                rate_pods_per_s=rate, cpu_cap=8000)

    def run_adaptive(rate, seed=23):
        """Open-loop wall-clock drive of the adaptive trigger (one
        thread: submissions and round-firing interleave exactly as the
        trigger dictates; the pipeline overlaps solve/publish)."""
        bus, sched = build()
        warm(sched, bus)
        loop = StreamingLoop(
            sched,
            apply_fn=lambda pod: bus.apply(Kind.POD, pod.uid, pod),
            delete_fn=lambda uid: bus.delete(Kind.POD, uid),
            config=cfg, pipelined=True, log=lambda *a: None,
        )
        trace = trace_for(rate, seed)
        pods_by_uid = {}
        t0 = time.perf_counter()
        i = 0
        arrivals = trace.arrivals
        try:
            while i < len(arrivals):
                now = time.perf_counter() - t0
                while i < len(arrivals) and arrivals[i].at <= now:
                    a = arrivals[i]
                    pod = PodSpec(
                        name=a.name, qos=a.qos,
                        requests={CPU: a.cpu, MEM: a.memory})
                    pods_by_uid[pod.uid] = dataclasses.replace(pod)
                    loop.submit(pod)
                    i += 1
                reason = loop.due()
                if reason is not None:
                    loop.fire_round(reason)
                    continue
                nxt = arrivals[i].at - (time.perf_counter() - t0) \
                    if i < len(arrivals) else 0.0
                dl = loop.gate.next_deadline()
                # the gate's deadlines live on ITS clock
                # (time.monotonic) — never mix clock domains here
                wait = nxt if dl is None else min(
                    nxt, max(0.0, dl - time.monotonic()))
                if wait > 0:
                    time.sleep(min(wait, 0.005))
            drained = loop.drain(timeout_s=30.0)
            drain_wall = time.perf_counter() - t0 - arrivals[-1].at
        finally:
            loop.stop()
        st = loop.status()
        lat = sched.timelines.stats()
        return {
            "bus": bus, "sched": sched, "status": st,
            "round_log": list(loop.round_log),
            "pods_by_uid": pods_by_uid,
            "latency": lat, "drained": drained,
            "drain_wall_s": max(0.0, drain_wall),
            "submitted": st["gate"]["submitted"],
            "bound": st["gate"]["bound"],
            "shed": st["gate"]["shed"]["capacity"]
            + st["gate"]["shed"]["deadline-exceeded"],
            "rounds": st["rounds"],
        }

    def run_fixed(rate, seed=23):
        """The same open-loop trace on the fixed cadence: a pipelined
        round every interval_s regardless of queue state (run_loop's
        shape) — the baseline the adaptive trigger must beat."""
        bus, sched = build()
        warm(sched, bus)
        pipeline = TickPipeline(sched, log=lambda *a: None)
        trace = trace_for(rate, seed)
        arrivals = trace.arrivals
        t0 = time.perf_counter()
        next_round = t0 + interval_s
        i = 0
        rounds = 0
        try:
            while i < len(arrivals):
                now = time.perf_counter()
                while i < len(arrivals) \
                        and arrivals[i].at <= now - t0:
                    a = arrivals[i]
                    pod = PodSpec(
                        name=a.name, qos=a.qos,
                        requests={CPU: a.cpu, MEM: a.memory})
                    bus.apply(Kind.POD, pod.uid, pod)
                    i += 1
                if now >= next_round:
                    pipeline.submit_round(now=time.time())
                    pipeline.prestage(now=time.time())
                    rounds += 1
                    next_round += interval_s
                    continue
                nxt_arr = (t0 + arrivals[i].at
                           if i < len(arrivals) else next_round)
                wait = min(next_round, nxt_arr) - time.perf_counter()
                if wait > 0:
                    time.sleep(min(wait, 0.005))
            # drain on the same cadence until everything published
            deadline = time.perf_counter() + 30.0
            while time.perf_counter() < deadline:
                pipeline.drain("bench")
                if not sched.cache.pending:
                    break
                pipeline.submit_round(now=time.time())
                rounds += 1
        finally:
            pipeline.stop()
        return {
            "bus": bus, "sched": sched,
            "latency": sched.timelines.stats(),
            "rounds": rounds,
        }

    def facet(rate, seed=23):
        # best-of-2 per arm on the p99: external load only ever ADDS
        # latency, so the min over runs isolates the systematic term —
        # the same spike-immunity argument as _timed()'s min and leg
        # 13's min-vs-min observatory overhead
        def best(run_fn):
            runs = [run_fn(rate, seed) for _ in range(2)]
            return min(
                runs,
                key=lambda r: r["latency"]["all"]["p99_s"] or 1e9,
            )

        fixed = best(run_fixed)
        adaptive = best(run_adaptive)
        f_lat, a_lat = fixed["latency"]["all"], adaptive["latency"]["all"]
        improvement = (
            f_lat["p99_s"] / a_lat["p99_s"]
            if a_lat["p99_s"] else 0.0
        )
        return fixed, adaptive, {
            "rate_pods_per_s": rate,
            "fixed_p50_s": f_lat["p50_s"],
            "fixed_p99_s": f_lat["p99_s"],
            "adaptive_p50_s": a_lat["p50_s"],
            "adaptive_p99_s": a_lat["p99_s"],
            "p99_improvement": improvement,
            "fixed_rounds": fixed["rounds"],
            "adaptive_rounds": adaptive["rounds"],
            "pods": a_lat["count"],
            # equal throughput: both arms placed the full stream
            "equal_throughput": (
                f_lat["count"] == a_lat["count"]
                == adaptive["submitted"]
            ),
            "shed": adaptive["shed"],
        }

    def replay_identical(adaptive):
        """The adaptive arm's recorded batches through the plain
        fixed-round loop: placements + node accounting bit-for-bit."""
        bus, sched = build()
        warm(sched, bus)
        for _reason, at, uids in adaptive["round_log"]:
            for uid in uids:
                pod = adaptive["pods_by_uid"].get(uid)
                if pod is not None:
                    bus.apply(Kind.POD, pod.uid, pod)
            sched.schedule_pending(now=at)
        mine = {u: getattr(p, "node_name", None)
                for u, p in adaptive["bus"].list(Kind.POD).items()}
        theirs = {u: getattr(p, "node_name", None)
                  for u, p in bus.list(Kind.POD).items()}
        if mine != theirs:
            return False
        got = lower_nodes(snapshot_from_bus(
            adaptive["bus"], now=1e9))
        want = lower_nodes(snapshot_from_bus(bus, now=1e9))
        return got.names == want.names and all(
            np.array_equal(getattr(got, f), getattr(want, f))
            for f in STAGED_NODE_FIELDS
        )

    low_fixed, low_adaptive, low = facet(rate_low, seed=23)
    mid_fixed, mid_adaptive, mid = facet(rate_mid, seed=29)
    identical = replay_identical(mid_adaptive)

    # -- the shed point: highest sustainable rate on a rate ladder ----------
    max_rate = float(os.environ.get("KTPU_BENCH_STREAM_RATE_MAX", 16000))
    ladder_s = float(os.environ.get("KTPU_BENCH_STREAM_LADDER_S", 1.5))
    rate = max(2 * rate_mid, 2000.0)
    sustained = rate_mid
    shed_at = None
    prev_duration = duration_s
    duration_s = ladder_s
    try:
        while rate <= max_rate:
            arm = run_adaptive(rate, seed=31)
            ok = (arm["shed"] == 0 and arm["drained"]
                  and arm["bound"] == arm["submitted"]
                  and arm["drain_wall_s"] <= 1.0)
            if not ok:
                shed_at = rate
                break
            sustained = rate
            rate *= 2
    finally:
        duration_s = prev_duration

    return {
        "n_nodes": n_nodes,
        "duration_s": duration_s,
        "interval_s": interval_s,
        "watermark": cfg.watermark,
        "lane_deadline_s": list(cfg.lane_deadline_s),
        "low": low,
        "mid": mid,
        # HEADLINE: adaptive vs fixed p99 at the mid sustained rate
        "p99_improvement_mid": mid["p99_improvement"],
        "p99_improvement_ge_2": mid["p99_improvement"] >= 2.0,
        "adaptive_p99_s": mid["adaptive_p99_s"],
        "fixed_p99_s": mid["fixed_p99_s"],
        "equal_throughput": low["equal_throughput"]
        and mid["equal_throughput"],
        "identical_to_fixed_replay": identical,
        "max_sustained_rate_pods_per_s": sustained,
        "shed_at_rate_pods_per_s": shed_at,
    }


def bench_slo_convergence(repeats):
    """Config #20 (ISSUE 18): the self-tuning serving control plane —
    ONE declared lane SLO, ONE controller parameterization, ONE seeded
    diurnal trace time-dilated to three load regimes (low / mid /
    saturating, testing/arrivals.py regime_scale).

    Leg 18 measured the adaptive trigger under hand-tuned knobs; this
    leg measures the CLOSED LOOP: the operator declares ``ls p99 <=
    5ms`` and starts from a deliberately slack config (ls deadline
    16ms), and the ServingSLOController (docs/DESIGN.md §25) must walk
    the knobs into the target at every regime. The whole leg runs on a
    fine fake-clock grid, so every latency is a deterministic function
    of the knob trajectory — what the record gates is control-plane
    BEHAVIOR (attainment, bounded decisions, replay determinism), not
    this box's solver wall. Facets:

    - **attainment**: at each regime the trailing-window ls p99 ends
      inside the declared target, with zero capacity sheds;
    - **static grid**: the same trace served (controller off) at the
      slack start deadline and at the converged-tight deadline — the
      start config breaches at EVERY regime (the controller earned its
      keep), the tight grid point shows what it converged toward;
    - **bounded + settled**: total knob decisions stay on the halving
      ladder (<= 12), never oscillating;
    - **replay determinism**: re-driving a fresh policy over the
      recorded observation ring reproduces the decision log
      bit-for-bit (the flight-recorder/debug-mux audit story).
    """
    from koordinator_tpu.apis.extension import QoSClass, ResourceName
    from koordinator_tpu.apis.types import NodeMetric, NodeSpec
    from koordinator_tpu.client.bus import APIServer, Kind
    from koordinator_tpu.client.wiring import wire_scheduler
    from koordinator_tpu.control.slo import (
        ServingSLOController,
        SLOSpec,
        replay_decisions,
    )
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.obs.timeline import PodTimelines
    from koordinator_tpu.scheduler import Scheduler
    from koordinator_tpu.scheduler.streaming import (
        StreamingConfig,
        StreamingLoop,
    )
    from koordinator_tpu.testing.arrivals import (
        REGIMES,
        diurnal_trace,
        regime_scale,
        trace_pods,
    )

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    n_nodes = int(os.environ.get("KTPU_BENCH_SLO_NODES", 16))
    duration_s = float(os.environ.get("KTPU_BENCH_SLO_SECONDS", 6.0))
    rate = float(os.environ.get("KTPU_BENCH_SLO_RATE", 50.0))
    target_s = float(os.environ.get("KTPU_BENCH_SLO_TARGET", 0.005))
    step_s = 0.001
    start_deadlines = (0.002, 0.016, 0.050)
    tight_deadlines = (0.002, 0.004, 0.050)
    spec = SLOSpec(ls=target_s)
    ctl_params = dict(window_s=0.4, reconcile_interval_s=0.05,
                      cooldown_s=0.45, min_samples=2, breach_rounds=2,
                      relax_rounds=8, relax_frac=0.5,
                      waste_threshold=0.5)

    class _NullHist:
        def observe(self, *a, **k):
            pass

    class _StubDevice:
        # the padding signal held at zero: the leg gates the latency
        # loop, not the batch-amortization heuristic
        def mark(self):
            return {"compiles": 0}

        def padding_waste(self):
            return 0.0

    def run_arm(trace, deadlines, with_controller):
        """One fake-clock closed- or open-loop serve of the trace."""
        clock = [100.0]
        bus = APIServer()
        sched = Scheduler(model=PlacementModel(use_pallas=False))
        sched.timelines = PodTimelines(clock=lambda: clock[0],
                                       histogram=_NullHist())
        wire_scheduler(bus, sched)
        for i in range(n_nodes):
            bus.apply(Kind.NODE, f"n{i}", NodeSpec(
                name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
            bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
                node_name=f"n{i}", node_usage={}, update_time=90.0))
        loop = StreamingLoop(
            sched,
            apply_fn=lambda pod: bus.apply(Kind.POD, pod.uid, pod),
            delete_fn=lambda uid: bus.delete(Kind.POD, uid),
            config=StreamingConfig(watermark=64,
                                   lane_deadline_s=deadlines),
            clock=lambda: clock[0], now_fn=lambda: clock[0],
            log=lambda *a: None,
        )
        ctl = None
        if with_controller:
            ctl = ServingSLOController(
                loop, spec, clock=lambda: clock[0],
                device=_StubDevice(), log=lambda *a: None, **ctl_params)
            loop.attach_controller(ctl)
        pairs, _ = trace_pods(trace)
        i, t = 0, 0.0
        end = trace.duration_s + 0.1
        while t <= end + 1e-9:
            clock[0] = 100.0 + t
            while i < len(pairs) and pairs[i][0] <= t + 1e-12:
                loop.submit(pairs[i][1], now=clock[0])
                i += 1
            loop.pump(clock[0])
            t = round(t + step_s, 6)
        final = sched.timelines.stats(
            window_s=max(0.5, 0.25 * trace.duration_s))
        gate = loop.status()["gate"]
        ls = final.get("ls") or {}
        p99 = ls.get("p99_s")
        out = {
            "final_ls_p99_s": p99,
            "attained": p99 is not None and p99 <= target_s,
            "rounds": loop.status()["rounds"],
            "bound": gate["bound"],
            "submitted": gate["submitted"],
            "capacity_shed": gate["shed"]["capacity"],
        }
        if ctl is not None:
            out["decisions"] = ctl.decisions_total()
            out["final_lane_deadline_s"] = list(loop.cfg.lane_deadline_s)
            out["replay_identical"] = replay_decisions(
                spec, ctl.observations(),
                base_deadlines=start_deadlines,
                **ctl_params) == ctl.decisions()
        loop.stop()
        return out

    base = diurnal_trace(seed=13, duration_s=duration_s,
                         rate_pods_per_s=rate)
    regimes = {}
    for label in sorted(REGIMES):
        trace = regime_scale(base, label)
        regimes[label] = {
            "controller": run_arm(trace, start_deadlines, True),
            "static_start": run_arm(trace, start_deadlines, False),
            "static_tight": run_arm(trace, tight_deadlines, False),
        }
    ctl_arms = [r["controller"] for r in regimes.values()]
    return {
        "n_nodes": n_nodes,
        "duration_s": duration_s,
        "rate_pods_per_s": rate,
        "target_ls_p99_s": target_s,
        "start_lane_deadline_s": list(start_deadlines),
        "regimes": regimes,
        # HEADLINE: the closed loop lands the declared SLO everywhere
        # the slack static start breaches it
        "slo_attained_all_regimes": all(a["attained"] for a in ctl_arms),
        "static_start_breaches": all(
            not r["static_start"]["attained"] for r in regimes.values()),
        "static_tight_attains": all(
            r["static_tight"]["attained"] for r in regimes.values()),
        "replay_identical": all(a["replay_identical"] for a in ctl_arms),
        "decisions_total_max": max(a["decisions"] for a in ctl_arms),
        "capacity_shed_total": sum(a["capacity_shed"] for a in ctl_arms),
    }


def bench_outage_failover_churn(repeats):
    """Config #11 (failure-domain hardening): a sidecar-backed churn
    run with the sidecar SIGKILLed mid-churn, under the supervised
    restart + degraded-mode failover stack (service/supervisor.py +
    service/failover.py).

    Reports the outage anatomy: ticks from the kill to the first
    degraded (in-process) solve, ticks spent in degraded mode, wall
    time from the kill to the first post-recovery remote solve, the
    supervisor/failover counters — and ``tick_identical_to_inprocess``,
    the whole point: every tick under the outage must match the
    fault-free in-process run bit for bit."""
    import tempfile

    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.apis.types import (
        ClusterSnapshot,
        NodeMetric,
        NodeSpec,
        PodSpec,
    )
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.service.client import RemoteSolver
    from koordinator_tpu.service.failover import FailoverSolver
    from koordinator_tpu.service.supervisor import SolverSupervisor
    from koordinator_tpu.state.cluster import ClusterDeltaTracker
    from koordinator_tpu.testing.chaos import InProcessSidecar

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    n_nodes = int(os.environ.get("KTPU_BENCH_OUTAGE_NODES", 512))
    dirty_per_tick = int(os.environ.get("KTPU_BENCH_OUTAGE_DIRTY", 16))
    pending_per_tick = 32
    ticks = max(20, int(os.environ.get("KTPU_BENCH_OUTAGE_TICKS", 40)))
    kill_tick = ticks // 3

    def build():
        rng = np.random.default_rng(42)
        nodes = [
            NodeSpec(name=f"n{i}", allocatable={CPU: 64000, MEM: 131072})
            for i in range(n_nodes)
        ]
        metrics = {
            f"n{i}": NodeMetric(
                node_name=f"n{i}",
                node_usage={CPU: int(rng.integers(500, 30000)),
                            MEM: int(rng.integers(512, 65536))},
                update_time=10.0,
            )
            for i in range(n_nodes)
        }
        tracker = ClusterDeltaTracker()
        return ClusterSnapshot(
            nodes=nodes, pods=[], pending_pods=[], node_metrics=metrics,
            now=20.0, delta_tracker=tracker,
        ), tracker

    def run(model, on_tick=None, warm=None):
        snap, tracker = build()
        rng = np.random.default_rng(7)
        snap.pending_pods = []
        model.schedule(snap)  # compile warmup, identical in both runs
        if warm is not None:
            warm()
        log, walls, modes, done_at = [], [], [], []
        for t in range(ticks):
            now = 20.0 + t
            for i in rng.choice(n_nodes, dirty_per_tick, replace=False):
                name = f"n{int(i)}"
                snap.node_metrics[name] = NodeMetric(
                    node_name=name,
                    node_usage={CPU: int(rng.integers(500, 30000)),
                                MEM: int(rng.integers(512, 65536))},
                    update_time=now,
                )
                tracker.mark_node(name)
            snap.pending_pods = [
                PodSpec(
                    name=f"t{t}p{j}",
                    requests={CPU: int(rng.integers(200, 1500)),
                              MEM: int(rng.integers(128, 1024))},
                )
                for j in range(pending_per_tick)
            ]
            snap.now = now
            if on_tick is not None:
                on_tick(t)
            by_uid = {p.uid: p for p in snap.pending_pods}
            t0 = time.time()
            result = model.schedule(snap)
            walls.append(time.time() - t0)
            done_at.append(time.time())
            modes.append(model.last_solver)
            log.append(sorted(result.items()))
            for uid, node in result.items():
                if node is not None:
                    pod = by_uid[uid]
                    pod.node_name = node
                    pod.assign_time = now
                    snap.pods.append(pod)
                    tracker.mark_node(node)
            snap.pending_pods = []
        return log, walls, modes, done_at

    tmp = tempfile.mkdtemp(prefix="ktpu-outage-")
    addr = os.path.join(tmp, "solver.sock")
    handles = []

    def spawn():
        handle = InProcessSidecar(addr)
        handles.append(handle)
        return handle

    # respawn backoff deliberately exceeds the failover threshold's
    # worth of tick budgets PLUS the local path's cold compile (~2s on
    # CPU): a faster restart heals inside the client's own retries and
    # the leg measures nothing
    supervisor = SolverSupervisor(
        addr, spawn_fn=spawn, probe_interval_s=0.2,
        backoff_base_s=8.0, backoff_cap_s=8.0, ready_timeout_s=60.0,
    ).start()
    remote = RemoteSolver(addr, timeout=60.0, backoff_base_s=0.01,
                          backoff_cap_s=0.05)
    backend = FailoverSolver(remote, failure_threshold=2,
                             recovery_probes=2)
    model = PlacementModel(config=SolverConfig(unroll=BENCH_UNROLL),
                           backend=backend, use_pallas=False)
    backend.on_flip_back = model.reset_staging
    kill_at = {"wall": None}

    recovery_wait_tick = kill_tick + max(4, (ticks - kill_tick) // 2)

    def on_tick(t):
        if t == kill_tick:
            kill_at["wall"] = time.time()
            handles[-1].kill()
        elif t == recovery_wait_tick:
            # deterministic recovery point: block until the supervised
            # respawn is serving so the remaining ticks measure the
            # flip-back (hysteresis probes + full-restage establish)
            deadline = time.time() + 60.0
            while time.time() < deadline:
                if supervisor.status()["state"] == "running":
                    return
                time.sleep(0.05)
        elif t > kill_tick and backend.status()["degraded"]:
            # pace degraded ticks at a scheduler-loop-like cadence so
            # the leg measures recovery against wall time instead of
            # racing every remaining tick through the local solver
            # before the supervised restart lands (the sleep runs
            # OUTSIDE the timed tick wall)
            time.sleep(0.15)

    try:
        log, walls, modes, done_at = run(
            model, on_tick=on_tick,
            # churn ticks carry a deadline so a dead sidecar costs a
            # bounded budget per tick, not a socket timeout; the warmup
            # above ran without one (cold compile)
            warm=lambda: setattr(remote, "deadline_s", 0.5),
        )
        ref_model = PlacementModel(
            config=SolverConfig(unroll=BENCH_UNROLL), use_pallas=False
        )
        ref_log, ref_walls, _ref_modes, _ref_done = run(ref_model)

        degraded_ticks = [
            i for i, m in enumerate(modes)
            if m in ("local-fallback", "local-degraded")
        ]
        recovered_ticks = [
            i for i, m in enumerate(modes)
            if i > kill_tick and m == "remote"
        ]
        healthy_walls = [w for i, w in enumerate(walls)
                        if modes[i] == "remote"]
        status = backend.status()
        return {
            "tick_identical_to_inprocess": log == ref_log,
            "ticks": ticks,
            "kill_tick": kill_tick,
            "ticks_to_first_degraded_solve": (
                degraded_ticks[0] - kill_tick if degraded_ticks else None
            ),
            "ticks_in_degraded_mode": len(degraded_ticks),
            "recovery_s": (
                None if not recovered_ticks or kill_at["wall"] is None
                else done_at[recovered_ticks[0]] - kill_at["wall"]
            ),
            "first_remote_tick_after_outage": (
                recovered_ticks[0] if recovered_ticks else None
            ),
            "supervisor_restarts": supervisor.restarts_total,
            "failovers_to_degraded": status["flips_to_degraded"],
            "failovers_to_remote": status["flips_to_remote"],
            "local_solves": status["local_solves"],
            "tick_wall_s": sum(walls) / len(walls),
            "healthy_tick_wall_s": (
                sum(healthy_walls) / len(healthy_walls)
                if healthy_walls else None
            ),
            "inprocess_tick_wall_s": sum(ref_walls) / len(ref_walls),
            "n_nodes": n_nodes,
            "pending_per_tick": pending_per_tick,
        }
    finally:
        supervisor.stop()
        backend.close()


def bench_audit_overhead_churn(repeats):
    """Config #12 (ISSUE 5): steady-state churn ticks over a wired bus
    with the anti-entropy auditor on vs off.

    The auditor's promise is "runtime proof, not runtime tax": a
    healthy churn run must show ZERO repairs (no false positives) and
    per-tick overhead under the documented bound
    (docs/DESIGN.md §14 — ``overhead_bound`` below) at the default
    cadence, with placements bit-identical to the auditor-less run.
    Records the amortized sweep cost (``audit_s``), the sweep/detect/
    repair counters, and the on/off tick walls."""
    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
    from koordinator_tpu.client.bus import APIServer, Kind
    from koordinator_tpu.client.wiring import wire_scheduler
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.scheduler import Scheduler
    from koordinator_tpu.scheduler.auditor import StateAuditor

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    n_nodes = int(os.environ.get("KTPU_BENCH_AUDIT_NODES", 1000))
    dirty_per_tick = 20
    pending_per_tick = 64
    ticks = max(6, min(repeats * 4, 12))
    interval = 4
    probe_rows = 64
    bound = 0.15  # documented: docs/DESIGN.md §14 probe-budget math
    # (measured ~0.05 at 1000 nodes / 64-row probe / every-4-rounds
    # cadence on CPU — the bound holds a 3x margin)

    def run(with_auditor):
        bus = APIServer()
        sched = Scheduler(
            model=PlacementModel(config=SolverConfig(unroll=BENCH_UNROLL))
        )
        wire_scheduler(bus, sched)
        auditor = None
        if with_auditor:
            auditor = StateAuditor(
                sched, bus, interval_rounds=interval,
                probe_rows=probe_rows,
            )
        rng = np.random.default_rng(42)
        for i in range(n_nodes):
            bus.apply(Kind.NODE, f"n{i}", NodeSpec(
                name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
            bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
                node_name=f"n{i}",
                node_usage={CPU: int(rng.integers(500, 30000)),
                            MEM: int(rng.integers(512, 65536))},
                update_time=10.0))
        walls = []
        audit_s = 0.0
        log = []
        for t in range(ticks):
            now = 20.0 + t
            for i in rng.choice(n_nodes, dirty_per_tick, replace=False):
                name = f"n{int(i)}"
                bus.apply(Kind.NODE_METRIC, name, NodeMetric(
                    node_name=name,
                    node_usage={CPU: int(rng.integers(500, 30000)),
                                MEM: int(rng.integers(512, 65536))},
                    update_time=now))
            for j in range(pending_per_tick):
                pod = PodSpec(
                    name=f"t{t}p{j}",
                    requests={CPU: int(rng.integers(200, 1500)),
                              MEM: int(rng.integers(128, 1024))})
                bus.apply(Kind.POD, pod.uid, pod)
            t0 = time.time()
            if auditor is not None:
                report = auditor.on_round(now=now)
                if report is not None:
                    audit_s += report["duration_s"]
            out = sched.schedule_pending(now=now)
            wall = time.time() - t0
            if t > 1:  # ticks 0-1 pay compiles + the cold full stage
                walls.append(wall)
            elif t == 1 and auditor is not None:
                # warm the probe's gather programs outside the timed
                # window: the bound below is a STEADY-STATE promise
                auditor.sweep("manual", now=now)
            log.append(sorted(out.items()))
        n = max(1, len(walls))
        status = auditor.status() if auditor is not None else {}
        return {
            "tick_wall_s": sum(walls) / n,
            "audit_s_per_tick": audit_s / ticks,
            "sweeps": status.get("sweeps", {}),
            "repairs": sum(status.get("repairs", {}).values()),
            "detections": sum(status.get("detections", {}).values()),
        }, log

    off, off_log = run(False)
    on, on_log = run(True)
    # the honest tax: amortized sweep cost per tick over the baseline
    # tick wall. (A raw on-vs-off wall diff is biased — the second run
    # reuses the first's warm jit caches and reads FASTER.)
    overhead = (
        on["audit_s_per_tick"] / off["tick_wall_s"]
        if off["tick_wall_s"] else 0.0
    )
    return {
        "tick_wall_s": on["tick_wall_s"],
        "tick_wall_off_s": off["tick_wall_s"],
        "audit_s": on["audit_s_per_tick"],
        "audit_sweeps": on["sweeps"],
        # both MUST be 0 on a healthy run: a false-positive repair
        # would mean the auditor itself perturbs correct state
        "audit_detections": on["detections"],
        "audit_repairs": on["repairs"],
        "overhead_ratio": overhead,
        "overhead_bound": bound,
        "within_bound": overhead <= bound,
        "identical_with_auditor": on_log == off_log,
        "n_nodes": n_nodes,
        "dirty_per_tick": dirty_per_tick,
        "pending_per_tick": pending_per_tick,
        "ticks": ticks,
        "audit_interval_rounds": interval,
        "audit_probe_rows": probe_rows,
    }


def bench_concurrent_solve(repeats):
    """Config #10 (PR 8): 8 concurrent sidecar clients hammering one
    solver — the admission gate's coalescing vs the per-connection
    inline baseline.

    Every client ships the same full-state plain request (same base
    fingerprint), barrier-synced per round so the 8 requests genuinely
    overlap. Baseline: ``PlacementService(admission=False)`` — the
    pre-gate behavior, 8 handler threads racing the device through the
    jit cache. Gated: the admission gate coalesces waiting same-base
    requests into one segmented device dispatch (staging the [N,R]
    world once instead of 8x). Recorded: per-request p50/p99 for both
    paths, the achieved coalesce ratio, and shed counts — the
    acceptance bar is gated p99 < inline p99."""
    import tempfile
    import threading

    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.service.admission import (
        AdmissionConfig,
        solve_coalesced,
    )
    from koordinator_tpu.service.client import PlacementClient
    from koordinator_tpu.service.codec import (
        SolveRequest,
        decode_response,
        encode_request,
        read_frame,
        write_frame,
    )
    from koordinator_tpu.service.server import (
        PlacementService,
        solve_from_request,
    )

    # overhead-dominated shape ON PURPOSE: the gate's win is amortizing
    # per-request fixed costs (staging, dispatch, GIL convoy) across
    # coalesced callers, so the leg measures exactly that regime; the
    # solve-compute-bound regime is configs #1-#9's territory
    n_nodes = int(os.environ.get("KTPU_BENCH_CONC_NODES", 500))
    n_pods = int(os.environ.get("KTPU_BENCH_CONC_PODS", 32))
    n_clients = 8
    warmup = 2
    rounds = warmup + max(30, repeats * 5)

    rng = np.random.default_rng(0)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, ResourceName.CPU] = 64000
    alloc[:, ResourceName.MEMORY] = 131072
    used = np.zeros_like(alloc)
    used[:, ResourceName.CPU] = rng.integers(0, 30000, n_nodes)
    used[:, ResourceName.MEMORY] = rng.integers(0, 65536, n_nodes)
    node = {
        "alloc": alloc, "used_req": used,
        "usage": np.zeros_like(alloc),
        "prod_usage": np.zeros_like(alloc),
        "est_extra": np.zeros_like(alloc),
        "prod_base": np.zeros_like(alloc),
        "metric_fresh": np.ones(n_nodes, bool),
        "schedulable": np.ones(n_nodes, bool),
    }
    req_cols = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req_cols[:, ResourceName.CPU] = rng.integers(200, 2000, n_pods)
    req_cols[:, ResourceName.MEMORY] = rng.integers(128, 2048, n_pods)
    pods = {
        "req": req_cols, "est": (req_cols * 85) // 100,
        "is_prod": np.zeros(n_pods, bool),
        "is_daemonset": np.zeros(n_pods, bool),
    }
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[ResourceName.CPU] = 1
    weights[ResourceName.MEMORY] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[ResourceName.CPU] = 65
    thresholds[ResourceName.MEMORY] = 95
    params = {
        "weights": weights, "thresholds": thresholds,
        "prod_thresholds": np.zeros(NUM_RESOURCES, np.int32),
    }

    def request():
        return SolveRequest(node=node, pods=pods, params=params)

    # pre-warm every program either path can hit (solo + each possible
    # coalesced lane count), so both runs measure steady state
    solve_from_request(request())
    for k in range(2, n_clients + 1):
        solve_coalesced([request()] * k)

    def run(admission):
        from koordinator_tpu.metrics.registry import Histogram
        from koordinator_tpu.obs.timeline import PodTimelines

        tmp = tempfile.mkdtemp(prefix="ktpu-bench-conc-")
        addr = os.path.join(tmp, "solver.sock")
        service = PlacementService(addr, admission=admission)
        service.start()
        barrier = threading.Barrier(n_clients)
        lats = [[] for _ in range(n_clients)]
        failures = []
        # per-request submit->bind timelines (obs/timeline.py — the
        # same machinery the wired scheduler feeds): every pod in a
        # request binds when its response lands, so the request
        # timeline IS each of its pods' submit->bind wall
        timelines = PodTimelines(
            capacity=1 << 16, completed_capacity=1 << 16,
            histogram=Histogram("bench_conc_e2e", label_names=("lane",)),
        )

        # every client ships the SAME bytes: encode once so the round
        # measures queue+solve+response, not 8x redundant client-side
        # npz packing fighting over the GIL
        payload = encode_request(request())

        def client(i):
            try:
                with PlacementClient(addr, timeout=600.0) as c:
                    stream = c._stream
                    for r in range(rounds):
                        barrier.wait(timeout=600)
                        uid = f"c{i}r{r}"
                        timelines.submit(uid, lane="ls")
                        t0 = time.time()
                        write_frame(stream, payload)
                        stream.flush()
                        resp = decode_response(read_frame(stream))
                        wall = time.time() - t0
                        assert resp.error == ""
                        assert (resp.assignments >= 0).any()
                        if r >= warmup:
                            timelines.published(uid)
                            lats[i].append(wall)
                        else:
                            timelines.forget(uid)
            except Exception as e:  # surface, don't hang the barrier
                failures.append(f"{type(e).__name__}: {e}")
                barrier.abort()

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        status = service.status()
        service.stop()
        if failures:
            raise RuntimeError(f"bench client failed: {failures[0]}")
        flat = np.asarray([w for per in lats for w in per])
        return flat, status, timelines.stats()

    inline_lat, _, _ = run(False)
    gated_lat, status, gated_timeline = run(True)
    adm = status["admission"]
    return {
        # per-pod submit->bind from the new timelines (ISSUE 7): the
        # concurrent-clients metric ROADMAP item 2 regresses against
        "pod_submit_bind_p50_s": gated_timeline["all"]["p50_s"],
        "pod_submit_bind_p99_s": gated_timeline["all"]["p99_s"],
        "pod_submit_bind_count": gated_timeline["all"]["count"],
        "p50_s": float(np.percentile(gated_lat, 50)),
        "p99_s": float(np.percentile(gated_lat, 99)),
        "inline_p50_s": float(np.percentile(inline_lat, 50)),
        "inline_p99_s": float(np.percentile(inline_lat, 99)),
        "p99_speedup_vs_inline": float(
            np.percentile(inline_lat, 99) / np.percentile(gated_lat, 99)
        ),
        "coalesce_ratio": adm["coalesce_ratio"],
        "coalesced_requests": adm["coalesced_requests_total"],
        "requests_total": adm["requests_total"],
        "shed": adm["shed"],
        "coalesce_window_s": AdmissionConfig().coalesce_window_s,
        "n_clients": n_clients,
        "n_nodes": n_nodes,
        "n_pods_per_request": n_pods,
        "rounds_timed": rounds - warmup,
    }


def bench_rebalance(repeats):
    """Config #5: the COMPLETE descheduler LowNodeLoad Balance pass at
    5k nodes / 30k running pods — classification + debounce + node sort
    + per-node victim sort (full PodSorter chain) + continueEviction
    headroom accounting, emitting the ordered eviction sequence. Checked
    against the independent scalar transliteration of
    low_node_load.go:134-326 (oracle/rebalance.py) at full shape."""
    from koordinator_tpu.apis.extension import QoSClass, ResourceName
    from koordinator_tpu.apis.types import (
        ClusterSnapshot,
        NodeMetric,
        NodeSpec,
        PodSpec,
    )
    from koordinator_tpu.descheduler import (
        LowNodeLoad,
        LowNodeLoadArgs,
        NodePool,
    )
    from koordinator_tpu.descheduler.framework import Evictor
    from koordinator_tpu.oracle.rebalance import RebalanceOracle

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    n_nodes, n_pods = 5000, 30000
    t_build0 = time.time()
    rng = np.random.default_rng(5)
    # skewed pod placement (squared uniform) so a tail of nodes crosses
    # the high threshold; node usage = Σ pod usage + a system share
    pod_node = (rng.random(n_pods) ** 2 * n_nodes).astype(np.int64)
    pod_cpu = rng.integers(200, 4000, n_pods)
    pod_mem = rng.integers(128, 4096, n_pods)
    qos_pool = [QoSClass.NONE, QoSClass.LS, QoSClass.BE]
    nodes, metrics, pods = [], {}, []
    pods_by_node = {}
    for j in range(n_pods):
        pod = PodSpec(
            name=f"p{j}",
            node_name=f"n{pod_node[j]}",
            requests={CPU: int(pod_cpu[j]), MEM: int(pod_mem[j])},
            qos=qos_pool[j % 3],
            priority=int((j % 4) * 1000),
            creation_time=float(j % 977),
        )
        pods.append(pod)
        pods_by_node.setdefault(pod.node_name, []).append(pod)
    for i in range(n_nodes):
        name = f"n{i}"
        nodes.append(NodeSpec(
            name=name, allocatable={CPU: 64000, MEM: 131072}
        ))
        on_node = pods_by_node.get(name, [])
        metrics[name] = NodeMetric(
            node_name=name,
            node_usage={
                CPU: min(sum(p.requests[CPU] for p in on_node) + 500,
                         64000),
                MEM: min(sum(p.requests[MEM] for p in on_node) + 1024,
                         131072),
            },
            pod_usages={
                p.uid: {CPU: p.requests[CPU], MEM: p.requests[MEM]}
                for p in on_node
            },
            update_time=100.0,
        )
    snapshot = ClusterSnapshot(
        nodes=nodes, pods=pods, node_metrics=metrics, now=120.0
    )
    args = LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={CPU: 45, MEM: 60},
        high_thresholds={CPU: 65, MEM: 80},
    )])
    build_s = time.time() - t_build0

    class RecordingEvictor(Evictor):
        def _do_evict(self, snapshot, pod, reason):
            return True

    plugin = LowNodeLoad(args)
    state = {}

    def sweep():
        evictor = RecordingEvictor()
        plugin.balance(snapshot, evictor)
        state["seq"] = [(p.node_name, p.uid) for p in evictor.evicted]
        return np.asarray([len(state["seq"])])

    best, _warm, _out = _timed(sweep, repeats)
    best_p, p99_s = _lat_stats(sweep, (), max(100, repeats))
    best = min(best, best_p)

    result = {
        "sweeps_per_sec": 1.0 / best,
        "p99_s": p99_s,
        "wall_ms": best * 1000,
        "nodes": n_nodes,
        "pods": n_pods,
        "evictions": len(state["seq"]),
        "scope": "full sweep: classify+debounce+sort+victims+headroom",
        # host-only sweep: lower = snapshot build, nothing stages
        **_leg_times(best, lower_s=build_s, stage_s=0.0),
    }
    if _oracle_enabled():
        t0 = time.time()
        want = RebalanceOracle(args).sweep(snapshot)
        result["oracle_wall_s"] = time.time() - t0
        result["identical_to_oracle"] = state["seq"] == want
        result["oracle_check_shape"] = "full"
        result["nodes_drained"] = len({n for n, _ in want})
    return result


def bench_sharded(repeats):
    """Multi-device solve throughput when the env has >1 device; else a
    smoke timing of the 8-device virtual-CPU dryrun (so shard_solver
    regressions are at least visible in the captured JSON)."""
    import jax

    devices = jax.devices()
    if len(devices) > 1:
        from koordinator_tpu.ops.binpack import SolverConfig
        from koordinator_tpu.parallel.mesh import (
            make_mesh, shard_kernel_solver, shard_node_state, shard_solver,
        )

        n_nodes = int(os.environ.get("KTPU_BENCH_NODES", 5000))
        n_pods = int(os.environ.get("KTPU_BENCH_PODS", 10000))
        state, pods, params = _problem(n_nodes, n_pods)
        mesh = make_mesh(devices)
        sstate = shard_node_state(state, mesh)
        scan = shard_solver(mesh, SolverConfig(unroll=BENCH_UNROLL))
        scan_fn = lambda s, p, pr: scan(s, p, pr)
        from koordinator_tpu.parallel.mesh import (
            distributed_kernel_supported,
        )

        kern_fn = None
        if devices[0].platform == "tpu" and distributed_kernel_supported():
            # sharded pallas kernel: per-shard VMEM carry, in-kernel
            # per-pod cross-shard winner merge over remote DMAs
            ksolve = shard_kernel_solver(mesh, SolverConfig())
            kern_fn = lambda s, p, pr: (
                lambda r: (r.node_state, r.assign)
            )(ksolve(s, p, pr))

        def cmp(a, b):
            return bool(
                (np.asarray(a[1]) == np.asarray(b[1])).all()
            ) and bool(
                (np.asarray(a[0].used_req) == np.asarray(b[0].used_req)).all()
            )

        best, warmup, _out, solver, win, scan_best, kvs = (
            _pick_kernel_or_scan(
                scan_fn, kern_fn, repeats, (sstate, pods, params), cmp
            )
        )
        p99_s = _p99(win, (sstate, pods, params), max(100, repeats))
        return {
            "mode": "multichip",
            "devices": len(devices),
            "pods_per_sec": n_pods / best,
            "scan_pods_per_sec": n_pods / scan_best,
            "solver": solver,
            "kernel_vs_scan": kvs,
            "p99_s": p99_s,
            "warmup_s": warmup,
            **_leg_times(best),
        }
    from __graft_entry__ import parse_dryrun_json

    t0 = time.time()
    info, detail = {}, None
    try:
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "__graft_entry__.py"),
             "--dryrun-multichip", "8"],
            capture_output=True, text=True, timeout=1800,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        # the driver's failure protocol: machine JSON + typed exit code
        # (no stdout string-matching — ISSUE 10 satellite 1)
        info = parse_dryrun_json(proc.stdout) or {}
        rc = proc.returncode
        ok = rc == 0 and info.get("ok") is True
        reason = info.get("reason")
        detail = info.get("detail")
        if not ok and reason is None:
            reason = "no-dryrun-json"
            detail = (proc.stderr or proc.stdout)[-300:] or "<no output>"
    except subprocess.TimeoutExpired:
        # a hung child (tunnel/env flake: measured 66-90s normally)
        # must cost this ENTRY, never the whole bench record
        ok, rc, reason = False, None, "timeout"
        detail = "dryrun subprocess timeout"
    wall = time.time() - t0
    result = {
        "mode": "dryrun_smoke",
        "devices": 8,
        "ok": ok,
        "rc": rc,
        "reason": reason,
        "wall_s": wall,
    }
    # the MULTICHIP preflight verdict (host-CPU-fingerprint cache
    # scoping + AOT round-trip) rides along so hardware rounds show it
    for key in ("preflight", "kernel_leg"):
        if info.get(key) is not None:
            result[key] = info[key]
    if not ok and detail:
        result["error"] = f"{reason}: {detail}"
    return result


def bench_sharded_churn_50k(repeats):
    """Config #14 (ISSUE 10): steady-state churn over a 50k-node world
    with the NODE AXIS SHARDED 8 ways — the capacity axis, past the
    16k-node ceiling of leg 7, through the sharded delta-staging path.

    Three arms from identical seeds:

    - **sharded delta** (the measured number): the staged world lives
      as a live ``NamedSharding``'d generation (padded to the per-shard
      bucket, split over the mesh once); each tick re-lowers only the
      dirty rows host-side and scatters them into their OWNING SHARD —
      the [N,R] world is never re-split;
    - **sharded full re-shard** (the pre-delta cost): no tracker, every
      tick re-lowers 50k rows and re-device_puts the world across the
      mesh (fewer ticks — each costs seconds, the point is the ratio);
    - **single-device delta** (the oracle): the same churn unsharded —
      per-tick placements and final node accounting must be
      BIT-IDENTICAL (``identical_to_single_device``), and the
      sharded-vs-single wall ratio IS the GSPMD merge overhead on this
      host (on TPU the in-kernel merge collapses it; DESIGN.md §5.1).

    Must run on a >= 8-device mesh: the parent bench process launches
    it through ``--leg`` in a virtual-CPU-forced child."""
    import jax

    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.parallel.mesh import make_mesh2d, node_sharding
    from koordinator_tpu.state.cluster import lower_nodes
    from koordinator_tpu.testing import (
        churn_tick_events,
        churn_world,
        fold_churn_binds,
    )

    devices = jax.devices()
    if len(devices) < 8:
        raise RuntimeError(
            f"leg needs an 8-device mesh, have {len(devices)} — run "
            "through bench.py --leg (virtual-CPU forcing)"
        )
    n_nodes = int(os.environ.get("KTPU_BENCH_SHARD_NODES", 50000))
    n_shards = int(os.environ.get("KTPU_BENCH_SHARD_COUNT", 8))
    dirty_per_tick = int(os.environ.get("KTPU_BENCH_SHARD_DIRTY", 64))
    pending_per_tick = int(os.environ.get("KTPU_BENCH_SHARD_PENDING", 128))
    ticks = max(4, min(repeats * 2, 8))
    full_ticks = 3  # each full-re-shard tick re-lowers the 50k world

    mesh = make_mesh2d(devices, node_shards=n_shards, pod_shards=1)
    sharding = node_sharding(mesh)

    def run(model, with_tracker, n_ticks):
        # one assigned pod per node (vs the shared default 2): at 50k
        # nodes the world build is Python-bound and the churn story
        # needs occupancy, not density
        snap, tracker = churn_world(
            n_nodes, assigned_per_node=1, with_tracker=with_tracker
        )
        rng = np.random.default_rng(7)
        walls = []
        sums = {"lower_s": 0.0, "stage_s": 0.0, "solve_s": 0.0}
        log = []
        for t in range(n_ticks):
            now = 20.0 + t
            by_uid = churn_tick_events(
                snap, tracker, rng, dirty=dirty_per_tick,
                pending=pending_per_tick, t=t, now=now,
            )
            t0 = time.time()
            result = model.schedule(snap)
            wall = time.time() - t0
            if t > 1:  # ticks 0-1 pay compiles + the cold full stage
                walls.append(wall)
                for k in sums:
                    sums[k] += model.last_timings[k]
            log.append(sorted(result.items()))
            fold_churn_binds(snap, tracker, result, by_uid, now)
        n = max(1, len(walls))
        return {
            "tick_wall_s": sum(walls) / n,
            **{k: v / n for k, v in sums.items()},
        }, log, snap

    config = SolverConfig(unroll=BENCH_UNROLL)
    delta, delta_log, delta_snap = run(
        PlacementModel(config=config, sharding=sharding), True, ticks
    )
    reshard, _, _ = run(
        PlacementModel(config=config, sharding=sharding), False, full_ticks
    )
    single, single_log, single_snap = run(
        PlacementModel(config=config), True, ticks
    )

    identical = delta_log == single_log
    if identical:
        got = lower_nodes(delta_snap)
        want = lower_nodes(single_snap)
        identical = got.names == want.names and all(
            np.array_equal(getattr(got, f), getattr(want, f))
            for f in ("alloc", "used_req", "usage", "est_extra")
        )
    from koordinator_tpu.parallel.mesh import shard_node_bucket

    return {
        "mode": "sharded_churn",
        "n_shards": n_shards,
        "n_nodes": n_nodes,
        "staged_nodes": shard_node_bucket(n_nodes, n_shards),
        "dirty_per_tick": dirty_per_tick,
        "pending_per_tick": pending_per_tick,
        "ticks": ticks,
        "pods_per_sec": pending_per_tick / delta["tick_wall_s"],
        "tick_wall_s": delta["tick_wall_s"],
        "lower_s": delta["lower_s"],
        "stage_s": delta["stage_s"],
        "solve_s": delta["solve_s"],
        "full_reshard_tick_wall_s": reshard["tick_wall_s"],
        "speedup_vs_full_reshard": (
            reshard["tick_wall_s"] / delta["tick_wall_s"]
        ),
        "single_device_tick_wall_s": single["tick_wall_s"],
        "merge_overhead_vs_single": (
            delta["tick_wall_s"] / single["tick_wall_s"]
        ),
        "identical_to_single_device": identical,
    }


def bench_shard_scaling_curve(repeats):
    """Config #15 (ISSUE 10): the POD-BATCH axis of the 2-D mesh as a
    measured scaling curve. The workload is one giant pod burst — L
    independent lanes of P pods each against a shared node base (the
    admission gate's coalesce shape) — solved at 1/2/4/8 lane shards on
    the same virtual-CPU mesh. Lanes never communicate, so this axis
    has no per-step merge and should scale near-linearly; the
    acceptance bar is >= 2x pods/s at 8 shards vs 1
    (``speedup_8x``). Every lane must be bit-identical to solving it
    alone on one device, and (oracle half) to the vectorized host
    oracle. ``merge_overhead_ratio`` records the other axis's price at
    the same base shape: the node-sharded solve vs the single chip —
    the per-pod-step cross-shard argmax that the in-kernel merge
    (DESIGN.md §5.1) exists to collapse on real ICI."""
    import jax

    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
    from koordinator_tpu.parallel.mesh import (
        make_mesh2d,
        shard_lane_solver,
        shard_node_state,
        shard_solver,
        stack_pod_lanes,
    )

    devices = jax.devices()
    if len(devices) < 8:
        raise RuntimeError(
            f"leg needs an 8-device mesh, have {len(devices)} — run "
            "through bench.py --leg (virtual-CPU forcing)"
        )
    n_nodes = int(os.environ.get("KTPU_BENCH_LANE_NODES", 2000))
    n_pods = int(os.environ.get("KTPU_BENCH_LANE_PODS", 256))
    n_lanes = int(os.environ.get("KTPU_BENCH_LANE_COUNT", 32))
    config = SolverConfig(unroll=8)  # vmapped lanes: the 32-unroll
    # compile at [L,P] scan shape costs minutes for a few % — not worth
    state, _, params = _problem(n_nodes, n_pods, seed=11)
    from __graft_entry__ import _example_problem

    lane_batches = [
        _example_problem(n_nodes, n_pods, seed=100 + l)[1]
        for l in range(n_lanes)
    ]
    lanes = stack_pod_lanes(lane_batches)
    total = n_lanes * n_pods

    curve = {}
    outs = {}
    for k in (1, 2, 4, 8):
        # assignments-only program: the [L,N,R] per-lane carries are
        # tens of MB per call, and their allocator churn alone makes
        # the small-k legs noisy (measured on the virtual-CPU mesh) —
        # the curve times what the scheduler reads back: placements
        solve = shard_lane_solver(
            make_mesh2d(devices, node_shards=1, pod_shards=k), config,
            want_state=False,
        )
        best, warm, out = _timed(
            lambda s, p, pr: solve(s, p, pr)[1], max(repeats, 4),
            state, lanes, params,
        )
        curve[str(k)] = {
            "pods_per_sec": total / best,
            "wall_s": best,
            "warmup_s": warm,
        }
        outs[k] = np.asarray(out)
    base = curve["1"]["wall_s"]
    speedups = {
        f"speedup_{k}x": base / curve[str(k)]["wall_s"] for k in (2, 4, 8)
    }

    # identity: the 8-shard lanes vs each lane solved alone, single
    # device — bit-identical assignments at every shard count, plus
    # the per-lane node carries through a want_state run (untimed),
    # plus the oracle half below
    single = _obs_jit("bench_lane_single", jax.jit(
        lambda s, p, pr: schedule_batch(s, p, pr, config)[1]
    ))
    single_full = _obs_jit("bench_lane_single_full", jax.jit(
        lambda s, p, pr: schedule_batch(s, p, pr, config)[0]
    ))
    assign8 = outs[8]
    lane_identical = all(
        bool((assign8[l] == np.asarray(
            single(state, lane_batches[l], params)
        )).all())
        for l in range(n_lanes)
    ) and all(
        bool((outs[k] == assign8).all()) for k in (1, 2, 4)
    )
    states8, _ = shard_lane_solver(
        make_mesh2d(devices, node_shards=1, pod_shards=8), config
    )(state, lanes, params)
    carries_identical = all(
        bool((np.asarray(states8.used_req[l]) == np.asarray(
            single_full(state, lane_batches[l], params).used_req
        )).all())
        for l in range(0, n_lanes, max(1, n_lanes // 8))
    )
    result = {
        "mode": "lane_scaling",
        "n_nodes": n_nodes,
        "pods_per_lane": n_pods,
        "lanes": n_lanes,
        "curve": curve,
        **speedups,
        "speedup_8x_ge_2": speedups["speedup_8x"] >= 2.0,
        "lanes_identical_to_single_device": lane_identical,
        "lane_carries_identical": carries_identical,
        **_leg_times(curve["8"]["wall_s"]),
    }
    if _oracle_enabled():
        from koordinator_tpu.oracle.vectorized import schedule_vectorized

        t0 = time.time()
        oracle_ok = all(
            bool((assign8[l] == schedule_vectorized(
                *_oracle_args(state, lane_batches[l], params)
            )).all())
            for l in range(n_lanes)
        )
        result["oracle_wall_s"] = time.time() - t0
        result["identical_to_oracle"] = oracle_ok
        result["oracle_check_shape"] = "full"

    # the node axis's price at the same shape: per-pod-step cross-shard
    # argmax merge (GSPMD allreduce on this host's virtual mesh)
    mesh_n = make_mesh2d(devices, node_shards=8, pod_shards=1)
    nsolve = shard_solver(mesh_n, config)
    sstate = shard_node_state(state, mesh_n)
    pods0 = lane_batches[0]
    n_best, _warm, n_out = _timed(
        lambda s, p, pr: nsolve(s, p, pr)[1], repeats,
        sstate, pods0, params,
    )
    s_best, _warm2, s_out = _timed(
        lambda s, p, pr: single(s, p, pr), repeats, state, pods0, params,
    )
    result["node_sharded_pods_per_sec"] = n_pods / n_best
    result["single_chip_pods_per_sec"] = n_pods / s_best
    result["merge_overhead_ratio"] = n_best / s_best
    result["node_sharded_identical"] = bool(
        (np.asarray(n_out) == np.asarray(s_out)).all()
    )
    return result


def bench_sharded_churn_100k(repeats):
    """Config #14b (ISSUE 11 satellite): the 100k-node single-domain
    point of the sharded churn leg — ROADMAP item 3's first unmeasured
    checkpoint — recorded beside the 50k number via the same harness
    (``KTPU_BENCH_SHARD_NODES`` honors an explicit override)."""
    os.environ.setdefault("KTPU_BENCH_SHARD_NODES", "100000")
    return bench_sharded_churn_50k(repeats)


def bench_multi_tenant_pool(repeats):
    """Config #16 (ISSUE 11): the multi-tenant solver pool — 16 tenant
    front-ends (two lanes per shard of the 8-device lane mesh), each
    delta-churning its OWN 1024-node world, through ONE shared sidecar
    whose admission gate batches their per-tick solves as lanes of a
    single multi-base dispatch (service/tenancy.py) — vs the same 16
    tenants each on a SOLO sidecar (16 services in this process, equal
    device count). Three measured facets:

    - **throughput + latency**: aggregate pods/s over the timed window
      and per-tenant submit->bind p50/p99 (obs/timeline.PodTimelines,
      the PR 12 machinery), both arms — warmup rounds barrier-synced,
      timed rounds free-running (the open-loop serving shape), each
      arm best-of-2 replays of the same deterministic streams (the
      repo's min-vs-min doctrine). Acceptance: pool >= 2x solo
      aggregate pods/s (``pool_speedup_ge_2``), plus the ``fleet8``
      sub-record measuring the ISSUE-named 8-tenants-vs-8-solo
      checkpoint whenever the headline fleet is larger.
    - **bit-identity**: every tenant's per-round placements through the
      pool equal its solo-sidecar run exactly
      (``tenants_identical_to_solo``) — the isolation contract at bench
      shape, solvable because worlds evolve deterministically per
      (tenant, round).
    - **overload isolation**: a deliberately unfair arrival mix — one
      tenant floods best-effort requests from several connections while
      the others tick paced latency-sensitive work against a small
      queue — must shed the FLOODING tenant (typed overloaded frames)
      while every other tenant completes un-shed; per-tenant shed
      counts land in the JSON (``storm``).

    Runs in the virtual-CPU 8-device child (``--leg``): the pool's lane
    dispatch shards tenants across the mesh, which is exactly the
    "K front-ends, one warm device pod" serving architecture of
    ROADMAP item 2."""
    import tempfile
    import threading

    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.metrics.components import SOLVER_SOLVE_DURATION
    from koordinator_tpu.metrics.registry import Histogram
    from koordinator_tpu.obs.timeline import PodTimelines
    from koordinator_tpu.service.admission import AdmissionConfig
    from koordinator_tpu.service.client import PlacementClient
    from koordinator_tpu.service.codec import (
        SolveRequest,
        decode_response,
        encode_request,
        read_frame,
        write_frame,
    )
    from koordinator_tpu.service.server import PlacementService
    from koordinator_tpu.service.tenancy import tenant_wire_value

    # a compute-weighted front-end tick shape (1024-node worlds — a
    # bucket width, the documented sizing guidance, so staging pays no
    # padding — with 64-pod bursts): at 500x32 BOTH arms drown in wire
    # overhead and the measured pool advantage collapses toward the
    # decode floor (measured: raw dispatch 2.8x but e2e 1.6x at 500x32
    # vs raw 5.2x here)
    n_tenants = int(os.environ.get("KTPU_BENCH_TENANTS", 16))
    n_nodes = int(os.environ.get("KTPU_BENCH_TENANT_NODES", 1024))
    n_pods = int(os.environ.get("KTPU_BENCH_TENANT_PODS", 64))
    warmup = 3
    rounds = warmup + max(32, repeats * 8)
    tenants = [f"tenant-{i}" for i in range(n_tenants)]

    def world(tenant_i):
        rng = np.random.default_rng(1000 + tenant_i)
        alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
        alloc[:, ResourceName.CPU] = 64000
        alloc[:, ResourceName.MEMORY] = 131072
        used = np.zeros_like(alloc)
        used[:, ResourceName.CPU] = rng.integers(0, 30000, n_nodes)
        used[:, ResourceName.MEMORY] = rng.integers(0, 65536, n_nodes)
        node = {
            "alloc": alloc, "used_req": used,
            "usage": np.zeros_like(alloc),
            "prod_usage": np.zeros_like(alloc),
            "est_extra": np.zeros_like(alloc),
            "prod_base": np.zeros_like(alloc),
            "metric_fresh": np.ones(n_nodes, bool),
            "schedulable": np.ones(n_nodes, bool),
        }
        weights = np.zeros(NUM_RESOURCES, np.int32)
        weights[ResourceName.CPU] = 1
        weights[ResourceName.MEMORY] = 1
        thresholds = np.zeros(NUM_RESOURCES, np.int32)
        thresholds[ResourceName.CPU] = 65
        thresholds[ResourceName.MEMORY] = 95
        params = {
            "weights": weights, "thresholds": thresholds,
            "prod_thresholds": np.zeros(NUM_RESOURCES, np.int32),
        }
        return node, params

    def tick_pods(tenant_i, r):
        rng = np.random.default_rng(7_000_000 + tenant_i * 10_000 + r)
        req_cols = np.zeros((n_pods, NUM_RESOURCES), np.int32)
        req_cols[:, ResourceName.CPU] = rng.integers(200, 2000, n_pods)
        req_cols[:, ResourceName.MEMORY] = rng.integers(128, 2048, n_pods)
        return {
            "req": req_cols, "est": (req_cols * 85) // 100,
            "is_prod": np.zeros(n_pods, bool),
            "is_daemonset": np.zeros(n_pods, bool),
        }

    def request(tenant_i, r, lane=None):
        """A PLAIN full-world request for (tenant, round) — the storm
        phase's arrival unit (full worlds make queue pressure cheap to
        generate; the throughput arms below ride the delta protocol)."""
        node, params = world(tenant_i)
        rng = np.random.default_rng(7_000_000 + tenant_i * 10_000 + r)
        node = {k: v.copy() for k, v in node.items()}
        dirty = rng.integers(0, n_nodes, 16)
        node["used_req"][dirty, ResourceName.CPU] = rng.integers(
            0, 40000, dirty.size
        )
        req = SolveRequest(
            node=node, params=params, pods=tick_pods(tenant_i, r),
        )
        adm = {"tenant": tenant_wire_value(tenants[tenant_i])}
        if lane is not None:
            adm["lane"] = np.asarray(lane, np.int64)
        req.admission = adm
        return req

    def tenant_payloads(tenant_i):
        """The tenant's round stream on the WIRE-DELTA protocol — the
        pool's steady-state serving shape (DESIGN §20): round 0
        establishes the staged base (full world + epoch), every later
        round ships 16 dirty rows + that tick's pod burst. Worlds
        evolve deterministically per (tenant, round), so the pool arm
        and the solo arm replay byte-identical streams and their
        placements must match."""
        node, params = world(tenant_i)
        adm = {"tenant": tenant_wire_value(tenants[tenant_i])}
        establish = SolveRequest(
            node={k: v.copy() for k, v in node.items()}, params=params,
            pods=tick_pods(tenant_i, 0),
            node_delta={"epoch": np.asarray(0, np.int64)},
        )
        establish.admission = adm
        out = [encode_request(establish)]
        for r in range(1, rounds):
            rng = np.random.default_rng(
                7_000_000 + tenant_i * 10_000 + r
            )
            idx = rng.choice(n_nodes, 16, replace=False)
            node["used_req"][idx, ResourceName.CPU] = rng.integers(
                0, 40000, idx.size
            )
            delta = {
                "idx": idx.astype(np.int32),
                "base_epoch": np.asarray(r - 1, np.int64),
                "epoch": np.asarray(r, np.int64),
            }
            delta.update({f: node[f][idx] for f in node})
            req = SolveRequest(
                node={}, params=params, pods=tick_pods(tenant_i, r),
                node_delta=delta,
            )
            req.admission = adm
            out.append(encode_request(req))
        return out

    # pre-encode every (tenant, round) payload: both arms replay the
    # same bytes, and client-side npz packing stays out of the timed
    # window (it is identical in both arms anyway)
    payloads = [tenant_payloads(i) for i in range(n_tenants)]

    def run_arm(addresses, nt):
        """Drive the round streams: tenant i talks to ``addresses[i]``
        (all the same address = the pool; distinct = solo sidecars).
        The warmup rounds are barrier-synced (compile warm-down), then
        the timed rounds FREE-RUN — each front-end ticks as fast as its
        responses land, the open-loop serving shape, so the pool's
        continuous batching (and the solo sidecars' independence) both
        express. Returns (wall_s over the timed window, per-tenant
        latency lists, per-tenant assignment logs, per-tenant timeline
        stats, solve-busy seconds)."""
        barrier = threading.Barrier(nt)
        lats = [[] for _ in range(nt)]
        logs = [[] for _ in range(nt)]
        failures = []
        timelines = [
            PodTimelines(
                capacity=1 << 12, completed_capacity=1 << 12,
                histogram=Histogram(f"bench_pool_e2e_{i}",
                                    label_names=("lane",)),
            )
            for i in range(nt)
        ]
        t_timed = [None]  # timed-window start (shared barrier stamp)
        ends = [None] * nt
        busy = [0.0, 0.0]  # solve-busy seconds around the window

        def client(i):
            try:
                with PlacementClient(addresses[i], timeout=600.0) as c:
                    stream = c._stream
                    for r in range(rounds):
                        if r <= warmup:
                            barrier.wait(timeout=600)
                        if r == warmup and i == 0:
                            t_timed[0] = time.time()
                            # busy window opens with the timed rounds so
                            # warmup compiles don't pollute occupancy
                            busy[0] = SOLVER_SOLVE_DURATION.sum()
                        uid = f"t{i}r{r}"
                        timelines[i].submit(uid, lane="ls")
                        t0 = time.time()
                        write_frame(stream, payloads[i][r])
                        stream.flush()
                        resp = decode_response(read_frame(stream))
                        wall = time.time() - t0
                        assert resp.error == "", resp.error
                        logs[i].append(np.asarray(resp.assignments))
                        if r >= warmup:
                            timelines[i].published(uid)
                            lats[i].append(wall)
                        else:
                            timelines[i].forget(uid)
                    ends[i] = time.time()
            except Exception as e:  # surface, don't hang the barrier
                failures.append(f"tenant {i}: {type(e).__name__}: {e}")
                barrier.abort()

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(nt)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=900)
        if failures:
            raise RuntimeError(f"bench client failed: {failures[0]}")
        # the aggregate window closes when the LAST tenant finishes its
        # stream — open-loop aggregate throughput, not one cursor's view
        wall = max(ends) - t_timed[0]
        busy[1] = SOLVER_SOLVE_DURATION.sum()
        return (wall, lats, logs, [t.stats() for t in timelines],
                busy[1] - busy[0])

    def measure_fleet(nt, reps=3):
        """One fleet size, both arms, best-of-``reps`` walls per arm —
        the repo's min-vs-min doctrine (box load only ever ADDS time,
        so the fastest replay of a deterministic stream is the
        systematic measurement; medians swung 0-8% under box load in
        PR 13's paired harness for a known sub-1% effect, and the
        pool-vs-solo ratio at 8 tenants swings ±8% run-to-run at
        best-of-2). Returns (pool_best, solo_best, identical,
        pool_status)."""
        pool_addr = os.path.join(tmp, f"pool{nt}.sock")
        pool = PlacementService(
            pool_addr,
            admission=AdmissionConfig(max_coalesce=nt),
        )
        pool.start()
        pool_best = None
        for _ in range(reps):
            res = run_arm([pool_addr] * nt, nt)
            if pool_best is None or res[0] < pool_best[0]:
                pool_best = res
        pool_status = pool.status()
        pool.stop()

        solo_addrs = [os.path.join(tmp, f"solo{nt}_{i}.sock")
                      for i in range(nt)]
        solos = [PlacementService(a) for a in solo_addrs]
        for svc in solos:
            svc.start()
        solo_best = None
        for _ in range(reps):
            res = run_arm(solo_addrs, nt)
            if solo_best is None or res[0] < solo_best[0]:
                solo_best = res
        for svc in solos:
            svc.stop()
        identical = all(
            len(pool_best[2][i]) == len(solo_best[2][i]) == rounds
            and all(
                np.array_equal(a, b)
                for a, b in zip(pool_best[2][i], solo_best[2][i])
            )
            for i in range(nt)
        )
        return pool_best, solo_best, identical, pool_status

    tmp = tempfile.mkdtemp(prefix="ktpu-bench-pool-")
    (pool_wall, pool_lats, pool_logs, pool_tl, pool_busy), \
        (solo_wall, solo_lats, solo_logs, solo_tl, solo_busy), \
        identical, pool_status = measure_fleet(n_tenants)

    # the ISSUE-named checkpoint rides along whenever the headline
    # fleet is LARGER: 8 tenants vs 8 solo sidecars, reusing the first
    # 8 tenants' streams (a smaller KTPU_BENCH_TENANTS run has no 8
    # payload streams to replay — the checkpoint is skipped, not
    # crashed)
    fleet8 = None
    if n_tenants > 8:
        (w8, _, _, _, _), (sw8, _, _, _, _), ident8, _ = \
            measure_fleet(8)
        timed8 = rounds - warmup
        fleet8 = {
            "pods_per_sec": 8 * timed8 * n_pods / w8,
            "solo_pods_per_sec": 8 * timed8 * n_pods / sw8,
            "pool_speedup_vs_solo": sw8 / w8,
            "pool_speedup_ge_2": sw8 / w8 >= 2.0,
            "tenants_identical_to_solo": ident8,
        }

    timed = rounds - warmup
    total_pods = n_tenants * timed * n_pods
    adm = pool_status["admission"]

    # -- unfair-mix storm: one tenant floods BE, the rest tick LS -----------
    storm = _tenant_storm(
        PlacementService, PlacementClient, AdmissionConfig, tmp,
        request, n_tenants, decode_response, encode_request, read_frame,
        write_frame,
    )

    flat = lambda lls: np.asarray([w for per in lls for w in per])
    per_tenant = {
        tenants[i]: {
            "pool_p50_s": pool_tl[i]["all"]["p50_s"],
            "pool_p99_s": pool_tl[i]["all"]["p99_s"],
            "solo_p50_s": solo_tl[i]["all"]["p50_s"],
            "solo_p99_s": solo_tl[i]["all"]["p99_s"],
        }
        for i in range(n_tenants)
    }
    pool_pps = total_pods / pool_wall
    solo_pps = total_pods / solo_wall
    return {
        "mode": "multi_tenant_pool",
        "n_tenants": n_tenants,
        "n_nodes_per_tenant": n_nodes,
        "n_pods_per_tick": n_pods,
        "rounds_timed": timed,
        "pods_per_sec": pool_pps,
        "solo_pods_per_sec": solo_pps,
        "pool_speedup_vs_solo": pool_pps / solo_pps,
        "pool_speedup_ge_2": pool_pps / solo_pps >= 2.0,
        "tenants_identical_to_solo": identical,
        "p50_s": float(np.percentile(flat(pool_lats), 50)),
        "p99_s": float(np.percentile(flat(pool_lats), 99)),
        "solo_p50_s": float(np.percentile(flat(solo_lats), 50)),
        "solo_p99_s": float(np.percentile(flat(solo_lats), 99)),
        "per_tenant": per_tenant,
        # device occupancy: summed solve-busy seconds over the timed
        # wall — the pool should buy MORE work per wall second on the
        # same devices, not just lower latency
        "pool_device_busy_ratio": pool_busy / max(pool_wall, 1e-9),
        "solo_device_busy_ratio": solo_busy / max(solo_wall, 1e-9),
        "lane_batches": adm["lane_batches_total"],
        "lane_requests": adm["lane_requests_total"],
        "coalesce_ratio": adm["coalesce_ratio"],
        "shed": adm["shed"],
        "storm": storm,
        **({"fleet8": fleet8} if fleet8 is not None else {}),
    }


def _tenant_storm(PlacementService, PlacementClient, AdmissionConfig,
                  tmp, request, n_tenants, decode_response,
                  encode_request, read_frame, write_frame):
    """The deliberately unfair arrival mix (leg 16's isolation facet):
    tenant 0 floods best-effort requests from several parallel
    connections against a small admission queue while every other
    tenant ticks paced latency-sensitive work. The pool must shed the
    flooder — typed ``overloaded`` frames, counted per tenant — while
    the paced tenants all complete; per-tenant shed counts and the
    paced tenants' worst p99 land in the record."""
    import threading

    from koordinator_tpu.service.admission import LANE_BE, LANE_LS

    addr = os.path.join(tmp, "storm.sock")
    # sizing for GUARANTEED pressure: the flood's connection count
    # (n_tenants + 4) exceeds the queue capacity (n_tenants), so the
    # flooder alone can fill it — every paced LS arrival then exercises
    # the fair-share victim scan against a best-effort backlog that is
    # reliably over its share. Capacity still covers the paced tenants
    # alone (n_tenants - 1 outstanding LS), so a paced refusal can only
    # come from genuinely transient full-of-LS instants (client-retried
    # below; the server-side per-tenant shed counters remain the
    # isolation measurement)
    service = PlacementService(
        addr,
        admission=AdmissionConfig(capacity=n_tenants,
                                  max_coalesce=n_tenants),
    )
    service.start()
    stop = threading.Event()
    flood_sent = [0]
    flood_shed = [0]
    paced_errors = []
    paced_lats = [[] for _ in range(n_tenants - 1)]
    flood_payload = encode_request(request(0, 0, lane=LANE_BE))

    def flooder():
        try:
            with PlacementClient(addr, timeout=60.0) as c:
                stream = c._stream
                while not stop.is_set():
                    write_frame(stream, flood_payload)
                    stream.flush()
                    resp = decode_response(read_frame(stream))
                    flood_sent[0] += 1
                    if resp.error.startswith("overloaded"):
                        flood_shed[0] += 1
        except Exception:
            pass  # a severed flood connection is not the measurement

    def paced(i):
        try:
            time.sleep(0.007 * i)  # staggered front-ends, not a gang
            with PlacementClient(addr, timeout=60.0) as c:
                stream = c._stream
                for r in range(10):
                    payload = encode_request(request(i, 100 + r,
                                                     lane=LANE_LS))
                    t0 = time.time()
                    for _attempt in range(20):
                        write_frame(stream, payload)
                        stream.flush()
                        resp = decode_response(read_frame(stream))
                        if not resp.error.startswith("overloaded"):
                            break
                        # a momentary full-of-LS queue refusal is
                        # client-retried (RemoteSolver's behavior); the
                        # SERVER-side per-tenant shed counters remain
                        # the isolation measurement
                        time.sleep(0.01)
                    paced_lats[i - 1].append(time.time() - t0)
                    if resp.error:
                        paced_errors.append(
                            f"tenant {i} round {r}: {resp.error}"
                        )
                    time.sleep(0.03)
        except Exception as e:
            paced_errors.append(f"tenant {i}: {type(e).__name__}: {e}")

    flooders = [threading.Thread(target=flooder)
                for _ in range(n_tenants + 4)]
    paceds = [
        threading.Thread(target=paced, args=(i,))
        for i in range(1, n_tenants)
    ]
    for t in flooders:
        t.start()
    time.sleep(0.1)  # let the flood establish pressure first
    for t in paceds:
        t.start()
    for t in paceds:
        t.join(timeout=300)
    stop.set()
    for t in flooders:
        t.join(timeout=60)
    status = service.status()["admission"]
    service.stop()
    shed_by_tenant = {
        t: row["shed_overloaded"]
        for t, row in status["tenants"].items()
    }
    flood_tenant = "tenant-0"
    paced_flat = [w for per in paced_lats for w in per]
    return {
        "flood_requests": flood_sent[0],
        "flood_shed_client_seen": flood_shed[0],
        "shed_by_tenant": shed_by_tenant,
        # the storm proved something only if the flooder actually got
        # shed — a too-fast drain would make isolation claims vacuous
        "storm_effective": shed_by_tenant.get(flood_tenant, 0) > 0,
        "paced_tenants_unshed": (
            not paced_errors
            and all(v == 0 for t, v in shed_by_tenant.items()
                    if t != flood_tenant)
        ),
        "paced_errors": paced_errors[:3],
        "paced_p99_s_under_storm": (
            float(np.percentile(np.asarray(paced_flat), 99))
            if paced_flat else None
        ),
    }


def bench_preemption_storm(repeats):
    """Config #19 (ISSUE 16): the preemption storm — every node packed
    tight with low-priority preemptible BE residents
    (``testing/chaos.preemption_storm``, same seed → same storm), then
    a wave of high-priority LS arrivals sized so plain fit fails: each
    can place ONLY by evicting a minimal victim set. Three facets:

    - **victim-selection throughput, device vs host**: the same
      evict-as-you-go sweep both ways over the first
      KTPU_BENCH_STORM_ORACLE_PODS arrivals. The host arm is the
      legacy backend's real per-pod cost — the scalar oracle walk
      (scheduler/preemption.find_preemption) plus a FULL cluster
      re-lower after every hit; the device arm is the production path
      (docs/DESIGN.md §24) — one vectorized joint place+evict dispatch
      per preemptor plus a one-row eviction delta
      (state/cluster.evict_resident_rows). Acceptance (budget-gated):
      device >= 10x host. The one-dispatch storm variant
      (``preempt_solve_scan``) rides beside it as scan_pods_per_sec —
      the whole wave's victim sets in a single dispatch.
    - **bit-parity + churn minimality**: the device sweep's per-pod
      (node, ordered victims) answers must equal the oracle's exactly
      (identical_to_oracle), so evictions-per-successful-placement
      lands ON the oracle's minimum (churn_vs_oracle == 1.0) — the
      descheduler gap closed without over-evicting.
    - **time-to-placed under the storm**: all arrivals submitted
      through the streaming intake (leg 18's adaptive trigger) at t0,
      rounds fired until the storm drains; per-pod submit→bind p50/p99
      from the PodTimelines ring. MAX_PREEMPTIONS_PER_ROUND bounds
      evictions per round, so the tail IS the round-cap queue — the
      storm's victims drain 32 preemptors at a time.

    Env knobs: KTPU_BENCH_STORM_NODES / _RPN (residents per node) /
    _ARRIVALS reshape the storm (defaults 1250 x 4 = 5k BE residents,
    1k LS arrivals); _ORACLE_PODS sizes the host-sweep subset (the
    full wave through the scalar walk would take minutes);
    KTPU_BENCH_STORM_PLACE=0 skips the streaming placement arm;
    _DRAIN_S bounds its drain wait."""
    from koordinator_tpu.apis.extension import ResourceName
    from koordinator_tpu.apis.types import PodSpec
    from koordinator_tpu.client.bus import APIServer, Kind
    from koordinator_tpu.client.wiring import wire_scheduler
    from koordinator_tpu.models.placement import PlacementModel
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.scheduler import Scheduler
    from koordinator_tpu.scheduler.preemption import find_preemption
    from koordinator_tpu.scheduler.streaming import (
        StreamingConfig,
        StreamingLoop,
    )
    from koordinator_tpu.state.cluster import (
        evict_resident_rows,
        lower_nodes,
    )
    from koordinator_tpu.testing.chaos import preemption_storm

    n_nodes = int(os.environ.get("KTPU_BENCH_STORM_NODES", 1250))
    rpn = int(os.environ.get("KTPU_BENCH_STORM_RPN", 4))
    n_arrivals = int(os.environ.get("KTPU_BENCH_STORM_ARRIVALS", 1000))
    oracle_pods = int(os.environ.get("KTPU_BENCH_STORM_ORACLE_PODS", 24))
    nodes, residents, arrivals = preemption_storm(
        seed=11, n_nodes=n_nodes, residents_per_node=rpn,
        n_arrivals=n_arrivals,
    )

    def standalone():
        sched = Scheduler(model=PlacementModel(
            config=SolverConfig(unroll=BENCH_UNROLL)))
        for node in nodes:
            sched.add_node(node)
        for pod in residents:
            sched.add_pod(pod)
        return sched

    sched = standalone()
    model = sched.model
    thresholds = np.asarray(model.params.thresholds)
    prod_thresholds = np.asarray(model.params.prod_thresholds)

    def staged_world():
        snapshot = sched.cache.snapshot(now=50.0)
        arrays = lower_nodes(snapshot, **model.lowering_kwargs())
        resident = model.lower_residents(snapshot, arrays)
        return snapshot, arrays, resident, model.resident_world(resident)

    # scan throughput: the whole wave's victim selection in ONE
    # dispatch (compile excluded; the world is never mutated, so the
    # repeat runs time pure dispatch+compute)
    _snap, arrays, resident, world = staged_world()
    scanned = model.preempt_scan_device(
        arrays, resident, arrivals, world=world)
    t0 = time.perf_counter()
    for _ in range(repeats):
        scanned = model.preempt_scan_device(
            arrays, resident, arrivals, world=world)
    scan_wall = (time.perf_counter() - t0) / repeats
    scan_hits = sum(1 for s in scanned if s is not None)

    # device sweep — the production per-pod path with one-row eviction
    # deltas, measured over the oracle subset so the host comparison
    # is apples-to-apples (same pods, same evict-as-you-go semantics)
    sweep = arrivals[:oracle_pods]
    snapshot, arrays, resident, world = staged_world()
    model.select_victims_device(arrays, resident, sweep[0], world=world)
    dev_hits = []
    dev_evictions = 0
    t0 = time.perf_counter()
    for pod in sweep:
        got = model.select_victims_device(
            arrays, resident, pod, world=world)
        if got is not None:
            node_name, uids = got
            dev_evictions += len(uids)
            evict_resident_rows(
                snapshot, arrays, resident, node_name, uids,
                **model.lowering_kwargs(),
            )
        dev_hits.append(got)
    device_wall = time.perf_counter() - t0

    # host sweep — the legacy backend's cost shape verbatim: oracle
    # walk, then a full cluster re-lower so later preemptors see the
    # eviction
    h_snapshot = sched.cache.snapshot(now=50.0)
    h_arrays = lower_nodes(h_snapshot, **model.lowering_kwargs())
    host_hits = []
    host_evictions = 0
    t0 = time.perf_counter()
    for pod in sweep:
        got = find_preemption(
            h_snapshot, pod, arrays=h_arrays,
            thresholds=thresholds, prod_thresholds=prod_thresholds,
        )
        if got is None:
            host_hits.append(None)
            continue
        node_name, victims = got
        host_hits.append((node_name, [v.uid for v in victims]))
        host_evictions += len(victims)
        wanted = {v.uid for v in victims}
        h_snapshot.pods = [
            p for p in h_snapshot.pods if p.uid not in wanted
        ]
        h_arrays = lower_nodes(h_snapshot, **model.lowering_kwargs())
    host_wall = time.perf_counter() - t0

    placements = sum(1 for h in host_hits if h is not None)
    out = {
        "n_nodes": n_nodes,
        "n_residents": len(residents),
        "n_arrivals": n_arrivals,
        "oracle_pods": oracle_pods,
        "scan_pods_per_sec": n_arrivals / scan_wall,
        "scan_hits": scan_hits,
        "device_pods_per_sec": len(sweep) / device_wall,
        "host_pods_per_sec": len(sweep) / host_wall,
        "device_vs_host_speedup": host_wall / device_wall,
        "identical_to_oracle": bool(dev_hits == host_hits),
        "placements": placements,
        "evictions_device": dev_evictions,
        "evictions_oracle": host_evictions,
        "churn_vs_oracle": (
            dev_evictions / host_evictions if host_evictions else 1.0
        ),
    }

    if os.environ.get("KTPU_BENCH_STORM_PLACE", "1") != "0":
        from koordinator_tpu.metrics.components import PREEMPT_VICTIMS
        from koordinator_tpu.parallel.mesh import pow2_quarter_bucket

        CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
        bus = APIServer()
        sched_p = Scheduler(model=PlacementModel(
            config=SolverConfig(unroll=BENCH_UNROLL)))
        wire_scheduler(bus, sched_p)
        for node in nodes:
            bus.apply(Kind.NODE, node.name, node)
        for pod in residents:
            bus.apply(Kind.POD, pod.uid, pod)
        # leg 18's warm discipline: compile-warm every pending-bucket
        # variant the draining wave can shrink through, or the
        # latency tail measures the compiler (the pods never place —
        # the world is packed — so deleting them restores it exactly)
        buckets = sorted({1} | {
            pow2_quarter_bucket(s, floor=8)
            for s in range(1, n_arrivals + 1)
        })
        for b, size in enumerate(buckets):
            uids = []
            for j in range(size):
                pod = PodSpec(name=f"stormwarm{b}x{j}",
                              requests={CPU: 1, MEM: 1})
                bus.apply(Kind.POD, pod.uid, pod)
                uids.append(pod.uid)
            sched_p.schedule_pending(now=60.0)
            for uid in uids:
                bus.delete(Kind.POD, uid)
        sched_p.timelines.reset()
        evicted0 = PREEMPT_VICTIMS.value({"outcome": "evicted"})
        loop = StreamingLoop(
            sched_p,
            apply_fn=lambda pod: bus.apply(Kind.POD, pod.uid, pod),
            delete_fn=lambda uid: bus.delete(Kind.POD, uid),
            config=StreamingConfig(watermark=64),
            pipelined=True, log=lambda *a: None,
        )
        t0 = time.perf_counter()
        try:
            for pod in arrivals:
                loop.submit(pod)
            drained = loop.drain(timeout_s=float(
                os.environ.get("KTPU_BENCH_STORM_DRAIN_S", 600)))
        finally:
            loop.stop()
        storm_wall = time.perf_counter() - t0
        lat = sched_p.timelines.stats()
        st = loop.status()
        out.update({
            "storm_drained": bool(drained),
            "storm_wall_s": storm_wall,
            "storm_rounds": st["rounds"],
            "storm_bound": st["gate"]["bound"],
            "storm_evictions": (
                PREEMPT_VICTIMS.value({"outcome": "evicted"}) - evicted0
            ),
            "time_to_placed_p50_s": lat["all"]["p50_s"],
            "time_to_placed_p99_s": lat["all"]["p99_s"],
        })
    return out


def bench_rebalance_storm(repeats):
    """Config #22 (ISSUE 20): the rebalance storm — a large imbalanced
    cluster (half the nodes hot over the high threshold, half cold)
    where one LoadAware Balance pass proposes thousands of evictions.
    Three facets:

    - **sweep throughput, device vs host**: the same ordered
      eviction walk both ways over the same world. The host arm is the
      reference-shaped per-pod Python loop (the bit-parity oracle kept
      verbatim in descheduler/loadaware.py); the device arm flattens
      the host-ordered candidate list into ONE ``lax.scan``
      (ops/rebalance.run_balance_sweep) and replays its decision
      streams through the evictor. The shared head (classification,
      scoring, sorting) rides inside both timings — this is
      whole-balance() wall, not kernel-only.
    - **bit-parity + churn**: the device sweep's eviction sequence
      (victim sets AND order) must equal the host walk's exactly
      (identical_to_oracle), so churn_vs_oracle == 1.0.
    - **budget-bounded eviction rate**: the same wave through a
      tightly budgeted MigrationArbiter (max_per_node=1): admitted
      evictions stop exactly at nodes-over-threshold, every refusal a
      typed counted deferral (budget_bounded gates both).

    Env knobs: KTPU_BENCH_REBALANCE_NODES / _PPN (pods per hot node)
    reshape the world (defaults 400 x 10 = 2k candidate pods on the
    200 hot nodes)."""
    from koordinator_tpu.apis.extension import QoSClass, ResourceName
    from koordinator_tpu.apis.types import (
        ClusterSnapshot,
        NodeMetric,
        NodeSpec,
        PodSpec,
    )
    from koordinator_tpu.control.migration import (
        MigrationArbiter,
        MigrationBudget,
    )
    from koordinator_tpu.descheduler import (
        LowNodeLoad,
        LowNodeLoadArgs,
        NodePool,
    )
    from koordinator_tpu.descheduler.framework import Evictor

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    n_nodes = int(os.environ.get("KTPU_BENCH_REBALANCE_NODES", 400))
    ppn = int(os.environ.get("KTPU_BENCH_REBALANCE_PPN", 10))
    rng = np.random.default_rng(22)

    def build_world():
        nodes, pods, metrics = [], [], {}
        for i in range(n_nodes):
            hot = i % 2 == 0
            node = NodeSpec(
                name=f"rb-n{i}",
                allocatable={CPU: 32000, MEM: 65536},
            )
            nodes.append(node)
            pod_usages = {}
            if hot:
                for j in range(ppn):
                    pod = PodSpec(
                        name=f"rb-p{i}-{j}", node_name=node.name,
                        requests={CPU: 200, MEM: 256},
                        qos=QoSClass.BE,
                        priority=int(rng.integers(0, 3) * 1000),
                        creation_time=float(rng.integers(0, 50)),
                    )
                    pods.append(pod)
                    pod_usages[pod.uid] = {
                        CPU: int(rng.integers(1500, 3200)),
                        MEM: int(rng.integers(2048, 6000)),
                    }
            usage = (
                {CPU: int(rng.integers(27000, 31000)),
                 MEM: int(rng.integers(56000, 64000))}
                if hot else
                {CPU: int(rng.integers(500, 3000)),
                 MEM: int(rng.integers(1024, 6000))}
            )
            metrics[node.name] = NodeMetric(
                node_name=node.name, node_usage=usage,
                pod_usages=pod_usages, update_time=100.0,
            )
        return ClusterSnapshot(nodes=nodes, pods=pods,
                               node_metrics=metrics, now=120.0)

    snapshot = build_world()
    pool = NodePool(low_thresholds={CPU: 30, MEM: 30},
                    high_thresholds={CPU: 60, MEM: 60})

    class Sink(Evictor):
        """Approves everything, mutates nothing: repeated sweeps time
        the same world."""

        def _do_evict(self, snap, pod, reason):
            return True

    def run(backend):
        sequences = []
        t0 = time.perf_counter()
        for _ in range(repeats):
            plugin = LowNodeLoad(LowNodeLoadArgs(
                node_pools=[pool], backend=backend))
            sink = Sink()
            plugin.balance(snapshot, sink)
            sequences.append([(p.node_name, p.uid) for p in sink.evicted])
        return (time.perf_counter() - t0) / repeats, sequences[-1]

    # warm the sweep kernel's candidate bucket off the clock
    run("device")
    device_wall, device_seq = run("device")
    host_wall, host_seq = run("host")

    # the budget-bounded arm: one admitted eviction per hot node, the
    # rest typed deferrals — the arbitrated control plane under load
    arb = MigrationArbiter(MigrationBudget(max_per_node=1))
    plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[pool],
                                         backend="device"))
    sink = Sink(arbiter=arb)
    t0 = time.perf_counter()
    plugin.balance(snapshot, sink)
    budget_wall = time.perf_counter() - t0
    status = arb.status()
    hot_nodes = {n for n, _ in host_seq}
    budget_bounded = (
        len(sink.evicted) <= len(hot_nodes)
        and all(c <= 1 for c in status["window_nodes"].values())
        and status["deferred_total"] > 0
        and set(status["deferred_by_reason"]) <= {"node-budget",
                                                  "cooldown"}
    )

    return {
        "n_nodes": n_nodes,
        "n_candidates": ppn * (n_nodes // 2),
        "evictions": len(host_seq),
        "device_wall_s": device_wall,
        "host_wall_s": host_wall,
        "device_vs_host_speedup": host_wall / device_wall,
        "device_evictions_per_sec": len(device_seq) / device_wall,
        "identical_to_oracle": bool(device_seq == host_seq),
        "churn_vs_oracle": (
            len(device_seq) / len(host_seq) if host_seq else 1.0
        ),
        "budgeted_evictions": len(sink.evicted),
        "budgeted_deferrals": status["deferred_total"],
        "budgeted_eviction_rate": (
            len(sink.evicted) / budget_wall if budget_wall else 0.0
        ),
        "budget_bounded": bool(budget_bounded),
    }


#: legs that need a REAL multi-device mesh — the parent bench process
#: may hold a single-device backend (or a TPU tunnel), so these run in
#: a fresh interpreter with the virtual-CPU 8-device forcing and hand
#: back one JSON line (rc + typed reason on failure, like the dryrun)
SUBPROCESS_LEGS = {
    "14_sharded_churn_50k": bench_sharded_churn_50k,
    "14b_sharded_churn_100k": bench_sharded_churn_100k,
    "15_shard_scaling_curve": bench_shard_scaling_curve,
    "16_multi_tenant_pool": bench_multi_tenant_pool,
}


def _leg_subprocess(name, timeout_s=3600):
    """Run ``SUBPROCESS_LEGS[name]`` via ``bench.py --leg`` on a forced
    8-device virtual CPU mesh; the child's JSON result (with its own
    device fingerprint) becomes the matrix entry."""
    import re

    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    # single-threaded Eigen per virtual device: with 8 device threads
    # alive, per-op intra-op fork-joins oversubscribe the host and the
    # small-shard-count legs time 3-10x noisier (measured); one thread
    # per device is also the honest analogue of one core per chip
    if "--xla_cpu_multi_thread_eigen" not in flags:
        flags += " --xla_cpu_multi_thread_eigen=false"
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--leg", name],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"error": "leg subprocess timeout", "rc": None,
                "reason": "timeout"}
    from __graft_entry__ import parse_last_json

    out = parse_last_json(proc.stdout, "leg")
    if out is None or out.get("leg") != name:
        return {
            "error": "no leg JSON in child output",
            "rc": proc.returncode,
            "reason": "no-leg-json",
            "tail": (proc.stderr or proc.stdout)[-300:],
        }
    result = out["result"]
    if proc.returncode != 0 and "error" not in result:
        result["error"] = f"child rc={proc.returncode}"
    result["rc"] = proc.returncode
    result["subprocess_wall_s"] = time.time() - t0
    return result


def _leg_child(name):
    """Child half of :func:`_leg_subprocess`: run one leg in THIS
    process (the env forcing already happened before jax imported) and
    print the one-line JSON result, device fingerprint included."""
    from koordinator_tpu.utils.compilation_cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache()
    from koordinator_tpu.obs.device import DEVICE_OBS

    repeats = max(1, int(os.environ.get("KTPU_BENCH_REPEATS", 3)))
    mark = DEVICE_OBS.mark()
    try:
        result = SUBPROCESS_LEGS[name](repeats)
    except Exception as e:
        print(json.dumps({"leg": name, "result": {
            "error": f"{type(e).__name__}: {e}",
        }}))
        return 1
    try:
        result["device"] = DEVICE_OBS.fingerprint(mark)
    except Exception as e:
        result["device"] = {"error": f"{type(e).__name__}: {e}"}

    def _round(obj):
        if isinstance(obj, dict):
            return {k: _round(v) for k, v in obj.items()}
        if isinstance(obj, float):
            return round(obj, 4)
        return obj

    print(json.dumps({"leg": name, "result": _round(result)}))
    return 0


def bench_warm_start():
    """Cold-start blackout mitigation (VERDICT r4 weak #5): seed the AOT
    executable cache with the flagship program, then a FRESH interpreter
    deserializes and runs it — the restart blackout a failed-over
    control plane actually pays. (The persistent XLA cache alone still
    re-traces the 32-unrolled scan every process — seconds of Python —
    so the solver warm path serializes the compiled executable,
    utils/compilation_cache.ExecutableCache.)"""
    import jax

    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
    from koordinator_tpu.utils.compilation_cache import ExecutableCache

    n_nodes = int(os.environ.get("KTPU_BENCH_NODES", 5000))
    n_pods = int(os.environ.get("KTPU_BENCH_PODS", 10000))
    key = f"bench-flagship-{n_nodes}x{n_pods}-unroll{BENCH_UNROLL}"
    state, pods, params = _problem(n_nodes, n_pods)
    config = SolverConfig(unroll=BENCH_UNROLL)
    t0 = time.time()
    ExecutableCache().get_or_compile(
        key,
        jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, config)),
        state, pods, params,
    )
    seed_s = time.time() - t0

    # the child must resolve to the SAME backend as this process (the
    # sitecustomize hook re-forces the ambient platform, so the env var
    # alone is not enough — mirror tests/conftest.py's config update)
    platform = jax.config.jax_platforms or jax.default_backend()
    code = (
        "import time, os\n"
        "import jax\n"
        f"jax.config.update('jax_platforms', {platform!r})\n"
        "from koordinator_tpu.utils.compilation_cache import "
        "ExecutableCache\n"
        "import numpy as np\n"
        "from koordinator_tpu.testing import example_problem\n"
        "n = int(os.environ.get('KTPU_BENCH_NODES', 5000))\n"
        "p = int(os.environ.get('KTPU_BENCH_PODS', 10000))\n"
        "state, pods, params = example_problem(n, p)\n"
        # the timed window covers what a restarted solver actually
        # pays: backend/device init (first jax.devices() inside load),
        # executable deserialization, transfer, execute, readback
        "t0 = time.time()\n"
        f"fn = ExecutableCache().load({key!r})\n"
        "assert fn is not None, 'executable cache miss'\n"
        "t_call = time.time()\n"
        "out = fn(state, pods, params)\n"
        "np.asarray(out[1])\n"
        "end = time.time()\n"
        "print('WARM_CALL', end - t_call)\n"
        "print('WARM_WARMUP', end - t0)\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=600, env=dict(os.environ),
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        values = {}
        for line in proc.stdout.splitlines():
            if line.startswith(("WARM_WARMUP", "WARM_CALL")):
                values[line.split()[0]] = float(line.split()[1])
        if "WARM_WARMUP" in values:
            return {
                # fresh-process restart cost: device init +
                # deserialization + first solve (readback forced);
                # compare against the flagship's cold warmup_s in this
                # same JSON
                "warm_warmup_s": values["WARM_WARMUP"],
                "first_solve_s": values.get("WARM_CALL"),
                "seed_compile_s": seed_s,
                "mode": "aot_executable",
            }
        return {"warm_warmup_s": None,
                "error": (proc.stderr or proc.stdout)[-400:]}
    except subprocess.TimeoutExpired:
        return {"warm_warmup_s": None, "error": "timeout"}


#: the warm-pool child: a fresh interpreter builds a bus-wired
#: scheduler over a seeded cluster and times the recovery window this
#: leg measures. ``restart`` mode is restart-to-first-bind — what a
#: SIGKILLed leader's replacement actually pays after its imports
#: (backend init, trace/compile OR warm-pool deserialize, staging,
#: first solve); the cold arm runs on an empty store, the warm arm on
#: the store the cold arm persisted. ``flip-cold``/``flip-warm`` model
#: the degraded FLIP instead: the scheduler solved remotely all along
#: (the local twin never compiled in-process), the sidecar dies, and
#: the first degraded solve pays either the cold local compile or the
#: prewarmed pool restore. Identical seeds, so tick-identity is exact.
_WARM_POOL_CHILD = """
import json, os, time
import jax
jax.config.update('jax_platforms', {platform!r})
from koordinator_tpu.utils.compilation_cache import enable_persistent_cache
enable_persistent_cache()
from koordinator_tpu.service.warmpool import WARM_POOL
mode = {mode!r}
n_nodes, n_pods, n_quotas = {n_nodes}, {n_pods}, {n_quotas}
# restart-to-first-bind is the SUM of the timed restart-work segments
# — boot restore, then scheduler build -> informer sync -> first
# solve -> bind — with the interpreter/import segments between them
# left out, exactly the window the committed warm_start probe defined
# ("what a restarted solver actually pays": imports are a fixed
# platform cost identical in both arms and unaddressable by the
# pool). ``import_s`` reports the excluded cost for transparency.
# The restore runs before the heavy stack imports (cmd/scheduler.py
# main's production ordering: deserialization right after interpreter
# start measures ~2x cheaper than after the full stack is imported).
_t_imports = 0.0
ttfb = 0.0
_seg = time.time()
prewarm_report = None
_restore_xla = 0
if mode not in ('flip-cold', 'promotion-cold') \
        and os.environ.get('KTPU_COMPILATION_CACHE_DIR'):
    from koordinator_tpu.obs.device import DEVICE_OBS
    _m0 = DEVICE_OBS.mark()
    WARM_POOL.configure()
    if WARM_POOL.active:
        WARM_POOL.restore(compile_missing=False)
    # the acceptance pin: a warm RESTORE is deserialization only —
    # zero backend compiles (solver_device_xla_compiles_total flat)
    _restore_xla = DEVICE_OBS.mark()['xla_compiles'] - _m0['xla_compiles']
_t_restore = time.time() - _seg
ttfb += _t_restore
_seg = time.time()
import numpy as np
from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import (
    GangMode, GangSpec, NodeMetric, NodeSpec, PodSpec, QuotaSpec,
    ReservationSpec, ReservationState,
)
from koordinator_tpu.client.bus import APIServer, Kind
from koordinator_tpu.client.wiring import wire_scheduler
from koordinator_tpu.cmd.scheduler import SchedulerConfig, build_scheduler

_t_imports += time.time() - _seg
_seg = time.time()
# ALL modes build through build_scheduler with one SchedulerConfig —
# the solver config and feature wiring must be byte-identical across
# arms or the program identities (and the placements) would diverge.
# flip-cold disables the pool outright: the first degraded solve pays
# the full trace + compile, today's no-pool behavior.
sched = build_scheduler(SchedulerConfig(
    host_fallback_cells=0, audit_interval_rounds=0,
    warm_pool=(mode not in ('flip-cold', 'promotion-cold'))))
if mode.startswith('flip'):
    # the degraded-flip shape: a sidecar-backed control plane whose
    # LOCAL twin never ran in this process. The remote dies on the
    # first solve (threshold 1), the machine flips, and the first
    # local solve is the window timed below. flip-warm's twin is
    # warm through the pool the restart arms populated (boot restore
    # + an explicit synchronous prewarm — backgrounded in production);
    # flip-cold pays the compile ON the flip.
    from koordinator_tpu.service.client import SolverUnavailable
    from koordinator_tpu.service.failover import FailoverSolver

    class DeadRemote:
        address = '/nonexistent-solver.sock'
        supports_staging_delta = False

        def solve_result(self, *a, **k):
            raise SolverUnavailable('sidecar gone')

        def close(self):
            pass

    backend = FailoverSolver(
        DeadRemote(), failure_threshold=1,
        probe_fn=lambda: False, prewarm=False,
    )
    t_pre = time.time()
    if mode == 'flip-warm':
        prewarm_report = backend.prewarm(background=False)
        assert prewarm_report and prewarm_report['restored'] \
            + prewarm_report['compiled'] >= 1, (
            'flip-warm prewarm covered nothing', prewarm_report)
    prewarm_s = time.time() - t_pre
    # attach the sidecar-shaped backend to the SAME model the restart
    # arms run (before any solve): every dispatch now routes remote,
    # dies, flips, and lands on the local twin
    sched.model.backend = backend
    backend.on_flip_back = sched.model.reset_staging
bus = APIServer()
wire_scheduler(bus, sched)
rng = np.random.default_rng(5)
for i in range(n_nodes):
    bus.apply(Kind.NODE, f'n{{i}}', NodeSpec(
        name=f'n{{i}}',
        allocatable={{R.CPU: 64000, R.MEMORY: 131072}}))
    bus.apply(Kind.NODE_METRIC, f'n{{i}}', NodeMetric(
        node_name=f'n{{i}}',
        node_usage={{R.CPU: int(rng.integers(0, 8000)),
                     R.MEMORY: int(rng.integers(0, 16384))}},
        update_time=90.0))
# n_quotas > 0 switches the cluster to the FULL featured solve
# (quota + gang + reservation state); the default is the PLAIN churn
# program — the flagship 5k-node bench shape whose cold compile is
# the blackout this leg measures. (Feature states inflate the
# SERIALIZED executable ~2-3x, so the featured variant's warm restore
# is slower while its cold compile barely grows — both variants are
# honest, the default matches the acceptance shape; set
# KTPU_BENCH_WARM_QUOTAS>0 for the featured variant.)
if n_quotas:
    for q in range(n_quotas):
        bus.apply(Kind.QUOTA, f'q{{q}}', QuotaSpec(
            name=f'q{{q}}',
            min={{R.CPU: 200000, R.MEMORY: 400000}},
            max={{R.CPU: 4000000, R.MEMORY: 8000000}}))
    for g in range(4):
        bus.apply(Kind.GANG, f'g{{g}}', GangSpec(
            name=f'g{{g}}', min_member=2, mode=GangMode.NON_STRICT))
    for r in range(8):
        bus.apply(Kind.RESERVATION, f'r{{r}}', ReservationSpec(
            name=f'r{{r}}', node_name=f'n{{r}}',
            state=ReservationState.AVAILABLE,
            requests={{R.CPU: 4000, R.MEMORY: 8192}}, ttl=0,
            allocate_once=False))
for j in range(n_pods):
    bus.apply(Kind.POD, f'p{{j}}', PodSpec(
        name=f'p{{j}}',
        quota=f'q{{j % n_quotas}}' if n_quotas else None,
        gang=f'g{{j % 4}}' if n_quotas and j < 8 else None,
        requests={{R.CPU: int(rng.integers(200, 2000)),
                   R.MEMORY: int(rng.integers(128, 2048))}}))
if mode.startswith('promotion'):
    # the SIGKILL-the-leader shape this repo actually ships (leader
    # election + standby, the chaos kill-the-leader property): the
    # standby built, synced, and — warm — boot-restored BEFORE the
    # outage; what the outage costs is promotion-to-first-bind. The
    # window opens when the dead leader's lease is taken: promotion
    # sweep (pool restore — idempotent after a warm boot — plus the
    # eager staged-world prestage) and the first solve to the first
    # bind. Cold pays the full trace + XLA compile inside it.
    from koordinator_tpu.scheduler.auditor import StateAuditor

    auditor = StateAuditor(
        sched, bus, interval_rounds=0,
        warm_pool=WARM_POOL if mode == 'promotion-warm' else None)
    ttfb = 0.0
    _seg = time.time()
    auditor.note_promotion()
    auditor.on_round(now=99.0)
# time-to-FIRST-bind, literally: the publish loop binds pod by pod
# and a bus watcher stamps the first placement landing — the moment
# the cluster is being served again. (The remaining publish fan-out
# is identical in every arm and measured separately below.)
first_bind = [None]
def _stamp_bind(event, name, pod):
    if first_bind[0] is None and getattr(pod, 'node_name', None):
        first_bind[0] = time.time()
bus.watch(Kind.POD, _stamp_bind)
t_solve = time.time()
out = sched.schedule_pending(now=100.0)
end = time.time()
ttfb += (first_bind[0] or end) - _seg
placed = sorted(
    (uid, node) for uid, node in out.items() if node is not None)
assert placed, 'nothing placed'
report = {{
    'ttfb_s': ttfb,
    'import_s': _t_imports,
    'restore_s': _t_restore,
    'first_solve_s': end - t_solve,
    'publish_tail_s': end - (first_bind[0] or end),
    'placed': len(placed),
    'placements_digest': __import__('hashlib').blake2b(
        repr(placed).encode(), digest_size=8).hexdigest(),
    'warm': {{k: WARM_POOL.status()[k] for k in
              ('serving', 'hits', 'misses', 'rejects', 'served',
               'quarantined')}},
}}
if mode == 'restart':
    WARM_POOL.persist()  # the leader's side: seed/refresh the store
    staged = sched.model.staged_cache.state
    report['staged_inputs_alive'] = (
        staged is not None and not staged.alloc.is_deleted())
elif mode.startswith('flip'):
    status = sched.model.backend.status()
    assert status['degraded'], 'the flip never happened'
    report['last_mode'] = status['last_mode']
    report['prewarm_s'] = prewarm_s
    report['prewarm'] = prewarm_report
    # which path answered: a prewarmed twin must have SERVED from the
    # pool (the jit cache cannot fake it), a cold twin compiled
    report['twin_served'] = WARM_POOL.status()['served']
else:
    report['pool_served'] = WARM_POOL.status()['served']
report['restore_xla_compiles'] = _restore_xla
print('LEG ' + json.dumps(report))
"""


def bench_failover_warm_pool():
    """Bench leg 17 (ISSUE 13 / DESIGN §21), two facets at the 5k-node
    bench shape (the flagship's PLAIN churn program by default;
    KTPU_BENCH_WARM_QUOTAS>0 switches to the featured
    quota+gang+reservation variant, whose 2-3x larger serialized
    executable restores proportionally slower), all in FRESH
    single-device interpreters:

    - **Restart**: SIGKILL-the-leader → restart-to-first-bind, cold
      store vs warm pool. The cold arm pays trace + XLA compile, the
      warm arm restores the executables the cold arm persisted.
      Acceptance: warm >= 3x faster, placements tick-identical, and
      the warm path served without donating (the staged inputs
      survive the warm solve).
    - **Degraded flip**: a sidecar-backed control plane whose local
      twin never compiled in-process meets a dead remote on its first
      solve — the first degraded solve pays either the cold local
      compile (today's critical-path cost) or the prewarmed pool
      restore, measured both ways on a separate store pair."""
    import re
    import shutil
    import tempfile

    import jax

    # the 5k-node bench shape: the cold arm re-traces + recompiles
    # the 32-unrolled scan — the multi-second blackout the pool
    # exists to remove. Pods stay moderate: past ~1k pods the shared
    # host epilogue (bus publish per pod) dominates BOTH arms and
    # only dilutes the ratio being measured
    n_nodes = int(os.environ.get("KTPU_BENCH_WARM_NODES", 5000))
    n_pods = int(os.environ.get("KTPU_BENCH_WARM_PODS", 512))
    n_quotas = int(os.environ.get("KTPU_BENCH_WARM_QUOTAS", 16))
    repeats = max(1, int(os.environ.get("KTPU_BENCH_WARM_REPEATS", 2)))
    platform = jax.config.jax_platforms or jax.default_backend()
    # one fresh store PER cold repeat (a second cold run on a used
    # store would be warm through the persisted entries), plus a fresh
    # pair for the flip-cold arm; warm arms share the first cold
    # run's populated store
    stores = [tempfile.mkdtemp(prefix="ktpu-warm-leg-")
              for _ in range(repeats)]
    store = stores[0]
    flip_cold_store = tempfile.mkdtemp(prefix="ktpu-warm-flipcold-")
    promo_cold_store = tempfile.mkdtemp(prefix="ktpu-warm-promocold-")
    env_base = dict(os.environ)
    # the restart shape is ONE device per control plane: strip the
    # suite/bench 8-virtual-device forcing so the pool serves
    env_base["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env_base.get("XLA_FLAGS", ""),
    ).strip()

    def run_arm(arm, mode, store_dir):
        code = _WARM_POOL_CHILD.format(
            platform=platform, mode=mode, n_nodes=n_nodes,
            n_pods=n_pods, n_quotas=n_quotas,
        )
        env = dict(env_base)
        env["KTPU_COMPILATION_CACHE_DIR"] = store_dir
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=900, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            return {"error": f"{arm} arm rc={proc.returncode}: "
                             f"{(proc.stderr or proc.stdout)[-400:]}"}
        for line in proc.stdout.splitlines():
            if line.startswith("LEG "):
                return json.loads(line[4:])
        return {"error": f"{arm} arm printed no LEG line"}

    try:
        # min-vs-min over the repeats (the repo's paired estimator:
        # box load only ever ADDS time) — each cold repeat on its own
        # fresh store so cold stays genuinely cold
        colds, warms = [], []
        for i in range(repeats):
            cold_i = run_arm(f"cold[{i}]", "restart", stores[i])
            if "error" in cold_i:
                return {"error": cold_i["error"]}
            colds.append(cold_i)
        for i in range(repeats):
            warm_i = run_arm(f"warm[{i}]", "restart", store)
            if "error" in warm_i:
                return {"error": warm_i["error"]}
            warms.append(warm_i)
        cold = min(colds, key=lambda r: r["ttfb_s"])
        warm = min(warms, key=lambda r: r["ttfb_s"])
        digests = {r["placements_digest"] for r in colds + warms}
        # the promotion facet — the SIGKILL-the-leader shape this repo
        # ships (leader election + hot standby, the chaos
        # kill-the-leader property): the standby boot-restored BEFORE
        # the outage, so the timed window is promotion-to-first-bind.
        # Cold on its own fresh store pair (true first compile inside
        # the window), warm on the populated store.
        promo_cold = run_arm("promotion-cold", "promotion-cold",
                             promo_cold_store)
        promo_warm = run_arm("promotion-warm", "promotion-warm", store)
        # the flip facet: cold on a FRESH store pair (true first-ever
        # local compile), warm on the store the restart arms populated
        # (program identity shares solve_batch across bindings)
        flip_cold = run_arm("flip-cold", "flip-cold", flip_cold_store)
        flip_warm = run_arm("flip-warm", "flip-warm", store)
        for arm, r in (("promotion-cold", promo_cold),
                       ("promotion-warm", promo_warm)):
            if "error" in r:
                return {"error": f"{arm}: {r['error']}"}
        speedup = (promo_cold["ttfb_s"]
                   / max(promo_warm["ttfb_s"], 1e-9))
        restart_speedup = cold["ttfb_s"] / max(warm["ttfb_s"], 1e-9)
        out = {
            "n_nodes": n_nodes,
            "n_pods": n_pods,
            # HEADLINE: SIGKILL-the-leader -> time-to-first-bind, the
            # promoted standby's window (cold pays trace + compile
            # inside it; warm prestages + serves from the pool)
            "cold_ttfb_s": promo_cold["ttfb_s"],
            "warm_ttfb_s": promo_warm["ttfb_s"],
            "warm_speedup": speedup,
            "warm_speedup_ge_3": speedup >= 3.0,
            "warm_promotion_served": promo_warm["pool_served"],
            # a warm RESTORE is deserialization only: zero backend
            # compiles (the acceptance's counter-flat pin)
            "warm_restore_xla_compiles":
                warm.get("restore_xla_compiles", 0)
                + promo_warm.get("restore_xla_compiles", 0),
            "tick_identical_promotion": (
                promo_cold["placements_digest"]
                == promo_warm["placements_digest"]
            ),
            # the fresh-process restart facet (same window the
            # committed warm_start probe uses: everything after
            # imports — boot restore, build, informer sync, first
            # solve to first bind), best-of-N min-vs-min
            "restart_cold_ttfb_s": cold["ttfb_s"],
            "restart_warm_ttfb_s": warm["ttfb_s"],
            "restart_warm_speedup": restart_speedup,
            "warm_restore_s": warm.get("restore_s"),
            "repeats": repeats,
            "tick_identical_cold_warm": (
                len(digests) == 1
                and cold["placed"] == warm["placed"]
                and promo_cold["placements_digest"] in digests
            ),
            "placed": cold["placed"],
            # the §19.2 acceptance: the warm arm SERVED from restored
            # executables (not a jit-cache accident) and never donated
            "warm_pool_served": warm["warm"]["served"],
            "warm_pool_hits": warm["warm"]["hits"],
            "warm_served_without_donation": (
                warm["warm"]["served"] >= 1
                and warm["staged_inputs_alive"]
            ),
            "cold_store_misses": cold["warm"]["misses"],
            "rejects": warm["warm"]["rejects"],
            "quarantined": warm["warm"]["quarantined"],
        }
        if "error" in flip_cold or "error" in flip_warm:
            # the flip facet degrades to a typed error entry; the
            # restart acceptance numbers above stand on their own
            out["flip_error"] = flip_cold.get("error") \
                or flip_warm.get("error")
            return out
        out.update({
            "degraded_flip_first_solve_cold_s":
                flip_cold["first_solve_s"],
            "degraded_flip_first_solve_warm_s":
                flip_warm["first_solve_s"],
            "flip_warm_speedup": (
                flip_cold["first_solve_s"]
                / max(flip_warm["first_solve_s"], 1e-9)
            ),
            # prewarm cost rides the STARTUP path (backgrounded in
            # production), not the flip's critical path — recorded so
            # the tradeoff is visible
            "flip_prewarm_s": flip_warm["prewarm_s"],
            "tick_identical_flip_cold_warm": (
                flip_cold["placements_digest"]
                == flip_warm["placements_digest"]
            ),
            "flip_twin_served": flip_warm["twin_served"],
        })
        return out
    except subprocess.TimeoutExpired:
        return {"error": "warm-pool child timeout"}
    finally:
        for s in stores:
            shutil.rmtree(s, ignore_errors=True)
        shutil.rmtree(flip_cold_store, ignore_errors=True)
        shutil.rmtree(promo_cold_store, ignore_errors=True)


def graftcheck_report():
    """Repo-wide graftcheck results (docs/DESIGN.md §11/§18): the total
    violation count (0 on a healthy tree, -1 if the checker itself
    fails) plus per-rule counts — all 0 on a healthy tree. Recorded in
    every bench record so the trajectory files double as lint history,
    and gated by tools/bench_diff.py: any nonzero per-rule count is an
    identity-flag regression."""
    try:
        from pathlib import Path

        from koordinator_tpu.analysis.graftcheck import (
            default_rules,
            load_allowlist,
        )
        from koordinator_tpu.analysis.graftcheck.engine import (
            iter_repo_modules,
            run_checks_timed,
        )

        root = Path(__file__).resolve().parent
        violations, _, stats = run_checks_timed(
            iter_repo_modules(root), default_rules(),
            load_allowlist(root / "graftcheck.toml"),
        )
        for v in violations:
            print(f"graftcheck: {v.format()}", file=sys.stderr)
        per_rule = {
            name: s["violations"] for name, s in sorted(stats.items())
        }
        # engine-level findings (stale allowlist entries, missing
        # justifications) count under their own pseudo-rule keys —
        # accumulated, so two stale entries record as 2, not 1
        for v in violations:
            if v.rule not in stats:
                per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
        return len(violations), per_rule
    except Exception as e:
        print(f"graftcheck failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return -1, {}


def bench_tenant_density(repeats):
    """Config #21 (ISSUE 19): pods/s vs resident-tenant fraction — the
    HBM working-set manager's degradation curve (docs/DESIGN.md §26).

    ONE fleet of 16 tenants on the wire-delta protocol (1024-node
    worlds, 64-pod bursts — leg 16's serving shape), served in-process
    at three budget lines: every world resident (f100), half resident
    (f50), a quarter resident (f25). Every arm replays byte-identical
    round streams, so what the curve measures is purely the ladder tax:
    demoted tenants restage host-pinned bases through the existing
    delta/scatter path before each solve. Facets the record gates:

    - **no_cliff**: each halving of the resident fraction costs < 4x
      throughput (graceful degradation, not a swap storm);
    - **identical_to_unbudgeted**: every (tenant, round) placement and
      used_req carry under every budget line is bit-identical to the
      unbudgeted reference arm — residency is invisible to answers;
    - **curve**: per-fraction pods/s plus the demotion/restage counts
      that priced it.
    """
    from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
    from koordinator_tpu.ops.binpack import STAGED_NODE_FIELDS
    from koordinator_tpu.service.codec import SolveRequest
    from koordinator_tpu.service.server import (
        NodeStateCache,
        solve_from_request,
    )
    from koordinator_tpu.state.workingset import WORKING_SET

    n_tenants = int(os.environ.get("KTPU_BENCH_DENSITY_TENANTS", 16))
    n_nodes = int(os.environ.get("KTPU_BENCH_DENSITY_NODES", 1024))
    n_pods = int(os.environ.get("KTPU_BENCH_DENSITY_PODS", 64))
    warmup = 2
    timed = max(4, int(os.environ.get("KTPU_BENCH_DENSITY_ROUNDS",
                                      repeats * 2)))
    rounds = warmup + timed

    def world(tenant_i):
        rng = np.random.default_rng(1000 + tenant_i)
        alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
        alloc[:, ResourceName.CPU] = 64000
        alloc[:, ResourceName.MEMORY] = 131072
        used = np.zeros_like(alloc)
        used[:, ResourceName.CPU] = rng.integers(0, 30000, n_nodes)
        used[:, ResourceName.MEMORY] = rng.integers(0, 65536, n_nodes)
        node = {
            "alloc": alloc, "used_req": used,
            "usage": np.zeros_like(alloc),
            "prod_usage": np.zeros_like(alloc),
            "est_extra": np.zeros_like(alloc),
            "prod_base": np.zeros_like(alloc),
            "metric_fresh": np.ones(n_nodes, bool),
            "schedulable": np.ones(n_nodes, bool),
        }
        weights = np.zeros(NUM_RESOURCES, np.int32)
        weights[ResourceName.CPU] = 1
        weights[ResourceName.MEMORY] = 1
        thresholds = np.zeros(NUM_RESOURCES, np.int32)
        thresholds[ResourceName.CPU] = 65
        thresholds[ResourceName.MEMORY] = 95
        params = {
            "weights": weights, "thresholds": thresholds,
            "prod_thresholds": np.zeros(NUM_RESOURCES, np.int32),
        }
        return node, params

    def tick_pods(tenant_i, r):
        rng = np.random.default_rng(8_000_000 + tenant_i * 10_000 + r)
        req_cols = np.zeros((n_pods, NUM_RESOURCES), np.int32)
        req_cols[:, ResourceName.CPU] = rng.integers(200, 2000, n_pods)
        req_cols[:, ResourceName.MEMORY] = rng.integers(128, 2048, n_pods)
        return {
            "req": req_cols, "est": (req_cols * 85) // 100,
            "is_prod": np.zeros(n_pods, bool),
            "is_daemonset": np.zeros(n_pods, bool),
        }

    def tenant_stream(tenant_i):
        """(establish, [(delta_request, round)]) — worlds evolve
        deterministically per (tenant, round) so every arm replays the
        identical stream."""
        node, params = world(tenant_i)
        establish = SolveRequest(
            node={k: v.copy() for k, v in node.items()}, params=params,
            pods=tick_pods(tenant_i, 0),
            node_delta={"epoch": np.asarray(0, np.int64)},
        )
        deltas = []
        for r in range(1, rounds):
            rng = np.random.default_rng(
                8_000_000 + tenant_i * 10_000 + r
            )
            idx = np.sort(rng.choice(n_nodes, 16, replace=False))
            node["used_req"][idx, ResourceName.CPU] = rng.integers(
                0, 40000, idx.size
            )
            delta = {
                "idx": idx.astype(np.int32),
                "base_epoch": np.asarray(r - 1, np.int64),
                "epoch": np.asarray(r, np.int64),
            }
            delta.update({f: node[f][idx].copy()
                          for f in STAGED_NODE_FIELDS})
            deltas.append(SolveRequest(
                node={}, params=params, pods=tick_pods(tenant_i, r),
                node_delta=delta,
            ))
        return establish, deltas

    streams = [tenant_stream(i) for i in range(n_tenants)]

    def run_arm(budget_worlds, world_bytes):
        """One serve of every stream under ``budget_worlds`` resident
        worlds (None = unbudgeted). Returns (pods/s over the timed
        rounds, per-(tenant, round) answer digests, ladder counts)."""
        WORKING_SET.reset()
        if budget_worlds is not None:
            # half-a-world of slack keeps the line strictly between
            # K and K+1 resident worlds — no boundary flapping
            WORKING_SET.set_budget(
                budget_worlds * world_bytes + world_bytes // 2)
        caches = [NodeStateCache(tenant=f"d{i}", lane="be")
                  for i in range(n_tenants)]
        digests = []
        try:
            for i, (establish, _) in enumerate(streams):
                resp = solve_from_request(establish, node_cache=caches[i])
                if resp.error:
                    raise RuntimeError(
                        f"tenant {i} establish: {resp.error}")
            demo0 = WORKING_SET.status()
            t0 = None
            placed = 0
            for r in range(rounds - 1):
                if r == warmup:
                    demo0 = WORKING_SET.status()
                    t0 = time.perf_counter()
                for i, (_, deltas) in enumerate(streams):
                    resp = solve_from_request(deltas[r],
                                              node_cache=caches[i])
                    if resp.error:
                        raise RuntimeError(
                            f"tenant {i} round {r + 1}: {resp.error}")
                    if t0 is not None:
                        placed += int(
                            np.sum(np.asarray(resp.assignments) >= 0))
                        digests.append((
                            i, r,
                            int(np.asarray(resp.assignments)
                                .astype(np.int64).sum()),
                            hash(np.asarray(resp.assignments)
                                 .tobytes()),
                            hash(np.asarray(resp.node_used_req)
                                 .tobytes()),
                        ))
            wall = time.perf_counter() - t0
            st = WORKING_SET.status()
            ladder = {
                "restages": sum(st["restages"].values())
                - sum(demo0["restages"].values()),
                "demotions": sum(st["demotions"].values())
                - sum(demo0["demotions"].values()),
                "resident_device": st["residents"]["device"],
            }
            return placed / wall if wall > 0 else 0.0, digests, ladder
        finally:
            for cache in caches:
                cache.close()
            WORKING_SET.reset()

    # price one staged world off a probe establish (budgets are set in
    # world units so the leg survives shape-env reconfiguration)
    probe = NodeStateCache(tenant="density-probe")
    resp = solve_from_request(streams[0][0], node_cache=probe)
    if resp.error:
        raise RuntimeError(f"density probe: {resp.error}")
    world_bytes = probe.device_bytes()
    probe.close()

    reference, ref_digests, _ = run_arm(None, world_bytes)
    fractions = {
        "f100": n_tenants,
        "f50": max(1, n_tenants // 2),
        "f25": max(1, n_tenants // 4),
    }
    curve = {}
    identical = True
    for name, budget_worlds in fractions.items():
        pods_per_sec, digests, ladder = run_arm(budget_worlds, world_bytes)
        identical = identical and digests == ref_digests
        curve[name] = {
            "pods_per_sec": pods_per_sec,
            "resident_worlds": budget_worlds,
            **ladder,
        }
    # the no-cliff flag: each halving of the resident fraction costs
    # < 4x throughput (restage is a transfer, not a recompile)
    halving_costs = [
        curve["f100"]["pods_per_sec"] / max(curve["f50"]["pods_per_sec"],
                                            1e-9),
        curve["f50"]["pods_per_sec"] / max(curve["f25"]["pods_per_sec"],
                                           1e-9),
    ]
    return {
        "n_tenants": n_tenants,
        "n_nodes": n_nodes,
        "n_pods": n_pods,
        "timed_rounds": timed,
        "world_bytes": int(world_bytes),
        "unbudgeted_pods_per_sec": reference,
        "curve": curve,
        "max_halving_cost": max(halving_costs),
        "no_cliff": all(c < 4.0 for c in halving_costs),
        "identical_to_unbudgeted": identical,
    }


def main():
    # persist compiled programs: every solver start after the first
    # warms from disk (measured by the warm_start entry below)
    from koordinator_tpu.utils.compilation_cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache()
    repeats = max(1, int(os.environ.get("KTPU_BENCH_REPEATS", 3)))
    from koordinator_tpu.obs.device import DEVICE_OBS as _DEV

    flagship_mark = _DEV.mark()
    try:
        flagship = bench_flagship(repeats)
        flagship["device"] = _DEV.fingerprint(flagship_mark)
    except Exception as e:
        # even a flagship failure must leave a JSON record (with the
        # matrix legs still measured) for the driver to capture
        print(f"flagship bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        flagship = {
            "pods_per_sec": 0.0, "scan_pods_per_sec": 0.0,
            "solver": "error", "p99_round_s": 0.0, "wall_s": 0.0,
            "scheduled": 0, "n_nodes": 0, "n_pods": 0, "warmup_s": 0.0,
            "devices": "?", "error": f"{type(e).__name__}: {e}",
        }

    DEVICE_OBS = _DEV
    from koordinator_tpu.obs.trace import TRACER

    def measured_span_cost():
        """Per-span emit cost (lock + ring append), micro-measured once
        on this box — the basis for every leg's trace_overhead_ratio."""
        from koordinator_tpu.obs.trace import SpanTracer

        probe = SpanTracer(capacity=1024)
        n = 20000
        t0 = time.perf_counter()
        for _ in range(n):
            probe.emit("probe", t0=0.0, t1=1.0)
        return (time.perf_counter() - t0) / n

    span_cost_s = measured_span_cost()

    def leg(fn, *args, **kw):
        # a single failing matrix leg must cost that ENTRY, never the
        # whole JSON record the driver captures
        spans_before = TRACER.span_count
        device_mark = DEVICE_OBS.mark()
        t0 = time.perf_counter()
        try:
            out = fn(*args, **kw)
        except Exception as e:
            print(f"bench leg {fn.__name__} failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return {"error": f"{type(e).__name__}: {e}"}
        wall = time.perf_counter() - t0
        if isinstance(out, dict) and "device" not in out:
            # the device fingerprint (ISSUE 8): compiles, flops/bytes,
            # peak memory, padding waste, live buffers over THIS leg —
            # what tools/bench_diff.py gates record-to-record. Compile
            # deltas are snapshotted before the fingerprint's own
            # analysis pass, so analysis compiles never pollute them.
            try:
                out["device"] = DEVICE_OBS.fingerprint(device_mark)
            except Exception as e:
                out["device"] = {"error": f"{type(e).__name__}: {e}"}
        if isinstance(out, dict) and "trace_overhead_ratio" not in out:
            # spans this leg emitted x measured per-span cost, over the
            # leg's wall — the tracing tax every leg pays (legs that
            # measure it directly, like the pipelined churn's on-vs-off
            # runs, keep their own number)
            spans = TRACER.span_count - spans_before
            out["trace_overhead_ratio"] = (
                spans * span_cost_s / wall if wall > 0 else 0.0
            )
            out["trace_spans_emitted"] = spans
        return out

    matrix = {}
    if os.environ.get("KTPU_BENCH_MATRIX", "1") != "0":
        matrix["1_fit_100x20"] = leg(bench_fit_with_oracle, repeats)
        matrix["1b_fit_500x200"] = leg(
            bench_fit_with_oracle, repeats, n_nodes=200, n_pods=500
        )
        matrix["2_loadaware_2kx500"] = leg(bench_loadaware, repeats)
        matrix["3_quota_5k_50q_1k"] = leg(bench_quota, repeats)
        matrix["4_gang_200x32"] = leg(bench_gang, repeats)
        matrix["5_rebalance_5kx30k"] = leg(bench_rebalance, repeats)
        matrix["6_numa_3kx1500"] = leg(bench_numa, repeats)
        matrix["7_fit_16k_nodes"] = leg(bench_fit_16k, repeats)
        matrix["8_full_features_5kx10k"] = leg(bench_full_features, repeats)
        matrix["9_churn_tick_5k"] = leg(bench_churn_tick, repeats)
        matrix["10_concurrent_solve_8way"] = leg(
            bench_concurrent_solve, repeats
        )
        matrix["11_outage_failover_churn"] = leg(
            bench_outage_failover_churn, repeats
        )
        matrix["12_audit_overhead_churn"] = leg(
            bench_audit_overhead_churn, repeats
        )
        matrix["13_pipelined_churn_5k"] = leg(
            bench_pipelined_churn, repeats
        )
    if os.environ.get("KTPU_BENCH_SHARDED", "1") != "0":
        matrix["sharded"] = leg(bench_sharded, repeats)
        # the measured sharded legs (ISSUE 10): real throughput on the
        # forced 8-device virtual-CPU mesh, in a fresh child process so
        # the parent's backend (possibly a single device or a TPU
        # tunnel) is untouched
        matrix["14_sharded_churn_50k"] = leg(
            _leg_subprocess, "14_sharded_churn_50k"
        )
        # the 100k single-domain point (ROADMAP item 3's first
        # unmeasured checkpoint) beside the 50k number; skippable —
        # the 100k world build alone is minutes of host time
        if os.environ.get("KTPU_BENCH_SHARD_100K", "1") != "0":
            matrix["14b_sharded_churn_100k"] = leg(
                _leg_subprocess, "14b_sharded_churn_100k"
            )
        matrix["15_shard_scaling_curve"] = leg(
            _leg_subprocess, "15_shard_scaling_curve"
        )
        matrix["16_multi_tenant_pool"] = leg(
            _leg_subprocess, "16_multi_tenant_pool"
        )
    if os.environ.get("KTPU_BENCH_STREAMING", "1") != "0":
        # the continuous-arrival serving leg (ISSUE 14): adaptive
        # trigger vs fixed cadence at sustained open-loop rates, plus
        # the shed point — its own toggle so the vcpu record rounds
        # (KTPU_BENCH_MATRIX=0) still measure the serving face
        matrix["18_streaming_arrival"] = leg(
            bench_streaming_arrival, repeats
        )
    if os.environ.get("KTPU_BENCH_STORM", "1") != "0":
        # the preemption-storm leg (ISSUE 16): device joint
        # place+evict vs the host oracle sweep, bit-parity and churn
        # minimality included — its own toggle like the streaming leg
        matrix["19_preemption_storm"] = leg(
            bench_preemption_storm, repeats
        )
    if os.environ.get("KTPU_BENCH_SLO", "1") != "0":
        # the closed-loop SLO leg (ISSUE 18): the declared-target
        # controller walking a slack start config into the lane SLO at
        # three regimes, fake-clock deterministic — its own toggle so
        # vcpu record rounds still gate the control plane
        matrix["20_slo_convergence"] = leg(
            bench_slo_convergence, repeats
        )
    if os.environ.get("KTPU_BENCH_DENSITY", "1") != "0":
        # the working-set degradation curve (#21, ISSUE 19): pods/s vs
        # resident-tenant fraction under the HBM budget
        matrix["21_tenant_density"] = leg(
            bench_tenant_density, repeats
        )
    if os.environ.get("KTPU_BENCH_REBALANCE", "1") != "0":
        # the rebalance-storm leg (ISSUE 20): the device Balance sweep
        # vs the host walk over a large imbalanced cluster (bit-parity
        # + churn), plus the budget-bounded arm through the migration
        # arbiter
        matrix["22_rebalance_storm"] = leg(
            bench_rebalance_storm, repeats
        )
    if os.environ.get("KTPU_BENCH_WARMPROBE", "1") != "0":
        matrix["warm_start"] = leg(bench_warm_start)
        # the warm-pool leg (ISSUE 13): SIGKILL-the-leader →
        # time-to-first-bind cold store vs warm pool, PLUS the
        # degraded-flip first-solve latency both ways, in fresh
        # single-device children (the respawned-leader shape)
        matrix["17_failover_warm_pool"] = leg(
            bench_failover_warm_pool
        )

    def _round(obj):
        if isinstance(obj, dict):
            return {k: _round(v) for k, v in obj.items()}
        if isinstance(obj, float):
            return round(obj, 4)
        return obj

    pods_per_sec = flagship["pods_per_sec"]
    result = {
        "metric": (
            f"batched placement churn ({flagship['n_pods']} pods / "
            f"{flagship['n_nodes']} nodes, {flagship['scheduled']} placed, "
            f"{flagship['devices']}, {flagship['solver']} solver, "
            f"warmup {flagship['warmup_s']:.1f}s)"
            + (" + BASELINE matrix configs 1-5" if matrix else "")
        ),
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / 10000.0, 3),
        "solver": flagship["solver"],
        "scan_pods_per_sec": round(flagship["scan_pods_per_sec"], 1),
        "p99_round_s": round(flagship["p99_round_s"], 4),
        "matrix": _round(matrix),
    }
    gc_total, gc_rules = graftcheck_report()
    result["graftcheck_violations"] = gc_total
    result["graftcheck_rules"] = gc_rules
    if "identical_to_oracle" in flagship:
        result["identical_to_oracle"] = flagship["identical_to_oracle"]
        result["oracle_wall_s"] = round(flagship["oracle_wall_s"], 2)
    if "error" in flagship:
        result["error"] = flagship["error"]
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--leg":
        sys.exit(_leg_child(sys.argv[2]))
    sys.exit(main())
