"""North-star benchmark: 10k-pending-pod / 5k-node churn burst.

Measures the batched placement solver (the TPU-native rebuild of the
scheduler's Filter→Score→Reserve inner loop) on the BASELINE.json target:
schedule a 10k-pod churn against 5k nodes; the target is < 1 s wall-clock,
i.e. >= 10k pods scheduled/sec. Prints exactly one JSON line:
``{"metric": ..., "value": pods_per_sec, "unit": "pods/s",
"vs_baseline": pods_per_sec / 10000}``.

State is device-resident: node arrays are staged once and stay on device
across churn batches (the steady-state regime of a real cluster); the
timed section is solve + assignments readback, which is what a scheduling
round costs.

Env knobs: KTPU_BENCH_NODES, KTPU_BENCH_PODS, KTPU_BENCH_REPEATS.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    n_nodes = int(os.environ.get("KTPU_BENCH_NODES", 5000))
    n_pods = int(os.environ.get("KTPU_BENCH_PODS", 10000))
    repeats = max(1, int(os.environ.get("KTPU_BENCH_REPEATS", 3)))

    import jax

    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
    from koordinator_tpu.parallel.mesh import (
        make_mesh,
        shard_node_state,
        shard_solver,
    )
    from __graft_entry__ import _example_problem

    state, pods, params = _example_problem(n_nodes, n_pods, seed=1)

    devices = jax.devices()
    if len(devices) > 1:
        mesh = make_mesh(devices)
        state = shard_node_state(state, mesh)
        solve = shard_solver(mesh)
    else:
        solve = jax.jit(
            lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig())
        )

    # warm-up: compile + first run
    t0 = time.time()
    new_state, assignments = solve(state, pods, params)
    jax.block_until_ready((new_state, assignments))
    warmup = time.time() - t0

    times = []
    for _ in range(repeats):
        t0 = time.time()
        new_state, assignments = solve(state, pods, params)
        out = np.asarray(assignments)  # include readback: it's part of a round
        times.append(time.time() - t0)
    elapsed = min(times)

    scheduled = int((out >= 0).sum())
    pods_per_sec = n_pods / elapsed
    result = {
        "metric": (
            f"batched placement churn ({n_pods} pods / {n_nodes} nodes, "
            f"{scheduled} placed, {len(devices)}x{devices[0].platform}, "
            f"warmup {warmup:.1f}s)"
        ),
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / 10000.0, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
