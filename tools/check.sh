#!/usr/bin/env bash
# The one-command gate: graftcheck (hot-path AST invariants) + the
# tier-1 test suite. Exits non-zero if either fails. CI and pre-commit
# both call this; bench.py additionally records the graftcheck
# violation count in every bench record (docs/DESIGN.md §11).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== graftcheck =="
# incremental by default: local rules scan the git-diff-scoped file set
# while the whole-program passes (sync-reach, lock-order,
# donation-safety, and the v3 shape-flow trio + metrics-hygiene —
# census/enumeration passes are only sound over the full graph) always
# load the full call graph; a clean tree falls back to the full scan
# automatically. GRAFTCHECK_FULL=1 forces a full local scan too
# (CI / release gates).
if [ "${GRAFTCHECK_FULL:-0}" = "1" ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m koordinator_tpu.analysis.graftcheck "$@"
else
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m koordinator_tpu.analysis.graftcheck --changed-files=auto "$@"
fi

echo "== chaos smoke =="
# a fast seeded fault-injection pass through the failure-domain layer
# (torn/corrupt/stalled frames + forced base loss): quick signal that
# the wire boundary still survives hostile transport before paying for
# the full suite. Sentinel-armed (ISSUE 15): every chaos test runs in
# a shape-flow sentinel window, so a compile whose signature falls
# outside the static enumeration fails here, not in a production tail.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_chaos.py \
    -q -m chaos -k smoke -p no:cacheprovider

echo "== pipeline smoke =="
# the overlapped tick path: a few-tick pipelined churn must end
# bit-identical to the serial loop (stage/solve/publish overlap is a
# pure latency move, never a semantic one)
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_pipeline.py \
    -q -k smoke -p no:cacheprovider

echo "== trace smoke =="
# the observability fabric: a pipelined run must export a valid
# Chrome-trace with stage(N+1)/solve(N) overlap visible while a serial
# run shows none, and tracing on vs off must stay tick-identical
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_obs.py \
    -q -k "smoke or tick_identical" -p no:cacheprovider

echo "== audit smoke =="
# the anti-entropy slice: seeded cache/staging corruption -> the
# auditor detects and repairs (counted) -> a kill-the-leader churn
# still finishes tick-identical to a crash-free run
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_chaos.py \
    -q -m chaos -k audit -p no:cacheprovider

echo "== sharded smoke =="
# the sharded staging slice (ISSUE 10): a short sharded delta churn on
# the 8-device virtual-CPU mesh must stay bit-identical to the
# single-device full restage, and the lane axis must match per-lane
# solo solves at non-pow2 shapes
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_shard_staging.py \
    -q -k "smoke or non_pow2" -p no:cacheprovider

echo "== multi-tenant smoke =="
# the multi-tenant pool slice (ISSUE 11): cross-tenant lane batches —
# plain and wire-delta — must stay bit-identical to each tenant
# solving solo, one gate dispatch per batch, and fair-share shedding
# must protect a within-share tenant from another tenant's burst
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_tenancy.py \
    -q -k smoke -p no:cacheprovider

echo "== bench diff smoke =="
# the perf regression gate's own health check: a record diffed against
# itself must pass clean (exit 0) — proves the loader handles the
# committed record format (including salvage of truncated tails) and
# that no comparator fires on identical inputs
python tools/bench_diff.py BENCH_r05.json BENCH_r05.json

echo "== streaming smoke =="
# the continuous-arrival serving slice (ISSUE 14): the adaptive
# trigger's fake-clock determinism (deadline-fires-first vs
# watermark-fires-first), and a short REAL pipelined streaming run
# that binds every submitted pod bit-identically to the fixed-round
# replay of its recorded arrival batches. Sentinel-armed (ISSUE 15):
# the drifting batch sizes of the arrival path are exactly the load
# shape recompile storms feed on, so every signature the compile ring
# observes here must sit inside the statically-enumerated bucket
# images (module teardown asserts zero violations; non-vacuity is
# additionally asserted on the unfiltered tier-1 run of these suites).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_streaming.py \
    -q -k "smoke or fires_first" -p no:cacheprovider

echo "== preemption smoke =="
# the joint place+evict slice (ISSUE 16): device victim selection,
# reprieve ORDER, and the quota-over-runtime no-reprieve edge must
# stay bit-identical to the host oracle (scheduler/preemption.py);
# the "verify" backend must agree end-to-end on a scheduling round;
# the seeded preemption storm additionally runs under the chaos
# suite's shape-flow sentinel (see the chaos smoke above)
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_preempt_device.py \
    -q -k "verify_backend or over_runtime or half_boundary or status" \
    -p no:cacheprovider
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_chaos.py \
    -q -m chaos -k preemption_storm -p no:cacheprovider

echo "== slo smoke =="
# the self-tuning serving control plane (ISSUE 18): the pure policy
# units (breach confirmation + cooldown, the burn-the-ceiling
# anti-oscillation bound, the watermark ratchet) and a short
# closed-loop run that must tighten the breaching lane inside its
# declared p99 target and replay its decision log bit-for-bit from
# the recorded observation ring; the leader-kill handoff leg (knob +
# intake adoption, exactly-once binds, bit-identical placements
# against the crash-free run) rides the chaos marker
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_slo_controller.py \
    -q -k "smoke or Policy" -p no:cacheprovider
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_slo_controller.py \
    -q -m chaos -k slo -p no:cacheprovider

echo "== migration smoke =="
# the arbitrated eviction control plane (ISSUE 20): the arbiter's
# budget/refusal-precedence units + replay determinism, the device
# Balance sweep's ordered bit-parity against the host walk (victim
# sets AND order, refusal fixpoint, verify backend), and the seeded
# eviction-storm property — budgets never exceeded in any window, no
# cascade, typed + counted deferrals, final placements bit-identical
# to the fault-free control arm — rides the chaos marker
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_migration.py \
    -q -k "not chaos" -p no:cacheprovider
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_rebalance_device.py \
    -q -k "parity or edges or bucket" -p no:cacheprovider
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_migration.py \
    -q -m chaos -p no:cacheprovider

echo "== sharded + multi-tenant + warm-pool + streaming bench budgets =="
# the measured sharded/multi-tenant/warm-pool/streaming legs are
# budget-gated (ISSUES 10/11/13/14): a scaling, merge-overhead,
# pool-throughput, per-tenant p99, warm-restart, or serving-tail
# regression in the committed record fails loudly — including the
# leg-17 acceptance flags and leg 18's adaptive-vs-fixed p99 (>=2x at
# the mid sustained rate), bit-identity replay, and shed-point
# bounds, pinned with equals/min bounds.
# (BENCH_vcpu_r09.json is the committed virtual-CPU-box record — legs
# 14/14b/15/16 run on the forced 8-device virtual mesh, leg 17 in
# fresh single-device children, and leg 18 in-process on the wall
# clock, so these budgets stay comparable whatever hardware records
# the r-series; r06/r07/r08 remain for history.)
python tools/bench_diff.py --budget tools/bench_budgets.json BENCH_vcpu_r09.json

echo "== warm pool smoke =="
# the AOT warm-pool slice (ISSUE 13): persist -> corrupt one entry ->
# restart must count exactly 1 typed reject (+ quarantine) and restore
# the other N-1 as hits, warm serving must be bit-identical with zero
# XLA recompiles, and every WARM_POOL_FAULT_KINDS corruption must
# degrade to a typed, counted, quarantined cold fallback — never a
# crash, never a stale-executable solve
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_warm_pool.py \
    -q -k "smoke or corrupt_entry_typed" -p no:cacheprovider

echo "== device observatory smoke =="
# the device-cost layer: compile telemetry + padding gauges must be
# exact, and the observatory on vs off must stay tick-identical
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_device_obs.py \
    -q -k "smoke or identical" -p no:cacheprovider

echo "== workingset smoke =="
# the HBM working-set slice (ISSUE 19, docs/DESIGN.md Â§26): the
# residency ladder's policy unit tests (victim order, budget boundary,
# typed alloc-failure retry/escalation) plus the 16-tenant chaos churn
# under every HBM_FAULT_KINDS kind â placements bit-identical to the
# fault-free arm, every degradation typed + counted, zero crashes
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_workingset.py \
    -q -k "unit or chaos" -p no:cacheprovider

echo "== tier-1 tests =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
