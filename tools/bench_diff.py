#!/usr/bin/env python3
"""bench_diff: machine-compare two bench records (or one record against
a committed budget file) and exit nonzero on regressions.

The bench trajectory (``BENCH_r*.json``) has never been
machine-compared — a throughput cliff, a recompile leak, or a padding
blow-up between two records was only visible to a human reading JSON.
This tool closes that gap and gates ``tools/check.sh``:

    python tools/bench_diff.py OLD.json NEW.json
    python tools/bench_diff.py --budget budgets.json NEW.json

Per matrix leg it diffs, with per-class thresholds (all overridable):

- throughput   (``*pods_per_sec``, ``speedup*``, top-level ``value``):
  regression when new < old x (1 - --throughput-drop)
- latency      (``*p99*_s``): regression when new > old x
  (1 + --p99-rise); sub-0.1 ms olds are ignored as noise
- device fingerprint (the ``device`` section every leg records since
  ISSUE 8): ``compiles``/``xla_compiles`` regress past
  max(old + --compiles-rise, old x 1.5); ``flops``/``bytes_accessed``/
  ``peak_bytes``/``live_bytes`` past old x (1 + --device-rise);
  ``padding_waste_ratio`` past old + --waste-rise (absolute)
- booleans: any flag that was true in OLD and is false in NEW
  (``identical_to_oracle``, ``tick_identical_*``, ``sub_10ms_p99``,
  ``ok``, ...) is a regression — identity and acceptance flags never
  silently flip off
- a leg erroring in NEW but not in OLD is a regression

Records load from (a) a bare bench JSON line, (b) a driver wrapper
with ``parsed``, (c) a wrapper whose ``tail`` holds the JSON line, or
(d) — salvage mode — a wrapper whose tail is front-truncated: every
balanced ``"leg": {...}`` object still present is recovered, so old
records remain diffable. Budget files map legs to dotted metric paths
with ``min``/``max`` bounds, or ``equals`` for exact values —
including booleans, so identity/acceptance flags can be pinned by a
budget and not only by record-to-record flip detection::

    {"13_pipelined_churn_5k": {"round_p99_s": {"max": 0.02},
                               "device.padding_waste_ratio": {"max": 0.95}},
     "16_multi_tenant_pool": {"tenants_identical_to_solo": {"equals": true}}}

Exit codes: 0 clean, 1 regressions, 2 usage/load errors.
Stdlib-only by design — the gate must run anywhere, jax or not.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Dict, List, Optional, Tuple

#: metric keys that identify a salvaged object as a bench leg
_LEG_MARKERS = (
    "pods_per_sec", "p99_s", "wall_s", "mode", "warm_warmup_s",
    "round_p99_s", "sweeps_per_sec", "recovery_s",
)


# -- record loading ----------------------------------------------------------

def _salvage_legs(text: str) -> Dict[str, dict]:
    """Recover every balanced ``"name": {...}`` object whose body looks
    like a bench leg from (possibly front-truncated) record text."""
    legs: Dict[str, dict] = {}
    for m in re.finditer(r'"([A-Za-z0-9_]+)":\s*\{', text):
        start = m.end() - 1
        try:
            obj, _ = json.JSONDecoder().raw_decode(text[start:])
        except ValueError:
            continue
        if isinstance(obj, dict) and any(k in obj for k in _LEG_MARKERS):
            legs[m.group(1)] = obj
    return legs


def load_record(path: str) -> dict:
    """A bench record as ``{"matrix": {leg: {...}}, ...top-level}``."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "matrix" in doc:
        return doc
    if isinstance(doc, dict):
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "matrix" in parsed:
            return parsed
        text = doc.get("tail", text) or text
    # the JSON line inside a driver tail
    idx = text.rfind('{"metric"')
    if idx >= 0:
        try:
            rec, _ = json.JSONDecoder().raw_decode(text[idx:])
            if isinstance(rec, dict) and "matrix" in rec:
                return rec
        except ValueError:
            pass
    legs = _salvage_legs(text)
    if not legs:
        raise ValueError(
            f"{path}: no bench record found (not a bench JSON line, "
            f"driver wrapper, or salvageable tail)"
        )
    # top-level scalars that survived truncation ride along when present
    top: dict = {"matrix": legs}
    for key in ("value", "p99_round_s", "graftcheck_violations"):
        m = list(re.finditer(rf'"{key}": ([-0-9.eE]+)', text))
        if m:
            top[key] = json.loads(m[-1].group(1))
    return top


# -- comparison --------------------------------------------------------------

class Thresholds:
    def __init__(self, throughput_drop=0.30, p99_rise=0.75,
                 compiles_rise=4, device_rise=0.50, waste_rise=0.15):
        self.throughput_drop = throughput_drop
        self.p99_rise = p99_rise
        self.compiles_rise = compiles_rise
        self.device_rise = device_rise
        self.waste_rise = waste_rise


def _flatten(d: dict, prefix: str = "") -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


def _classify(key: str) -> Optional[str]:
    """Which comparison class a flattened metric key belongs to."""
    leaf = key.rsplit(".", 1)[-1]
    if leaf == "error":
        return "error"
    if leaf.endswith("pods_per_sec") or leaf.startswith("speedup") \
            or key == "value":
        return "throughput"
    if "p99" in leaf and leaf.endswith("_s"):
        return "p99"
    if key.startswith("device.") or ".device." in key:
        if leaf in ("compiles", "xla_compiles"):
            return "compiles"
        if leaf in ("flops", "bytes_accessed", "peak_bytes",
                    "live_bytes"):
            return "device-cost"
        if leaf == "padding_waste_ratio":
            return "waste"
    if key == "graftcheck_violations":
        return "compiles"  # same shape: small count that must not grow
    return None


def compare_records(old: dict, new: dict, thr: Thresholds
                    ) -> Tuple[List[dict], List[str]]:
    """(rows, notes): every compared metric with its verdict."""
    rows: List[dict] = []
    notes: List[str] = []

    def compare_flat(leg: str, o: Dict[str, object],
                     n: Dict[str, object]) -> None:
        for key in sorted(set(o) & set(n)):
            ov, nv = o[key], n[key]
            verdict = None
            if isinstance(ov, bool) or isinstance(nv, bool):
                if ov is True and nv is False:
                    verdict = "REGRESSION"
                elif ov == nv:
                    verdict = "ok"
                else:
                    verdict = "improved"
                rows.append({"leg": leg, "metric": key, "old": ov,
                             "new": nv, "verdict": verdict})
                continue
            cls = _classify(key)
            if cls is None or not isinstance(ov, (int, float)) \
                    or not isinstance(nv, (int, float)):
                continue
            if cls == "throughput":
                bad = ov > 0 and nv < ov * (1 - thr.throughput_drop)
            elif cls == "p99":
                bad = ov >= 1e-4 and nv > ov * (1 + thr.p99_rise)
            elif cls == "compiles":
                bad = nv > max(ov + thr.compiles_rise, ov * 1.5)
            elif cls == "device-cost":
                bad = ov > 0 and nv > ov * (1 + thr.device_rise)
            else:  # waste
                bad = nv > ov + thr.waste_rise
            rows.append({
                "leg": leg, "metric": key, "old": ov, "new": nv,
                "verdict": "REGRESSION" if bad else "ok",
            })
        for key in sorted(set(n) - set(o)):
            if key.rsplit(".", 1)[-1] == "error":
                rows.append({"leg": leg, "metric": key, "old": None,
                             "new": n[key], "verdict": "REGRESSION"})

    old_m, new_m = old.get("matrix", {}), new.get("matrix", {})
    top_old = {k: v for k, v in old.items() if k != "matrix"
               and not isinstance(v, (dict, str))}
    top_new = {k: v for k, v in new.items() if k != "matrix"
               and not isinstance(v, (dict, str))}
    compare_flat("<top>", top_old, top_new)
    # per-rule graftcheck counts (ISSUE 9): identity-flag semantics —
    # ANY nonzero count in NEW is a regression, whether or not OLD
    # recorded the rule (new rules must arrive clean, and a rule
    # disappearing from NEW while OLD had it is flagged like a leg
    # error). Not thresholded: lint findings never average out.
    rules_old = old.get("graftcheck_rules") or {}
    rules_new = new.get("graftcheck_rules") or {}
    if isinstance(rules_new, dict):
        for rule in sorted(rules_new):
            nv = rules_new[rule]
            bad = isinstance(nv, (int, float)) and nv > 0
            rows.append({
                "leg": "<graftcheck>", "metric": rule,
                "old": rules_old.get(rule), "new": nv,
                "verdict": "REGRESSION" if bad else "ok",
            })
        if isinstance(rules_old, dict):
            for rule in sorted(set(rules_old) - set(rules_new)):
                rows.append({
                    "leg": "<graftcheck>", "metric": rule,
                    "old": rules_old[rule], "new": None,
                    "verdict": "REGRESSION",
                })
    for leg in sorted(set(old_m) & set(new_m)):
        if not isinstance(old_m[leg], dict) or \
                not isinstance(new_m[leg], dict):
            continue
        compare_flat(leg, _flatten(old_m[leg]), _flatten(new_m[leg]))
    for leg in sorted(set(old_m) - set(new_m)):
        notes.append(f"leg {leg} present in OLD only (not compared)")
    for leg in sorted(set(new_m) - set(old_m)):
        notes.append(f"leg {leg} new in NEW (not compared)")
    return rows, notes


def compare_budget(budget: dict, new: dict) -> List[dict]:
    rows: List[dict] = []
    matrix = new.get("matrix", {})
    for leg, metrics in budget.items():
        if leg.startswith("_"):
            continue  # "_comment" and friends: annotations, not legs
        source = new if leg == "<top>" else matrix.get(leg)
        if not isinstance(source, dict):
            rows.append({"leg": leg, "metric": "<leg>", "old": "budget",
                         "new": "missing", "verdict": "REGRESSION"})
            continue
        flat = _flatten(source)
        for key, bound in metrics.items():
            val = flat.get(key)
            if "equals" in bound:
                # exact-value bounds: identity/acceptance FLAGS a budget
                # must hold (e.g. {"equals": true} on a bit-identity
                # flag), beside the numeric min/max family
                rows.append({
                    "leg": leg, "metric": key, "old": bound, "new": val,
                    "verdict": ("ok" if val == bound["equals"]
                                else "REGRESSION"),
                })
                continue
            if not isinstance(val, (int, float)) or isinstance(val, bool):
                rows.append({"leg": leg, "metric": key, "old": bound,
                             "new": val, "verdict": "REGRESSION"})
                continue
            bad = (
                ("max" in bound and val > bound["max"])
                or ("min" in bound and val < bound["min"])
            )
            rows.append({
                "leg": leg, "metric": key, "old": bound, "new": val,
                "verdict": "REGRESSION" if bad else "ok",
            })
    return rows


# -- output ------------------------------------------------------------------

def _fmt(v) -> str:
    if isinstance(v, bool) or v is None:
        return str(v)
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def print_table(rows: List[dict], show_all: bool) -> int:
    regressions = [r for r in rows if r["verdict"] == "REGRESSION"]
    shown = rows if show_all else regressions
    if shown:
        widths = [
            max(len(str(r[c])) if c != "old" and c != "new"
                else len(_fmt(r[c])) for r in shown + [
                    {"leg": "leg", "metric": "metric", "old": "old",
                     "new": "new", "verdict": "verdict"}])
            for c in ("leg", "metric", "old", "new", "verdict")
        ]
        header = ("leg", "metric", "old", "new", "verdict")
        print("  ".join(h.ljust(w) for h, w in zip(header, widths)))
        for r in shown:
            cells = (str(r["leg"]), str(r["metric"]), _fmt(r["old"]),
                     _fmt(r["new"]), str(r["verdict"]))
            print("  ".join(c.ljust(w) for c, w in zip(cells, widths)))
    by_leg: Dict[str, int] = {}
    for r in rows:
        by_leg.setdefault(str(r["leg"]), 0)
        if r["verdict"] == "REGRESSION":
            by_leg[str(r["leg"])] += 1
    clean = [leg for leg, n in sorted(by_leg.items()) if n == 0]
    print(
        f"bench_diff: {len(rows)} metrics compared across "
        f"{len(by_leg)} legs — {len(regressions)} regression(s)"
        + (f"; clean: {', '.join(clean)}" if clean and not show_all
           else "")
    )
    return 1 if regressions else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        "bench_diff", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("records", nargs="+",
                        help="OLD.json NEW.json, or NEW.json with --budget")
    parser.add_argument("--budget", default=None,
                        help="budget JSON: {leg: {dotted.key: {max|min}}}")
    parser.add_argument("--all", action="store_true",
                        help="print every compared metric, not only "
                             "regressions")
    parser.add_argument("--json", action="store_true",
                        help="machine output: the row list as JSON")
    parser.add_argument("--throughput-drop", type=float, default=0.30)
    parser.add_argument("--p99-rise", type=float, default=0.75)
    parser.add_argument("--compiles-rise", type=float, default=4)
    parser.add_argument("--device-rise", type=float, default=0.50)
    parser.add_argument("--waste-rise", type=float, default=0.15)
    args = parser.parse_args(argv)

    try:
        if args.budget is not None:
            if len(args.records) != 1:
                parser.error("--budget takes exactly one record")
            with open(args.budget) as f:
                budget = json.load(f)
            rows = compare_budget(budget, load_record(args.records[0]))
            notes: List[str] = []
        else:
            if len(args.records) != 2:
                parser.error("expected OLD.json NEW.json")
            thr = Thresholds(args.throughput_drop, args.p99_rise,
                             args.compiles_rise, args.device_rise,
                             args.waste_rise)
            rows, notes = compare_records(
                load_record(args.records[0]),
                load_record(args.records[1]), thr,
            )
    except (OSError, ValueError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"rows": rows, "notes": notes}))
        return 1 if any(r["verdict"] == "REGRESSION" for r in rows) else 0
    for note in notes:
        print(f"note: {note}")
    return print_table(rows, args.all)


if __name__ == "__main__":
    raise SystemExit(main())
