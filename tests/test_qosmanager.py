"""qosmanager tests: suppress budget math, cpuset selection, cfs quota
policy, evictors, cpu burst.

Oracles: cpu_suppress.go:137-163 (budget), :653 (cpuset policy), :589
(cfs quota); memory_evict.go:101-160; cpu_evict.go:246-360.
"""

import dataclasses

import pytest

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metriccache import MetricCache, MetricKind
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.qosmanager import (
    CPUBurst,
    CPUEvictor,
    CPUInfo,
    CPUSuppress,
    MemoryEvictor,
    QoSContext,
    QoSManager,
)
from koordinator_tpu.koordlet.qosmanager.cpusuppress import (
    calculate_be_suppress_mcpu,
    select_suppress_cpus,
)
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.resourceexecutor.executor import ensure_cgroup_dir
from koordinator_tpu.koordlet.system.cgroup import (
    CPU_BURST,
    CPU_CFS_QUOTA,
    CPU_SET,
    SystemConfig,
)
from koordinator_tpu.manager.sloconfig import (
    NodeSLOSpec,
    ResourceThresholdStrategy,
)


def topo_2numa_8cpu():
    """2 NUMA nodes x 2 cores x 2 HT = 8 cpus; siblings adjacent ids."""
    infos = []
    for node in range(2):
        for core in range(2):
            for ht in range(2):
                cpu_id = node * 4 + core * 2 + ht
                infos.append(CPUInfo(
                    cpu_id=cpu_id, core_id=node * 2 + core,
                    socket_id=0, node_id=node,
                ))
    return infos


class StaticPods:
    def __init__(self, pods):
        self.pods = pods

    def running_pods(self):
        return self.pods


def make_ctx(tmp_path, pods, slo=None, cap_mcpu=8000, cap_mem=16384,
             evict=None):
    cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"),
                       proc_root=str(tmp_path / "proc"))
    ensure_cgroup_dir("kubepods/besteffort", cfg)
    for p in pods:
        ensure_cgroup_dir(p.cgroup_dir, cfg)
        for c in p.containers.values():
            ensure_cgroup_dir(c, cfg)
    mc = MetricCache()
    return QoSContext(
        metric_cache=mc,
        executor=ResourceUpdateExecutor(cfg, auditor=Auditor()),
        pod_provider=StaticPods(pods),
        system_config=cfg,
        node_slo=slo or NodeSLOSpec(
            resource_used_threshold_with_be=ResourceThresholdStrategy(
                enable=True
            )
        ),
        node_capacity_mcpu=cap_mcpu,
        node_capacity_mem_mib=cap_mem,
        cpu_infos=topo_2numa_8cpu(),
        evict=evict,
        auditor=Auditor(),
    )


class TestSuppressBudget:
    def test_formula(self):
        # cap 8000, threshold 65 -> 5200; LS used 2000; sys = 3000-2500=500
        got = calculate_be_suppress_mcpu(
            capacity_mcpu=8000, threshold_percent=65,
            node_used_mcpu=3000.0,
            pod_used_mcpu={"ls": 2000.0, "be": 500.0},
            non_be_uids={"ls"}, reserved_mcpu=0,
        )
        assert got == 5200 - 2000 - 500

    def test_reserved_wins_over_system(self):
        got = calculate_be_suppress_mcpu(
            8000, 65, 2000.0, {"ls": 2000.0}, {"ls"}, reserved_mcpu=700
        )
        # system = max(2000-2000, 0) = 0; max(0, 700) = 700
        assert got == 5200 - 2000 - 700


class TestSelectCPUs:
    def test_ht_pairs_scattered_across_numa(self):
        cpus = select_suppress_cpus(4, topo_2numa_8cpu(), old_count=0)
        assert len(cpus) == 4
        # scattered: 2 from each NUMA node, HT-paired
        numa0 = [c for c in cpus if c < 4]
        numa1 = [c for c in cpus if c >= 4]
        assert len(numa0) == 2 and len(numa1) == 2
        assert numa0[1] == numa0[0] + 1  # sibling pair

    def test_minimum_two(self):
        assert len(select_suppress_cpus(0, topo_2numa_8cpu(), 0)) == 2

    def test_growth_rate_limited(self):
        # 8 cpus -> max increase ceil(0.8)=1 per round
        cpus = select_suppress_cpus(8, topo_2numa_8cpu(), old_count=2)
        assert len(cpus) == 3

    def test_capped_at_available(self):
        assert len(select_suppress_cpus(64, topo_2numa_8cpu(), 0)) == 8


class TestCPUSuppressStrategy:
    def _prime(self, ctx, node_mcpu, be_mcpu, ls_mcpu):
        mc = ctx.metric_cache
        mc.append(MetricKind.NODE_CPU_USAGE, None, 100.0, node_mcpu)
        mc.append(MetricKind.POD_CPU_USAGE, {"pod": "ls"}, 100.0, ls_mcpu)
        mc.append(MetricKind.POD_CPU_USAGE, {"pod": "be"}, 100.0, be_mcpu)

    def _pods(self):
        return [
            PodMeta("ls", "kubepods/burstable/ls", QoSClass.LS),
            PodMeta("be", "kubepods/besteffort/be", QoSClass.BE,
                    containers={"c": "kubepods/besteffort/be/c"}),
        ]

    def test_cpuset_policy_writes_be_dirs(self, tmp_path):
        ctx = make_ctx(tmp_path, self._pods())
        self._prime(ctx, 3000, 500, 2000)
        CPUSuppress().execute(ctx, now=100.0)
        # budget (8000*65% - 2000 - 500)/1000 = 2.7 -> ceil -> 3 cpus
        # (reference cpu_suppress.go:388 rounds the BE cpuset size up)
        got = CPU_SET.read("kubepods/besteffort", ctx.system_config)
        assert got == "0,1,2"
        assert CPU_SET.read("kubepods/besteffort/be/c",
                            ctx.system_config) == "0,1,2"

    def test_cfs_quota_policy(self, tmp_path):
        slo = NodeSLOSpec(
            resource_used_threshold_with_be=ResourceThresholdStrategy(
                enable=True, cpu_suppress_policy="cfsQuota",
            )
        )
        ctx = make_ctx(tmp_path, self._pods(), slo=slo)
        CPU_CFS_QUOTA.write("kubepods/besteffort", "-1", ctx.system_config)
        self._prime(ctx, 3000, 500, 2000)
        CPUSuppress().execute(ctx, now=100.0)
        got = int(CPU_CFS_QUOTA.read("kubepods/besteffort",
                                     ctx.system_config))
        assert got == 2700 * 100000 // 1000

    def test_disabled_recovers(self, tmp_path):
        ctx = make_ctx(tmp_path, self._pods())
        self._prime(ctx, 3000, 500, 2000)
        s = CPUSuppress()
        s.execute(ctx, now=100.0)
        assert CPU_SET.read("kubepods/besteffort",
                            ctx.system_config) == "0,1,2"
        ctx.node_slo.resource_used_threshold_with_be.enable = False
        s.execute(ctx, now=101.0)
        got = CPU_SET.read("kubepods/besteffort", ctx.system_config)
        assert got == "0,1,2,3,4,5,6,7"

    def test_kernel_range_cpuset_counted_correctly(self, tmp_path):
        # kernel normalizes cpuset to "0-7": growth limit must see 8 old
        # cpus, not 2, and not clamp the new set below the budget
        ctx = make_ctx(tmp_path, self._pods())
        CPU_SET.write("kubepods/besteffort", "0-7", ctx.system_config)
        self._prime(ctx, 3000, 500, 2000)  # budget 2.7 -> ceil -> 3 cpus
        CPUSuppress().execute(ctx, now=100.0)
        assert CPU_SET.read("kubepods/besteffort",
                            ctx.system_config) == "0,1,2"

    def test_quota_small_delta_bypassed(self, tmp_path):
        slo = NodeSLOSpec(
            resource_used_threshold_with_be=ResourceThresholdStrategy(
                enable=True, cpu_suppress_policy="cfsQuota",
            )
        )
        ctx = make_ctx(tmp_path, self._pods(), slo=slo)
        self._prime(ctx, 3000, 500, 2000)
        s = CPUSuppress()
        s.execute(ctx, now=100.0)
        first = CPU_CFS_QUOTA.read("kubepods/besteffort", ctx.system_config)
        # tiny usage change: delta below 1% of capacity*period -> bypass
        ctx.metric_cache.append(
            MetricKind.POD_CPU_USAGE, {"pod": "ls"}, 101.0, 2010.0)
        s.execute(ctx, now=101.0)
        assert CPU_CFS_QUOTA.read(
            "kubepods/besteffort", ctx.system_config) == first


class TestMemoryEvictor:
    def test_evicts_largest_lowest_priority_until_released(self, tmp_path):
        evicted = []
        pods = [
            PodMeta("be1", "kubepods/besteffort/be1", QoSClass.BE,
                    name="be1", priority=5500),
            PodMeta("be2", "kubepods/besteffort/be2", QoSClass.BE,
                    name="be2", priority=5000),
            PodMeta("be3", "kubepods/besteffort/be3", QoSClass.BE,
                    name="be3", priority=5000),
        ]
        ctx = make_ctx(
            tmp_path, pods,
            evict=lambda ps, r: evicted.extend(p.uid for p in ps) or [],
        )
        mc = ctx.metric_cache
        # node at 80% of 16384 MiB (threshold 70) -> release to 68%
        mc.append(MetricKind.NODE_MEMORY_USAGE, None, 100.0, 0.80 * 16384)
        mc.append(MetricKind.POD_MEMORY_USAGE, {"pod": "be1"}, 100.0, 512.0)
        mc.append(MetricKind.POD_MEMORY_USAGE, {"pod": "be2"}, 100.0, 1024.0)
        mc.append(MetricKind.POD_MEMORY_USAGE, {"pod": "be3"}, 100.0, 2048.0)
        MemoryEvictor().execute(ctx, now=100.0)
        # need (80-68)% * 16384 = 1966 MiB: be3 (prio 5000, 2048) suffices
        assert evicted == ["be3"]

    def test_below_threshold_no_evict(self, tmp_path):
        evicted = []
        pods = [PodMeta("be1", "kubepods/besteffort/be1", QoSClass.BE)]
        ctx = make_ctx(tmp_path, pods,
                       evict=lambda ps, r: evicted.extend(ps) or [])
        ctx.metric_cache.append(
            MetricKind.NODE_MEMORY_USAGE, None, 100.0, 0.5 * 16384)
        MemoryEvictor().execute(ctx, now=100.0)
        assert evicted == []

    def test_cooldown(self, tmp_path):
        evicted = []
        pods = [PodMeta("be1", "kubepods/besteffort/be1", QoSClass.BE)]
        ctx = make_ctx(tmp_path, pods,
                       evict=lambda ps, r: evicted.extend(ps) or [])
        ctx.metric_cache.append(
            MetricKind.NODE_MEMORY_USAGE, None, 100.0, 0.9 * 16384)
        m = MemoryEvictor()
        m.execute(ctx, now=100.0)
        ctx.metric_cache.append(
            MetricKind.NODE_MEMORY_USAGE, None, 130.0, 0.9 * 16384)
        m.execute(ctx, now=130.0)  # within 60s cooldown
        assert len(evicted) == 1


class TestCPUEvictor:
    def _slo(self):
        return NodeSLOSpec(
            resource_used_threshold_with_be=ResourceThresholdStrategy(
                enable=True,
                cpu_evict_be_satisfaction_lower_percent=60,
                cpu_evict_be_satisfaction_upper_percent=80,
            )
        )

    def test_evicts_when_starved(self, tmp_path):
        evicted = []
        pods = [
            PodMeta("be1", "kubepods/besteffort/be1", QoSClass.BE,
                    priority=5000, cpu_request_mcpu=2000),
            PodMeta("be2", "kubepods/besteffort/be2", QoSClass.BE,
                    priority=5500, cpu_request_mcpu=2000),
        ]
        ctx = make_ctx(tmp_path, pods, slo=self._slo(),
                       evict=lambda ps, r: evicted.extend(
                           p.uid for p in ps) or [])
        # BE tier quota 2 cores against 4 cores requested -> 50% < 60%
        CPU_CFS_QUOTA.write("kubepods/besteffort", "200000",
                            ctx.system_config)
        mc = ctx.metric_cache
        mc.append(MetricKind.BE_CPU_USAGE, None, 100.0, 1900.0)  # 95% of limit
        mc.append(MetricKind.POD_CPU_USAGE, {"pod": "be1"}, 100.0, 900.0)
        mc.append(MetricKind.POD_CPU_USAGE, {"pod": "be2"}, 100.0, 1000.0)
        CPUEvictor().execute(ctx, now=100.0)
        # release (0.8-0.5)*4000 = 1200 mCPU: be1 (lowest priority) first
        assert evicted == ["be1"]

    def test_not_starved_no_evict(self, tmp_path):
        evicted = []
        pods = [PodMeta("be1", "kubepods/besteffort/be1", QoSClass.BE,
                        cpu_request_mcpu=2000)]
        ctx = make_ctx(tmp_path, pods, slo=self._slo(),
                       evict=lambda ps, r: evicted.extend(ps) or [])
        CPU_CFS_QUOTA.write("kubepods/besteffort", "200000",
                            ctx.system_config)
        # usage far below limit: not starved
        ctx.metric_cache.append(MetricKind.BE_CPU_USAGE, None, 100.0, 500.0)
        CPUEvictor().execute(ctx, now=100.0)
        assert evicted == []

    def test_evict_by_allocatable_policy(self, tmp_path):
        """CPUEvictPolicy=evictByAllocatable (cpu_evict.go:148-151):
        satisfaction uses the BE tier's batch allocatable, not the cfs
        real limit — the same cluster that is healthy by real-limit is
        starved by allocatable."""
        evicted = []
        pods = [
            PodMeta("be1", "kubepods/besteffort/be1", QoSClass.BE,
                    priority=5000, cpu_request_mcpu=2000),
            PodMeta("be2", "kubepods/besteffort/be2", QoSClass.BE,
                    priority=5500, cpu_request_mcpu=2000),
        ]
        slo = self._slo()
        slo.resource_used_threshold_with_be.cpu_evict_policy = (
            "evictByAllocatable"
        )
        ctx = make_ctx(tmp_path, pods, slo=slo,
                       evict=lambda ps, r: evicted.extend(
                           p.uid for p in ps) or [])
        # real limit healthy (4 cores for 4000m requested = 100%)...
        CPU_CFS_QUOTA.write("kubepods/besteffort", "400000",
                            ctx.system_config)
        # ...but batch allocatable reclaimed down to 2 cores: 50% < 60%
        ctx = dataclasses.replace(ctx, be_allocatable_fn=lambda: 2000)
        ctx.metric_cache.append(
            MetricKind.BE_CPU_USAGE, None, 100.0, 1900.0)
        CPUEvictor().execute(ctx, now=100.0)
        assert evicted == ["be1"]
        # the default (real-limit) policy does NOT evict here
        evicted2 = []
        slo2 = self._slo()
        ctx2 = make_ctx(tmp_path, pods, slo=slo2,
                        evict=lambda ps, r: evicted2.extend(ps) or [])
        CPU_CFS_QUOTA.write("kubepods/besteffort", "400000",
                            ctx2.system_config)
        ctx2.metric_cache.append(
            MetricKind.BE_CPU_USAGE, None, 100.0, 1900.0)
        CPUEvictor().execute(ctx2, now=100.0)
        assert evicted2 == []

    def test_evict_window_averages_out_spike(self, tmp_path):
        """cpu_evict_time_window_seconds widens the usage average: a
        single stale spike inside a long window no longer clears the
        usage-high-enough gate."""
        evicted = []
        pods = [PodMeta("be1", "kubepods/besteffort/be1", QoSClass.BE,
                        priority=5000, cpu_request_mcpu=4000)]
        slo = self._slo()
        slo.resource_used_threshold_with_be.cpu_evict_time_window_seconds = (
            300
        )
        ctx = make_ctx(tmp_path, pods, slo=slo,
                       evict=lambda ps, r: evicted.extend(ps) or [])
        CPU_CFS_QUOTA.write("kubepods/besteffort", "200000",
                            ctx.system_config)
        # one old spike + mostly idle samples across the 300s window:
        # the windowed average stays under the usage threshold
        mc = ctx.metric_cache
        mc.append(MetricKind.BE_CPU_USAGE, None, -150.0, 1900.0)
        for t in range(-140, 101, 20):
            mc.append(MetricKind.BE_CPU_USAGE, None, float(t), 100.0)
        CPUEvictor().execute(ctx, now=100.0)
        assert evicted == []


class TestCPUBurst:
    def test_burst_applied_to_ls_with_limit(self, tmp_path):
        pods = [
            PodMeta("ls", "kubepods/burstable/ls", QoSClass.LS,
                    cpu_limit_mcpu=2000,
                    containers={"c": "kubepods/burstable/ls/c"}),
            PodMeta("be", "kubepods/besteffort/be", QoSClass.BE,
                    cpu_limit_mcpu=2000),
        ]
        slo = NodeSLOSpec()
        slo.cpu_burst_strategy.policy = "auto"
        ctx = make_ctx(tmp_path, pods, slo=slo)
        CPUBurst().execute(ctx, now=100.0)
        # 2000 mCPU * 100000us * 1000% / 100 / 1000 = 2_000_000 us
        assert CPU_BURST.read("kubepods/burstable/ls",
                              ctx.system_config) == "2000000"
        assert CPU_BURST.read("kubepods/burstable/ls/c",
                              ctx.system_config) == "2000000"
        with pytest.raises(OSError):
            CPU_BURST.read("kubepods/besteffort/be", ctx.system_config)

    def test_burst_degrades_when_share_pool_hot(self, tmp_path):
        pods = [PodMeta("ls", "kubepods/burstable/ls", QoSClass.LS,
                        cpu_limit_mcpu=2000)]
        slo = NodeSLOSpec()
        slo.cpu_burst_strategy.policy = "auto"
        ctx = make_ctx(tmp_path, pods, slo=slo)
        # node at 60% > 50% share pool threshold
        ctx.metric_cache.append(
            MetricKind.NODE_CPU_USAGE, None, 100.0, 4800.0)
        CPUBurst().execute(ctx, now=100.0)
        assert CPU_BURST.read("kubepods/burstable/ls",
                              ctx.system_config) == "0"


class TestCFSQuotaBurst:
    """The quota-burst half (cpu_burst.go applyCFSQuotaBurst): throttled
    pods scale up 1.2x toward base*CFSQuotaBurstPercent; exhausted
    limiter / overloaded node scale down 0.8x toward base."""

    def _pod(self):
        return PodMeta("ls", "kubepods/burstable/ls", QoSClass.LS,
                       cpu_limit_mcpu=2000,
                       containers={"c": "kubepods/burstable/ls/c"},
                       container_limits_mcpu={"c": 2000})

    def _ctx(self, tmp_path, quota_us=200000):
        slo = NodeSLOSpec()
        slo.cpu_burst_strategy.policy = "auto"
        ctx = make_ctx(tmp_path, [self._pod()], slo=slo)
        CPU_CFS_QUOTA.write("kubepods/burstable/ls", str(quota_us),
                            ctx.system_config)
        CPU_CFS_QUOTA.write("kubepods/burstable/ls/c", str(quota_us),
                            ctx.system_config)
        # idle share pool (an UNKNOWN node state holds scale-ups,
        # matching changeOperationByNode)
        ctx.metric_cache.append(
            MetricKind.NODE_CPU_USAGE, None, 100.0, 1000.0)
        return ctx

    def test_throttled_pod_scales_up(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.metric_cache.append(
            MetricKind.POD_CPU_THROTTLED_RATIO, {"pod": "ls"}, 100.0, 0.4)
        CPUBurst().execute(ctx, now=100.0)
        # 200000 * 1.2 = 240000, under ceil 600000 (300%)
        assert CPU_CFS_QUOTA.read("kubepods/burstable/ls",
                                  ctx.system_config) == "240000"
        assert CPU_CFS_QUOTA.read("kubepods/burstable/ls/c",
                                  ctx.system_config) == "240000"

    def test_scale_up_clamped_at_ceil(self, tmp_path):
        ctx = self._ctx(tmp_path, quota_us=590000)
        ctx.metric_cache.append(
            MetricKind.POD_CPU_THROTTLED_RATIO, {"pod": "ls"}, 100.0, 0.4)
        CPUBurst().execute(ctx, now=100.0)
        assert CPU_CFS_QUOTA.read("kubepods/burstable/ls",
                                  ctx.system_config) == "600000"

    def test_unthrottled_pod_remains(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.metric_cache.append(
            MetricKind.POD_CPU_THROTTLED_RATIO, {"pod": "ls"}, 100.0, 0.0)
        CPUBurst().execute(ctx, now=100.0)
        assert CPU_CFS_QUOTA.read("kubepods/burstable/ls",
                                  ctx.system_config) == "200000"

    def test_overloaded_node_scales_down(self, tmp_path):
        ctx = self._ctx(tmp_path, quota_us=400000)
        ctx.metric_cache.append(
            MetricKind.NODE_CPU_USAGE, None, 100.0, 4800.0)  # 60% > 50%
        ctx.metric_cache.append(
            MetricKind.POD_CPU_THROTTLED_RATIO, {"pod": "ls"}, 100.0, 0.4)
        CPUBurst().execute(ctx, now=100.0)
        # down step 0.8: 400000 -> 320000, floored at base 200000
        assert CPU_CFS_QUOTA.read("kubepods/burstable/ls",
                                  ctx.system_config) == "320000"

    def test_exhausted_limiter_scales_down(self, tmp_path):
        ctx = self._ctx(tmp_path, quota_us=400000)
        ctx.node_slo.cpu_burst_strategy.cfs_quota_burst_period_seconds = 10
        ctx.metric_cache.append(
            MetricKind.POD_CPU_THROTTLED_RATIO, {"pod": "ls"}, 100.0, 0.4)
        burst = CPUBurst()
        # drain the token bucket: sustained usage at 300% of limit
        for t in range(100, 160, 10):
            ctx.metric_cache.append(
                MetricKind.POD_CPU_USAGE, {"pod": "ls"}, float(t), 6000.0)
            burst.execute(ctx, now=float(t))
        assert burst._limiters["ls"].token <= 0
        value = int(CPU_CFS_QUOTA.read("kubepods/burstable/ls",
                                       ctx.system_config))
        assert value < 400000  # scaled down, not up, despite throttling

    def test_limiter_ticks_while_policy_disabled(self, tmp_path):
        """The limiter clock must advance during a disabled stretch:
        otherwise the first allow() after re-enable integrates the whole
        gap as one dt and slams the bucket to -capacity (ADVICE r4)."""
        ctx = self._ctx(tmp_path, quota_us=400000)
        ctx.node_slo.cpu_burst_strategy.cfs_quota_burst_period_seconds = 10
        burst = CPUBurst()
        burst.execute(ctx, now=100.0)  # creates the limiter
        lim = burst._limiters["ls"]
        token_before = lim.token
        ctx.node_slo.cpu_burst_strategy.policy = "cpuBurstOnly"
        burst.execute(ctx, now=500.0)  # long disabled stretch
        assert lim.last == 500.0
        ctx.node_slo.cpu_burst_strategy.policy = "auto"
        ctx.metric_cache.append(
            MetricKind.POD_CPU_USAGE, {"pod": "ls"}, 501.0, 6000.0)
        burst.execute(ctx, now=501.0)
        # dt = 1s at 300% usage drains 200 tokens — NOT 400s worth
        assert lim.token >= token_before - 250

    def test_reset_when_quota_burst_disabled(self, tmp_path):
        ctx = self._ctx(tmp_path, quota_us=400000)
        ctx.node_slo.cpu_burst_strategy.policy = "cpuBurstOnly"
        CPUBurst().execute(ctx, now=100.0)
        assert CPU_CFS_QUOTA.read("kubepods/burstable/ls",
                                  ctx.system_config) == "200000"

    def test_policy_none_runs_one_cleanup_pass(self, tmp_path):
        """Disabling the feature must not leave a 3x quota override:
        the plugin stays enabled for ONE cleanup pass (reset quota,
        zero burst buffer), then goes quiet."""
        ctx = self._ctx(tmp_path)
        ctx.metric_cache.append(
            MetricKind.POD_CPU_THROTTLED_RATIO, {"pod": "ls"}, 100.0, 0.4)
        burst = CPUBurst()
        burst.execute(ctx, now=100.0)
        assert CPU_CFS_QUOTA.read("kubepods/burstable/ls",
                                  ctx.system_config) == "240000"
        ctx.node_slo.cpu_burst_strategy.policy = "none"
        assert burst.enabled(ctx)  # dirty: cleanup still due
        burst.execute(ctx, now=101.0)
        assert CPU_CFS_QUOTA.read("kubepods/burstable/ls",
                                  ctx.system_config) == "200000"
        assert CPU_BURST.read("kubepods/burstable/ls",
                              ctx.system_config) == "0"
        assert not burst.enabled(ctx)  # clean: stays off now

    def test_normalized_node_burst_floors_at_normalized_quota(self, tmp_path):
        """With a cpu-normalization ratio active, burst bases divide by
        the ratio: an overload scale-down shrinks toward the NORMALIZED
        quota instead of inflating back to full spec."""
        ctx = self._ctx(tmp_path, quota_us=125000)  # ceil(200000/1.6)
        ctx.cpu_normalization_ratio = 1.6
        ctx.metric_cache.append(
            MetricKind.NODE_CPU_USAGE, None, 100.0, 4800.0)  # overload
        CPUBurst().execute(ctx, now=100.0)
        # down step 0.8 from 125000 clamps at base 125000 — NOT 200000
        assert CPU_CFS_QUOTA.read("kubepods/burstable/ls",
                                  ctx.system_config) == "125000"
        # and scaling up from the normalized base stays under the
        # normalized ceiling: 125000*1.2 = 150000 <= 375000
        ctx2 = self._ctx(tmp_path, quota_us=125000)
        ctx2.cpu_normalization_ratio = 1.6
        ctx2.metric_cache.append(
            MetricKind.POD_CPU_THROTTLED_RATIO, {"pod": "ls"}, 100.0, 0.4)
        CPUBurst().execute(ctx2, now=100.0)
        assert CPU_CFS_QUOTA.read("kubepods/burstable/ls",
                                  ctx2.system_config) == "150000"


class TestQoSManager:
    def test_tick_intervals(self, tmp_path):
        runs = []

        class Fake:
            name = "fake"
            interval_seconds = 10.0

            def enabled(self, ctx):
                return True

            def execute(self, ctx, now):
                runs.append(now)

        ctx = make_ctx(tmp_path, [])
        mgr = QoSManager(ctx, [Fake()])
        mgr.tick(0.0)
        mgr.tick(5.0)
        mgr.tick(10.0)
        assert runs == [0.0, 10.0]
