"""Leader election + fencing (VERDICT round-2 ask 5).

Reference semantics: every koordinator binary acquires a lease before
its loops start (cmd/koord-scheduler/app/server.go:226-252,
cmd/koord-manager/main.go:123-126). Two instances on one bus must yield
exactly one active; failover hands over without double-placement, and a
deposed leader's in-flight writes are fenced off.
"""

import dataclasses

import pytest

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.client import APIServer, Kind, wire_manager, wire_scheduler
from koordinator_tpu.client.leaderelection import (
    FencingError,
    LeaderElector,
    Lease,
)
from koordinator_tpu.scheduler import Scheduler


def two_electors(bus, **kw):
    a = LeaderElector(bus, "koord-scheduler", "sched-a", **kw)
    b = LeaderElector(bus, "koord-scheduler", "sched-b", **kw)
    return a, b


class TestElection:
    def test_first_ticker_leads_second_stands_by(self):
        bus = APIServer()
        a, b = two_electors(bus)
        assert a.tick(0.0) is True
        assert b.tick(0.1) is False
        assert a.is_leader() and not b.is_leader()
        lease = bus.get(Kind.LEASE, "koord-scheduler")
        assert lease.holder == "sched-a" and lease.token == 1

    def test_renew_keeps_leadership_and_token(self):
        bus = APIServer()
        a, b = two_electors(bus)
        a.tick(0.0)
        for t in (2.0, 4.0, 6.0, 8.0, 14.0):  # gaps within renew_deadline
            assert a.tick(t) is True
            assert b.tick(t + 0.1) is False
        assert bus.get(Kind.LEASE, "koord-scheduler").token == 1

    def test_failover_on_expiry_bumps_token(self):
        bus = APIServer()
        started, stopped = [], []
        a, b = two_electors(bus)
        b.on_started_leading = lambda: started.append("b")
        a.tick(0.0)
        a.tick(2.0)  # last renew at t=2; then sched-a dies
        assert b.tick(10.0) is False          # 2 + 15 not yet reached
        assert b.tick(17.5) is True           # lease expired: take over
        assert started == ["b"]
        lease = bus.get(Kind.LEASE, "koord-scheduler")
        assert lease.holder == "sched-b"
        assert lease.token == 2               # fencing token advanced

    def test_renew_deadline_demotes_paused_leader(self):
        """A leader paused past renew_deadline gives up leadership
        (client-go's renew-deadline semantics) instead of assuming the
        lease is still safely held."""
        bus = APIServer()
        stopped = []
        a = LeaderElector(bus, "koord-scheduler", "sched-a",
                          on_stopped_leading=lambda: stopped.append("a"))
        a.tick(0.0)
        assert a.tick(11.0) is False          # gap > renew_deadline (10)
        assert stopped == ["a"]
        # next tick re-acquires (nobody else took it; token unchanged
        # because holdership never actually moved)
        assert a.tick(11.5) is True
        assert bus.get(Kind.LEASE, "koord-scheduler").token == 1

    def test_release_hands_over_immediately(self):
        bus = APIServer()
        a, b = two_electors(bus)
        a.tick(0.0)
        a.release()
        assert not a.is_leader()
        assert b.tick(0.5) is True            # no expiry wait
        # tokens stay monotone ACROSS a release: the lease object is
        # kept (holder cleared), so b's token bumps past a's instead of
        # restarting at 1 — fencing-token consumers order by it
        assert bus.get(Kind.LEASE, "koord-scheduler").token == 2

    def test_deposed_leader_write_is_fenced(self):
        bus = APIServer()
        a, b = two_electors(bus)
        a.tick(0.0)
        a.tick(2.0)
        b.tick(18.0)                          # takes over after expiry
        writes = []
        with pytest.raises(FencingError):
            a.fenced(lambda: writes.append("boom"))
        assert writes == []                   # nothing applied
        # the new leader's fenced writes go through
        b.fenced(lambda: writes.append("ok"))
        assert writes == ["ok"]

    def test_lease_expiry_helper(self):
        lease = Lease(holder="x", acquire_time=0.0, renew_time=5.0,
                      duration_seconds=15.0)
        assert not lease.expired(19.9)
        assert lease.expired(20.0)


class TestFailoverNoDoublePlacement:
    def test_two_schedulers_one_bus(self):
        """The VERDICT scenario: two wired schedulers; the leader places,
        the standby doesn't; kill the leader and the standby takes over
        and schedules new work exactly once."""
        bus = APIServer()
        sched_a, sched_b = Scheduler(), Scheduler()
        ea = LeaderElector(bus, "koord-scheduler", "a")
        eb = LeaderElector(bus, "koord-scheduler", "b")
        wire_scheduler(bus, sched_a, elector=ea)
        wire_scheduler(bus, sched_b, elector=eb)
        bus.apply(Kind.NODE, "n0", NodeSpec(
            name="n0", allocatable={R.CPU: 16000, R.MEMORY: 32768}))
        bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
            node_name="n0", node_usage={}, update_time=0.0))
        bus.apply(Kind.POD, "default/p1", PodSpec(
            name="p1", requests={R.CPU: 1000}))

        def elected_round(elector, scheduler, now):
            """One run_loop iteration (cmd/scheduler.py run_loop)."""
            if not elector.tick(now):
                return None
            return scheduler.schedule_pending(now=now)

        out_a = elected_round(ea, sched_a, 0.0)
        out_b = elected_round(eb, sched_b, 0.1)
        assert out_a["default/p1"] == "n0"
        assert out_b is None                  # standby never solved

        # leader dies; a new pod arrives; standby takes over and is the
        # ONLY one to place it
        bus.apply(Kind.POD, "default/p2", PodSpec(
            name="p2", requests={R.CPU: 1000}))
        out_b = elected_round(eb, sched_b, 20.0)
        assert out_b["default/p2"] == "n0"
        # the zombie's fenced evictions now raise instead of mutating
        with pytest.raises(FencingError):
            ea.fenced(lambda: None)

    def test_two_managers_one_bus_fenced_patch(self):
        """Two manager loops: only the leader PATCHes nodes; after
        failover the deposed loop's reconcile raises FencingError
        instead of overwriting the new leader's numbers."""
        bus = APIServer()
        ea = LeaderElector(bus, "koord-manager", "a")
        eb = LeaderElector(bus, "koord-manager", "b")
        loop_a = wire_manager(bus, elector=ea)
        loop_b = wire_manager(bus, elector=eb)
        bus.apply(Kind.NODE, "n0", NodeSpec(
            name="n0", allocatable={R.CPU: 32000, R.MEMORY: 65536}))
        bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
            node_name="n0", node_usage={R.CPU: 2000, R.MEMORY: 4096},
            sys_usage={R.CPU: 500}, update_time=100.0))
        ea.tick(0.0)
        eb.tick(0.1)
        assert loop_a.reconcile(now=101.0) == 1
        assert bus.get(Kind.NODE, "n0").allocatable.get(R.BATCH_CPU, 0) > 0

        eb.tick(20.0)  # manager-a died; b takes the lease
        assert eb.is_leader()
        # system usage moved enough to shift batch allocatable past the
        # diff threshold — both loops would PATCH; only the leader's
        # write may land
        bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
            node_name="n0", node_usage={R.CPU: 12000, R.MEMORY: 16384},
            sys_usage={R.CPU: 9000}, update_time=101.5))
        with pytest.raises(FencingError):
            loop_a.reconcile(now=102.0)
        assert loop_b.reconcile(now=102.0) == 1


def test_deposed_descheduler_discards_migrations():
    """A descheduler loop that computed migrations while holding the
    lease, but was deposed before the mutation phase, raises instead of
    double-evicting (matches the scheduler/manager fencing)."""
    from koordinator_tpu.client.wiring import wire_descheduler
    from koordinator_tpu.descheduler.framework import (
        Descheduler,
        MigrationEvictor,
        Profile,
    )
    from koordinator_tpu.descheduler.loadaware import (
        LowNodeLoad,
        LowNodeLoadArgs,
        NodePool,
    )

    bus = APIServer()
    ea = LeaderElector(bus, "koord-descheduler", "a")
    eb = LeaderElector(bus, "koord-descheduler", "b")
    plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={R.CPU: 30}, high_thresholds={R.CPU: 70})]))
    loop = wire_descheduler(bus, Descheduler(
        profiles=[Profile(name="d", balance_plugins=[plugin])],
        evictor=MigrationEvictor()), elector=ea)
    bus.apply(Kind.NODE, "hot", NodeSpec(
        name="hot", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE, "cold", NodeSpec(
        name="cold", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE_METRIC, "hot", NodeMetric(
        node_name="hot", node_usage={R.CPU: 9000}, update_time=100.0))
    bus.apply(Kind.NODE_METRIC, "cold", NodeMetric(
        node_name="cold", node_usage={R.CPU: 200}, update_time=100.0))
    victim = PodSpec(name="heavy", requests={R.CPU: 4000}, node_name="hot")
    bus.apply(Kind.POD, "default/heavy", victim)

    ea.tick(0.0)
    eb.tick(20.0)  # a deposed before its cycle's mutation phase
    with pytest.raises(FencingError):
        loop.run_once(now=110.0)
    # nothing was applied: no jobs, no reservations, pod untouched
    assert not bus.list(Kind.MIGRATION_JOB)
    assert not bus.list(Kind.RESERVATION)
    assert bus.get(Kind.POD, "default/heavy").node_name == "hot"


def test_evict_through_bus_is_fenced(monkeypatch):
    """wire_scheduler's eviction callback routes through the elector:
    a deposed leader cannot delete a victim pod from the bus."""
    bus = APIServer()
    s = Scheduler()
    e = LeaderElector(bus, "koord-scheduler", "a")
    wire_scheduler(bus, s, elector=e)
    pod = PodSpec(name="v", requests={R.CPU: 100})
    bus.apply(Kind.POD, "default/v", pod)
    e.tick(0.0)
    # leader evicts fine
    s.evict_pod_fn(pod)
    assert bus.get(Kind.POD, "default/v") is None
    # re-add; depose; eviction must fence
    bus.apply(Kind.POD, "default/v", pod)
    other = LeaderElector(bus, "koord-scheduler", "b")
    other.tick(20.0)
    with pytest.raises(FencingError):
        s.evict_pod_fn(pod)
    assert bus.get(Kind.POD, "default/v") is not None
