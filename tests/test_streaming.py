"""Streaming serving mode tests (ISSUE 14 / DESIGN §22).

The adaptive trigger's whole contract is "change WHEN rounds fire,
never WHAT they decide", which makes three things properties:

- fake-clock trigger determinism — a lone urgent pod fires a round at
  its lane deadline (deadline-fires-first) while a burst crossing the
  watermark fires immediately (watermark-fires-first), in a provable
  order;
- bit-identity — a streaming run's final placements and node
  accounting equal replaying the SAME arrival sequence (the recorded
  per-round batches) through the fixed-round loop;
- zero silent drops — every submitted pod resolves: bound, typed
  shed, or typed deadline expiry; submitted == bound + shed + expired
  once drained.

Plus the intake's QoS shed policy (BE first, arrivals that outrank
nothing refused), the timeline-capacity backpressure wiring, the
rolling latency window, and a real-pipeline smoke slice for check.sh.
"""

import time

import numpy as np
import pytest

from koordinator_tpu.apis.extension import QoSClass, ResourceName
from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.client.bus import APIServer, Kind
from koordinator_tpu.client.wiring import snapshot_from_bus, wire_scheduler
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.obs.timeline import PodTimelines
from koordinator_tpu.ops.binpack import STAGED_NODE_FIELDS
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.scheduler.streaming import (
    OUTCOME_BOUND,
    OUTCOME_EXPIRED,
    OUTCOME_SHED,
    ArrivalGate,
    StreamingConfig,
    StreamingLoop,
)
from koordinator_tpu.state.cluster import lower_nodes
from koordinator_tpu.testing.arrivals import (
    TRACE_KINDS,
    make_trace,
    trace_pods,
)

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY


@pytest.fixture(autouse=True)
def _shape_flow_under_streaming(shape_flow_sentinel):
    """Every streaming scenario runs inside a shape-flow sentinel
    window (ISSUE 15): the continuous-arrival path's drifting batch
    sizes are exactly the load shape that recompile storms feed on, so
    every signature the compile ring observes here must sit inside the
    statically-enumerated bucket images (module teardown asserts zero
    violations and non-vacuity)."""
    shape_flow_sentinel.begin_window()
    yield
    shape_flow_sentinel.verify_window()


N_NODES = 8


def _seed_bus(bus, n_nodes=N_NODES):
    for i in range(n_nodes):
        bus.apply(Kind.NODE, f"n{i}", NodeSpec(
            name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
        bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
            node_name=f"n{i}", node_usage={}, update_time=90.0))


def _wire(clock, config=None, pipelined=False, n_nodes=N_NODES,
          timelines=None):
    """A bus-wired scheduler + StreamingLoop on a fake clock."""
    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    if timelines is not None:
        sched.timelines = timelines
    wire_scheduler(bus, sched)
    _seed_bus(bus, n_nodes)
    loop = StreamingLoop(
        sched,
        apply_fn=lambda pod: bus.apply(Kind.POD, pod.uid, pod),
        delete_fn=lambda uid: bus.delete(Kind.POD, uid),
        config=config or StreamingConfig(),
        pipelined=pipelined,
        clock=lambda: clock[0],
        now_fn=lambda: clock[0],
        log=lambda *a: None,
    )
    return bus, sched, loop


def _pod(name, cpu=500, mem=256, qos=QoSClass.NONE, gang=None):
    return PodSpec(name=name, requests={CPU: cpu, MEM: mem}, qos=qos,
                   gang=gang)


# -- trigger determinism (fake clock) ----------------------------------------

class TestTrigger:
    def test_deadline_fires_first_for_lone_urgent_pod(self):
        """A lone system-lane pod fires a round at ITS 2ms lane
        deadline — not the be/ls deadlines, not a fixed cadence."""
        clock = [100.0]
        cfg = StreamingConfig(watermark=64,
                              lane_deadline_s=(0.002, 0.010, 0.050))
        bus, sched, loop = _wire(clock, cfg)
        assert loop.submit(_pod("urgent", qos=QoSClass.SYSTEM),
                           now=clock[0]) == "queued"
        assert loop.due(clock[0]) is None
        assert loop.gate.next_deadline() == pytest.approx(100.002)
        clock[0] = 100.0015
        assert loop.pump(clock[0]) is None  # not due yet
        clock[0] = 100.002
        assert loop.pump(clock[0]) == "deadline"
        assert loop.gate.outcome("default/urgent") == OUTCOME_BOUND
        loop.stop()

    def test_watermark_fires_first_for_a_burst(self):
        """A burst crossing the watermark fires IMMEDIATELY — before
        any lane deadline — and the whole burst rides one round."""
        clock = [200.0]
        cfg = StreamingConfig(watermark=16,
                              lane_deadline_s=(0.002, 0.010, 0.050))
        bus, sched, loop = _wire(clock, cfg)
        for j in range(40):
            assert loop.submit(_pod(f"b{j}"), now=clock[0]) == "queued"
        # zero time has passed: no deadline is due, the watermark is
        assert loop.due(clock[0]) == "watermark"
        assert loop.pump(clock[0]) == "watermark"
        st = loop.status()
        assert st["rounds"] == 1, "the burst fragmented into rounds"
        assert st["gate"]["bound"] == 40
        reason, _now, uids = loop.round_log[-1]
        assert reason == "watermark" and len(uids) == 40
        loop.stop()

    def test_deadline_ordering_across_lanes(self):
        """With one pod per lane submitted together, the trigger time
        is the SYSTEM deadline (the minimum over queued deadlines)."""
        clock = [300.0]
        cfg = StreamingConfig(watermark=64,
                              lane_deadline_s=(0.002, 0.010, 0.050))
        bus, sched, loop = _wire(clock, cfg)
        loop.submit(_pod("be-pod", qos=QoSClass.BE), now=clock[0])
        loop.submit(_pod("ls-pod", qos=QoSClass.LS), now=clock[0])
        loop.submit(_pod("sys-pod", qos=QoSClass.SYSTEM), now=clock[0])
        assert loop.gate.next_deadline() == pytest.approx(300.002)
        clock[0] = 300.002
        assert loop.pump(clock[0]) == "deadline"
        # ALL queued pods ride the fired round, not only the trigger
        assert loop.status()["gate"]["bound"] == 3
        loop.stop()

    def test_min_round_interval_floors_the_dispatch_rate(self):
        clock = [400.0]
        cfg = StreamingConfig(watermark=1, min_round_interval_s=0.020,
                              lane_deadline_s=(0.002, 0.010, 0.050))
        bus, sched, loop = _wire(clock, cfg)
        loop.submit(_pod("p0"), now=clock[0])
        assert loop.pump(clock[0]) == "watermark"
        loop.submit(_pod("p1"), now=clock[0])
        assert loop.pump(clock[0]) is None  # floored
        clock[0] += 0.021  # past the floor (0.020 lands a float ulp short)
        assert loop.pump(clock[0]) == "watermark"
        loop.stop()

    def test_trigger_reason_lands_on_the_round_trace(self):
        from koordinator_tpu.obs.trace import TRACER

        clock = [500.0]
        bus, sched, loop = _wire(
            clock, StreamingConfig(watermark=1))
        TRACER.clear()
        loop.submit(_pod("traced"), now=clock[0])
        loop.pump(clock[0])
        rounds = [e for e in TRACER.events() if e["name"] == "round"]
        assert rounds and rounds[-1]["args"]["trigger"] == "watermark"
        loop.stop()


# -- intake shed policy ------------------------------------------------------

class TestIntakeShed:
    def test_be_shed_first_and_outranked_arrival_refused(self):
        clock = [100.0]
        cfg = StreamingConfig(watermark=64, capacity=3)
        bus, sched, loop = _wire(clock, cfg)
        assert loop.submit(_pod("be0", qos=QoSClass.BE),
                           now=clock[0]) == "queued"
        assert loop.submit(_pod("be1", qos=QoSClass.BE),
                           now=clock[0]) == "queued"
        assert loop.submit(_pod("ls0", qos=QoSClass.LS),
                           now=clock[0]) == "queued"
        # at capacity: an LS arrival evicts the NEWEST BE entry
        assert loop.submit(_pod("ls1", qos=QoSClass.LS),
                           now=clock[0]) == "queued"
        assert loop.gate.outcome("default/be1") == OUTCOME_SHED
        assert bus.get(Kind.POD, "default/be1") is None, \
            "the shed victim must leave the bus"
        # a BE arrival at capacity outranks nothing: refused, and it
        # never touches the bus
        assert loop.submit(_pod("be2", qos=QoSClass.BE),
                           now=clock[0]) == "shed"
        assert bus.get(Kind.POD, "default/be2") is None
        assert loop.gate.outcome("default/be2") == OUTCOME_SHED
        st = loop.status()["gate"]
        assert st["shed"]["capacity"] == 2
        # nothing silent: every submitted pod is accounted for
        assert st["submitted"] == 5
        loop.stop()

    def test_timeline_capacity_drop_is_backpressure_not_silence(self):
        """PodTimelines refusing a sample at capacity must land in the
        gate's shed accounting (reason timeline-capacity) — and the
        pod itself still schedules."""
        from koordinator_tpu.metrics.components import STREAM_SHED

        clock = [100.0]
        tl = PodTimelines(capacity=2)
        bus, sched, loop = _wire(
            clock, StreamingConfig(watermark=64), timelines=tl)
        before = STREAM_SHED.value({"lane": "ls",
                                    "reason": "timeline-capacity"})
        for j in range(3):
            assert loop.submit(_pod(f"p{j}"), now=clock[0]) == "queued"
        st = loop.status()
        assert st["gate"]["shed"]["timeline-capacity"] == 1
        assert st["latency"]["dropped"] == 1
        assert STREAM_SHED.value(
            {"lane": "ls", "reason": "timeline-capacity"}
        ) == before + 1
        clock[0] += 0.010
        loop.pump(clock[0])
        # the dropped-SAMPLE pod still bound — backpressure, not a drop
        assert loop.gate.outcome("default/p2") == OUTCOME_BOUND
        loop.stop()

    def test_unplaceable_pod_expires_typed_after_max_rounds(self):
        clock = [100.0]
        cfg = StreamingConfig(watermark=64, max_pod_rounds=2,
                              lane_deadline_s=(0.002, 0.010, 0.050))
        bus, sched, loop = _wire(clock, cfg)
        # nothing can host 100 CPUs: the pod is unplaceable
        loop.submit(_pod("whale", cpu=100000, mem=999999), now=clock[0])
        clock[0] += 0.011
        assert loop.pump(clock[0]) == "deadline"   # round 1: unplaced
        assert loop.gate.outcome("default/whale") is None
        clock[0] += 0.011
        assert loop.pump(clock[0]) == "deadline"   # round 2: expires
        assert loop.gate.outcome("default/whale") == OUTCOME_EXPIRED
        assert bus.get(Kind.POD, "default/whale") is None, \
            "an expired pod must leave the bus (typed, observed)"
        st = loop.status()["gate"]
        assert st["shed"]["deadline-exceeded"] == 1
        assert loop.gate.unresolved() == 0
        loop.stop()


# -- bit-identity vs the fixed-round replay ----------------------------------

def _replay_fixed(round_log, pods_by_uid, gangs, n_nodes=N_NODES):
    """Replay recorded per-round arrival batches through the plain
    fixed-round loop: apply round i's arrivals, schedule once — the
    trigger policy changed WHEN rounds fired, so re-driving the same
    batches through schedule_pending must reproduce the decisions."""
    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    wire_scheduler(bus, sched)
    _seed_bus(bus, n_nodes)
    for name, spec in gangs.items():
        bus.apply(Kind.GANG, name, spec)
    for reason, now, uids in round_log:
        for uid in uids:
            pod = pods_by_uid[uid]
            bus.apply(Kind.POD, pod.uid, pod)
        sched.schedule_pending(now=now)
    return bus, sched


@pytest.mark.parametrize("kind", ["heavy-tail", "gang-wave"])
def test_streaming_bit_identical_to_fixed_round_replay(kind):
    """The tentpole property: a full streaming run (fake clock, seeded
    arrival trace, adaptive triggers) ends with final placements AND
    node accounting bit-identical to replaying its recorded per-round
    arrival batches through the fixed-round loop."""
    import dataclasses

    clock = [100.0]
    cfg = StreamingConfig(watermark=8,
                          lane_deadline_s=(0.002, 0.010, 0.050))
    bus, sched, loop = _wire(clock, cfg)
    trace = make_trace(kind, seed=11, duration_s=1.0,
                       rate_pods_per_s=60.0)
    pairs, gangs = trace_pods(trace)
    for name, spec in gangs.items():
        bus.apply(Kind.GANG, name, spec)
    pods_by_uid = {}
    for at, pod in pairs:
        clock[0] = 100.0 + at
        verdict = loop.submit(pod, now=clock[0])
        assert verdict == "queued"
        pods_by_uid[pod.uid] = dataclasses.replace(pod)
        loop.pump(clock[0])
    # drain the tail: advance past every deadline until quiet
    for _ in range(64):
        clock[0] += 0.050
        if loop.pump(clock[0]) is None and loop.gate.depth() == 0:
            break
    st = loop.status()["gate"]
    # zero silent drops: every submitted pod has a terminal outcome
    assert st["submitted"] == len(pairs)
    assert loop.gate.unresolved() == 0
    assert st["submitted"] == (st["bound"] + st["shed"]["capacity"]
                               + st["shed"]["deadline-exceeded"])
    r_bus, r_sched = _replay_fixed(list(loop.round_log), pods_by_uid,
                                   gangs)
    # final placements: every pod on the same node, bit for bit
    mine = {u: getattr(p, "node_name", None)
            for u, p in bus.list(Kind.POD).items()}
    replay = {u: getattr(p, "node_name", None)
              for u, p in r_bus.list(Kind.POD).items()}
    assert mine == replay
    # node accounting bit-for-bit
    got = lower_nodes(snapshot_from_bus(bus, now=500.0))
    want = lower_nodes(snapshot_from_bus(r_bus, now=500.0))
    assert got.names == want.names
    for f in STAGED_NODE_FIELDS:
        np.testing.assert_array_equal(
            getattr(got, f), getattr(want, f),
            err_msg=f"node accounting diverged: {f}")
    loop.stop()


def test_arrival_traces_are_seed_deterministic():
    for kind in TRACE_KINDS:
        a = make_trace(kind, seed=5, duration_s=2.0, rate_pods_per_s=40)
        b = make_trace(kind, seed=5, duration_s=2.0, rate_pods_per_s=40)
        c = make_trace(kind, seed=6, duration_s=2.0, rate_pods_per_s=40)
        assert a.arrivals == b.arrivals, kind
        assert a.arrivals != c.arrivals, kind
        assert all(x.at <= y.at for x, y in
                   zip(a.arrivals, a.arrivals[1:])), kind
        assert len(a) > 0


def test_burst_storm_has_mid_trace_storms():
    tr = make_trace("burst-storm", seed=2, duration_s=4.0,
                    rate_pods_per_s=10.0, bursts=2, burst_pods=32)
    storm = [a for a in tr if "s0" in a.name or "s1" in a.name]
    assert len(storm) == 64
    assert all(0.1 * 4.0 <= a.at <= 0.9 * 4.0 + 0.01 for a in storm)


# -- rolling latency window --------------------------------------------------

def test_rolling_window_excludes_stale_samples():
    t = [1000.0]
    tl = PodTimelines(clock=lambda: t[0], histogram=_NullHist())
    tl.submit("old", "ls")
    t[0] += 0.5
    tl.published("old")
    t[0] += 100.0
    tl.submit("fresh", "ls")
    t[0] += 0.25
    tl.published("fresh")
    assert tl.stats()["all"]["count"] == 2
    rolling = tl.stats(window_s=30.0)
    assert rolling["all"]["count"] == 1
    assert rolling["all"]["p50_s"] == pytest.approx(0.25)
    status = tl.status()
    assert status["rolling"]["window_s"] == tl.ROLLING_WINDOW_S
    assert status["rolling"]["all"]["count"] == 1


class _NullHist:
    def observe(self, *a, **k):
        pass


# -- the real pipelined loop (smoke) -----------------------------------------

def test_smoke_streaming_pipelined_real_clock():
    """check.sh's streaming smoke slice: a short REAL run — pipelined
    rounds, the self-pacing loop thread, wall-clock triggers — binds
    every submitted pod and stays bit-identical to the fixed-round
    replay of its recorded batches."""
    import dataclasses

    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    wire_scheduler(bus, sched)
    _seed_bus(bus)
    cfg = StreamingConfig(watermark=8,
                          lane_deadline_s=(0.002, 0.010, 0.030))
    loop = StreamingLoop(
        sched,
        apply_fn=lambda pod: bus.apply(Kind.POD, pod.uid, pod),
        delete_fn=lambda uid: bus.delete(Kind.POD, uid),
        config=cfg, pipelined=True, log=lambda *a: None,
    ).start()
    pods_by_uid = {}
    try:
        for wave in range(3):
            for j in range(12):
                pod = _pod(f"w{wave}p{j}",
                           qos=QoSClass.SYSTEM if j == 0 else
                           QoSClass.NONE)
                pods_by_uid[pod.uid] = dataclasses.replace(pod)
                assert loop.submit(pod) == "queued"
            time.sleep(0.05)
        assert loop.drain(timeout_s=30.0), loop.status()
    finally:
        loop.stop()
    st = loop.status()
    assert st["gate"]["bound"] == 36
    assert st["gate"]["submitted"] == 36
    assert st["rounds"] >= 1
    # the latency surface is live: every bind produced a sample
    assert st["latency"]["latency"]["all"]["count"] == 36
    r_bus, _ = _replay_fixed(list(loop.round_log), pods_by_uid, {})
    mine = {u: getattr(p, "node_name", None)
            for u, p in bus.list(Kind.POD).items()}
    replay = {u: getattr(p, "node_name", None)
              for u, p in r_bus.list(Kind.POD).items()}
    assert mine == replay


def test_run_loop_streaming_branch_validates_wiring():
    from koordinator_tpu.cmd.scheduler import SchedulerConfig, run_loop

    sched = Scheduler(model=PlacementModel(use_pallas=False))
    config = SchedulerConfig(streaming=True)
    with pytest.raises(ValueError, match="StreamingLoop"):
        run_loop(sched, config)


def test_build_streaming_loop_bus_intake_and_debug_surface():
    """cmd wiring: externally-applied pending pods enter the intake
    through the bus watch, the debug mux serves the streaming status,
    and loop.submit's own applies are not double-admitted."""
    from koordinator_tpu.cmd.scheduler import (
        SchedulerConfig,
        build_streaming_loop,
    )

    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    wire_scheduler(bus, sched)
    _seed_bus(bus)
    config = SchedulerConfig(streaming=True, stream_watermark=2)
    loop = build_streaming_loop(sched, bus, config, log=lambda *a: None)
    try:
        # an external component applies a pending pod directly
        ext = _pod("external")
        bus.apply(Kind.POD, ext.uid, ext)
        assert loop.gate.depth() == 1
        # loop.submit applies to the bus; the watch must not re-admit
        loop.submit(_pod("mine"))
        assert loop.gate.depth() == 2
        assert loop.status()["gate"]["submitted"] == 2
        # the debug surface is registered
        assert "streaming" in sched.services.names()
        assert sched.services.query("streaming")["gate"]["submitted"] == 2
        loop.pump()
        assert loop.gate.outcome("default/external") == OUTCOME_BOUND
        assert loop.gate.outcome("default/mine") == OUTCOME_BOUND
    finally:
        loop.stop()


def test_gate_forget_on_bus_delete():
    """A tracked pending pod deleted on the bus leaves intake
    bookkeeping (the remove_pod chain), so it neither fires rounds nor
    counts as unresolved."""
    clock = [100.0]
    bus, sched, loop = _wire(clock, StreamingConfig(watermark=64))
    loop.submit(_pod("doomed"), now=clock[0])
    assert loop.gate.depth() == 1
    bus.delete(Kind.POD, "default/doomed")
    assert loop.gate.depth() == 0
    assert loop.gate.unresolved() == 0
    loop.stop()


def test_gate_direct_unit_watermark_vs_deadline():
    """ArrivalGate alone (no scheduler): the two triggers and their
    precedence, unit-level."""
    t = [0.0]
    gate = ArrivalGate(StreamingConfig(
        watermark=2, lane_deadline_s=(0.001, 0.010, 0.050)),
        clock=lambda: t[0])
    gate.admit("a", 2, now=0.0)          # be: deadline 0.05
    assert gate.due(0.0) is None
    assert gate.next_deadline() == pytest.approx(0.05)
    gate.admit("b", 0, now=0.0)          # system: deadline 0.001 AND
    assert gate.due(0.0) == "watermark"  # watermark crossed — it wins
    batch = gate.take_round()
    assert [e.uid for e in batch] == ["b", "a"]  # lane priority order
    assert gate.due(0.0) is None


def test_gate_resolves_queued_entry_placed_by_overlapped_round():
    """Pipelined-race regression: round N+1's batch is taken BEFORE
    round N retires, so a pod round N requeued can be PLACED by round
    N+1 while it sits in the queue. Its bound outcome must resolve
    (and the entry leave the intake) — not leak in-flight forever."""
    from koordinator_tpu.models.placement import ScheduleResult

    t = [0.0]
    gate = ArrivalGate(StreamingConfig(
        watermark=64, lane_deadline_s=(0.002, 0.010, 0.050)),
        clock=lambda: t[0])
    gate.admit("p", 1, now=0.0)
    taken = gate.take_round()
    assert [e.uid for e in taken] == ["p"]
    # round N: unplaced → requeued (back to the QUEUE, not inflight)
    gate.resolve_round(ScheduleResult({"p": None}), now=0.0)
    assert gate.depth() == 1 and gate.outcome("p") is None
    # round N+1 (batch taken before N retired, snapshot spans ALL
    # pending pods) places it while it is queued
    counts = gate.resolve_round(ScheduleResult({"p": "n3"}), now=0.1)
    assert counts["bound"] == 1
    assert gate.outcome("p") == OUTCOME_BOUND
    assert gate.depth() == 0 and gate.unresolved() == 0
    # the waiting transition resolves from the queue too
    gate.admit("g", 1, now=0.2)
    gate.resolve_round(
        ScheduleResult({"g": "n1"}, waiting={"g": "n1"}), now=0.2)
    assert gate.unresolved() == 1  # Permit-held, not leaked
    gate.resolve_round(ScheduleResult({"g": "n1"}), now=0.3)
    assert gate.outcome("g") == OUTCOME_BOUND
    assert gate.unresolved() == 0


def test_observe_readmits_recreated_pod():
    """A pod deleted and re-created under the same namespace/name (the
    ordinary k8s recreate flow) is a NEW arrival: the bus-watch intake
    must re-admit it, not skip it because its predecessor resolved."""
    clock = [100.0]
    bus, sched, loop = _wire(clock, StreamingConfig(watermark=64))
    loop.submit(_pod("phoenix"), now=clock[0])
    clock[0] += 0.011
    loop.pump(clock[0])
    assert loop.gate.outcome("default/phoenix") == OUTCOME_BOUND
    bus.delete(Kind.POD, "default/phoenix")
    # recreated under the same name: a fresh pending arrival applied
    # by another component, routed to the intake by the bus watch
    reborn = _pod("phoenix")
    bus.apply(Kind.POD, reborn.uid, reborn)
    loop.observe(reborn, now=clock[0])
    assert loop.gate.depth() == 1, "recreated pod was not re-admitted"
    clock[0] += 0.011
    loop.pump(clock[0])
    assert loop.gate.unresolved() == 0
    loop.stop()


def test_pipelined_loop_wires_failover_flip_quiesce():
    """A pipelined StreamingLoop must chain the backend's flip hooks
    to a pipeline drain (run_loop's contract: a flip's epoch reset
    never races an in-flight tick's retire) and restore the originals
    on stop()."""
    calls = []

    class StubBackend:
        on_flip_back = None
        on_flip_degraded = None

    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    sched.model.backend = StubBackend()
    prev = sched.model.backend.on_flip_back = lambda: calls.append("prev")
    wire_scheduler(bus, sched)
    _seed_bus(bus)
    loop = StreamingLoop(
        sched, apply_fn=lambda pod: bus.apply(Kind.POD, pod.uid, pod),
        pipelined=True, log=lambda *a: None,
    )
    try:
        assert sched.model.backend.on_flip_back is not prev
        sched.model.backend.on_flip_back()   # a recovery flip fires
        assert calls == ["prev"], "the pre-existing hook must chain"
        assert sched.model.backend.on_flip_degraded is not None
    finally:
        loop.stop()
    assert sched.model.backend.on_flip_back is prev, \
        "stop() must restore the original hook"
    assert sched.model.backend.on_flip_degraded is None


def test_fire_round_requeues_batch_on_untyped_failure():
    """An UNTYPED round failure still fails loudly, but the taken
    batch must return to the queue first — leaked in-flight entries
    would break the zero-silent-drop accounting forever."""
    clock = [100.0]
    bus, sched, loop = _wire(clock, StreamingConfig(watermark=1))
    loop.submit(_pod("p0"), now=clock[0])
    sched.schedule_pending = lambda **kw: (_ for _ in ()).throw(
        RuntimeError("injected"))
    with pytest.raises(RuntimeError, match="injected"):
        loop.fire_round("watermark", now=clock[0])
    assert loop.gate.depth() == 1, "the batch leaked out of the queue"
    assert loop.gate.unresolved() == 1
    loop.stop()
