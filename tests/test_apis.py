"""Protocol-layer tests: QoS/priority parsing, resource vectors, estimator."""

from koordinator_tpu.apis.extension import (
    NUM_RESOURCES,
    PriorityClass,
    QoSClass,
    ResourceName,
    priority_class_of,
    qos_class_of,
)
from koordinator_tpu.apis.types import (
    PodSpec,
    resources_to_vector,
    vector_to_resources,
)
from koordinator_tpu.state.cluster import (
    DEFAULT_MEMORY_REQUEST_MIB,
    DEFAULT_MILLI_CPU_REQUEST,
    estimate_pod_used,
    translate_resource_by_priority,
)


def test_qos_parsing():
    # reference: apis/extension/qos.go:31-40
    assert qos_class_of("LSE") == QoSClass.LSE
    assert qos_class_of("LSR") == QoSClass.LSR
    assert qos_class_of("LS") == QoSClass.LS
    assert qos_class_of("BE") == QoSClass.BE
    assert qos_class_of("SYSTEM") == QoSClass.SYSTEM
    assert qos_class_of("bogus") == QoSClass.NONE
    assert qos_class_of(None) == QoSClass.NONE
    assert QoSClass.LS.is_latency_sensitive
    assert not QoSClass.BE.is_latency_sensitive


def test_priority_bands():
    # reference: apis/extension/priority.go:37-49,84-101
    assert priority_class_of(value=9500) == PriorityClass.PROD
    assert priority_class_of(value=9000) == PriorityClass.PROD
    assert priority_class_of(value=9999) == PriorityClass.PROD
    assert priority_class_of(value=7500) == PriorityClass.MID
    assert priority_class_of(value=5999) == PriorityClass.BATCH
    assert priority_class_of(value=3000) == PriorityClass.FREE
    assert priority_class_of(value=8500) == PriorityClass.NONE
    assert priority_class_of(value=0) == PriorityClass.NONE
    assert priority_class_of(name="koord-batch") == PriorityClass.BATCH
    # label takes precedence over numeric value
    assert priority_class_of(name="koord-mid", value=9500) == PriorityClass.MID


def test_resource_vector_roundtrip():
    res = {ResourceName.CPU: 4000, ResourceName.MEMORY: 8192}
    vec = resources_to_vector(res)
    assert vec.shape == (NUM_RESOURCES,)
    assert vec[ResourceName.CPU] == 4000
    assert vector_to_resources(vec) == res


def test_translate_resource_by_priority():
    assert (
        translate_resource_by_priority(ResourceName.CPU, PriorityClass.BATCH)
        == ResourceName.BATCH_CPU
    )
    assert (
        translate_resource_by_priority(ResourceName.MEMORY, PriorityClass.MID)
        == ResourceName.MID_MEMORY
    )
    assert (
        translate_resource_by_priority(ResourceName.CPU, PriorityClass.PROD)
        == ResourceName.CPU
    )


def test_estimator_request_scaling():
    # request 1000m cpu, 1024 MiB; defaults scale cpu 85%, mem 70%
    # (default_estimator.go:57-110; defaults.go:45-48)
    pod = PodSpec(
        name="a",
        requests={ResourceName.CPU: 1000, ResourceName.MEMORY: 1024},
        priority=9500,
    )
    est = estimate_pod_used(pod)
    assert est[ResourceName.CPU] == 850       # round(1000*85/100)
    assert est[ResourceName.MEMORY] == 717    # round(1024*70/100) = 716.8 -> 717


def test_estimator_limit_overrides_scaling():
    # limit > request forces factor 100 and uses the limit
    pod = PodSpec(
        name="a",
        requests={ResourceName.CPU: 1000},
        limits={ResourceName.CPU: 2000},
    )
    est = estimate_pod_used(pod)
    assert est[ResourceName.CPU] == 2000


def test_estimator_zero_request_defaults():
    pod = PodSpec(name="a")
    est = estimate_pod_used(pod)
    assert est[ResourceName.CPU] == DEFAULT_MILLI_CPU_REQUEST
    assert est[ResourceName.MEMORY] == DEFAULT_MEMORY_REQUEST_MIB


def test_estimator_batch_pod_reads_batch_columns():
    pod = PodSpec(
        name="b",
        requests={ResourceName.BATCH_CPU: 2000, ResourceName.BATCH_MEMORY: 2048},
        priority=5500,  # koord-batch band
    )
    est = estimate_pod_used(pod)
    assert est[ResourceName.CPU] == 1700      # round(2000*85/100)
    assert est[ResourceName.MEMORY] == 1434   # round(2048*70/100) = 1433.6


def test_estimator_cap_at_limit():
    # estimate would round above the limit -> capped
    pod = PodSpec(
        name="a",
        requests={ResourceName.CPU: 100},
        limits={ResourceName.CPU: 84},  # limit < request: use request, factor 85
    )
    est = estimate_pod_used(pod)
    # round(100*85/100)=85 capped at limit 84
    assert est[ResourceName.CPU] == 84
