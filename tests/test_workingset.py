"""HBM working-set manager (ISSUE 19 / docs/DESIGN.md §26): staged
tenant worlds governed under a fixed device-memory budget by a
three-rung residency ladder (device → host-pinned → cold), with
demotion policy (BE-first, then weight, then LRU), admission headroom,
a typed alloc-failure demote+retry ladder — and the load-bearing
property: placements are BIT-IDENTICAL at every rung, because every
rung re-enters a staging path the delta-parity suite already pins.
"""

import gc

import numpy as np
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES
from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.ops.binpack import STAGED_NODE_FIELDS
from koordinator_tpu.service.codec import SolveRequest
from koordinator_tpu.service.server import NodeStateCache, solve_from_request
from koordinator_tpu.state.workingset import (
    RUNG_COLD,
    RUNG_DEVICE,
    RUNG_HOST,
    WORKING_SET,
    InjectedAllocFailure,
    WorkingSetExhausted,
    WorkingSetManager,
)


@pytest.fixture(autouse=True)
def _clean_working_set():
    """Each test starts (and leaves) the process singleton empty and
    unbudgeted — residents registered by other suites' long-lived
    caches just re-touch on their next use."""
    WORKING_SET.reset()
    yield
    WORKING_SET.reset()


class _FakeWorld:
    """A resident with controllable pricing and demote hooks."""

    def __init__(self, nbytes=100):
        self.nbytes = nbytes
        self.on_device = True
        self.host = True
        self.refuse = False

    def device_bytes(self):
        return self.nbytes if self.on_device else 0

    def demote_device(self):
        if self.refuse or not self.on_device:
            return False
        self.on_device = False
        return True

    def demote_cold(self):
        if self.refuse or (not self.on_device and not self.host):
            return False
        self.on_device = False
        self.host = False
        return True


def _rungs(manager):
    return {row["key"]: row["rung"] for row in manager.status()["rows"]}


# -- unit: policy, budget math, retry ladder --------------------------------

class TestWorkingSetUnit:
    def test_unit_victim_order_be_first_then_weight_then_lru(self):
        m = WorkingSetManager()
        worlds = {
            "be-heavy": ("be", 5.0),
            "ls-light": ("ls", 1.0),
            "ls-heavy": ("ls", 5.0),
            "sys": ("system", 1.0),
        }
        objs = {}
        for key, (lane, weight) in worlds.items():
            objs[key] = _FakeWorld(100)
            m.register(key, objs[key], lane=lane, weight=weight)
            m.touch(key)
        assert m.device_bytes() == 400
        # free 150: the BE world first (lane rank), then the lightest
        # LS world — weight orders within a lane before recency
        m.set_budget(250)
        rungs = _rungs(m)
        assert rungs["be-heavy"] == RUNG_HOST
        assert rungs["ls-light"] == RUNG_HOST
        assert rungs["ls-heavy"] == RUNG_DEVICE
        assert rungs["sys"] == RUNG_DEVICE
        assert m.device_bytes() == 200

    def test_unit_lru_breaks_ties_within_lane_and_weight(self):
        m = WorkingSetManager()
        # residents are weakly held: keep the worlds alive in the test
        worlds = {k: _FakeWorld(100) for k in ("old", "mid", "new")}
        for key, w in worlds.items():
            m.register(key, w, lane="ls", weight=1.0)
        for key in ("old", "mid", "new"):
            m.touch(key)
        m.touch("old")  # re-use: "mid" is now least recent
        m.set_budget(250)
        assert _rungs(m)["mid"] == RUNG_HOST
        assert _rungs(m)["old"] == RUNG_DEVICE
        assert _rungs(m)["new"] == RUNG_DEVICE

    def test_unit_budget_boundary_off_by_one(self):
        m = WorkingSetManager()
        worlds = {k: _FakeWorld(128) for k in ("a", "b")}
        for key, w in worlds.items():
            m.register(key, w)
            m.touch(key)
        # exactly at the line: nothing demotes
        m.set_budget(256)
        assert m.device_bytes() == 256
        assert m.status()["demotions"] == {}
        # one byte under: exactly one victim
        m.set_budget(255)
        assert m.device_bytes() == 128
        assert m.status()["demotions"] == {"budget": 1}

    def test_unit_admission_demotes_instead_of_overallocating(self):
        m = WorkingSetManager(budget_bytes=256)
        worlds = {k: _FakeWorld(128) for k in ("a", "b")}
        for key, w in worlds.items():
            m.register(key, w)
            m.touch(key)
        new = _FakeWorld(128)
        m.register("c", new)
        # headroom is made BEFORE the allocation lands
        m.admit("c", 128)
        assert m.device_bytes() + 128 <= 256
        assert m.status()["demotions"] == {"admission": 1}
        m.touch("c")
        assert m.device_bytes() <= 256

    def test_unit_protected_key_never_demoted_counts_oversubscribed(self):
        m = WorkingSetManager(budget_bytes=256)
        only = _FakeWorld(512)
        m.register("only", only)
        m.touch("only")
        # nothing to evict but the world just used: the solve proceeds,
        # the overshoot is counted instead of fought
        assert _rungs(m)["only"] == RUNG_DEVICE
        assert m.status()["oversubscribed"] >= 1

    def test_unit_busy_resident_skipped(self):
        m = WorkingSetManager()
        busy, idle = _FakeWorld(100), _FakeWorld(100)
        busy.refuse = True  # demote hook reports mid-solve
        m.register("busy", busy, lane="be")
        m.register("idle", idle, lane="ls")
        m.touch("busy")
        m.touch("idle")
        m.set_budget(150)
        # the BE world would be first in policy order but refuses; the
        # LS world is taken instead of the manager stalling
        assert _rungs(m)["busy"] == RUNG_DEVICE
        assert _rungs(m)["idle"] == RUNG_HOST

    def test_unit_squeeze_is_transient(self):
        m = WorkingSetManager(budget_bytes=400)
        worlds = {k: _FakeWorld(100) for k in ("a", "b")}
        for key, w in worlds.items():
            m.register(key, w)
            m.touch(key)
        demoted = m.squeeze(0.25)
        assert demoted >= 1
        st = m.status()
        assert st["effective_budget_bytes"] == 400  # restored
        assert st["demotions"]["budget"] == demoted

    def test_unit_alloc_failure_retry_ladder_typed_and_counted(self):
        m = WorkingSetManager()
        victim = _FakeWorld(100)
        m.register("victim", victim)
        m.touch("victim")
        m.register("me", _FakeWorld(100))
        m.arm_fault("stage", 2)
        calls = []

        def fn():
            calls.append(1)
            return "staged"

        assert m.run_staged("me", "stage", fn) == "staged"
        # the armed faults raise BEFORE fn runs: the landed staging
        # executed exactly once (bit-identity by construction)
        assert calls == [1]
        st = m.status()
        assert st["alloc_failures"] == {"stage": 2}
        assert st["demotions"].get("alloc-failure", 0) >= 1
        assert not victim.on_device

    def test_unit_alloc_failure_escalates_host_to_cold(self):
        m = WorkingSetManager()
        w = _FakeWorld(100)
        m.register("w", w)
        m.touch("w")
        m.set_budget(1)  # already host-pinned: the device rung is empty
        m.set_budget(None)
        assert _rungs(m)["w"] == RUNG_HOST
        me = _FakeWorld(0)
        m.register("me", me)
        m.arm_fault("scatter", 1)
        assert m.run_staged("me", "scatter", lambda: "ok") == "ok"
        # nothing on the device rung to demote: the ladder drops the
        # coldest host world's arrays instead
        assert not w.host

    def test_unit_exhaustion_raises_typed(self):
        m = WorkingSetManager(max_alloc_retries=2)
        m.register("me", _FakeWorld(0))
        m.arm_fault("stage", 10)
        with pytest.raises(WorkingSetExhausted):
            m.run_staged("me", "stage", lambda: "never")
        assert m.status()["alloc_failures"]["stage"] == 3  # 1 + 2 retries

    def test_unit_non_alloc_errors_propagate_unchanged(self):
        m = WorkingSetManager()
        m.register("me", _FakeWorld(0))
        with pytest.raises(ZeroDivisionError):
            m.run_staged("me", "stage", lambda: 1 // 0)
        assert m.status()["alloc_failures"] == {}

    def test_unit_injected_failure_is_alloc_shaped(self):
        from koordinator_tpu.state.workingset import is_alloc_failure

        assert is_alloc_failure(InjectedAllocFailure("x"))
        assert is_alloc_failure(RuntimeError("RESOURCE_EXHAUSTED: oom"))
        assert is_alloc_failure(RuntimeError("Out of memory allocating"))
        assert not is_alloc_failure(ValueError("bad shape"))

    def test_unit_dead_resident_pruned_not_demoted(self):
        m = WorkingSetManager()
        w = _FakeWorld(100)
        m.register("dead", w)
        m.touch("dead")
        live = _FakeWorld(100)
        m.register("live", live, lane="system")
        m.touch("live")
        del w
        gc.collect()
        m.set_budget(100)
        st = m.status()
        # the dead world's entry is dropped by the victim walk, its
        # bytes come off the ledger without a demotion hook call
        assert all(row["key"] != "dead" for row in st["rows"])
        assert st["residents"][RUNG_DEVICE] == 1

    def test_unit_status_rows_bounded(self):
        m = WorkingSetManager()
        for i in range(64):
            m.register(f"t{i}", _FakeWorld(10 + i))
            m.touch(f"t{i}")
        assert len(m.status()["rows"]) == 32
        assert m.status()["residents"][RUNG_DEVICE] == 64


# -- the wire-facing ladder: NodeStateCache ---------------------------------

def _world(n_nodes, seed):
    rng = np.random.default_rng(seed)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, R.CPU] = 16000
    alloc[:, R.MEMORY] = 32768
    used = np.zeros_like(alloc)
    used[:, R.CPU] = rng.integers(0, 8000, n_nodes)
    used[:, R.MEMORY] = rng.integers(0, 16384, n_nodes)
    node = {
        "alloc": alloc,
        "used_req": used,
        "usage": np.zeros_like(alloc),
        "prod_usage": np.zeros_like(alloc),
        "est_extra": np.zeros_like(alloc),
        "prod_base": np.zeros_like(alloc),
        "metric_fresh": np.ones(n_nodes, bool),
        "schedulable": np.ones(n_nodes, bool),
    }
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[R.CPU] = 1
    weights[R.MEMORY] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[R.CPU] = 65
    thresholds[R.MEMORY] = 95
    params = {
        "weights": weights,
        "thresholds": thresholds,
        "prod_thresholds": np.zeros(NUM_RESOURCES, np.int32),
    }
    return node, params


def _pods(n_pods, seed):
    rng = np.random.default_rng(seed)
    req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = rng.choice([500, 1000, 2000, 3000], n_pods)
    req[:, R.MEMORY] = rng.choice([256, 1024, 2048], n_pods)
    return {
        "req": req,
        "est": (req * 85) // 100,
        "is_prod": rng.uniform(size=n_pods) < 0.4,
        "is_daemonset": np.zeros(n_pods, bool),
    }


def _full_request(node, params, pods, epoch):
    return SolveRequest(
        node={f: v.copy() for f, v in node.items()}, params=params,
        pods=pods, node_delta={"epoch": np.asarray(epoch, np.int64)},
    )


def _delta_request(params, pods, idx, rows, base, epoch):
    delta = {
        "idx": np.asarray(idx, np.int32),
        "base_epoch": np.asarray(base, np.int64),
        "epoch": np.asarray(epoch, np.int64),
    }
    delta.update(rows)
    return SolveRequest(node={}, params=params, pods=pods,
                        node_delta=delta)


def _patch(node, rng, k=3):
    """Mutate k random rows of the reference world in place; return the
    wire delta rows (all staged columns for those rows)."""
    n = node["alloc"].shape[0]
    idx = np.sort(rng.choice(n, size=min(k, n), replace=False))
    node["used_req"][idx, R.CPU] = rng.integers(0, 12000, idx.size)
    node["usage"][idx, R.MEMORY] = rng.integers(0, 8192, idx.size)
    rows = {f: node[f][idx].copy() for f in STAGED_NODE_FIELDS}
    return idx, rows


def _assert_same(got, want, where=""):
    assert not got.error, f"{where}: {got.error}"
    assert not want.error, f"{where}: control errored: {want.error}"
    np.testing.assert_array_equal(got.assignments, want.assignments,
                                  err_msg=where)
    np.testing.assert_array_equal(got.node_used_req, want.node_used_req,
                                  err_msg=where)


class TestNodeCacheLadder:
    def test_host_pinned_restage_bit_identical(self):
        """A demoted-to-host base restages through apply() and every
        solve matches an always-resident twin bit-for-bit."""
        node, params = _world(10, seed=3)
        twin_node = {f: v.copy() for f, v in node.items()}
        cache = NodeStateCache(tenant="t", lane="be")
        twin = NodeStateCache(tenant="twin", lane="be")
        pods = _pods(4, seed=7)
        r0 = solve_from_request(_full_request(node, params, pods, 0),
                                node_cache=cache)
        w0 = solve_from_request(_full_request(twin_node, params, pods, 0),
                                node_cache=twin)
        _assert_same(r0, w0, "establish")
        rng = np.random.default_rng(11)
        for r in range(1, 5):
            idx, rows = _patch(node, rng)
            for f in STAGED_NODE_FIELDS:
                twin_node[f][idx] = rows[f]
            # force the ladder every round: device half dropped, host
            # kept — apply() must restage before patching
            assert WORKING_SET.demote(cache._ws_key)
            got = solve_from_request(
                _delta_request(params, pods, idx, rows, r - 1, r),
                node_cache=cache)
            want = solve_from_request(
                _delta_request(params, pods, idx, rows, r - 1, r),
                node_cache=twin)
            _assert_same(got, want, f"round {r}")
        assert WORKING_SET.status()["restages"].get("host", 0) >= 4

    def test_cold_demotion_typed_mismatch_then_reestablish(self):
        node, params = _world(10, seed=5)
        cache = NodeStateCache(tenant="t")
        pods = _pods(3, seed=9)
        solve_from_request(_full_request(node, params, pods, 0),
                           node_cache=cache)
        assert WORKING_SET.demote(cache._ws_key, rung=RUNG_COLD,
                                  reason="alloc-failure")
        rng = np.random.default_rng(13)
        idx, rows = _patch(node, rng)
        got = solve_from_request(
            _delta_request(params, pods, idx, rows, 0, 1),
            node_cache=cache)
        # typed, never a crash — and the protocol's existing self-heal
        # (re-establish) lands the same solve a delta would have
        assert got.error is not None
        assert got.error.startswith("delta-base-mismatch")
        healed = solve_from_request(_full_request(node, params, pods, 1),
                                    node_cache=cache)
        want = solve_from_request(
            SolveRequest(node=node, params=params, pods=pods))
        _assert_same(healed, want, "re-establish")

    def test_256_tenants_under_32_resident_budget(self):
        """256 tenants admitted on one device under a budget holding
        ~32 staged worlds: the census honors the line, and demoted
        tenants' solves stay bit-identical to the unbudgeted path."""
        node, params = _world(8, seed=1)
        pods = _pods(2, seed=2)
        probe = NodeStateCache(tenant="probe")
        solve_from_request(_full_request(node, params, pods, 0),
                           node_cache=probe)
        world_bytes = probe.device_bytes()
        assert world_bytes > 0
        probe.close()
        WORKING_SET.set_budget(32 * world_bytes)
        caches = {}
        for t in range(256):
            tnode, _ = _world(8, seed=100 + t)
            caches[t] = NodeStateCache(tenant=f"t{t}")
            resp = solve_from_request(_full_request(tnode, params, pods, 0),
                                      node_cache=caches[t])
            assert not resp.error
        st = WORKING_SET.status()
        census = st["residents"]
        assert census[RUNG_DEVICE] <= 32
        assert census[RUNG_DEVICE] + census[RUNG_HOST] \
            + census[RUNG_COLD] == 256
        assert st["used_bytes"] <= 32 * world_bytes
        assert st["demotions"].get("admission", 0) \
            + st["demotions"].get("budget", 0) >= 224
        # demoted tenants solve on: delta against a host-pinned base
        # restages and matches the full-solve of the patched world
        rng = np.random.default_rng(17)
        checked = 0
        for t in range(0, 256, 33):
            if caches[t].state is not None or caches[t].host is None:
                continue
            tnode, _ = _world(8, seed=100 + t)
            idx, rows = _patch(tnode, rng)
            got = solve_from_request(
                _delta_request(params, pods, idx, rows, 0, 1),
                node_cache=caches[t])
            want = solve_from_request(
                SolveRequest(node=tnode, params=params, pods=pods))
            _assert_same(got, want, f"tenant {t}")
            checked += 1
        assert checked >= 3
        for cache in caches.values():
            cache.close()

    def test_restage_zero_xla_recompiles(self, xla_compiles):
        """A warmed restage compiles nothing: the re-upload reuses the
        exact staged shapes, so the ladder costs transfer, not XLA."""
        node, params = _world(10, seed=21)
        cache = NodeStateCache(tenant="t")
        pods = _pods(3, seed=22)
        solve_from_request(_full_request(node, params, pods, 0),
                           node_cache=cache)
        rng = np.random.default_rng(23)
        idx, rows = _patch(node, rng)
        resp = solve_from_request(
            _delta_request(params, pods, idx, rows, 0, 1),
            node_cache=cache)
        assert not resp.error
        xla_compiles.clear()
        for r in range(2, 5):
            assert cache.demote_device()
            idx, rows = _patch(node, rng)
            resp = solve_from_request(
                _delta_request(params, pods, idx, rows, r - 1, r),
                node_cache=cache)
            assert not resp.error
        assert xla_compiles == []


# -- the in-process ladder: StagedStateCache --------------------------------

class TestStagedCacheLadder:
    def _snapshot(self, seed, n_nodes=12):
        from koordinator_tpu.apis.extension import (
            PriorityClass,
            ResourceName,
        )
        from koordinator_tpu.apis.types import (
            ClusterSnapshot,
            NodeMetric,
            NodeSpec,
            PodSpec,
        )
        from koordinator_tpu.state.cluster import ClusterDeltaTracker

        CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
        rng = np.random.default_rng(seed)
        nodes = [
            NodeSpec(name=f"n{i}",
                     allocatable={CPU: int(rng.integers(8000, 64000)),
                                  MEM: int(rng.integers(8192, 131072))})
            for i in range(n_nodes)
        ]
        pods = [
            PodSpec(name=f"p{j}", node_name=nodes[j % n_nodes].name,
                    requests={CPU: int(rng.integers(100, 4000)),
                              MEM: int(rng.integers(64, 4096))},
                    priority_class=(PriorityClass.PROD if rng.random() < 0.4
                                    else PriorityClass.NONE),
                    assign_time=float(rng.integers(0, 400)))
            for j in range(2 * n_nodes)
        ]
        metrics = {
            n.name: NodeMetric(
                node_name=n.name,
                node_usage={CPU: int(rng.integers(0, 32000)),
                            MEM: int(rng.integers(0, 65536))},
                update_time=350.0,
            )
            for n in nodes
        }
        tracker = ClusterDeltaTracker()
        return ClusterSnapshot(
            nodes=nodes, pods=pods, pending_pods=[],
            node_metrics=metrics, reservations=[], now=400.0,
            delta_tracker=tracker,
        ), tracker

    @staticmethod
    def _assert_state_equal(got, want, where=""):
        assert (got is None) == (want is None)
        for f in STAGED_NODE_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(got, f)), np.asarray(getattr(want, f)),
                err_msg=f"{where}: {f}")

    def test_staged_cache_every_rung_bit_identical(self):
        """The in-process staged cluster survives both demotion rungs
        with a bit-identical staged world: host-rung restage (device
        half re-established from kept host arrays) and cold-rung
        re-lower (full path from typed truth)."""
        from koordinator_tpu.models.placement import PlacementModel

        model = PlacementModel()
        cache = model.staged_cache
        twin = PlacementModel().staged_cache
        snap, tracker = self._snapshot(seed=31)
        _, state0, _, _ = cache.ensure(snap)
        _, want0, _, _ = twin.ensure(snap)
        self._assert_state_equal(state0, want0, "initial")
        # rung 1: device dropped, host kept — the delta path restages
        assert WORKING_SET.demote(cache._ws_key)
        tracker.mark_node(snap.nodes[0].name)
        snap.node_metrics[snap.nodes[0].name].node_usage[
            list(snap.node_metrics[snap.nodes[0].name].node_usage)[0]
        ] += 500
        _, state1, meta1, _ = cache.ensure(snap)
        _, want1, _, _ = twin.ensure(snap)
        self._assert_state_equal(state1, want1, "host-rung restage")
        assert WORKING_SET.status()["restages"].get("host", 0) >= 1
        # rung 2: host dropped too — re-lowered from typed truth
        assert WORKING_SET.demote(cache._ws_key, rung=RUNG_COLD,
                                  reason="alloc-failure")
        _, state2, meta2, _ = cache.ensure(snap)
        _, want2, _, _ = twin.ensure(snap)
        self._assert_state_equal(state2, want2, "cold-rung relower")
        assert cache.last_path == "full"
        assert WORKING_SET.status()["restages"].get("cold", 0) >= 1

    def test_staged_cache_epoch_monotone_across_cold(self):
        from koordinator_tpu.models.placement import PlacementModel

        cache = PlacementModel().staged_cache
        snap, _ = self._snapshot(seed=37)
        cache.ensure(snap)
        before = cache.epoch
        assert cache.demote_cold()
        cache.ensure(snap)
        assert cache.epoch > before


# -- the chaos property: churn under injected pressure ----------------------


class TestHBMChaos:
    """16 tenants churn deltas while HBMSaboteur injects every
    :data:`HBM_FAULT_KINDS` kind against a tight budget. The property:
    every landed placement and its node accounting is bit-identical to
    the fault-free control arm, every degradation is typed and counted
    within its label domain, and no tick crashes."""

    def _script(self, n_tenants=16, rounds=6, n_nodes=8):
        """Precompute every tenant's request material once; both arms
        replay exactly the same worlds, patches, and pods."""
        _, params = _world(n_nodes, seed=0)
        pods = _pods(3, seed=41)
        script = {}
        for t in range(n_tenants):
            node, _ = _world(n_nodes, seed=300 + t)
            rng = np.random.default_rng(7000 + t)
            base = {f: v.copy() for f, v in node.items()}
            steps = []
            for _r in range(rounds):
                idx, rows = _patch(node, rng)
                steps.append((idx, rows,
                              {f: v.copy() for f, v in node.items()}))
            script[t] = (base, steps)
        return params, pods, script

    def _run_arm(self, params, pods, script, rounds, saboteur=None):
        """One churn arm; returns {(tenant, round): response}. A typed
        cold-base error self-heals through the protocol's existing
        re-establish path — never an exception, never a dropped solve."""
        caches = {t: NodeStateCache(tenant=f"c{t}", lane="be")
                  for t in script}
        out = {}
        tick = 0
        for t, (base, _steps) in script.items():
            resp = solve_from_request(_full_request(base, params, pods, 0),
                                      node_cache=caches[t])
            assert not resp.error, f"tenant {t} establish: {resp.error}"
            out[(t, 0)] = resp
        for r in range(1, rounds + 1):
            for t, (_base, steps) in script.items():
                if saboteur is not None:
                    saboteur.inject(tick)
                tick += 1
                idx, rows, snap = steps[r - 1]
                resp = solve_from_request(
                    _delta_request(params, pods, idx, rows, r - 1, r),
                    node_cache=caches[t])
                if resp.error:
                    # the ONE sanctioned degradation: a cold base
                    # answers typed, and re-establishing the patched
                    # world lands the solve the delta would have
                    assert resp.error.startswith("delta-base-mismatch"), \
                        f"tenant {t} round {r}: {resp.error}"
                    resp = solve_from_request(
                        _full_request(snap, params, pods, r),
                        node_cache=caches[t])
                    assert not resp.error, \
                        f"tenant {t} round {r} re-establish: {resp.error}"
                out[(t, r)] = resp
        for cache in caches.values():
            cache.close()
        return out

    def test_chaos_16_tenant_churn_all_fault_kinds_bit_identical(self):
        from koordinator_tpu.testing.chaos import (
            HBM_FAULT_KINDS,
            FaultSchedule,
            HBMSaboteur,
        )

        rounds = 6
        params, pods, script = self._script(rounds=rounds)
        # price one world so the budget line means "~6 of 16 resident"
        probe = NodeStateCache(tenant="probe")
        resp = solve_from_request(
            _full_request(script[0][0], params, pods, 0), node_cache=probe)
        assert not resp.error
        world_bytes = probe.device_bytes()
        assert world_bytes > 0
        probe.close()

        control = self._run_arm(params, pods, script, rounds)

        WORKING_SET.reset()
        WORKING_SET.set_budget(6 * world_bytes)
        schedule = FaultSchedule.generate(
            seed=29, n_requests=len(script) * rounds, rate=0.5,
            kinds=HBM_FAULT_KINDS)
        sab = HBMSaboteur(schedule)
        chaos = self._run_arm(params, pods, script, rounds, saboteur=sab)

        # pressure actually landed, across every fault kind
        assert set(sab.injected) == set(HBM_FAULT_KINDS), sab.injected
        assert sum(sab.injected.values()) >= 10
        # the load-bearing property: bit-identical placements AND node
        # accounting at every (tenant, round), at whatever rung each
        # solve happened to find its base
        assert set(chaos) == set(control)
        for key, want in control.items():
            _assert_same(chaos[key], want, f"tenant/round {key}")
        # every degradation typed + counted within its label domain
        st = WORKING_SET.status()
        assert set(st["demotions"]) <= {"admission", "budget",
                                        "alloc-failure"}
        assert set(st["restages"]) <= {"host", "cold"}
        assert set(st["alloc_failures"]) <= {"stage", "scatter"}
        assert sum(st["demotions"].values()) > 0
        assert sum(st["restages"].values()) > 0
        assert sum(st["alloc_failures"].values()) > 0
