"""Multi-chip sharding tests on the virtual 8-device CPU mesh: the sharded
solver must produce identical assignments to the single-device path."""

import numpy as np
import jax
import jax.numpy as jnp

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.apis.types import ClusterSnapshot, NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.parallel.mesh import (
    make_mesh,
    pad_node_arrays,
    shard_node_state,
    shard_solver,
)
from koordinator_tpu.state.cluster import lower_nodes, lower_pending_pods

RNG = np.random.default_rng(7)


def _snapshot(n_nodes, n_pods):
    nodes = [
        NodeSpec(
            name=f"n{i}",
            allocatable={
                ResourceName.CPU: int(RNG.choice([16000, 32000, 64000])),
                ResourceName.MEMORY: int(RNG.choice([32768, 65536, 131072])),
            },
        )
        for i in range(n_nodes)
    ]
    metrics = {
        f"n{i}": NodeMetric(
            node_name=f"n{i}",
            node_usage={
                ResourceName.CPU: int(RNG.integers(0, 8000)),
                ResourceName.MEMORY: int(RNG.integers(0, 16384)),
            },
            update_time=95.0,
        )
        for i in range(n_nodes)
    }
    pending = [
        PodSpec(
            name=f"p{i}",
            priority=int(RNG.choice([9500, 7500, 5500])),
            requests={
                ResourceName.CPU: int(RNG.choice([500, 1000, 2000])),
                ResourceName.MEMORY: int(RNG.choice([1024, 2048, 4096])),
            },
        )
        for i in range(n_pods)
    ]
    return ClusterSnapshot(nodes=nodes, pending_pods=pending, node_metrics=metrics, now=100.0)


def _stage(arrays):
    return NodeState(
        alloc=jnp.asarray(arrays.alloc),
        used_req=jnp.asarray(arrays.used_req),
        usage=jnp.asarray(arrays.usage),
        prod_usage=jnp.asarray(arrays.prod_usage),
        est_extra=jnp.asarray(arrays.est_extra),
        prod_base=jnp.asarray(arrays.prod_base),
        metric_fresh=jnp.asarray(arrays.metric_fresh),
        schedulable=jnp.asarray(arrays.schedulable),
    )


def test_virtual_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_solver_matches_unsharded():
    snap = _snapshot(50, 40)  # 50 nodes -> padded to 56 over 8 shards
    node_arrays = lower_nodes(snap)
    pod_arrays = lower_pending_pods(snap.pending_pods)

    mesh = make_mesh()
    padded = pad_node_arrays(node_arrays, mesh.devices.size)
    assert padded.alloc.shape[0] % 8 == 0

    pods = PodBatch.build(
        req=jnp.asarray(pod_arrays.req),
        est=jnp.asarray(pod_arrays.est),
        is_prod=jnp.asarray(pod_arrays.is_prod),
        is_daemonset=jnp.asarray(pod_arrays.is_daemonset),
    )
    params = ScoreParams(
        weights=jnp.asarray(
            np.array([1, 1] + [0] * (NUM_RESOURCES - 2), dtype=np.int32)
        ),
        thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )

    # unsharded reference
    _, want = schedule_batch(_stage(padded), pods, params, SolverConfig())

    # sharded
    state = shard_node_state(_stage(padded), mesh)
    solve = shard_solver(mesh)
    new_state, got = solve(state, pods, params)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # state stays sharded for the next solve
    assert not new_state.used_req.is_fully_replicated
    # pad nodes never chosen
    assert (np.asarray(got) < 50).all()


def test_sharded_solver_matches_unsharded_on_2d_mesh():
    """The same node-axis program on a ``nodes × pods`` 2-D mesh
    (node_shards=4, pod_shards=2): node arrays split over ``nodes``
    and replicate over ``pods`` — results stay bit-identical."""
    from koordinator_tpu.parallel.mesh import make_mesh2d

    snap = _snapshot(40, 24)
    node_arrays = lower_nodes(snap)
    pod_arrays = lower_pending_pods(snap.pending_pods)
    mesh = make_mesh2d(node_shards=4, pod_shards=2)
    padded = pad_node_arrays(node_arrays, 4)
    pods = PodBatch.build(
        req=jnp.asarray(pod_arrays.req),
        est=jnp.asarray(pod_arrays.est),
        is_prod=jnp.asarray(pod_arrays.is_prod),
        is_daemonset=jnp.asarray(pod_arrays.is_daemonset),
    )
    params = ScoreParams(
        weights=jnp.asarray(
            np.array([1, 1] + [0] * (NUM_RESOURCES - 2), dtype=np.int32)
        ),
        thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )
    _, want = schedule_batch(_stage(padded), pods, params, SolverConfig())
    state = shard_node_state(_stage(padded), mesh)
    _, got = shard_solver(mesh)(state, pods, params)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_dryrun_failure_protocol_json():
    """The driver's machine protocol: parse_dryrun_json finds the last
    dryrun object, and a classified failure maps to its typed exit
    code."""
    from __graft_entry__ import (
        DRYRUN_EXIT_CODES,
        DryrunFailure,
        parse_dryrun_json,
    )

    out = (
        'noise\n{"dryrun": {"ok": false, "reason": "stale"}}\n'
        'more\n{"dryrun": {"ok": true, "reason": null, "kernel_leg": '
        '"ok"}}\ndryrun ok\n'
    )
    info = parse_dryrun_json(out)
    assert info == {"ok": True, "reason": None, "kernel_leg": "ok"}
    assert parse_dryrun_json("nothing here") is None
    err = DryrunFailure("identity-diverged", "assign[3] differs")
    assert DRYRUN_EXIT_CODES[err.reason] == 11
    # every typed reason has a distinct nonzero code
    codes = list(DRYRUN_EXIT_CODES.values())
    assert len(set(codes)) == len(codes) and all(c != 0 for c in codes)


def test_padding_preserves_assignments():
    snap = _snapshot(13, 17)
    node_arrays = lower_nodes(snap)
    pod_arrays = lower_pending_pods(snap.pending_pods)
    params = ScoreParams(
        weights=jnp.asarray(np.array([1, 1] + [0] * (NUM_RESOURCES - 2), np.int32)),
        thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )
    pods = PodBatch.build(
        req=jnp.asarray(pod_arrays.req),
        est=jnp.asarray(pod_arrays.est),
        is_prod=jnp.asarray(pod_arrays.is_prod),
        is_daemonset=jnp.asarray(pod_arrays.is_daemonset),
    )
    _, want = schedule_batch(_stage(node_arrays), pods, params, SolverConfig())
    padded = pad_node_arrays(node_arrays, 8)
    _, got = schedule_batch(_stage(padded), pods, params, SolverConfig())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
