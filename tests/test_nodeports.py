"""NodePorts host-port conflict filtering.

Oracle: the upstream k8s NodePorts plugin the reference vendors
(k8s.io/kubernetes v1.24 pkg/scheduler/framework/plugins/nodeports) and
its hostport e2e scope (test/e2e/scheduling/). Covers both paths:
incremental framework chain and the batched validate loop.
"""

import pytest

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.scheduler.plugins.nodeports import pod_host_ports


def scheduler_with_nodes(*names, cpu=16000):
    s = Scheduler()
    for name in names:
        s.add_node(NodeSpec(name=name,
                            allocatable={R.CPU: cpu, R.MEMORY: 32768}))
        s.update_node_metric(NodeMetric(node_name=name, node_usage={},
                                        update_time=99.0))
    return s


class TestNormalization:
    def test_int_is_tcp(self):
        assert pod_host_ports(PodSpec(name="p", host_ports=[80])) == {"tcp:80"}

    def test_string_protocols(self):
        got = pod_host_ports(PodSpec(name="p", host_ports=["udp:53", "TCP:80"]))
        assert got == {"udp:53", "tcp:80"}

    def test_no_ports(self):
        assert pod_host_ports(PodSpec(name="p")) == frozenset()


class TestBatched:
    def test_conflict_routes_to_other_node(self):
        s = scheduler_with_nodes("n0", "n1")
        s.add_pod(PodSpec(name="a", host_ports=[8080],
                          requests={R.CPU: 1000}))
        out = s.schedule_pending(now=100.0)
        first = out["default/a"]
        assert first in ("n0", "n1")
        s.add_pod(PodSpec(name="b", host_ports=[8080],
                          requests={R.CPU: 1000}))
        out = s.schedule_pending(now=101.0)
        assert out["default/b"] is not None
        assert out["default/b"] != first

    def test_single_node_conflict_unschedulable(self):
        s = scheduler_with_nodes("n0")
        s.add_pod(PodSpec(name="a", host_ports=[80], requests={R.CPU: 100}))
        s.schedule_pending(now=100.0)
        s.add_pod(PodSpec(name="b", host_ports=[80], requests={R.CPU: 100}))
        out = s.schedule_pending(now=101.0)
        assert out["default/b"] is None

    def test_same_batch_conflict_spreads(self):
        """Two pending pods with the same port in ONE batch: the
        validate loop's holds force them onto different nodes."""
        s = scheduler_with_nodes("n0", "n1")
        s.add_pod(PodSpec(name="a", host_ports=[443], requests={R.CPU: 100}))
        s.add_pod(PodSpec(name="b", host_ports=[443], requests={R.CPU: 100}))
        out = s.schedule_pending(now=100.0)
        assert {out["default/a"], out["default/b"]} == {"n0", "n1"}

    def test_different_protocols_no_conflict(self):
        s = scheduler_with_nodes("n0")
        s.add_pod(PodSpec(name="a", host_ports=["tcp:53"],
                          requests={R.CPU: 100}))
        s.schedule_pending(now=100.0)
        s.add_pod(PodSpec(name="b", host_ports=["udp:53"],
                          requests={R.CPU: 100}))
        out = s.schedule_pending(now=101.0)
        assert out["default/b"] == "n0"

    def test_port_freed_on_delete(self):
        s = scheduler_with_nodes("n0")
        pod = PodSpec(name="a", host_ports=[9000], requests={R.CPU: 100})
        s.add_pod(pod)
        s.schedule_pending(now=100.0)
        s.remove_pod(s.cache.pods["default/a"])
        s.add_pod(PodSpec(name="b", host_ports=[9000],
                          requests={R.CPU: 100}))
        out = s.schedule_pending(now=101.0)
        assert out["default/b"] == "n0"


class TestIncremental:
    def test_incremental_cycle_respects_ports(self):
        s = scheduler_with_nodes("n0", "n1")
        s.batched_placement = False
        s.add_pod(PodSpec(name="a", host_ports=[8080],
                          requests={R.CPU: 1000}))
        out = s.schedule_pending(now=100.0)
        first = out["default/a"]
        s.add_pod(PodSpec(name="b", host_ports=[8080],
                          requests={R.CPU: 1000}))
        out = s.schedule_pending(now=101.0)
        assert out["default/b"] is not None and out["default/b"] != first

    def test_incremental_single_node_unschedulable(self):
        s = scheduler_with_nodes("n0")
        s.batched_placement = False
        s.add_pod(PodSpec(name="a", host_ports=[80], requests={R.CPU: 100}))
        s.schedule_pending(now=100.0)
        s.add_pod(PodSpec(name="b", host_ports=[80], requests={R.CPU: 100}))
        out = s.schedule_pending(now=101.0)
        assert out["default/b"] is None


def test_host_port_pod_with_unmanaged_device_stays_special():
    """A host-port pod whose device_requests hold only unmanaged vendor
    resources must keep its special flag (code-review regression: the
    device block used to clobber it)."""
    s = scheduler_with_nodes("n0")
    s.add_pod(PodSpec(name="a", host_ports=[80], requests={R.CPU: 100}))
    s.schedule_pending(now=100.0)
    s.add_pod(PodSpec(name="b", host_ports=[80], requests={R.CPU: 100},
                      device_requests={"vendor.example/foo": 1}))
    out = s.schedule_pending(now=101.0)
    assert out["default/b"] is None  # port conflict still enforced


def test_standalone_model_static_port_rows():
    """A bare PlacementModel (no fine manager) still filters host-port
    conflicts against assigned pods."""
    from koordinator_tpu.apis.types import ClusterSnapshot
    from koordinator_tpu.models.placement import PlacementModel

    nodes = [NodeSpec(name=f"n{i}", allocatable={R.CPU: 8000,
                                                 R.MEMORY: 16384})
             for i in range(2)]
    metrics = {n.name: NodeMetric(node_name=n.name, update_time=99.0)
               for n in nodes}
    assigned = PodSpec(name="a", host_ports=[8080], node_name="n0",
                       requests={R.CPU: 100})
    pending = PodSpec(name="b", host_ports=[8080], requests={R.CPU: 100})
    out = PlacementModel().schedule(ClusterSnapshot(
        nodes=nodes, pods=[assigned], pending_pods=[pending],
        node_metrics=metrics, now=100.0,
    ))
    assert out["default/b"] == "n1"


def test_unplaceable_claimant_does_not_starve_later_pod():
    """An all-conflicted first claimant must not claim its ports and
    starve a placeable later pod (code-review regression)."""
    from koordinator_tpu.apis.types import ClusterSnapshot
    from koordinator_tpu.models.placement import PlacementModel

    node = NodeSpec(name="n0", allocatable={R.CPU: 8000, R.MEMORY: 16384})
    metrics = {"n0": NodeMetric(node_name="n0", update_time=99.0)}
    holder = PodSpec(name="h", host_ports=[81], node_name="n0",
                     requests={R.CPU: 100})
    stuck = PodSpec(name="a", host_ports=[80, 81], requests={R.CPU: 100})
    free = PodSpec(name="b", host_ports=[80], requests={R.CPU: 100})
    out = PlacementModel().schedule(ClusterSnapshot(
        nodes=[node], pods=[holder], pending_pods=[stuck, free],
        node_metrics=metrics, now=100.0,
    ))
    assert out["default/a"] is None     # 81 genuinely conflicted
    assert out["default/b"] == "n0"     # 80 free: not starved


def test_standalone_model_defers_same_batch_port_claimants():
    """Without the validate loop the standalone model must never emit
    two same-port placements in one batch: the later claimant is
    deferred to the next round (code-review regression)."""
    from koordinator_tpu.apis.types import ClusterSnapshot
    from koordinator_tpu.models.placement import PlacementModel

    node = NodeSpec(name="n0", allocatable={R.CPU: 8000, R.MEMORY: 16384})
    metrics = {"n0": NodeMetric(node_name="n0", update_time=99.0)}
    a = PodSpec(name="a", host_ports=[80], requests={R.CPU: 100})
    b = PodSpec(name="b", host_ports=[80], requests={R.CPU: 100})
    model = PlacementModel()
    out = model.schedule(ClusterSnapshot(
        nodes=[node], pods=[], pending_pods=[a, b],
        node_metrics=metrics, now=100.0,
    ))
    placed = [uid for uid, nd in out.items() if nd is not None]
    assert placed == ["default/a"]      # b deferred, not conflicting
    # next round: a is assigned; b sees the port taken on n0
    a.node_name = out["default/a"]
    out = model.schedule(ClusterSnapshot(
        nodes=[node], pods=[a], pending_pods=[b],
        node_metrics=metrics, now=101.0,
    ))
    assert out["default/b"] is None     # single node: genuinely stuck


def test_selector_blocked_claimant_does_not_starve():
    """A pod unplaceable due to its node selector (ports all free) must
    not claim its ports (code-review regression: the claim check must
    use the FULL accumulated mask)."""
    from koordinator_tpu.apis.types import ClusterSnapshot
    from koordinator_tpu.models.placement import PlacementModel

    node = NodeSpec(name="n0", allocatable={R.CPU: 8000, R.MEMORY: 16384})
    metrics = {"n0": NodeMetric(node_name="n0", update_time=99.0)}
    blocked = PodSpec(name="a", host_ports=[80], requests={R.CPU: 100},
                      node_selector={"zone": "nowhere"})
    free = PodSpec(name="b", host_ports=[80], requests={R.CPU: 100})
    out = PlacementModel().schedule(ClusterSnapshot(
        nodes=[node], pods=[], pending_pods=[blocked, free],
        node_metrics=metrics, now=100.0,
    ))
    assert out["default/a"] is None
    assert out["default/b"] == "n0"
