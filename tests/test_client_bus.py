"""The in-process API-server bus (coverage item 2): components coordinate
only through watched objects, closing the reference's §3.2/§3.3 loop —
koordlet reports NodeMetric → manager computes batch overcommit and
patches Node → scheduler places a BE pod on the batch resources.
"""



from koordinator_tpu.apis.extension import QoSClass, ResourceName as R
from koordinator_tpu.apis.types import (
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
)
from koordinator_tpu.client import APIServer, Kind, wire_manager, wire_scheduler
from koordinator_tpu.client.bus import EventType
from koordinator_tpu.scheduler import Scheduler


class TestBus:
    def test_watch_replays_then_streams(self):
        bus = APIServer()
        bus.apply(Kind.NODE, "n0", NodeSpec(name="n0"))
        events = []
        bus.watch(Kind.NODE, lambda e, n, o: events.append((e, n)))
        assert events == [(EventType.ADDED, "n0")]
        bus.apply(Kind.NODE, "n0", NodeSpec(name="n0"))
        bus.apply(Kind.NODE, "n1", NodeSpec(name="n1"))
        bus.delete(Kind.NODE, "n0")
        assert events == [
            (EventType.ADDED, "n0"),
            (EventType.MODIFIED, "n0"),
            (EventType.ADDED, "n1"),
            (EventType.DELETED, "n0"),
        ]

    def test_get_list(self):
        bus = APIServer()
        bus.apply(Kind.QUOTA, "t", QuotaSpec(name="t"))
        assert bus.get(Kind.QUOTA, "t").name == "t"
        assert list(bus.list(Kind.QUOTA)) == ["t"]
        assert bus.get(Kind.QUOTA, "missing") is None


class TestWiredScheduler:
    def test_scheduler_follows_bus(self):
        bus = APIServer()
        s = Scheduler()
        wire_scheduler(bus, s)
        bus.apply(Kind.NODE, "n0", NodeSpec(
            name="n0", allocatable={R.CPU: 16000, R.MEMORY: 32768}))
        bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
            node_name="n0", node_usage={}, update_time=99.0))
        pod = PodSpec(name="p", requests={R.CPU: 1000})
        bus.apply(Kind.POD, "default/p", pod)
        out = s.schedule_pending(now=100.0)
        assert out["default/p"] == "n0"
        bus.delete(Kind.POD, "default/p")
        assert "default/p" not in s.cache.pods


def test_bindings_published_and_koordlet_wired():
    """Bindings flow THROUGH the bus (the reference Binds via the API
    server): a wired koordlet sees its node's pods appear via watch, and
    the manager-rendered NodeSLO reaches its informer."""
    from koordinator_tpu.client import wire_koordlet, wire_manager
    from koordinator_tpu.koordlet.statesinformer import StatesInformer
    from koordinator_tpu.manager.nodeslo import NodeSLOController

    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    informer = StatesInformer()
    loop = wire_koordlet(bus, informer, "n0")
    events = []
    bus.watch(Kind.POD, lambda e, n, o: events.append((e, n)))

    bus.apply(Kind.NODE, "n0", NodeSpec(
        name="n0", allocatable={R.CPU: 16000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
        node_name="n0", node_usage={}, update_time=99.0))
    bus.apply(Kind.POD, "default/p", PodSpec(name="p", qos=QoSClass.LS,
                                             requests={R.CPU: 1000}))
    assert informer.running_pods() == []      # pending: not on any node
    out = s.schedule_pending(now=100.0)
    assert out["default/p"] == "n0"
    # the bind was re-published as a MODIFIED event...
    assert (EventType.MODIFIED, "default/p") in events
    # ...and the koordlet informer now holds the pod as PodMeta
    metas = informer.running_pods()
    assert [m.uid for m in metas] == ["default/p"]
    assert metas[0].cpu_request_mcpu == 1000
    assert loop.pods()[0].node_name == "n0"

    # manager renders NodeSLO onto the bus; the informer receives it
    manager = wire_manager(bus, nodeslo=NodeSLOController())
    manager.reconcile(now=100.0)
    assert bus.get(Kind.NODE_SLO, "n0") is not None
    assert informer.get_node_slo() is bus.get(Kind.NODE_SLO, "n0")

    # eviction through the bus drops it from the informer too
    bus.delete(Kind.POD, "default/p")
    assert informer.running_pods() == []


def test_koordlet_reports_nrt_and_devices_over_bus():
    """The koordlet's NRT + Device reporters publish through the bus
    sinks; the scheduler's NUMA manager and device cache receive them
    through its watches."""
    from koordinator_tpu.client import wire_koordlet
    from koordinator_tpu.client.wiring import koordlet_report_sinks
    from koordinator_tpu.device.cache import DeviceEntry, DeviceType
    from koordinator_tpu.device.cache import DeviceResourceName as DR
    from koordinator_tpu.koordlet.statesinformer import (
        DeviceReporter,
        NodeTopologyReporter,
        StatesInformer,
    )
    from koordinator_tpu.koordlet.system.cgroup import SystemConfig
    from koordinator_tpu.koordlet.system.cpuinfo import ProcessorInfo

    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    informer = StatesInformer()
    topo_sink, dev_sink = koordlet_report_sinks(bus)

    class FakeDevices:
        def list_devices(self):
            return [DeviceEntry(minor=0, device_type=DeviceType.GPU,
                                resources={DR.GPU_CORE: 100})]

    cpu_infos = [ProcessorInfo(cpu_id=i, core_id=i % 2, socket_id=0,
                               node_id=0) for i in range(4)]
    loop = wire_koordlet(
        bus, informer, "n0",
        topology_reporter=NodeTopologyReporter(
            "n0", SystemConfig(), topo_sink, cpu_infos=cpu_infos),
        device_reporter=DeviceReporter("n0", FakeDevices(), dev_sink),
    )
    loop.topology_reporter.sync()
    loop.device_reporter.sync()
    # the CRs are on the bus and the scheduler consumed them
    assert bus.get(Kind.NODE_RESOURCE_TOPOLOGY, "n0") is not None
    assert bus.get(Kind.DEVICE, "n0")[0].minor == 0
    assert s.numa_manager.get_topology("n0").numa_node_resources
    assert s.device_cache.get("n0").device_infos


def test_waiting_gang_member_not_visible_to_koordlet():
    """A gang member held at the Permit barrier is assumed (node_name
    set) but NOT bound: a MODIFIED event on it must not make a wired
    koordlet run it (code-review regression)."""
    from koordinator_tpu.apis.types import GangMode, GangSpec
    from koordinator_tpu.client import wire_koordlet
    from koordinator_tpu.koordlet.statesinformer import StatesInformer

    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    informer = StatesInformer()
    wire_koordlet(bus, informer, "n0")
    bus.apply(Kind.NODE, "n0", NodeSpec(
        name="n0", allocatable={R.CPU: 16000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
        node_name="n0", node_usage={}, update_time=99.0))
    # a 2-member NonStrict gang with one member present: the placed
    # member waits at Permit holding its node
    bus.apply(Kind.GANG, "g", GangSpec(name="g", min_member=2,
                                       mode=GangMode.NON_STRICT))
    lone = PodSpec(name="m0", gang="g", requests={R.CPU: 1000})
    bus.apply(Kind.POD, "default/m0", lone)
    out = s.schedule_pending(now=100.0)
    assert out["default/m0"] is None and out.waiting["default/m0"] == "n0"
    assert s.cache.pods["default/m0"].waiting_permit
    # a stray MODIFIED event (e.g. a label refresh) must not leak the
    # held placement to the agent
    bus.apply(Kind.POD, "default/m0", s.cache.pods["default/m0"])
    assert informer.running_pods() == []

    # the second member arrives: the barrier opens, both publish, the
    # agent now runs both
    bus.apply(Kind.POD, "default/m1", PodSpec(
        name="m1", gang="g", requests={R.CPU: 1000}))
    out = s.schedule_pending(now=101.0)
    assert out["default/m0"] == "n0" and out["default/m1"] == "n0"
    assert not s.cache.pods["default/m0"].waiting_permit
    assert sorted(m.uid for m in informer.running_pods()) == [
        "default/m0", "default/m1"]


def test_full_colocation_loop_over_bus():
    """§3.2 + §3.3 + §3.1 end-to-end: NodeMetric report → manager batch
    overcommit PATCH → scheduler places a BE pod against batch-cpu."""
    bus = APIServer()
    scheduler = Scheduler()
    wire_scheduler(bus, scheduler)
    manager = wire_manager(bus)

    # the node joins with native resources only (no batch columns yet)
    node = NodeSpec(name="n0", allocatable={R.CPU: 32000, R.MEMORY: 65536})
    bus.apply(Kind.NODE, "n0", node)

    # a BE pod requesting batch-cpu cannot schedule yet
    be_pod = PodSpec(name="be", qos=QoSClass.BE, priority=5500,
                     requests={R.BATCH_CPU: 4000})
    bus.apply(Kind.POD, "default/be", be_pod)
    out = scheduler.schedule_pending(now=100.0)
    assert out["default/be"] is None

    # koordlet-side report lands on the bus: low prod usage -> large
    # reclaimable batch capacity
    bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
        node_name="n0",
        node_usage={R.CPU: 6000, R.MEMORY: 8192},
        sys_usage={R.CPU: 1000},
        update_time=100.0,
    ))

    # manager reconcile: computes kubernetes.io/batch-* and PATCHes the
    # node; the scheduler sees the new allocatable through its watch
    synced = manager.reconcile(now=110.0)
    assert synced == 1
    patched = bus.get(Kind.NODE, "n0")
    assert patched.allocatable.get(R.BATCH_CPU, 0) > 4000

    out = scheduler.schedule_pending(now=120.0)
    assert out["default/be"] == "n0"


def test_modified_pod_does_not_double_count_quota():
    """Informer MODIFIED events must not re-register quota requests
    (round-2 review fix)."""
    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    bus.apply(Kind.QUOTA, "t", QuotaSpec(name="t", min={R.CPU: 1000},
                                         max={R.CPU: 8000}))
    pod = PodSpec(name="p", quota="t", requests={R.CPU: 2000})
    bus.apply(Kind.POD, "default/p", pod)
    bus.apply(Kind.POD, "default/p", pod)      # status-ish refresh
    import dataclasses

    refreshed = dataclasses.replace(pod, labels={"x": "y"})
    bus.apply(Kind.POD, "default/p", refreshed)
    info = s.quota_manager.quotas["t"]
    assert info.request[int(R.CPU)] == 2000    # not 4000/6000
    bus.delete(Kind.POD, "default/p")
    assert s.quota_manager.quotas["t"].request[int(R.CPU)] == 0


def test_deletes_propagate_for_every_kind(tmp_path):
    from koordinator_tpu.device.cache import DeviceEntry, DeviceType
    from koordinator_tpu.device.cache import DeviceResourceName as DR
    from koordinator_tpu.apis.types import (
        GangSpec,
        ReservationSpec,
        ReservationState,
    )
    from koordinator_tpu.numa.hints import NUMATopologyPolicy
    from koordinator_tpu.numa.manager import TopologyOptions
    from koordinator_tpu.numa.topology import CPUTopology

    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    bus.apply(Kind.NODE, "n0", NodeSpec(name="n0", allocatable={R.CPU: 16000}))
    bus.apply(Kind.NODE_METRIC, "n0",
              NodeMetric(node_name="n0", update_time=1.0))
    bus.apply(Kind.QUOTA, "t", QuotaSpec(name="t", max={R.CPU: 100}))
    bus.apply(Kind.GANG, "g", GangSpec(name="g", min_member=2))
    bus.apply(Kind.RESERVATION, "r", ReservationSpec(
        name="r", node_name="n0", state=ReservationState.AVAILABLE))
    topo = CPUTopology.build(sockets=1, nodes_per_socket=1,
                             cores_per_node=2, threads_per_core=2)
    bus.apply(Kind.NODE_RESOURCE_TOPOLOGY, "n0", TopologyOptions(
        cpu_topology=topo, policy=NUMATopologyPolicy.NONE,
        numa_node_resources={0: {R.CPU: 4000}}))
    bus.apply(Kind.DEVICE, "n0", [DeviceEntry(
        minor=0, device_type=DeviceType.GPU, resources={DR.GPU_CORE: 100})])

    for kind, name in ((Kind.QUOTA, "t"), (Kind.GANG, "g"),
                       (Kind.RESERVATION, "r"), (Kind.NODE_METRIC, "n0"),
                       (Kind.NODE_RESOURCE_TOPOLOGY, "n0"),
                       (Kind.DEVICE, "n0")):
        bus.delete(kind, name)
    assert "t" not in s.cache.quotas and "t" not in s.quota_manager.quotas
    assert "g" not in s.cache.gangs and "g" not in s.gang_manager.gangs
    assert "r" not in s.cache.reservations
    assert "n0" not in s.cache.node_metrics
    assert not s.numa_manager.get_topology("n0").numa_node_resources
    assert not s.device_cache.get("n0").device_infos

    bus.delete(Kind.NODE, "n0")
    assert "n0" not in s.cache.nodes


def test_quota_delete_withdraws_parent_accounting():
    """Deleting a child quota must withdraw its propagated request from
    ancestors (round-2 review fix)."""
    bus = APIServer()
    s = Scheduler(cluster_total={R.CPU: 100000})
    wire_scheduler(bus, s)
    bus.apply(Kind.QUOTA, "parent", QuotaSpec(
        name="parent", is_parent=True, min={R.CPU: 10000}, max={R.CPU: 50000}))
    bus.apply(Kind.QUOTA, "child", QuotaSpec(
        name="child", parent="parent", min={R.CPU: 1000}, max={R.CPU: 50000}))
    pod = PodSpec(name="p", quota="child", requests={R.CPU: 2000})
    bus.apply(Kind.POD, "default/p", pod)
    assert s.quota_manager.quotas["parent"].child_request[int(R.CPU)] == 2000
    bus.delete(Kind.QUOTA, "child")
    assert "child" not in s.quota_manager.quotas
    assert s.quota_manager.quotas["parent"].child_request[int(R.CPU)] == 0


def test_assigned_pod_request_update_keeps_used_accounted():
    """A MODIFIED event changing an assigned pod's requests swaps the
    quota used in place instead of dropping it (round-2 review fix)."""
    import dataclasses

    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    bus.apply(Kind.NODE, "n0", NodeSpec(
        name="n0", allocatable={R.CPU: 16000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
        node_name="n0", node_usage={}, update_time=99.0))
    bus.apply(Kind.QUOTA, "t", QuotaSpec(name="t", min={R.CPU: 1000},
                                         max={R.CPU: 10000}))
    pod = PodSpec(name="p", quota="t", requests={R.CPU: 2000})
    bus.apply(Kind.POD, "default/p", pod)
    s.schedule_pending(now=100.0)
    live = s.cache.pods["default/p"]
    assert live.node_name == "n0"
    assert s.quota_manager.quotas["t"].used[int(R.CPU)] == 2000

    resized = dataclasses.replace(live, requests={R.CPU: 3000})
    bus.apply(Kind.POD, "default/p", resized)
    updated = s.cache.pods["default/p"]
    assert updated.node_name == "n0"            # placement preserved
    assert s.quota_manager.quotas["t"].used[int(R.CPU)] == 3000
    assert s.quota_manager.quotas["t"].request[int(R.CPU)] == 3000


def test_gang_delete_unwedges_group_cycle():
    """Deleting a gang clears its children's schedule-cycle attempts so
    sibling gangs in the group can proceed (round-2 review fix)."""
    from koordinator_tpu.apis.types import GangSpec

    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    bus.apply(Kind.NODE, "n0", NodeSpec(
        name="n0", allocatable={R.CPU: 16000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
        node_name="n0", node_usage={}, update_time=99.0))
    bus.apply(Kind.GANG, "g1", GangSpec(name="g1", min_member=2,
                                        gang_group=["g1", "g2"]))
    bus.apply(Kind.GANG, "g2", GangSpec(name="g2", min_member=2,
                                        gang_group=["g1", "g2"]))
    for g in ("g1", "g2"):
        for i in range(2):
            bus.apply(Kind.POD, f"default/{g}-{i}",
                      PodSpec(name=f"{g}-{i}", gang=g,
                              requests={R.CPU: 99000}))  # never fits
    # everyone attempts and fails; strict rejection invalidates the cycle
    for g in ("g1", "g2"):
        for i in range(2):
            s.schedule_one(f"default/{g}-{i}", now=100.0)

    # g2 (and its pods) go away; g1's pods shrink to schedulable size
    bus.delete(Kind.GANG, "g2")
    for i in range(2):
        bus.delete(Kind.POD, f"default/g2-{i}")
        bus.apply(Kind.POD, f"default/g1-{i}",
                  PodSpec(name=f"g1-{i}", gang="g1", requests={R.CPU: 1000}))
    # first round records the cycle attempts (rejections count, matching
    # the reference's deferred setChildScheduleCycle); the cycle then
    # re-opens and the second round places the gang
    [s.schedule_one(f"default/g1-{i}", now=101.0) for i in range(2)]
    outcomes = [s.schedule_one(f"default/g1-{i}", now=102.0) for i in range(2)]
    assert {o.status for o in outcomes} <= {"waiting", "bound"}
    assert outcomes[-1].status == "bound"  # barrier opened


def test_descheduler_loop_migrates_over_bus():
    """§3.4 over the bus: an overloaded node's pod gets a MigrationJob,
    a reservation placed by the batched solver, and flows back through
    the bus into the scheduler's queue — then lands on the idle node."""
    from koordinator_tpu.apis.extension import ResourceName as R
    from koordinator_tpu.client.wiring import wire_descheduler
    from koordinator_tpu.descheduler.framework import (
        Descheduler,
        MigrationEvictor,
        Profile,
    )
    from koordinator_tpu.descheduler.loadaware import (
        LowNodeLoad,
        LowNodeLoadArgs,
        NodePool,
    )

    bus = APIServer()
    scheduler = Scheduler()
    wire_scheduler(bus, scheduler)
    # hot: 90% cpu usage; cold: idle
    bus.apply(Kind.NODE, "hot", NodeSpec(
        name="hot", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE, "cold", NodeSpec(
        name="cold", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE_METRIC, "hot", NodeMetric(
        node_name="hot", node_usage={R.CPU: 9000}, update_time=100.0))
    bus.apply(Kind.NODE_METRIC, "cold", NodeMetric(
        node_name="cold", node_usage={R.CPU: 200}, update_time=100.0))
    victim = PodSpec(name="heavy", requests={R.CPU: 4000}, node_name="hot")
    bus.apply(Kind.POD, "default/heavy", victim)

    plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={R.CPU: 30}, high_thresholds={R.CPU: 70},
    )]))
    loop = wire_descheduler(
        bus,
        Descheduler(profiles=[Profile(name="d", balance_plugins=[plugin])],
                    evictor=MigrationEvictor()),
    )
    migrated = loop.run_once(now=110.0)
    assert migrated == ["default/heavy"]
    # the job + its reservation are on the bus
    jobs = bus.list(Kind.MIGRATION_JOB)
    assert len(jobs) == 1
    resvs = bus.list(Kind.RESERVATION)
    assert len(resvs) == 1
    resv = next(iter(resvs.values()))
    assert resv.node_name == "cold"   # solver chose the idle node
    # the evicted pod is pending in the scheduler; next round binds it
    # on the reserved cold node
    out = scheduler.schedule_pending(now=120.0)
    assert out["default/heavy"] == "cold"


def test_migration_releases_assigned_state_and_prunes():
    """Migrating a scheduler-ASSUMED pod releases its quota used via the
    bus delete, completed jobs leave the dedup window, and no stale
    reservations resurrect (round-2 review fixes)."""
    from koordinator_tpu.client.wiring import wire_descheduler
    from koordinator_tpu.descheduler.framework import (
        Descheduler,
        DirectEvictor,
        MigrationEvictor,
        Profile,
    )
    from koordinator_tpu.descheduler.loadaware import (
        LowNodeLoad,
        LowNodeLoadArgs,
        NodePool,
    )
    import pytest

    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    bus.apply(Kind.NODE, "hot", NodeSpec(
        name="hot", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE, "cold", NodeSpec(
        name="cold", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    # hot looks fine at schedule time; cold starts unschedulable so the
    # pod lands on hot
    bus.apply(Kind.NODE_METRIC, "hot", NodeMetric(
        node_name="hot", node_usage={R.CPU: 1000}, update_time=100.0))
    bus.apply(Kind.NODE_METRIC, "cold", NodeMetric(
        node_name="cold", node_usage={R.CPU: 100}, update_time=100.0))
    bus.apply(Kind.QUOTA, "t", QuotaSpec(name="t", min={R.CPU: 1000},
                                         max={R.CPU: 9000}))
    bus.apply(Kind.NODE, "cold", NodeSpec(
        name="cold", allocatable={R.CPU: 10000, R.MEMORY: 32768},
        unschedulable=True))
    bus.apply(Kind.POD, "default/heavy", PodSpec(
        name="heavy", quota="t", requests={R.CPU: 4000}))
    out0 = s.schedule_pending(now=100.0)
    assert out0["default/heavy"] == "hot"
    assert s.quota_manager.quotas["t"].used[int(R.CPU)] == 4000
    # hot then runs hot; cold reopens before the descheduling cycle
    bus.apply(Kind.NODE_METRIC, "hot", NodeMetric(
        node_name="hot", node_usage={R.CPU: 9000}, update_time=105.0))
    bus.apply(Kind.NODE, "cold", NodeSpec(
        name="cold", allocatable={R.CPU: 10000, R.MEMORY: 32768}))

    plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={R.CPU: 30}, high_thresholds={R.CPU: 70})]))
    loop = wire_descheduler(bus, Descheduler(
        profiles=[Profile(name="d", balance_plugins=[plugin])],
        evictor=MigrationEvictor()))
    loop.run_once(now=110.0)
    # the delete released the quota used exactly once; the re-apply
    # re-registered the request (pending)
    assert s.quota_manager.quotas["t"].used[int(R.CPU)] == 0
    assert "default/heavy" in s.cache.pending
    # completed jobs pruned from the evictor's dedup window
    assert loop.descheduler.evictor.jobs == []

    # direct evictors are rejected outright
    with pytest.raises(TypeError):
        wire_descheduler(bus, Descheduler(profiles=[], evictor=DirectEvictor()))


def test_preemption_eviction_propagates_to_bus():
    """ADVICE round-2 fix: a preemption victim must be deleted from the
    bus (the reference deletes via the API server), not just the local
    cache — otherwise koordlet/manager keep treating it as running and a
    later MODIFIED event double-books the node."""
    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    bus.apply(Kind.NODE, "n0", NodeSpec(
        name="n0", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
        node_name="n0", node_usage={}, update_time=99.0))
    bus.apply(Kind.QUOTA, "a", QuotaSpec(
        name="a", min={R.CPU: 10000}, max={R.CPU: 10000}))
    victim = PodSpec(name="low", quota="a", priority=10,
                     requests={R.CPU: 8000})
    bus.apply(Kind.POD, "default/low", victim)
    out = s.schedule_pending(now=100.0)
    assert out["default/low"] == "n0"

    preemptor = PodSpec(name="high", quota="a", priority=100,
                        requests={R.CPU: 4000})
    bus.apply(Kind.POD, "default/high", preemptor)
    result = s.schedule_pending(now=101.0)
    assert result.nominations == {"default/high": "n0"}
    # the victim is gone from the BUS, not just the scheduler cache
    assert bus.get(Kind.POD, "default/low") is None
    assert "default/low" not in s.cache.pods
    # the preemptor binds next round on the freed capacity
    out = s.schedule_pending(now=102.0)
    assert out["default/high"] == "n0"


def test_migration_probe_does_not_consume_reservations():
    """ADVICE round-2 fix: the descheduler's reservation-placement probe
    carries the victim's labels; it must not consume label-owned
    reservations (the reference skips reservation matching for reserve
    pods — reservationutil.IsReservePod)."""
    from koordinator_tpu.apis.types import ReservationSpec, ReservationState
    from koordinator_tpu.client.wiring import wire_descheduler
    from koordinator_tpu.descheduler.framework import (
        Descheduler,
        MigrationEvictor,
        Profile,
    )
    from koordinator_tpu.descheduler.loadaware import (
        LowNodeLoad,
        LowNodeLoadArgs,
        NodePool,
    )

    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    bus.apply(Kind.NODE, "hot", NodeSpec(
        name="hot", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE, "cold", NodeSpec(
        name="cold", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE_METRIC, "hot", NodeMetric(
        node_name="hot", node_usage={R.CPU: 9000}, update_time=100.0))
    bus.apply(Kind.NODE_METRIC, "cold", NodeMetric(
        node_name="cold", node_usage={R.CPU: 200}, update_time=100.0))
    victim = PodSpec(name="heavy", requests={R.CPU: 4000}, node_name="hot",
                     labels={"app": "web"})
    bus.apply(Kind.POD, "default/heavy", victim)
    # a pre-existing allocate_once reservation owned by the SAME labels:
    # the probe must not burn it
    bus.apply(Kind.RESERVATION, "standing", ReservationSpec(
        name="standing", node_name="cold", state=ReservationState.AVAILABLE,
        allocatable={R.CPU: 4000}, owner_labels={"app": "web"},
        allocate_once=True))

    plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={R.CPU: 30}, high_thresholds={R.CPU: 70})]))
    loop = wire_descheduler(bus, Descheduler(
        profiles=[Profile(name="d", balance_plugins=[plugin])],
        evictor=MigrationEvictor()))
    migrated = loop.run_once(now=110.0)
    assert migrated == ["default/heavy"]
    standing = bus.get(Kind.RESERVATION, "standing")
    assert standing.state == ReservationState.AVAILABLE
    assert not standing.allocated
    assert not any(u.startswith("__resv__")
                   for u in standing.allocated_pod_uids)


def test_migration_probe_sees_reserved_capacity_as_occupied():
    """Review fix follow-up: the probe skips reservation MATCHING but must
    still see existing reservations' capacity holds — otherwise two
    migrations double-book one free node."""
    from koordinator_tpu.apis.types import ReservationSpec, ReservationState
    from koordinator_tpu.client.wiring import wire_descheduler
    from koordinator_tpu.descheduler.framework import (
        Descheduler,
        MigrationEvictor,
        Profile,
    )
    from koordinator_tpu.descheduler.loadaware import (
        LowNodeLoad,
        LowNodeLoadArgs,
        NodePool,
    )

    bus = APIServer()
    s = Scheduler()
    wire_scheduler(bus, s)
    bus.apply(Kind.NODE, "hot", NodeSpec(
        name="hot", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE, "cold", NodeSpec(
        name="cold", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE_METRIC, "hot", NodeMetric(
        node_name="hot", node_usage={R.CPU: 9000}, update_time=100.0))
    bus.apply(Kind.NODE_METRIC, "cold", NodeMetric(
        node_name="cold", node_usage={R.CPU: 200}, update_time=100.0))
    bus.apply(Kind.POD, "default/heavy", PodSpec(
        name="heavy", requests={R.CPU: 4000}, node_name="hot"))
    # an unrelated reservation already holds 7000 of cold's 10000: the
    # victim's 4000 probe cannot fit there any more
    bus.apply(Kind.RESERVATION, "taken", ReservationSpec(
        name="taken", node_name="cold", state=ReservationState.AVAILABLE,
        allocatable={R.CPU: 7000}, owner_labels={"app": "other"},
        allocate_once=True))

    plugin = LowNodeLoad(LowNodeLoadArgs(node_pools=[NodePool(
        low_thresholds={R.CPU: 30}, high_thresholds={R.CPU: 70})]))
    loop = wire_descheduler(bus, Descheduler(
        profiles=[Profile(name="d", balance_plugins=[plugin])],
        evictor=MigrationEvictor()))
    migrated = loop.run_once(now=110.0)
    # no node can host the victim: nothing migrates, no new reservation
    assert migrated == []
    assert list(bus.list(Kind.RESERVATION)) == ["taken"]
    assert bus.get(Kind.POD, "default/heavy").node_name == "hot"


class TestNodeReservationTransform:
    """Scheduler-side informer transform (node_transformer.go
    TransformNodeWithNodeReservation): node-reservation trims the
    scheduler's allocatable view; other bus watchers keep the raw node."""

    def _node(self, policy=None, nested=True, cpu=4000):
        import json as _json

        from koordinator_tpu.apis.extension import (
            ANNOTATION_NODE_RESERVATION,
        )

        spec = {"resources": {"cpu": cpu}} if nested else {"cpu": cpu}
        if policy is not None:
            spec["applyPolicy"] = policy
        return NodeSpec(
            name="n0",
            allocatable={R.CPU: 10000, R.MEMORY: 32768},
            annotations={
                ANNOTATION_NODE_RESERVATION: _json.dumps(spec)
            },
        )

    def test_scheduler_sees_trimmed_allocatable(self):
        from koordinator_tpu.scheduler import Scheduler

        bus = APIServer()
        sched = Scheduler()
        wire_scheduler(bus, sched)
        bus.apply(Kind.NODE, "n0", self._node())
        bus.apply(Kind.NODE_METRIC, "n0", NodeMetric(
            node_name="n0", node_usage={}, update_time=99.0))
        # 7000m fits raw 10000m but not the trimmed 6000m
        bus.apply(Kind.POD, "default/p", PodSpec(
            name="p", requests={R.CPU: 7000}))
        out = sched.schedule_pending(now=100.0)
        assert out["default/p"] is None
        # the bus object itself stays untrimmed (shared raw view)
        assert bus.get(Kind.NODE, "n0").allocatable[R.CPU] == 10000
        # a fitting pod still places
        bus.apply(Kind.POD, "default/q", PodSpec(
            name="q", requests={R.CPU: 5000}))
        assert sched.schedule_pending(now=101.0)["default/q"] == "n0"

    def test_reserved_cpus_only_policy_not_trimmed(self):
        from koordinator_tpu.client.wiring import transform_node

        node = transform_node(self._node(policy="ReservedCPUsOnly"))
        assert node.allocatable[R.CPU] == 10000

    def test_flat_form_and_malformed_tolerated(self):
        import json as _json

        from koordinator_tpu.apis.extension import (
            ANNOTATION_NODE_RESERVATION,
        )
        from koordinator_tpu.client.wiring import transform_node

        assert transform_node(
            self._node(nested=False)
        ).allocatable[R.CPU] == 6000
        broken = NodeSpec(
            name="n0", allocatable={R.CPU: 10000},
            annotations={ANNOTATION_NODE_RESERVATION: "{not json"},
        )
        assert transform_node(broken).allocatable[R.CPU] == 10000
        oversub = transform_node(self._node(cpu=999999))
        assert oversub.allocatable[R.CPU] == 0  # non-negative clamp
