"""Concurrency stress: the bus + leader election under thread contention.

SURVEY §5.2: the reference's concurrency assurance is ``go test -race``
over lock-based structures. The analogue here: hammer the shared
structures from real threads and assert the invariants that locks exist
to protect — serialized transactions, exactly-one-leader, and a
consistent store under concurrent apply/delete/watch.
"""

import threading

import numpy as np

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import NodeSpec, PodSpec
from koordinator_tpu.client import APIServer, Kind
from koordinator_tpu.client.leaderelection import FencingError, LeaderElector


class TestBusUnderContention:
    def test_transactions_serialize(self):
        """N threads increment a counter object through transact: every
        increment must survive (lost updates = broken store lock)."""
        bus = APIServer()
        bus.apply(Kind.NODE, "counter", {"n": 0})
        threads, per = 8, 200

        def worker():
            for _ in range(per):
                def txn():
                    cur = bus.get(Kind.NODE, "counter")
                    bus.apply(Kind.NODE, "counter", {"n": cur["n"] + 1})
                bus.transact(txn)

        ts = [threading.Thread(target=worker) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert bus.get(Kind.NODE, "counter")["n"] == threads * per

    def test_concurrent_apply_delete_watch_consistent(self):
        """Interleaved applies/deletes with a watcher mirroring state:
        after the dust settles the mirror equals the store."""
        bus = APIServer()
        mirror = {}
        mlock = threading.Lock()

        def on_pod(event, name, pod):
            with mlock:
                if event.value == "DELETED":
                    mirror.pop(name, None)
                else:
                    mirror[name] = pod

        bus.watch(Kind.POD, on_pod)
        rng = np.random.default_rng(0)
        ops = []
        for i in range(400):
            ops.append(("apply", f"p{i % 50}"))
            if rng.random() < 0.3:
                ops.append(("delete", f"p{int(rng.integers(0, 50))}"))
        chunks = [ops[i::4] for i in range(4)]

        def worker(chunk):
            for op, name in chunk:
                if op == "apply":
                    bus.apply(Kind.POD, name, PodSpec(name=name))
                else:
                    bus.delete(Kind.POD, name)

        ts = [threading.Thread(target=worker, args=(c,)) for c in chunks]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        store = bus.list(Kind.POD)
        with mlock:
            assert set(mirror) == set(store)


class TestDeltaTrackerUnderContention:
    def test_concurrent_marks_never_lost_and_epochs_monotone(self):
        """Regression net for the PR-6 snapshot-epoch race fix:
        concurrent ``mark_node`` calls racing snapshot-style epoch
        captures. Invariants: a mark is visible to ``dirty_since(e)``
        for ANY epoch e captured before the mark (no lost dirty rows),
        every mark gets a distinct epoch, and each thread's own marks
        carry strictly increasing epochs (the unlocked ``epoch += 1``
        this guards against would let two racing marks share one)."""
        from concurrent.futures import ThreadPoolExecutor

        from koordinator_tpu.state.cluster import ClusterDeltaTracker

        tracker = ClusterDeltaTracker()
        e0 = tracker.epoch
        threads, per = 8, 150

        def worker(i):
            observed, names = [], []
            for k in range(per):
                name = f"t{i}-m{k}"
                before = tracker.epoch  # a consumer's sync-point capture
                tracker.mark_node(name)
                # the mark must land at an epoch AFTER any previously
                # captured sync point — a consumer synced at `before`
                # can never lose it
                assert name in tracker.dirty_since(before), name
                # only this thread ever writes this key; reading it
                # races nothing
                observed.append(tracker._marks[name])
                names.append(name)
            return observed, names

        with ThreadPoolExecutor(max_workers=threads) as ex:
            results = list(ex.map(worker, range(threads)))
        all_epochs = [e for obs, _ in results for e in obs]
        assert len(set(all_epochs)) == len(all_epochs), (
            "two marks shared an epoch"
        )
        for obs, _ in results:
            assert obs == sorted(obs), "a thread saw non-monotone epochs"
        marked = {n for _, names in results for n in names}
        assert set(tracker.dirty_since(e0)) == marked, "lost dirty rows"
        assert tracker.epoch == e0 + threads * per


class TestAdmissionGateUnderContention:
    def test_every_submit_answered_and_accounted(self):
        """8 threads hammer the bounded gate with mixed lanes and some
        already-expired deadlines: EVERY submit must come back with a
        real response or a typed error (never silence / a hang), and
        the gate's books must balance — dispatched + shed == submitted,
        queues empty, all frames marked delivered."""
        from concurrent.futures import ThreadPoolExecutor

        from koordinator_tpu.service.admission import (
            AdmissionConfig,
            AdmissionGate,
        )
        from koordinator_tpu.service.codec import SolveRequest, SolveResponse

        def stub(request, config, node_cache):
            n = int(np.asarray(request.pods["req"]).shape[0])
            return SolveResponse(assignments=np.zeros(n, np.int32))

        gate = AdmissionGate(
            stub, AdmissionConfig(capacity=16, max_coalesce=1)
        )
        n_threads, per = 8, 40

        def worker(i):
            rng = np.random.default_rng(i)
            outcomes = []
            for k in range(per):
                adm = {"lane": np.asarray(int(rng.integers(0, 3)), np.int64)}
                if k % 7 == 0:
                    adm["deadline_s"] = np.asarray(0.0, np.float64)
                req = SolveRequest(
                    node={"x": np.asarray([i, k])},
                    pods={"req": np.zeros((2, 4), np.int32)},
                    params={},
                    admission=adm,
                )
                entry = gate.submit(req, None)
                resp = entry.wait(timeout=30)
                entry.delivered()
                assert resp is not None, "a submit was never answered"
                outcomes.append(resp.error)
            return outcomes

        try:
            with ThreadPoolExecutor(max_workers=n_threads) as ex:
                results = list(ex.map(worker, range(n_threads)))
            errors = [e for out in results for e in out]
            assert len(errors) == n_threads * per
            allowed = ("", "overloaded", "deadline-exceeded")
            assert all(e.startswith(allowed) for e in errors)
            # every submit is accounted exactly once: dispatched or shed
            st = gate.stats()
            shed = st["shed"]
            assert (
                st["requests_total"]
                + shed["overloaded"]
                + shed["deadline-exceeded"]
                + shed["shutting-down"]
            ) == n_threads * per
            assert all(d == 0 for d in st["queue_depth"].values())
            assert gate.wait_delivered(timeout=2.0)
        finally:
            gate.shutdown(timeout=2)


class TestElectionUnderContention:
    def test_fenced_writes_serialize_across_leaders(self):
        """16 electors ticking concurrently across expiring leases.
        ``is_leader`` is advisory (a deposed leader may believe until its
        next tick — the client-go zombie window); the HARD invariant is
        fencing: successful fenced writes carry non-decreasing tokens and
        each token belongs to exactly one identity — a zombie's write
        raises instead of interleaving with the new leader's."""
        bus = APIServer()
        electors = [
            LeaderElector(bus, "lease", f"id{i}", lease_duration=0.5,
                          renew_deadline=0.4, retry_period=0.05)
            for i in range(16)
        ]
        stop = threading.Event()
        log = []  # (token, identity) for every SUCCESSFUL fenced write
        zombies_fenced = [0]
        now_lock = threading.Lock()
        clock = [0.0]

        def tick_loop(elector):
            while not stop.is_set():
                with now_lock:
                    clock[0] += 0.01
                    now = clock[0]
                if elector.tick(now):
                    token = elector.token
                    try:
                        elector.fenced(
                            lambda: log.append((token, elector.identity))
                        )
                    except FencingError:
                        zombies_fenced[0] += 1

        ts = [threading.Thread(target=tick_loop, args=(e,)) for e in electors]
        for t in ts:
            t.start()
        import time as _time

        _time.sleep(1.0)
        stop.set()
        for t in ts:
            t.join()
        assert log, "no leader ever wrote"
        # tokens non-decreasing in wall order (writes serialized by the
        # store lock) and single-owner per token
        tokens = [t for t, _ in log]
        assert tokens == sorted(tokens), "a stale token wrote after a newer one"
        owner = {}
        for token, identity in log:
            assert owner.setdefault(token, identity) == identity, (
                f"token {token} written by {identity} and {owner[token]}"
            )
