"""koord-runtime-proxy: CRI interposition (VERDICT missing item 8).

Reference: pkg/runtimeproxy/server/cri/criserver.go (intercept + transparent
pass-through + failOver), config.go failure policy, store/.
"""

import pytest

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.runtimehooks.hooks import (
    FailurePolicy,
    HookRegistry,
    Stage,
)
from koordinator_tpu.koordlet.runtimehooks.server import RuntimeHookServer
from koordinator_tpu.runtimeproxy import (
    CRIRequest,
    RuntimeManagerCriServer,
)


class RecordingBackend:
    """Fake containerd: records forwarded requests."""

    def __init__(self, pods=()):
        self.requests = []
        self._pods = list(pods)

    def handle(self, request):
        self.requests.append(request)
        return {"ok": True, "method": request.method}

    def list_pods(self):
        return self._pods


def be_pod(uid="be1"):
    return PodMeta(
        uid=uid, cgroup_dir=f"kubepods/besteffort/pod{uid}",
        qos=QoSClass.BE,
        containers={"c0": f"kubepods/besteffort/pod{uid}/c0"},
    )


def hook_server(registry=None, policy=FailurePolicy.IGNORE):
    return RuntimeHookServer(registry or HookRegistry(), fail_policy=policy)


class TestIntercept:
    def test_hooked_method_runs_hooks_and_forwards(self):
        registry = HookRegistry()
        seen = []

        def set_shares(ctx):
            seen.append(ctx.request.pod_meta.uid)
            ctx.response.cpu_shares = 2

        registry.register(Stage.PRE_CREATE_CONTAINER, "set-shares", "", set_shares)
        backend = RecordingBackend()
        proxy = RuntimeManagerCriServer(hook_server(registry), backend)
        req = CRIRequest(method="CreateContainer", pod=be_pod(), container="c0")
        out = proxy.intercept(req)
        assert seen == ["be1"]
        # hook response merged into the forwarded request
        assert backend.requests[0].resources.cpu_shares == 2
        assert out.backend_response["ok"]

    def test_unknown_method_transparent(self):
        backend = RecordingBackend()
        proxy = RuntimeManagerCriServer(hook_server(), backend)
        req = CRIRequest(method="ListImages")
        out = proxy.intercept(req)
        assert backend.requests == [req]
        assert out.hook_response is None

    def test_store_tracks_sandboxes(self):
        backend = RecordingBackend()
        proxy = RuntimeManagerCriServer(hook_server(), backend)
        pod = be_pod()
        proxy.intercept(CRIRequest(method="RunPodSandbox", pod=pod))
        assert proxy.store.pod("be1") is pod
        # a later call can resolve the pod from the store by uid
        req = CRIRequest(method="UpdateContainerResources",
                         container="c0", payload={"pod_uid": "be1"})
        proxy.intercept(req)
        assert backend.requests[-1] is req
        proxy.intercept(CRIRequest(method="StopPodSandbox", pod=pod))
        assert proxy.store.pod("be1") is None

    def test_failure_policy_ignore_forwards_unmodified(self):
        registry = HookRegistry()

        def boom(ctx):
            raise RuntimeError("hook down")

        registry.register(Stage.PRE_CREATE_CONTAINER, "boom", "", boom)
        backend = RecordingBackend()
        proxy = RuntimeManagerCriServer(
            hook_server(registry),
            backend,
            failure_policy=FailurePolicy.IGNORE,
        )
        req = CRIRequest(method="CreateContainer", pod=be_pod(), container="c0")
        out = proxy.intercept(req)
        assert out.backend_response["ok"]          # still forwarded
        assert out.hook_response is None
        assert backend.requests[0].resources.cpu_shares is None

    def test_failure_policy_fail_raises(self):
        """The PROXY's Fail policy governs even when the hook server was
        built with its default Ignore policy (review fix)."""
        registry = HookRegistry()

        def boom(ctx):
            raise RuntimeError("hook down")

        registry.register(Stage.PRE_CREATE_CONTAINER, "boom", "", boom)
        backend = RecordingBackend()
        proxy = RuntimeManagerCriServer(
            hook_server(registry),  # default IGNORE server
            backend,
            failure_policy=FailurePolicy.FAIL,
        )
        with pytest.raises(RuntimeError):
            proxy.intercept(
                CRIRequest(method="CreateContainer", pod=be_pod(),
                           container="c0")
            )
        assert backend.requests == []  # the CRI call failed, not forwarded

    def test_post_stop_hooks_run_after_forward_and_never_block(self):
        """Stop calls forward FIRST; a failing post-stop hook can't keep
        the sandbox alive (review fix)."""
        registry = HookRegistry()
        order = []

        def post_stop(ctx):
            order.append("hook")
            raise RuntimeError("post-stop hook down")

        registry.register(Stage.POST_STOP_POD_SANDBOX, "ps", "", post_stop)
        backend = RecordingBackend()
        real_handle = backend.handle

        def handle(req):
            order.append("backend")
            return real_handle(req)

        backend.handle = handle
        proxy = RuntimeManagerCriServer(
            hook_server(registry), backend,
            failure_policy=FailurePolicy.FAIL,
        )
        pod = be_pod()
        proxy.store.record_pod(pod)
        out = proxy.intercept(CRIRequest(method="StopPodSandbox", pod=pod))
        assert order == ["backend", "hook"]
        assert out.backend_response["ok"]
        assert proxy.store.pod(pod.uid) is None

    def test_fail_over_rebuilds_store(self):
        pods = [be_pod("a"), be_pod("b")]
        backend = RecordingBackend(pods=pods)
        proxy = RuntimeManagerCriServer(hook_server(), backend)
        assert proxy.fail_over() == 2
        assert proxy.store.pod("a") is pods[0]
        assert proxy.store.pod("b") is pods[1]


def test_end_to_end_groupidentity_through_proxy(tmp_path):
    """The §3.5 flow: kubelet → proxy → hooks (bvt from NodeSLO) → merge
    into the CRI request."""
    from koordinator_tpu.koordlet.runtimehooks.groupidentity import (
        BvtPlugin as GroupIdentityPlugin,
    )
    from koordinator_tpu.manager.sloconfig import (
        CPUQOS,
        NodeSLOSpec,
        QoSConfig,
        ResourceQOSStrategy,
    )

    registry = HookRegistry()
    plugin = GroupIdentityPlugin()
    plugin.register(registry)
    plugin.update_rule(
        NodeSLOSpec(
            resource_qos_strategy=ResourceQOSStrategy(
                be=QoSConfig(enable=True, cpu=CPUQOS(group_identity=-1))
            )
        )
    )
    backend = RecordingBackend()
    proxy = RuntimeManagerCriServer(hook_server(registry), backend)
    req = CRIRequest(method="RunPodSandbox", pod=be_pod())
    out = proxy.intercept(req)
    assert out.hook_response is not None
    assert out.hook_response.cpu_bvt == -1  # BE group identity
    assert backend.requests[0].resources.cpu_bvt == -1


def test_post_start_hooks_dispatch_after_forward():
    """POST_START_CONTAINER hooks run after StartContainer forwards
    (review fix: the post side of the dispatch table)."""
    registry = HookRegistry()
    order = []
    registry.register(Stage.PRE_START_CONTAINER, "pre", "",
                      lambda ctx: order.append("pre"))
    registry.register(Stage.POST_START_CONTAINER, "post", "",
                      lambda ctx: order.append("post"))
    backend = RecordingBackend()
    real_handle = backend.handle
    backend.handle = lambda req: (order.append("backend"), real_handle(req))[1]
    proxy = RuntimeManagerCriServer(hook_server(registry), backend)
    proxy.intercept(CRIRequest(method="StartContainer", pod=be_pod(),
                               container="c0"))
    assert order == ["pre", "backend", "post"]
