"""Solver service boundary (VERDICT missing item 10 / SURVEY §5.8):
control plane ↔ solver over a framed binary protocol."""

import io
import threading

import numpy as np
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName as R
from koordinator_tpu.service import (
    PlacementClient,
    PlacementService,
    SolveRequest,
    decode_request,
    decode_response,
    encode_request,
    encode_response,
    read_frame,
    write_frame,
)
from koordinator_tpu.service.codec import SolveResponse
from koordinator_tpu.service.server import solve_from_request


def _problem(n_nodes=4, n_pods=6):
    rng = np.random.default_rng(0)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, R.CPU] = 16000
    alloc[:, R.MEMORY] = 32768
    node = {
        "alloc": alloc,
        "used_req": np.zeros_like(alloc),
        "usage": np.zeros_like(alloc),
        "prod_usage": np.zeros_like(alloc),
        "est_extra": np.zeros_like(alloc),
        "prod_base": np.zeros_like(alloc),
        "metric_fresh": np.ones(n_nodes, bool),
        "schedulable": np.ones(n_nodes, bool),
    }
    req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = rng.choice([1000, 2000], n_pods)
    pods = {
        "req": req,
        "est": (req * 85) // 100,
        "is_prod": np.zeros(n_pods, bool),
        "is_daemonset": np.zeros(n_pods, bool),
    }
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[R.CPU] = 1
    weights[R.MEMORY] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[R.CPU] = 65
    thresholds[R.MEMORY] = 95
    params = {
        "weights": weights,
        "thresholds": thresholds,
        "prod_thresholds": np.zeros(NUM_RESOURCES, np.int32),
    }
    return SolveRequest(node=node, pods=pods, params=params)


class TestCodec:
    def test_framing_roundtrip(self):
        buf = io.BytesIO()
        write_frame(buf, b"hello")
        write_frame(buf, b"world!")
        buf.seek(0)
        assert read_frame(buf) == b"hello"
        assert read_frame(buf) == b"world!"
        assert read_frame(buf) is None  # EOF

    def test_request_roundtrip(self):
        req = _problem()
        decoded = decode_request(encode_request(req))
        for group, dec in (
            (req.node, decoded.node),
            (req.pods, decoded.pods),
            (req.params, decoded.params),
        ):
            assert set(group) == set(dec)
            for key in group:
                np.testing.assert_array_equal(group[key], dec[key])

    def test_response_roundtrip(self):
        resp = SolveResponse(
            assignments=np.array([0, 1, -1], np.int32),
            node_used_req=np.ones((2, NUM_RESOURCES), np.int32),
            error="",
        )
        decoded = decode_response(encode_response(resp))
        np.testing.assert_array_equal(decoded.assignments, resp.assignments)
        np.testing.assert_array_equal(decoded.node_used_req, resp.node_used_req)
        err = decode_response(
            encode_response(SolveResponse(np.empty(0, np.int32), error="boom"))
        )
        assert err.error == "boom"


class TestSolveHandler:
    def test_matches_in_process_solve(self):
        import jax.numpy as jnp

        from koordinator_tpu.ops.binpack import (
            NodeState,
            PodBatch,
            ScoreParams,
            SolverConfig,
            schedule_batch,
        )

        req = _problem()
        wire = solve_from_request(req)
        state = NodeState(**{k: jnp.asarray(v) for k, v in req.node.items()})
        pods = PodBatch.build(
            req=jnp.asarray(req.pods["req"]),
            est=jnp.asarray(req.pods["est"]),
            is_prod=jnp.asarray(req.pods["is_prod"]),
            is_daemonset=jnp.asarray(req.pods["is_daemonset"]),
        )
        params = ScoreParams(**{k: jnp.asarray(v) for k, v in req.params.items()})
        _, want = schedule_batch(state, pods, params, SolverConfig())
        np.testing.assert_array_equal(wire.assignments, np.asarray(want))

    def test_malformed_request_returns_error(self):
        req = _problem()
        del req.node["alloc"]
        resp = solve_from_request(req)
        assert resp.error and "KeyError" in resp.error


class TestServiceEndToEnd:
    def test_uds_roundtrip(self, tmp_path):
        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        try:
            req = _problem()
            with PlacementClient(addr) as client:
                resp = client.solve(req)
                assert (resp.assignments >= 0).all()
                # the mutated accounting columns come back for the cache
                assert resp.node_used_req.sum() == req.pods["req"].sum()
                # second solve over the same connection (jit cache warm)
                resp2 = client.solve(req)
                np.testing.assert_array_equal(resp.assignments, resp2.assignments)
        finally:
            service.stop()

    def test_concurrent_clients(self, tmp_path):
        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        results = {}

        def worker(i):
            with PlacementClient(addr) as client:
                results[i] = client.solve(_problem()).assignments

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == 4
            for i in range(1, 4):
                np.testing.assert_array_equal(results[0], results[i])
        finally:
            service.stop()

    def test_server_error_surfaces_to_client(self, tmp_path):
        addr = str(tmp_path / "solver2.sock")
        service = PlacementService(addr)
        service.start()
        try:
            req = _problem()
            del req.params["weights"]
            with PlacementClient(addr) as client:
                with pytest.raises(RuntimeError, match="solver error"):
                    client.solve(req)
        finally:
            service.stop()


def test_malformed_payload_keeps_connection(tmp_path):
    """A garbage frame gets an error response, not a dropped connection
    (review fix: decode inside the error boundary)."""
    import socket

    from koordinator_tpu.service.codec import read_frame, write_frame

    addr = str(tmp_path / "solver3.sock")
    service = PlacementService(addr)
    service.start()
    try:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(addr)
        stream = sock.makefile("rwb")
        write_frame(stream, b"this is not an npz archive")
        stream.flush()
        payload = read_frame(stream)
        assert payload is not None
        resp = decode_response(payload)
        assert "decode failed" in resp.error
        # connection still usable for a real solve
        write_frame(stream, encode_request(_problem()))
        stream.flush()
        ok = decode_response(read_frame(stream))
        assert ok.error == "" and (ok.assignments >= 0).all()
        stream.close()
        sock.close()
    finally:
        service.stop()


class TestSharedSecret:
    def test_tcp_secret_gates_solves(self):
        """ADVICE round-2 fix: TCP mode can require a shared-secret hello
        frame; unauthenticated peers are dropped before any solve."""
        service = PlacementService(("127.0.0.1", 0), secret=b"s3cret")
        service.start()
        addr = service._server.server_address
        try:
            with PlacementClient(addr, timeout=10.0,
                                 secret=b"s3cret") as client:
                assert (client.solve(_problem()).assignments >= 0).all()
            with pytest.raises((ConnectionError, OSError)):
                with PlacementClient(addr, timeout=10.0,
                                     secret=b"wrong") as client:
                    client.solve(_problem())
            with pytest.raises((ConnectionError, OSError)):
                with PlacementClient(addr, timeout=10.0) as client:
                    client.solve(_problem())
        finally:
            service.stop()


class TestKernelRouting:
    def test_forced_pallas_matches_scan_path(self, monkeypatch):
        """KTPU_SOLVER_PALLAS=1 routes the sidecar's solve onto the
        pallas kernel (interpret mode off-TPU) — responses must be
        byte-identical to the scan path, reservation outputs included."""
        import koordinator_tpu.service.server as server

        rng = np.random.default_rng(7)
        req = _problem(n_nodes=40, n_pods=24)
        # give the solve a reservation table so the kernel's newest
        # path crosses the wire too
        n_resv = 3
        free = np.zeros((n_resv, NUM_RESOURCES), np.int32)
        free[:, R.CPU] = rng.integers(2000, 9000, n_resv)
        req.resv = {
            "node": rng.integers(0, 40, n_resv).astype(np.int32),
            "free": free,
            "allocate_once": rng.uniform(size=n_resv) < 0.5,
            "match": rng.uniform(size=(24, n_resv)) < 0.5,
        }

        def run(flag):
            monkeypatch.setenv("KTPU_SOLVER_PALLAS", flag)
            monkeypatch.setattr(server, "_pallas_enabled", [None])
            return solve_from_request(req)

        kern = run("1")
        scan = run("0")
        assert not kern.error and not scan.error
        for field in ("assignments", "node_used_req", "commit", "waiting",
                      "rejected", "raw_assign", "resv_vstar", "resv_delta"):
            np.testing.assert_array_equal(
                getattr(kern, field), getattr(scan, field), err_msg=field)
        assert (kern.resv_vstar >= 0).sum() > 0  # reservations consumed

    def test_kernel_error_trips_breaker_not_request(self, monkeypatch):
        """A kernel failure falls back to the scan for THAT request and
        disables routing afterwards — never an error response."""
        import koordinator_tpu.service.server as server

        monkeypatch.setenv("KTPU_SOLVER_PALLAS", "1")
        monkeypatch.setattr(server, "_pallas_enabled", [None])

        def boom(*a, **kw):
            raise RuntimeError("kernel exploded")

        import koordinator_tpu.ops.pallas_binpack as pb

        monkeypatch.setattr(pb, "pallas_solve_batch", boom)
        with pytest.warns(RuntimeWarning, match="disabled after error"):
            resp = solve_from_request(_problem())
        assert not resp.error
        assert server._pallas_enabled[0] is False  # breaker tripped
