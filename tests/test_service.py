"""Solver service boundary (VERDICT missing item 10 / SURVEY §5.8):
control plane ↔ solver over a framed binary protocol."""

import io
import threading

import numpy as np
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName as R
from koordinator_tpu.service import (
    PlacementClient,
    PlacementService,
    SolveRequest,
    decode_request,
    decode_response,
    encode_request,
    encode_response,
    read_frame,
    write_frame,
)
from koordinator_tpu.service.codec import SolveResponse
from koordinator_tpu.service.server import solve_from_request


def _problem(n_nodes=4, n_pods=6):
    rng = np.random.default_rng(0)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, R.CPU] = 16000
    alloc[:, R.MEMORY] = 32768
    node = {
        "alloc": alloc,
        "used_req": np.zeros_like(alloc),
        "usage": np.zeros_like(alloc),
        "prod_usage": np.zeros_like(alloc),
        "est_extra": np.zeros_like(alloc),
        "prod_base": np.zeros_like(alloc),
        "metric_fresh": np.ones(n_nodes, bool),
        "schedulable": np.ones(n_nodes, bool),
    }
    req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = rng.choice([1000, 2000], n_pods)
    pods = {
        "req": req,
        "est": (req * 85) // 100,
        "is_prod": np.zeros(n_pods, bool),
        "is_daemonset": np.zeros(n_pods, bool),
    }
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[R.CPU] = 1
    weights[R.MEMORY] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[R.CPU] = 65
    thresholds[R.MEMORY] = 95
    params = {
        "weights": weights,
        "thresholds": thresholds,
        "prod_thresholds": np.zeros(NUM_RESOURCES, np.int32),
    }
    return SolveRequest(node=node, pods=pods, params=params)


class TestCodec:
    def test_framing_roundtrip(self):
        buf = io.BytesIO()
        write_frame(buf, b"hello")
        write_frame(buf, b"world!")
        buf.seek(0)
        assert read_frame(buf) == b"hello"
        assert read_frame(buf) == b"world!"
        assert read_frame(buf) is None  # EOF

    def test_request_roundtrip(self):
        req = _problem()
        decoded = decode_request(encode_request(req))
        for group, dec in (
            (req.node, decoded.node),
            (req.pods, decoded.pods),
            (req.params, decoded.params),
        ):
            assert set(group) == set(dec)
            for key in group:
                np.testing.assert_array_equal(group[key], dec[key])

    def test_response_roundtrip(self):
        resp = SolveResponse(
            assignments=np.array([0, 1, -1], np.int32),
            node_used_req=np.ones((2, NUM_RESOURCES), np.int32),
            error="",
        )
        decoded = decode_response(encode_response(resp))
        np.testing.assert_array_equal(decoded.assignments, resp.assignments)
        np.testing.assert_array_equal(decoded.node_used_req, resp.node_used_req)
        err = decode_response(
            encode_response(SolveResponse(np.empty(0, np.int32), error="boom"))
        )
        assert err.error == "boom"


class TestSolveHandler:
    def test_matches_in_process_solve(self):
        import jax.numpy as jnp

        from koordinator_tpu.ops.binpack import (
            NodeState,
            PodBatch,
            ScoreParams,
            SolverConfig,
            schedule_batch,
        )

        req = _problem()
        wire = solve_from_request(req)
        state = NodeState(**{k: jnp.asarray(v) for k, v in req.node.items()})
        pods = PodBatch.build(
            req=jnp.asarray(req.pods["req"]),
            est=jnp.asarray(req.pods["est"]),
            is_prod=jnp.asarray(req.pods["is_prod"]),
            is_daemonset=jnp.asarray(req.pods["is_daemonset"]),
        )
        params = ScoreParams(**{k: jnp.asarray(v) for k, v in req.params.items()})
        _, want = schedule_batch(state, pods, params, SolverConfig())
        np.testing.assert_array_equal(wire.assignments, np.asarray(want))

    def test_malformed_request_returns_error(self):
        req = _problem()
        del req.node["alloc"]
        resp = solve_from_request(req)
        assert resp.error and "KeyError" in resp.error


class TestServiceEndToEnd:
    def test_uds_roundtrip(self, tmp_path):
        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        try:
            req = _problem()
            with PlacementClient(addr) as client:
                resp = client.solve(req)
                assert (resp.assignments >= 0).all()
                # the mutated accounting columns come back for the cache
                assert resp.node_used_req.sum() == req.pods["req"].sum()
                # second solve over the same connection (jit cache warm)
                resp2 = client.solve(req)
                np.testing.assert_array_equal(resp.assignments, resp2.assignments)
        finally:
            service.stop()

    def test_concurrent_clients(self, tmp_path):
        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        results = {}

        def worker(i):
            with PlacementClient(addr) as client:
                results[i] = client.solve(_problem()).assignments

        try:
            threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == 4
            for i in range(1, 4):
                np.testing.assert_array_equal(results[0], results[i])
        finally:
            service.stop()

    def test_server_error_surfaces_to_client(self, tmp_path):
        addr = str(tmp_path / "solver2.sock")
        service = PlacementService(addr)
        service.start()
        try:
            req = _problem()
            del req.params["weights"]
            with PlacementClient(addr) as client:
                with pytest.raises(RuntimeError, match="solver error"):
                    client.solve(req)
        finally:
            service.stop()


def test_malformed_payload_keeps_connection(tmp_path):
    """A garbage frame gets an error response, not a dropped connection
    (review fix: decode inside the error boundary)."""
    import socket

    from koordinator_tpu.service.codec import read_frame, write_frame

    addr = str(tmp_path / "solver3.sock")
    service = PlacementService(addr)
    service.start()
    try:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(addr)
        stream = sock.makefile("rwb")
        write_frame(stream, b"this is not an npz archive")
        stream.flush()
        payload = read_frame(stream)
        assert payload is not None
        resp = decode_response(payload)
        assert "decode failed" in resp.error
        # connection still usable for a real solve
        write_frame(stream, encode_request(_problem()))
        stream.flush()
        ok = decode_response(read_frame(stream))
        assert ok.error == "" and (ok.assignments >= 0).all()
        stream.close()
        sock.close()
    finally:
        service.stop()


class TestSharedSecret:
    def test_tcp_secret_gates_solves(self):
        """ADVICE round-2 fix: TCP mode can require a shared-secret hello
        frame; unauthenticated peers are dropped before any solve."""
        service = PlacementService(("127.0.0.1", 0), secret=b"s3cret")
        service.start()
        addr = service._server.server_address
        try:
            with PlacementClient(addr, timeout=10.0,
                                 secret=b"s3cret") as client:
                assert (client.solve(_problem()).assignments >= 0).all()
            with pytest.raises((ConnectionError, OSError)):
                with PlacementClient(addr, timeout=10.0,
                                     secret=b"wrong") as client:
                    client.solve(_problem())
            with pytest.raises((ConnectionError, OSError)):
                with PlacementClient(addr, timeout=10.0) as client:
                    client.solve(_problem())
        finally:
            service.stop()


class TestKernelRouting:
    def test_forced_pallas_matches_scan_path(self, monkeypatch):
        """KTPU_SOLVER_PALLAS=1 routes the sidecar's solve onto the
        pallas kernel (interpret mode off-TPU) — responses must be
        byte-identical to the scan path, reservation outputs included."""
        import koordinator_tpu.service.server as server

        rng = np.random.default_rng(7)
        req = _problem(n_nodes=40, n_pods=24)
        # give the solve a reservation table so the kernel's newest
        # path crosses the wire too
        n_resv = 3
        free = np.zeros((n_resv, NUM_RESOURCES), np.int32)
        free[:, R.CPU] = rng.integers(2000, 9000, n_resv)
        req.resv = {
            "node": rng.integers(0, 40, n_resv).astype(np.int32),
            "free": free,
            "allocate_once": rng.uniform(size=n_resv) < 0.5,
            "match": rng.uniform(size=(24, n_resv)) < 0.5,
        }

        def run(flag):
            monkeypatch.setenv("KTPU_SOLVER_PALLAS", flag)
            monkeypatch.setattr(server, "_pallas_enabled", [None])
            return solve_from_request(req)

        kern = run("1")
        scan = run("0")
        assert not kern.error and not scan.error
        for field in ("assignments", "node_used_req", "commit", "waiting",
                      "rejected", "raw_assign", "resv_vstar", "resv_delta"):
            np.testing.assert_array_equal(
                getattr(kern, field), getattr(scan, field), err_msg=field)
        assert (kern.resv_vstar >= 0).sum() > 0  # reservations consumed

    def test_kernel_error_trips_breaker_not_request(self, monkeypatch):
        """A kernel failure falls back to the scan for THAT request and
        feeds the consecutive-failure breaker: one transient error does
        NOT disable routing, K consecutive ones do — never an error
        response either way."""
        import koordinator_tpu.service.server as server

        monkeypatch.setenv("KTPU_SOLVER_PALLAS", "1")
        monkeypatch.setattr(server, "_pallas_enabled", [None])
        monkeypatch.setattr(server, "_breaker", server.KernelBreaker())

        def boom(*a, **kw):
            raise RuntimeError("kernel exploded")

        import koordinator_tpu.ops.pallas_binpack as pb

        monkeypatch.setattr(pb, "pallas_solve_batch", boom)
        with pytest.warns(RuntimeWarning, match="kernel failure"):
            resp = solve_from_request(_problem())
        assert not resp.error
        # one failure: routing still on (the old breaker's any-error
        # permanent trip was ADVICE r5 low #2)
        assert server._pallas_enabled[0] is True
        assert not server._breaker.status()["tripped"]
        # two more consecutive failures open the breaker
        for _ in range(2):
            with pytest.warns(RuntimeWarning):
                assert not solve_from_request(_problem()).error
        assert server._breaker.status()["tripped"]
        assert server.kernel_breaker_status()["routing_enabled"] is True
        # tripped: the next request rides the scan silently, no warning
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("error")
            assert not solve_from_request(_problem()).error


class TestKernelBreaker:
    """Unit semantics of the consecutive-failure breaker."""

    def test_threshold_and_reset(self):
        from koordinator_tpu.service.server import KernelBreaker

        b = KernelBreaker(threshold=3, cooldown_s=60.0, clock=lambda: 0.0)
        assert b.allow()
        b.record_failure(RuntimeError("x"))
        b.record_failure(RuntimeError("x"))
        assert not b.status()["tripped"]
        b.record_success()  # a success resets the streak
        b.record_failure(RuntimeError("x"))
        b.record_failure(RuntimeError("x"))
        assert not b.status()["tripped"]
        b.record_failure(RuntimeError("boom"))
        st = b.status()
        assert st["tripped"] and st["total_trips"] == 1
        assert "boom" in st["last_error"]
        assert not b.allow()

    def test_cooldown_half_open_probe(self):
        from koordinator_tpu.service.server import KernelBreaker

        now = [0.0]
        b = KernelBreaker(threshold=1, cooldown_s=30.0,
                          clock=lambda: now[0])
        b.record_failure(RuntimeError("x"))
        assert not b.allow()
        now[0] = 31.0
        assert b.allow()        # ONE half-open probe per window
        assert not b.allow()    # a second caller inside the window waits
        b.record_failure(RuntimeError("still broken"))
        now[0] = 45.0
        assert not b.allow()    # the failed probe re-armed the cooldown
        now[0] = 62.0
        assert b.allow()
        b.record_success()      # the probe solved: breaker closes fully
        assert b.allow() and not b.status()["tripped"]


class TestNodeDeltaProtocol:
    """The incremental staging wire path: establish → delta → mismatch
    recovery, always bit-identical to full-state requests."""

    def _establish(self, req, epoch):
        import dataclasses

        return dataclasses.replace(
            req, node_delta={"epoch": np.asarray(epoch, np.int64)}
        )

    def test_establish_then_delta_matches_full(self):
        from koordinator_tpu.service.server import NodeStateCache

        cache = NodeStateCache()
        req = _problem(n_nodes=6, n_pods=5)
        first = solve_from_request(self._establish(req, 1), node_cache=cache)
        assert not first.error and cache.epoch == 1

        # mutate two node rows, solve via delta AND via a full request
        import dataclasses

        node2 = {k: np.array(v, copy=True) for k, v in req.node.items()}
        node2["used_req"][1, R.CPU] = 9000
        node2["schedulable"][4] = False
        idx = np.asarray([1, 4], np.int32)
        delta = {
            "idx": idx,
            "base_epoch": np.asarray(1, np.int64),
            "epoch": np.asarray(2, np.int64),
            **{f: node2[f][idx] for f in (
                "alloc", "used_req", "usage", "prod_usage", "est_extra",
                "prod_base", "metric_fresh", "schedulable",
            )},
        }
        via_delta = solve_from_request(
            dataclasses.replace(req, node={}, node_delta=delta),
            node_cache=cache,
        )
        assert not via_delta.error and cache.epoch == 2
        via_full = solve_from_request(
            dataclasses.replace(req, node=node2)
        )
        np.testing.assert_array_equal(
            via_delta.assignments, via_full.assignments
        )
        np.testing.assert_array_equal(
            via_delta.node_used_req, via_full.node_used_req
        )

    def test_delta_base_mismatch_is_loud(self):
        import dataclasses

        from koordinator_tpu.service.server import NodeStateCache

        cache = NodeStateCache()
        req = _problem()
        delta = {
            "idx": np.asarray([0], np.int32),
            "base_epoch": np.asarray(7, np.int64),
            "epoch": np.asarray(8, np.int64),
            **{f: req.node[f][:1] for f in req.node},
        }
        resp = solve_from_request(
            dataclasses.replace(req, node={}, node_delta=delta),
            node_cache=cache,
        )
        assert "delta-base-mismatch" in resp.error

    def test_remote_solver_delta_roundtrip(self, tmp_path):
        """RemoteSolver with a staging delta: establish, then ship only
        dirty rows; a sidecar restart transparently re-establishes."""
        import jax.numpy as jnp

        from koordinator_tpu.models.placement import NodeStagingDelta
        from koordinator_tpu.ops.binpack import (
            NodeState,
            PodBatch,
            ScoreParams,
            SolverConfig,
        )
        from koordinator_tpu.service.client import RemoteSolver

        req = _problem(n_nodes=6, n_pods=5)
        state = NodeState(**{k: jnp.asarray(v) for k, v in req.node.items()})
        batch = PodBatch.build(**{k: jnp.asarray(v)
                                  for k, v in req.pods.items()})
        params = ScoreParams(**{k: jnp.asarray(v)
                                for k, v in req.params.items()})
        config = SolverConfig()

        sock = str(tmp_path / "solver.sock")
        service = PlacementService(sock)
        service.start()
        try:
            solver = RemoteSolver(sock)
            r1 = solver.solve_result(
                state, batch, params, config,
                staging=(1, NodeStagingDelta(1)),
            )
            assert solver.last_request == "establish"

            host = {k: np.array(v, copy=True) for k, v in req.node.items()}
            host["used_req"][2, R.CPU] = 12000
            idx = np.asarray([2], np.int32)
            rows = {f: host[f][idx] for f in host}
            state2 = NodeState(**{k: jnp.asarray(v)
                                  for k, v in host.items()})
            r2 = solver.solve_result(
                state2, batch, params, config,
                staging=(2, NodeStagingDelta(2, 1, idx, rows)),
            )
            assert solver.last_request == "delta"
            want = solve_from_request(
                SolveRequest(node=host, pods=req.pods, params=req.params)
            )
            np.testing.assert_array_equal(r2.assign, want.assignments)
            np.testing.assert_array_equal(
                np.asarray(r2.node_state.used_req), want.node_used_req
            )

            # restart the sidecar: the client must fall back to full
            service.stop()
            service2 = PlacementService(sock)
            service2.start()
            try:
                r3 = solver.solve_result(
                    state2, batch, params, config,
                    staging=(3, NodeStagingDelta(3, 2, idx, rows)),
                )
                assert solver.last_request == "establish"
                np.testing.assert_array_equal(r3.assign, want.assignments)
            finally:
                service2.stop()
        finally:
            try:
                service.stop()
            except Exception:
                pass

    def test_request_specific_failure_refunds_probe(self):
        from koordinator_tpu.service.server import KernelBreaker

        now = [0.0]
        b = KernelBreaker(threshold=1, cooldown_s=30.0,
                          clock=lambda: now[0])
        b.record_failure(RuntimeError("x"))
        now[0] = 31.0
        assert b.allow()        # probe slot consumed
        b.refund_probe()        # ...but the solve never tested health
        assert b.allow()        # slot returned: the NEXT request probes
