"""Differential: the vectorized oracle's NUMA + reservation + quota +
gang modeling vs the device scan solver (VERDICT r4 #2).

The oracle (oracle/vectorized.py) re-derives each feature from the
reference semantics (nodenumaresource/scoring.go for the NUMA term,
reservation transformer restore + Reserve for credit/consumption) in
sequential numpy, structured nothing like the lax.scan; these tests
randomize shapes and feature mixes and require bit-identity on the
assignment AND every mutated carry (used_req, numa_free, resv_free,
quota used) — so configs 6/7-style workloads are checked against
reference semantics, not merely kernel==scan self-consistency.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from __graft_entry__ import _example_problem
from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.ops.binpack import (
    NumaAux,
    ResvArrays,
    SolverConfig,
    solve_batch,
)
from koordinator_tpu.oracle.vectorized import (
    VectorQuota,
    solve_full_vectorized,
)


def _with_numa(state, pods, rng):
    cap = np.asarray(state.alloc)
    free = (cap * rng.uniform(0.2, 1.0, cap.shape)).astype(np.int32)
    state = state._replace(
        numa_cap=jnp.asarray(cap), numa_free=jnp.asarray(free)
    )
    n_pods = np.asarray(pods.req).shape[0]
    pods = pods._replace(
        has_numa_policy=jnp.asarray(rng.uniform(size=n_pods) < 0.4)
    )
    aux = NumaAux(
        node_policy=jnp.asarray(rng.uniform(size=cap.shape[0]) < 0.5)
    )
    return state, pods, aux


def _resv_arrays(n_nodes, n_pods, n_resv, rng):
    node = rng.integers(0, n_nodes, n_resv).astype(np.int32)
    free = np.zeros((n_resv, NUM_RESOURCES), np.int32)
    free[:, ResourceName.CPU] = rng.integers(0, 3000, n_resv)
    free[:, ResourceName.MEMORY] = rng.integers(0, 3000, n_resv)
    allocate_once = rng.uniform(size=n_resv) < 0.5
    # owner-style match: each reservation matches a contiguous slice of
    # pods; some pods match several reservations, most match none
    match = np.zeros((n_pods, n_resv), bool)
    for v in range(n_resv):
        lo = int(rng.integers(0, max(n_pods - 8, 1)))
        match[lo:lo + int(rng.integers(2, 10)), v] = True
    return ResvArrays(
        node=jnp.asarray(node),
        free=jnp.asarray(free),
        allocate_once=jnp.asarray(allocate_once),
        match=jnp.asarray(match),
    )


def _quota(state, pods, n_quota, rng):
    from koordinator_tpu.ops.quota import QuotaState

    cap = np.asarray(state.alloc)
    n_pods = np.asarray(pods.req).shape[0]
    qid = rng.integers(-1, n_quota, n_pods).astype(np.int32)
    pods = pods._replace(
        quota_id=jnp.asarray(qid),
        non_preemptible=jnp.asarray(rng.uniform(size=n_pods) < 0.3),
    )
    total = cap.astype(np.int64).sum(axis=0)
    mn = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mx = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    for r in (ResourceName.CPU, ResourceName.MEMORY):
        mn[:, r] = total[r] // (2 * n_quota)
        mx[:, r] = total[r] // 3
    req_np = np.asarray(pods.req).astype(np.int64)
    child_request = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    sel = qid >= 0
    np.add.at(child_request, qid[sel], req_np[sel])
    qstate = QuotaState.build(
        min=mn, max=mx, weight=mx, allow_lent=np.ones(n_quota, bool),
        total=total, child_request=child_request,
    )
    vq = VectorQuota(
        min_=mn, max_=mx, auto_min=np.asarray(qstate.auto_min),
        weight=mx, allow_lent=np.ones(n_quota, bool), total=total,
    )
    return pods, qstate, vq, qid


def _gang(pods, n_gangs, members, rng):
    from koordinator_tpu.ops.gang import GangState

    n_pods = np.asarray(pods.req).shape[0]
    gang_id = np.full(n_pods, -1, np.int32)
    count = min(n_gangs * members, n_pods)
    gang_id[:count] = np.repeat(
        np.arange(n_gangs, dtype=np.int32), members
    )[:count]
    strict = rng.uniform(size=n_gangs) < 0.7
    gstate = GangState.build(
        min_member=[members] * n_gangs, strict=strict
    )
    return pods._replace(gang_id=jnp.asarray(gang_id)), gstate, gang_id


def _check(result, oracle, qstate_used=None):
    np.testing.assert_array_equal(
        np.asarray(result.assign), oracle["assign"]
    )
    np.testing.assert_array_equal(
        np.asarray(result.node_state.used_req), oracle["used_req"]
    )
    if "numa_free" in oracle:
        np.testing.assert_array_equal(
            np.asarray(result.node_state.numa_free), oracle["numa_free"]
        )
        np.testing.assert_array_equal(
            np.asarray(result.numa_consumed), oracle["numa_consumed"]
        )
    if "resv_free" in oracle:
        np.testing.assert_array_equal(
            np.asarray(result.resv_free), oracle["resv_free"]
        )
        np.testing.assert_array_equal(
            np.asarray(result.resv_vstar), oracle["resv_vstar"]
        )
    if qstate_used is not None:
        np.testing.assert_array_equal(
            np.asarray(result.quota_state.used), qstate_used
        )


@pytest.mark.parametrize("seed", range(6))
def test_numa_oracle_identity(seed):
    n_nodes, n_pods = 96, 256
    state, pods, params = _example_problem(n_nodes, n_pods, seed=seed)
    rng = np.random.default_rng(seed)
    state, pods, aux = _with_numa(state, pods, rng)
    result = jax.jit(
        lambda s, p, pr: solve_batch(s, p, pr, SolverConfig(), numa=aux)
    )(state, pods, params)
    oracle = solve_full_vectorized(state, pods, params, numa_aux=aux)
    _check(result, oracle)
    assert int(np.asarray(result.numa_consumed).sum()) > 0


@pytest.mark.parametrize("seed", range(6))
def test_reservation_oracle_identity(seed):
    n_nodes, n_pods, n_resv = 64, 200, 24
    state, pods, params = _example_problem(n_nodes, n_pods, seed=seed)
    rng = np.random.default_rng(100 + seed)
    resv = _resv_arrays(n_nodes, n_pods, n_resv, rng)
    result = jax.jit(
        lambda s, p, pr: solve_batch(s, p, pr, SolverConfig(), resv=resv)
    )(state, pods, params)
    oracle = solve_full_vectorized(state, pods, params, resv=resv)
    _check(result, oracle)
    assert int((np.asarray(result.resv_vstar) >= 0).sum()) > 0


@pytest.mark.parametrize("seed", range(4))
def test_all_features_oracle_identity(seed):
    """Quota + gang + NUMA + reservations fused in one solve — the full
    epilogue (strict-gang release of node/NUMA/reservation/quota holds)
    checked bit-for-bit."""
    n_nodes, n_pods, n_quota, n_gangs, n_resv = 80, 320, 8, 12, 16
    state, pods, params = _example_problem(n_nodes, n_pods, seed=seed)
    rng = np.random.default_rng(200 + seed)
    state, pods, aux = _with_numa(state, pods, rng)
    resv = _resv_arrays(n_nodes, n_pods, n_resv, rng)
    pods, qstate, vq, qid = _quota(state, pods, n_quota, rng)
    pods, gstate, gang_id = _gang(pods, n_gangs, 8, rng)

    result = jax.jit(
        lambda s, p, pr, q, g: solve_batch(
            s, p, pr, SolverConfig(), q, g, resv=resv, numa=aux
        )
    )(state, pods, params, qstate, gstate)

    oracle = solve_full_vectorized(
        state, pods, params,
        quota=vq, pod_quota_id=qid,
        pod_non_preemptible=np.asarray(pods.non_preemptible),
        gang_id=gang_id,
        gang_min_member=np.asarray(gstate.min_member),
        gang_bound_count=np.asarray(gstate.bound_count),
        gang_strict=np.asarray(gstate.strict),
        gang_group_id=np.asarray(gstate.group_id),
        numa_aux=aux, resv=resv,
    )
    _check(result, oracle, qstate_used=vq.used)
    assert int((np.asarray(result.resv_vstar) >= 0).sum()) > 0


def test_all_features_epilogue_forced_rejection():
    """A gang too large to place fully forces the Strict release path:
    node, NUMA, reservation and quota holds all roll back, oracle
    bit-identical."""
    n_nodes, n_pods, n_quota, n_resv = 24, 160, 4, 10
    state, pods, params = _example_problem(n_nodes, n_pods, seed=99)
    rng = np.random.default_rng(99)
    state, pods, aux = _with_numa(state, pods, rng)
    resv = _resv_arrays(n_nodes, n_pods, n_resv, rng)
    pods, qstate, vq, qid = _quota(state, pods, n_quota, rng)
    # one strict gang whose min_member exceeds the pod count: never
    # satisfiable, so every placed member rolls back through the
    # epilogue release
    pods, gstate, gang_id = _gang(pods, 1, n_pods, rng)
    gstate = gstate._replace(
        min_member=jnp.asarray([n_pods + 1], jnp.int32),
        strict=jnp.ones(1, bool),
    )

    result = jax.jit(
        lambda s, p, pr, q, g: solve_batch(
            s, p, pr, SolverConfig(), q, g, resv=resv, numa=aux
        )
    )(state, pods, params, qstate, gstate)

    oracle = solve_full_vectorized(
        state, pods, params,
        quota=vq, pod_quota_id=qid,
        pod_non_preemptible=np.asarray(pods.non_preemptible),
        gang_id=gang_id,
        gang_min_member=np.asarray(gstate.min_member),
        gang_bound_count=np.asarray(gstate.bound_count),
        gang_strict=np.asarray(gstate.strict),
        gang_group_id=np.asarray(gstate.group_id),
        numa_aux=aux, resv=resv,
    )
    _check(result, oracle, qstate_used=vq.used)
    assert int(np.asarray(result.rejected).sum()) > 0
    assert int((np.asarray(result.resv_vstar) >= 0).sum()) > 0
    assert int(np.asarray(result.numa_consumed).sum()) > 0
