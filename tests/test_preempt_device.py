"""Differential: the device joint place+evict solve vs the host oracle.

The oracle (scheduler/preemption.py) is the scalar transliteration of
the reference's ``SelectVictimsOnNode``/``find_preemption``
(preempt.go:103-294); ops/preempt.py re-derives the same decision as
vectorized passes over the ``[N, P]`` resident world. These tests drive
both over randomized clusters — priority/quota/preemptible diversity,
stale metrics, unschedulable nodes, loadaware threshold boundaries,
over-runtime quotas — and require the chosen node AND the ORDERED
victim list to match exactly, per pod, through whole eviction rounds.
"""

import numpy as np
import pytest

from koordinator_tpu.apis.extension import (
    PriorityClass,
    QoSClass,
    ResourceName,
)
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    NodeSpec,
    PodSpec,
    resources_to_vector,
)
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.scheduler.preemption import (
    find_preemption,
    plan_defrag,
)
from koordinator_tpu.state.cluster import (
    evict_resident_rows,
    lower_nodes,
    lower_resident_pods,
)

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY

QUOTAS = [None, "team-a", "team-b"]


def storm_cluster(rng, n_nodes=12, n_residents=60, stale_frac=0.15,
                  unsched_frac=0.1, metric_frac=0.8):
    nodes, pods, metrics = [], [], {}
    for i in range(n_nodes):
        nodes.append(NodeSpec(
            name=f"n{i}",
            allocatable={CPU: int(rng.integers(8000, 32000)),
                         MEM: int(rng.integers(16384, 65536))},
            unschedulable=bool(rng.random() < unsched_frac),
        ))
    for j in range(n_residents):
        node = nodes[int(rng.integers(n_nodes))]
        pods.append(PodSpec(
            name=f"p{j}",
            node_name=node.name,
            requests={CPU: int(rng.integers(500, 6000)),
                      MEM: int(rng.integers(512, 8192))},
            qos=QoSClass.BE,
            priority=int(rng.integers(0, 6) * 500),
            preemptible=bool(rng.random() < 0.8),
            quota=QUOTAS[int(rng.integers(len(QUOTAS)))],
            assign_time=float(rng.integers(0, 40)),
        ))
    for node in nodes:
        if rng.random() < metric_frac:
            cap = node.allocatable
            metrics[node.name] = NodeMetric(
                node_name=node.name,
                node_usage={
                    CPU: int(rng.integers(0, int(cap[CPU] * 1.05))),
                    MEM: int(rng.integers(0, int(cap[MEM] * 1.05))),
                },
                update_time=(
                    -1000.0 if rng.random() < stale_frac else 100.0
                ),
            )
    return ClusterSnapshot(nodes=nodes, pods=pods, node_metrics=metrics,
                           now=120.0)


def preemptor(rng, k=0):
    return PodSpec(
        name=f"ls{k}",
        requests={CPU: int(rng.integers(2000, 12000)),
                  MEM: int(rng.integers(2048, 16384))},
        qos=QoSClass.LS,
        priority_class=(
            PriorityClass.PROD if rng.random() < 0.5
            else PriorityClass.NONE
        ),
        priority=int(rng.integers(1000, 4000)),
        quota=QUOTAS[int(rng.integers(len(QUOTAS)))],
        is_daemonset=bool(rng.random() < 0.1),
    )


def oracle_pair(snapshot, pod, model, arrays, quota_used=None,
                used_limit=None):
    want = find_preemption(
        snapshot, pod, quota_used=quota_used, used_limit=used_limit,
        arrays=arrays,
        thresholds=np.asarray(model.params.thresholds),
        prod_thresholds=np.asarray(model.params.prod_thresholds),
    )
    return None if want is None else (want[0], [v.uid for v in want[1]])


@pytest.mark.parametrize("seed", range(12))
def test_select_victims_identity(seed):
    """Per-preemptor device selection == oracle: node, victim set AND
    reprieve order, over diverse random worlds."""
    rng = np.random.default_rng(seed)
    snapshot = storm_cluster(rng)
    model = PlacementModel(use_pallas=False)
    arrays = lower_nodes(snapshot, **model.lowering_kwargs())
    resident = model.lower_residents(snapshot, arrays)
    world = model.resident_world(resident)
    for k in range(8):
        pod = preemptor(rng, k)
        got = model.select_victims_device(
            arrays, resident, pod, world=world,
        )
        want = oracle_pair(snapshot, pod, model, arrays)
        assert got == want, f"pod {k}: device {got} != oracle {want}"


@pytest.mark.parametrize("seed", range(8))
def test_quota_gate_identity(seed):
    """The ElasticQuota reprieve gate: with headroom the reprieve loop
    runs; over-runtime (used + podReq > usedLimit) NO victim is
    reprieved — both paths must agree on both regimes, including the
    all-candidates victim list the no-reprieve edge produces."""
    rng = np.random.default_rng(100 + seed)
    snapshot = storm_cluster(rng, stale_frac=0.0, unsched_frac=0.0)
    model = PlacementModel(use_pallas=False)
    arrays = lower_nodes(snapshot, **model.lowering_kwargs())
    resident = model.lower_residents(snapshot, arrays)
    world = model.resident_world(resident)
    import dataclasses

    for k in range(6):
        pod = preemptor(rng, k)
        if pod.quota is None:
            pod = dataclasses.replace(pod, quota="team-a")
        headroom = bool(rng.random() < 0.5)
        req = resources_to_vector(pod.requests)
        quota_used = np.full(
            len(req), int(rng.integers(0, 20000)), dtype=np.int64
        )
        if headroom:
            used_limit = quota_used + req + 10000
        else:
            used_limit = quota_used  # any positive req dim overflows
        got = model.select_victims_device(
            arrays, resident, pod,
            quota_used=quota_used, used_limit=used_limit, world=world,
        )
        want = oracle_pair(
            snapshot, pod, model, arrays,
            quota_used=quota_used, used_limit=used_limit,
        )
        assert got == want, (
            f"pod {k} headroom={headroom}: device {got} != oracle {want}"
        )


@pytest.mark.parametrize("seed", range(6))
def test_eviction_round_identity(seed):
    """A whole multi-preemptor round: per-pod device dispatch with the
    incremental eviction delta (evict_resident_rows) vs the oracle loop
    with full re-lowering — the rounds must agree pod for pod, and the
    delta-maintained arrays must stay bit-identical to from-scratch
    lowering after every eviction."""
    from koordinator_tpu.ops.binpack import STAGED_NODE_FIELDS

    rng = np.random.default_rng(200 + seed)
    dev_snap = storm_cluster(rng)
    model = PlacementModel(use_pallas=False)
    # an independent oracle arm over an identical world
    rng2 = np.random.default_rng(200 + seed)
    ora_snap = storm_cluster(rng2)

    dev_arrays = lower_nodes(dev_snap, **model.lowering_kwargs())
    resident = model.lower_residents(dev_snap, dev_arrays)
    ora_arrays = lower_nodes(ora_snap, **model.lowering_kwargs())
    world = model.resident_world(resident)
    for k in range(6):
        pod = preemptor(rng, k)
        got = model.select_victims_device(
            dev_arrays, resident, pod, world=world,
        )
        want = oracle_pair(ora_snap, pod, model, ora_arrays)
        assert got == want, f"round step {k}: {got} != {want}"
        if got is None:
            continue
        node_name, uids = got
        evict_resident_rows(
            dev_snap, dev_arrays, resident, node_name, uids,
            **model.lowering_kwargs(),
        )
        wanted = set(uids)
        ora_snap.pods = [p for p in ora_snap.pods if p.uid not in wanted]
        ora_arrays = lower_nodes(ora_snap, **model.lowering_kwargs())
        # the eviction delta is bit-identical to full relowering
        for f in STAGED_NODE_FIELDS:
            np.testing.assert_array_equal(
                getattr(dev_arrays, f), getattr(ora_arrays, f),
                err_msg=f"eviction delta diverged on {f} at step {k}",
            )


@pytest.mark.parametrize("seed", range(6))
def test_preempt_scan_identity_disjoint_quota(seed):
    """The scanned storm variant == the sequential per-pod path when no
    quota gate is armed (the regime the scan is exact in): same nodes,
    same ordered victims, eviction deltas carried in-scan."""
    rng = np.random.default_rng(300 + seed)
    snapshot = storm_cluster(rng)
    model = PlacementModel(use_pallas=False)
    arrays = lower_nodes(snapshot, **model.lowering_kwargs())
    resident = model.lower_residents(snapshot, arrays)
    pods = [preemptor(rng, k) for k in range(5)]
    scanned = model.preempt_scan_device(arrays, resident, pods)

    # sequential reference: per-pod device dispatch + eviction deltas
    seq_snap = storm_cluster(np.random.default_rng(300 + seed))
    seq_arrays = lower_nodes(seq_snap, **model.lowering_kwargs())
    seq_res = model.lower_residents(seq_snap, seq_arrays)
    for k, pod in enumerate(pods):
        got = model.select_victims_device(seq_arrays, seq_res, pod)
        assert scanned[k] == got, (
            f"scan step {k}: {scanned[k]} != sequential {got}"
        )
        if got is None:
            continue
        evict_resident_rows(
            seq_snap, seq_arrays, seq_res, got[0], got[1],
            **model.lowering_kwargs(),
        )


@pytest.mark.parametrize("seed", range(8))
def test_defrag_identity(seed):
    """Headroom repack: device plan == host oracle (node, drain set and
    least-important-first order), including the no-drain-needed answer
    when the hole already fits."""
    rng = np.random.default_rng(400 + seed)
    snapshot = storm_cluster(rng)
    model = PlacementModel(use_pallas=False)
    arrays = lower_nodes(snapshot, **model.lowering_kwargs())
    resident = model.lower_residents(snapshot, arrays)
    for k in range(4):
        target = resources_to_vector({
            CPU: int(rng.integers(4000, 20000)),
            MEM: int(rng.integers(4096, 32768)),
        })
        max_prio = int(rng.integers(500, 3000))
        got = model.plan_defrag_device(arrays, resident, target, max_prio)
        plan = plan_defrag(snapshot, target, max_prio, arrays=arrays)
        want = None if plan is None else (
            plan[0], [v.uid for v in plan[1]]
        )
        assert got == want, f"defrag {k}: device {got} != oracle {want}"


def test_loadaware_half_boundary_identity():
    """The percent_rounded .5 boundary (used=23/total=40 → exact 57.5 →
    58, where the reference's float64 lands 57): the preemption
    loadaware gate must agree between device and oracle exactly AT the
    boundary, both when the threshold equals the rounded value (node
    fails, eviction can't help) and one above (node passes)."""
    nodes = [NodeSpec(name="n0", allocatable={CPU: 40, MEM: 65536})]
    residents = [
        PodSpec(name=f"b{j}", node_name="n0",
                requests={CPU: 10, MEM: 16384},
                priority=100, assign_time=float(j))
        for j in range(3)
    ]
    metrics = {"n0": NodeMetric(
        node_name="n0", node_usage={CPU: 23, MEM: 0}, update_time=100.0,
    )}
    snapshot = ClusterSnapshot(
        nodes=nodes, pods=residents, node_metrics=metrics, now=120.0,
    )
    pod = PodSpec(name="ls", requests={CPU: 25, MEM: 1024}, priority=900)
    for cpu_thr, expect_hit in ((58, False), (59, True)):
        model = PlacementModel(
            use_pallas=False, usage_thresholds={CPU: cpu_thr},
        )
        arrays = lower_nodes(snapshot, **model.lowering_kwargs())
        resident = model.lower_residents(snapshot, arrays)
        got = model.select_victims_device(arrays, resident, pod)
        want = oracle_pair(snapshot, pod, model, arrays)
        assert got == want, f"thr={cpu_thr}: {got} != {want}"
        assert (got is not None) == expect_hit


def test_quota_over_runtime_no_reprieve_order():
    """Over-runtime quota: the oracle appends EVERY candidate in
    importance order (no reprieve at all); the device victim mask read
    along the importance-sorted P axis must produce exactly that list."""
    nodes = [NodeSpec(name="n0", allocatable={CPU: 10000, MEM: 65536})]
    residents = [
        PodSpec(name=f"b{j}", node_name="n0",
                requests={CPU: 2000, MEM: 1024},
                priority=[300, 100, 300, 200][j],
                assign_time=[5.0, 1.0, 2.0, 9.0][j],
                quota="q")
        for j in range(4)
    ]
    snapshot = ClusterSnapshot(
        nodes=nodes, pods=residents, node_metrics={}, now=120.0,
    )
    pod = PodSpec(name="ls", requests={CPU: 4000, MEM: 2048},
                  priority=900, quota="q")
    model = PlacementModel(use_pallas=False)
    arrays = lower_nodes(snapshot, **model.lowering_kwargs())
    resident = model.lower_residents(snapshot, arrays)
    req = resources_to_vector(pod.requests)
    quota_used = np.full_like(req, 100)
    used_limit = quota_used  # any positive req dim is over
    got = model.select_victims_device(
        arrays, resident, pod, quota_used=quota_used,
        used_limit=used_limit,
    )
    want = oracle_pair(snapshot, pod, model, arrays,
                       quota_used=quota_used, used_limit=used_limit)
    assert got == want
    # all four candidates, importance order: prio desc, then assign asc
    assert got is not None
    assert got[1] == [
        "default/b2", "default/b0", "default/b3", "default/b1",
    ]


def test_verify_backend_runs_and_agrees():
    """The scheduler's "verify" backend runs device AND oracle per
    preemptor and raises on any divergence — a storm round through it
    is the end-to-end parity harness."""
    from koordinator_tpu.testing.chaos import preemption_storm

    nodes, residents, arrivals = preemption_storm(
        seed=11, n_nodes=6, residents_per_node=3, n_arrivals=3,
        quota="storm-q",
    )
    sched = Scheduler(model=PlacementModel(use_pallas=False),
                      preemption_backend="verify")
    for node in nodes:
        sched.add_node(node)
    for pod in residents + arrivals:
        sched.add_pod(pod)
    out = sched.schedule_pending(now=100.0)
    assert getattr(out, "nominations", None), "no preemption happened"


def test_unknown_backend_rejected():
    with pytest.raises(ValueError):
        Scheduler(preemption_backend="gpu")


def test_victim_bucket_padding_is_inert():
    """Bucket-padded resident columns can never be selected: the same
    world lowered with and without bucketing gives identical answers."""
    rng = np.random.default_rng(7)
    snapshot = storm_cluster(rng, stale_frac=0.0, unsched_frac=0.0)
    model = PlacementModel(use_pallas=False)
    arrays = lower_nodes(snapshot, **model.lowering_kwargs())
    bucketed = model.lower_residents(snapshot, arrays)
    raw = lower_resident_pods(snapshot, arrays)  # no bucket
    assert bucketed.p >= raw.p
    for k in range(4):
        pod = preemptor(rng, k)
        got_b = model.select_victims_device(arrays, bucketed, pod)
        got_r = model.select_victims_device(arrays, raw, pod)
        assert got_b == got_r


def test_placement_service_status_has_preemption_section(tmp_path):
    """Eviction counters ride the same operator surface as everything
    else: PlacementService.status() carries a preemption section with
    attempts, per-outcome victim counts and defrag drains — the bounded
    label set the metrics-hygiene rules enumerate."""
    from koordinator_tpu.service.server import PlacementService

    service = PlacementService(str(tmp_path / "preempt-status.sock"))
    service.start()
    try:
        status = service.status()
        section = status["preemption"]
        assert set(section) == {"attempts", "victims", "defrag_drains"}
        assert set(section["victims"]) == {
            "selected", "reprieved", "evicted",
        }
        for value in section["victims"].values():
            assert value >= 0
    finally:
        service.stop()


@pytest.mark.slow
def test_storm_scale_parity_slow():
    """Storm-scale parity (excluded from tier-1): the bench-leg-19
    world — 5k BE residents across 1250 packed nodes — swept through
    the device per-pod path WITH eviction deltas against the host
    oracle with full re-lowers, plus the one-dispatch scan variant
    hitting every arrival. Small-shape parity is pinned dozens of ways
    above; this pins it at the shape the throughput claim is made."""
    from koordinator_tpu.testing.chaos import preemption_storm

    nodes, residents, arrivals = preemption_storm(
        seed=11, n_nodes=1250, residents_per_node=4, n_arrivals=64,
    )
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    for node in nodes:
        sched.add_node(node)
    for pod in residents:
        sched.add_pod(pod)
    model = sched.model
    snapshot = sched.cache.snapshot(now=50.0)
    arrays = lower_nodes(snapshot, **model.lowering_kwargs())
    resident = model.lower_residents(snapshot, arrays)
    world = model.resident_world(resident)
    scanned = model.preempt_scan_device(
        arrays, resident, arrivals, world=world)
    assert sum(1 for s in scanned if s is not None) == len(arrivals)
    h_snapshot = sched.cache.snapshot(now=50.0)
    h_arrays = lower_nodes(h_snapshot, **model.lowering_kwargs())
    for pod in arrivals[:16]:
        got = model.select_victims_device(
            arrays, resident, pod, world=world)
        want = oracle_pair(h_snapshot, pod, model, h_arrays)
        assert got == want, f"storm-scale divergence for {pod.uid}"
        if got is None:
            continue
        node_name, uids = got
        evict_resident_rows(
            snapshot, arrays, resident, node_name, uids,
            **model.lowering_kwargs(),
        )
        wanted = set(uids)
        h_snapshot.pods = [
            p for p in h_snapshot.pods if p.uid not in wanted
        ]
        h_arrays = lower_nodes(h_snapshot, **model.lowering_kwargs())
