"""Self-tuning serving control plane tests (ISSUE 18 / DESIGN §25).

The ServingSLOController's whole contract is "declared SLO in, bounded
knob moves out, every move auditable and replayable", which makes four
things properties:

- **convergence without retuning** — ONE controller parameterization,
  driven by ONE seeded diurnal trace time-dilated to three load
  regimes (low / mid / saturating), ends every regime inside the
  declared lane SLO with a bounded number of knob adjustments;
- **anti-oscillation** — the pure policy, fed observations that
  alternate breach/under as its own knob moves would produce, settles:
  total adjustments are bounded on the halving ladder (a relax whose
  value breaches burns its ceiling and is never retried) and the tail
  of a long run is decision-free;
- **replay determinism** — re-driving a FRESH policy over the recorded
  observation ring reproduces the live decision sequence bit-for-bit
  (decisions depend on observations + policy state only, never wall
  clocks or live gate state);
- **HA handoff** — SIGKILL the streaming leader mid-trace: the standby
  promotes off the lease, adopts the published knob state AND the
  watch-fed intake, every submitted pod still resolves exactly once
  (zero double-admissions, zero silent drops), and final placements +
  node accounting are bit-identical to a crash-free run.

Plus the satellite seams: the intake's shed/expiry resolutions folded
into PodTimelines' rolling per-lane stats, ArrivalGate.retune's queued-
deadline restamp, note_bound's exactly-once mirror resolution, the
regime_scale time-dilation hook, the flight-recorder payload registry,
and the cmd wiring (--slo-* flags building and registering the
controller; --streaming + --leader-elect no longer refused).
"""

import dataclasses
import json

import numpy as np
import pytest

from koordinator_tpu.apis.extension import QoSClass, ResourceName
from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.client.bus import APIServer, EventType, Kind
from koordinator_tpu.client.leaderelection import LeaderElector
from koordinator_tpu.client.wiring import snapshot_from_bus, wire_scheduler
from koordinator_tpu.control.slo import (
    DEFAULT_STATE_NAME,
    KnobBounds,
    ServingSLOController,
    SLOSpec,
    replay_decisions,
)
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.obs.timeline import PodTimelines
from koordinator_tpu.ops.binpack import STAGED_NODE_FIELDS
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.scheduler.streaming import (
    OUTCOME_BOUND,
    ArrivalGate,
    StreamingConfig,
    StreamingLoop,
)
from koordinator_tpu.state.cluster import lower_nodes
from koordinator_tpu.testing.arrivals import (
    REGIMES,
    diurnal_trace,
    regime_scale,
    trace_pods,
)

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY


@pytest.fixture(autouse=True)
def _shape_flow_under_slo(shape_flow_sentinel):
    """The closed-loop runs fire real adaptive rounds whose batch sizes
    drift with the controller's own knob moves — exactly the load shape
    recompile storms feed on, so every scenario runs inside a
    shape-flow sentinel window (ISSUE 15)."""
    shape_flow_sentinel.begin_window()
    yield
    shape_flow_sentinel.verify_window()


N_NODES = 8


class _NullHist:
    def observe(self, *a, **k):
        pass


class _StubDevice:
    """Deterministic device-observatory stand-in: the policy's padding
    signal under test control, zero global DEVICE_OBS coupling."""

    def __init__(self, waste=0.0, compiles=0):
        self.waste = waste
        self.compiles = compiles

    def mark(self):
        return {"compiles": self.compiles}

    def padding_waste(self):
        return self.waste


def _seed_bus(bus, n_nodes=N_NODES):
    for i in range(n_nodes):
        bus.apply(Kind.NODE, f"n{i}", NodeSpec(
            name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
        bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
            node_name=f"n{i}", node_usage={}, update_time=90.0))


def _wire(clock, config=None, n_nodes=N_NODES, timelines=None):
    """A bus-wired scheduler + StreamingLoop on a fake clock."""
    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    if timelines is not None:
        sched.timelines = timelines
    wire_scheduler(bus, sched)
    _seed_bus(bus, n_nodes)
    loop = StreamingLoop(
        sched,
        apply_fn=lambda pod: bus.apply(Kind.POD, pod.uid, pod),
        delete_fn=lambda uid: bus.delete(Kind.POD, uid),
        config=config or StreamingConfig(),
        clock=lambda: clock[0],
        now_fn=lambda: clock[0],
        log=lambda *a: None,
    )
    return bus, sched, loop


def _pod(name, cpu=500, mem=256, qos=QoSClass.NONE):
    return PodSpec(name=name, requests={CPU: cpu, MEM: mem}, qos=qos)


#: ONE controller parameterization shared by every regime run — the
#: "without retuning" half of the convergence property (cooldown >
#: window so each decision is evaluated on a fully post-decision
#: sample window before the next may fire)
CTL = dict(window_s=0.4, reconcile_interval_s=0.05, cooldown_s=0.45,
           min_samples=2, breach_rounds=2, relax_rounds=8,
           relax_frac=0.5, waste_threshold=0.5)

#: starting knobs every closed-loop scenario begins from: the ls lane
#: deliberately 3x+ slack against the declared target below, so the
#: controller must act (watermark high enough that deadlines trigger)
START_CFG = dict(watermark=64, lane_deadline_s=(0.002, 0.016, 0.050))

LS_TARGET = 0.005


def _obs(seq, now, knobs, lanes=None, waste=0.0):
    return {"seq": seq, "now": now, "window_s": 0.4,
            "lanes": lanes or {}, "knobs": knobs,
            "device": {"compiles": 0, "padding_waste": waste}}


def _lane(count, p99, shed=None):
    return {"count": count, "p99_s": p99, "shed": dict(shed or {})}


class _PolicyLoop:
    """Enough loop surface for a policy-only controller (cfg for the
    relax-ceiling seed; step() itself never touches a loop)."""

    def __init__(self, cfg):
        self.cfg = cfg


def _policy(spec, **over):
    params = dict(CTL)
    params.update(over)
    return ServingSLOController(
        _PolicyLoop(StreamingConfig(**START_CFG)), spec,
        device=_StubDevice(), log=lambda *a: None, **params)


def _apply_to_knobs(knobs, d):
    """Mirror ServingSLOController._apply onto a synthetic knob dict
    (pure-policy tests evolve the observation's knobs themselves)."""
    if d["knob"] == "watermark":
        knobs["watermark"] = d["new"]
    elif d["knob"] == "capacity":
        knobs["capacity"] = d["new"]
    else:
        i = ("system", "ls", "be").index(d["lane"])
        knobs["lane_deadline_s"] = list(knobs["lane_deadline_s"])
        knobs["lane_deadline_s"][i] = d["new"]


# -- the pure policy (no scheduler, no clock) --------------------------------

class TestPolicy:
    def _knobs(self):
        return {"watermark": 64,
                "lane_deadline_s": [0.002, 0.016, 0.050],
                "capacity": 4096}

    def test_breach_needs_confirmation_then_halves_the_lane_deadline(self):
        ctl = _policy(SLOSpec(ls=LS_TARGET))
        knobs = self._knobs()
        lanes = {"ls": _lane(10, 0.016)}
        assert ctl.step(_obs(1, 0.0, knobs, lanes)) is None  # 1st sight
        d = ctl.step(_obs(2, 0.05, knobs, lanes))            # confirmed
        assert d is not None
        assert (d["signal"], d["lane"], d["knob"]) == \
            ("p99-over", "ls", "deadline")
        assert d["old"] == 0.016 and d["new"] == pytest.approx(0.008)
        assert d["observed"] == 0.016 and d["target"] == LS_TARGET

    def test_cooldown_gates_emission_but_streaks_keep_counting(self):
        ctl = _policy(SLOSpec(ls=LS_TARGET))
        knobs = self._knobs()
        lanes = {"ls": _lane(10, 0.016)}
        ctl.step(_obs(1, 0.0, knobs, lanes))
        assert ctl.step(_obs(2, 0.05, knobs, lanes)) is not None
        # inside the cooldown: confirmed breaches emit NOTHING
        assert ctl.step(_obs(3, 0.10, knobs, lanes)) is None
        assert ctl.step(_obs(4, 0.40, knobs, lanes)) is None
        # first observation past the cooldown fires immediately — the
        # streak kept counting through the quiet window
        d = ctl.step(_obs(5, 0.55, knobs, lanes))
        assert d is not None and d["knob"] == "deadline"

    def test_system_lane_outranks_be_on_simultaneous_breach(self):
        ctl = _policy(SLOSpec(system=0.001, be=0.010))
        knobs = self._knobs()
        lanes = {"system": _lane(10, 0.0021), "be": _lane(10, 0.050)}
        ctl.step(_obs(1, 0.0, knobs, lanes))
        d = ctl.step(_obs(2, 0.05, knobs, lanes))
        assert d["lane"] == "system"

    def test_deadline_floor_falls_through_to_watermark_with_ratchet(self):
        bounds = KnobBounds(deadline_floor_s=0.002)
        ctl = _policy(SLOSpec(ls=0.001), bounds=bounds)
        knobs = self._knobs()
        knobs["lane_deadline_s"] = [0.002, 0.002, 0.050]  # ls floored
        lanes = {"ls": _lane(10, 0.004)}
        ctl.step(_obs(1, 0.0, knobs, lanes))
        d = ctl.step(_obs(2, 0.05, knobs, lanes))
        assert (d["knob"], d["old"], d["new"]) == ("watermark", 64, 32)
        _apply_to_knobs(knobs, d)
        # the one-way ratchet: after a latency-driven watermark cut,
        # padding waste may NEVER raise the watermark again
        healthy = {"ls": _lane(10, 0.0004)}
        d2 = ctl.step(_obs(3, 1.0, knobs, healthy, waste=0.9))
        assert d2 is None or d2["signal"] != "padding-waste"

    def test_window_shed_pressure_doubles_capacity_capped(self):
        bounds = KnobBounds(capacity_max=8192)
        ctl = _policy(SLOSpec(ls=LS_TARGET), bounds=bounds)
        knobs = self._knobs()
        lanes = {"be": _lane(4, 0.001, shed={"capacity": 7})}
        d = ctl.step(_obs(1, 0.0, knobs, lanes))
        assert (d["signal"], d["knob"]) == ("shed-capacity", "capacity")
        assert d["old"] == 4096 and d["new"] == 8192
        assert d["observed"] == 7
        _apply_to_knobs(knobs, d)
        # at the cap: shed pressure has no actuator left — no decision
        assert ctl.step(_obs(2, 1.0, knobs, lanes)) is None

    def test_padding_waste_raises_watermark_only_when_healthy(self):
        ctl = _policy(SLOSpec(ls=LS_TARGET))
        knobs = self._knobs()
        healthy = {"ls": _lane(10, 0.001)}
        # shed in the window vetoes the batch-amortization raise
        shedding = {"ls": _lane(10, 0.001, shed={"capacity": 1})}
        assert ctl.step(_obs(1, 0.0, knobs, shedding, waste=0.9)) \
            is not None  # capacity doubling wins instead
        ctl2 = _policy(SLOSpec(ls=LS_TARGET))
        d = ctl2.step(_obs(1, 0.0, knobs, healthy, waste=0.9))
        assert (d["signal"], d["knob"], d["new"]) == \
            ("padding-waste", "watermark", 128)

    def test_relax_is_capped_and_a_breached_relax_burns_its_ceiling(self):
        ctl = _policy(SLOSpec(ls=LS_TARGET), relax_rounds=3)
        knobs = self._knobs()
        knobs["lane_deadline_s"] = [0.002, 0.004, 0.050]  # tightened
        under = {"ls": _lane(10, 0.001)}
        t = [0.0]

        def step(lanes):
            t[0] += 0.5  # every obs past the cooldown
            return ctl.step(_obs(int(t[0] * 10), t[0], knobs, lanes))

        assert step(under) is None
        assert step(under) is None
        d = step(under)  # 3rd consecutive comfortable window: relax
        assert (d["signal"], d["knob"]) == ("p99-under", "deadline")
        assert d["old"] == 0.004 and d["new"] == pytest.approx(0.008)
        _apply_to_knobs(knobs, d)
        # the relaxed value breaches: tighten back AND burn the ceiling
        breached = {"ls": _lane(10, 0.009)}
        step(breached)
        d2 = step(breached)
        assert d2["knob"] == "deadline" and \
            d2["new"] == pytest.approx(0.004)
        _apply_to_knobs(knobs, d2)
        # sustained under again: the burned rung is NEVER retried
        for _ in range(8):
            assert step(under) is None

    def test_adjustments_bounded_under_adversarial_feedback(self):
        """The anti-oscillation bound, adversarially: feed the policy
        2000 observations where its own moves flip the signal (tight →
        comfortably under, relaxed → breached). The burn rule must
        settle it — bounded total decisions, a decision-free tail."""
        ctl = _policy(SLOSpec(ls=LS_TARGET), relax_rounds=3)
        knobs = self._knobs()
        decisions = []
        for i in range(2000):
            d_ls = knobs["lane_deadline_s"][1]
            p99 = d_ls * 1.05 if d_ls > 0.004 else 0.001
            obs = _obs(i + 1, i * 0.5, knobs,
                       {"ls": _lane(10, p99)})
            d = ctl.step(obs)
            if d is not None:
                decisions.append(d)
                _apply_to_knobs(knobs, d)
        assert 1 <= len(decisions) <= 8, decisions
        assert all(x["now"] < 100.0 for x in decisions), \
            "the policy never settled"

    def test_ungoverned_spec_only_acts_on_shed_and_padding(self):
        ctl = _policy(SLOSpec())
        knobs = self._knobs()
        lanes = {"ls": _lane(50, 9.9)}  # huge p99, but no target
        for i in range(5):
            assert ctl.step(_obs(i + 1, i * 0.5, knobs, lanes)) is None


def test_slospec_parse_flag_strings():
    spec = SLOSpec.parse("p99=0.002", None, "0.05")
    assert spec.system == 0.002 and spec.ls is None and spec.be == 0.05
    assert spec.any() and spec.target("be") == 0.05
    assert SLOSpec.parse().any() is False
    assert SLOSpec.parse(ls="").ls is None
    with pytest.raises(ValueError, match="p99"):
        SLOSpec.parse(system="p50=0.1")


# -- timeline failure fold (the bugfix satellite) ----------------------------

def test_timeline_note_shed_folds_into_rolling_stats():
    t = [1000.0]
    tl = PodTimelines(clock=lambda: t[0], histogram=_NullHist())
    tl.submit("u1", "be")
    tl.note_shed("be", "capacity", uid="u1")
    tl.note_shed("ls", "deadline-exceeded")
    stats = tl.stats()
    assert stats["all"]["shed"] == {"capacity": 1,
                                    "deadline-exceeded": 1}
    assert stats["be"]["shed"] == {"capacity": 1}
    # a lane with failures but no latency samples still appears — a
    # lane shedding EVERYTHING must not vanish from the surface
    assert stats["ls"]["count"] == 0
    assert stats["ls"]["shed"] == {"deadline-exceeded": 1}
    # the shed pod's active timeline closed without observing
    assert tl.status()["inflight"] == 0
    # failures age out of the rolling window like latency samples
    t[0] += 100.0
    assert tl.stats(window_s=30.0)["all"]["shed"] == {}


def test_gate_shed_and_expiry_resolutions_reach_timeline_stats():
    """End-to-end: the intake's capacity evictions/refusals and
    deadline expiries land in PodTimelines.stats(window_s=) per lane —
    the failure half of the controller's observation."""
    clock = [100.0]
    tl = PodTimelines(clock=lambda: clock[0], histogram=_NullHist())
    cfg = StreamingConfig(watermark=64, capacity=3, max_pod_rounds=2,
                          lane_deadline_s=(0.002, 0.010, 0.050))
    bus, sched, loop = _wire(clock, cfg, timelines=tl)
    assert loop.submit(_pod("be0", qos=QoSClass.BE),
                       now=clock[0]) == "queued"
    assert loop.submit(_pod("be1", qos=QoSClass.BE),
                       now=clock[0]) == "queued"
    assert loop.submit(_pod("ls0"), now=clock[0]) == "queued"
    # at capacity: an LS arrival evicts the newest BE; a BE arrival
    # outranks nothing and is refused — both are lane-"be" failures
    assert loop.submit(_pod("ls1"), now=clock[0]) == "queued"
    assert loop.submit(_pod("be2", qos=QoSClass.BE),
                       now=clock[0]) == "shed"
    stats = tl.stats(window_s=5.0)
    assert stats["be"]["shed"] == {"capacity": 2}
    # an unplaceable LS pod expires after max_pod_rounds: a typed
    # deadline-exceeded failure on ITS lane (admitting it at capacity
    # evicts the remaining BE — a third capacity failure)
    loop.submit(_pod("whale", cpu=999999, mem=999999), now=clock[0])
    clock[0] += 0.011
    loop.pump(clock[0])
    clock[0] += 0.011
    loop.pump(clock[0])
    stats = tl.stats(window_s=5.0)
    assert stats["ls"]["shed"] == {"deadline-exceeded": 1}
    assert stats["be"]["shed"] == {"capacity": 3}
    # the survivors' latency samples sit beside the failures, and a
    # lane that shed EVERYTHING still surfaces
    assert stats["ls"]["count"] == 2
    assert stats["be"]["count"] == 0
    loop.stop()


# -- retune + note_bound (the actuator seams) --------------------------------

def test_retune_restamps_queued_deadlines_and_wakes_triggers():
    t = [0.0]
    gate = ArrivalGate(StreamingConfig(
        watermark=64, lane_deadline_s=(0.002, 0.010, 0.050)),
        clock=lambda: t[0])
    gate.admit("p", 1, now=0.0)
    assert gate.next_deadline() == pytest.approx(0.010)
    # tightening the ls deadline restamps the QUEUED entry: the new
    # deadline governs pods admitted under the old config too
    gate.retune(lane_deadline_s=(0.002, 0.004, 0.050))
    assert gate.cfg.lane_deadline_s == (0.002, 0.004, 0.050)
    assert gate.next_deadline() == pytest.approx(0.004)
    assert gate.due(0.0039) is None
    assert gate.due(0.004) == "deadline"
    # a watermark cut below the current depth arms the other trigger
    gate.retune(watermark=1)
    assert gate.cfg.watermark == 1
    assert gate.due(0.0) == "watermark"
    gate.retune(capacity=8)
    assert gate.cfg.capacity == 8


def test_note_bound_resolves_mirror_exactly_once():
    """The HA standby's accounting seam: a bind published by ANOTHER
    seat resolves the watch-fed mirror entry; a uid inside THIS seat's
    firing round is left to resolve_round (exactly-once outcomes)."""
    from koordinator_tpu.models.placement import ScheduleResult

    t = [0.0]
    gate = ArrivalGate(StreamingConfig(
        watermark=64, lane_deadline_s=(0.002, 0.010, 0.050)),
        clock=lambda: t[0])
    gate.admit("mirror", 1, now=0.0)
    gate.note_bound("mirror")
    assert gate.outcome("mirror") == OUTCOME_BOUND
    assert gate.depth() == 0 and gate.unresolved() == 0
    assert gate.status()["bound"] == 1
    # in-flight uid: note_bound defers to resolve_round
    gate.admit("own", 1, now=0.0)
    gate.take_round()
    gate.note_bound("own")
    assert gate.outcome("own") is None
    gate.resolve_round(ScheduleResult({"own": "n1"}), now=0.1)
    assert gate.outcome("own") == OUTCOME_BOUND
    assert gate.status()["bound"] == 2, "bound double-counted"


# -- regime_scale (the load-regime satellite) --------------------------------

def test_regime_scale_dilates_time_and_preserves_the_pod_sequence():
    base = diurnal_trace(seed=3, duration_s=2.0, rate_pods_per_s=40.0)
    assert set(REGIMES) == {"low", "mid", "saturating"}
    sat = regime_scale(base, "saturating")
    assert sat.kind == "diurnal@saturating"
    assert sat.duration_s == pytest.approx(0.5)
    assert sat.rate_pods_per_s == pytest.approx(160.0)
    assert len(sat) == len(base)
    for a, b in zip(base, sat):
        assert b.at == pytest.approx(a.at / 4.0)
        # the pod SEQUENCE is byte-identical: same names, lanes, sizes
        assert (a.name, a.lane, a.cpu, a.memory, a.gang) == \
            (b.name, b.lane, b.cpu, b.memory, b.gang)
    mid = regime_scale(base, "mid")
    assert mid.arrivals == base.arrivals
    assert regime_scale(base, 2.0).kind == "diurnal@x2"
    with pytest.raises(ValueError, match="positive"):
        regime_scale(base, 0.0)
    with pytest.raises(KeyError):
        regime_scale(base, "warp")


# -- the closed loop end to end ----------------------------------------------

def _run_closed_loop(trace, spec, tail_s=0.1, ctl_params=CTL,
                     t0=100.0, step_s=0.001):
    """Drive one scaled trace through a bus-wired StreamingLoop with
    the controller attached, on a fine fake-clock grid (the grid — not
    the arrival instants — bounds trigger overshoot, so latency is
    governed by the knobs under test, not the driver)."""
    clock = [t0]
    tl = PodTimelines(clock=lambda: clock[0], histogram=_NullHist())
    bus, sched, loop = _wire(
        clock, StreamingConfig(**START_CFG), timelines=tl)
    ctl = ServingSLOController(
        loop, spec, clock=lambda: clock[0], device=_StubDevice(),
        log=lambda *a: None, **ctl_params)
    loop.attach_controller(ctl)
    pairs, gangs = trace_pods(trace)
    for name, g in gangs.items():
        bus.apply(Kind.GANG, name, g)
    i, t = 0, 0.0
    end = trace.duration_s + tail_s
    while t <= end + 1e-9:
        clock[0] = t0 + t
        while i < len(pairs) and pairs[i][0] <= t + 1e-12:
            assert loop.submit(pairs[i][1], now=clock[0]) == "queued"
            i += 1
        loop.pump(clock[0])
        t = round(t + step_s, 6)
    assert i == len(pairs)
    return bus, sched, loop, ctl, tl, clock


#: the ONE seeded diurnal workload every regime run dilates
_BASE_TRACE = dict(seed=13, duration_s=6.0, rate_pods_per_s=50.0)


@pytest.mark.parametrize("regime", sorted(REGIMES))
def test_slo_convergence_across_regimes_without_retuning(regime):
    """The tentpole property: one spec, one controller
    parameterization, one seeded diurnal trace — at every load regime
    the loop ends inside the declared ls p99 target, sheds nothing at
    capacity, keeps every knob move inside bounds, and the decision
    log replays bit-for-bit."""
    spec = SLOSpec(ls=LS_TARGET)
    trace = regime_scale(diurnal_trace(**_BASE_TRACE), regime)
    bus, sched, loop, ctl, tl, clock = _run_closed_loop(trace, spec)
    try:
        # zero silent drops, nothing shed at capacity
        st = loop.status()["gate"]
        assert loop.gate.unresolved() == 0
        assert st["shed"]["capacity"] == 0
        assert st["submitted"] == st["bound"] == len(trace)
        # the controller ACTED (the start knobs breach by design) and
        # stayed bounded on the halving ladder
        decisions = ctl.decisions()
        assert 1 <= ctl.decisions_total() <= 12, decisions
        bounds = ctl.bounds
        for d in decisions:
            if d["knob"] == "deadline":
                assert bounds.deadline_floor_s <= d["new"] <= 0.016
            elif d["knob"] == "watermark":
                assert bounds.watermark_min <= d["new"] \
                    <= bounds.watermark_max
        # the ls deadline tightened below its 3x-slack starting point
        assert loop.cfg.lane_deadline_s[1] < 0.016
        # convergence: the trailing window's ls p99 is inside the SLO
        final = tl.stats(window_s=max(0.5, 0.25 * trace.duration_s))
        assert final["ls"]["count"] > 0
        assert final["ls"]["p99_s"] <= LS_TARGET
        assert final["ls"]["shed"] == {}
        if regime != "saturating":
            # knobs settle: the final 30% of the run is decision-free
            # (saturating compresses the whole trace to ~1.5s, inside
            # the convergence transient — bounded totals cover it)
            settle_at = 100.0 + 0.7 * trace.duration_s
            assert all(d["now"] <= settle_at for d in decisions), \
                "the controller kept adjusting at steady state"
        # replay determinism: a fresh policy over the recorded
        # observation ring reproduces the decisions bit-for-bit
        replayed = replay_decisions(
            spec, ctl.observations(),
            base_deadlines=START_CFG["lane_deadline_s"], **CTL)
        assert replayed == decisions
    finally:
        loop.stop()


def test_smoke_slo_controller_closes_the_loop():
    """check.sh's slo smoke slice: a short mid-regime closed-loop run
    must tighten the breaching lane deadline, end inside the target,
    surface its decisions on the debug status, and replay
    bit-for-bit."""
    spec = SLOSpec(ls=LS_TARGET)
    trace = diurnal_trace(seed=5, duration_s=1.6, rate_pods_per_s=60.0)
    bus, sched, loop, ctl, tl, clock = _run_closed_loop(trace, spec)
    try:
        assert ctl.decisions_total() >= 1
        assert loop.cfg.lane_deadline_s[1] < 0.016
        final = tl.stats(window_s=0.4)
        assert final["ls"]["count"] > 0
        assert final["ls"]["p99_s"] <= LS_TARGET
        status = ctl.status()
        assert status["spec"]["ls"] == LS_TARGET
        assert status["decisions_total"] == ctl.decisions_total()
        assert status["decisions"][-1]["knob"] in ("deadline",
                                                   "watermark")
        assert status["knobs"]["lane_deadline_s"] == \
            list(loop.cfg.lane_deadline_s)
        # the loop's own status carries the controller summary
        assert loop.status()["slo"]["decisions"] == \
            ctl.decisions_total()
        assert replay_decisions(
            spec, ctl.observations(),
            base_deadlines=START_CFG["lane_deadline_s"], **CTL
        ) == ctl.decisions()
    finally:
        loop.stop()


# -- flight-recorder stamping ------------------------------------------------

def test_flight_payload_hook_stamps_decisions_into_dumps(tmp_path):
    from koordinator_tpu.obs.flight import FlightRecorder

    ctl = _policy(SLOSpec(ls=LS_TARGET))
    knobs = {"watermark": 64, "lane_deadline_s": [0.002, 0.016, 0.050],
             "capacity": 4096}
    lanes = {"ls": _lane(10, 0.016)}
    ctl.step(_obs(1, 0.0, knobs, lanes))
    d = ctl.step(_obs(2, 0.05, knobs, lanes))
    with ctl._lock:  # policy-only instance: record the decision ring
        ctl._ring.append(d)
        ctl._decisions_total += 1
    rec = FlightRecorder(dump_dir=str(tmp_path), min_interval_s=0.0)
    rec.register_payload("slo", ctl.flight_payload)
    path = rec.trigger("manual", detail="test")
    assert path is not None
    payload = json.loads(open(path).read())
    assert payload["slo"]["decisions_total"] == 1
    assert payload["slo"]["decisions"][0]["signal"] == "p99-over"
    assert payload["slo"]["spec"]["ls"] == LS_TARGET
    # reserved section names are refused loudly
    with pytest.raises(ValueError, match="reserved"):
        rec.register_payload("rounds", dict)
    # a raising hook degrades to a typed error section, never a lost
    # dump
    rec.register_payload("bad", lambda: 1 / 0)
    path2 = rec.trigger("manual", detail="again")
    payload2 = json.loads(open(path2).read())
    assert "ZeroDivisionError" in payload2["bad"]["error"]
    assert payload2["slo"]["decisions_total"] == 1
    rec.unregister_payload("bad")
    path3 = rec.trigger("manual", detail="third")
    assert "bad" not in json.loads(open(path3).read())


# -- HA: knob-state adoption -------------------------------------------------

def test_on_promoted_adopts_published_knob_state():
    clock = [100.0]
    bus, sched, loop = _wire(clock, StreamingConfig(**START_CFG))
    ctl = ServingSLOController(
        loop, SLOSpec(ls=LS_TARGET), bus=bus,
        clock=lambda: clock[0], device=_StubDevice(),
        log=lambda *a: None, **CTL)
    try:
        # nothing published yet: adoption is a no-op
        assert ctl.on_promoted() is False
        bus.apply(Kind.NODE_SLO, DEFAULT_STATE_NAME, {
            "seq": 9,
            "knobs": {"watermark": 16,
                      "lane_deadline_s": [0.001, 0.004, 0.025],
                      "capacity": 8192},
        })
        assert ctl.on_promoted() is True
        assert loop.cfg.watermark == 16
        assert loop.cfg.lane_deadline_s == (0.001, 0.004, 0.025)
        assert loop.cfg.capacity == 8192
        assert ctl.status()["adopted_state"] is True
    finally:
        loop.stop()


# -- the chaos leg: SIGKILL the streaming leader mid-trace -------------------

#: chaos controller params: relax disabled so the post-failover quiet
#: phase is provably decision-free in BOTH runs (the bit-identity
#: comparison needs the knobs frozen once converged)
CHAOS_CTL = dict(CTL, relax_rounds=10 ** 6)


def _ha_seat(bus, clock, identity, spec):
    """One scheduler seat on the shared bus: wired scheduler, a
    StreamingLoop with the elector folded into its trigger loop, the
    SLO controller riding it, and the cmd-layer bus watch (pending →
    intake, binds → mirror resolution)."""
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    sched.timelines = PodTimelines(clock=lambda: clock[0],
                                   histogram=_NullHist())
    elector = None
    if identity is not None:
        elector = LeaderElector(bus, "koord-scheduler", identity,
                                lease_duration=1.0)
    wire_scheduler(bus, sched, elector=elector)
    loop = StreamingLoop(
        sched,
        apply_fn=lambda pod: bus.apply(Kind.POD, pod.uid, pod),
        delete_fn=lambda uid: bus.delete(Kind.POD, uid),
        config=StreamingConfig(**START_CFG),
        clock=lambda: clock[0], now_fn=lambda: clock[0],
        log=lambda *a: None,
    )
    ctl = ServingSLOController(
        loop, spec, bus=bus, elector=elector,
        clock=lambda: clock[0], device=_StubDevice(),
        log=lambda *a: None, **CHAOS_CTL)
    loop.attach_controller(ctl)
    if elector is not None:
        loop.attach_elector(elector)

    def on_pod(event, name, pod):
        if event is EventType.DELETED:
            return
        if getattr(pod, "node_name", None) is not None:
            loop.observe_bound(pod)
            return
        loop.observe(pod)

    bus.watch(Kind.POD, on_pod)
    return sched, loop, ctl, elector


def _gap_trace():
    """One seeded diurnal trace with a 1.3s arrival gap inserted at
    1.5s — the quiet stretch the leader is killed into (lease 1.0s +
    retry headroom fits inside the gap, so failover costs zero rounds
    and bit-identity against the crash-free run is a hard assertion,
    not a race)."""
    base = diurnal_trace(seed=23, duration_s=3.0, rate_pods_per_s=40.0)
    arrivals = tuple(
        a if a.at < 1.5 else dataclasses.replace(a, at=a.at + 1.3)
        for a in base.arrivals
    )
    return dataclasses.replace(base, arrivals=arrivals,
                               duration_s=base.duration_s + 1.3)


def _drive_ha(kill: bool, spec):
    """Drive the gap trace on a fake-clock grid. ``kill=True`` runs
    two elected seats and stops ticking the leader at 1.62s (mid-gap,
    intake drained); ``kill=False`` is the crash-free single-seat
    reference. Returns (bus, seats, binds-per-uid, submitted uids)."""
    KILL_AT = 1.62
    trace = _gap_trace()
    clock = [100.0]
    bus = APIServer()
    binds, prev_node = {}, {}

    def bind_watch(event, name, pod):
        node = getattr(pod, "node_name", None)
        if event is EventType.DELETED:
            prev_node.pop(pod.uid, None)
            return
        if node is not None and prev_node.get(pod.uid) != node:
            binds[pod.uid] = binds.get(pod.uid, 0) + 1
        prev_node[pod.uid] = node

    bus.watch(Kind.POD, bind_watch)
    if kill:
        seats = [_ha_seat(bus, clock, "seat-a", spec),
                 _ha_seat(bus, clock, "seat-b", spec)]
    else:
        seats = [_ha_seat(bus, clock, None, spec)]
    _seed_bus(bus)
    pairs, _ = trace_pods(trace)
    submitted = []
    i, t = 0, 0.0
    end = trace.duration_s + 0.1
    while t <= end + 1e-9:
        clock[0] = 100.0 + t
        live = seats[-1] if (kill and t >= KILL_AT) else seats[0]
        while i < len(pairs) and pairs[i][0] <= t + 1e-12:
            assert live[1].submit(pairs[i][1], now=clock[0]) == "queued"
            submitted.append(pairs[i][1].uid)
            i += 1
        if not kill or t < KILL_AT:
            seats[0][1].pump(clock[0])
        if kill:
            seats[-1][1].pump(clock[0])
        t = round(t + 0.001, 6)
    assert i == len(pairs)
    return bus, seats, binds, submitted


@pytest.mark.chaos
def test_chaos_slo_leader_kill_inherits_knobs_and_intake():
    """The HA acceptance property: SIGKILL the streaming leader
    mid-trace (after the controller converged). The standby promotes
    off the lease inside the arrival gap, adopts the published knob
    state AND the watch-fed intake; every submitted pod binds exactly
    once (zero double-admissions, zero silent drops), the standby's
    mirror fully resolves, and final placements + node accounting are
    bit-identical to the crash-free run."""
    spec = SLOSpec(ls=LS_TARGET)
    bus, seats, binds, submitted = _drive_ha(kill=True, spec=spec)
    (sched_a, loop_a, ctl_a, ea) = seats[0]
    (sched_b, loop_b, ctl_b, eb) = seats[1]
    r_bus, r_seats, r_binds, r_submitted = _drive_ha(kill=False,
                                                     spec=spec)
    (_, r_loop, r_ctl, _) = r_seats[0]
    try:
        # the leadership actually moved
        assert eb.is_leader() is True
        assert loop_b.status()["leader"] is True
        assert loop_b.status()["rounds"] >= 1, \
            "the promoted standby never fired a round"
        # knob inheritance: the controller converged on seat A, B
        # adopted the published state — and made no decisions of its
        # own (the adopted knobs already satisfy the SLO)
        assert ctl_a.decisions_total() >= 1
        assert ctl_b.status()["adopted_state"] is True
        assert ctl_b.decisions_total() == 0
        assert loop_b.cfg.lane_deadline_s == loop_a.cfg.lane_deadline_s
        assert loop_b.cfg.lane_deadline_s[1] < 0.016
        state = bus.get(Kind.NODE_SLO, DEFAULT_STATE_NAME)
        assert state["knobs"]["lane_deadline_s"] == \
            list(loop_a.cfg.lane_deadline_s)
        # zero silent drops across the failover: every submitted pod
        # bound exactly once, and the standby's watch-fed mirror fully
        # resolved (the leader's binds resolved it via note_bound)
        assert sorted(binds) == sorted(set(submitted))
        assert all(n == 1 for n in binds.values()), \
            "a pod bound more than once across the failover"
        assert loop_b.gate.unresolved() == 0
        for uid in submitted:
            assert getattr(bus.get(Kind.POD, uid), "node_name", None) \
                is not None
        # the crash-free reference made the SAME decisions (seat A's
        # pre-kill convergence) and the SAME placements, bit for bit
        assert r_ctl.decisions() == ctl_a.decisions()
        assert sorted(r_binds) == sorted(binds)
        mine = {u: getattr(p, "node_name", None)
                for u, p in bus.list(Kind.POD).items()}
        ref = {u: getattr(p, "node_name", None)
               for u, p in r_bus.list(Kind.POD).items()}
        assert mine == ref
        got = lower_nodes(snapshot_from_bus(bus, now=500.0))
        want = lower_nodes(snapshot_from_bus(r_bus, now=500.0))
        assert got.names == want.names
        for f in STAGED_NODE_FIELDS:
            np.testing.assert_array_equal(
                getattr(got, f), getattr(want, f),
                err_msg=f"node accounting diverged: {f}")
    finally:
        loop_a.stop()
        loop_b.stop()
        r_loop.stop()


# -- cmd wiring --------------------------------------------------------------

def test_build_slo_controller_wires_debug_and_flight_surfaces():
    from koordinator_tpu.cmd.scheduler import (
        SchedulerConfig,
        build_slo_controller,
        build_streaming_loop,
    )
    from koordinator_tpu.obs.flight import FLIGHT

    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    wire_scheduler(bus, sched)
    _seed_bus(bus)
    config = SchedulerConfig(streaming=True, slo_ls="p99=0.005",
                             slo_window_s=0.4, slo_cooldown_s=0.45)
    loop = build_streaming_loop(sched, bus, config, log=lambda *a: None)
    ctl = None
    try:
        ctl = build_slo_controller(loop, bus, config,
                                   log=lambda *a: None)
        assert ctl is not None
        assert ctl.spec.ls == 0.005 and ctl.spec.system is None
        assert ctl.window_s == 0.4 and ctl.cooldown_s == 0.45
        assert "slo" in sched.services.names()
        assert sched.services.query("slo")["spec"]["ls"] == 0.005
        assert loop.status()["slo"]["decisions"] == 0
        assert "slo" in FLIGHT._payload_hooks
    finally:
        FLIGHT.unregister_payload("slo")
        loop.stop()
    # no declared target: the static flags stay in charge
    bus2 = APIServer()
    sched2 = Scheduler(model=PlacementModel(use_pallas=False))
    wire_scheduler(bus2, sched2)
    loop2 = build_streaming_loop(sched2, bus2, SchedulerConfig(
        streaming=True), log=lambda *a: None)
    try:
        assert build_slo_controller(loop2, bus2,
                                    SchedulerConfig(streaming=True),
                                    log=lambda *a: None) is None
        assert "slo" not in sched2.services.names()
    finally:
        loop2.stop()


def test_run_loop_streaming_accepts_leader_elect():
    """The refusal is gone: run_loop's streaming branch folds the
    elector into the trigger loop instead of raising (the loop here is
    pre-stopped so run() returns immediately; the attach/unchain round
    trip is the wiring under test)."""
    from koordinator_tpu.cmd.scheduler import (
        SchedulerConfig,
        build_streaming_loop,
        run_loop,
    )

    bus = APIServer()
    sched = Scheduler(model=PlacementModel(use_pallas=False))
    elector = LeaderElector(bus, "koord-scheduler", "me")
    wire_scheduler(bus, sched, elector=elector)
    config = SchedulerConfig(streaming=True)
    loop = build_streaming_loop(sched, bus, config, log=lambda *a: None)
    loop.stop()  # pre-stopped: run() exits its loop immediately
    assert run_loop(sched, config, elector=elector, streaming=loop) == 0
    # stop() unchained the promotion hook
    assert elector.on_started_leading is None
