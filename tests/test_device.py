"""Tests for device allocation (mirrors reference deviceshare tests:
device_allocator_test.go, devicehandler_gpu_test.go, utils_test.go)."""

import json

import pytest

from koordinator_tpu.apis.extension import (
    ANNOTATION_DEVICE_ALLOCATED,
    ANNOTATION_DEVICE_ALLOCATE_HINTS,
    ANNOTATION_DEVICE_JOINT_ALLOCATE,
    ResourceName,
)
from koordinator_tpu.apis.types import ClusterSnapshot, NodeSpec, PodSpec
from koordinator_tpu.device.allocator import (
    AutopilotAllocator,
    DeviceHint,
    DeviceUnschedulable,
    JointAllocate,
    normalize_device_requests,
)
from koordinator_tpu.device.cache import (
    DeviceEntry,
    DeviceResourceName as DR,
    DeviceType,
    NodeDevice,
    NodeDeviceCache,
    VirtualFunction,
)
from koordinator_tpu.scheduler.framework import SchedulingFramework
from koordinator_tpu.scheduler.plugins.deviceshare import DeviceSharePlugin

GPU_FULL = {DR.GPU_CORE: 100, DR.GPU_MEMORY: 16384, DR.GPU_MEMORY_RATIO: 100}


def gpu_node(n_gpus=4, with_rdma=False, numa_split=True):
    entries = []
    for i in range(n_gpus):
        entries.append(
            DeviceEntry(
                minor=i,
                device_type=DeviceType.GPU,
                resources=dict(GPU_FULL),
                numa_node=i // 2 if numa_split else 0,
                pcie_id=str(i // 2),
            )
        )
    if with_rdma:
        for i in range(2):
            entries.append(
                DeviceEntry(
                    minor=i,
                    device_type=DeviceType.RDMA,
                    resources={DR.RDMA: 100},
                    numa_node=i,
                    pcie_id=str(i),
                    vfs=[
                        VirtualFunction(bus_id=f"0000:{i}0:00.{v}")
                        for v in range(4)
                    ],
                )
            )
    return NodeDevice("node-a", entries)


class TestNormalize:
    def test_nvidia_gpu_expands(self):
        out = normalize_device_requests({DR.NVIDIA_GPU: 2})
        assert out[DeviceType.GPU] == {DR.GPU_CORE: 200, DR.GPU_MEMORY_RATIO: 200}

    def test_koord_gpu_percent(self):
        out = normalize_device_requests({DR.KOORD_GPU: 50})
        assert out[DeviceType.GPU] == {DR.GPU_CORE: 50, DR.GPU_MEMORY_RATIO: 50}

    def test_core_plus_memory(self):
        out = normalize_device_requests({DR.GPU_CORE: 50, DR.GPU_MEMORY: 8192})
        assert out[DeviceType.GPU] == {DR.GPU_CORE: 50, DR.GPU_MEMORY: 8192}

    def test_invalid_combination(self):
        with pytest.raises(DeviceUnschedulable):
            normalize_device_requests({DR.NVIDIA_GPU: 1, DR.GPU_CORE: 50})

    def test_invalid_percentage(self):
        with pytest.raises(DeviceUnschedulable):
            normalize_device_requests({DR.KOORD_GPU: 150})

    def test_rdma_fpga(self):
        out = normalize_device_requests({DR.RDMA: 100, DR.FPGA: 100})
        assert out[DeviceType.RDMA] == {DR.RDMA: 100}
        assert out[DeviceType.FPGA] == {DR.FPGA: 100}


class TestAllocator:
    def test_partial_gpu_share(self):
        nd = gpu_node(1)
        allocator = AutopilotAllocator(
            nd, normalize_device_requests({DR.KOORD_GPU: 50})
        )
        allocs = allocator.allocate()[DeviceType.GPU]
        assert len(allocs) == 1
        # memory filled from total: 50% of 16 GiB
        assert allocs[0].resources[DR.GPU_MEMORY] == 8192

    def test_multi_gpu(self):
        nd = gpu_node(4)
        allocator = AutopilotAllocator(
            nd, normalize_device_requests({DR.NVIDIA_GPU: 2})
        )
        allocs = allocator.allocate()[DeviceType.GPU]
        assert len(allocs) == 2
        assert all(a.resources[DR.GPU_CORE] == 100 for a in allocs)

    def test_insufficient_devices(self):
        nd = gpu_node(1)
        allocator = AutopilotAllocator(
            nd, normalize_device_requests({DR.NVIDIA_GPU: 2})
        )
        with pytest.raises(DeviceUnschedulable):
            allocator.allocate()

    def test_two_half_gpus_share_device(self):
        nd = gpu_node(1)
        a1 = AutopilotAllocator(nd, normalize_device_requests({DR.KOORD_GPU: 50}))
        from koordinator_tpu.device.allocator import DeviceAllocation  # noqa
        nd.apply("pod-1", a1.allocate())
        a2 = AutopilotAllocator(nd, normalize_device_requests({DR.KOORD_GPU: 50}))
        allocs = a2.allocate()[DeviceType.GPU]
        assert allocs[0].minor == 0
        nd.apply("pod-2", {DeviceType.GPU: allocs})
        a3 = AutopilotAllocator(nd, normalize_device_requests({DR.KOORD_GPU: 10}))
        with pytest.raises(DeviceUnschedulable):
            a3.allocate()

    def test_least_allocated_prefers_free_device(self):
        nd = gpu_node(2, numa_split=False)
        a1 = AutopilotAllocator(nd, normalize_device_requests({DR.KOORD_GPU: 50}))
        nd.apply("pod-1", a1.allocate())
        a2 = AutopilotAllocator(nd, normalize_device_requests({DR.KOORD_GPU: 50}))
        allocs = a2.allocate()[DeviceType.GPU]
        assert allocs[0].minor == 1  # least-allocated picks the idle gpu

    def test_most_allocated_packs(self):
        nd = gpu_node(2, numa_split=False)
        a1 = AutopilotAllocator(
            nd, normalize_device_requests({DR.KOORD_GPU: 50}),
            scorer="MostAllocated",
        )
        nd.apply("pod-1", a1.allocate())
        a2 = AutopilotAllocator(
            nd, normalize_device_requests({DR.KOORD_GPU: 40}),
            scorer="MostAllocated",
        )
        assert a2.allocate()[DeviceType.GPU][0].minor == 0

    def test_numa_affinity_filters(self):
        nd = gpu_node(4)
        allocator = AutopilotAllocator(
            nd, normalize_device_requests({DR.NVIDIA_GPU: 1}),
            numa_affinity=1 << 1,  # NUMA node 1 only → minors 2,3
        )
        allocs = allocator.allocate()[DeviceType.GPU]
        assert allocs[0].minor in (2, 3)

    def test_vf_allocation(self):
        nd = gpu_node(2, with_rdma=True)
        hints = {DeviceType.RDMA: DeviceHint(vf_selector={})}
        allocator = AutopilotAllocator(
            nd, normalize_device_requests({DR.RDMA: 100}), hints=hints
        )
        allocs = allocator.allocate()[DeviceType.RDMA]
        assert allocs[0].vf_bus_ids == ["0000:00:00.0"]
        nd.apply("pod-1", {DeviceType.RDMA: allocs})
        # next VF is the following bus id on the scored-best device
        a2 = AutopilotAllocator(
            nd, normalize_device_requests({DR.RDMA: 100}), hints=hints
        )
        # device 0 is fully used now → device 1
        assert a2.allocate()[DeviceType.RDMA][0].minor == 1

    def test_joint_allocate_same_pcie(self):
        nd = gpu_node(4, with_rdma=True)
        joint = JointAllocate(
            device_types=[DeviceType.GPU, DeviceType.RDMA],
            required_scope="SamePCIe",
        )
        allocator = AutopilotAllocator(
            nd,
            normalize_device_requests({DR.NVIDIA_GPU: 2, DR.RDMA: 100}),
            joint_allocate=joint,
        )
        allocs = allocator.allocate()
        gpu_pcies = {nd.entry(DeviceType.GPU, a.minor).pcie_id
                     for a in allocs[DeviceType.GPU]}
        rdma_pcies = {nd.entry(DeviceType.RDMA, a.minor).pcie_id
                      for a in allocs[DeviceType.RDMA]}
        assert gpu_pcies == rdma_pcies

    def test_apply_for_all_strategy(self):
        nd = gpu_node(2, with_rdma=True)
        hints = {DeviceType.RDMA: DeviceHint(allocate_strategy="ApplyForAll")}
        allocator = AutopilotAllocator(
            nd, normalize_device_requests({DR.RDMA: 1}), hints=hints
        )
        allocs = allocator.allocate()[DeviceType.RDMA]
        assert len(allocs) == 2  # all rdma devices

    def test_unhealthy_device_skipped(self):
        entries = [
            DeviceEntry(minor=0, device_type=DeviceType.GPU,
                        resources=dict(GPU_FULL), health=False),
            DeviceEntry(minor=1, device_type=DeviceType.GPU,
                        resources=dict(GPU_FULL)),
        ]
        nd = NodeDevice("node-a", entries)
        allocator = AutopilotAllocator(
            nd, normalize_device_requests({DR.NVIDIA_GPU: 1})
        )
        assert allocator.allocate()[DeviceType.GPU][0].minor == 1


class TestReviewRegressions:
    """Scenarios from the adversarial review of the first device cut."""

    def test_joint_allocate_never_overallocates_primary(self):
        # 3 PCIes with 1 free GPU each, pod wants 2 via joint-allocate:
        # must get exactly 2, not one per preferred PCIe
        entries = [
            DeviceEntry(minor=i, device_type=DeviceType.GPU,
                        resources=dict(GPU_FULL), numa_node=0, pcie_id=str(i))
            for i in range(3)
        ]
        entries.append(DeviceEntry(
            minor=0, device_type=DeviceType.RDMA,
            resources={DR.RDMA: 100}, numa_node=0, pcie_id="0"))
        nd = NodeDevice("node-a", entries)
        allocator = AutopilotAllocator(
            nd, normalize_device_requests({DR.NVIDIA_GPU: 2}),
            joint_allocate=JointAllocate(device_types=[DeviceType.GPU,
                                                       DeviceType.RDMA]),
        )
        allocs = allocator.allocate()
        assert len(allocs[DeviceType.GPU]) == 2

    def test_same_pcie_secondary_spreads_across_pcies(self):
        # RDMA minors 0,1 on p0 and 2 on p1: SamePCIe needs one per
        # primary PCIe, not the two best-scored on one switch
        entries = [
            DeviceEntry(minor=0, device_type=DeviceType.GPU,
                        resources=dict(GPU_FULL), pcie_id="p0"),
            DeviceEntry(minor=1, device_type=DeviceType.GPU,
                        resources=dict(GPU_FULL), pcie_id="p1"),
            DeviceEntry(minor=0, device_type=DeviceType.RDMA,
                        resources={DR.RDMA: 100}, pcie_id="p0"),
            DeviceEntry(minor=1, device_type=DeviceType.RDMA,
                        resources={DR.RDMA: 100}, pcie_id="p0"),
            DeviceEntry(minor=2, device_type=DeviceType.RDMA,
                        resources={DR.RDMA: 100}, pcie_id="p1"),
        ]
        nd = NodeDevice("node-a", entries)
        allocator = AutopilotAllocator(
            nd,
            normalize_device_requests({DR.NVIDIA_GPU: 2, DR.RDMA: 100}),
            joint_allocate=JointAllocate(
                device_types=[DeviceType.GPU, DeviceType.RDMA],
                required_scope="SamePCIe",
            ),
        )
        allocs = allocator.allocate()
        rdma_pcies = {nd.entry(DeviceType.RDMA, a.minor).pcie_id
                      for a in allocs[DeviceType.RDMA]}
        assert rdma_pcies == {"p0", "p1"}

    def test_joint_allocate_skips_unrequested_types(self):
        nd = gpu_node(2, with_rdma=True)
        allocator = AutopilotAllocator(
            nd, normalize_device_requests({DR.NVIDIA_GPU: 1}),
            joint_allocate=JointAllocate(
                device_types=[DeviceType.GPU, DeviceType.RDMA]
            ),
        )
        allocs = allocator.allocate()
        assert DeviceType.RDMA not in allocs

    def test_apply_for_all_ignores_unhealthy(self):
        entries = [
            DeviceEntry(minor=0, device_type=DeviceType.RDMA,
                        resources={DR.RDMA: 100}),
            DeviceEntry(minor=1, device_type=DeviceType.RDMA,
                        resources={DR.RDMA: 100}, health=False),
        ]
        nd = NodeDevice("node-a", entries)
        allocator = AutopilotAllocator(
            nd, normalize_device_requests({DR.RDMA: 1}),
            hints={DeviceType.RDMA: DeviceHint(allocate_strategy="ApplyForAll")},
        )
        allocs = allocator.allocate()[DeviceType.RDMA]
        assert [a.minor for a in allocs] == [0]

    def test_unknown_extended_resource_ignored(self):
        cache = NodeDeviceCache()
        cache.nodes["node-a"] = gpu_node(1)
        fw = SchedulingFramework([DeviceSharePlugin(cache)])
        snapshot = ClusterSnapshot(
            nodes=[NodeSpec(name="node-a",
                            allocatable={ResourceName.CPU: 16000})]
        )
        pod = PodSpec(name="p1", device_requests={"example.com/foo": 1})
        assert fw.schedule_one(snapshot, pod).status == "bound"


class TestPlugin:
    def build(self, n_gpus=2):
        cache = NodeDeviceCache()
        nd = gpu_node(n_gpus, with_rdma=True)
        cache.nodes["node-a"] = nd
        plugin = DeviceSharePlugin(cache)
        snapshot = ClusterSnapshot(
            nodes=[NodeSpec(name="node-a",
                            allocatable={ResourceName.CPU: 16000})]
        )
        return plugin, cache, snapshot

    def test_gpu_pod_bound_and_annotated(self):
        plugin, cache, snapshot = self.build()
        fw = SchedulingFramework([plugin])
        pod = PodSpec(name="p1", device_requests={"nvidia.com/gpu": 1})
        out = fw.schedule_one(snapshot, pod)
        assert out.status == "bound"
        allocated = json.loads(pod.annotations[ANNOTATION_DEVICE_ALLOCATED])
        assert len(allocated["gpu"]) == 1

    def test_non_device_pod_skips(self):
        plugin, cache, snapshot = self.build()
        fw = SchedulingFramework([plugin])
        pod = PodSpec(name="p1", requests={ResourceName.CPU: 1000})
        assert fw.schedule_one(snapshot, pod).status == "bound"

    def test_exhaustion_unschedulable(self):
        plugin, cache, snapshot = self.build(n_gpus=1)
        fw = SchedulingFramework([plugin])
        p1 = PodSpec(name="p1", device_requests={"nvidia.com/gpu": 1})
        assert fw.schedule_one(snapshot, p1).status == "bound"
        p2 = PodSpec(name="p2", device_requests={"nvidia.com/gpu": 1})
        out = fw.schedule_one(snapshot, p2)
        assert out.status == "unschedulable"

    def test_joint_allocate_annotation(self):
        plugin, cache, snapshot = self.build(n_gpus=4)
        fw = SchedulingFramework([plugin])
        pod = PodSpec(
            name="p1",
            device_requests={"nvidia.com/gpu": 2, "rdma": 100},
            annotations={
                ANNOTATION_DEVICE_JOINT_ALLOCATE: json.dumps(
                    {"deviceTypes": ["gpu", "rdma"], "requiredScope": "SamePCIe"}
                ),
                ANNOTATION_DEVICE_ALLOCATE_HINTS: json.dumps(
                    {"rdma": {"vfSelector": {}}}
                ),
            },
        )
        out = fw.schedule_one(snapshot, pod)
        assert out.status == "bound"
        allocated = json.loads(pod.annotations[ANNOTATION_DEVICE_ALLOCATED])
        assert allocated["rdma"][0]["vfs"]
