"""Tests for the NUMA stack: topology, accumulator, hints, manager, plugin.

Scenarios mirror the reference's table-driven tests
(pkg/scheduler/plugins/nodenumaresource/cpu_accumulator_test.go,
pkg/scheduler/frameworkext/topologymanager/policy_test.go).
"""

import json

import numpy as np
import pytest

from koordinator_tpu.apis.extension import (
    ANNOTATION_RESOURCE_SPEC,
    ANNOTATION_RESOURCE_STATUS,
    QoSClass,
    ResourceName,
)
from koordinator_tpu.apis.types import ClusterSnapshot, NodeSpec, PodSpec
from koordinator_tpu.numa.accumulator import (
    CPUAllocationError,
    take_cpus,
    take_preferred_cpus,
)
from koordinator_tpu.numa.hints import (
    NUMATopologyHint,
    NUMATopologyPolicy,
    mask_bits,
    mask_of,
    merge_hints,
)
from koordinator_tpu.numa.manager import (
    PodAllocation,
    ResourceManager,
    ResourceOptions,
    TopologyOptions,
    generate_resource_hints,
)
from koordinator_tpu.numa.topology import (
    AllocatedCPUs,
    CPUBindPolicy,
    CPUExclusivePolicy,
    CPUTopology,
    NUMAAllocateStrategy,
)
from koordinator_tpu.scheduler.framework import SchedulingFramework
from koordinator_tpu.scheduler.plugins.nodenumaresource import (
    NodeNUMAResourcePlugin,
)


def two_socket_topo():
    # 2 sockets x 1 NUMA node x 4 cores x 2 threads = 16 cpus
    return CPUTopology.build(
        sockets=2, nodes_per_socket=1, cores_per_node=4, threads_per_core=2
    )


def all_available(topo):
    return np.ones(topo.num_cpus, dtype=bool)


class TestTopology:
    def test_build_shape(self):
        topo = two_socket_topo()
        assert topo.num_cpus == 16
        assert topo.num_cores == 8
        assert topo.num_nodes == 2
        assert topo.num_sockets == 2
        assert topo.cpus_per_core == 2
        assert topo.cpus_per_node == 8
        assert topo.cpus_per_socket == 8


class TestAccumulator:
    def test_full_pcpus_takes_whole_cores(self):
        topo = two_socket_topo()
        got = take_cpus(
            topo, 1, all_available(topo), AllocatedCPUs.empty(topo), 4,
            CPUBindPolicy.FULL_PCPUS,
        )
        assert len(got) == 4
        # whole physical cores: every taken core contributes both threads
        cores = topo.core_id[got]
        assert all((cores == c).sum() == 2 for c in set(cores))
        # single NUMA node
        assert len(set(topo.node_id[got])) == 1

    def test_spread_takes_one_per_core(self):
        topo = two_socket_topo()
        got = take_cpus(
            topo, 1, all_available(topo), AllocatedCPUs.empty(topo), 4,
            CPUBindPolicy.SPREAD_BY_PCPUS,
        )
        assert len(got) == 4
        assert len(set(topo.core_id[got])) == 4

    def test_insufficient_raises(self):
        topo = two_socket_topo()
        with pytest.raises(CPUAllocationError):
            take_cpus(
                topo, 1, all_available(topo), AllocatedCPUs.empty(topo), 17,
                CPUBindPolicy.FULL_PCPUS,
            )

    def test_most_allocated_packs_partial_node(self):
        topo = two_socket_topo()
        avail = all_available(topo)
        # node 0 partially consumed: core 0 (cpus 0,1) taken
        avail[0] = avail[1] = False
        got = take_cpus(
            topo, 1, avail, AllocatedCPUs.empty(topo), 2,
            CPUBindPolicy.FULL_PCPUS,
            strategy=NUMAAllocateStrategy.MOST_ALLOCATED,
        )
        # most-allocated packs onto the busier node 0
        assert set(topo.node_id[got]) == {0}

    def test_least_allocated_spreads_to_free_node(self):
        topo = two_socket_topo()
        avail = all_available(topo)
        avail[0] = avail[1] = False
        got = take_cpus(
            topo, 1, avail, AllocatedCPUs.empty(topo), 2,
            CPUBindPolicy.FULL_PCPUS,
            strategy=NUMAAllocateStrategy.LEAST_ALLOCATED,
        )
        assert set(topo.node_id[got]) == {1}

    def test_pcpu_exclusive_avoids_claimed_cores(self):
        topo = two_socket_topo()
        allocated = AllocatedCPUs.empty(topo)
        allocated.exclusive_in_cores.add(0)  # core 0 claimed PCPU-exclusive
        avail = all_available(topo)
        avail[0] = False  # cpu 0 allocated, sibling cpu 1 still free
        got = take_cpus(
            topo, 1, avail, allocated, 4, CPUBindPolicy.SPREAD_BY_PCPUS,
            exclusive_policy=CPUExclusivePolicy.PCPU_LEVEL,
        )
        assert 1 not in got  # sibling of exclusive core avoided

    def test_ref_count_sharing(self):
        topo = two_socket_topo()
        allocated = AllocatedCPUs.empty(topo)
        allocated.ref_count[:8] = 1  # node 0 cpus shared once already
        avail = all_available(topo)  # max_ref_count=2: all still available
        got = take_cpus(
            topo, 2, avail, allocated, 2, CPUBindPolicy.SPREAD_BY_PCPUS,
        )
        assert len(got) == 2

    def test_preferred_cpus_first(self):
        topo = two_socket_topo()
        preferred = np.zeros(topo.num_cpus, dtype=bool)
        preferred[[8, 9]] = True  # reservation-held cpus on node 1
        got = take_preferred_cpus(
            topo, 1, all_available(topo), preferred,
            AllocatedCPUs.empty(topo), 4, CPUBindPolicy.FULL_PCPUS,
        )
        assert {8, 9} <= set(int(c) for c in got)

    def test_needs_more_than_one_socket(self):
        topo = two_socket_topo()
        got = take_cpus(
            topo, 1, all_available(topo), AllocatedCPUs.empty(topo), 12,
            CPUBindPolicy.FULL_PCPUS,
        )
        assert len(got) == 12


class TestHintMerge:
    def test_none_policy_always_admits(self):
        hint, admit = merge_hints(NUMATopologyPolicy.NONE, [0, 1], [])
        assert admit and hint.affinity is None

    def test_best_effort_picks_narrowest_preferred(self):
        providers = [
            {
                "cpu": [
                    NUMATopologyHint(mask_of([0]), True),
                    NUMATopologyHint(mask_of([0, 1]), False),
                ]
            }
        ]
        hint, admit = merge_hints(NUMATopologyPolicy.BEST_EFFORT, [0, 1], providers)
        assert admit and hint.affinity == mask_of([0]) and hint.preferred

    def test_best_effort_admits_unpreferred(self):
        providers = [{"cpu": [NUMATopologyHint(mask_of([0, 1]), False)]}]
        hint, admit = merge_hints(NUMATopologyPolicy.BEST_EFFORT, [0, 1], providers)
        assert admit and not hint.preferred

    def test_restricted_rejects_unpreferred(self):
        providers = [{"cpu": [NUMATopologyHint(mask_of([0, 1]), False)]}]
        _, admit = merge_hints(NUMATopologyPolicy.RESTRICTED, [0, 1], providers)
        assert not admit

    def test_single_numa_rejects_multi_node(self):
        providers = [{"cpu": [NUMATopologyHint(mask_of([0, 1]), True)]}]
        _, admit = merge_hints(
            NUMATopologyPolicy.SINGLE_NUMA_NODE, [0, 1], providers
        )
        assert not admit

    def test_single_numa_admits_single_node(self):
        providers = [{"cpu": [NUMATopologyHint(mask_of([1]), True)]}]
        hint, admit = merge_hints(
            NUMATopologyPolicy.SINGLE_NUMA_NODE, [0, 1], providers
        )
        assert admit and hint.affinity == mask_of([1])

    def test_cross_provider_and(self):
        providers = [
            {"cpu": [NUMATopologyHint(mask_of([0, 1]), True)]},
            {"gpu": [NUMATopologyHint(mask_of([1]), True)]},
        ]
        hint, admit = merge_hints(NUMATopologyPolicy.BEST_EFFORT, [0, 1], providers)
        assert hint.affinity == mask_of([1])

    def test_empty_resource_hints_means_unsatisfiable(self):
        providers = [{"cpu": []}]
        hint, admit = merge_hints(NUMATopologyPolicy.RESTRICTED, [0, 1], providers)
        assert not admit


class TestResourceHints:
    def test_min_affinity_preferred(self):
        numa_res = {
            0: {ResourceName.CPU: 8000, ResourceName.MEMORY: 1024},
            1: {ResourceName.CPU: 8000, ResourceName.MEMORY: 1024},
        }
        avail = {n: dict(r) for n, r in numa_res.items()}
        hints = generate_resource_hints(
            numa_res, {ResourceName.CPU: 4000, ResourceName.MEMORY: 512}, avail
        )
        cpu_hints = hints[ResourceName.CPU]
        # single-node masks feasible → preferred; two-node mask not preferred
        by_mask = {h.affinity: h for h in cpu_hints}
        assert by_mask[mask_of([0])].preferred
        assert by_mask[mask_of([1])].preferred
        assert not by_mask[mask_of([0, 1])].preferred

    def test_free_gate_drops_hint_but_keeps_min_size(self):
        numa_res = {
            0: {ResourceName.CPU: 8000},
            1: {ResourceName.CPU: 8000},
        }
        # node 0 busy: only 1000 free
        avail = {0: {ResourceName.CPU: 1000}, 1: {ResourceName.CPU: 8000}}
        hints = generate_resource_hints(
            numa_res, {ResourceName.CPU: 4000}, avail
        )
        masks = {h.affinity for h in hints[ResourceName.CPU]}
        assert mask_of([0]) not in masks
        assert mask_of([1]) in masks
        # min affinity size is still 1 (capacity-feasible), so [1] preferred
        by_mask = {h.affinity: h for h in hints[ResourceName.CPU]}
        assert by_mask[mask_of([1])].preferred

    def test_lack_resource_node_excluded(self):
        numa_res = {
            0: {ResourceName.CPU: 8000, ResourceName.GPU: 200},
            1: {ResourceName.CPU: 8000},
        }
        avail = {n: dict(r) for n, r in numa_res.items()}
        hints = generate_resource_hints(numa_res, {ResourceName.GPU: 100}, avail)
        masks = {h.affinity for h in hints[ResourceName.GPU]}
        assert masks == {mask_of([0])}


class TestResourceManager:
    def make_manager(self):
        topo = two_socket_topo()
        mgr = ResourceManager()
        mgr.update_topology(
            "node-a",
            TopologyOptions(
                cpu_topology=topo,
                policy=NUMATopologyPolicy.BEST_EFFORT,
                numa_node_resources={
                    0: {ResourceName.CPU: 8000, ResourceName.MEMORY: 1024},
                    1: {ResourceName.CPU: 8000, ResourceName.MEMORY: 1024},
                },
            ),
        )
        return mgr

    def test_allocate_cpuset_and_release(self):
        mgr = self.make_manager()
        options = ResourceOptions(
            requests={ResourceName.CPU: 4000},
            num_cpus_needed=4,
            request_cpu_bind=True,
            cpu_bind_policy=CPUBindPolicy.FULL_PCPUS,
        )
        alloc = mgr.allocate("node-a", "pod-1", options)
        assert len(alloc.cpuset) == 4
        mgr.update("node-a", PodAllocation(
            pod_uid="pod-1", cpuset=alloc.cpuset,
        ))
        avail, _ = mgr.available_cpus("node-a")
        assert int(avail.sum()) == 12
        mgr.release("node-a", "pod-1")
        avail, _ = mgr.available_cpus("node-a")
        assert int(avail.sum()) == 16

    def test_allocate_by_hint_distributes_evenly(self):
        mgr = self.make_manager()
        options = ResourceOptions(
            requests={ResourceName.CPU: 8000, ResourceName.MEMORY: 1024},
            hint=NUMATopologyHint(mask_of([0, 1]), True),
        )
        alloc = mgr.allocate("node-a", "pod-1", options)
        assert set(alloc.numa_resources) == {0, 1}
        assert alloc.numa_resources[0][ResourceName.CPU] == 4000
        assert alloc.numa_resources[1][ResourceName.CPU] == 4000

    def test_allocate_insufficient_numa_raises(self):
        mgr = self.make_manager()
        options = ResourceOptions(
            requests={ResourceName.CPU: 20000},
            hint=NUMATopologyHint(mask_of([0, 1]), True),
        )
        with pytest.raises(CPUAllocationError):
            mgr.allocate("node-a", "pod-1", options)


class TestPlugin:
    def build(self, policy=NUMATopologyPolicy.NONE):
        topo = two_socket_topo()
        mgr = ResourceManager()
        mgr.update_topology(
            "node-a",
            TopologyOptions(
                cpu_topology=topo,
                policy=policy,
                numa_node_resources={
                    0: {ResourceName.CPU: 8000, ResourceName.MEMORY: 1024},
                    1: {ResourceName.CPU: 8000, ResourceName.MEMORY: 1024},
                },
            ),
        )
        plugin = NodeNUMAResourcePlugin(mgr)
        snapshot = ClusterSnapshot(
            nodes=[NodeSpec(
                name="node-a",
                allocatable={ResourceName.CPU: 16000, ResourceName.MEMORY: 2048},
            )]
        )
        return plugin, mgr, snapshot

    def test_lsr_pod_gets_cpuset(self):
        plugin, mgr, snapshot = self.build()
        fw = SchedulingFramework([plugin])
        pod = PodSpec(
            name="p1", qos=QoSClass.LSR,
            requests={ResourceName.CPU: 4000, ResourceName.MEMORY: 512},
        )
        outcome = fw.schedule_one(snapshot, pod)
        assert outcome.status == "bound"
        status = json.loads(pod.annotations[ANNOTATION_RESOURCE_STATUS])
        assert len(status["cpuset"]) == 4

    def test_non_integer_cpuset_rejected(self):
        plugin, mgr, snapshot = self.build()
        fw = SchedulingFramework([plugin])
        pod = PodSpec(
            name="p1", qos=QoSClass.LSR, requests={ResourceName.CPU: 2500}
        )
        outcome = fw.schedule_one(snapshot, pod)
        assert outcome.status == "unschedulable"
        assert "integer" in outcome.reason

    def test_single_numa_policy_constrains(self):
        plugin, mgr, snapshot = self.build(NUMATopologyPolicy.SINGLE_NUMA_NODE)
        fw = SchedulingFramework([plugin])
        # fits on one NUMA node → admitted
        pod = PodSpec(
            name="p1", qos=QoSClass.LS,
            requests={ResourceName.CPU: 6000, ResourceName.MEMORY: 512},
        )
        assert fw.schedule_one(snapshot, pod).status == "bound"
        # cannot fit any single NUMA node → rejected
        pod2 = PodSpec(
            name="p2", qos=QoSClass.LS,
            requests={ResourceName.CPU: 12000, ResourceName.MEMORY: 512},
        )
        outcome = fw.schedule_one(snapshot, pod2)
        assert outcome.status == "unschedulable"

    def test_exclusive_annotation_honored(self):
        plugin, mgr, snapshot = self.build()
        fw = SchedulingFramework([plugin])
        pod = PodSpec(
            name="p1", qos=QoSClass.LSE,
            requests={ResourceName.CPU: 2000},
            annotations={
                ANNOTATION_RESOURCE_SPEC: json.dumps(
                    {"cpuBindPolicy": "FullPCPUs", "cpuExclusivePolicy": "PCPULevel"}
                )
            },
        )
        outcome = fw.schedule_one(snapshot, pod)
        assert outcome.status == "bound"
        cpus = json.loads(pod.annotations[ANNOTATION_RESOURCE_STATUS])["cpuset"]
        topo = mgr.get_topology("node-a").cpu_topology
        assert len({int(topo.core_id[c]) for c in cpus}) == 1  # one full core

    def test_reserve_commits_and_unreserve_rolls_back(self):
        plugin, mgr, snapshot = self.build()
        fw = SchedulingFramework([plugin])
        pod = PodSpec(
            name="p1", qos=QoSClass.LSR, requests={ResourceName.CPU: 8000}
        )
        assert fw.schedule_one(snapshot, pod).status == "bound"
        avail, _ = mgr.available_cpus("node-a")
        assert int(avail.sum()) == 8
        assert mgr.get_allocated_cpuset("node-a", pod.uid) is not None
