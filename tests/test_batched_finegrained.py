"""Differential tests: the batched solver vs the incremental plugin chain
with ALL seven plugins active (VERDICT round-1 item 3).

The batched path (PlacementModel.schedule + propose/validate/refine) must
place a mixed batch — cpuset LSR + GPU + reserved + gang + quota pods —
identically to running the incremental Filter→Score→Reserve cycle
pod-by-pod (reference: pkg/scheduler/plugins/nodenumaresource/plugin.go:
219-431, deviceshare/plugin.go, reservation/transformer.go).
"""

import json

import numpy as np
import pytest

from koordinator_tpu.apis.extension import (
    ANNOTATION_DEVICE_ALLOCATED,
    ANNOTATION_RESOURCE_SPEC,
    ANNOTATION_RESOURCE_STATUS,
    QoSClass,
    ResourceName as R,
)
from koordinator_tpu.apis.types import (
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
    ReservationState,
)
from koordinator_tpu.device.cache import DeviceEntry, DeviceType
from koordinator_tpu.device.cache import DeviceResourceName as DR
from koordinator_tpu.numa.hints import NUMATopologyPolicy
from koordinator_tpu.numa.manager import TopologyOptions
from koordinator_tpu.numa.topology import CPUTopology
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.state.cluster import schedule_order

GPU_FULL = {DR.GPU_CORE: 100, DR.GPU_MEMORY: 16384, DR.GPU_MEMORY_RATIO: 100}


def _numa_options(policy=NUMATopologyPolicy.BEST_EFFORT):
    # 2 sockets x 1 NUMA node x 4 cores x 2 threads = 16 cpus
    topo = CPUTopology.build(
        sockets=2, nodes_per_socket=1, cores_per_node=4, threads_per_core=2
    )
    return TopologyOptions(
        cpu_topology=topo,
        policy=policy,
        numa_node_resources={
            0: {R.CPU: 8000, R.MEMORY: 16384},
            1: {R.CPU: 8000, R.MEMORY: 16384},
        },
    )


def _gpu_entries(n_gpus=4):
    return [
        DeviceEntry(
            minor=i,
            device_type=DeviceType.GPU,
            resources=dict(GPU_FULL),
            numa_node=i // 2,
            pcie_id=str(i // 2),
        )
        for i in range(n_gpus)
    ]


def _mixed_cluster():
    s = Scheduler(cluster_total={R.CPU: 64000, R.MEMORY: 131072})
    for name in ("n0", "n1", "n2", "n3"):
        s.add_node(NodeSpec(name=name, allocatable={R.CPU: 16000, R.MEMORY: 32768}))
        # n3 is the least loaded so the reservation owner (top priority,
        # scheduled first) strictly prefers it via the loadaware score
        usage = {R.CPU: 500} if name == "n3" else {R.CPU: 4000}
        s.update_node_metric(
            NodeMetric(node_name=name, node_usage=usage, update_time=99.0)
        )
    s.update_node_topology("n0", _numa_options())
    s.update_node_topology("n1", _numa_options())
    s.update_node_devices("n2", _gpu_entries())
    s.update_reservation(
        ReservationSpec(
            name="resv-ml",
            requests={R.CPU: 8000},
            allocatable={R.CPU: 8000},
            owner_labels={"team": "ml"},
            node_name="n3",
            state=ReservationState.AVAILABLE,
            allocate_once=False,
        )
    )
    s.update_quota(QuotaSpec(name="t", min={R.CPU: 1000}, max={R.CPU: 4000}))
    s.update_gang(GangSpec(name="g", min_member=2))
    # fillers make n0-n2 too full for the 15000-mCPU reservation owner:
    # only n3 (8000 free + 8000 reserved credit) can take it
    for name in ("n0", "n1", "n2"):
        s.add_pod(
            PodSpec(name=f"filler-{name}", requests={R.CPU: 2000}, node_name=name)
        )
    return s


def _mixed_pods():
    return [
        PodSpec(
            name="lsr",
            qos=QoSClass.LSR,
            requests={R.CPU: 4000, R.MEMORY: 2048},
            annotations={
                ANNOTATION_RESOURCE_SPEC: json.dumps(
                    {"cpuBindPolicy": "FullPCPUs"}
                )
            },
        ),
        PodSpec(
            name="gpu1",
            requests={R.CPU: 2000, R.MEMORY: 1024},
            device_requests={"nvidia.com/gpu": 2},
        ),
        PodSpec(
            name="mlres",
            requests={R.CPU: 15000, R.MEMORY: 1024},
            labels={"team": "ml"},
            priority=100,
        ),
        PodSpec(name="q1", quota="t", requests={R.CPU: 3000}),
        PodSpec(name="q2", quota="t", requests={R.CPU: 3000}),
        PodSpec(name="g1", gang="g", requests={R.CPU: 1000}),
        PodSpec(name="g2", gang="g", requests={R.CPU: 1000}),
        PodSpec(name="plain", requests={R.CPU: 1000, R.MEMORY: 512}),
    ]


def _assignments(s):
    return {
        uid: pod.node_name
        for uid, pod in s.cache.pods.items()
        if pod.node_name is not None
    }


def test_mixed_batch_matches_incremental():
    sb = _mixed_cluster()
    si = _mixed_cluster()
    pods = _mixed_pods()
    for pod in pods:
        sb.add_pod(pod)
    # fresh objects for the incremental scheduler (annotations are mutated)
    pods_i = _mixed_pods()
    for pod in pods_i:
        si.add_pod(pod)

    out = sb.schedule_pending(now=100.0)

    order = schedule_order(pods_i)
    for idx in order:
        si.schedule_one(pods_i[idx].uid, now=100.0)

    got_b = _assignments(sb)
    got_i = _assignments(si)
    assert got_b == got_i

    # the cpuset pod landed on a topology node with pinned cpus
    lsr_b = sb.cache.pods["default/lsr"]
    lsr_i = si.cache.pods["default/lsr"]
    assert lsr_b.node_name in ("n0", "n1")
    status_b = json.loads(lsr_b.annotations[ANNOTATION_RESOURCE_STATUS])
    status_i = json.loads(lsr_i.annotations[ANNOTATION_RESOURCE_STATUS])
    assert status_b["cpuset"] == status_i["cpuset"]
    assert len(status_b["cpuset"]) == 4

    # the GPU pod landed on the device node with identical allocations
    gpu_b = sb.cache.pods["default/gpu1"]
    assert gpu_b.node_name == "n2"
    alloc_b = json.loads(gpu_b.annotations[ANNOTATION_DEVICE_ALLOCATED])
    alloc_i = json.loads(
        si.cache.pods["default/gpu1"].annotations[ANNOTATION_DEVICE_ALLOCATED]
    )
    assert alloc_b == alloc_i
    assert len(alloc_b["gpu"]) == 2

    # the reservation owner consumed reserved capacity on n3
    assert sb.cache.pods["default/mlres"].node_name == "n3"
    resv_b = sb.cache.reservations["resv-ml"]
    resv_i = si.cache.reservations["resv-ml"]
    assert resv_b.allocated.get(R.CPU) == resv_i.allocated.get(R.CPU) == 8000
    assert "default/mlres" in resv_b.allocated_pod_uids

    # quota admitted exactly one of q1/q2 (runtime = max = 4000)
    q_placed = [u for u in ("default/q1", "default/q2") if u in got_b]
    assert len(q_placed) == 1
    assert ("default/q1" in got_b) == ("default/q1" in got_i)

    # both gang members committed
    assert "default/g1" in got_b and "default/g2" in got_b


def test_cpuset_conflict_triggers_refine():
    """Two cpuset pods that both need n0 (the only topology node): the
    validation loop must discover the second take() fails and re-solve —
    second pod ends unschedulable, not phantom-placed."""
    s = Scheduler()
    for name in ("n0", "n1"):
        s.add_node(NodeSpec(name=name, allocatable={R.CPU: 16000, R.MEMORY: 32768}))
        s.update_node_metric(
            NodeMetric(node_name=name, node_usage={}, update_time=99.0)
        )
    s.update_node_topology("n0", _numa_options(policy=NUMATopologyPolicy.NONE))
    # n1 has no CPU topology -> cpuset pods infeasible there
    p1 = PodSpec(name="c1", qos=QoSClass.LSR, requests={R.CPU: 10000})
    p2 = PodSpec(name="c2", qos=QoSClass.LSR, requests={R.CPU: 10000})
    s.add_pod(p1)
    s.add_pod(p2)
    out = s.schedule_pending(now=100.0)
    placed = [u for u, n in out.items() if n is not None]
    assert placed == ["default/c1"]
    assert out["default/c2"] is None
    # and the placed pod really holds 10 pinned cpus
    cpus = s.numa_manager.get_allocated_cpuset("n0", "default/c1")
    assert cpus is not None and len(cpus) == 10


def test_batched_reservation_credit_and_consumption():
    """Batched counterpart of test_reservation_held_for_owner: non-owner
    blocked by the hold, owner placed through the credit, consumption
    recorded on the ReservationSpec."""
    s = Scheduler()
    s.add_node(NodeSpec(name="n0", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    s.update_node_metric(NodeMetric(node_name="n0", node_usage={}, update_time=99.0))
    s.update_reservation(
        ReservationSpec(
            name="resv",
            requests={R.CPU: 8000},
            allocatable={R.CPU: 8000},
            owner_labels={"team": "ml"},
            node_name="n0",
            state=ReservationState.AVAILABLE,
            allocate_once=False,
        )
    )
    s.add_pod(PodSpec(name="other", requests={R.CPU: 4000}))
    s.add_pod(PodSpec(name="mlpod", requests={R.CPU: 4000}, labels={"team": "ml"}))
    out = s.schedule_pending(now=100.0)
    assert out["default/other"] is None
    assert out["default/mlpod"] == "n0"
    resv = s.cache.reservations["resv"]
    assert resv.allocated.get(R.CPU) == 4000
    assert "default/mlpod" in resv.allocated_pod_uids

    # next round: 4000 reserved-free remain + 2000 unreserved; the
    # non-owner still only sees 2000
    s.add_pod(PodSpec(name="other2", requests={R.CPU: 3000}))
    s.add_pod(PodSpec(name="ml2", requests={R.CPU: 3000}, labels={"team": "ml"}))
    out2 = s.schedule_pending(now=101.0)
    assert out2["default/other2"] is None
    assert out2["default/ml2"] == "n0"


def test_allocate_once_reservation_releases_hold_in_batch():
    """allocate_once: first matching pod consumes, reservation flips
    SUCCEEDED, remaining hold is released for later pods IN THE SAME
    batch (the scan releases it; the incremental path re-lowers)."""
    s = Scheduler()
    s.add_node(NodeSpec(name="n0", allocatable={R.CPU: 10000, R.MEMORY: 32768}))
    s.update_node_metric(NodeMetric(node_name="n0", node_usage={}, update_time=99.0))
    s.update_reservation(
        ReservationSpec(
            name="resv",
            requests={R.CPU: 8000},
            allocatable={R.CPU: 8000},
            owner_labels={"team": "ml"},
            node_name="n0",
            state=ReservationState.AVAILABLE,
            allocate_once=True,
        )
    )
    s.add_pod(PodSpec(name="ml1", requests={R.CPU: 2000}, labels={"team": "ml"}))
    # after ml1 consumes (allocate_once), the 6000 remainder is released:
    # a non-owner 5000 pod fits (10000 - 2000 - 3000 used elsewhere = ok)
    s.add_pod(PodSpec(name="other", requests={R.CPU: 5000}))
    out = s.schedule_pending(now=100.0)
    assert out["default/ml1"] == "n0"
    assert out["default/other"] == "n0"
    resv = s.cache.reservations["resv"]
    assert resv.state == ReservationState.SUCCEEDED
    assert resv.allocated.get(R.CPU) == 2000


def test_gang_rejection_rolls_back_reservation_and_numa():
    """A Strict gang that can't fully place: its member's reservation
    consumption and cpuset hold must be rolled back at batch end."""
    s = Scheduler()
    s.add_node(NodeSpec(name="n0", allocatable={R.CPU: 4000, R.MEMORY: 8192}))
    s.update_node_metric(NodeMetric(node_name="n0", node_usage={}, update_time=99.0))
    s.update_node_topology("n0", _numa_options(policy=NUMATopologyPolicy.NONE))
    s.update_reservation(
        ReservationSpec(
            name="resv",
            requests={R.CPU: 2000},
            allocatable={R.CPU: 2000},
            owner_labels={"team": "ml"},
            node_name="n0",
            state=ReservationState.AVAILABLE,
            allocate_once=False,
        )
    )
    s.update_gang(GangSpec(name="g", min_member=2))
    # ga fits (via reservation credit + cpuset), gb (8 cpus) cannot fit
    ga = PodSpec(
        name="ga", gang="g", qos=QoSClass.LSR, requests={R.CPU: 2000},
        labels={"team": "ml"},
    )
    gb = PodSpec(name="gb", gang="g", requests={R.CPU: 8000})
    s.add_pod(ga)
    s.add_pod(gb)
    out = s.schedule_pending(now=100.0)
    assert out["default/ga"] is None and out["default/gb"] is None
    resv = s.cache.reservations["resv"]
    assert not resv.allocated.get(R.CPU)
    assert "default/ga" not in resv.allocated_pod_uids
    # the cpuset hold was rolled back too
    assert s.numa_manager.get_allocated_cpuset("n0", "default/ga") is None


def test_waiting_gang_pod_quota_accounted_and_released():
    """A NonStrict waiting gang member holds its quota (as the incremental
    Reserve does); deleting it releases exactly once — used never goes
    negative (round-2 review fix)."""
    from koordinator_tpu.apis.types import GangMode

    s = Scheduler()
    s.add_node(NodeSpec(name="n0", allocatable={R.CPU: 16000, R.MEMORY: 32768}))
    s.update_node_metric(NodeMetric(node_name="n0", node_usage={}, update_time=99.0))
    s.update_quota(QuotaSpec(name="t", min={R.CPU: 1000}, max={R.CPU: 8000}))
    s.update_gang(GangSpec(name="g", min_member=2, mode=GangMode.NON_STRICT))
    pod = PodSpec(name="w1", gang="g", quota="t", requests={R.CPU: 2000})
    s.add_pod(pod)
    out = s.schedule_pending(now=100.0)
    assert out.waiting.get("default/w1") == "n0"
    used = s.quota_manager.quotas["t"].used
    assert used[int(R.CPU)] == 2000
    s.remove_pod(pod)
    used = s.quota_manager.quotas["t"].used
    assert used[int(R.CPU)] == 0


def test_refine_loop_with_bucketing_device_conflict():
    """The dirty/re-solve path under pod bucketing: two GPU pods compete
    for the only device node; the refine re-solve must keep padded scan
    dims consistent (round-2 review fix)."""
    s = Scheduler()
    for name in ("n0", "n1"):
        s.add_node(NodeSpec(name=name, allocatable={R.CPU: 16000, R.MEMORY: 32768}))
        s.update_node_metric(
            NodeMetric(node_name=name, node_usage={}, update_time=99.0)
        )
    s.update_node_devices("n0", _gpu_entries(4))
    # each wants 3 of the 4 GPUs: only one can be satisfied
    g1 = PodSpec(name="g1", requests={R.CPU: 1000},
                 device_requests={"nvidia.com/gpu": 3})
    g2 = PodSpec(name="g2", requests={R.CPU: 1000},
                 device_requests={"nvidia.com/gpu": 3})
    s.add_pod(g1)
    s.add_pod(g2)
    out = s.schedule_pending(now=100.0)
    placed = sorted(u for u, n in out.items() if n is not None)
    assert placed == ["default/g1"]
    assert out["default/g2"] is None
