"""Feature gates + component entry points (VERDICT round-1 item 9).

Reference: pkg/features/{features,scheduler_features,koordlet_features}.go
and cmd/* component configs.
"""

import pytest

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.cmd import (
    DeschedulerConfig,
    KoordletConfig,
    ManagerConfig,
    SchedulerConfig,
    build_descheduler,
    build_koordlet,
    build_manager,
    build_scheduler,
)
from koordinator_tpu.features import FeatureGate, KOORDLET_GATES


class TestFeatureGate:
    def test_defaults_and_overrides(self):
        g = FeatureGate({"A": True, "B": False})
        assert g.enabled("A") and not g.enabled("B")
        g.set("B", True)
        assert g.enabled("B")
        with pytest.raises(KeyError):
            g.enabled("Nope")
        with pytest.raises(KeyError):
            g.set("Nope", True)

    def test_spec_parsing(self):
        g = FeatureGate({"A": True, "B": False})
        g.set_from_spec("A=false, B=true")
        assert not g.enabled("A") and g.enabled("B")
        with pytest.raises(ValueError):
            g.set_from_spec("A")
        with pytest.raises(ValueError):
            g.set_from_spec("A=maybe")

    def test_reference_koordlet_defaults(self):
        # koordlet_features.go:154-173
        assert KOORDLET_GATES.enabled("BECPUSuppress")
        assert KOORDLET_GATES.enabled("CPUBurst")
        assert KOORDLET_GATES.enabled("RdtResctrl")
        assert not KOORDLET_GATES.enabled("BECPUEvict")
        assert not KOORDLET_GATES.enabled("CPICollector")


class TestKoordletAssembly:
    def test_gates_toggle_strategies(self, tmp_path):
        gates = FeatureGate(dict(KOORDLET_GATES.as_dict()))
        daemon = build_koordlet(
            KoordletConfig(
                cgroup_root=str(tmp_path / "cg"),
                proc_root=str(tmp_path / "proc"),
                feature_gates="BECPUEvict=true,CPUBurst=false,CPICollector=true",
            ),
            gates=gates,
        )
        names = {s.name for s in daemon.qos_manager.strategies}
        assert "cpusuppress" in names or "CPUSuppress" in {
            type(s).__name__ for s in daemon.qos_manager.strategies
        }
        types = {type(s).__name__ for s in daemon.qos_manager.strategies}
        assert "CPUEvictor" in types       # enabled by the spec
        assert "CPUBurst" not in types     # disabled by the spec
        collector_types = {
            type(c).__name__ for c in daemon.metrics_advisor.collectors
        }
        assert "PerformanceCollector" in collector_types
        # a tick runs without error on the empty informer
        daemon.tick(now=1.0)

    def test_default_assembly(self, tmp_path):
        gates = FeatureGate(dict(KOORDLET_GATES.as_dict()))
        daemon = build_koordlet(
            KoordletConfig(cgroup_root=str(tmp_path / "cg")), gates=gates
        )
        types = {type(s).__name__ for s in daemon.qos_manager.strategies}
        assert types == {"CPUSuppress", "CPUBurst", "ResctrlReconcile"}


class TestSchedulerEntry:
    def test_build_and_round(self):
        s = build_scheduler(SchedulerConfig())
        s.add_node(NodeSpec(name="n0", allocatable={R.CPU: 8000, R.MEMORY: 16384}))
        s.update_node_metric(
            NodeMetric(node_name="n0", node_usage={}, update_time=99.0)
        )
        s.add_pod(PodSpec(name="a", requests={R.CPU: 1000}))
        out = s.schedule_pending(now=100.0)
        assert out["default/a"] == "n0"

    def test_batched_placement_gate_off_uses_incremental(self):
        from koordinator_tpu.features import FeatureGate

        gates = FeatureGate({
            "BatchedPlacement": True, "ElasticQuotaPreemption": True,
            "CompatibleCSIStorageCapacity": False,
            "DisableCSIStorageCapacityInformer": False,
            "CompatiblePodDisruptionBudget": False,
            "DisablePodDisruptionBudgetInformer": False,
            "ResizePod": False,
        })
        s = build_scheduler(
            SchedulerConfig(feature_gates="BatchedPlacement=false"),
            gates=gates,
        )
        assert not s.batched_placement
        s.add_node(NodeSpec(name="n0", allocatable={R.CPU: 8000, R.MEMORY: 16384}))
        s.update_node_metric(
            NodeMetric(node_name="n0", node_usage={}, update_time=99.0)
        )
        s.add_pod(PodSpec(name="a", requests={R.CPU: 1000}))
        out = s.schedule_pending(now=100.0)
        assert out["default/a"] == "n0"


class TestKoordletDaemonAssembly:
    def _config(self, tmp_path, **kw):
        from koordinator_tpu.cmd.koordlet import KoordletConfig

        return KoordletConfig(
            cgroup_root=str(tmp_path / "cg"),
            proc_root=str(tmp_path / "proc"), **kw,
        )

    def test_runtimehooks_wired_with_collectors(self, tmp_path):
        from koordinator_tpu.cmd.koordlet import build_koordlet

        daemon = build_koordlet(self._config(tmp_path))
        assert daemon.runtime_hooks is not None
        assert daemon.pleg is None           # reconciler mode default
        names = {c.name for c in daemon.metrics_advisor.collectors}
        assert {"podthrottled", "nodestorageinfo"} <= names
        assert "device" not in names         # Accelerators off by default

        accel = build_koordlet(
            self._config(tmp_path, feature_gates="Accelerators=true")
        )
        assert "device" in {
            c.name for c in accel.metrics_advisor.collectors
        }

    def test_nri_mode_actuates_from_pleg(self, tmp_path):
        """--runtime-hooks-mode=nri: a pod cgroup dir appearing drives
        hook dispatch through the daemon's own PLEG."""
        from koordinator_tpu.apis.extension import QoSClass
        from koordinator_tpu.cmd.koordlet import build_koordlet
        from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
        from koordinator_tpu.koordlet.resourceexecutor.executor import (
            ensure_cgroup_dir,
        )
        from koordinator_tpu.koordlet.system.cgroup import (
            CPU_BVT_WARP_NS,
            SystemConfig,
        )
        from koordinator_tpu.manager.sloconfig import NodeSLOSpec

        cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"),
                           proc_root=str(tmp_path / "proc"))
        for d in ("kubepods", "kubepods/burstable"):
            ensure_cgroup_dir(d, cfg)
        daemon = build_koordlet(
            self._config(tmp_path, runtime_hooks_mode="nri")
        )
        assert daemon.pleg is not None and daemon.nri_server is not None
        slo = NodeSLOSpec()
        for tier in ("lsr", "ls", "be"):
            getattr(slo.resource_qos_strategy, tier).enable = True
        daemon.states_informer.set_node_slo(slo)
        pod = PodMeta("ls", "kubepods/burstable/podls", QoSClass.LS,
                      containers={"main": "kubepods/burstable/podls/main"})
        daemon.states_informer.set_pods([pod])
        ensure_cgroup_dir(pod.cgroup_dir, cfg)
        daemon.tick(now=100.0)
        assert daemon.nri_server.handled.get("RunPodSandbox") == 1
        assert CPU_BVT_WARP_NS.read(pod.cgroup_dir, cfg) == "2"

    def test_unknown_hooks_mode_rejected(self, tmp_path):
        import pytest

        from koordinator_tpu.cmd.koordlet import build_koordlet

        with pytest.raises(ValueError, match="sidecar"):
            build_koordlet(
                self._config(tmp_path, runtime_hooks_mode="sidecar")
            )


class TestManagerDescheduler:
    def test_manager_gates(self):
        m = build_manager(ManagerConfig())
        pod = PodSpec(name="x", requests={R.CPU: 100})
        mutated, violations = m.admit_pod(pod)
        assert violations == []
        from koordinator_tpu.features import FeatureGate, MANAGER_GATES

        gates = FeatureGate(dict(MANAGER_GATES.as_dict()))
        m2 = build_manager(
            ManagerConfig(feature_gates="PodMutatingWebhook=false"),
            gates=gates,
        )
        assert m2.mutating_webhook is None

    def test_descheduler_build(self):
        d = build_descheduler(DeschedulerConfig(high_cpu_percent=70))
        assert d.profiles[0].balance_plugins[0].args.node_pools[0].high_thresholds[
            R.CPU
        ] == 70


class TestBusWiredMains:
    """cmd mains construct real bus wiring (VERDICT r2: 'cmd mains are
    demos, not components')."""

    def _cluster_json(self, tmp_path):
        import json

        path = tmp_path / "cluster.json"
        path.write_text(json.dumps({
            "nodes": [{"name": "n0", "cpu": 16000, "memory": 32768},
                      {"name": "n1", "cpu": 16000, "memory": 32768}],
            "pods": [{"name": "a", "cpu": 2000, "memory": 1024},
                     {"name": "b", "cpu": 1000, "memory": 512},
                     {"name": "busy", "cpu": 4000, "memory": 2048,
                      "node": "n0"}],
        }))
        return str(path)

    def test_scheduler_main_schedules_seeded_cluster(self, tmp_path, capsys):
        from koordinator_tpu.cmd import scheduler as cmd_sched

        rc = cmd_sched.main(
            ["--once", "--cluster-json", self._cluster_json(tmp_path)]
        )
        assert rc == 0
        assert "2/2 placed" in capsys.readouterr().out

    def test_scheduler_main_sidecar_backend(self, tmp_path, capsys):
        from koordinator_tpu.cmd import scheduler as cmd_sched
        from koordinator_tpu.service.server import PlacementService

        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        try:
            rc = cmd_sched.main([
                "--once", "--cluster-json", self._cluster_json(tmp_path),
                "--placement-backend", "sidecar",
                "--solver-address", addr,
            ])
            assert rc == 0
            assert "2/2 placed" in capsys.readouterr().out
        finally:
            service.stop()

    def test_scheduler_main_sidecar_down_skips_round(self, tmp_path, capsys):
        from koordinator_tpu.cmd import scheduler as cmd_sched

        rc = cmd_sched.main([
            "--once", "--cluster-json", self._cluster_json(tmp_path),
            "--placement-backend", "sidecar",
            "--solver-address", str(tmp_path / "nothing.sock"),
        ])
        assert rc == 1
        assert "round skipped" in capsys.readouterr().out

    def test_manager_main_reconciles(self, tmp_path, capsys):
        from koordinator_tpu.cmd import manager as cmd_mgr

        rc = cmd_mgr.main(
            ["--once", "--cluster-json", self._cluster_json(tmp_path)]
        )
        assert rc == 0
        assert "2 nodes synced" in capsys.readouterr().out

    def test_descheduler_main_runs_cycle(self, tmp_path, capsys):
        from koordinator_tpu.cmd import descheduler as cmd_desch

        rc = cmd_desch.main(
            ["--once", "--cluster-json", self._cluster_json(tmp_path)]
        )
        assert rc == 0
        assert "descheduling cycle" in capsys.readouterr().out

    def test_runtimeproxy_main_once(self, tmp_path):
        """The 5th binary: serve one connection over UDS, intercept a
        hooked method, run a registered hook, reply with its response."""
        import json
        import socket
        import threading

        from koordinator_tpu.cmd import runtimeproxy as cmd_proxy
        from koordinator_tpu.koordlet.runtimehooks import (
            HookRegistry,
            RuntimeHookServer,
            Stage,
        )

        registry = HookRegistry()

        def set_shares(ctx):
            ctx.response.cpu_shares = 512

        registry.register(Stage.PRE_RUN_POD_SANDBOX, "t", "", set_shares)
        proxy = cmd_proxy.build_proxy(
            cmd_proxy.RuntimeProxyConfig(),
            hook_server=RuntimeHookServer(registry, executor=None),
        )
        sock_path = str(tmp_path / "proxy.sock")
        t = threading.Thread(
            target=cmd_proxy.serve,
            args=(proxy, sock_path),
            kwargs={"once": True, "log": lambda *_: None},
            daemon=True,
        )
        t.start()
        import time as _time

        for _ in range(100):
            if cmd_proxy.os.path.exists(sock_path):
                break
            _time.sleep(0.02)
        client = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        client.connect(sock_path)
        # unknown method: transparent pass-through
        client.sendall(b'{"method": "Version"}\n')
        f = client.makefile()
        out = json.loads(f.readline())
        assert out["backend"]["ok"] and out["hook"] is None
        # hooked method with a pod in the store
        from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta

        proxy.store.record_pod(PodMeta(
            "u1", "kubepods/podu1", containers={"main": "kubepods/podu1/main"}
        ))
        # documented frame: pod_uid at TOP level (no payload nesting)
        client.sendall(json.dumps(
            {"method": "RunPodSandbox", "pod_uid": "u1"}
        ).encode() + b"\n")
        out = json.loads(f.readline())
        assert out["hook"]["cpu_shares"] == 512
        # container-level method carries the container name
        registry.register(Stage.PRE_CREATE_CONTAINER, "t", "",
                          lambda ctx: setattr(ctx.response, "cpuset", "0-1"))
        client.sendall(json.dumps(
            {"method": "CreateContainer", "pod_uid": "u1",
             "container": "main"}
        ).encode() + b"\n")
        out = json.loads(f.readline())
        assert out["hook"]["cpuset"] == "0-1"
        client.close()
        t.join(timeout=5)

    def test_solver_main_once(self, tmp_path, capsys):
        from koordinator_tpu.cmd import solver as cmd_solver

        rc = cmd_solver.main(
            ["--once", "--listen", str(tmp_path / "s.sock")]
        )
        assert rc == 0
        assert "serving" in capsys.readouterr().out
