"""NRI-mode runtimehooks (VERDICT round-2 ask 8): event-driven hook
invocation from the PLEG stream, distinct from the proxy and reconciler
modes.

Oracle: pkg/koordlet/runtimehooks/nri/server.go — event subscription,
per-event hook dispatch with standalone application, Synchronize on
registration, failure policy / disabled stages.
"""

import json

from koordinator_tpu.apis.extension import ANNOTATION_RESOURCE_STATUS, QoSClass
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
from koordinator_tpu.koordlet.pleg import PLEG
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.resourceexecutor.executor import ensure_cgroup_dir
from koordinator_tpu.koordlet.runtimehooks import NriServer, RuntimeHooks
from koordinator_tpu.koordlet.runtimehooks.nri import ALL_EVENTS
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.system.cgroup import (
    CPU_BVT_WARP_NS,
    CPU_SET,
    SystemConfig,
)
from koordinator_tpu.manager.sloconfig import NodeSLOSpec


def make_env(tmp_path):
    cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"),
                       proc_root=str(tmp_path / "proc"))
    for d in ("kubepods", "kubepods/burstable", "kubepods/besteffort"):
        ensure_cgroup_dir(d, cfg)
    informer = StatesInformer()
    executor = ResourceUpdateExecutor(cfg, auditor=Auditor())
    hooks = RuntimeHooks(informer, executor)
    # arm the bvt rule (groupidentity defaults are disabled)
    slo = NodeSLOSpec()
    slo.resource_qos_strategy.lsr.enable = True
    slo.resource_qos_strategy.ls.enable = True
    slo.resource_qos_strategy.be.enable = True
    informer.set_node_slo(slo)
    return cfg, informer, hooks


def lsr_pod():
    return PodMeta(
        "lsr", "kubepods/podlsr", QoSClass.LSR,
        containers={"main": "kubepods/podlsr/main"},
        annotations={ANNOTATION_RESOURCE_STATUS: json.dumps(
            {"cpuset": [0, 1]})},
    )


def ls_pod():
    return PodMeta(
        "ls", "kubepods/burstable/podls", QoSClass.LS,
        containers={"main": "kubepods/burstable/podls/main"},
    )


class TestDispatch:
    def test_pod_added_event_lands_bvt_in_cgroupfs(self, tmp_path):
        """A pod dir appearing in the PLEG stream triggers the sandbox
        stage and the groupidentity bvt value lands in the fake
        cgroupfs — no reconciler pass involved."""
        cfg, informer, hooks = make_env(tmp_path)
        pleg = PLEG(cfg)
        informer.set_pods([])        # informer in sync before attach
        nri = hooks.attach_nri(pleg)
        pleg.poll()                  # primer

        pod = ls_pod()
        informer.set_pods([pod])     # kubelet knows the pod...
        ensure_cgroup_dir(pod.cgroup_dir, cfg)  # ...then the dir appears
        pleg.poll()
        assert nri.handled.get("RunPodSandbox") == 1
        assert CPU_BVT_WARP_NS.read(pod.cgroup_dir, cfg) == "2"

    def test_container_added_pins_cpuset(self, tmp_path):
        cfg, informer, hooks = make_env(tmp_path)
        pleg = PLEG(cfg)
        informer.set_pods([])
        nri = hooks.attach_nri(pleg)
        pleg.poll()

        pod = lsr_pod()
        informer.set_pods([pod])
        ensure_cgroup_dir(pod.cgroup_dir, cfg)
        pleg.poll()                  # pod event
        ensure_cgroup_dir(pod.containers["main"], cfg)
        pleg.poll()                  # container event
        assert nri.handled.get("CreateContainer") == 1
        assert CPU_SET.read(pod.containers["main"], cfg) == "0,1"

    def test_unknown_dir_dropped_not_crashed(self, tmp_path):
        cfg, informer, hooks = make_env(tmp_path)
        pleg = PLEG(cfg)
        informer.set_pods([])
        nri = hooks.attach_nri(pleg)
        pleg.poll()
        ensure_cgroup_dir("kubepods/podghost", cfg)
        pleg.poll()
        assert nri.dropped == 1
        assert not nri.handled

    def test_event_subscription_filters(self, tmp_path):
        cfg, informer, hooks = make_env(tmp_path)
        pleg = PLEG(cfg)
        pod = ls_pod()
        informer.set_pods([pod])
        nri = hooks.attach_nri(pleg, events={"CreateContainer"})
        pleg.poll()
        ensure_cgroup_dir(pod.cgroup_dir, cfg)
        assert pleg.poll()           # POD_ADDED fired on the stream...
        assert not nri.handled       # ...but not subscribed

    def test_disabled_stage_skipped(self, tmp_path):
        cfg, informer, hooks = make_env(tmp_path)
        pleg = PLEG(cfg)
        pod = ls_pod()
        informer.set_pods([pod])
        nri = hooks.attach_nri(pleg, disable_stages={"PreRunPodSandbox"})
        pleg.poll()
        ensure_cgroup_dir(pod.cgroup_dir, cfg)
        pleg.poll()
        assert not nri.handled


class TestStopEvents:
    def test_stop_hooks_run_after_informer_dropped_pod(self, tmp_path):
        """Deletion ordering in practice: the informer drops the pod
        BEFORE the runtime tears down the cgroup dir. The stop stages
        must still resolve through the retained index (code-review
        regression)."""
        import shutil
        import os

        cfg, informer, hooks = make_env(tmp_path)
        pleg = PLEG(cfg)
        pod = ls_pod()
        informer.set_pods([pod])
        nri = hooks.attach_nri(pleg)
        ensure_cgroup_dir(pod.cgroup_dir, cfg)
        ensure_cgroup_dir(pod.containers["main"], cfg)
        pleg.poll()

        informer.set_pods([])        # informer drops the pod first...
        shutil.rmtree(os.path.join(cfg.cgroup_root, "cpu",
                                   pod.cgroup_dir))  # ...then the dir goes
        pleg.poll()
        assert nri.handled.get("StopPodSandbox") == 1
        assert nri.handled.get("StopContainer") == 1
        assert nri.dropped == 0

    def test_unknown_event_name_rejected(self, tmp_path):
        import pytest

        cfg, informer, hooks = make_env(tmp_path)
        with pytest.raises(ValueError, match="CreateContainers"):
            hooks.attach_nri(PLEG(cfg), events={"CreateContainers"})

    def test_unknown_stage_name_rejected(self, tmp_path):
        import pytest

        cfg, informer, hooks = make_env(tmp_path)
        with pytest.raises(ValueError, match="PreRunPodsandbox"):
            hooks.attach_nri(PLEG(cfg),
                             disable_stages={"PreRunPodsandbox"})


class TestSynchronize:
    def test_attach_synchronizes_existing_pods(self, tmp_path):
        """A restarted koordlet converges immediately: attach() re-runs
        hooks over every running pod (server.go Synchronize)."""
        cfg, informer, hooks = make_env(tmp_path)
        pod = lsr_pod()
        ensure_cgroup_dir(pod.cgroup_dir, cfg)
        ensure_cgroup_dir(pod.containers["main"], cfg)
        informer.set_pods([pod])
        pleg = PLEG(cfg)
        nri = NriServer(hooks.server, informer)
        nri.attach(pleg)             # attach runs the Synchronize pass
        assert CPU_SET.read(pod.containers["main"], cfg) == "0,1"
        assert CPU_BVT_WARP_NS.read(pod.cgroup_dir, cfg) == "2"

    def test_all_events_constant_matches_names(self):
        assert ALL_EVENTS == {"RunPodSandbox", "StopPodSandbox",
                              "CreateContainer", "StopContainer"}
