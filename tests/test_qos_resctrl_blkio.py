"""resctrl/RDT, blkio, cgreconcile strategies + native CPI perf module
(VERDICT round-1 item 8).

Reference: pkg/koordlet/util/system/resctrl.go (mask math :576-605),
qosmanager/plugins/resctrl/resctrl_reconcile.go, blkio_reconcile.go,
cgreconcile/cgroup_reconcile.go, util/perf_group/perf_group_linux.go.
"""

import os

import pytest

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metriccache import MetricCache, MetricKind
from koordinator_tpu.koordlet.metricsadvisor.framework import (
    CollectorContext,
    PodMeta,
)
from koordinator_tpu.koordlet.metricsadvisor.performance import (
    PerformanceCollector,
)
from koordinator_tpu.koordlet.qosmanager import QoSContext
from koordinator_tpu.koordlet.qosmanager.blkio import BlkIOReconcile
from koordinator_tpu.koordlet.qosmanager.cgreconcile import (
    CgroupResourcesReconcile,
)
from koordinator_tpu.koordlet.qosmanager.resctrl import (
    ResctrlReconcile,
    pod_resctrl_group,
)
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.resourceexecutor.executor import ensure_cgroup_dir
from koordinator_tpu.koordlet.system.cgroup import SystemConfig
from koordinator_tpu.koordlet.system.resctrl import (
    ResctrlFS,
    ResctrlSchemata,
    calculate_cat_l3_mask,
    calculate_mba,
)
from koordinator_tpu.manager.sloconfig import (
    BlockCfg,
    MemoryQOS,
    NodeSLOSpec,
    QoSConfig,
    ResctrlQOS,
    ResourceQOSStrategy,
)
from koordinator_tpu.native import PerfGroup, PerfUnavailable


class StaticPods:
    def __init__(self, pods):
        self.pods = pods

    def running_pods(self):
        return self.pods


def make_ctx(tmp_path, pods, slo=None, cap_mem=16384):
    cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"),
                       proc_root=str(tmp_path / "proc"))
    for d in ("kubepods/besteffort", "kubepods/burstable"):
        ensure_cgroup_dir(d, cfg)
    for p in pods:
        ensure_cgroup_dir(p.cgroup_dir, cfg)
        for c in p.containers.values():
            ensure_cgroup_dir(c, cfg)
    return QoSContext(
        metric_cache=MetricCache(),
        executor=ResourceUpdateExecutor(cfg, auditor=Auditor()),
        pod_provider=StaticPods(pods),
        system_config=cfg,
        node_slo=slo or NodeSLOSpec(),
        node_capacity_mem_mib=cap_mem,
    )


class TestMaskMath:
    def test_reference_examples(self):
        # resctrl.go:594-600 documented cases
        assert calculate_cat_l3_mask(0x3FF, 10, 80) == "fe"
        assert calculate_cat_l3_mask(0x7FF, 10, 50) == "3c"
        assert calculate_cat_l3_mask(0x7FF, 0, 30) == "f"
        assert calculate_cat_l3_mask(0xFF, 0, 100) == "ff"

    def test_invalid(self):
        with pytest.raises(ValueError, match="illegal cbm"):
            calculate_cat_l3_mask(0x5, 0, 100)  # non-contiguous
        with pytest.raises(ValueError, match="percent"):
            calculate_cat_l3_mask(0xFF, 50, 50)

    def test_mba(self):
        assert calculate_mba(100) == "100"
        assert calculate_mba(85) == "90"   # intel rounds up to 10s
        assert calculate_mba(80) == "80"
        assert calculate_mba(100, vendor="amd") == "2048000"
        assert calculate_mba(50, vendor="amd") == str(25 * 1024 // 2)

    def test_schemata_roundtrip(self):
        s = ResctrlSchemata(l3={0: "ff", 1: "ff"}, mb={0: "100"})
        parsed = ResctrlSchemata.parse(s.render())
        assert parsed.l3 == {0: "ff", 1: "ff"}
        assert parsed.mb == {0: "100"}


def fake_resctrl(tmp_path, cbm="7ff", cache_ids=(0, 1)):
    root = tmp_path / "resctrl"
    (root / "info" / "L3").mkdir(parents=True)
    (root / "info" / "L3" / "cbm_mask").write_text(cbm + "\n")
    l3 = ";".join(f"{i}={cbm}" for i in cache_ids)
    mb = ";".join(f"{i}=100" for i in cache_ids)
    (root / "schemata").write_text(f"L3:{l3}\nMB:{mb}\n")
    cfg = SystemConfig()
    fs = ResctrlFS(cfg)
    cfg.resctrl_root = str(root)  # type: ignore[attr-defined]
    return fs


class TestResctrlReconcile:
    def test_group_mapping(self):
        assert pod_resctrl_group(QoSClass.LSE) == "LSR"
        assert pod_resctrl_group(QoSClass.LSR) == "LSR"
        assert pod_resctrl_group(QoSClass.LS) == "LS"
        assert pod_resctrl_group(QoSClass.BE) == "BE"
        assert pod_resctrl_group(QoSClass.NONE) == ""

    def test_schemata_and_tasks(self, tmp_path):
        fs = fake_resctrl(tmp_path)
        pods = [
            PodMeta(uid="be1", cgroup_dir="kubepods/besteffort/podbe1",
                    qos=QoSClass.BE),
            PodMeta(uid="ls1", cgroup_dir="kubepods/burstable/podls1",
                    qos=QoSClass.LS),
        ]
        ctx = make_ctx(tmp_path, pods)
        # give the BE pod tasks in its fake cgroup
        procs = os.path.join(ctx.system_config.cgroup_root, "cpu",
                             pods[0].cgroup_dir, "cgroup.procs")
        with open(procs, "w") as f:
            f.write("101\n102\n")
        strategy = ResctrlReconcile(fs=fs)
        assert strategy.enabled(ctx)
        strategy.execute(ctx, now=1.0)

        # BE group: default strategy caps LLC to 0-30% -> mask of 0x7ff
        be = fs.read_schemata("BE")
        assert be.l3 == {0: "f", 1: "f"}
        assert be.mb == {0: "100", 1: "100"}
        # LS keeps the full mask
        ls = fs.read_schemata("LS")
        assert ls.l3 == {0: "7ff", 1: "7ff"}
        # BE pod tasks moved into the BE group
        assert fs.read_tasks("BE") == [101, 102]

    def test_idempotent_no_rewrite(self, tmp_path):
        fs = fake_resctrl(tmp_path)
        ctx = make_ctx(tmp_path, [])
        strategy = ResctrlReconcile(fs=fs)
        strategy.execute(ctx, now=1.0)
        first = fs.read_schemata("BE").render()
        assert not fs.write_schemata_line(
            "BE", "L3:0=f;1=f"
        )  # unchanged -> no write
        strategy.execute(ctx, now=2.0)
        assert fs.read_schemata("BE").render() == first


class TestCgReconcile:
    def test_memory_qos_written(self, tmp_path):
        slo = NodeSLOSpec(
            resource_qos_strategy=ResourceQOSStrategy(
                be=QoSConfig(
                    enable=True,
                    memory=MemoryQOS(min_limit_percent=50,
                                     low_limit_percent=80,
                                     throttling_percent=90),
                    resctrl=ResctrlQOS(cat_range_end_percent=30),
                )
            )
        )
        pod = PodMeta(
            uid="be1", cgroup_dir="kubepods/besteffort/podbe1",
            qos=QoSClass.BE, memory_request_mib=1024, memory_limit_mib=2048,
            containers={"c0": "kubepods/besteffort/podbe1/c0"},
        )
        ctx = make_ctx(tmp_path, [pod], slo=slo)
        strategy = CgroupResourcesReconcile()
        assert strategy.enabled(ctx)
        strategy.execute(ctx, now=1.0)

        mib = 1024 * 1024
        root = ctx.system_config.cgroup_root
        read = lambda d, f: open(os.path.join(root, "memory", d, f)).read()
        assert read(pod.cgroup_dir, "memory.min") == str(1024 * mib // 2)
        assert read(pod.cgroup_dir, "memory.low") == str(1024 * mib * 80 // 100)
        assert read("kubepods/besteffort/podbe1/c0", "memory.min") == str(
            1024 * mib // 2
        )
        assert read("kubepods/besteffort/podbe1/c0", "memory.high") == str(
            2048 * mib * 90 // 100
        )
        # tier rollup
        assert read("kubepods/besteffort", "memory.min") == str(1024 * mib // 2)

    def test_disabled_without_config(self, tmp_path):
        ctx = make_ctx(tmp_path, [])
        assert not CgroupResourcesReconcile().enabled(ctx)


class TestBlkIO:
    def test_throttles_written_v1(self, tmp_path):
        slo = NodeSLOSpec(
            resource_qos_strategy=ResourceQOSStrategy(
                be=QoSConfig(
                    enable=True,
                    blkio=[BlockCfg(device="253:0", read_bps=10485760,
                                    write_iops=200)],
                )
            )
        )
        pod = PodMeta(uid="be1", cgroup_dir="kubepods/besteffort/podbe1",
                      qos=QoSClass.BE)
        ctx = make_ctx(tmp_path, [pod], slo=slo)
        strategy = BlkIOReconcile()
        assert strategy.enabled(ctx)
        strategy.execute(ctx, now=1.0)

        root = ctx.system_config.cgroup_root
        path = os.path.join(root, "blkio", "kubepods/besteffort",
                            "blkio.throttle.read_bps_device")
        assert open(path).read() == "253:0 10485760"
        pod_path = os.path.join(root, "blkio", pod.cgroup_dir,
                                "blkio.throttle.write_iops_device")
        assert open(pod_path).read() == "253:0 200"

    def test_io_max_packing_v2(self, tmp_path):
        from koordinator_tpu.koordlet.system.cgroup import BLKIO_READ_BPS

        packed = BLKIO_READ_BPS.v2_encode("253:0 1000", "253:0 wbps=2000")
        assert packed == "253:0 rbps=1000 wbps=2000"
        cleared = BLKIO_READ_BPS.v2_encode("253:0 0", packed)
        assert cleared == "253:0 rbps=max wbps=2000"


class TestNativePerf:
    def test_fake_counters_and_cpi(self):
        g = PerfGroup.fake(3000, 1000)
        c1, i1 = g.read()
        c2, i2 = g.read()
        assert (c2 - c1, i2 - i1) == (3000, 1000)
        g.close()

    def test_performance_collector_appends_cpi(self, tmp_path):
        cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"))
        mc = MetricCache()
        pod = PodMeta(uid="p1", cgroup_dir="kubepods/podp1",
                      qos=QoSClass.LS,
                      containers={"c0": "kubepods/podp1/c0"})
        ctx = CollectorContext(metric_cache=mc, system_config=cfg,
                               pod_provider=StaticPods([pod]))
        collector = PerformanceCollector(
            source_factory=lambda cdir: PerfGroup.fake(2500, 1000)
        )
        collector.setup(ctx)
        assert collector.enabled()
        collector.collect(now=1.0)   # primer
        collector.collect(now=2.0)
        ts, vs = mc.query(MetricKind.CONTAINER_CPI,
                          {"pod": "p1", "container": "c0"})
        assert len(vs) == 1
        assert vs[0] == pytest.approx(2.5)

    def test_perf_unavailable_disables_collector(self, tmp_path):
        """perf_event_open rejection (host-level) disables the collector;
        a missing container cgroup (transient) merely skips the tick."""
        cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"))
        pod = PodMeta(uid="p1", cgroup_dir="kubepods/podp1",
                      qos=QoSClass.LS,
                      containers={"c0": "kubepods/podp1/c0"})
        ctx = CollectorContext(metric_cache=MetricCache(),
                               system_config=cfg,
                               pod_provider=StaticPods([pod]))

        def no_perf(cdir):
            raise PerfUnavailable("perf_event_paranoid")

        collector = PerformanceCollector(source_factory=no_perf)
        collector.setup(ctx)
        collector.collect(now=1.0)
        assert not collector.enabled()

        def vanished(cdir):
            raise FileNotFoundError(cdir)

        transient = PerformanceCollector(source_factory=vanished)
        transient.setup(ctx)
        transient.collect(now=1.0)
        assert transient.enabled()  # retried next tick


class TestBlkIOStaleRemoval:
    def test_deleted_block_cfg_clears_throttle(self, tmp_path):
        slo = NodeSLOSpec(
            resource_qos_strategy=ResourceQOSStrategy(
                be=QoSConfig(enable=True,
                             blkio=[BlockCfg(device="253:0", read_bps=1000)])
            )
        )
        ctx = make_ctx(tmp_path, [], slo=slo)
        strategy = BlkIOReconcile()
        strategy.execute(ctx, now=1.0)
        root = ctx.system_config.cgroup_root
        path = os.path.join(root, "blkio", "kubepods/besteffort",
                            "blkio.throttle.read_bps_device")
        assert open(path).read() == "253:0 1000"

        # config removed: the next pass writes the remover and the
        # strategy stays enabled for that pass
        ctx.node_slo.resource_qos_strategy.be.blkio = []
        assert strategy.enabled(ctx)
        strategy.execute(ctx, now=2.0)
        assert open(path).read() == "253:0 0"


def test_vendor_detection(tmp_path):
    from koordinator_tpu.koordlet.system.resctrl import detect_vendor

    (tmp_path / "cpuinfo").write_text("vendor_id\t: AuthenticAMD\n")
    assert detect_vendor(str(tmp_path)) == "amd"
    (tmp_path / "cpuinfo").write_text("vendor_id\t: GenuineIntel\n")
    assert detect_vendor(str(tmp_path)) == "intel"
    assert detect_vendor(str(tmp_path / "missing")) == "intel"
