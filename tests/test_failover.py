"""Failure-domain units: the FailoverSolver state machine, the
SolverSupervisor restart/breaker logic, and run_loop's outage
accounting (ISSUE 4 tentpole §1-2 + satellite 1)."""

import numpy as np
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName as R
from koordinator_tpu.cmd.scheduler import (
    SchedulerConfig,
    build_scheduler,
    run_loop,
)
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.service.client import RemoteSolver, SolverUnavailable
from koordinator_tpu.service.failover import FailoverSolver
from koordinator_tpu.service.server import PlacementService
from koordinator_tpu.service.supervisor import (
    RestartBreaker,
    SolverSupervisor,
    connection_probe,
)


def _wire_problem(n_nodes=4, n_pods=5):
    import jax.numpy as jnp

    from koordinator_tpu.ops.binpack import (
        NodeState,
        PodBatch,
        ScoreParams,
        SolverConfig,
    )

    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, R.CPU] = 16000
    alloc[:, R.MEMORY] = 32768
    state = NodeState(
        alloc=jnp.asarray(alloc),
        used_req=jnp.zeros_like(jnp.asarray(alloc)),
        usage=jnp.zeros_like(jnp.asarray(alloc)),
        prod_usage=jnp.zeros_like(jnp.asarray(alloc)),
        est_extra=jnp.zeros_like(jnp.asarray(alloc)),
        prod_base=jnp.zeros_like(jnp.asarray(alloc)),
        metric_fresh=jnp.ones(n_nodes, bool),
        schedulable=jnp.ones(n_nodes, bool),
    )
    req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = 1000
    batch = PodBatch.build(
        req=jnp.asarray(req), est=jnp.asarray((req * 85) // 100),
        is_prod=jnp.zeros(n_pods, bool),
        is_daemonset=jnp.zeros(n_pods, bool),
    )
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[R.CPU] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[R.CPU] = 65
    params = ScoreParams(
        weights=jnp.asarray(weights),
        thresholds=jnp.asarray(thresholds),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, np.int32),
    )
    return state, batch, params, SolverConfig()


def _fast_remote(addr, **kw):
    kw.setdefault("retry_total_s", 0.05)
    kw.setdefault("backoff_base_s", 0.01)
    kw.setdefault("backoff_cap_s", 0.02)
    return RemoteSolver(addr, **kw)


class TestFailoverSolver:
    def test_outage_falls_back_then_flips_degraded(self, tmp_path):
        """No skipped solves: every call against a dead sidecar is
        answered locally (bit-identical to the in-process scan), and
        the K-th consecutive failure flips the machine to degraded so
        later solves stop paying the remote timeout."""
        from koordinator_tpu.ops.binpack import solve_batch

        backend = FailoverSolver(
            _fast_remote(str(tmp_path / "nowhere.sock")),
            failure_threshold=2, recovery_probes=2,
        )
        args = _wire_problem()
        want = solve_batch(*args)
        r1 = backend.solve_result(*args)
        assert backend.last_mode == "local-fallback"
        assert not backend.status()["degraded"]  # 1 < threshold
        r2 = backend.solve_result(*args)
        assert backend.status()["degraded"]  # flipped on the 2nd
        r3 = backend.solve_result(*args)
        assert backend.last_mode == "local-degraded"
        for r in (r1, r2, r3):
            np.testing.assert_array_equal(
                np.asarray(r.assign), np.asarray(want.assign)
            )
            np.testing.assert_array_equal(
                np.asarray(r.node_state.used_req),
                np.asarray(want.node_state.used_req),
            )
        assert backend.status()["flips_to_degraded"] == 1
        assert backend.status()["local_solves"] == 3

    def test_hysteresis_and_epoch_reset_on_flip_back(self, tmp_path):
        """M consecutive healthy probes flip back (one blip resets the
        count); flip-back drops the remote delta base and fires the
        on_flip_back hook so the next request re-establishes."""
        addr = str(tmp_path / "solver.sock")
        probes = {"ok": False}
        flip_back_calls = []
        remote = _fast_remote(addr)
        backend = FailoverSolver(
            remote, failure_threshold=1, recovery_probes=2,
            probe_fn=lambda: probes["ok"],
            on_flip_back=lambda: flip_back_calls.append(1),
        )
        args = _wire_problem()
        backend.solve_result(*args)  # dead sidecar: flips immediately
        assert backend.status()["degraded"]

        # unhealthy probes keep it degraded
        assert not backend.maybe_recover()
        # one healthy, one blip: the count resets (hysteresis)
        probes["ok"] = True
        assert not backend.maybe_recover()
        probes["ok"] = False
        assert not backend.maybe_recover()
        assert backend.status()["healthy_probes"] == 0
        assert flip_back_calls == []

        # now the sidecar really is back
        service = PlacementService(addr)
        service.start()
        try:
            # fake a stale established base: flip-back must clear it
            remote._server_epoch = 7
            probes["ok"] = True
            assert not backend.maybe_recover()  # 1/2
            assert backend.maybe_recover()      # 2/2: flips back
            assert not backend.status()["degraded"]
            assert flip_back_calls == [1]
            assert remote._server_epoch is None  # epoch reset
            result = backend.solve_result(*args)
            assert backend.last_mode == "remote"
            assert (np.asarray(result.assign) >= 0).all()
            assert backend.status()["flips_to_remote"] == 1
        finally:
            service.stop()
            backend.close()

    def test_overloaded_past_budget_falls_back_local(self):
        """A sidecar that sheds this caller past its retry budget is an
        outage from the scheduler's seat: the terminal SolverOverloaded
        must be answered locally, not escape and crash the round loop
        (review finding on the first cut of this layer)."""
        from koordinator_tpu.service.client import SolverOverloaded

        class _SheddingRemote:
            address = "/nowhere"
            supports_staging_delta = False

            def solve_result(self, *a, **kw):
                raise SolverOverloaded("overloaded: scripted")

        backend = FailoverSolver(
            _SheddingRemote(), failure_threshold=1, recovery_probes=2,
            probe_fn=lambda: False,
        )
        result = backend.solve_result(*_wire_problem())
        assert backend.last_mode == "local-fallback"
        assert backend.status()["degraded"]
        assert (np.asarray(result.assign) >= 0).all()

    def test_run_loop_skips_on_overloaded(self):
        """Without failover, a terminal overloaded shed skips the round
        (counted under its own reason) instead of killing the loop."""
        from koordinator_tpu.metrics.components import ROUNDS_SKIPPED
        from koordinator_tpu.service.client import SolverOverloaded

        class _SheddedScheduler:
            def schedule_pending(self):
                raise SolverOverloaded("overloaded: queue full")

        before = ROUNDS_SKIPPED.value({"reason": "solver-overloaded"})
        rc = run_loop(
            _SheddedScheduler(),
            SchedulerConfig(schedule_interval_seconds=0.0),
            log=lambda *_: None, max_rounds=2,
        )
        assert rc == 2
        after = ROUNDS_SKIPPED.value({"reason": "solver-overloaded"})
        assert after - before == 2

    def test_build_scheduler_wires_failover(self, tmp_path):
        cfg = SchedulerConfig(
            placement_backend="sidecar",
            solver_address=str(tmp_path / "none.sock"),
            solver_failover=True,
        )
        scheduler = build_scheduler(cfg)
        backend = scheduler.model.backend
        assert isinstance(backend, FailoverSolver)
        # flip-back is wired to the model's full-restage reset
        assert backend.on_flip_back == scheduler.model.reset_staging


class TestRunLoopOutageAccounting:
    def test_skipped_rounds_counted_and_logged(self):
        """Satellite 1: the skip is no longer silent — counted in the
        metric and carried in the log line."""
        from koordinator_tpu.metrics.components import ROUNDS_SKIPPED

        class _DeadSolverScheduler:
            def schedule_pending(self):
                raise SolverUnavailable("sidecar gone")

        lines = []
        before = ROUNDS_SKIPPED.value({"reason": "solver-unavailable"})
        rc = run_loop(
            _DeadSolverScheduler(),
            SchedulerConfig(schedule_interval_seconds=0.0),
            log=lines.append, max_rounds=3,
        )
        assert rc == 3  # three attempted rounds, three skips
        after = ROUNDS_SKIPPED.value({"reason": "solver-unavailable"})
        assert after - before == 3
        assert "3 skipped so far" in lines[-1]

    def test_failover_means_zero_skipped_rounds(self, tmp_path):
        """Satellite 1 regression: with failover enabled the loop never
        skips a round even though the sidecar is down for its whole
        life."""
        from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
        from koordinator_tpu.metrics.components import ROUNDS_SKIPPED
        from koordinator_tpu.scheduler import Scheduler

        backend = FailoverSolver(
            _fast_remote(str(tmp_path / "nowhere.sock")),
            failure_threshold=1, recovery_probes=2,
        )
        model = PlacementModel(backend=backend, use_pallas=False)
        backend.on_flip_back = model.reset_staging
        scheduler = Scheduler(model=model)
        scheduler.add_node(NodeSpec(
            name="n0", allocatable={R.CPU: 16000, R.MEMORY: 32768}))
        scheduler.update_node_metric(NodeMetric(
            node_name="n0", node_usage={}, update_time=1.0))
        pod = PodSpec(name="p0", requests={R.CPU: 1000})
        scheduler.add_pod(pod)

        before = ROUNDS_SKIPPED.value({"reason": "solver-unavailable"})
        rc = run_loop(
            scheduler, SchedulerConfig(schedule_interval_seconds=0.0),
            log=lambda *_: None, max_rounds=3,
        )
        assert rc == 0  # zero skipped rounds
        after = ROUNDS_SKIPPED.value({"reason": "solver-unavailable"})
        assert after - before == 0
        assert scheduler.cache.pods[pod.uid].node_name == "n0"
        assert backend.status()["degraded"]  # it really was an outage


class _FakeProc:
    def __init__(self):
        self.returncode = None
        self.killed = 0
        self.pid = 4242

    def poll(self):
        return self.returncode

    def kill(self):
        self.killed += 1
        self.returncode = -9


class TestSolverSupervisor:
    def _supervisor(self, spawned, probe, clock=None, **kw):
        def spawn():
            proc = _FakeProc()
            spawned.append(proc)
            return proc

        kw.setdefault("probe_interval_s", 0.01)
        kw.setdefault("backoff_base_s", 0.0)
        kw.setdefault("backoff_cap_s", 0.0)
        sup = SolverSupervisor(
            ("127.0.0.1", 1), spawn_fn=spawn, probe_fn=probe,
            sleep=lambda _s: None,
            **({"clock": clock} if clock else {}), **kw,
        )
        return sup

    def test_crash_detected_and_restarted(self):
        spawned = []
        sup = self._supervisor(spawned, probe=lambda: True)
        sup.start(wait_ready=True, monitor=False)
        try:
            assert sup.check_once() == "running"
            spawned[-1].returncode = 1  # child crashed
            assert sup.check_once() == "restarted"
            assert sup.restarts_total == 1
            assert sup.last_exit_code == 1
            assert len(spawned) == 2
            assert sup.check_once() == "running"
        finally:
            sup.stop()

    def test_hung_child_killed_after_probe_threshold(self):
        spawned = []
        alive = {"ok": True}
        sup = self._supervisor(
            spawned, probe=lambda: alive["ok"],
            probe_failure_threshold=3,
        )
        sup.start(wait_ready=True, monitor=False)
        try:
            alive["ok"] = False  # process alive, socket unreachable
            assert sup.check_once() == "probe-failed"
            assert sup.check_once() == "probe-failed"
            assert sup.check_once() == "restarted"  # 3rd failure: hung
            assert spawned[0].killed == 1
            assert sup.restarts_total == 1
        finally:
            sup.stop()

    def test_fresh_spawn_gets_ready_grace_not_hung(self):
        """A respawned child paying its cold start (real koord-solver:
        a multi-second JAX import) must not be declared hung by failed
        probes — that was an infanticide loop where every respawn was
        killed before it ever served. Failed probes only count once the
        child has served, or its ready grace expired."""
        now = [0.0]
        spawned = []
        alive = {"ok": True}
        sup = self._supervisor(
            spawned, probe=lambda: alive["ok"], clock=lambda: now[0],
            probe_failure_threshold=3, ready_timeout_s=60.0,
        )
        sup.start(wait_ready=True, monitor=False)
        try:
            # crash -> respawn; the new child is "cold" (probe fails)
            alive["ok"] = False
            spawned[-1].returncode = 1
            assert sup.check_once() == "restarted"
            for _ in range(10):  # way past probe_failure_threshold
                assert sup.check_once() == "starting"
            assert len(spawned) == 2  # never killed while starting
            # the child comes up: normal running state
            alive["ok"] = True
            assert sup.check_once() == "running"
            # ...and from then on failures DO count toward hung
            alive["ok"] = False
            assert sup.check_once() == "probe-failed"
            assert sup.check_once() == "probe-failed"
            assert sup.check_once() == "restarted"
            # a child that never comes up is hung once the grace ends
            now[0] = 100.0  # past ready_timeout_s since the respawn
            assert sup.check_once() == "probe-failed"
        finally:
            sup.stop()

    def test_restart_storm_opens_breaker_then_half_open(self):
        now = [0.0]
        spawned = []
        sup = self._supervisor(
            spawned, probe=lambda: False, clock=lambda: now[0],
            breaker=RestartBreaker(
                threshold=3, window_s=60.0, cooldown_s=30.0,
                clock=lambda: now[0],
            ),
        )
        sup.start(wait_ready=False, monitor=False)
        try:
            # children that die on arrival: every check restarts
            for i in range(3):
                spawned[-1].returncode = 1
                assert sup.check_once() == "restarted", i
            # 3 restarts in the window: the breaker is open
            spawned[-1].returncode = 1
            assert sup.check_once() == "breaker-open"
            assert sup.status()["breaker"]["open"]
            assert len(spawned) == 4  # no respawn while open
            # cooldown elapsed: ONE half-open respawn is allowed
            now[0] = 31.0
            assert sup.check_once() == "restarted"
            spawned[-1].returncode = 1
            assert sup.check_once() == "breaker-open"
        finally:
            sup.stop()

    def test_connection_probe_against_real_service(self, tmp_path):
        addr = str(tmp_path / "probe.sock")
        assert not connection_probe(addr, timeout_s=0.2)
        service = PlacementService(addr)
        service.start()
        try:
            assert connection_probe(addr, timeout_s=0.5)
        finally:
            service.stop()
        assert not connection_probe(addr, timeout_s=0.2)
