"""Sharded staging + the 2-D mesh (ISSUE 10, docs/DESIGN.md §19).

The node axis of the staged world splits over the mesh's ``nodes``
axis and stays resident as a live NamedSharding'd generation: a full
stage pads to the per-shard bucket and splits ONCE, every later churn
tick scatters only the dirty rows into their owning shard. The
pod-batch (``pods``) axis shards stacked independent lanes. Both must
be invisible in results: sharded delta churn == single-device full
restage bit-for-bit, every lane == its solo single-device solve.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    NodeSpec,
    PodSpec,
)
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.ops.binpack import (
    STAGED_NODE_FIELDS,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.parallel.mesh import (
    POD_AXIS,
    make_mesh2d,
    mesh_axis_size,
    node_shard_count,
    node_sharding,
    lane_sharding,
    shard_lane_solver,
    shard_node_bucket,
    stack_pod_lanes,
)
from koordinator_tpu.state.cluster import (
    ClusterDeltaTracker,
    lower_nodes,
    pad_node_rows,
)

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY


# -- harness -----------------------------------------------------------------
# the world/tick generators are the shared ones bench legs 9/14 use
# (koordinator_tpu.testing churn_world/churn_tick_events) — one churn
# protocol, no bench-vs-test drift

def build_world(n_nodes, with_tracker, seed=42, assigned_per_node=2):
    from koordinator_tpu.testing import churn_world

    return churn_world(
        n_nodes, assigned_per_node=assigned_per_node, seed=seed,
        with_tracker=with_tracker,
    )


def churn(model, snap, tracker, ticks, dirty=11, pending=16, seed=7,
          structure_tick=None):
    """Seeded churn: per tick, metric refreshes (+ an optional node-ADD
    structure event), a pending wave, binds folded back. Returns the
    per-tick placement logs and the final snapshot."""
    from koordinator_tpu.testing import (
        churn_tick_events,
        fold_churn_binds,
    )

    rng = np.random.default_rng(seed)
    log = []
    for t in range(ticks):
        now = 20.0 + t
        if structure_tick is not None and t == structure_tick:
            name = f"extra{t}"
            snap.nodes.append(
                NodeSpec(name=name, allocatable={CPU: 64000, MEM: 131072})
            )
            snap.node_metrics[name] = NodeMetric(
                node_name=name,
                node_usage={CPU: 1000, MEM: 1024}, update_time=now,
            )
            if tracker is not None:
                tracker.mark_structure()
        by_uid = churn_tick_events(
            snap, tracker, rng, dirty=dirty, pending=pending, t=t,
            now=now,
        )
        result = model.schedule(snap)
        log.append(sorted(result.items()))
        fold_churn_binds(snap, tracker, result, by_uid, now)
    return log, snap


def sharded_model(n_shards=8, **kw):
    mesh = make_mesh2d(node_shards=n_shards, pod_shards=1)
    return PlacementModel(sharding=node_sharding(mesh), **kw)


def assert_worlds_identical(snap_a, snap_b):
    got = lower_nodes(snap_a)
    want = lower_nodes(snap_b)
    assert got.names == want.names
    for f in STAGED_NODE_FIELDS:
        np.testing.assert_array_equal(getattr(got, f), getattr(want, f))


# -- sharded delta staging == single-device full restage ---------------------

def test_sharded_churn_smoke():
    """check.sh slice: a short sharded delta churn must match the
    single-device full-restage run tick for tick."""
    model_s = sharded_model()
    model_1 = PlacementModel()
    snap_s, tracker_s = build_world(120, True)
    snap_1, _ = build_world(120, False)
    log_s, end_s = churn(model_s, snap_s, tracker_s, ticks=4)
    log_1, end_1 = churn(model_1, snap_1, None, ticks=4)
    assert log_s == log_1
    assert_worlds_identical(end_s, end_1)
    # the delta path actually ran sharded (not a silent full fallback)
    assert model_s.staged_cache.last_path == "delta"
    staged = model_s.staged_cache.state
    assert staged.alloc.shape[0] == shard_node_bucket(120, 8)
    assert node_shard_count(staged.alloc.sharding) == 8


def test_sharded_churn_property_with_structure_change():
    """Longer seeded churn including a node-ADD structure event: the
    sharded world re-pads/re-splits on the structure fallback and stays
    bit-identical to the unsharded full-restage run — placements AND
    final node accounting."""
    model_s = sharded_model()
    model_1 = PlacementModel()
    snap_s, tracker_s = build_world(250, True, seed=9)
    snap_1, _ = build_world(250, False, seed=9)
    log_s, end_s = churn(model_s, snap_s, tracker_s, ticks=9, dirty=17,
                         pending=24, structure_tick=4)
    log_1, end_1 = churn(model_1, snap_1, None, ticks=9, dirty=17,
                         pending=24, structure_tick=4)
    assert log_s == log_1
    assert_worlds_identical(end_s, end_1)
    # node accounting: every bind landed exactly once in both worlds
    placed_s = sorted(
        (p.uid, p.node_name) for p in end_s.pods if p.node_name
    )
    placed_1 = sorted(
        (p.uid, p.node_name) for p in end_1.pods if p.node_name
    )
    assert placed_s == placed_1


def test_sharded_delta_vs_sharded_full_restage():
    """The delta path on the SAME sharded mesh equals a tracker-less
    sharded run (full re-shard per tick) — the staging cache is a pure
    latency move on the sharded axis too."""
    model_d = sharded_model()
    model_f = sharded_model()
    snap_d, tracker_d = build_world(90, True, seed=3)
    snap_f, _ = build_world(90, False, seed=3)
    log_d, end_d = churn(model_d, snap_d, tracker_d, ticks=5)
    log_f, end_f = churn(model_f, snap_f, None, ticks=5)
    assert log_d == log_f
    assert_worlds_identical(end_d, end_f)
    assert model_d.staged_cache.last_path == "delta"
    # the tracker-less model never engages the staging cache at all —
    # every tick is a from-scratch lower + sharded stage
    assert model_f.staged_cache.last_path is None


def test_sharded_scatter_respects_pin():
    """The donation double-buffer on the sharded world: while a staged
    generation is pinned (an in-flight solve holds it), a delta
    ensure() must write a FRESH generation and leave the pinned
    buffers bit-identical — the PIN_SPECS clobber guard, sharded."""
    model = sharded_model()
    snap, tracker = build_world(64, True, seed=5)
    snap.pending_pods = []
    cache = model.staged_cache
    cache.ensure(snap)
    pinned = cache.state
    before = {
        f: np.asarray(getattr(pinned, f)) for f in STAGED_NODE_FIELDS
    }
    cache.pin(pinned)
    try:
        name = "n3"
        old = snap.node_metrics[name]
        snap.node_metrics[name] = NodeMetric(
            node_name=name, node_usage={CPU: 31337, MEM: 4096},
            update_time=21.0, pod_usages=old.pod_usages,
        )
        tracker.mark_node(name)
        snap.now = 21.0
        arrays, fresh, _times, _sync = cache.ensure(snap)
        assert fresh is not pinned
        # the pinned generation was not clobbered by the scatter
        for f in STAGED_NODE_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(pinned, f)), before[f]
            )
        # the fresh generation carries the update, still sharded
        idx = arrays.names.index(name)
        assert int(np.asarray(fresh.usage)[idx, int(CPU)]) == 31337
        assert node_shard_count(fresh.alloc.sharding) == 8
    finally:
        cache.unpin(pinned)


def test_sharded_scatter_never_donates():
    """The sharded delta scatter must take the NON-donating twin even
    when unpinned: a persistent-cache replay of the donated
    multi-device scatter mis-aliases same-shaped outputs on this jax
    build (ISSUE 10 — staged used_req/prod_usage came back swapped on
    the first warm-cache delta tick). Observable contract: the
    previous sharded generation survives an ensure() (a donated one
    would be deleted), while the single-device fast path still
    donates."""
    def one_delta_tick(model):
        snap, tracker = build_world(40, True, seed=21)
        snap.pending_pods = []
        cache = model.staged_cache
        cache.ensure(snap)
        prev = cache.state
        name = "n5"
        old = snap.node_metrics[name]
        snap.node_metrics[name] = NodeMetric(
            node_name=name, node_usage={CPU: 11111, MEM: 2048},
            update_time=21.0, pod_usages=old.pod_usages,
        )
        tracker.mark_node(name)
        snap.now = 21.0
        cache.ensure(snap)
        return prev, cache.state

    prev, fresh = one_delta_tick(sharded_model())
    assert fresh is not prev
    assert not prev.alloc.is_deleted(), (
        "sharded delta scatter donated the previous generation — the "
        "warm-cache alias bug is reachable again"
    )
    prev1, fresh1 = one_delta_tick(PlacementModel())
    assert prev1.alloc.is_deleted(), (
        "single-device delta scatter stopped donating — the PR 6 "
        "steady-state fast path regressed"
    )


def test_sharded_churn_zero_recompiles_warmed(xla_compiles):
    """The sharded churn tick's steady state performs ZERO XLA
    recompiles: the per-shard node bucket, the pod bucket, and the
    dirty-row bucket pin every shape once warmed (the xla_compiles
    fixture extended to the sharded path — ISSUE 10 acceptance)."""
    model = sharded_model()
    snap, tracker = build_world(100, True, seed=13)
    churn(model, snap, tracker, ticks=4, dirty=9, pending=16)
    xla_compiles.clear()
    churn(model, snap, tracker, ticks=2, dirty=9, pending=16, seed=77)
    assert xla_compiles == [], (
        "warmed sharded churn ticks recompiled: " + "\n".join(xla_compiles)
    )


# -- pod-batch (lane) axis ---------------------------------------------------

def _params():
    return ScoreParams(
        weights=jnp.asarray(
            np.array([1, 1] + [0] * (NUM_RESOURCES - 2), np.int32)
        ),
        thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )


def _lane_problem(n_nodes, n_pods, n_lanes, seed=17):
    from koordinator_tpu.testing import example_problem

    state, _, _ = example_problem(n_nodes, 1, seed=seed)
    batches = [
        example_problem(n_nodes, n_pods, seed=seed + 1 + l)[1]
        for l in range(n_lanes)
    ]
    return state, batches, _params()


@pytest.mark.parametrize("n_lanes,n_pods,pod_shards", [
    (5, 37, 4),    # non-divisible lanes AND non-pow2 pod count
    (3, 100, 8),   # fewer lanes than shards
])
def test_pod_axis_sharding_identity_non_pow2(n_lanes, n_pods, pod_shards):
    """Every lane of the pod-batch-sharded solve is bit-identical to
    solving that lane alone on a single device — at non-power-of-two
    pod counts and lane counts that do not divide the shard count
    (blocked-duplicate lane padding, trimmed outputs)."""
    state, batches, params = _lane_problem(150, n_pods, n_lanes)
    mesh = make_mesh2d(node_shards=1, pod_shards=pod_shards)
    solve = shard_lane_solver(mesh, SolverConfig())
    node_states, assign = solve(state, stack_pod_lanes(batches), params)
    assign = np.asarray(assign)
    assert assign.shape == (n_lanes, n_pods)
    for l, batch in enumerate(batches):
        want_state, want = schedule_batch(
            state, batch, params, SolverConfig()
        )
        np.testing.assert_array_equal(assign[l], np.asarray(want))
        np.testing.assert_array_equal(
            np.asarray(node_states.used_req[l]),
            np.asarray(want_state.used_req),
        )


def test_lane_solver_on_true_2d_mesh():
    """nodes × pods both > 1: lanes split over ``pods`` while the base
    splits over ``nodes`` — still bit-identical per lane."""
    state, batches, params = _lane_problem(160, 24, 4, seed=23)
    mesh = make_mesh2d(node_shards=2, pod_shards=4)
    assert mesh_axis_size(mesh, POD_AXIS) == 4
    solve = shard_lane_solver(mesh, SolverConfig())
    _, assign = solve(state, stack_pod_lanes(batches), params)
    assign = np.asarray(assign)
    for l, batch in enumerate(batches):
        _, want = schedule_batch(state, batch, params, SolverConfig())
        np.testing.assert_array_equal(assign[l], np.asarray(want))


def test_stack_pod_lanes_rejects_mixed_presence():
    state, batches, params = _lane_problem(20, 4, 2)
    withp = batches[0]._replace(
        has_numa_policy=jnp.zeros(4, bool)
    )
    with pytest.raises(ValueError):
        stack_pod_lanes([withp, batches[1]])


# -- padding buckets + gauges ------------------------------------------------

def test_shard_node_bucket_properties():
    for n, k in [(1, 8), (50, 8), (120, 8), (5000, 8), (50000, 8),
                 (16384, 4), (7, 2)]:
        target = shard_node_bucket(n, k)
        assert target >= n
        assert target % k == 0
        local = target // k
        assert local >= 8
        # quarter-step pow2 buckets bound the waste: the local width
        # never exceeds one quarter-step above the true local need
        # (floor 8) — a regression to full next-pow2 rounding would
        # double the padded memory at 50k x 8 and fail here
        need = -(-n // k)
        if need > 8:
            power = 1 << (need - 1).bit_length()
            step = max(1, power // 8)
            assert local <= need + step, (n, k, local, need, step)
        else:
            assert local == 8
    assert shard_node_bucket(100, 1) == 100  # unsharded: no padding


def test_pad_node_rows_inert_and_identity():
    snap, _ = build_world(10, False)
    arrays = lower_nodes(snap)
    padded = pad_node_rows(arrays, 16)
    assert padded.n == 16
    assert list(padded.names[:10]) == list(arrays.names)
    assert not padded.schedulable[10:].any()
    assert not padded.metric_fresh[10:].any()
    assert (padded.alloc[10:] == 0).all()
    assert (padded.metric_update_time[10:] == -np.inf).all()
    # no-op when already at target, and identical real rows
    assert pad_node_rows(arrays, 10) is arrays
    np.testing.assert_array_equal(padded.used_req[:10], arrays.used_req)
    # padded world solves identically (padding rows never win)
    from koordinator_tpu.state.cluster import lower_pending_pods

    snap.pending_pods = [
        PodSpec(name=f"p{j}", requests={CPU: 500, MEM: 512})
        for j in range(6)
    ]
    pod_arrays = lower_pending_pods(snap.pending_pods)
    pods = PodBatch.build(
        req=jnp.asarray(pod_arrays.req),
        est=jnp.asarray(pod_arrays.est),
        is_prod=jnp.asarray(pod_arrays.is_prod),
        is_daemonset=jnp.asarray(pod_arrays.is_daemonset),
    )

    def stage(a):
        from koordinator_tpu.ops.binpack import NodeState

        return NodeState(
            alloc=jnp.asarray(a.alloc),
            used_req=jnp.asarray(a.used_req),
            usage=jnp.asarray(a.usage),
            prod_usage=jnp.asarray(a.prod_usage),
            est_extra=jnp.asarray(a.est_extra),
            prod_base=jnp.asarray(a.prod_base),
            metric_fresh=jnp.asarray(a.metric_fresh),
            schedulable=jnp.asarray(a.schedulable),
        )

    _, want = schedule_batch(stage(arrays), pods, _params(), SolverConfig())
    _, got = schedule_batch(stage(padded), pods, _params(), SolverConfig())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert (np.asarray(got) < 10).all()


def test_padding_waste_gauges_recorded():
    """The sharded stage and the lane pad both feed the observatory's
    padding gauges (``shard_nodes``, ``pod_lanes``)."""
    model = sharded_model()
    snap, tracker = build_world(100, True)
    snap.pending_pods = []
    model.staged_cache.ensure(snap)
    padding = DEVICE_OBS.status()["padding"]
    assert "shard_nodes" in padding
    gauge = padding["shard_nodes"]
    assert gauge["real"] == 100
    assert gauge["padded"] == shard_node_bucket(100, 8)

    state, batches, params = _lane_problem(30, 8, 3)
    solve = shard_lane_solver(
        make_mesh2d(node_shards=1, pod_shards=2), SolverConfig()
    )
    solve(state, stack_pod_lanes(batches), params)
    padding = DEVICE_OBS.status()["padding"]
    assert padding["pod_lanes"]["real"] == 3
    assert padding["pod_lanes"]["padded"] == 4


def test_explain_scores_trimmed_to_real_nodes_when_sharded():
    """explain's breakdown columns must come back at the REAL node
    count on a sharded model — untrimmed padded columns counted the
    padding rows as rejections and could index names[] out of range
    in the top-K detail (found driving /explain on the --node-shards
    scheduler)."""
    from koordinator_tpu.obs.explain import explain_scores

    model = sharded_model()
    snap, _ = build_world(10, False)
    snap.pending_pods = [
        PodSpec(name="big", requests={CPU: 10_000_000, MEM: 512})
    ]
    arrays, cols = explain_scores(model, snap, snap.pending_pods[0])
    assert arrays.n == 10
    for name, col in cols.items():
        assert col.shape[0] == 10, (name, col.shape)
    assert int((~cols["fit_feasible"]).sum()) <= 10


def test_build_scheduler_node_shards_flag():
    """--node-shards wires a node-sharded model (host fallback forced
    off — a tiny solve must never sync the whole mesh) and refuses the
    sidecar backend."""
    from koordinator_tpu.cmd.scheduler import (
        SchedulerConfig,
        build_scheduler,
    )

    sched = build_scheduler(SchedulerConfig(node_shards=8))
    assert sched.model._node_shards == 8
    assert sched.model.host_fallback_cells == 0
    with pytest.raises(ValueError):
        build_scheduler(SchedulerConfig(
            node_shards=8, placement_backend="sidecar",
        ))


def test_mesh2d_shapes_and_sharding_helpers():
    mesh = make_mesh2d(node_shards=2, pod_shards=4)
    assert dict(mesh.shape) == {"nodes": 2, "pods": 4}
    assert node_shard_count(node_sharding(mesh)) == 2
    # the helper counts LEADING-axis shards for any NamedSharding: a
    # lane sharding's leading (lane) axis splits over ``pods``
    assert node_shard_count(lane_sharding(mesh)) == 4
    assert node_shard_count(None) == 1
    with pytest.raises(ValueError):
        make_mesh2d(node_shards=8, pod_shards=2)  # needs 16 devices
