"""Device telemetry / podthrottled / nodestorageinfo collectors
(VERDICT r2 item 4).

Reference: pkg/koordlet/metricsadvisor/devices/gpu/collector_gpu_linux.go
(NVML inventory + utilization), collectors/{podthrottled,nodestorageinfo}.
The fake sysfs accel tree stands in for libtpu-metrics/NVML the same way
the fake cgroupfs stands in for the kernel.
"""

import os

import pytest

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.device.cache import (
    DeviceResourceName as DR,
    DeviceType,
)
from koordinator_tpu.koordlet.metriccache import (
    AggregationType as A,
    MetricCache,
    MetricKind,
)
from koordinator_tpu.koordlet.metricsadvisor.devices import (
    DeviceCollector,
    NodeStorageInfoCollector,
    PodThrottledCollector,
)
from koordinator_tpu.koordlet.metricsadvisor.framework import (
    CollectorContext,
    PodMeta,
)
from koordinator_tpu.koordlet.system.cgroup import CPU_STAT, SystemConfig


def write_accel(sysfs_root, minor, device_type="tpu", healthy=1,
                mem_total=16384, mem_used=0, utilization=0, numa=0,
                socket=0, pcie="0000:00"):
    d = os.path.join(sysfs_root, "class", "accel", f"accel{minor}")
    os.makedirs(d, exist_ok=True)
    for name, value in (
        ("device_type", device_type), ("healthy", healthy),
        ("mem_total_mib", mem_total), ("mem_used_mib", mem_used),
        ("utilization", utilization), ("numa_node", numa),
        ("socket_id", socket), ("pcie_id", pcie),
    ):
        with open(os.path.join(d, name), "w") as f:
            f.write(str(value))


@pytest.fixture
def env(tmp_path):
    cfg = SystemConfig(
        cgroup_root=str(tmp_path / "cgroup"),
        proc_root=str(tmp_path / "proc"),
        sysfs_root=str(tmp_path / "sys"),
    )
    os.makedirs(cfg.proc_root, exist_ok=True)
    return cfg, MetricCache()


class StaticPods:
    def __init__(self, pods):
        self.pods = pods

    def running_pods(self):
        return self.pods


class TestDeviceCollector:
    def test_inventory_and_telemetry(self, env):
        cfg, mc = env
        write_accel(cfg.sysfs_root, 0, device_type="gpu", mem_total=16384,
                    mem_used=2048, utilization=35, numa=1, pcie="0000:1a")
        write_accel(cfg.sysfs_root, 1, device_type="gpu", healthy=0,
                    mem_total=16384, utilization=90)
        c = DeviceCollector()
        c.setup(CollectorContext(metric_cache=mc, system_config=cfg))
        assert c.enabled()

        devices = c.list_devices()
        assert [d.minor for d in devices] == [0, 1]
        assert devices[0].device_type is DeviceType.GPU
        assert devices[0].resources[DR.GPU_MEMORY] == 16384
        assert devices[0].resources[DR.GPU_CORE] == 100
        assert devices[0].numa_node == 1
        assert devices[0].pcie_id == "0000:1a"
        assert devices[0].health
        assert not devices[1].health  # unhealthy device reported as such

        c.collect(10.0)
        assert mc.aggregate(MetricKind.DEVICE_UTIL, {"minor": "0"},
                            agg=A.LAST) == pytest.approx(35.0)
        assert mc.aggregate(MetricKind.DEVICE_MEMORY_USED, {"minor": "0"},
                            agg=A.LAST) == pytest.approx(2048.0)
        assert mc.aggregate(MetricKind.DEVICE_UTIL, {"minor": "1"},
                            agg=A.LAST) == pytest.approx(90.0)

    def test_disabled_without_tree(self, env):
        cfg, mc = env
        c = DeviceCollector()
        c.setup(CollectorContext(metric_cache=mc, system_config=cfg))
        assert not c.enabled()
        assert c.list_devices() == []

    def test_tpu_type_label(self, env):
        cfg, mc = env
        write_accel(cfg.sysfs_root, 0, device_type="tpu")
        c = DeviceCollector()
        c.setup(CollectorContext(metric_cache=mc, system_config=cfg))
        d = c.list_devices()[0]
        assert d.labels["type"] == "tpu"


class TestPodThrottled:
    def _write_stat(self, cfg, cgroup_dir, periods, throttled):
        path = CPU_STAT.path(cgroup_dir, cfg)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(f"nr_periods {periods}\nnr_throttled {throttled}\n"
                    f"throttled_time 12345\n")

    def test_ratio_between_ticks(self, env):
        cfg, mc = env
        pod = PodMeta("p1", "kubepods/p1", QoSClass.LS)
        self._write_stat(cfg, pod.cgroup_dir, 100, 10)
        c = PodThrottledCollector()
        c.setup(CollectorContext(metric_cache=mc, system_config=cfg,
                                 pod_provider=StaticPods([pod])))
        c.collect(0.0)   # primer
        assert mc.aggregate(MetricKind.POD_CPU_THROTTLED_RATIO,
                            {"pod": "p1"}) is None
        # +100 periods, +25 throttled -> ratio 0.25
        self._write_stat(cfg, pod.cgroup_dir, 200, 35)
        c.collect(1.0)
        assert mc.aggregate(
            MetricKind.POD_CPU_THROTTLED_RATIO, {"pod": "p1"}, agg=A.LAST
        ) == pytest.approx(0.25)


class TestNodeStorageInfo:
    def _write_diskstats(self, cfg, sectors_read, sectors_written, ticks):
        with open(os.path.join(cfg.proc_root, "diskstats"), "w") as f:
            f.write(
                f"   8       0 sda 100 0 {sectors_read} 50 200 0 "
                f"{sectors_written} 80 0 {ticks} 500\n"
                #  partition lines are skipped (sda1 AND nvme/mmcblk
                #  partitions — the kernel folds them into the disk)
                f"   8       1 sda1 1 0 8 1 1 0 8 1 0 1 1\n"
                f" 259       0 nvme0n1 10 0 80 5 20 0 160 8 0 10 50\n"
                f" 259       1 nvme0n1p1 1 0 8 1 1 0 8 1 0 1 1\n"
                f" 179       1 mmcblk0p1 1 0 8 1 1 0 8 1 0 1 1\n"
            )

    def test_rates_and_util(self, env):
        cfg, mc = env
        self._write_diskstats(cfg, 1000, 2000, 0)
        c = NodeStorageInfoCollector()
        c.setup(CollectorContext(metric_cache=mc, system_config=cfg))
        assert c.enabled()
        c.collect(0.0)  # primer
        # +1000 sectors read, +4000 written, +250ms busy over 1s
        self._write_diskstats(cfg, 2000, 6000, 250)
        c.collect(1.0)
        last = lambda k: mc.aggregate(k, {"dev": "sda"}, agg=A.LAST)
        assert last(MetricKind.NODE_DISK_READ_BPS) == pytest.approx(
            1000 * 512)
        assert last(MetricKind.NODE_DISK_WRITE_BPS) == pytest.approx(
            4000 * 512)
        assert last(MetricKind.NODE_DISK_IO_UTIL) == pytest.approx(25.0)
        # partition lines produced no series; the nvme DISK did
        for part in ("sda1", "nvme0n1p1", "mmcblk0p1"):
            assert mc.aggregate(MetricKind.NODE_DISK_READ_BPS,
                                {"dev": part}) is None
        assert mc.aggregate(MetricKind.NODE_DISK_READ_BPS,
                            {"dev": "nvme0n1"}, agg=A.LAST) is not None


def test_deviceshare_schedules_against_collector_devices(tmp_path):
    """End-to-end over the bus: fake sysfs accel tree -> DeviceCollector
    -> DeviceReporter publishes Device objects -> wire_scheduler intake
    -> DeviceShare places a GPU pod on the reporting node."""
    from koordinator_tpu.apis.extension import ResourceName as R
    from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
    from koordinator_tpu.client import APIServer, Kind, wire_scheduler
    from koordinator_tpu.koordlet.statesinformer.reporters import (
        DeviceReporter,
    )
    from koordinator_tpu.scheduler import Scheduler

    cfg = SystemConfig(sysfs_root=str(tmp_path / "sys"))
    write_accel(cfg.sysfs_root, 0, mem_total=16384)
    write_accel(cfg.sysfs_root, 1, mem_total=16384, healthy=0)

    bus = APIServer()
    scheduler = Scheduler()
    wire_scheduler(bus, scheduler)
    bus.apply(Kind.NODE, "node-a", NodeSpec(
        name="node-a", allocatable={R.CPU: 16000, R.MEMORY: 32768}))
    bus.apply(Kind.NODE, "node-b", NodeSpec(
        name="node-b", allocatable={R.CPU: 16000, R.MEMORY: 32768}))
    for n in ("node-a", "node-b"):
        bus.apply(Kind.NODE_METRIC, n, NodeMetric(
            node_name=n, node_usage={}, update_time=99.0))

    # koordlet on node-a reports its collector-read inventory to the bus
    collector = DeviceCollector(cfg)
    reporter = DeviceReporter(
        "node-a", collector,
        lambda node, entries: bus.apply(Kind.DEVICE, node, entries),
    )
    entries = reporter.sync()
    assert len(entries) == 2

    pod = PodSpec(name="gpu-pod", requests={R.CPU: 1000},
                  device_requests={DR.NVIDIA_GPU: 1})
    bus.apply(Kind.POD, pod.uid, pod)
    out = scheduler.schedule_pending(now=100.0)
    # only node-a has devices; the unhealthy accel1 is not allocatable,
    # the healthy accel0 is
    assert out[pod.uid] == "node-a"
    node_dev = scheduler.device_cache.get("node-a")
    assert pod.uid in {
        uid for alloc in node_dev.allocations.values() for uid in alloc
    } or node_dev is not None
