"""runtimehooks tests: registry, bvt rule, cpuset, batchresource,
reconciler, server — ending with the e2e check that a scheduled LSR
pod's cpuset and a BE pod's cfs quota land in fake-cgroupfs files.

Oracles: hooks/hooks.go:47-100 (registry), groupidentity/rule.go:78-222
(bvt rule + actuation), cpuset/rule.go:46-146 + cpuset.go:171-214
(pinning + quota unset), batchresource/batch_resource.go:95-244
(limit translation), reconciler/reconciler.go.
"""

import json

import pytest

from koordinator_tpu.apis.extension import (
    ANNOTATION_RESOURCE_STATUS,
    QoSClass,
    ResourceName,
)
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metricsadvisor.framework import (
    ContainerBatchResources,
    PodMeta,
)
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.resourceexecutor.executor import (
    ensure_cgroup_dir,
)
from koordinator_tpu.koordlet.runtimehooks import (
    BatchResourcePlugin,
    BvtPlugin,
    CpusetPlugin,
    FailurePolicy,
    HookRegistry,
    KubeQOS,
    NodeTopoInfo,
    PodContext,
    Reconciler,
    RuntimeHooks,
    RuntimeHookServer,
    Stage,
    milli_cpu_to_quota,
    milli_cpu_to_shares,
    parse_rule,
)
from koordinator_tpu.koordlet.runtimehooks.protocol import ContainerContext
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.koordlet.system.cgroup import (
    CPU_BVT_WARP_NS,
    CPU_CFS_QUOTA,
    CPU_SET,
    CPU_SHARES,
    MEMORY_LIMIT,
    SystemConfig,
)
from koordinator_tpu.manager.sloconfig import NodeSLOSpec


def pin_annotation(cpus, numa_resources=None):
    status = {"cpuset": list(cpus)}
    if numa_resources:
        status["numaNodeResources"] = numa_resources
    return {ANNOTATION_RESOURCE_STATUS: json.dumps(status)}


def lsr_pod():
    return PodMeta(
        "lsr-pod", "kubepods/podlsr", QoSClass.LSR,
        containers={"main": "kubepods/podlsr/main"},
        annotations=pin_annotation([0, 1, 4, 5]),
    )


def be_pod():
    return PodMeta(
        "be-pod", "kubepods/besteffort/podbe", QoSClass.BE,
        containers={"work": "kubepods/besteffort/podbe/work"},
        batch_resources={
            "work": ContainerBatchResources(
                request_mcpu=1000, limit_mcpu=2000,
                memory_limit_bytes=512 * 1024 * 1024,
            ),
        },
    )


def ls_pod():
    return PodMeta(
        "ls-pod", "kubepods/burstable/podls", QoSClass.LS,
        containers={"main": "kubepods/burstable/podls/main"},
    )


def make_fs(tmp_path, pods):
    cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"),
                       proc_root=str(tmp_path / "proc"))
    for d in ("kubepods", "kubepods/burstable", "kubepods/besteffort"):
        ensure_cgroup_dir(d, cfg)
    for p in pods:
        ensure_cgroup_dir(p.cgroup_dir, cfg)
        for c in p.containers.values():
            ensure_cgroup_dir(c, cfg)
    return cfg, ResourceUpdateExecutor(cfg, auditor=Auditor())


class TestRegistry:
    def test_register_and_run_in_order(self):
        reg = HookRegistry()
        calls = []
        reg.register(Stage.PRE_RUN_POD_SANDBOX, "a", "", lambda p: calls.append("a"))
        reg.register(Stage.PRE_RUN_POD_SANDBOX, "b", "", lambda p: calls.append("b"))
        reg.run_hooks(Stage.PRE_RUN_POD_SANDBOX, PodContext.from_meta(ls_pod()))
        assert calls == ["a", "b"]

    def test_duplicate_name_rejected(self):
        reg = HookRegistry()
        reg.register(Stage.PRE_RUN_POD_SANDBOX, "a", "", lambda p: None)
        with pytest.raises(ValueError):
            reg.register(Stage.PRE_RUN_POD_SANDBOX, "a", "", lambda p: None)

    def test_failure_policy(self):
        reg = HookRegistry()

        def boom(p):
            raise RuntimeError("x")

        calls = []
        reg.register(Stage.PRE_RUN_POD_SANDBOX, "boom", "", boom)
        reg.register(Stage.PRE_RUN_POD_SANDBOX, "after", "",
                     lambda p: calls.append("after"))
        errs = []
        reg.run_hooks(Stage.PRE_RUN_POD_SANDBOX,
                      PodContext.from_meta(ls_pod()),
                      FailurePolicy.IGNORE, errors=errs)
        assert calls == ["after"] and len(errs) == 1
        with pytest.raises(RuntimeError):
            reg.run_hooks(Stage.PRE_RUN_POD_SANDBOX,
                          PodContext.from_meta(ls_pod()),
                          FailurePolicy.FAIL)

    def test_stages_with_hooks(self):
        reg = HookRegistry()
        reg.register(Stage.PRE_CREATE_CONTAINER, "a", "", lambda p: None)
        assert reg.stages_with_hooks() == [Stage.PRE_CREATE_CONTAINER]


class TestBvtRule:
    def test_default_slo_rule(self):
        # defaults: LSR/LS group_identity=2, BE=-1, but enable=False
        # everywhere -> all values none (0)
        rule = parse_rule(NodeSLOSpec())
        assert not rule.enable
        assert rule.pod_bvt(QoSClass.LS, KubeQOS.BURSTABLE) == 0

    def _enabled_slo(self):
        slo = NodeSLOSpec()
        slo.resource_qos_strategy.lsr.enable = True
        slo.resource_qos_strategy.ls.enable = True
        slo.resource_qos_strategy.be.enable = True
        return slo

    def test_enabled_rule_values(self):
        rule = parse_rule(self._enabled_slo())
        assert rule.enable
        assert rule.pod_bvt(QoSClass.LSE, KubeQOS.GUARANTEED) == 2
        assert rule.pod_bvt(QoSClass.LSR, KubeQOS.GUARANTEED) == 2
        assert rule.pod_bvt(QoSClass.LS, KubeQOS.BURSTABLE) == 2
        assert rule.pod_bvt(QoSClass.BE, KubeQOS.BESTEFFORT) == -1
        # unlabeled pods fall back to kube tier
        assert rule.pod_bvt(QoSClass.NONE, KubeQOS.GUARANTEED) == 2
        assert rule.pod_bvt(QoSClass.NONE, KubeQOS.BESTEFFORT) == -1
        # guaranteed DIR stays 0 (kernel constraint)
        assert rule.kube_qos_dir_bvt(KubeQOS.GUARANTEED) == 0
        assert rule.kube_qos_dir_bvt(KubeQOS.BURSTABLE) == 2
        assert rule.kube_qos_dir_bvt(KubeQOS.BESTEFFORT) == -1

    def test_be_only_enabled(self):
        slo = NodeSLOSpec()
        slo.resource_qos_strategy.be.enable = True
        rule = parse_rule(slo)
        assert rule.enable
        assert rule.pod_bvt(QoSClass.LS, KubeQOS.BURSTABLE) == 0
        assert rule.pod_bvt(QoSClass.BE, KubeQOS.BESTEFFORT) == -1
        # guaranteed pod fallback: neither lsr nor ls enabled -> 0
        assert rule.pod_bvt(QoSClass.NONE, KubeQOS.GUARANTEED) == 0

    def test_rule_update_writes_dirs_and_pods(self, tmp_path):
        pods = [ls_pod(), be_pod()]
        cfg, executor = make_fs(tmp_path, pods)
        plugin = BvtPlugin()
        plugin.update_rule(self._enabled_slo())
        written = plugin.rule_update(pods, executor)
        assert written > 0
        assert CPU_BVT_WARP_NS.read("kubepods/burstable", cfg) == "2"
        assert CPU_BVT_WARP_NS.read("kubepods/besteffort", cfg) == "-1"
        assert CPU_BVT_WARP_NS.read("kubepods", cfg) == "0"
        assert CPU_BVT_WARP_NS.read("kubepods/burstable/podls", cfg) == "2"
        assert CPU_BVT_WARP_NS.read(
            "kubepods/besteffort/podbe/work", cfg) == "-1"


class TestCpusetPlugin:
    def _topo(self):
        return NodeTopoInfo(
            share_pools={0: "2-3", 1: "6-7"},
            be_share_pools={0: "3", 1: "7"},
        )

    def test_annotation_pin_wins(self):
        p = CpusetPlugin()
        p.update_rule(self._topo())
        ctx = ContainerContext.from_meta(lsr_pod(), "main")
        p.set_container_cpuset(ctx)
        assert ctx.response.cpuset == "0,1,4,5"
        assert ctx.response.cfs_quota_us == -1  # unset to avoid throttle

    def test_ls_all_share_pools(self):
        p = CpusetPlugin()
        p.update_rule(self._topo())
        ctx = ContainerContext.from_meta(ls_pod(), "main")
        p.set_container_cpuset(ctx)
        assert ctx.response.cpuset == "2-3,6-7"
        assert ctx.response.cfs_quota_us is None

    def test_numa_aware_share_pool(self):
        pod = PodMeta(
            "ls-numa", "kubepods/burstable/podn", QoSClass.LS,
            containers={"main": "kubepods/burstable/podn/main"},
            annotations={ANNOTATION_RESOURCE_STATUS: json.dumps({
                "numaNodeResources": [
                    {"node": 1,
                     "resources": {str(int(ResourceName.CPU)): 2000}},
                ],
            })},
        )
        p = CpusetPlugin()
        p.update_rule(self._topo())
        ctx = ContainerContext.from_meta(pod, "main")
        p.set_container_cpuset(ctx)
        assert ctx.response.cpuset == "6-7"

    def test_be_container_cleared(self):
        p = CpusetPlugin()
        p.update_rule(self._topo())
        ctx = ContainerContext.from_meta(be_pod(), "work")
        p.set_container_cpuset(ctx)
        assert ctx.response.cpuset == ""  # cleared -> no write emitted
        assert ctx.updaters() == []

    def test_kubelet_static_leaves_alone(self):
        topo = self._topo()
        topo.kubelet_policy = "static"
        p = CpusetPlugin()
        p.update_rule(topo)
        pod = PodMeta("g", "kubepods/podg", QoSClass.NONE,
                      containers={"main": "kubepods/podg/main"})
        ctx = ContainerContext.from_meta(pod, "main")
        p.set_container_cpuset(ctx)
        assert ctx.response.cpuset is None

    def test_pod_quota_unset_for_pinned(self):
        p = CpusetPlugin()
        ctx = PodContext.from_meta(lsr_pod())
        p.unset_pod_cpu_quota(ctx)
        assert ctx.response.cfs_quota_us == -1


class TestBatchResourcePlugin:
    def test_conversions(self):
        assert milli_cpu_to_shares(0) == 2
        assert milli_cpu_to_shares(1000) == 1024
        assert milli_cpu_to_quota(-1) == -1
        assert milli_cpu_to_quota(2000) == 200000
        assert milli_cpu_to_quota(5) == 1000  # floor at 1000us

    def test_pod_resources(self):
        plugin = BatchResourcePlugin()
        ctx = PodContext.from_meta(be_pod())
        plugin.set_pod_resources(ctx)
        assert ctx.response.cpu_shares == 1024
        assert ctx.response.cfs_quota_us == 200000
        assert ctx.response.memory_limit_bytes == 512 * 1024 * 1024

    def test_unlimited_container_makes_pod_unlimited(self):
        pod = be_pod()
        pod.batch_resources["extra"] = ContainerBatchResources(
            request_mcpu=500, limit_mcpu=None, memory_limit_bytes=None,
        )
        plugin = BatchResourcePlugin()
        ctx = PodContext.from_meta(pod)
        plugin.set_pod_resources(ctx)
        assert ctx.response.cpu_shares == milli_cpu_to_shares(1500)
        assert ctx.response.cfs_quota_us == -1
        assert ctx.response.memory_limit_bytes == -1

    def test_non_be_untouched(self):
        plugin = BatchResourcePlugin()
        ctx = PodContext.from_meta(ls_pod())
        plugin.set_pod_resources(ctx)
        assert not ctx.response.is_origin_res_changed()

    def test_cpu_normalization_ratio_shrinks_quota(self):
        plugin = BatchResourcePlugin()
        plugin.update_rule(cpu_normalization_ratio=1.5)
        ctx = ContainerContext.from_meta(be_pod(), "work")
        plugin.set_container_resources(ctx)
        # ceil(200000 / 1.5) = 133334
        assert ctx.response.cfs_quota_us == 133334

    def test_cfs_quota_disabled_unsets(self):
        plugin = BatchResourcePlugin()
        plugin.update_rule(cfs_quota_enabled=False)
        ctx = PodContext.from_meta(be_pod())
        plugin.set_pod_resources(ctx)
        assert ctx.response.cfs_quota_us == -1


class TestEndToEnd:
    """The VERDICT round-1 'done' check: a scheduled LSR pod's cpuset and
    a BE pod's cfs quota land in cgroup files."""

    def _wire(self, tmp_path, pods):
        cfg, executor = make_fs(tmp_path, pods)
        informer = StatesInformer()
        rh = RuntimeHooks(informer, executor)
        rh.set_node_topo(NodeTopoInfo(share_pools={0: "2-3", 1: "6-7"}))
        slo = NodeSLOSpec()
        slo.resource_qos_strategy.lsr.enable = True
        slo.resource_qos_strategy.ls.enable = True
        slo.resource_qos_strategy.be.enable = True
        informer.set_node_slo(slo)
        return cfg, informer, rh

    def test_reconciler_actuates_everything(self, tmp_path):
        pods = [lsr_pod(), be_pod(), ls_pod()]
        cfg, informer, rh = self._wire(tmp_path, pods)
        informer.set_pods(pods)  # fires the reconcile callback

        # LSR pod: scheduler-pinned cpuset lands in the container file
        assert CPU_SET.read("kubepods/podlsr/main", cfg) == "0,1,4,5"
        # pinned pod's cfs quota unset at pod level
        assert CPU_CFS_QUOTA.read("kubepods/podlsr", cfg) == "-1"

        # BE pod: batch limits land as cfs quota + shares + memory limit
        assert CPU_CFS_QUOTA.read("kubepods/besteffort/podbe", cfg) == "200000"
        assert CPU_SHARES.read("kubepods/besteffort/podbe", cfg) == "1024"
        assert MEMORY_LIMIT.read(
            "kubepods/besteffort/podbe", cfg) == str(512 * 1024 * 1024)
        assert CPU_CFS_QUOTA.read(
            "kubepods/besteffort/podbe/work", cfg) == "200000"

        # LS pod: bvt=2 on its dir; share-pool cpuset on its container
        assert CPU_BVT_WARP_NS.read("kubepods/burstable/podls", cfg) == "2"
        assert CPU_SET.read("kubepods/burstable/podls/main", cfg) == "2-3,6-7"

        # kube-QoS dirs carry the tier bvt
        assert CPU_BVT_WARP_NS.read("kubepods/besteffort", cfg) == "-1"

    def test_slo_disable_resets_bvt(self, tmp_path):
        pods = [ls_pod()]
        cfg, informer, rh = self._wire(tmp_path, pods)
        informer.set_pods(pods)
        assert CPU_BVT_WARP_NS.read("kubepods/burstable/podls", cfg) == "2"
        informer.set_node_slo(NodeSLOSpec())  # all-disabled
        assert CPU_BVT_WARP_NS.read("kubepods/burstable/podls", cfg) == "0"
        assert CPU_BVT_WARP_NS.read("kubepods/burstable", cfg) == "0"

    def test_server_event_path(self, tmp_path):
        pods = [be_pod()]
        cfg, informer, rh = self._wire(tmp_path, pods)
        res = rh.server.create_container(pods[0], "work", apply=True)
        assert res.cfs_quota_us == 200000
        assert CPU_CFS_QUOTA.read(
            "kubepods/besteffort/podbe/work", cfg) == "200000"

    def test_topo_change_reactuates_cpuset(self, tmp_path):
        pods = [ls_pod()]
        cfg, informer, rh = self._wire(tmp_path, pods)
        informer.set_pods(pods)
        assert CPU_SET.read("kubepods/burstable/podls/main", cfg) == "2-3,6-7"
        # share pools widen: rule change alone must re-actuate (no pod
        # event needed)
        rh.set_node_topo(NodeTopoInfo(share_pools={0: "2-5", 1: "6-7"}))
        assert CPU_SET.read("kubepods/burstable/podls/main", cfg) == "2-5,6-7"

    def test_v2_merge_compares_in_v1_value_space(self, tmp_path):
        """cgroup-v2 merge pass must decode cpu.weight back to shares
        before comparing: a shrink (1024 < current 2048) must NOT be
        written during the top-down only-grow pass."""
        import os

        from koordinator_tpu.koordlet.resourceexecutor import (
            CgroupUpdater,
            merge_if_value_larger,
        )

        cfg = SystemConfig(cgroup_root=str(tmp_path / "cg2"),
                           proc_root=str(tmp_path / "proc2"),
                           use_cgroup_v2=True)
        os.makedirs(str(tmp_path / "cg2" / "kubepods"), exist_ok=True)
        executor = ResourceUpdateExecutor(cfg, auditor=Auditor())
        # current: shares 2048 -> v2 weight encoding
        CPU_SHARES.write("kubepods", CPU_SHARES.encode("2048", "", cfg), cfg)
        weight_2048 = CPU_SHARES.read("kubepods", cfg)
        shrink = CgroupUpdater("cpu.shares", "kubepods", "1024",
                               merge_if_value_larger)
        assert not executor.update(False, shrink, merge=True)
        assert CPU_SHARES.read("kubepods", cfg) == weight_2048
        grow = CgroupUpdater("cpu.shares", "kubepods", "4096",
                             merge_if_value_larger)
        assert executor.update(False, grow, merge=True)
        assert CPU_SHARES.read("kubepods", cfg) == CPU_SHARES.encode(
            "4096", "", cfg)

    def test_server_no_apply_returns_mutation_only(self, tmp_path):
        pods = [be_pod()]
        cfg, informer, rh = self._wire(tmp_path, pods)
        res = rh.server.run_pod_sandbox(pods[0], apply=False)
        assert res.cpu_shares == 1024
        with pytest.raises(OSError):
            CPU_SHARES.read("kubepods/besteffort/podbe", cfg)
