"""Scheduling trace fabric tests (ISSUE 7).

- Chrome-trace export golden properties: valid JSON, spans nest, a
  pipelined run shows stage(N+1)/prestage overlapping solve(N) across
  tracks while a serial run stays strictly sequential.
- Per-pod timeline histogram correctness under a fake clock, and the
  wired end-to-end path (submit at intake, closed at publish).
- Flight-recorder trigger matrix: one test per trigger, driving the
  REAL code path that fires it (auditor sweep over sabotaged state,
  failover flip, fencing abort through run_loop, deferred pipelined
  publish error, client-side deadline exhaustion).
- Explain oracle parity: per-node, per-feature-column scores and
  filter verdicts bit-identical to the oracle's scalar decision
  functions on a full-feature scenario, and the explain winner equal
  to the incremental plugin chain's pick.
- The span-fed stuck watchdog, the codec v3 trace group, and the
  debug-mux endpoints.
"""

import json
import os
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from koordinator_tpu.apis.extension import PriorityClass, QoSClass, ResourceName
from koordinator_tpu.apis.types import (
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
    ReservationState,
)
from koordinator_tpu.client.bus import APIServer, Kind
from koordinator_tpu.client.wiring import wire_scheduler
from koordinator_tpu.obs.flight import FLIGHT, _default_dump_dir
from koordinator_tpu.obs.timeline import PodTimelines
from koordinator_tpu.obs.trace import TRACER, SpanTracer
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.scheduler.pipeline import TickPipeline

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY


@pytest.fixture(autouse=True)
def _fresh_trace():
    TRACER.clear()
    TRACER.set_enabled(True)
    yield
    TRACER.clear()
    TRACER.set_enabled(True)


@pytest.fixture
def flight_dir(tmp_path):
    FLIGHT.reset()
    FLIGHT.configure(dump_dir=str(tmp_path), min_interval_s=0.0)
    yield tmp_path
    FLIGHT.reset()
    FLIGHT.configure(dump_dir=_default_dump_dir(), min_interval_s=1.0)


def _wired(n_nodes=8, cpu=64000, mem=131072):
    bus = APIServer()
    sched = Scheduler()
    wire_scheduler(bus, sched)
    for i in range(n_nodes):
        bus.apply(Kind.NODE, f"n{i}", NodeSpec(
            name=f"n{i}", allocatable={CPU: cpu, MEM: mem}))
        bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
            node_name=f"n{i}", node_usage={CPU: 1000 * (i % 4)},
            update_time=10.0))
    return bus, sched


def _arrive(bus, rng, t, n=12):
    for j in range(n):
        pod = PodSpec(name=f"t{t}p{j}",
                      requests={CPU: int(rng.integers(200, 1200)),
                                MEM: int(rng.integers(128, 1024))})
        bus.apply(Kind.POD, pod.uid, pod)


def _interval(ev):
    return ev["t0"], ev["t0"] + (ev["dur"] or 0.0)


def _overlaps(a, b):
    a0, a1 = _interval(a)
    b0, b1 = _interval(b)
    return a0 < b1 and b0 < a1


class _SlowFlight:
    """Stretches a dispatched solve's publisher-side finalize so the
    coordinator's overlap window is deterministic on any box."""

    def __init__(self, inner, delay_s):
        self.inner = inner
        self.delay_s = delay_s

    @property
    def timings(self):
        return self.inner.timings

    def finalize(self):
        time.sleep(self.delay_s)
        return self.inner.finalize()


# -- chrome export + overlap -------------------------------------------------

def test_smoke_trace_export_pipelined_overlap_serial_sequential():
    """The golden-property slice check.sh runs: the exported trace is
    valid Chrome-trace JSON with nesting intact; a pipelined run shows
    the overlap window crossing the publisher's solve span; a serial
    run is strictly sequential across rounds."""
    # pipelined half -------------------------------------------------------
    bus, sched = _wired()
    rng = np.random.default_rng(3)
    orig_async = sched.model.schedule_async
    sched.model.schedule_async = (
        lambda snapshot: _SlowFlight(orig_async(snapshot), 0.05)
    )
    pipeline = TickPipeline(sched, log=lambda *a: None)
    _arrive(bus, rng, 0)
    for t in range(3):
        pipeline.submit_round(now=20.0 + t)
        # arrivals land mid-flight, then the overlap window warms them
        _arrive(bus, rng, t + 1)
        pipeline.prestage(now=20.0 + t)
    pipeline.drain("test")
    pipeline.stop()

    exported = TRACER.chrome_trace()
    blob = json.dumps(exported)
    parsed = json.loads(blob)
    events = parsed["traceEvents"]
    assert any(e.get("ph") == "M" and e["name"] == "thread_name"
               for e in events)
    for e in events:
        if e.get("ph") == "X":
            assert e["dur"] >= 1 and "ts" in e and "round" in e["args"]

    spans = TRACER.events()
    by_name = lambda n: [e for e in spans if e["name"] == n]
    # nesting: the read-back sits inside its device_solve span, and the
    # lower/stage slices sit inside begin_tick
    for rb in by_name("read_back"):
        assert any(
            ds["round"] == rb["round"]
            and ds["t0"] <= rb["t0"]
            and _interval(rb)[1] <= _interval(ds)[1] + 1e-6
            for ds in by_name("device_solve")
        )
    for low in by_name("lower"):
        assert any(
            bt["round"] == low["round"]
            and bt["t0"] <= low["t0"] + 1e-6
            and _interval(low)[1] <= _interval(bt)[1] + 1e-6
            for bt in by_name("begin_tick")
        )
    # the pipeline's signature: an overlap-window prestage crossing an
    # in-flight solve on ANOTHER track
    assert any(
        _overlaps(ps, ds) and ps["track"] != ds["track"]
        for ps in by_name("prestage")
        for ds in by_name("device_solve")
    ), "pipelined run must show prestage overlapping the device solve"

    # serial half ----------------------------------------------------------
    TRACER.clear()
    bus2, sched2 = _wired()
    rng2 = np.random.default_rng(3)
    for t in range(3):
        _arrive(bus2, rng2, t)
        sched2.schedule_pending(now=20.0 + t)
    serial = TRACER.events()
    assert not [e for e in serial if e["name"] == "prestage"]
    solves = {e["round"]: e for e in serial if e["name"] == "device_solve"}
    for e in serial:
        prev = solves.get(e["round"] - 1)
        if prev is not None:
            assert e["t0"] >= _interval(prev)[1] - 1e-6, (
                "serial run must not overlap a prior round's solve"
            )


def test_pipelined_tracing_on_off_tick_identical():
    """Tracing is observation only: the same seeded pipelined churn
    places identically with the tracer on and off."""

    def drive(enabled):
        TRACER.clear()
        TRACER.set_enabled(enabled)
        bus, sched = _wired()
        rng = np.random.default_rng(11)
        log = []
        pipeline = TickPipeline(
            sched, log=lambda *a: None,
            on_result=lambda out: log.append(sorted(out.items())),
        )
        _arrive(bus, rng, 0)
        for t in range(4):
            pipeline.submit_round(now=20.0 + t)
            _arrive(bus, rng, t + 1)
            pipeline.prestage(now=20.0 + t)
        pipeline.drain("test")
        pipeline.stop()
        return log

    on = drive(True)
    off = drive(False)
    assert on == off and len(on) == 4
    TRACER.set_enabled(True)


def test_tracer_ring_bounded_and_disabled_noop():
    t = SpanTracer(capacity=4)
    for i in range(10):
        t.emit(f"s{i}", t0=float(i), t1=float(i) + 1.0)
    assert len(t.events()) == 4
    assert t.span_count == 10
    t.set_enabled(False)
    t.emit("dropped", t0=0.0, t1=1.0)
    assert len(t.events()) == 4
    # open marks keep working with recording off: the watchdog's food
    t.mark_open("round:1")
    assert "round:1" in t.open_marks()
    assert t.mark_closed("round:1") is not None


# -- per-pod timelines -------------------------------------------------------

def test_pod_timeline_histogram_fake_clock():
    from koordinator_tpu.metrics.registry import Histogram

    clock = [100.0]
    hist = Histogram("test_pod_e2e_seconds", label_names=("lane",))
    tl = PodTimelines(clock=lambda: clock[0], histogram=hist)
    tl.submit("a", lane="ls")
    tl.submit("b", lane="be")
    clock[0] = 101.0
    tl.mark_many(["a", "b"], "staged")
    clock[0] = 102.0
    tl.mark("a", "solved")
    clock[0] = 103.5
    assert tl.published("a") == pytest.approx(3.5)
    assert hist.count({"lane": "ls"}) == 1
    assert hist.sum({"lane": "ls"}) == pytest.approx(3.5)
    # a forgotten pod is not a latency sample
    tl.forget("b")
    assert hist.count({"lane": "be"}) == 0
    # re-submitting an active uid must not reset its stamps
    tl.submit("c", lane="system")
    clock[0] = 110.0
    tl.submit("c", lane="system")
    clock[0] = 112.0
    assert tl.published("c") == pytest.approx(8.5)
    stats = tl.stats()
    assert stats["all"]["count"] == 2
    assert stats["ls"]["p50_s"] == pytest.approx(3.5)


def test_pod_timeline_capacity_refuses_newest_keeps_tail():
    """At capacity the NEW submit is refused and counted: evicting the
    oldest would silently drop exactly the longest-waiting pods — the
    p99 tail the histogram exists to observe."""
    from koordinator_tpu.metrics.registry import Histogram

    clock = [100.0]
    hist = Histogram("test_pod_e2e_cap_seconds", label_names=("lane",))
    tl = PodTimelines(capacity=2, clock=lambda: clock[0], histogram=hist)
    tl.submit("old", lane="ls")
    tl.submit("mid", lane="ls")
    clock[0] = 150.0
    tl.submit("new", lane="ls")
    st = tl.status()
    assert st["inflight"] == 2
    assert st["dropped"] == 1
    assert tl.published("new") is None               # never admitted
    assert tl.published("old") == pytest.approx(50.0)  # tail survives
    # capacity freed: the next submit is admitted again
    tl.submit("late", lane="ls")
    assert tl.status()["dropped"] == 1
    clock[0] = 151.0
    assert tl.published("late") == pytest.approx(1.0)


def test_pod_timeline_preserved_carries_stamps():
    """preserved(): original stamps (submit above all) win over the
    round-trip's fresh ones, the refreshed pod's lane wins, and a
    capacity-refused re-add restores the pre-existing sample."""
    from koordinator_tpu.metrics.registry import Histogram

    clock = [100.0]
    hist = Histogram("test_pod_e2e_pres_seconds", label_names=("lane",))
    tl = PodTimelines(clock=lambda: clock[0], histogram=hist)
    tl.submit("a", lane="ls")
    clock[0] = 105.0
    tl.mark("a", "staged")
    with tl.preserved("a"):
        tl.forget("a")
        clock[0] = 110.0
        tl.submit("a", lane="be")
    clock[0] = 112.0
    assert tl.published("a") == pytest.approx(12.0)  # submit=100 kept
    assert hist.count({"lane": "be"}) == 1           # new lane kept
    # unknown uid: a no-op carry
    with tl.preserved("ghost"):
        pass
    assert tl.status()["inflight"] == 0
    # re-add refused at capacity: the pre-existing sample survives
    small = PodTimelines(capacity=1, clock=lambda: clock[0],
                         histogram=hist)
    small.submit("x", lane="ls")
    with small.preserved("x"):
        small.forget("x")
        small.submit("filler", lane="ls")
        small.submit("x", lane="ls")        # refused (at capacity)
    assert small.status()["dropped"] == 1
    clock[0] = 120.0
    assert small.published("x") == pytest.approx(8.0)


def test_pod_timeline_survives_accounted_refresh():
    """An informer MODIFIED refresh of a PENDING pod's accounted fields
    re-runs remove_pod+add_pod for the quota/gang side effects — the
    submit stamp must ride through, or a mid-wait field refresh hides
    the queue-wait tail from scheduler_pod_e2e_seconds (regression:
    the round-trip forgot + freshly re-submitted the timeline)."""
    from koordinator_tpu.metrics.registry import Histogram

    clock = [100.0]
    hist = Histogram("test_pod_e2e_refresh_seconds",
                     label_names=("lane",))
    bus, sched = _wired()
    sched.timelines = PodTimelines(clock=lambda: clock[0],
                                   histogram=hist)
    pod = PodSpec(name="w", requests={CPU: 1000, MEM: 1024})
    bus.apply(Kind.POD, pod.uid, pod)
    clock[0] = 130.0
    refreshed = PodSpec(name="w", requests={CPU: 1200, MEM: 1024})
    assert refreshed.uid == pod.uid and refreshed is not pod
    bus.apply(Kind.POD, refreshed.uid, refreshed)
    assert sched.timelines.status()["inflight"] == 1
    clock[0] = 131.0
    out = sched.schedule_pending(now=20.0)
    assert out[refreshed.uid] is not None
    assert hist.count({"lane": "ls"}) == 1
    # 31s of pending wall, not the 1s since the refresh
    assert hist.sum({"lane": "ls"}) == pytest.approx(31.0)


def test_serial_loop_opens_publish_watchdog_mark():
    """The default (non-pipelined) loop publishes inline; its publish
    must still feed the stuck-publish watchdog (regression: only the
    pipelined publisher opened publish:<id> marks, so a serial publish
    wedged on a half-open connection showed zero open marks and
    check_stuck reported healthy)."""
    bus, sched = _wired()
    rng = np.random.default_rng(11)
    _arrive(bus, rng, 0, n=4)
    seen = []

    def watch(event, name, pod):
        if getattr(pod, "node_name", None):
            seen.append(dict(TRACER.open_marks()))

    bus.watch(Kind.POD, watch)
    sched.schedule_pending(now=20.0)
    assert seen, "no binding published"
    # mid-publish (observed from inside the bus apply) the mark is
    # open, keyed by THIS scheduler's committed round — not the shared
    # process-global counter a second wired scheduler would bump
    assert any(f"publish:{sched.last_round_id}" in marks
               for marks in seen)
    # and it closes with the round — a finished publish is not stuck
    assert not any(k.startswith("publish:") for k in TRACER.open_marks())


def test_failed_epilogue_closes_round_mark():
    """A FencingError raised from the commit_tick EPILOGUE (a fenced
    preemption eviction mid-takeover) — not just from finalize — must
    close round:<id> (regression: the guard only covered finalize, so
    the already-retired round ghosted the watchdog and every flight
    dump's open_spans forever)."""
    from koordinator_tpu.client.leaderelection import FencingError

    bus, sched = _wired()
    rng = np.random.default_rng(7)
    _arrive(bus, rng, 0)

    def boom(result, pending, at):
        raise FencingError("deposed")

    sched._preempt_unplaced = boom
    with pytest.raises(FencingError):
        sched.schedule_pending(now=20.0)
    assert not any(k.startswith("round:") for k in TRACER.open_marks())


def test_build_scheduler_applies_obs_config(flight_dir, tmp_path):
    """SchedulerConfig.trace / flight_dir must take effect for
    embedders calling build_scheduler()+run_loop(), not only via the
    CLI main() (regression: the knobs were applied in main() alone)."""
    from koordinator_tpu.cmd.scheduler import (
        SchedulerConfig,
        build_scheduler,
    )

    other = tmp_path / "elsewhere"
    build_scheduler(SchedulerConfig(
        trace=False, flight_dir=str(other), host_fallback_cells=0))
    assert not TRACER.enabled
    assert FLIGHT.status()["dump_dir"] == str(other)
    build_scheduler(SchedulerConfig(host_fallback_cells=0))
    assert TRACER.enabled


def test_pod_e2e_wired_submit_to_publish():
    from koordinator_tpu.metrics.components import POD_E2E

    before = POD_E2E.count({"lane": "ls"})
    bus, sched = _wired()
    rng = np.random.default_rng(5)
    _arrive(bus, rng, 0, n=6)
    out = sched.schedule_pending(now=20.0)
    placed = sum(1 for v in out.values() if v is not None)
    assert placed == 6
    assert POD_E2E.count({"lane": "ls"}) == before + 6
    assert sched.timelines.stats()["all"]["count"] >= 6


# -- flight recorder trigger matrix ------------------------------------------

def _dumps_for(flight_dir, trigger):
    return [p for p in os.listdir(flight_dir)
            if p.startswith(f"flight-{trigger}-")]


def test_flight_trigger_auditor_detection(flight_dir):
    from koordinator_tpu.scheduler.auditor import StateAuditor
    from koordinator_tpu.testing.chaos import FaultSchedule, StateSaboteur

    bus, sched = _wired()
    auditor = StateAuditor(sched, bus, interval_rounds=4, probe_rows=8)
    rng = np.random.default_rng(1)
    _arrive(bus, rng, 0, n=8)
    sched.schedule_pending(now=20.0)
    saboteur = StateSaboteur(
        FaultSchedule({0: "corrupt-cache-cell"}), sched, seed=0
    )
    assert saboteur.inject(0) == "corrupt-cache-cell"
    report = auditor.sweep("manual", now=21.0)
    assert report["detections"]
    paths = _dumps_for(flight_dir, "auditor-detection")
    assert len(paths) == 1
    payload = json.loads((flight_dir / paths[0]).read_text())
    assert payload["trigger"] == "auditor-detection"
    assert payload["extra"]["detections"]


def test_flight_trigger_failover_flip(flight_dir):
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.service.client import SolverUnavailable
    from koordinator_tpu.service.failover import FailoverSolver
    from koordinator_tpu.testing import example_problem

    class DeadRemote:
        address = ("127.0.0.1", 1)

        def solve_result(self, *a, **kw):
            raise SolverUnavailable("down")

    fo = FailoverSolver(DeadRemote(), failure_threshold=1,
                        probe_fn=lambda: False)
    state, pods, params = example_problem(6, 4, seed=2)
    result = fo.solve_result(state, pods, params, SolverConfig())
    assert result.assign.shape[0] == 4  # answered in-process
    paths = _dumps_for(flight_dir, "failover-flip")
    assert len(paths) == 1
    payload = json.loads((flight_dir / paths[0]).read_text())
    assert "to-degraded" in payload["detail"]
    # rounds recorded before the flip ride along
    assert isinstance(payload["rounds"], list)


def test_flight_trigger_fencing_abort(flight_dir):
    from koordinator_tpu.client.leaderelection import FencingError
    from koordinator_tpu.cmd.scheduler import SchedulerConfig, run_loop

    sched = Scheduler()

    def boom(now=None):
        raise FencingError("deposed")

    sched.schedule_pending = boom
    rc = run_loop(sched, SchedulerConfig(schedule_interval_seconds=0.01),
                  once=True, log=lambda *a: None)
    assert rc == 1
    paths = _dumps_for(flight_dir, "fencing-abort")
    assert len(paths) == 1


def test_flight_trigger_deferred_pipeline_error(flight_dir):
    bus, sched = _wired(n_nodes=2)

    def bad_publish(out):
        raise RuntimeError("publish wedge")

    pipeline = TickPipeline(sched, publish=bad_publish,
                            log=lambda *a: None)
    rng = np.random.default_rng(7)
    _arrive(bus, rng, 0, n=2)
    pipeline.submit_round(now=20.0)
    with pytest.raises(RuntimeError, match="publish wedge"):
        pipeline.drain("test")
    pipeline.stop()
    paths = _dumps_for(flight_dir, "pipeline-deferred-error")
    assert len(paths) == 1
    payload = json.loads((flight_dir / paths[0]).read_text())
    assert "RuntimeError" in payload["detail"]
    # the dump must contain the round that FAILED (error-flagged), not
    # only the rounds leading up to it — _retire bailed before its
    # record_round, so the error path records it
    failed = [r for r in payload["rounds"] if r.get("error")]
    assert failed and "RuntimeError" in failed[-1]["error"]


def test_flight_trigger_deadline_exceeded(flight_dir):
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.service.client import (
        RemoteSolver,
        SolverDeadlineExceeded,
    )
    from koordinator_tpu.testing import example_problem

    # a black-hole server: accepts connections, never answers — each
    # attempt parks on the budget-capped socket wait until the
    # client-side deadline drains
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(4)
    held = []

    def accept_and_hold():
        while True:
            try:
                conn, _ = listener.accept()
            except OSError:
                return
            held.append(conn)

    t = threading.Thread(target=accept_and_hold, daemon=True)
    t.start()
    try:
        # retries > the attempts the budget can hold: the guaranteed-
        # minimum-retries clause defers the transport raise, so the
        # budget check at the loop top is what fires — the client-side
        # deadline-exceeded path
        solver = RemoteSolver(listener.getsockname(), deadline_s=0.15,
                              retries=3, backoff_base_s=0.005)
        state, pods, params = example_problem(4, 3, seed=1)
        with pytest.raises(SolverDeadlineExceeded):
            solver.solve_result(state, pods, params, SolverConfig())
        solver.close()
    finally:
        listener.close()
        for conn in held:
            conn.close()
    paths = _dumps_for(flight_dir, "deadline-exceeded")
    assert len(paths) == 1


def test_flight_rate_limit(flight_dir):
    FLIGHT.configure(min_interval_s=60.0)
    assert FLIGHT.trigger("manual", detail="first") is not None
    assert FLIGHT.trigger("manual", detail="suppressed") is None
    assert len(_dumps_for(flight_dir, "manual")) == 1


# -- explain parity ----------------------------------------------------------

def _full_feature_scheduler():
    """Quota + reservation + stale/overloaded metrics + prod pods +
    selector pods on one typed scheduler — the full-feature explain
    scenario."""
    s = Scheduler()
    for i in range(8):
        s.add_node(NodeSpec(
            name=f"n{i}",
            allocatable={CPU: 16000, MEM: 32768},
            labels={"zone": "a" if i < 4 else "b"},
        ))
        # n6 stale metric (old update_time), n7 overloaded
        s.update_node_metric(NodeMetric(
            node_name=f"n{i}",
            node_usage={CPU: 15000 if i == 7 else 1000 * i,
                        MEM: 2048 * i},
            update_time=1.0 if i == 6 else 90.0,
        ))
    s.update_quota(QuotaSpec(name="q", min={CPU: 2000, MEM: 1024},
                             max={CPU: 6000, MEM: 4096}))
    s.update_reservation(ReservationSpec(
        name="resv-a", node_name="n2", requests={CPU: 2000},
        allocatable={CPU: 2000},
        state=ReservationState.AVAILABLE,
        owner_pod_uids=["default/owned"],
    ))
    s.add_pod(PodSpec(name="plain", requests={CPU: 1500, MEM: 512}))
    s.add_pod(PodSpec(name="prod",
                      requests={CPU: 2000, MEM: 1024},
                      priority_class=PriorityClass.PROD,
                      qos=QoSClass.LS))
    s.add_pod(PodSpec(name="quota-pod", quota="q",
                      requests={CPU: 1000, MEM: 256}))
    s.add_pod(PodSpec(name="picky",
                      requests={CPU: 500, MEM: 128},
                      node_selector={"zone": "b"}))
    s.add_pod(PodSpec(name="owned", requests={CPU: 800, MEM: 128}))
    s.add_pod(PodSpec(name="be-pod", qos=QoSClass.BE,
                      requests={CPU: 400, MEM: 64}))
    return s


def test_explain_oracle_parity_full_features():
    """Acceptance: explain's per-column scores/verdicts match the
    oracle's plugin decision functions bit-for-bit on the full-feature
    scenario."""
    from koordinator_tpu.obs.explain import explain_scores
    from koordinator_tpu.oracle.scheduler import (
        fit_filter_node,
        least_allocated_score_node,
        loadaware_filter_node,
        loadaware_score_node,
    )
    from koordinator_tpu.state.cluster import lower_pending_pods

    s = _full_feature_scheduler()
    snapshot = s.cache.snapshot(now=100.0)
    assert snapshot.pending_pods
    weights = np.asarray(s.model.params.weights)
    thresholds = np.asarray(s.model.params.thresholds)
    prod_thresholds = np.asarray(s.model.params.prod_thresholds)
    for pod in snapshot.pending_pods:
        arrays, cols = explain_scores(s.model, snapshot, pod)
        pa = lower_pending_pods(
            [pod],
            scaling_factors=s.model.scaling_factors,
            resource_weights=s.model.resource_weights,
        )
        req, est = pa.req[0], pa.est[0]
        is_prod = bool(pa.is_prod[0])
        is_ds = bool(pa.is_daemonset[0])
        for i in range(arrays.n):
            assert cols["fit_score"][i] == least_allocated_score_node(
                req, arrays.alloc[i], arrays.used_req[i], weights
            ), (pod.name, i)
            assert cols["loadaware_score"][i] == loadaware_score_node(
                est, arrays.alloc[i], arrays.usage[i],
                arrays.est_extra[i], arrays.prod_base[i],
                bool(arrays.metric_fresh[i]), weights, is_prod,
                s.model.config.score_according_prod,
            ), (pod.name, i)
            assert bool(cols["fit_feasible"][i]) == fit_filter_node(
                req, arrays.alloc[i], arrays.used_req[i]
            )
            assert bool(cols["loadaware_feasible"][i]) == \
                loadaware_filter_node(
                    arrays.alloc[i], arrays.usage[i],
                    arrays.prod_usage[i], bool(arrays.metric_fresh[i]),
                    thresholds, prod_thresholds, is_ds, is_prod,
                )


def test_explain_winner_matches_incremental_chain():
    from koordinator_tpu.obs.explain import PlacementExplainer

    s = _full_feature_scheduler()
    explainer = PlacementExplainer(s)
    s.debug.dump_scores = True
    payload = explainer.explain("default/plain", now=100.0)
    outcome = s.schedule_one("default/plain", now=100.0)
    assert outcome.status == "bound"
    assert payload["winner"] == outcome.node
    # the weighted totals equal the plugin chain's recorded scores
    chain_scores = s.debug.scores[0]["scores"]
    for detail in payload["top_nodes"]:
        if detail["feasible"]:
            assert (detail["scores"]["weighted_total"]
                    == chain_scores[detail["node"]]), detail["node"]
    # explain answers are kept on the debug recorder (bounded)
    assert list(s.debug.explains)[-1] is payload


def test_explain_unschedulable_and_selector():
    from koordinator_tpu.obs.explain import PlacementExplainer

    s = _full_feature_scheduler()
    payload = PlacementExplainer(s).explain(
        "default/picky", node="n0", now=100.0
    )
    # zone-a nodes fail the selector; the queried node shows why
    assert payload["filter_rejections"]["selector"] == 4
    assert payload["queried_node"]["filters"]["selector"] is False
    assert payload["winner"] is not None  # zone b has room
    s.add_pod(PodSpec(name="impossible",
                      requests={CPU: 10 ** 8}))
    impossible = PlacementExplainer(s).explain(
        "default/impossible", now=100.0
    )
    assert impossible["winner"] is None
    assert impossible["feasible_count"] == 0
    assert impossible["filter_rejections"]["fit"] == 8


# -- watchdog ----------------------------------------------------------------

def test_monitor_stuck_counts_once_and_clears():
    from koordinator_tpu.metrics.components import STUCK_CYCLES
    from koordinator_tpu.scheduler.monitor import SchedulerMonitor

    tracer = SpanTracer()
    mon = SchedulerMonitor(tracer=tracer, timeout_seconds=5.0,
                           log=lambda *a: None)
    before_round = STUCK_CYCLES.value({"kind": "round"})
    before_pub = STUCK_CYCLES.value({"kind": "publish"})
    tracer.mark_open("round:7")
    tracer.mark_open("publish:6")
    later = tracer.now() + 30.0
    assert sorted(mon.check_stuck(now=later)) == ["publish:6", "round:7"]
    # counted exactly once, not per check
    mon.check_stuck(now=later + 1.0)
    assert STUCK_CYCLES.value({"kind": "round"}) == before_round + 1
    assert STUCK_CYCLES.value({"kind": "publish"}) == before_pub + 1
    tracer.mark_closed("round:7")
    tracer.mark_closed("publish:6")
    assert mon.check_stuck(now=later) == []
    # a fresh wedge on a NEW mark counts again
    tracer.mark_open("round:8")
    mon.check_stuck(now=later + 60.0)
    assert STUCK_CYCLES.value({"kind": "round"}) == before_round + 2


def test_monitor_stuck_counts_once_across_monitors():
    """The counted-stuck flag lives with the MARK, not the monitor: a
    leader + standby in one process (run_loop checks before the elector
    gate, so both monitors run) plus a debug-mux status() reader must
    count one stuck round once, not once per watcher."""
    from koordinator_tpu.metrics.components import STUCK_CYCLES
    from koordinator_tpu.scheduler.monitor import SchedulerMonitor

    tracer = SpanTracer()
    leader = SchedulerMonitor(tracer=tracer, timeout_seconds=5.0,
                              log=lambda *a: None)
    standby = SchedulerMonitor(tracer=tracer, timeout_seconds=5.0,
                               log=lambda *a: None)
    before = STUCK_CYCLES.value({"kind": "round"})
    tracer.mark_open("round:9")
    later = tracer.now() + 30.0
    # both report it stuck (the VERDICT is per-caller)...
    assert leader.check_stuck(now=later) == ["round:9"]
    assert standby.check_stuck(now=later) == ["round:9"]
    standby.status()
    # ...but the metric counts the mark exactly once
    assert STUCK_CYCLES.value({"kind": "round"}) == before + 1
    # reusing the key (mark closed, later reopened) re-arms the flag
    tracer.mark_closed("round:9")
    tracer.mark_open("round:9")
    leader.check_stuck(now=tracer.now() + 30.0)
    assert STUCK_CYCLES.value({"kind": "round"}) == before + 2


def test_standby_observed_binding_forgets_timeline():
    """A standby watching the leader bind pods must not leak open
    timelines: the observed binding is not this scheduler's latency
    sample, so the entry is dropped unobserved."""
    bus, standby = _wired()
    pod = PodSpec(name="w", requests={CPU: 1000, MEM: 1024})
    bus.apply(Kind.POD, pod.uid, pod)
    assert standby.timelines.status()["inflight"] == 1
    # the leader's bind arrives as a fresh bound object on the bus
    bound = PodSpec(name="w", node_name="n0", assign_time=20.0,
                    requests={CPU: 1000, MEM: 1024})
    bus.apply(Kind.POD, bound.uid, bound)
    assert pod.uid in standby.cache.pods
    assert pod.uid not in standby.cache.pending
    st = standby.timelines.status()
    assert st["inflight"] == 0
    assert st["latency"]["all"]["count"] == 0  # forgotten, not observed


def test_flight_dump_files_capped_on_disk(flight_dir):
    """The per-trigger rate limit bounds the dump RATE; the file cap
    bounds the TOTAL — a flapping trigger must not fill the disk."""
    from koordinator_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder(dump_dir=str(flight_dir), min_interval_s=0.0,
                         max_files=3)
    for i in range(8):
        rec.record_round({"round": i})
        assert rec.trigger("manual", detail=f"flap {i}") is not None
    files = sorted(p.name for p in flight_dir.glob("flight-manual-*.json"))
    assert len(files) == 3
    assert files == ["flight-manual-0006.json", "flight-manual-0007.json",
                     "flight-manual-0008.json"]


def test_failed_round_and_publish_close_their_marks():
    """A FAILED round/publish is handled (skipped, deferred) — not
    STUCK: its watchdog mark must close, or check_stuck flags a ghost
    forever (regression: fenced publishes leaked publish:<id> marks
    across the whole process lifetime)."""
    rng = np.random.default_rng(5)

    # publish raises (the fenced-publish shape) inside the pipeline
    bus, sched = _wired()
    _arrive(bus, rng, 0)
    boom = RuntimeError("fenced")

    def bad_publish(result):
        raise boom

    pipeline = TickPipeline(sched, publish=bad_publish,
                            log=lambda *a: None)
    pipeline.submit_round(now=100.0)
    with pytest.raises(RuntimeError):
        pipeline.drain("test")  # the deferred error surfaces here
    pipeline.stop()
    assert not any(k.startswith("publish:") for k in TRACER.open_marks())

    # solve dispatch raises (the sidecar-outage shape) in begin_tick
    bus2, sched2 = _wired()
    _arrive(bus2, rng, 1)

    def bad_dispatch(snapshot):
        raise RuntimeError("solver gone")

    sched2.model.schedule_async = bad_dispatch
    with pytest.raises(RuntimeError):
        sched2.begin_tick(now=100.0)
    assert not any(k.startswith("round:") for k in TRACER.open_marks())


# -- wire trace context ------------------------------------------------------

def test_codec_trace_group_roundtrip_and_unknown_prefix():
    import io

    from koordinator_tpu.service.codec import (
        SolveRequest,
        decode_request,
        encode_request,
    )

    node = {"alloc": np.ones((2, 3), np.int32)}
    req = SolveRequest(
        node=node, pods={"req": np.ones((1, 3), np.int32)},
        params={"weights": np.ones(3, np.int32)},
        trace={"round": np.asarray(7, np.int64),
               "span": np.asarray(42, np.int64)},
    )
    decoded = decode_request(encode_request(req))
    assert int(decoded.trace["round"]) == 7
    assert int(decoded.trace["span"]) == 42
    # an unknown future prefix is skipped, exactly like trace is by a
    # v2 server
    buf = io.BytesIO()
    np.savez(buf, **{"z.mystery": np.zeros(1), "n.alloc": node["alloc"]})
    tolerant = decode_request(buf.getvalue())
    assert "alloc" in tolerant.node and tolerant.trace is None


def test_sidecar_spans_join_scheduler_trace(tmp_path):
    """A RemoteSolver round trip tags the in-process sidecar's solve
    span with the scheduler's (round, span) trace context."""
    from koordinator_tpu.ops.binpack import SolverConfig
    from koordinator_tpu.service.client import RemoteSolver
    from koordinator_tpu.service.server import PlacementService
    from koordinator_tpu.testing import example_problem

    addr = str(tmp_path / "solver.sock")
    service = PlacementService(addr, admission=False)
    service.start()
    try:
        solver = RemoteSolver(addr)
        state, pods, params = example_problem(6, 4, seed=3)
        result = solver.solve_result(state, pods, params, SolverConfig())
        assert result.assign.shape[0] == 4
        spans = TRACER.events()
        wire = [e for e in spans if e["name"] == "wire_solve"]
        sidecar = [e for e in spans if e["name"] == "sidecar_solve"]
        assert wire and sidecar
        assert sidecar[-1]["args"]["span"] == wire[-1]["args"]["span"]
        solver.close()
    finally:
        service.stop()


def test_admission_gate_emits_queue_wait_spans(tmp_path):
    from koordinator_tpu.service.admission import AdmissionGate
    from koordinator_tpu.service.codec import SolveRequest
    from koordinator_tpu.service.server import solve_from_request
    from koordinator_tpu.testing import example_problem

    state, pods, params = example_problem(4, 3, seed=5)
    req = SolveRequest(
        node={f: np.asarray(getattr(state, f))
              for f in ("alloc", "used_req", "usage", "prod_usage",
                        "est_extra", "prod_base", "metric_fresh",
                        "schedulable")},
        pods={f: np.asarray(getattr(pods, f))
              for f in ("req", "est", "is_prod", "is_daemonset")},
        params={f: np.asarray(getattr(params, f))
                for f in ("weights", "thresholds", "prod_thresholds")},
        trace={"round": np.asarray(3, np.int64),
               "span": np.asarray(9, np.int64)},
    )
    from koordinator_tpu.ops.binpack import SolverConfig

    gate = AdmissionGate(solve_from_request)
    try:
        entry = gate.submit(req, SolverConfig())
        resp = entry.wait(timeout=30.0)
        assert resp is not None and resp.error == ""
    finally:
        gate.shutdown()
    waits = [e for e in TRACER.events() if e["name"] == "queue_wait"]
    assert waits and waits[-1]["args"]["round"] == 3
    assert waits[-1]["args"]["lane"] == "ls"


# -- debug mux ---------------------------------------------------------------

def test_debug_http_trace_and_explain_endpoints():
    from koordinator_tpu.obs.explain import PlacementExplainer
    from koordinator_tpu.utils.debug_http import DebugHTTPServer

    s = _full_feature_scheduler()
    server = DebugHTTPServer(
        services=s.services, debug=s.debug, tracer=TRACER,
        explain=PlacementExplainer(s).explain,
    ).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/debug/trace") as resp:
            trace = json.loads(resp.read())
        assert "traceEvents" in trace
        with urllib.request.urlopen(
            f"{base}/explain?pod=default/plain&node=n0"
        ) as resp:
            payload = json.loads(resp.read())
        assert payload["winner"] is not None
        assert payload["queried_node"]["node"] == "n0"
        with urllib.request.urlopen(f"{base}/debug/dumps") as resp:
            dumps = json.loads(resp.read())
        assert dumps["explains"]  # the explain above was recorded
        # the monitor + timeline services ride the standard registry
        with urllib.request.urlopen(
            f"{base}/apis/v1/plugins/pod-timelines"
        ) as resp:
            assert "latency" in json.loads(resp.read())
        with urllib.request.urlopen(
            f"{base}/apis/v1/plugins/monitor"
        ) as resp:
            assert json.loads(resp.read())["stuck"] == []
    finally:
        server.stop()
