"""ElasticQuota tests: water-filling golden cases, tree manager semantics,
device == oracle differential, and quota-gated solver scheduling."""

import numpy as np
import jax.numpy as jnp

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.apis.types import QuotaSpec
from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.ops.quota import (
    QuotaState,
    normalize_weights,
    quota_admit,
    quota_assume,
    quota_runtime,
    water_filling_device,
)
from koordinator_tpu.oracle.placement import (
    SequentialQuota,
    schedule_sequential_quota,
)
from koordinator_tpu.quota.core import GroupQuotaManager, water_filling

RNG = np.random.default_rng(5)
CPU = ResourceName.CPU
MEM = ResourceName.MEMORY


# ---------------------------------------------------------------------------
# water_filling golden cases (hand-derived from runtime_quota_calculator.go)
# ---------------------------------------------------------------------------

def test_water_filling_proportional_share():
    # both adjustable, equal weight, no clamping: remaining split evenly
    rt = water_filling(100, [50, 100], [10, 20], [0, 0], [1, 1], [True, True])
    assert rt == [45, 55]  # 10+35, 20+35


def test_water_filling_clamp_and_repool():
    # A clamps at its request; surplus re-pooled into B
    rt = water_filling(100, [12, 100], [10, 20], [0, 0], [1, 1], [True, True])
    assert rt == [12, 88]


def test_water_filling_non_lent_keeps_min():
    # non-lent group keeps autoScaleMin even with request below it
    rt = water_filling(100, [5, 100], [30, 0], [0, 0], [1, 1], [False, True])
    assert rt == [30, 70]


def test_water_filling_lent_gives_request():
    rt = water_filling(100, [5, 100], [30, 0], [0, 0], [1, 1], [True, True])
    assert rt == [5, 95]


def test_water_filling_guarantee_overrides_min():
    # guarantee > min raises autoScaleMin
    rt = water_filling(100, [50, 100], [10, 20], [40, 0], [1, 1], [True, True])
    # A: auto 40, B: auto 20; remaining 40 -> +20 each; A clamps at its
    # request 50, the surplus 10 re-pools into B: [50, 50]
    assert rt == [50, 50]


def test_water_filling_zero_weight_no_distribution():
    rt = water_filling(100, [50, 50], [10, 10], [0, 0], [0, 0], [True, True])
    assert rt == [10, 10]  # nothing distributed beyond autoScaleMin


def test_water_filling_overcommitted_total():
    # remaining <= 0: only the base allocation
    rt = water_filling(25, [50, 100], [10, 20], [0, 0], [1, 1], [True, True])
    assert rt == [10, 20]


def test_water_filling_float64_vs_exact_rational():
    # the two delta roundings agree except on float64 artifacts; randomized
    for _ in range(200):
        k = int(RNG.integers(2, 6))
        total = int(RNG.integers(0, 100_000))
        req = RNG.integers(0, 50_000, k).tolist()
        mn = RNG.integers(0, 10_000, k).tolist()
        w = RNG.integers(0, 100, k).tolist()
        lent = (RNG.uniform(size=k) < 0.8).tolist()
        a = water_filling(total, req, mn, [0] * k, w, lent, exact_rational=False)
        b = water_filling(total, req, mn, [0] * k, w, lent, exact_rational=True)
        assert sum(np.abs(np.array(a) - np.array(b))) <= k  # off-by-rounding only
        # conservation: the distributed total never exceeds max(total, Σ base)
        # plus half-up rounding slack (one unit per group per round, exactly
        # like the reference's +0.5 per node)
        base = [
            mn[i] if (req[i] > mn[i] or not lent[i]) else req[i] for i in range(k)
        ]
        assert sum(a) <= max(total, sum(base)) + k
        # runtime never exceeds the request for adjustable groups
        for i in range(k):
            if lent[i]:
                assert a[i] <= max(req[i], mn[i])


# ---------------------------------------------------------------------------
# GroupQuotaManager (tree semantics)
# ---------------------------------------------------------------------------

def _vec(cpu=0, mem=0):
    v = np.zeros(NUM_RESOURCES, dtype=np.int64)
    v[CPU] = cpu
    v[MEM] = mem
    return v


def test_manager_flat_tree_runtime_and_admission():
    mgr = GroupQuotaManager(cluster_total={CPU: 100_000, MEM: 200_000})
    mgr.update_quota(QuotaSpec(name="a", min={CPU: 10_000}, max={CPU: 80_000},
                               shared_weight={CPU: 1}))
    mgr.update_quota(QuotaSpec(name="b", min={CPU: 20_000}, max={CPU: 100_000},
                               shared_weight={CPU: 1}))
    # requests exceed mins -> adjustable; remaining split by weight
    mgr.add_request("a", _vec(cpu=50_000))
    mgr.add_request("b", _vec(cpu=100_000))
    rt_a = mgr.refresh_runtime("a")
    rt_b = mgr.refresh_runtime("b")
    assert rt_a[CPU] == 45_000   # 10k + 35k
    assert rt_b[CPU] == 55_000   # 20k + 35k

    # admission: used + req <= runtime (requests above were already
    # registered, runtime for a is 45k)
    mgr.add_used("a", _vec(cpu=44_000))
    assert mgr.can_admit("a", _vec(cpu=1_000))
    assert not mgr.can_admit("a", _vec(cpu=2_000))


def test_manager_hierarchy_parent_runtime_caps_children():
    mgr = GroupQuotaManager(cluster_total={CPU: 100_000})
    mgr.update_quota(QuotaSpec(name="team", parent=None, is_parent=True,
                               min={CPU: 0}, max={CPU: 40_000},
                               shared_weight={CPU: 1}))
    mgr.update_quota(QuotaSpec(name="team/x", parent="team",
                               min={CPU: 0}, max={CPU: 100_000},
                               shared_weight={CPU: 1}))
    mgr.update_quota(QuotaSpec(name="team/y", parent="team",
                               min={CPU: 0}, max={CPU: 100_000},
                               shared_weight={CPU: 1}))
    mgr.add_request("team/x", _vec(cpu=50_000))
    mgr.add_request("team/y", _vec(cpu=50_000))
    # team's limited request = min(100k, max 40k) = 40k -> team runtime 40k
    # (whole cluster is free), split evenly between x and y
    rt_x = mgr.refresh_runtime("team/x")
    rt_y = mgr.refresh_runtime("team/y")
    assert rt_x[CPU] == 20_000
    assert rt_y[CPU] == 20_000
    assert mgr.quotas["team"].runtime[CPU] == 40_000


def test_manager_request_propagates_limited():
    mgr = GroupQuotaManager(cluster_total={CPU: 100_000})
    mgr.update_quota(QuotaSpec(name="p", is_parent=True, min={}, max={CPU: 30_000}))
    mgr.update_quota(QuotaSpec(name="p/c", parent="p", min={}, max={CPU: 10_000}))
    mgr.add_request("p/c", _vec(cpu=50_000))
    # child's limited request is 10k; parent sees only 10k
    assert mgr.quotas["p/c"].request[CPU] == 50_000
    assert mgr.quotas["p"].child_request[CPU] == 10_000


def test_manager_non_preemptible_against_min():
    mgr = GroupQuotaManager(cluster_total={CPU: 100_000})
    mgr.update_quota(QuotaSpec(name="a", min={CPU: 5_000}, max={CPU: 50_000}))
    mgr.add_request("a", _vec(cpu=4_000), non_preemptible=True)
    mgr.add_used("a", _vec(cpu=4_000), non_preemptible=True)
    # incoming pods register their request at creation (OnPodAdd), then the
    # PreFilter admission check runs
    mgr.add_request("a", _vec(cpu=1_000), non_preemptible=True)
    assert mgr.can_admit("a", _vec(cpu=1_000), non_preemptible=True)
    mgr.add_request("a", _vec(cpu=1_000))  # second pod's request
    assert not mgr.can_admit("a", _vec(cpu=2_000), non_preemptible=True)
    # preemptible pod can exceed min (up to runtime)
    assert mgr.can_admit("a", _vec(cpu=2_000), non_preemptible=False)


def test_manager_system_default_reduce_total():
    mgr = GroupQuotaManager(cluster_total={CPU: 100_000})
    mgr.update_quota(QuotaSpec(name="system", min={}, max={CPU: 1 << 40}))
    mgr.update_quota(QuotaSpec(name="a", min={CPU: 0}, max={CPU: 200_000},
                               shared_weight={CPU: 1}))
    mgr.add_used("system", _vec(cpu=30_000))
    mgr.add_request("a", _vec(cpu=100_000))
    rt = mgr.refresh_runtime("a")
    assert rt[CPU] == 70_000  # total minus system used


# ---------------------------------------------------------------------------
# device path == oracle
# ---------------------------------------------------------------------------

def _random_quota_state(q):
    mn = np.zeros((q, NUM_RESOURCES), dtype=np.int64)
    mx = np.zeros((q, NUM_RESOURCES), dtype=np.int64)
    mn[:, CPU] = RNG.integers(0, 20_000, q)
    mn[:, MEM] = RNG.integers(0, 40_000, q)
    mx[:, CPU] = mn[:, CPU] + RNG.integers(0, 200_000, q)
    mx[:, MEM] = mn[:, MEM] + RNG.integers(0, 400_000, q)
    guar = (mn * RNG.uniform(0, 1.5, mn.shape)).astype(np.int64)
    auto_min = np.maximum(mn, guar)
    weight = np.zeros((q, NUM_RESOURCES), dtype=np.int64)
    weight[:, CPU] = RNG.integers(0, 1 << 20, q)  # exercises normalization
    weight[:, MEM] = RNG.integers(0, 50, q)
    allow = RNG.uniform(size=q) < 0.8
    total = np.zeros(NUM_RESOURCES, dtype=np.int64)
    total[CPU] = RNG.integers(0, 500_000)
    total[MEM] = RNG.integers(0, 1_000_000)
    return mn, mx, auto_min, weight, allow, total


def test_device_water_filling_matches_oracle():
    for _ in range(25):
        q = int(RNG.integers(2, 12))
        mn, mx, auto_min, weight, allow, total = _random_quota_state(q)
        req = np.minimum(
            (mx * RNG.uniform(0, 1.2, mx.shape)).astype(np.int64), mx
        )
        weight_n = normalize_weights(weight)
        got = np.asarray(
            water_filling_device(
                jnp.asarray(total, jnp.int32),
                jnp.asarray(req, jnp.int32),
                jnp.asarray(auto_min, jnp.int32),
                jnp.asarray(weight_n, jnp.int32),
                jnp.asarray(allow),
            )
        )
        for r in (CPU, MEM):
            want = water_filling(
                int(total[r]), req[:, r], mn[:, r], auto_min[:, r],
                weight_n[:, r].astype(np.int64), allow, exact_rational=True,
            )
            np.testing.assert_array_equal(got[:, r], np.asarray(want), err_msg=f"dim {r}")


def test_quota_gated_solver_matches_oracle():
    # BASELINE config #3 shape at test scale: pods across quota groups
    n, p, q = 25, 120, 6
    mn, mx, auto_min, weight, allow, total = _random_quota_state(q)
    weight_n = normalize_weights(weight)

    alloc = np.zeros((n, NUM_RESOURCES), dtype=np.int64)
    alloc[:, CPU] = RNG.choice([32000, 64000], n)
    alloc[:, MEM] = RNG.choice([65536, 131072], n)
    total[CPU] = alloc[:, CPU].sum()
    total[MEM] = alloc[:, MEM].sum()

    req = np.zeros((p, NUM_RESOURCES), dtype=np.int64)
    req[:, CPU] = RNG.choice([1000, 2000, 4000], p)
    req[:, MEM] = RNG.choice([2048, 4096], p)
    est = (req * 85) // 100
    quota_id = RNG.integers(-1, q, p).astype(np.int32)
    non_pre = RNG.uniform(size=p) < 0.3

    zeros2 = np.zeros((n, NUM_RESOURCES), dtype=np.int64)
    state = NodeState(
        alloc=jnp.asarray(alloc, jnp.int32),
        used_req=jnp.asarray(zeros2, jnp.int32),
        usage=jnp.asarray(zeros2, jnp.int32),
        prod_usage=jnp.asarray(zeros2, jnp.int32),
        est_extra=jnp.asarray(zeros2, jnp.int32),
        prod_base=jnp.asarray(zeros2, jnp.int32),
        metric_fresh=jnp.ones(n, bool),
        schedulable=jnp.ones(n, bool),
    )
    pods = PodBatch.build(
        req=jnp.asarray(req, jnp.int32),
        est=jnp.asarray(est, jnp.int32),
        is_prod=jnp.zeros(p, bool),
        is_daemonset=jnp.zeros(p, bool),
        quota_id=jnp.asarray(quota_id),
        non_preemptible=jnp.asarray(non_pre),
    )
    w = np.zeros(NUM_RESOURCES, dtype=np.int64)
    w[CPU] = w[MEM] = 1
    params = ScoreParams(
        weights=jnp.asarray(w, jnp.int32),
        thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )
    # every pending pod's request registers with its quota at creation
    child_request = np.zeros((q, NUM_RESOURCES), dtype=np.int64)
    for i in range(p):
        if quota_id[i] >= 0:
            child_request[quota_id[i]] += req[i]
    qstate = QuotaState.build(
        min=mn,
        max=mx,
        guarantee=auto_min,
        weight=weight,  # raw weights: build() normalizes
        allow_lent=allow,
        child_request=child_request,
        total=total,
    )
    (_, final_q), got = schedule_batch(state, pods, params, SolverConfig(), qstate)

    oracle_q = SequentialQuota(mn, mx, auto_min, weight_n.astype(np.int64), allow, total)
    want = schedule_sequential_quota(
        alloc, zeros2, zeros2, zeros2, zeros2, zeros2,
        np.ones(n, bool), np.ones(n, bool),
        req, est, np.zeros(p, bool), np.zeros(p, bool),
        quota_id, non_pre, oracle_q,
        w, np.zeros(NUM_RESOURCES, np.int64), np.zeros(NUM_RESOURCES, np.int64),
    )
    np.testing.assert_array_equal(np.asarray(got), np.array(want))
    # both placed and quota-rejected pods must occur for a meaningful test
    got_np = np.asarray(got)
    assert (got_np >= 0).any() and (got_np < 0).any()
    # device-side accounting matches the oracle's
    np.testing.assert_array_equal(np.asarray(final_q.used), oracle_q.used)
