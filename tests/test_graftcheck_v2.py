"""graftcheck v2: whole-program passes — per-rule self-tests + teeth.

The ISSUE 9 layers, mirroring test_graftcheck.py's structure:

1. each new rule detects its seeded-violation fixture
   (``tests/fixtures/graftcheck/``) and stays quiet on the seeded
   clean paths beside it;
2. the real repo's lock graph is ACYCLIC and non-vacuous (the known
   cross-class edges are present — an empty graph would pass an
   acyclicity check for the wrong reason);
3. injected violations in REAL source fail loudly: a cross-module
   ``jax.device_get`` two calls below the hot path, a read-after-
   donate in the staging cache, and the PR 11 shape itself — the
   donated scatter with its pin guard stripped;
4. the runtime lock-order shim detects a seeded inversion and stays
   quiet on reentrant/ordered acquisitions (its chaos-suite teeth live
   in test_chaos.py/test_pipeline.py as autouse fixtures);
5. the CLI's ``--changed-files`` incremental mode still runs the
   whole-program passes and reports per-rule wall time in JSON.
"""

import ast
import json
import threading
from pathlib import Path

import pytest

from koordinator_tpu.analysis.graftcheck import (
    ModuleFile,
    default_rules,
    load_allowlist,
    load_module,
    run_checks,
)
from koordinator_tpu.analysis.graftcheck.callgraph import (
    Program,
    build_program,
)
from koordinator_tpu.analysis.graftcheck.engine import (
    iter_repo_modules,
    run_checks_timed,
)
from koordinator_tpu.analysis.graftcheck.rules import (
    DeterminismRule,
    DonationRule,
    LOCK_NODES,
    LockNode,
    LockOrderRule,
    PinSpec,
    SyncReachRule,
)
from koordinator_tpu.analysis.graftcheck.rules.lock_order import (
    build_lock_graph,
    find_cycles,
)

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "graftcheck"


def _fixture(name: str) -> ModuleFile:
    rel = f"tests/fixtures/graftcheck/{name}"
    return load_module(FIXTURES / name, rel)


@pytest.fixture(scope="module")
def repo_program():
    return build_program(list(iter_repo_modules(REPO)))


# -- 1. the new rules detect their seeded fixtures ---------------------------

def test_sync_reach_fixture_detected():
    helper = _fixture("sync_reach_helper.py")
    hot = _fixture("sync_reach_hot.py")
    rule = SyncReachRule(
        scope=("tests/fixtures/graftcheck/sync_reach_hot.py",)
    )
    violations = rule.check_program(Program([helper, hot]))
    assert violations, "cross-module sync leak not detected"
    assert {v.func for v in violations} == {"hot_schedule"}, (
        "hot_clean must not flag; only the leaking call site does"
    )
    v = violations[0]
    assert v.symbol == "jax.device_get"
    assert "sync_reach_helper.py" in v.message
    assert v.path.endswith("sync_reach_hot.py")


def test_lock_cycle_fixture_detected():
    module = _fixture("lock_cycle_bad.py")
    path = "tests/fixtures/graftcheck/lock_cycle_bad.py"
    rule = LockOrderRule(locks=(
        LockNode(path=path, class_name="CacheA", lock="_lock"),
        LockNode(path=path, class_name="CacheB", lock="_lock"),
    ))
    violations = rule.check_program(Program([module]))
    assert len(violations) == 1, [v.format() for v in violations]
    v = violations[0]
    assert "CacheA._lock" in v.symbol and "CacheB._lock" in v.symbol
    assert "potential deadlock" in v.message


def test_donation_fixture_detected():
    module = _fixture("donate_bad.py")
    path = "tests/fixtures/graftcheck/donate_bad.py"
    rule = DonationRule(pin_specs=(
        PinSpec(path=path, class_name="PinnedCache", attr="state",
                pin_attr="_pinned"),
    ))
    violations = rule.check_program(Program([module]))
    by_func = {v.func for v in violations}
    assert by_func == {
        "read_after_donate", "loop_redonate", "PinnedCache.unguarded",
    }, [v.format() for v in violations]
    # the guard shapes stay quiet: reassign-at-call, temporary args,
    # and the pin-guarded branch
    for quiet in ("safe_reassign", "safe_temporary",
                  "PinnedCache.guarded"):
        assert quiet not in by_func


def test_determinism_fixture_detected():
    module = _fixture("determinism_bad.py")
    rule = DeterminismRule(
        scope=("tests/fixtures/graftcheck/determinism_bad.py",)
    )
    violations = rule.check(module)
    by_func = {v.func for v in violations}
    assert by_func == {
        "clock_into_device", "clock_into_wire", "rng_into_wire",
        "unseeded_draw_into_device", "set_order_into_device",
    }, [v.format() for v in violations]
    # direct source calls keep their chain as the label; values that
    # flowed through a binding carry the binding name
    assert all("bit-parity poisoned" in v.message for v in violations)
    labels = {v.symbol for v in violations}
    assert "stamp" in labels and "nonce" in labels


# -- 2. the real repo's lock graph: acyclic AND non-vacuous ------------------

def test_repo_lock_graph_acyclic_and_populated(repo_program):
    edges, _ = build_lock_graph(repo_program, LOCK_NODES)
    assert find_cycles(edges) == [], "lock-order cycle in the repo"
    pairs = {(e.held, e.acquired) for e in edges}
    # the load-bearing cross-class orders this PR documents (§18): an
    # empty graph would be vacuously acyclic — pin the known edges
    assert ("SchedulerCache._lock", "ClusterDeltaTracker._lock") in pairs
    assert ("StagedStateCache._lock", "ClusterDeltaTracker._lock") in pairs
    assert ("StateAuditor._lock", "StagedStateCache._lock") in pairs
    assert ("DeviceObservatory._profile_io_lock",
            "DeviceObservatory._lock") in pairs
    assert len(pairs) >= 10, sorted(pairs)


def test_repo_wide_clean_with_v2_rules(repo_program):
    violations, _, stats = run_checks_timed(
        repo_program.modules, default_rules(),
        load_allowlist(REPO / "graftcheck.toml"),
    )
    assert violations == [], "\n".join(v.format() for v in violations)
    # all nine rules ran and are individually clean
    assert set(stats) >= {
        "sync-reach", "lock-order", "donation-safety",
        "determinism-taint",
    }
    assert all(s["violations"] == 0 for s in stats.values())


# -- 3. injected violations in REAL source fail loudly -----------------------

def _reparse(path: str, source: str) -> ModuleFile:
    return ModuleFile(path=path, tree=ast.parse(source, filename=path),
                      source=source)


def _run_with_replacement(path: str, source: str):
    mods = {
        m.path: m for m in iter_repo_modules(REPO)
    }
    mods[path] = _reparse(path, source)
    return run_checks(
        list(mods.values()), default_rules(),
        load_allowlist(REPO / "graftcheck.toml"),
    )


def test_injected_cross_module_device_get_fails():
    """A ``jax.device_get`` seeded into the host oracle — a module NO
    local rule scope names, two calls below the hot path
    (``PlacementModel._host_solve`` → ``schedule_vectorized``) — must
    fail the check interprocedurally."""
    path = "koordinator_tpu/oracle/vectorized.py"
    source = (REPO / path).read_text()
    lines = source.split("\n")
    for i, line in enumerate(lines):
        if line.startswith("def schedule_vectorized("):
            j = i
            while not lines[j].rstrip().endswith(":"):
                j += 1
            lines.insert(j + 1, "    import jax; jax.device_get(alloc)")
            break
    else:
        pytest.fail("schedule_vectorized anchor not found")
    violations, _ = _run_with_replacement(path, "\n".join(lines))
    reach = [v for v in violations if v.rule == "sync-reach"]
    assert reach, "buried cross-module device_get not detected"
    assert any(
        v.func == "PlacementModel._host_solve"
        and v.symbol == "jax.device_get" for v in reach
    ), [v.format() for v in reach]


_DONATED_ANCHOR = """\
                                cur = self.state
                                self.state = WORKING_SET.run_staged(
                                    self._ws_key, "scatter",
                                    lambda: scatter_node_rows_donated(
                                        cur, jnp.asarray(sidx), srows,
                                    ),
                                )"""


def test_injected_read_after_donate_fails():
    """The PR 11 clobber class, liveness half: keep an alias to the
    donated generation and read it after the dispatch."""
    path = "koordinator_tpu/models/placement.py"
    source = (REPO / path).read_text()
    assert _DONATED_ANCHOR in source
    injected = source.replace(
        _DONATED_ANCHOR,
        _DONATED_ANCHOR + "\n                                _ = cur.alloc",
    )
    violations, _ = _run_with_replacement(path, injected)
    hits = [v for v in violations if v.rule == "donation-safety"]
    assert any(
        v.func == "StagedStateCache._ensure" and v.symbol == "cur"
        for v in hits
    ), [v.format() for v in hits]


_PIN_GUARD_ANCHOR = """\
                            if (self.state is self._pinned
                                    or self.model._node_shards > 1):"""

_PIN_GUARD_REPLACEMENT = """\
                            if self.model._node_shards > 1:"""


def test_injected_unguarded_donation_fails():
    """The PR 11 clobber class, pin half: drop the pin disjunct from
    the copied/donated routing so the donated scatter's else-branch no
    longer proves not-pinned — the exact pre-fix shape, now
    machine-rejected."""
    path = "koordinator_tpu/models/placement.py"
    source = (REPO / path).read_text()
    assert _PIN_GUARD_ANCHOR in source, (
        "pin-guard anchor drifted — update the fixture"
    )
    injected = source.replace(_PIN_GUARD_ANCHOR, _PIN_GUARD_REPLACEMENT)
    violations, _ = _run_with_replacement(path, injected)
    hits = [v for v in violations if v.rule == "donation-safety"]
    assert any(
        v.func == "StagedStateCache._ensure"
        and v.symbol == "self.state"
        and "pinned" in v.message for v in hits
    ), [v.format() for v in hits]


# -- 4. the runtime shim -----------------------------------------------------

def test_runtime_shim_detects_inversion():
    from koordinator_tpu.testing.lockorder import (
        LockOrderShim,
        _CheckedLock,
    )

    shim = LockOrderShim(
        static_edges=[("A._lock", "B._lock")], lock_map=[]
    )
    shim.enabled = True
    a = _CheckedLock(threading.Lock(), "A._lock", shim)
    b = _CheckedLock(threading.Lock(), "B._lock", shim)
    with a:
        with b:
            pass  # consistent with the static order
    assert shim.violations == []
    with b:
        with a:  # inversion: B held, A acquired, static says A before B
            pass
    assert len(shim.violations) == 1
    v = shim.violations[0]
    assert v["kind"] == "order-inversion"
    assert (v["held"], v["acquired"]) == ("B._lock", "A._lock")


def test_runtime_shim_reentrant_and_same_class():
    from koordinator_tpu.testing.lockorder import (
        LockOrderShim,
        _CheckedLock,
    )

    shim = LockOrderShim(static_edges=[], lock_map=[])
    shim.enabled = True
    r = _CheckedLock(threading.RLock(), "C._lock", shim)
    with r:
        with r:  # same-instance reentry: legal, no edge
            pass
    assert shim.violations == []
    d1 = _CheckedLock(threading.Lock(), "D._lock", shim)
    d2 = _CheckedLock(threading.Lock(), "D._lock", shim)
    with d1:
        with d2:  # two instances of one class nested: deadlock shape
            pass
    assert [v["kind"] for v in shim.violations] == [
        "same-class-nesting"
    ]


def test_runtime_shim_instruments_real_classes():
    """install() wraps new instances of the mapped classes and the
    obs singletons; acquisitions are observed and uninstall restores
    the constructors."""
    from koordinator_tpu.scheduler.cache import SchedulerCache
    from koordinator_tpu.testing.lockorder import (
        LockOrderShim,
        _CheckedLock,
    )

    shim = LockOrderShim.from_static_analysis()
    orig_init = SchedulerCache.__init__
    with shim:
        from koordinator_tpu.apis.types import NodeSpec

        cache = SchedulerCache()
        assert isinstance(cache._lock, _CheckedLock)
        cache.add_node(NodeSpec(name="n0", allocatable={}))
        assert shim.acquisitions > 0
        assert shim.violations == []
    assert SchedulerCache.__init__ is orig_init


# -- 5. CLI: incremental mode + per-rule stats -------------------------------

def test_cli_changed_files_json(capsys):
    from koordinator_tpu.analysis.graftcheck.__main__ import main

    rc = main([
        "--changed-files=koordinator_tpu/models/placement.py",
        "--format=json",
    ])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["violation_count"] == 0
    assert payload["changed_files"] == [
        "koordinator_tpu/models/placement.py"
    ]
    rules = payload["rules"]
    # whole-program passes ran despite the narrowed local set
    for name in ("sync-reach", "lock-order", "donation-safety"):
        assert name in rules and rules[name]["violations"] == 0
    assert all("wall_s" in s for s in rules.values())


def test_lock_order_reentrant_self_edge_suppressed():
    """An RLock-backed class legally re-acquires its own lock through
    sibling-method calls; the static pass must not report that as a
    self-edge deadlock — while a non-reentrant class with the same
    shape still flags."""
    import textwrap

    src = textwrap.dedent('''
        import threading

        class Re:
            def __init__(self):
                self._lock = threading.RLock()
            def outer(self):
                with self._lock:
                    return self.inner()
            def inner(self):
                with self._lock:
                    return 1
    ''')
    path = "tests/fixtures/graftcheck/_reentrant_virtual.py"
    module = ModuleFile(path=path, tree=ast.parse(src), source=src)
    flagged = LockOrderRule(locks=(
        LockNode(path=path, class_name="Re", lock="_lock"),
    )).check_program(Program([module]))
    assert len(flagged) == 1 and "Re._lock" in flagged[0].symbol
    quiet = LockOrderRule(locks=(
        LockNode(path=path, class_name="Re", lock="_lock",
                 reentrant=True),
    )).check_program(Program([module]))
    assert quiet == []


def test_changed_files_still_reports_missing_justification(tmp_path):
    """The incremental mode may skip staleness for unscanned entries —
    but a missing `reason` needs no rescan and must fail even when the
    entry's file is outside the changed set (check.sh's default mode)."""
    from koordinator_tpu.analysis.graftcheck.engine import (
        load_allowlist as _load,
    )

    toml = tmp_path / "graftcheck.toml"
    toml.write_text(
        '[[allow]]\nrule = "host-sync"\n'
        'path = "koordinator_tpu/models/placement.py"\n'
    )
    violations, _, _ = run_checks_timed(
        iter_repo_modules(REPO), default_rules(), _load(toml),
        changed=["koordinator_tpu/ops/binpack.py"],
    )
    rules = {v.rule for v in violations}
    assert "allowlist-justification" in rules
    # staleness for the same unscanned entry stays unknowable
    assert "stale-allowlist" not in rules


def test_changed_files_does_not_flag_unscanned_allowlist_stale():
    """An incremental run over a file with no allowlisted syncs must
    not report the OTHER files' entries as stale — their rules never
    rescanned them."""
    allowlist = load_allowlist(REPO / "graftcheck.toml")
    violations, _, _ = run_checks_timed(
        iter_repo_modules(REPO), default_rules(), allowlist,
        changed=["koordinator_tpu/ops/binpack.py"],
    )
    assert [v for v in violations if v.rule == "stale-allowlist"] == []
