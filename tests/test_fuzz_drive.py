"""Randomized cross-component drive: the whole wired control plane under
a seeded random op stream, with global invariants after every step.

Prior rounds found their real bugs by DRIVING wired surfaces, not by
unit tests (standby binding observation, normalization-vs-burst quota
clobber, node-capacity-unknown holding scale-ups). This drive
randomizes the inputs the units never combine: pod arrivals with
mixed QoS/quota/gangs, deletions mid-gang, node cordons and removals,
stale and missing metrics, descheduler sweeps with migrations, and
checks the invariants no single component owns:

1. stickiness — an assigned pod never moves without a migration job;
2. fit — per-node assigned native-CPU requests fit allocatable;
3. quota — every quota's used == Σ assigned member requests;
4. gangs — a STRICT gang is all-or-nothing: placed members number
   either 0 or >= min_member;
5. cordon — a node cordoned at step t receives no NEW placements;
6. liveness — deleted pods vanish from the scheduler cache.
"""

import numpy as np
import pytest

from koordinator_tpu.apis.extension import QoSClass, ResourceName as R
from koordinator_tpu.apis.types import (
    GangMode,
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
    ReservationSpec,
    ReservationState,
    resources_to_vector,
)
from koordinator_tpu.client import APIServer, Kind, wire_scheduler
from koordinator_tpu.client.wiring import wire_descheduler
from koordinator_tpu.descheduler import (
    Descheduler,
    LowNodeLoad,
    LowNodeLoadArgs,
    MigrationEvictor,
    NodePool,
    Profile,
)
from koordinator_tpu.scheduler import Scheduler

NODE_CPU, NODE_MEM = 16000, 32768


def _drive(seed: int, rounds: int = 60) -> dict:
    # NOTE on compile-cache pressure: reservation-bearing snapshots
    # would trace a fresh program per raw [P,V] match shape and the
    # accumulated executables exhaust the process mmap budget (the
    # conftest per-module clear can't help within one module). The
    # model's reservation-axis bucketing (PlacementModel.resv_bucket)
    # collapses V to power-of-two buckets, so drives reuse each other's
    # programs instead — no per-drive cache clearing needed.
    rng = np.random.default_rng(seed)
    bus = APIServer()
    scheduler = Scheduler()
    wire_scheduler(bus, scheduler)
    desch = wire_descheduler(bus, Descheduler(
        profiles=[Profile(name="lnl", balance_plugins=[LowNodeLoad(
            LowNodeLoadArgs(node_pools=[NodePool(
                low_thresholds={R.CPU: 30}, high_thresholds={R.CPU: 70},
            )])
        )])],
        evictor=MigrationEvictor(),
    ))

    for q in ("qa", "qb"):
        bus.apply(Kind.QUOTA, q, QuotaSpec(
            name=q, min={R.CPU: 8000, R.MEMORY: 16384},
            max={R.CPU: 60000, R.MEMORY: 120000},
        ))

    n_nodes = int(rng.integers(6, 14))
    for i in range(n_nodes):
        bus.apply(Kind.NODE, f"n{i}", NodeSpec(
            name=f"n{i}", allocatable={R.CPU: NODE_CPU, R.MEMORY: NODE_MEM},
        ))

    next_id = 0
    next_gang = 0
    next_resv = 0
    live: list = []
    gang_min: dict = {}
    cordoned: set = set()
    placements: dict = {}
    migrated: set = set()
    owner_keys: list = []
    #: allocate_once reservations' consumer count at SUCCEEDED time
    consumed_now: dict = {}
    stats = {"placed": 0, "migrated": 0, "gangs": 0, "deleted": 0,
             "cordons": 0, "reservations": 0, "resv_consumed": 0}

    def arrive_plain():
        nonlocal next_id
        # some arrivals carry a reservation owner label so live
        # reservations actually get matched and consumed
        labels = {}
        if owner_keys and rng.random() < 0.5:
            labels = {"own": str(rng.choice(owner_keys))}
        pod = PodSpec(
            name=f"p{next_id}",
            qos=[QoSClass.LS, QoSClass.BE, QoSClass.NONE][next_id % 3],
            priority=int(rng.choice([9500, 5500, 3000])),
            requests={R.CPU: int(rng.integers(200, 4000)),
                      R.MEMORY: int(rng.integers(256, 4096))},
            quota=str(rng.choice(["qa", "qb"])),
            labels=labels,
        )
        next_id += 1
        bus.apply(Kind.POD, pod.uid, pod)
        live.append(pod.uid)

    def reserve():
        nonlocal next_resv
        key = f"w{next_resv}"
        spec = ReservationSpec(
            name=f"r{next_resv}",
            node_name=f"n{int(rng.integers(0, n_nodes))}",
            state=ReservationState.AVAILABLE,
            requests={R.CPU: int(rng.integers(2000, 8000)),
                      R.MEMORY: int(rng.integers(1024, 8192))},
            owner_labels={"own": key},
            allocate_once=bool(rng.random() < 0.4),
        )
        spec.allocatable = dict(spec.requests)
        next_resv += 1
        owner_keys.append(key)
        bus.apply(Kind.RESERVATION, spec.name, spec)
        stats["reservations"] += 1

    def arrive_gang():
        nonlocal next_id, next_gang
        size = int(rng.integers(2, 6))
        name = f"g{next_gang}"
        next_gang += 1
        gang_min[name] = size
        stats["gangs"] += 1
        bus.apply(Kind.GANG, name, GangSpec(
            name=name, min_member=size, total_member=size,
            mode=GangMode.STRICT,
        ))
        cpu = int(rng.integers(200, 3000))
        for _ in range(size):
            pod = PodSpec(
                name=f"p{next_id}", gang=name,
                requests={R.CPU: cpu, R.MEMORY: 512},
                quota=str(rng.choice(["qa", "qb"])),
            )
            next_id += 1
            bus.apply(Kind.POD, pod.uid, pod)
            live.append(pod.uid)

    def delete_pod():
        if len(live) < 4:
            return
        victim = live.pop(int(rng.integers(0, len(live))))
        bus.delete(Kind.POD, victim)
        placements.pop(victim, None)
        stats["deleted"] += 1

    def cordon():
        name = f"n{int(rng.integers(0, n_nodes))}"
        node = bus.get(Kind.NODE, name)
        import dataclasses

        bus.apply(Kind.NODE, name,
                  dataclasses.replace(node, unschedulable=True))
        cordoned.add(name)
        stats["cordons"] += 1

    def publish_metrics(now, stale_frac):
        by_node: dict = {}
        for pod in bus.list(Kind.POD).values():
            if pod.node_name is not None:
                by_node.setdefault(pod.node_name, []).append(pod)
        for i in range(n_nodes):
            name = f"n{i}"
            if rng.random() < stale_frac:
                continue  # metric withheld this round
            on_node = by_node.get(name, [])
            cpu = sum(p.requests.get(R.CPU, 0) for p in on_node)
            boost = 9000 if rng.random() < 0.15 else 300
            bus.apply(Kind.NODE_METRIC, name, NodeMetric(
                node_name=name,
                node_usage={R.CPU: min(cpu + boost, NODE_CPU),
                            R.MEMORY: 2048},
                pod_usages={
                    p.uid: {R.CPU: p.requests.get(R.CPU, 0),
                            R.MEMORY: p.requests.get(R.MEMORY, 0)}
                    for p in on_node
                },
                update_time=now,
            ))

    for step in range(rounds):
        t = 100.0 + 30.0 * step
        # random op mix
        roll = rng.random()
        if roll < 0.5:
            arrive_plain()
        elif roll < 0.7:
            arrive_gang()
        elif roll < 0.85:
            delete_pod()
        elif roll < 0.92:
            reserve()
        elif roll < 0.95 and len(cordoned) < n_nodes - 2:
            cordon()

        publish_metrics(t, stale_frac=0.1)
        pre_placed = {
            uid: p.node_name for uid, p in bus.list(Kind.POD).items()
            if p.node_name is not None
        }
        scheduler.schedule_pending(now=t + 1)
        if step > 8 and step % 4 == 0:
            migrated.update(desch.run_once(now=t + 2))
            scheduler.schedule_pending(now=t + 3)

        # -- invariants ---------------------------------------------------
        pods_on_bus = bus.list(Kind.POD)
        per_node: dict = {}
        per_gang_placed: dict = {}
        for uid, pod in pods_on_bus.items():
            if pod.gang:
                per_gang_placed.setdefault(pod.gang, 0)
            if pod.node_name is None:
                continue
            prev = placements.get(uid)
            if prev is not None and prev != pod.node_name:
                assert uid in migrated, (
                    f"seed {seed} step {step}: {uid} moved {prev} -> "
                    f"{pod.node_name} without migration"
                )
            placements[uid] = pod.node_name
            per_node[pod.node_name] = (
                per_node.get(pod.node_name, 0) + pod.requests.get(R.CPU, 0)
            )
            if pod.gang:
                per_gang_placed[pod.gang] = (
                    per_gang_placed.get(pod.gang, 0) + 1
                )
            # 5. no NEW placement on a cordoned node
            if pod.node_name in cordoned and uid not in pre_placed:
                raise AssertionError(
                    f"seed {seed} step {step}: {uid} newly placed on "
                    f"cordoned {pod.node_name}"
                )
        for name, used in per_node.items():
            node = bus.get(Kind.NODE, name)
            assert used <= node.allocatable[R.CPU], (
                f"seed {seed} step {step}: {name} overcommitted {used}"
            )
        # 4. strict gangs all-or-nothing (members still pending count 0)
        for gname, placed_count in per_gang_placed.items():
            need = gang_min.get(gname)
            if need is None:
                continue
            # deletions can shrink a previously-satisfied gang below
            # min_member; only gangs with no deletions are bound by the
            # gate
            members_alive = sum(
                1 for p in pods_on_bus.values() if p.gang == gname
            )
            if members_alive >= need:
                assert placed_count == 0 or placed_count >= need, (
                    f"seed {seed} step {step}: strict gang {gname} "
                    f"partially placed {placed_count}/{need}"
                )
        # 3. quota accounting
        for qname in ("qa", "qb"):
            info = scheduler.quota_manager.quotas.get(qname)
            if info is None:
                continue
            want_cpu = sum(
                p.requests.get(R.CPU, 0)
                for p in pods_on_bus.values()
                if p.quota == qname and p.node_name is not None
            )
            got = int(np.asarray(info.used, dtype=np.int64)[R.CPU])
            assert got == want_cpu, (
                f"seed {seed} step {step}: quota {qname} used {got} != "
                f"pods {want_cpu}"
            )
        # 6. no leaked cache holds
        for uid, cached in scheduler.cache.pods.items():
            if cached.node_name is not None:
                assert uid in pods_on_bus, (
                    f"seed {seed} step {step}: cache holds deleted {uid}"
                )
        # 7. reservation accounting: allocated never exceeds allocatable,
        #    consumers are real pods, and a SUCCEEDED allocate_once
        #    reservation stops admitting new consumers
        live_resv = bus.list(Kind.RESERVATION)
        for rname, spec in live_resv.items():
            # allocatable falls back to requests when unset (migration
            # reservations) — same rule as reservation_free
            alloc_vec = resources_to_vector(spec.allocatable or spec.requests)
            used_vec = resources_to_vector(spec.allocated)
            assert (used_vec <= alloc_vec).all(), (
                f"seed {seed} step {step}: reservation {rname} "
                f"over-allocated {spec.allocated} > {spec.allocatable}"
            )
            for uid in spec.allocated_pod_uids:
                assert uid.startswith("default/p"), uid
            if (spec.allocate_once
                    and getattr(spec.state, "value", spec.state)
                    == "Succeeded"):
                consumed_now.setdefault(rname, len(spec.allocated_pod_uids))
                assert len(spec.allocated_pod_uids) == consumed_now[rname], (
                    f"seed {seed} step {step}: SUCCEEDED allocate_once "
                    f"{rname} kept admitting consumers"
                )
        stats["resv_consumed"] = max(
            stats["resv_consumed"],
            sum(len(s.allocated_pod_uids) for s in live_resv.values()),
        )

    stats["placed"] = sum(
        1 for p in bus.list(Kind.POD).values() if p.node_name is not None
    )
    stats["migrated"] = len(migrated)
    return stats


#: drive results by seed — the aggregate check reuses the per-seed
#: test runs instead of re-running all eight drives (halves the
#: module's wall time)
_DRIVE_STATS: dict = {}


def _drive_cached(seed: int) -> dict:
    if seed not in _DRIVE_STATS:
        _DRIVE_STATS[seed] = _drive(seed)
    return _DRIVE_STATS[seed]


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_drive(seed):
    stats = _drive_cached(seed)
    assert stats["placed"] > 5  # the drive genuinely scheduled work


def test_fuzz_coverage_aggregate():
    """Across the seeds, every op class and outcome must actually have
    occurred — no vacuously green fuzzing."""
    total = {"placed": 0, "migrated": 0, "gangs": 0, "deleted": 0,
             "cordons": 0, "reservations": 0, "resv_consumed": 0}
    for seed in range(8):
        stats = _drive_cached(seed)
        for k in total:
            total[k] += stats[k]
    assert total["placed"] > 100
    assert total["gangs"] > 10
    assert total["deleted"] > 20
    assert total["cordons"] > 3
    assert total["migrated"] >= 1
    assert total["reservations"] > 5
    assert total["resv_consumed"] > 0  # reservations really got consumed
