"""The arbitrated eviction control plane (docs/DESIGN.md §27).

Units for :class:`MigrationArbiter` (budget semantics, typed refusal
precedence, replay determinism) and :class:`DefragController` (the
closed defrag loop's hysteresis/cooldown policy and its observation
replay), the zero-budget bit-identity contracts (arbiter wired with the
unlimited default must leave preemption and defrag_headroom
bit-identical to the legacy no-arbiter paths), and the chaos
eviction-storm property: a seeded storm under arbitration never exceeds
any declared budget in any window, never cascades, defers with typed +
counted refusals only, and lands final placements + node accounting
bit-identical to a fault-free control arm.
"""

import numpy as np
import pytest

from koordinator_tpu.apis.extension import (
    PriorityClass,
    QoSClass,
    ResourceName,
)
from koordinator_tpu.apis.types import (
    GangSpec,
    NodeSpec,
    PodSpec,
    resources_to_vector,
)
from koordinator_tpu.control.migration import (
    REASONS,
    SOURCES,
    DefragController,
    DefragPolicy,
    MigrationArbiter,
    MigrationBudget,
    replay_requests,
)
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.ops.binpack import STAGED_NODE_FIELDS
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.state.cluster import lower_nodes
from koordinator_tpu.testing.chaos import (
    EVICTION_STORM_FAULT_KINDS,
    FaultSchedule,
    eviction_storm_world,
)

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY


# -- arbiter units -----------------------------------------------------------


def test_unlimited_default_admits_everything():
    arb = MigrationArbiter(clock=lambda: 100.0)
    v = arb.request("preemption", "n1", ["a", "b", "c"],
                    lanes=["be", "be", "ls"])
    assert v.admitted == ("a", "b", "c")
    assert v.deferred == ()
    assert v.apply
    assert arb.budget().unlimited
    assert len(arb.decisions()) == 1
    assert arb.decisions()[0]["admitted"] == ["a", "b", "c"]


def test_round_budget_caps_and_resets():
    arb = MigrationArbiter(MigrationBudget(max_per_round=2))
    arb.begin_round(1)
    v = arb.request("preemption", "n1", ["a", "b", "c"], now=0.0)
    assert v.admitted == ("a", "b")
    assert v.deferred == (("c", "round-budget"),)
    # a later request in the SAME round sees the spent cap
    v2 = arb.request("rebalance", "n2", ["d"], now=1.0)
    assert v2.deferred == (("d", "round-budget"),)
    # a new round resets the per-round count (windows are per-node/lane)
    arb.begin_round(2)
    v3 = arb.request("rebalance", "n2", ["d"], now=2.0)
    assert v3.admitted == ("d",)


def test_node_budget_window_purges():
    arb = MigrationArbiter(MigrationBudget(max_per_node=2, window_s=10.0))
    assert arb.request("defrag", "n1", ["a", "b"], now=0.0).admitted == (
        "a", "b")
    v = arb.request("defrag", "n1", ["c"], now=5.0)
    assert v.deferred == (("c", "node-budget"),)
    # another node is unaffected
    assert arb.request("defrag", "n2", ["d"], now=5.0).admitted == ("d",)
    # past the window the node's budget refills
    v2 = arb.request("defrag", "n1", ["c"], now=11.0)
    assert v2.admitted == ("c",)


def test_tenant_budget_per_lane():
    arb = MigrationArbiter(MigrationBudget(max_per_tenant=1))
    v = arb.request("rebalance", "n1", ["a", "b", "c"],
                    lanes=["be", "be", "ls"], now=0.0)
    # one per lane: the second BE victim defers, the LS victim admits
    assert v.admitted == ("a", "c")
    assert v.deferred == (("b", "tenant-budget"),)


def test_node_cooldown_arms_within_batch():
    arb = MigrationArbiter(MigrationBudget(node_cooldown_s=10.0))
    v = arb.request("rebalance", "n1", ["a", "b"], now=0.0)
    # the first admission arms the cooldown for the rest of the batch
    assert v.admitted == ("a",)
    assert v.deferred == (("b", "cooldown"),)
    assert arb.request("rebalance", "n1", ["c"], now=5.0).deferred == (
        ("c", "cooldown"),)
    assert arb.request("rebalance", "n1", ["c"], now=10.0).admitted == (
        "c",)
    # a node-less request (no cooldown key) is never cooldown-deferred
    assert arb.request("workingset", None, ["d"], now=10.5).admitted == (
        "d",)


def test_gang_min_available_guard():
    arb = MigrationArbiter()
    v = arb.request(
        "preemption", "n1", ["a", "b", "c"],
        gangs=["g1", "g1", None], gang_headroom={"g1": 1}, now=0.0,
    )
    # gang g1 may lose ONE more member; the second defers typed
    assert v.admitted == ("a", "c")
    assert v.deferred == (("b", "gang-min-available"),)
    # the admitted loss is remembered across requests in the window
    v2 = arb.request("preemption", "n2", ["d"], gangs=["g1"],
                     gang_headroom={"g1": 1}, now=1.0)
    assert v2.deferred == (("d", "gang-min-available"),)


def test_refusal_precedence_order():
    # REASONS is the check precedence: a victim violating several
    # budgets counts under the first
    assert REASONS == ("cooldown", "round-budget", "node-budget",
                       "tenant-budget", "gang-min-available")
    arb = MigrationArbiter(MigrationBudget(
        max_per_round=1, max_per_node=1, node_cooldown_s=100.0,
    ))
    arb.begin_round(1)
    assert arb.request("preemption", "n1", ["a"], now=0.0).admitted == (
        "a",)
    # now violates cooldown AND round AND node budgets: typed cooldown
    v = arb.request("preemption", "n1", ["b"], now=1.0)
    assert v.deferred == (("b", "cooldown"),)
    # off-node (no cooldown key): the round budget wins next
    v2 = arb.request("preemption", "n2", ["c"], now=1.0)
    assert v2.deferred == (("c", "round-budget"),)


def test_all_or_nothing_defers_whole_batch():
    arb = MigrationArbiter(MigrationBudget(max_per_node=1))
    v = arb.request("preemption", "n1", ["a", "b"], now=0.0,
                    all_or_nothing=True)
    # the batch refusal is typed by the first violation; the member
    # that would have been admitted defers under the same reason
    assert v.admitted == ()
    assert v.deferred == (("a", "node-budget"), ("b", "node-budget"))
    # nothing committed: a divisible request still has the full budget
    assert arb.request("preemption", "n1", ["a"], now=0.0).admitted == (
        "a",)


def test_dry_run_classifies_without_acting():
    arb = MigrationArbiter(MigrationBudget(max_per_node=1, dry_run=True))
    v = arb.request("rebalance", "n1", ["a", "b"], now=0.0)
    assert not v.apply
    assert v.admitted == ("a",)
    assert v.deferred == (("b", "node-budget"),)
    assert v.record["dry_run"]
    # no window bookkeeping committed: the same classification repeats
    v2 = arb.request("rebalance", "n1", ["a", "b"], now=1.0)
    assert v2.admitted == ("a",) and not v2.apply


def test_note_is_undeferrable_and_counted():
    arb = MigrationArbiter(MigrationBudget(max_per_node=1))
    # the working-set demotion already happened: recorded, never deferred
    arb.note("workingset", "n1", ["ws-a"], lanes=["be"], now=0.0)
    rec = arb.decisions()[-1]
    assert rec["undeferrable"] and rec["admitted"] == ["ws-a"]
    # ...and it spent the node's window budget: whole-truth accounting
    v = arb.request("rebalance", "n1", ["b"], now=1.0)
    assert v.deferred == (("b", "node-budget"),)
    # a second note on the same exhausted node still lands
    arb.note("workingset", "n1", ["ws-b"], lanes=["be"], now=2.0)
    assert arb.decisions()[-1]["admitted"] == ["ws-b"]


def test_set_budget_keeps_window_history():
    arb = MigrationArbiter(MigrationBudget(max_per_node=5))
    assert len(arb.request("defrag", "n1", ["a", "b", "c"],
                           now=0.0).admitted) == 3
    # the mid-wave squeeze: new caps judge already-admitted evictions
    arb.set_budget(MigrationBudget(max_per_node=3))
    v = arb.request("defrag", "n1", ["d"], now=1.0)
    assert v.deferred == (("d", "node-budget"),)


def test_replay_requests_bit_identical():
    budget = MigrationBudget(max_per_round=3, max_per_node=2,
                             max_per_tenant=2, window_s=30.0,
                             node_cooldown_s=0.0)
    arb = MigrationArbiter(budget)
    arb.begin_round(1)
    arb.request("preemption", "n1", ["a", "b", "c"],
                lanes=["be", "be", "ls"], now=0.0)
    arb.note("workingset", "n2", ["w1"], lanes=["be"], now=1.0)
    arb.begin_round(2)
    arb.request("rebalance", "n1", ["d"], now=2.0, all_or_nothing=True)
    arb.request("defrag", "n3", ["e", "f"], gangs=["g", "g"],
                gang_headroom={"g": 1}, now=40.0)
    records = arb.decisions()
    assert replay_requests(budget, records) == records


def test_unknown_source_and_misaligned_lanes_raise():
    arb = MigrationArbiter()
    with pytest.raises(ValueError):
        arb.request("gremlin", "n1", ["a"])
    with pytest.raises(ValueError):
        arb.request("defrag", "n1", ["a", "b"], lanes=["be"])
    with pytest.raises(ValueError):
        arb.note("gremlin", "n1", ["a"])


def test_status_and_flight_payload_shapes():
    arb = MigrationArbiter(MigrationBudget(max_per_node=1))
    arb.begin_round(7)
    arb.request("rebalance", "n1", ["a", "b"], now=0.0)
    status = arb.status()
    assert status["requests_total"] == 2
    assert status["admitted_total"] == 1
    assert status["deferred_total"] == 1
    assert status["deferred_by_reason"] == {"node-budget": 1}
    assert status["round"] == 7 and status["round_admitted"] == 1
    assert status["window_nodes"] == {"n1": 1}
    payload = arb.flight_payload()
    assert payload["deferred_total"] == 1
    assert payload["decisions"][-1]["deferred"] == [
        {"uid": "b", "reason": "node-budget"}]


# -- defrag controller units -------------------------------------------------


def _frag_obs(now, frag=True):
    return {"seq": 0, "now": now, "frag": frag, "gang": "g1",
            "demand": [4000, 8192, 0, 0, 0, 0, 0, 0][:],
            "max_victim_priority": 5000, "pending_gangs": 1,
            "total_free": []}


def test_defrag_policy_confirm_streak_and_cooldown():
    ctl = DefragController(scheduler=None,
                           policy=DefragPolicy(confirm=2, cooldown_s=30.0))
    assert ctl.step(_frag_obs(0.0)) is None          # streak 1 < confirm
    d = ctl.step(_frag_obs(1.0))
    assert d is not None and d["signal"] == "frag-over"
    # cooldown: confirmed streaks inside the quiet period do not act
    assert ctl.step(_frag_obs(2.0)) is None
    assert ctl.step(_frag_obs(3.0)) is None
    # a clean observation resets the streak (hysteresis)
    assert ctl.step(_frag_obs(40.0, frag=False)) is None
    assert ctl.step(_frag_obs(41.0)) is None
    d2 = ctl.step(_frag_obs(42.0))
    assert d2 is not None
    assert ctl.decisions_total() == 2


def _fragmented_scheduler(arbiter=None):
    """Two half-full nodes whose aggregate holds a gang member that
    fits neither: textbook fragmentation the repack can fix."""
    sched = Scheduler(model=PlacementModel(use_pallas=False),
                      preemption_backend="host")
    sched.migration_arbiter = arbiter
    for i in range(2):
        sched.add_node(NodeSpec(
            name=f"f{i}", allocatable={CPU: 8000, MEM: 16384}))
        sched.add_pod(PodSpec(
            name=f"be-{i}", node_name=f"f{i}",
            requests={CPU: 5000, MEM: 10240}, qos=QoSClass.BE,
            priority=200, assign_time=float(i)))
    sched.cache.update_gang(GangSpec(name="g1", min_member=1))
    sched.add_pod(PodSpec(
        name="gang-member", gang="g1",
        requests={CPU: 6000, MEM: 12288}, qos=QoSClass.LS,
        priority_class=PriorityClass.PROD, priority=6000))
    return sched


def test_defrag_observe_detects_fragmentation():
    sched = _fragmented_scheduler()
    ctl = DefragController(sched)
    obs = ctl.observe(now=100.0)
    assert obs["frag"] and obs["gang"] == "g1"
    assert obs["demand"] == resources_to_vector(
        {CPU: 6000, MEM: 12288}).tolist()
    assert obs["max_victim_priority"] == 6000
    # drain one node: the hole now fits, the signal clears
    sched.remove_pod(sched.cache.pods[
        [u for u, p in sched.cache.pods.items() if p.name == "be-0"][0]])
    assert not ctl.observe(now=101.0)["frag"]


def test_defrag_reconcile_applies_through_arbiter():
    arb = MigrationArbiter()
    sched = _fragmented_scheduler(arbiter=arb)
    ctl = DefragController(
        sched, policy=DefragPolicy(interval_s=1.0, confirm=2,
                                   cooldown_s=30.0))
    assert ctl.reconcile(now=0.0) is None          # streak 1
    d = ctl.reconcile(now=2.0)
    assert d is not None
    assert d["outcome"]["node"] in ("f0", "f1")
    assert len(d["outcome"]["drains"]) == 1
    # the drain passed through the arbiter under the defrag source
    assert arb.decisions()[-1]["source"] == "defrag"
    assert arb.decisions()[-1]["admitted"] == d["outcome"]["drains"]
    # the interval gate: a reconcile inside it is a no-op
    assert ctl.maybe_reconcile(now=2.5) is None
    # the world is defragmented now: no further decisions
    assert ctl.reconcile(now=10.0) is None
    assert ctl.reconcile(now=12.0) is None


def test_defrag_dry_run_records_without_acting():
    sched = _fragmented_scheduler()
    ctl = DefragController(
        sched, policy=DefragPolicy(interval_s=1.0, confirm=1,
                                   dry_run=True))
    d = ctl.reconcile(now=0.0)
    assert d is not None and d["dry_run"]
    assert d["outcome"] == {"node": None, "drains": [],
                            "skipped": "dry-run"}
    # nothing was evicted: both residents still placed
    assert len(_placements(sched)) == 2


def test_defrag_replay_decisions():
    sched = _fragmented_scheduler(arbiter=MigrationArbiter())
    ctl = DefragController(
        sched, policy=DefragPolicy(interval_s=1.0, confirm=2,
                                   cooldown_s=5.0))
    for t in range(8):
        ctl.reconcile(now=float(t * 2))
    recorded = [dict(d) for d in ctl.status()["decisions"]]
    for d in recorded:
        d.pop("outcome", None)
    assert recorded, "the loop never decided"
    assert ctl.replay_decisions() == recorded


# -- zero-budget bit-identity ------------------------------------------------


def _storm_scheduler(arbiter, seed=3, n_nodes=8):
    nodes, residents, arrivals = eviction_storm_world(
        seed=seed, n_nodes=n_nodes)
    sched = Scheduler(model=PlacementModel(use_pallas=False),
                      preemption_backend="host")
    sched.migration_arbiter = arbiter
    for node in nodes:
        sched.add_node(node)
    for pod in residents:
        sched.add_pod(pod)
    for pod in arrivals:
        sched.add_pod(pod)
    return sched


def _placements(sched):
    return sorted((p.name, p.node_name)
                  for p in sched.cache.pods.values() if p.node_name)


def _run_storm(sched, ticks=6, saboteur=None):
    log = []
    for t in range(ticks):
        now = 100.0 + 2.0 * t
        if saboteur is not None:
            saboteur(t, now, sched)
        out = sched.schedule_pending(now=now)
        log.append((t, sorted(out.items()),
                    sorted(out.nominations.items())))
    return log


def test_zero_budget_preemption_bit_identical():
    """The arbiter wired with the unlimited default budget must leave a
    whole preemption storm bit-identical to the legacy no-arbiter path:
    same per-tick results, same nominations, same final placements,
    same staged node accounting."""
    legacy = _storm_scheduler(arbiter=None)
    arbitrated = _storm_scheduler(arbiter=MigrationArbiter())
    want = _run_storm(legacy)
    got = _run_storm(arbitrated)
    assert got == want
    assert _placements(arbitrated) == _placements(legacy)
    got_arrays = lower_nodes(arbitrated.cache.snapshot(now=200.0))
    want_arrays = lower_nodes(legacy.cache.snapshot(now=200.0))
    assert got_arrays.names == want_arrays.names
    for f in STAGED_NODE_FIELDS:
        np.testing.assert_array_equal(
            getattr(got_arrays, f), getattr(want_arrays, f),
            err_msg=f"node accounting diverged: {f}")
    # every eviction passed through the arbiter, none deferred
    status = arbitrated.migration_arbiter.status()
    assert status["admitted_total"] > 0
    assert status["deferred_total"] == 0


def test_zero_budget_defrag_bit_identical():
    legacy = _fragmented_scheduler()
    arbitrated = _fragmented_scheduler(arbiter=MigrationArbiter())
    target = resources_to_vector({CPU: 6000, MEM: 12288})
    want = legacy.defrag_headroom(target, 5000, apply=True, now=10.0)
    got = arbitrated.defrag_headroom(target, 5000, apply=True, now=10.0)
    assert got == want
    assert _placements(arbitrated) == _placements(legacy)


# -- the chaos eviction storm ------------------------------------------------


def _assert_budget_compliance(records, budget_at, skip_notes=True):
    """Walk the decision ring and re-check every admitted eviction
    against the budget in effect WHEN it was admitted: per-round,
    per-node/per-lane sliding windows, cooldowns. ``budget_at(seq)``
    returns the MigrationBudget governing that record."""
    node_times, lane_times = {}, {}
    node_last = {}
    round_counts = {}
    for rec in records:
        budget = budget_at(rec["seq"])
        now = rec["now"]
        horizon = now - budget.window_s
        for times in (node_times, lane_times):
            for key in list(times):
                times[key] = [t for t in times[key] if t > horizon]
        admitted = rec["admitted"]
        if rec.get("dry_run"):
            assert not rec.get("undeferrable")
            continue
        if rec.get("undeferrable") and skip_notes:
            # notes commit against windows but are exempt from caps
            for _ in admitted:
                node_times.setdefault(rec["node"], []).append(now)
            continue
        rnd = rec["round"]
        for i, uid in enumerate(admitted):
            lane = rec["lanes"][rec["uids"].index(uid)]
            if budget.max_per_round is not None and rnd is not None:
                assert round_counts.get(rnd, 0) < budget.max_per_round, (
                    f"round {rnd} over budget at {uid}")
                round_counts[rnd] = round_counts.get(rnd, 0) + 1
            if budget.max_per_node is not None and rec["node"]:
                assert len(node_times.get(rec["node"], [])) < \
                    budget.max_per_node, f"node window over at {uid}"
            if budget.max_per_tenant is not None and lane is not None:
                assert len(lane_times.get(lane, [])) < \
                    budget.max_per_tenant, f"lane window over at {uid}"
            if budget.node_cooldown_s > 0 and rec["node"]:
                last = node_last.get(rec["node"])
                assert last is None or now - last >= \
                    budget.node_cooldown_s, f"cooldown violated at {uid}"
            if rec["node"]:
                node_times.setdefault(rec["node"], []).append(now)
                node_last[rec["node"]] = now
            if lane is not None:
                lane_times.setdefault(lane, []).append(now)


@pytest.mark.chaos
def test_chaos_eviction_storm_budgets_and_identity():
    """The arbitration property (docs/DESIGN.md §27): a seeded
    unique-fit eviction storm — preemption waves, a mid-storm
    arbitrated rebalance wave, a budget squeeze mid-wave — driven
    through a tightly budgeted arbiter must (1) never exceed any
    declared budget in any window, (2) never cascade (each victim
    evicted at most once), (3) defer only with typed + counted
    refusals, and (4) land final placements and staged node accounting
    bit-identical to the fault-free control arm."""
    from koordinator_tpu.descheduler.loadaware import (
        LowNodeLoad,
        LowNodeLoadArgs,
    )
    from koordinator_tpu.metrics.components import MIGRATION_DEFERRALS

    N, TICKS = 10, 14
    schedule = FaultSchedule({
        2: "preemption-storm",        # the storm itself (seeded world)
        5: "rebalance-wave",          # an arbitrated Balance sweep
        7: "budget-squeeze-mid-wave",  # caps tightened against history
    })
    for kind in schedule.events.values():
        assert kind in EVICTION_STORM_FAULT_KINDS

    # ---- fault-free control arm (legacy: no arbiter, no faults) ------
    control = _storm_scheduler(arbiter=None, seed=11, n_nodes=N)
    _run_storm(control, ticks=TICKS)
    control_placed = _placements(control)
    assert len(control_placed) == N, "control arm never converged"

    # ---- the storm arm -----------------------------------------------
    loose = MigrationBudget(max_per_round=4, max_per_node=2,
                            max_per_tenant=6, window_s=3.0)
    tight = MigrationBudget(max_per_round=2, max_per_node=1,
                            max_per_tenant=3, window_s=3.0)
    arb = MigrationArbiter(loose)
    sched = _storm_scheduler(arbiter=arb, seed=11, n_nodes=N)
    plugin = LowNodeLoad(LowNodeLoadArgs(backend="host"))
    squeeze_seq = {"at": None}
    deferrals_before = {
        r: MIGRATION_DEFERRALS.value({"source": "preemption",
                                      "reason": r}) for r in REASONS}

    def saboteur(t, now, s):
        if schedule.fault_for(t) == "rebalance-wave":
            # full-cluster metrics absent -> the sweep classifies
            # nothing abnormal; the wave still exercises the arbitrated
            # sink end to end (an eviction here would be arbitrated)
            s.rebalance_sweep(plugin, now=now)
        if schedule.fault_for(t) == "budget-squeeze-mid-wave":
            arb.set_budget(tight)
            squeeze_seq["at"] = (arb.decisions() or [{}])[-1].get(
                "seq", 0)

    _run_storm(sched, ticks=TICKS, saboteur=saboteur)

    # (4) bit-identical convergence: deferrals reshuffled WHEN
    # evictions landed, never WHERE
    assert _placements(sched) == control_placed
    got = lower_nodes(sched.cache.snapshot(now=300.0))
    want = lower_nodes(control.cache.snapshot(now=300.0))
    assert got.names == want.names
    for f in STAGED_NODE_FIELDS:
        np.testing.assert_array_equal(
            getattr(got, f), getattr(want, f),
            err_msg=f"node accounting diverged: {f}")

    records = arb.decisions()
    # (1) no declared budget exceeded in any window, judged against
    # the budget in effect at each decision (the squeeze included)
    def budget_at(seq):
        at = squeeze_seq["at"]
        return loose if at is None or seq <= at else tight
    _assert_budget_compliance(records, budget_at)

    # (2) no cascade: every victim evicted at most once
    evicted = [u for rec in records if not rec.get("dry_run")
               for u in rec["admitted"]]
    assert len(evicted) == len(set(evicted))
    assert len(evicted) == N

    # (3) the storm actually deferred, every deferral typed + counted
    deferred = [d for rec in records for d in rec["deferred"]]
    assert deferred, "the tight budget never engaged"
    assert all(d["reason"] in REASONS for d in deferred)
    status = arb.status()
    assert status["deferred_total"] == len(deferred)
    assert sum(status["deferred_by_reason"].values()) == len(deferred)
    counted = sum(
        MIGRATION_DEFERRALS.value({"source": "preemption", "reason": r})
        - deferrals_before[r] for r in REASONS)
    assert counted == sum(
        1 for rec in records if rec["source"] == "preemption"
        for _ in rec["deferred"])

    # replay determinism holds under the FINAL budget for the post-
    # squeeze suffix of the ring (the squeeze point splits the replay)
    at = squeeze_seq["at"]
    suffix = [r for r in records if r["seq"] > at]
    assert replay_requests(tight, suffix) == suffix


@pytest.mark.chaos
def test_chaos_rebalance_wave_respects_budget():
    """A live LoadAware wave over an imbalanced cluster with an
    arbitrated evictor: evictions stop exactly at the declared node
    budget, the over-budget proposals surface as typed rebalance
    deferrals, and the sweep itself never errors."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_rebalance_oracle import RecordingEvictor, random_cluster

    from koordinator_tpu.descheduler.loadaware import (
        LowNodeLoad,
        LowNodeLoadArgs,
    )

    rng = np.random.default_rng(29)
    snapshot = random_cluster(rng)
    arb = MigrationArbiter(MigrationBudget(max_per_node=1))
    plugin = LowNodeLoad(LowNodeLoadArgs())

    # the unthrottled oracle arm: how many the wave WANTS to evict
    free = RecordingEvictor()
    plugin.balance(random_cluster(np.random.default_rng(29)), free)

    evictor = RecordingEvictor(arbiter=arb)
    plugin.balance(snapshot, evictor)
    per_node = {}
    for node, _uid in evictor.sequence:
        per_node[node] = per_node.get(node, 0) + 1
    assert all(c <= 1 for c in per_node.values()), per_node
    if len(free.sequence) > len(evictor.sequence):
        reasons = {d["reason"] for rec in arb.decisions()
                   for d in rec["deferred"]}
        assert reasons == {"node-budget"}
