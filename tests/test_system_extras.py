"""sysreconcile, core scheduling, kidled cold pages, pagecache
(remaining SURVEY §2.9 / coverage items 27-28/35).

Reference: pkg/koordlet/qosmanager/plugins/sysreconcile/system_config.go,
util/system/core_sched_linux.go, util/system/kidled_util.go,
metricsadvisor/collectors/{coldmemoryresource,pagecache}.
"""

import os

from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.metriccache import MetricCache, MetricKind
from koordinator_tpu.koordlet.metricsadvisor.collectors import (
    ColdMemoryCollector,
    PageCacheCollector,
)
from koordinator_tpu.koordlet.metricsadvisor.framework import CollectorContext
from koordinator_tpu.koordlet.qosmanager import QoSContext, SystemConfigReconcile
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.system.cgroup import SystemConfig
from koordinator_tpu.koordlet.system.core_sched import (
    CoreSched,
    FakeKernel,
    PIDTYPE_PID,
)
from koordinator_tpu.koordlet.system.kidled import (
    Kidled,
    parse_idle_page_stats,
)
from koordinator_tpu.manager.sloconfig import NodeSLOSpec, SystemStrategy


class NoPods:
    def running_pods(self):
        return []


def make_ctx(tmp_path, strategy, cap_mem=16384):
    cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"),
                       proc_root=str(tmp_path / "proc"))
    return QoSContext(
        metric_cache=MetricCache(),
        executor=ResourceUpdateExecutor(cfg, auditor=Auditor()),
        pod_provider=NoPods(),
        system_config=cfg,
        node_slo=NodeSLOSpec(system_strategy=strategy),
        node_capacity_mem_mib=cap_mem,
    )


class TestSysReconcile:
    def test_writes_vm_knobs(self, tmp_path):
        # 16 GiB node, factor 100/10000 -> min_free = 16*1024*1024*100/10000
        ctx = make_ctx(tmp_path, SystemStrategy(
            min_free_kbytes_factor=100, watermark_scale_factor=150,
        ), cap_mem=16384)
        s = SystemConfigReconcile()
        assert s.enabled(ctx)
        s.execute(ctx, now=1.0)
        vm = tmp_path / "proc" / "sys" / "vm"
        assert (vm / "min_free_kbytes").read_text() == str(
            16384 * 1024 * 100 // 10000
        )
        assert (vm / "watermark_scale_factor").read_text() == "150"

    def test_out_of_range_skipped(self, tmp_path):
        ctx = make_ctx(tmp_path, SystemStrategy(
            min_free_kbytes_factor=1, watermark_scale_factor=5000,
        ), cap_mem=64)  # 64 MiB * 1/10000 = 6 kbytes < floor
        SystemConfigReconcile().execute(ctx, now=1.0)
        vm = tmp_path / "proc" / "sys" / "vm"
        assert not (vm / "min_free_kbytes").exists()
        assert not (vm / "watermark_scale_factor").exists()


class TestCoreSched:
    def test_cookie_lifecycle_on_fake_kernel(self):
        kernel = FakeKernel()
        cs = CoreSched(prctl=kernel.prctl)
        assert cs.supported()
        assert cs.get(100) == 0
        assert cs.create(100, PIDTYPE_PID)
        cookie = cs.get(100)
        assert cookie and cookie > 0
        assert cs.assign_group_cookie(100, [101, 102]) == 2
        assert kernel.cookies[101] == cookie
        assert kernel.cookies[102] == cookie

    def test_unsupported_kernel(self):
        cs = CoreSched(prctl=FakeKernel(supported=False).prctl)
        assert not cs.supported()
        assert cs.get(1) is None


IDLE_STATS = """\
# version: 1.0
# scan_period_in_seconds: 120
# use_hierarchy: 1
# buckets: 1,2,5,15,30,60,120,240
cfei 0 0 100 200 300 0 0 0
dfei 0 0 0 50 0 0 0 0
cfui 0 0 0 0 25 0 0 0
dfui 0 0 0 0 0 0 0 0
csei 999 0 0 0 0 0 0 0
"""


class TestKidled:
    def test_parse_and_cold_bytes(self):
        stats = parse_idle_page_stats(IDLE_STATS)
        assert stats.scan_period_seconds == 120
        assert stats.use_hierarchy == 1
        assert stats.buckets == [1, 2, 5, 15, 30, 60, 120, 240]
        # boundary 3: buckets [15,+inf) -> cfei 200+300, dfei 50, cfui 25
        assert stats.cold_page_bytes(boundary=3) == 575
        # csei is not a cold-page class
        assert stats.cold_page_bytes(boundary=0) == 675

    def test_collector(self, tmp_path):
        cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"),
                           proc_root=str(tmp_path / "proc"))
        cfg.sysfs_root = str(tmp_path / "sys")
        kid_dir = tmp_path / "sys" / "kernel" / "mm" / "kidled"
        kid_dir.mkdir(parents=True)
        (kid_dir / "scan_period_in_seconds").write_text("120\n")
        mem_root = tmp_path / "cg" / "memory"
        mem_root.mkdir(parents=True)
        (mem_root / "memory.idle_page_stats").write_text(IDLE_STATS)

        mc = MetricCache()
        ctx = CollectorContext(metric_cache=mc, system_config=cfg)
        c = ColdMemoryCollector(cold_boundary=3)
        c.setup(ctx)
        assert c.enabled()
        c.collect(now=1.0)
        ts, vs = mc.query(MetricKind.NODE_COLD_PAGE_BYTES, None)
        assert list(vs) == [575.0]

        kidled = Kidled(cfg)
        kidled.set_scan_period(60)
        assert (kid_dir / "scan_period_in_seconds").read_text() == "60"


def test_pagecache_collector(tmp_path):
    proc = tmp_path / "proc"
    proc.mkdir()
    (proc / "meminfo").write_text(
        "MemTotal: 16384000 kB\nCached: 2048000 kB\n"
    )
    cfg = SystemConfig(cgroup_root=str(tmp_path / "cg"), proc_root=str(proc))
    mc = MetricCache()
    ctx = CollectorContext(metric_cache=mc, system_config=cfg)
    c = PageCacheCollector()
    c.setup(ctx)
    assert c.enabled()
    c.collect(now=1.0)
    ts, vs = mc.query(MetricKind.NODE_PAGE_CACHE_MIB, None)
    assert list(vs) == [2048000 / 1024.0]


def test_core_expeller_through_bvt_plugin():
    """The core-expeller path: BvtPlugin tags expeller-class pods' task
    groups with shared cookies via CoreSched (round-2 review wiring)."""
    from koordinator_tpu.apis.extension import QoSClass
    from koordinator_tpu.koordlet.metricsadvisor.framework import PodMeta
    from koordinator_tpu.koordlet.runtimehooks.groupidentity import BvtPlugin
    from koordinator_tpu.manager.sloconfig import (
        CPUQOS,
        QoSConfig,
        ResourceQOSStrategy,
    )

    kernel = FakeKernel()
    plugin = BvtPlugin(core_sched=CoreSched(prctl=kernel.prctl))
    plugin.update_rule(NodeSLOSpec(
        resource_qos_strategy=ResourceQOSStrategy(
            lsr=QoSConfig(enable=True,
                          cpu=CPUQOS(group_identity=2, core_expeller=True)),
            ls=QoSConfig(enable=True, cpu=CPUQOS(group_identity=2)),
        )
    ))
    assert QoSClass.LSR in plugin.rule.core_expeller_qos
    assert QoSClass.LS not in plugin.rule.core_expeller_qos

    pods = [
        PodMeta(uid="lsr1", cgroup_dir="kubepods/podlsr1", qos=QoSClass.LSR),
        PodMeta(uid="ls1", cgroup_dir="kubepods/burstable/podls1",
                qos=QoSClass.LS),
    ]
    pids = {"lsr1": [10, 11, 12], "ls1": [20]}
    tagged = plugin.apply_core_expeller(pods, lambda p: pids[p.uid])
    assert tagged == 1
    cookie = kernel.cookies[10]
    assert cookie > 0
    assert kernel.cookies[11] == cookie and kernel.cookies[12] == cookie
    assert 20 not in kernel.cookies  # LS has no expeller
