"""Chaos property tests: the failure-domain layer under deterministic
fault injection (ISSUE 4 tentpole §3).

The solver is integer arithmetic end to end (DESIGN.md §2), the local
failover path runs the same ``solve_batch`` program the sidecar does,
and the delta protocol recovers to full restages — so "a churn run
under injected faults ends bit-identical to a fault-free run" is a
TESTABLE property, not an aspiration. These tests drive a multi-tick
churn through a :class:`ChaosProxy` with a seeded/scripted
:class:`FaultSchedule` and assert exactly that: every tick completes,
and the final placements AND node accounting match the in-process
fault-free reference tick for tick.
"""

import threading
import time

import numpy as np
import pytest

from koordinator_tpu.apis.extension import ResourceName
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    NodeSpec,
    PodSpec,
)
from koordinator_tpu.client.bus import APIServer, EventType, Kind
from koordinator_tpu.client.leaderelection import LeaderElector
from koordinator_tpu.client.wiring import snapshot_from_bus, wire_scheduler
from koordinator_tpu.models.placement import PlacementModel
from koordinator_tpu.ops.binpack import STAGED_NODE_FIELDS
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.scheduler.auditor import StateAuditor
from koordinator_tpu.service.client import RemoteSolver
from koordinator_tpu.service.failover import FailoverSolver
from koordinator_tpu.service.supervisor import SolverSupervisor
from koordinator_tpu.state.cluster import ClusterDeltaTracker, lower_nodes
from koordinator_tpu.testing.chaos import (
    ChaosProxy,
    FaultSchedule,
    InProcessSidecar,
    StateSaboteur,
)

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY


@pytest.fixture(autouse=True, scope="module")
def _lock_order_under_chaos(lock_order_shim):
    """Every chaos scenario in this module — six wire fault kinds,
    state sabotage, kill-the-leader — runs under the lock-order shim:
    zero acquisitions may violate the statically-declared order
    (asserted at module teardown by the shim fixture)."""
    yield lock_order_shim


@pytest.fixture(autouse=True)
def _shape_flow_under_chaos(shape_flow_sentinel):
    """Every chaos scenario also runs inside a shape-flow sentinel
    window (ISSUE 15): any signature the compile ring observes during
    the scenario must be inside the statically-enumerated signature
    space — a recompile storm under fault injection fails here, not in
    a production tail (module teardown asserts zero violations and
    non-vacuity)."""
    shape_flow_sentinel.begin_window()
    yield
    shape_flow_sentinel.verify_window()


N_NODES = 16
PENDING_PER_TICK = 8
DIRTY_PER_TICK = 3
WARMUP_TICKS = 2  # empty-pending ticks that pay the compiles


def _build(seed):
    rng = np.random.default_rng(seed)
    nodes = [
        NodeSpec(
            name=f"n{i}",
            allocatable={CPU: int(rng.integers(16000, 64000)),
                         MEM: int(rng.integers(32768, 131072))},
        )
        for i in range(N_NODES)
    ]
    metrics = {
        n.name: NodeMetric(
            node_name=n.name,
            node_usage={CPU: int(rng.integers(0, 8000)),
                        MEM: int(rng.integers(0, 16384))},
            update_time=10.0,
        )
        for n in nodes
    }
    tracker = ClusterDeltaTracker()
    snap = ClusterSnapshot(
        nodes=nodes, pods=[], pending_pods=[], node_metrics=metrics,
        now=20.0, delta_tracker=tracker,
    )
    return snap, tracker


def _run_churn(model, ticks, seed, hooks=None, after_warmup=None):
    """The seeded churn: per tick, refresh a few node metrics, schedule
    a pending queue, bind the placements. Returns (per-tick placement
    log, final snapshot). ``hooks[tick]`` runs before that tick's solve
    (fault-free runs pass none — hooks must never touch the snapshot);
    ``after_warmup`` runs once, after the compile-warming empty ticks."""
    hooks = hooks or {}
    snap, tracker = _build(seed)
    rng = np.random.default_rng(seed + 1)
    log = []
    for t in range(WARMUP_TICKS):
        snap.pending_pods = []
        model.schedule(snap)  # same shapes as real ticks (bucket 64)
    if after_warmup is not None:
        after_warmup()
    for t in range(ticks):
        now = 30.0 + t
        for i in rng.choice(N_NODES, DIRTY_PER_TICK, replace=False):
            name = f"n{int(i)}"
            snap.node_metrics[name] = NodeMetric(
                node_name=name,
                node_usage={CPU: int(rng.integers(0, 12000)),
                            MEM: int(rng.integers(0, 32768))},
                update_time=now,
            )
            tracker.mark_node(name)
        snap.pending_pods = [
            PodSpec(
                name=f"t{t}p{j}",
                requests={CPU: int(rng.integers(200, 2000)),
                          MEM: int(rng.integers(128, 2048))},
            )
            for j in range(PENDING_PER_TICK)
        ]
        snap.now = now
        if t in hooks:
            hooks[t]()
        by_uid = {p.uid: p for p in snap.pending_pods}
        result = model.schedule(snap)
        log.append((t, sorted(result.items())))
        for uid, node in result.items():
            if node is not None:
                pod = by_uid[uid]
                pod.node_name = node
                pod.assign_time = now
                snap.pods.append(pod)
                tracker.mark_node(node)
        snap.pending_pods = []
    return log, snap


def _assert_identical(chaos_log, chaos_snap, ref_log, ref_snap):
    assert len(chaos_log) == len(ref_log)
    for (t_a, a), (t_b, b) in zip(chaos_log, ref_log):
        assert a == b, f"placements diverged at tick {t_a}"
    got = lower_nodes(chaos_snap)
    want = lower_nodes(ref_snap)
    assert got.names == want.names
    for f in STAGED_NODE_FIELDS:
        np.testing.assert_array_equal(
            getattr(got, f), getattr(want, f),
            err_msg=f"node accounting diverged: {f}",
        )


@pytest.mark.chaos
def test_chaos_smoke_transport_faults(tmp_path):
    """Quick signal (tools/check.sh chaos smoke): a 10-tick churn with
    torn, corrupted, and base-dropping frames on the wire completes
    every tick bit-identical to the fault-free run — the RemoteSolver
    retry machinery alone absorbs isolated transport faults."""
    solver_addr = str(tmp_path / "solver.sock")
    proxy_addr = str(tmp_path / "proxy.sock")
    sidecar = InProcessSidecar(solver_addr)
    schedule = FaultSchedule({
        4: "torn-response",
        6: "corrupt-response",
        8: "drop-base",
    })
    proxy = ChaosProxy(proxy_addr, solver_addr, schedule).start()
    try:
        remote = RemoteSolver(
            proxy_addr, timeout=30.0, retry_total_s=5.0,
            backoff_base_s=0.01, backoff_cap_s=0.05,
        )
        model = PlacementModel(backend=remote, use_pallas=False)
        log, snap = _run_churn(model, ticks=10, seed=11)
        ref_log, ref_snap = _run_churn(
            PlacementModel(use_pallas=False), ticks=10, seed=11
        )
        _assert_identical(log, snap, ref_log, ref_snap)
        # every scripted fault actually fired
        assert set(proxy.faults_injected) == {
            "torn-response", "corrupt-response", "drop-base"
        }
        remote.close()
    finally:
        proxy.stop()
        sidecar.kill()


@pytest.mark.chaos
def test_chaos_property_outage_failover_recovery(tmp_path):
    """The full property (acceptance criterion): a 44-tick churn under
    a scripted fault schedule — torn/corrupt/stalled/reset frames,
    forced base loss, and a sidecar SIGKILL mid-request — completes
    EVERY tick. The supervisor restarts the killed sidecar, the
    failover backend flips to degraded and back with hysteresis (with
    the flip-back epoch reset), and the final placements plus node
    accounting are bit-identical to a fault-free run."""
    solver_addr = str(tmp_path / "solver.sock")
    proxy_addr = str(tmp_path / "proxy.sock")
    ticks = 44

    handle_holder = []

    def spawn():
        handle = InProcessSidecar(solver_addr)
        handle_holder.append(handle)
        return handle

    # the supervisor is deliberately SLOWER than TWO ticks' retry
    # budgets (2 x 0.8s deadline): the outage must span the failover
    # threshold so the machine actually flips — a faster restart heals
    # inside the client's own retries (correct, but not the property
    # under test; the first run of this test proved exactly that)
    supervisor = SolverSupervisor(
        solver_addr,
        spawn_fn=spawn,
        probe_interval_s=0.3,
        probe_timeout_s=0.2,
        ready_timeout_s=30.0,
        backoff_base_s=4.0,  # jittered to [2.0, 4.0]s before respawn
        backoff_cap_s=4.0,
    ).start()

    schedule = FaultSchedule({
        6: "torn-response",
        10: "corrupt-response",
        14: "stall",
        18: "drop-base",
        22: "reset-request",
        26: "kill-server",
    })
    proxy = ChaosProxy(
        proxy_addr, solver_addr, schedule,
        kill_fn=lambda: handle_holder[-1].kill(),
        stall_s=1.2,
    ).start()

    remote = RemoteSolver(
        proxy_addr, timeout=30.0, retries=1,
        backoff_base_s=0.01, backoff_cap_s=0.05,
    )
    backend = FailoverSolver(remote, failure_threshold=2,
                             recovery_probes=2)
    model = PlacementModel(backend=backend, use_pallas=False)
    backend.on_flip_back = model.reset_staging

    def arm_deadline():
        # warmup solved with no deadline (the sidecar's cold compile may
        # exceed any sane budget); churn ticks carry one so a stalled
        # frame becomes a typed SolverDeadlineExceeded, not a hang
        remote.deadline_s = 0.8

    def wait_supervised_restart():
        # deterministic recovery point: by this tick the SIGKILL fault
        # has fired; block until the supervisor's respawn passes its
        # readiness probes so the remaining ticks exercise flip-back
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if (supervisor.status()["state"] == "running"
                    and len(handle_holder) > 1):
                return
            time.sleep(0.05)
        raise AssertionError("supervisor never restarted the sidecar")

    modes = []
    original_schedule = model.schedule

    def observing_schedule(snap):
        out = original_schedule(snap)
        modes.append(model.last_solver)
        return out

    model.schedule = observing_schedule

    try:
        log, snap = _run_churn(
            model, ticks=ticks, seed=29,
            hooks={30: wait_supervised_restart},
            after_warmup=arm_deadline,
        )
        ref_log, ref_snap = _run_churn(
            PlacementModel(use_pallas=False), ticks=ticks, seed=29
        )
        # ---- the property: every tick completed, bit-identical -------
        assert len(log) == ticks
        _assert_identical(log, snap, ref_log, ref_snap)
        # ---- the machinery actually exercised its states -------------
        status = backend.status()
        assert status["flips_to_degraded"] >= 1
        assert status["flips_to_remote"] >= 1
        assert not status["degraded"]  # recovered by the end
        assert supervisor.restarts_total >= 1
        assert len(handle_holder) >= 2  # a respawn really happened
        assert "kill-server" in proxy.faults_injected
        degraded_ticks = sum(
            1 for m in modes if m in ("local-fallback", "local-degraded")
        )
        assert degraded_ticks >= 1
        assert modes[-1] == "remote"  # post-recovery ticks went remote
        # flip-back re-established the wire base from a full restage
        assert remote.last_request in ("establish", "delta")
    finally:
        proxy.stop()
        supervisor.stop()
        backend.close()


# ---------------------------------------------------------------------------
# ISSUE 5: two-scheduler kill-the-leader chaos + the anti-entropy auditor
# ---------------------------------------------------------------------------

AUDIT_NODES = 8
AUDIT_TICKS = 24
KILL_TICK = 10       # the leader is SIGKILLed between rounds, mid-churn
SWEEP_EVERY = 4      # auditor cadence in rounds


def _drive_cluster(seed, *, kill_leader, sabotage):
    """Seeded churn over a wired bus. ``kill_leader=True`` runs TWO
    leader-elected schedulers, stops ticking the leader at KILL_TICK
    (the observable behavior of SIGKILL from the bus's seat), and lets
    the standby promote; corruptions from ``sabotage`` (a FaultSchedule
    events dict over STATE_FAULT_KINDS) are injected into the STANDBY —
    the state a newly promoted leader inherits. ``kill_leader=False``
    is the crash-free single-scheduler reference. Lease timings are
    chosen so failover costs zero rounds (tick gap 2.0 > lease 1.0):
    bit-identity against the reference is then a hard assertion, not a
    race. Returns (per-tick placement log, bus, info)."""
    rng = np.random.default_rng(seed)
    bus = APIServer()
    binds = {}
    prev_node = {}

    def bind_watch(event, name, pod):
        node = getattr(pod, "node_name", None)
        if event is EventType.DELETED:
            prev_node.pop(pod.uid, None)
            return
        if node is not None and prev_node.get(pod.uid) != node:
            binds[pod.uid] = binds.get(pod.uid, 0) + 1
        prev_node[pod.uid] = node

    bus.watch(Kind.POD, bind_watch)
    info = {"binds": binds}
    if kill_leader:
        sched_a = Scheduler(model=PlacementModel(use_pallas=False))
        sched_b = Scheduler(model=PlacementModel(use_pallas=False))
        ea = LeaderElector(bus, "koord-scheduler", "a", lease_duration=1.0)
        eb = LeaderElector(bus, "koord-scheduler", "b", lease_duration=1.0)
        aud_a = StateAuditor(sched_a, bus, interval_rounds=SWEEP_EVERY,
                             probe_rows=AUDIT_NODES)
        aud_b = StateAuditor(sched_b, bus, interval_rounds=SWEEP_EVERY,
                             probe_rows=AUDIT_NODES)
        ea.on_started_leading = aud_a.note_promotion
        eb.on_started_leading = aud_b.note_promotion
        wire_scheduler(bus, sched_a, elector=ea)
        wire_scheduler(bus, sched_b, elector=eb)
        saboteur = StateSaboteur(
            FaultSchedule(sabotage), sched_b, seed=seed
        )
        seats = [(ea, sched_a, aud_a), (eb, sched_b, aud_b)]
        info.update(aud_a=aud_a, aud_b=aud_b, saboteur=saboteur,
                    sched_b=sched_b)
    else:
        sched = Scheduler(model=PlacementModel(use_pallas=False))
        wire_scheduler(bus, sched)
        saboteur = None
        seats = [(None, sched, None)]

    CPU, MEM = ResourceName.CPU, ResourceName.MEMORY
    for i in range(AUDIT_NODES):
        bus.apply(Kind.NODE, f"n{i}", NodeSpec(
            name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
        bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
            node_name=f"n{i}",
            node_usage={CPU: int(rng.integers(0, 8000)),
                        MEM: int(rng.integers(0, 16384))},
            update_time=90.0))

    log = []
    for t in range(AUDIT_TICKS):
        now = 100.0 + 2.0 * t
        for i in rng.choice(AUDIT_NODES, 2, replace=False):
            name = f"n{int(i)}"
            bus.apply(Kind.NODE_METRIC, name, NodeMetric(
                node_name=name,
                node_usage={CPU: int(rng.integers(0, 12000)),
                            MEM: int(rng.integers(0, 32768))},
                update_time=now))
        for j in range(4):
            pod = PodSpec(
                name=f"t{t}p{j}",
                requests={CPU: int(rng.integers(200, 2000)),
                          MEM: int(rng.integers(128, 2048))})
            bus.apply(Kind.POD, pod.uid, pod)
        if saboteur is not None:
            saboteur.inject(t)
        out = None
        for elector, sched, auditor in seats:
            if elector is None:
                out = sched.schedule_pending(now=now)
                continue
            if kill_leader and elector is seats[0][0] and t >= KILL_TICK:
                continue  # SIGKILLed: the deposed leader never ticks again
            if elector.tick(now):
                auditor.on_round(now=now)
                out = sched.schedule_pending(now=now)
        assert out is not None, f"no leader ran tick {t}"
        log.append((t, sorted(out.items())))
    return log, bus, info


@pytest.mark.chaos
def test_chaos_audit_kill_leader_promotion_sweep():
    """The ISSUE 5 acceptance property: SIGKILL the leader mid-churn
    with cache/staging corruptions planted in the standby; the standby
    promotes, the promotion sweep audits and repairs BEFORE its first
    solve, a later periodic sweep catches the staged-row desync through
    the parity probe, and the run finishes with placements AND node
    accounting bit-identical to a crash-free run, zero double-binds —
    and every injected corruption detected AND repaired with the
    matching scheduler_audit_* counter incremented."""
    from koordinator_tpu.metrics.components import (
        AUDIT_DETECTIONS,
        AUDIT_REPAIRS,
    )

    sabotage = {
        3: "corrupt-cache-cell",   # standby cache lies about a placement
        5: "orphan-assume",        # ghost assume with no pod behind it
        14: "desync-staged-row",   # staged row drifts, no tracker mark
    }
    watched = (
        ("cache-bus", "stale-pod"),
        ("cache-bus", "orphan-assume"),
        ("device-parity", "staged-host-drift"),
        ("device-parity", "staged-device-drift"),
    )
    det_before = {
        (b, k): AUDIT_DETECTIONS.value({"boundary": b, "kind": k})
        for b, k in watched
    }
    rep_before = {
        a: AUDIT_REPAIRS.value({"action": a})
        for a in ("targeted", "full-restage")
    }

    live_log, live_bus, info = _drive_cluster(
        31, kill_leader=True, sabotage=sabotage)
    ref_log, ref_bus, _ = _drive_cluster(
        31, kill_leader=False, sabotage={})

    # ---- bit-identical to the crash-free run, tick for tick ----------
    assert live_log == ref_log
    got = lower_nodes(snapshot_from_bus(live_bus, now=200.0))
    want = lower_nodes(snapshot_from_bus(ref_bus, now=200.0))
    assert got.names == want.names
    for f in STAGED_NODE_FIELDS:
        np.testing.assert_array_equal(
            getattr(got, f), getattr(want, f),
            err_msg=f"node accounting diverged: {f}")

    # ---- zero double-binds (fencing + single-leader rounds) ----------
    assert info["binds"], "churn never bound anything"
    assert all(c == 1 for c in info["binds"].values()), info["binds"]

    # ---- every corruption was injected, detected, and repaired -------
    assert info["saboteur"].injected == {
        "corrupt-cache-cell": 1, "orphan-assume": 1,
        "desync-staged-row": 1,
    }
    status_b = info["aud_b"].status()
    assert status_b["sweeps"]["promotion"] == 1  # once per acquisition
    # the standby's detections are EXACTLY the injected drift — the
    # healthy rounds around them produce zero false positives
    assert status_b["detections"] == {
        "cache-bus/stale-pod": 1,
        "cache-bus/orphan-assume": 1,
        "device-parity/staged-host-drift": 1,
        "device-parity/staged-device-drift": 1,
    }
    assert status_b["repairs"]["targeted"] == 2
    assert status_b["repairs"]["full-restage"] == 1
    assert status_b["last"]["unrepaired"] == []
    # the deposed leader's sweeps saw a healthy cache: no detections
    assert info["aud_a"].status()["detections"] == {}
    # no repair happened uncounted: the global metric series moved in
    # lockstep with the per-instance counts
    for b, k in watched:
        delta = AUDIT_DETECTIONS.value(
            {"boundary": b, "kind": k}) - det_before[(b, k)]
        assert delta == status_b["detections"][f"{b}/{k}"]
    assert AUDIT_REPAIRS.value(
        {"action": "targeted"}) - rep_before["targeted"] == 2
    assert AUDIT_REPAIRS.value(
        {"action": "full-restage"}) - rep_before["full-restage"] == 1


# -- ISSUE 13: the kill-the-leader property rides the AOT warm pool ----------

@pytest.mark.chaos
def test_chaos_restart_storm_warm_restores(tmp_path, xla_compiles):
    """The warm-pool leg of the kill-the-leader chaos property
    (ISSUE 13 / DESIGN §21): SIGKILL the leader K times in a row
    mid-churn; each standby promotes with a POPULATED pool. Every
    promotion warm-restores — after the first generation the process
    performs ZERO XLA recompiles (the ``xla_compiles`` fixture) and the
    process-wide monitoring counter (``solver_device_xla_compiles_total``)
    stays flat, while every new leader's solves are answered by
    executables deserialized from the shared store (``served`` counts
    them; in-memory jit caches cannot fake that, the warm path
    short-circuits before the jit). The storm's placements and node
    accounting end bit-identical to the crash-free reference — and
    ``test_chaos_audit_kill_leader_promotion_sweep`` pins the
    cold-promotion run to that same reference, so warm and cold
    promotions are bit-identical to EACH OTHER by transitivity. A store
    entry corrupted mid-storm degrades that generation to cold — typed
    reject, counted, entry quarantined — WITHOUT losing a tick."""
    import jax

    from koordinator_tpu.obs.device import DEVICE_OBS
    from koordinator_tpu.ops.binpack import solve_batch
    from koordinator_tpu.service.warmpool import WarmPool
    from koordinator_tpu.testing.chaos import sabotage_store

    store = str(tmp_path / "warm-store")
    # fresh-process conditions: generation 0's compiles must be real,
    # observable events (earlier modules' shared jit caches would
    # otherwise hide them from the manifest)
    jax.clear_caches()
    DEVICE_OBS.reset()

    STORM_NODES, TICKS = 20, 14
    KILLS = (4, 7, 10)          # three SIGKILLs mid-churn
    CORRUPT_BEFORE = 10         # the LAST generation meets a bad store

    def arrivals(run_rng, t):
        dirty = run_rng.choice(STORM_NODES, 2, replace=False)
        metrics = [
            (f"n{int(i)}", int(run_rng.integers(0, 12000)),
             int(run_rng.integers(0, 32768)))
            for i in dirty
        ]
        pods = [
            (f"t{t}p{j}", int(run_rng.integers(200, 2000)),
             int(run_rng.integers(128, 2048)))
            for j in range(4)
        ]
        return metrics, pods

    def seed_bus(bus, run_rng):
        for i in range(STORM_NODES):
            bus.apply(Kind.NODE, f"n{i}", NodeSpec(
                name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
            bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
                node_name=f"n{i}",
                node_usage={CPU: int(run_rng.integers(0, 8000)),
                            MEM: int(run_rng.integers(0, 16384))},
                update_time=90.0))

    def apply_tick(bus, run_rng, t, now):
        metrics, pods = arrivals(run_rng, t)
        for name, cpu, mem in metrics:
            bus.apply(Kind.NODE_METRIC, name, NodeMetric(
                node_name=name, node_usage={CPU: cpu, MEM: mem},
                update_time=now))
        for name, cpu, mem in pods:
            pod = PodSpec(name=name, requests={CPU: cpu, MEM: mem})
            bus.apply(Kind.POD, pod.uid, pod)

    # ---- crash-free reference ----------------------------------------
    ref_rng = np.random.default_rng(77)
    ref_bus = APIServer()
    ref_sched = Scheduler(model=PlacementModel(use_pallas=False))
    wire_scheduler(ref_bus, ref_sched)
    seed_bus(ref_bus, ref_rng)
    ref_log = []
    for t in range(TICKS):
        now = 100.0 + 2.0 * t
        apply_tick(ref_bus, ref_rng, t, now)
        out = ref_sched.schedule_pending(now=now)
        ref_log.append((t, sorted(out.items())))

    # ---- the storm ---------------------------------------------------
    rng = np.random.default_rng(77)
    bus = APIServer()

    def spawn_generation(ident):
        sched = Scheduler(model=PlacementModel(use_pallas=False))
        pool = WarmPool().configure(store, force_single_device=True)
        pool.adopt(sched.model._solve, solve_batch, config_argpos=3)
        elector = LeaderElector(bus, "koord-scheduler", ident,
                                lease_duration=1.0)
        auditor = StateAuditor(sched, bus, interval_rounds=0,
                               warm_pool=pool)
        elector.on_started_leading = auditor.note_promotion
        wire_scheduler(bus, sched, elector=elector)
        return {"sched": sched, "pool": pool, "elector": elector,
                "auditor": auditor, "ticks": 0}

    seed_bus(bus, rng)
    generations = [spawn_generation("g0")]
    log = []
    for t in range(TICKS):
        now = 100.0 + 2.0 * t
        if t in KILLS:
            gen = generations[-1]
            gen["pool"].persist()  # the running leader's side of §21
            # SIGKILL: the leader never ticks again; a fresh process
            # (fresh model, fresh pool over the SHARED store) takes over
            if t == CORRUPT_BEFORE:
                assert sabotage_store(store, "bitflipped-entry", seed=5)
            generations.append(spawn_generation(f"g{len(generations)}"))
            if t == KILLS[0]:
                # generation 0 paid the storm's only compiles
                xla_compiles.clear()
                obs_mark = DEVICE_OBS.mark()
        apply_tick(bus, rng, t, now)
        gen = generations[-1]
        assert gen["elector"].tick(now), f"no leader at tick {t}"
        gen["auditor"].on_round(now=now)
        out = gen["sched"].schedule_pending(now=now)
        gen["ticks"] += 1
        log.append((t, sorted(out.items())))
        if t == CORRUPT_BEFORE - 1:
            # end of the clean phase: generations 1..K-1 ran entirely
            # warm — zero XLA recompiles since generation 0, and the
            # always-on monitoring counter agrees (the acceptance
            # criterion: solver_device_xla_compiles_total delta == 0)
            assert xla_compiles == [], (
                "a warm generation recompiled: " + "; ".join(xla_compiles)
            )
            assert (DEVICE_OBS.mark()["xla_compiles"]
                    - obs_mark["xla_compiles"]) == 0

    # ---- bit-identical to the crash-free run, tick for tick ----------
    assert log == ref_log
    got = lower_nodes(snapshot_from_bus(bus, now=200.0))
    want = lower_nodes(snapshot_from_bus(ref_bus, now=200.0))
    assert got.names == want.names
    for f in STAGED_NODE_FIELDS:
        np.testing.assert_array_equal(
            getattr(got, f), getattr(want, f),
            err_msg=f"node accounting diverged: {f}")

    # ---- every clean promotion warm-restored -------------------------
    for gen in generations[1:-1]:
        status = gen["pool"].status()
        assert status["hits"] >= 1, "no executable loaded from disk"
        assert status["served"] == gen["ticks"], (
            "a warm generation's solve fell through to the jit path"
        )
        assert status["quarantined"] == 0
        warm = gen["auditor"].last_report["warm"]
        assert warm["pool"]["restored"] >= 1
        assert "error" not in (warm.get("prestage") or {})

    # ---- the corrupted-store generation degraded to cold -------------
    last = generations[-1]
    status = last["pool"].status()
    assert status["quarantined"] == 1
    assert status["rejects"].get("fingerprint") == 1
    assert status["served"] == 0          # cold: the jit path answered
    assert last["ticks"] == TICKS - CORRUPT_BEFORE  # zero lost ticks
    assert last["auditor"].last_report["kind"] == "promotion"


@pytest.mark.chaos
def test_chaos_streaming_burst_storm_sigkill(tmp_path):
    """Streaming chaos slice (ISSUE 14 / DESIGN §22): a burst-storm
    arrival trace served by the ADAPTIVE trigger through the pipelined
    tick path, with the solver sidecar SIGKILLed mid-storm under
    supervisor + failover. Every submitted pod must resolve (bound — no
    typed sheds fire at this load), zero silent drops (submitted ==
    bound once drained), and the run must end bit-identical to the
    fault-free streaming run of the SAME trace — the outage changes
    which backend answers, never what is decided or when rounds fire."""
    import dataclasses

    from koordinator_tpu.scheduler.streaming import (
        StreamingConfig,
        StreamingLoop,
    )
    from koordinator_tpu.testing.arrivals import make_trace, trace_pods

    trace = make_trace("burst-storm", seed=9, duration_s=2.0,
                       rate_pods_per_s=20.0, bursts=1, burst_pods=40,
                       burst_span_s=0.020)
    pairs, _gangs = trace_pods(trace)
    storm_idx = [i for i, (_at, p) in enumerate(pairs) if "s0" in p.name]
    kill_idx = storm_idx[len(storm_idx) // 2]  # mid-storm

    def run(model, kill=None):
        bus = APIServer()
        sched = Scheduler(model=model)
        wire_scheduler(bus, sched)
        for i in range(N_NODES):
            bus.apply(Kind.NODE, f"n{i}", NodeSpec(
                name=f"n{i}", allocatable={CPU: 64000, MEM: 131072}))
            bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
                node_name=f"n{i}", node_usage={}, update_time=90.0))
        clock = [100.0]
        loop = StreamingLoop(
            sched,
            apply_fn=lambda pod: bus.apply(Kind.POD, pod.uid, pod),
            delete_fn=lambda uid: bus.delete(Kind.POD, uid),
            config=StreamingConfig(
                watermark=16, lane_deadline_s=(0.002, 0.010, 0.050)),
            pipelined=True,
            clock=lambda: clock[0], now_fn=lambda: clock[0],
            log=lambda *a: None,
        )
        try:
            for i, (at, pod) in enumerate(pairs):
                clock[0] = 100.0 + at
                assert loop.submit(
                    dataclasses.replace(pod), now=clock[0]) == "queued"
                if kill is not None and i == kill_idx:
                    kill()
                loop.pump(clock[0])
            for _ in range(64):
                clock[0] += 0.050
                if loop.pump(clock[0]) is None \
                        and loop.gate.unresolved() == 0:
                    break
        finally:
            loop.stop()
        placements = {u: getattr(p, "node_name", None)
                      for u, p in bus.list(Kind.POD).items()}
        return placements, bus, loop.status(), list(loop.round_log)

    # ---- the faulty arm: sidecar + supervisor + failover -------------
    solver_addr = str(tmp_path / "solver.sock")
    handles = []

    def spawn():
        handle = InProcessSidecar(solver_addr)
        handles.append(handle)
        return handle

    supervisor = SolverSupervisor(
        solver_addr, spawn_fn=spawn,
        probe_interval_s=0.2, probe_timeout_s=0.2, ready_timeout_s=30.0,
        # respawn strictly slower than the post-kill solve's retry
        # budget, so the outage reliably produces degraded solves
        backoff_base_s=2.0, backoff_cap_s=2.0,
    ).start()
    remote = RemoteSolver(solver_addr, timeout=30.0, retries=0,
                          retry_total_s=0.3,
                          backoff_base_s=0.01, backoff_cap_s=0.02)
    backend = FailoverSolver(remote, failure_threshold=1,
                             recovery_probes=1)
    try:
        placements, bus, status, round_log = run(
            PlacementModel(backend=backend, use_pallas=False),
            kill=lambda: handles[-1].kill(),
        )
        flips = backend.status()
    finally:
        supervisor.stop()
        backend.close()

    # ---- the fault-free reference arm (in-process solver) ------------
    ref_placements, ref_bus, ref_status, ref_round_log = run(
        PlacementModel(use_pallas=False))

    # every submitted pod resolved bound; zero silent drops
    for st in (status, ref_status):
        gate = st["gate"]
        assert gate["submitted"] == len(pairs)
        assert gate["bound"] == len(pairs)
        assert gate["shed"]["capacity"] == 0
        assert gate["shed"]["deadline-exceeded"] == 0
        assert gate["inflight"] == 0 and gate["waiting_permit"] == 0
    # the outage was real: a degraded flip answered solves locally,
    # and the supervisor respawned the killed sidecar
    assert flips["flips_to_degraded"] >= 1
    assert flips["local_solves"] >= 1
    assert len(handles) >= 1
    # the trigger schedule did not shift: same rounds, same batches
    assert [(r, tuple(u)) for r, _n, u in round_log] \
        == [(r, tuple(u)) for r, _n, u in ref_round_log]
    # bit-identical to the fault-free streaming run
    assert placements == ref_placements
    got = lower_nodes(snapshot_from_bus(bus, now=500.0))
    want = lower_nodes(snapshot_from_bus(ref_bus, now=500.0))
    assert got.names == want.names
    for f in STAGED_NODE_FIELDS:
        np.testing.assert_array_equal(
            getattr(got, f), getattr(want, f),
            err_msg=f"node accounting diverged: {f}")


@pytest.mark.chaos
def test_chaos_preemption_storm_signatures_and_parity():
    """A seeded preemption storm (testing/chaos.preemption_storm) under
    the module's shape-flow sentinel: every LS arrival can place only by
    evicting BE residents, so the round exercises the joint place+evict
    solve's compile signatures — preempt_solve, preempt_solve_scan and
    defrag_repack with their victim/preemptor bucket axes. Any signature
    the compile ring observes outside graftcheck's static enumeration
    fails at module teardown; the scheduler itself runs in "verify"
    backend, so every device nomination is asserted bit-identical to the
    host oracle inline."""
    from koordinator_tpu.apis.extension import PriorityClass
    from koordinator_tpu.apis.types import resources_to_vector
    from koordinator_tpu.metrics.components import PREEMPT_VICTIMS
    from koordinator_tpu.testing.chaos import preemption_storm

    nodes, residents, arrivals = preemption_storm(
        seed=5, n_nodes=8, residents_per_node=4, n_arrivals=4,
    )
    sched = Scheduler(model=PlacementModel(use_pallas=False),
                      preemption_backend="verify")
    for node in nodes:
        sched.add_node(node)
    for pod in residents:
        sched.add_pod(pod)
    for pod in arrivals:
        sched.add_pod(pod)
    evicted_before = PREEMPT_VICTIMS.value({"outcome": "evicted"})
    out = sched.schedule_pending(now=100.0)
    noms = getattr(out, "nominations", None) or {}
    # the packed world admits no plain placement: nominations must come
    # from eviction, and the counters must show real victim flow
    assert noms, "storm produced no preemption nominations"
    assert PREEMPT_VICTIMS.value({"outcome": "evicted"}) > evicted_before
    # the scanned storm variant and the defrag planner see the same
    # world (their compile signatures join the sentinel window too)
    snapshot = sched.cache.snapshot(now=101.0)
    arrays = lower_nodes(snapshot, **sched.model.lowering_kwargs())
    resident = sched.model.lower_residents(snapshot, arrays)
    scanned = sched.model.preempt_scan_device(
        arrays, resident, arrivals[:2],
    )
    assert len(scanned) == 2
    sched.defrag_headroom(
        resources_to_vector({CPU: 8000, MEM: 16384}),
        max_victim_priority=5000,
    )
