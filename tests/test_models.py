"""End-to-end PlacementModel tests through the typed public API, with gang
gating as the deciding factor (not masked by fit rejection)."""

import numpy as np
import pytest

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    GangMode,
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
)
from koordinator_tpu.models import PlacementModel


def _nodes(n, cpu=16000, mem=32768):
    return [
        NodeSpec(name=f"n{i}", allocatable={R.CPU: cpu, R.MEMORY: mem})
        for i in range(n)
    ]


def _metrics(n):
    return {
        f"n{i}": NodeMetric(
            node_name=f"n{i}", node_usage={R.CPU: 500}, update_time=99.0
        )
        for i in range(n)
    }


def test_strict_gang_below_min_rejected_even_though_pods_fit():
    # 4 nodes with plenty of room; gang needs 5 members but only 2 exist.
    # Each pod fits individually -> the gang gate is the only reason for None.
    pods = [
        PodSpec(name=f"g-{i}", gang="g", requests={R.CPU: 1000}) for i in range(2)
    ]
    snap = ClusterSnapshot(
        nodes=_nodes(4),
        node_metrics=_metrics(4),
        pending_pods=pods,
        gangs={"g": GangSpec(name="g", min_member=5)},
        now=100.0,
    )
    out = PlacementModel().schedule(snap)
    assert out["default/g-0"] is None and out["default/g-1"] is None
    assert out.waiting == {}


def test_nonstrict_gang_below_min_reported_waiting():
    pods = [
        PodSpec(name=f"g-{i}", gang="g", requests={R.CPU: 1000}) for i in range(2)
    ]
    snap = ClusterSnapshot(
        nodes=_nodes(4),
        node_metrics=_metrics(4),
        pending_pods=pods,
        gangs={"g": GangSpec(name="g", min_member=5, mode=GangMode.NON_STRICT)},
        now=100.0,
    )
    out = PlacementModel().schedule(snap)
    # not committed, but holding nodes at the Permit barrier
    assert out["default/g-0"] is None and out["default/g-1"] is None
    assert set(out.waiting) == {"default/g-0", "default/g-1"}


def test_gang_satisfied_commits():
    pods = [
        PodSpec(name=f"g-{i}", gang="g", requests={R.CPU: 1000}) for i in range(3)
    ]
    snap = ClusterSnapshot(
        nodes=_nodes(4),
        node_metrics=_metrics(4),
        pending_pods=pods,
        gangs={"g": GangSpec(name="g", min_member=3)},
        now=100.0,
    )
    out = PlacementModel().schedule(snap)
    assert all(out[f"default/g-{i}"] is not None for i in range(3))


def test_gang_bound_members_count_toward_min():
    # 2 members already running; 1 pending completes min_member=3
    running = [
        PodSpec(name=f"r{i}", gang="g", requests={R.CPU: 1000}, node_name="n0")
        for i in range(2)
    ]
    pending = [PodSpec(name="p", gang="g", requests={R.CPU: 1000})]
    snap = ClusterSnapshot(
        nodes=_nodes(4),
        node_metrics=_metrics(4),
        pods=running,
        pending_pods=pending,
        gangs={"g": GangSpec(name="g", min_member=3)},
        now=100.0,
    )
    out = PlacementModel().schedule(snap)
    assert out["default/p"] is not None


def test_quota_caps_through_model():
    pods = [
        PodSpec(name="a", quota="t", requests={R.CPU: 9000}, priority=9500),
        PodSpec(name="b", quota="t", requests={R.CPU: 1000}, priority=9400),
    ]
    snap = ClusterSnapshot(
        nodes=_nodes(3),
        node_metrics=_metrics(3),
        pending_pods=pods,
        quotas={"t": QuotaSpec(name="t", min={R.CPU: 2000}, max={R.CPU: 9000})},
        now=100.0,
    )
    out = PlacementModel().schedule(snap)
    assert out["default/a"] is not None
    assert out["default/b"] is None  # 9000 + 1000 > max 9000


class TestPodBucketing:
    def test_bucket_sizes(self):
        from koordinator_tpu.models.placement import PlacementModel

        b = PlacementModel.pod_bucket
        assert b(1) == 64 and b(64) == 64
        assert b(65) == 80          # steps of 16 below 128
        assert b(100) == 112
        assert b(1000) == 1024
        assert b(1025) == 1280      # steps of 256 below 2048
        for p in (1, 7, 65, 100, 999, 4097, 10000):
            assert b(p) >= p
            assert b(p) <= max(64, int(p * 1.25) + 1)

    def test_bucketed_schedule_identical(self):
        from koordinator_tpu.apis.extension import ResourceName as R
        from koordinator_tpu.apis.types import (
            ClusterSnapshot,
            NodeMetric,
            NodeSpec,
            PodSpec,
        )
        from koordinator_tpu.models.placement import PlacementModel

        def snap():
            return ClusterSnapshot(
                nodes=[
                    NodeSpec(name=f"n{i}",
                             allocatable={R.CPU: 16000, R.MEMORY: 32768})
                    for i in range(3)
                ],
                pending_pods=[
                    PodSpec(name=f"p{i}", requests={R.CPU: 1000 + 100 * i})
                    for i in range(7)
                ],
                node_metrics={
                    f"n{i}": NodeMetric(node_name=f"n{i}", node_usage={},
                                        update_time=99.0)
                    for i in range(3)
                },
                now=100.0,
            )

        bucketed = PlacementModel(pod_bucketing=True).schedule(snap())
        plain = PlacementModel(pod_bucketing=False).schedule(snap())
        assert dict(bucketed) == dict(plain)
        assert len(bucketed) == 7  # padding never leaks into results


class TestRandomizedDifferential:
    """Broad randomized sweep: the batched solver must equal the
    pure-python sequential oracle on arbitrary cluster shapes (stale
    metrics, unschedulable nodes, daemonsets, prod mix, zero requests,
    tight capacity)."""

    @pytest.mark.parametrize("seed", range(6))
    def test_batched_equals_oracle(self, seed):
        import numpy as np

        from koordinator_tpu.apis.extension import NUM_RESOURCES
        from koordinator_tpu.apis.extension import ResourceName as R
        from koordinator_tpu.oracle.placement import schedule_sequential
        from koordinator_tpu.ops.binpack import (
            NodeState,
            PodBatch,
            ScoreParams,
            SolverConfig,
            schedule_batch,
        )

        rng = np.random.default_rng(seed)
        n_nodes = int(rng.integers(3, 40))
        n_pods = int(rng.integers(5, 80))
        alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
        alloc[:, R.CPU] = rng.choice([2000, 8000, 32000], n_nodes)
        alloc[:, R.MEMORY] = rng.choice([0, 4096, 32768], n_nodes)
        usage = (alloc * rng.uniform(0, 1.0, alloc.shape)).astype(np.int32)
        used0 = (alloc * rng.uniform(0, 0.4, alloc.shape)).astype(np.int32)
        est_extra = (usage * rng.uniform(0, 0.3, usage.shape)).astype(np.int32)
        prod_base = (usage * rng.uniform(0, 0.5, usage.shape)).astype(np.int32)
        fresh = rng.uniform(size=n_nodes) > 0.25
        sched = rng.uniform(size=n_nodes) > 0.1
        req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
        req[:, R.CPU] = rng.choice([0, 250, 1000, 6000, 50000], n_pods)
        req[:, R.MEMORY] = rng.choice([0, 512, 8192], n_pods)
        est = (req * 85) // 100
        is_prod = rng.uniform(size=n_pods) < 0.5
        is_ds = rng.uniform(size=n_pods) < 0.15
        weights = np.zeros(NUM_RESOURCES, np.int32)
        weights[R.CPU] = int(rng.integers(1, 3))
        weights[R.MEMORY] = int(rng.integers(1, 3))
        thresholds = np.zeros(NUM_RESOURCES, np.int32)
        thresholds[R.CPU] = 65
        thresholds[R.MEMORY] = 95
        prod_thresholds = np.zeros(NUM_RESOURCES, np.int32)
        score_prod = bool(rng.integers(0, 2))
        if score_prod:
            prod_thresholds[R.CPU] = 70

        import jax.numpy as jnp

        state = NodeState(
            alloc=jnp.asarray(alloc), used_req=jnp.asarray(used0),
            usage=jnp.asarray(usage), prod_usage=jnp.asarray(usage // 2),
            est_extra=jnp.asarray(est_extra),
            prod_base=jnp.asarray(prod_base),
            metric_fresh=jnp.asarray(fresh), schedulable=jnp.asarray(sched),
        )
        pods = PodBatch.build(
            req=jnp.asarray(req), est=jnp.asarray(est),
            is_prod=jnp.asarray(is_prod), is_daemonset=jnp.asarray(is_ds),
        )
        params = ScoreParams(
            weights=jnp.asarray(weights),
            thresholds=jnp.asarray(thresholds),
            prod_thresholds=jnp.asarray(prod_thresholds),
        )
        config = SolverConfig(score_according_prod=score_prod)
        _, got = schedule_batch(state, pods, params, config)
        want = schedule_sequential(
            alloc, used0, usage, usage // 2, est_extra, prod_base,
            fresh, sched, req, est, is_prod, is_ds,
            weights, thresholds, prod_thresholds,
            score_according_prod=score_prod,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_host_fallback_identical_and_routed():
    """Tiny plain solves route to the host sequential path when the
    cutoff is enabled (VERDICT r2: small shapes lose to the host) — same
    results, no device round trip."""
    from koordinator_tpu.apis.extension import ResourceName as R
    from koordinator_tpu.apis.types import (
        ClusterSnapshot, NodeMetric, NodeSpec, PodSpec,
    )
    from koordinator_tpu.models.placement import PlacementModel

    def snap():
        return ClusterSnapshot(
            nodes=[NodeSpec(name=f"n{i}",
                            allocatable={R.CPU: 16000, R.MEMORY: 32768})
                   for i in range(20)],
            pending_pods=[
                PodSpec(name=f"p{i}",
                        requests={R.CPU: 500 + 100 * (i % 7)},
                        is_daemonset=(i % 11 == 0))
                for i in range(100)
            ],
            node_metrics={
                f"n{i}": NodeMetric(node_name=f"n{i}",
                                    node_usage={R.CPU: 900 * (i % 3)},
                                    update_time=99.0)
                for i in range(20)
            },
            now=100.0,
        )

    host = PlacementModel(host_fallback_cells=16384)
    device = PlacementModel(host_fallback_cells=0)
    out_host = host.schedule(snap())
    out_device = device.schedule(snap())
    assert host.last_solver == "host"
    assert device.last_solver in ("scan", "pallas")
    assert dict(out_host) == dict(out_device)

    # quota'd solves never take the host shortcut (plain path only)
    from koordinator_tpu.apis.types import QuotaSpec

    s = snap()
    s.quotas = {"t": QuotaSpec(name="t", min={R.CPU: 1000},
                               max={R.CPU: 90000})}
    for pod in s.pending_pods:
        pod.quota = "t"
    host.schedule(s)
    assert host.last_solver != "host"


def test_resv_axis_bucketing_identity():
    """Reservation tables of different sizes pad to one shape bucket
    with inert rows — identical schedules with bucketing on and off
    (the off path solves at the raw V)."""
    from koordinator_tpu.apis.extension import ResourceName as R
    from koordinator_tpu.apis.types import (
        ClusterSnapshot,
        NodeMetric,
        NodeSpec,
        PodSpec,
        ReservationSpec,
        ReservationState,
    )
    from koordinator_tpu.models.placement import PlacementModel

    assert PlacementModel.resv_bucket(1) == 8
    assert PlacementModel.resv_bucket(8) == 8
    assert PlacementModel.resv_bucket(9) == 16

    def snap(n_resv):
        nodes = [NodeSpec(name=f"n{i}",
                          allocatable={R.CPU: 8000, R.MEMORY: 16384})
                 for i in range(6)]
        resvs = [ReservationSpec(
            name=f"r{v}", node_name=f"n{v % 6}",
            state=ReservationState.AVAILABLE,
            requests={R.CPU: 2000 + 100 * v},
            allocatable={R.CPU: 2000 + 100 * v},
            owner_labels={"own": "w"},
        ) for v in range(n_resv)]
        return ClusterSnapshot(
            nodes=nodes,
            pending_pods=[
                PodSpec(name=f"p{i}", requests={R.CPU: 1500},
                        labels={"own": "w"})
                for i in range(4)
            ],
            node_metrics={f"n{i}": NodeMetric(node_name=f"n{i}",
                                              node_usage={},
                                              update_time=99.0)
                          for i in range(6)},
            reservations=resvs,
            now=100.0,
        )

    for n_resv in (1, 3, 7):
        bucketed = PlacementModel(pod_bucketing=True).schedule(snap(n_resv))
        raw = PlacementModel(pod_bucketing=False).schedule(snap(n_resv))
        assert dict(bucketed) == dict(raw), n_resv
        assert bucketed.resv_allocs == raw.resv_allocs
