"""End-to-end PlacementModel tests through the typed public API, with gang
gating as the deciding factor (not masked by fit rejection)."""

import numpy as np

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    GangMode,
    GangSpec,
    NodeMetric,
    NodeSpec,
    PodSpec,
    QuotaSpec,
)
from koordinator_tpu.models import PlacementModel


def _nodes(n, cpu=16000, mem=32768):
    return [
        NodeSpec(name=f"n{i}", allocatable={R.CPU: cpu, R.MEMORY: mem})
        for i in range(n)
    ]


def _metrics(n):
    return {
        f"n{i}": NodeMetric(
            node_name=f"n{i}", node_usage={R.CPU: 500}, update_time=99.0
        )
        for i in range(n)
    }


def test_strict_gang_below_min_rejected_even_though_pods_fit():
    # 4 nodes with plenty of room; gang needs 5 members but only 2 exist.
    # Each pod fits individually -> the gang gate is the only reason for None.
    pods = [
        PodSpec(name=f"g-{i}", gang="g", requests={R.CPU: 1000}) for i in range(2)
    ]
    snap = ClusterSnapshot(
        nodes=_nodes(4),
        node_metrics=_metrics(4),
        pending_pods=pods,
        gangs={"g": GangSpec(name="g", min_member=5)},
        now=100.0,
    )
    out = PlacementModel().schedule(snap)
    assert out["default/g-0"] is None and out["default/g-1"] is None
    assert out.waiting == {}


def test_nonstrict_gang_below_min_reported_waiting():
    pods = [
        PodSpec(name=f"g-{i}", gang="g", requests={R.CPU: 1000}) for i in range(2)
    ]
    snap = ClusterSnapshot(
        nodes=_nodes(4),
        node_metrics=_metrics(4),
        pending_pods=pods,
        gangs={"g": GangSpec(name="g", min_member=5, mode=GangMode.NON_STRICT)},
        now=100.0,
    )
    out = PlacementModel().schedule(snap)
    # not committed, but holding nodes at the Permit barrier
    assert out["default/g-0"] is None and out["default/g-1"] is None
    assert set(out.waiting) == {"default/g-0", "default/g-1"}


def test_gang_satisfied_commits():
    pods = [
        PodSpec(name=f"g-{i}", gang="g", requests={R.CPU: 1000}) for i in range(3)
    ]
    snap = ClusterSnapshot(
        nodes=_nodes(4),
        node_metrics=_metrics(4),
        pending_pods=pods,
        gangs={"g": GangSpec(name="g", min_member=3)},
        now=100.0,
    )
    out = PlacementModel().schedule(snap)
    assert all(out[f"default/g-{i}"] is not None for i in range(3))


def test_gang_bound_members_count_toward_min():
    # 2 members already running; 1 pending completes min_member=3
    running = [
        PodSpec(name=f"r{i}", gang="g", requests={R.CPU: 1000}, node_name="n0")
        for i in range(2)
    ]
    pending = [PodSpec(name="p", gang="g", requests={R.CPU: 1000})]
    snap = ClusterSnapshot(
        nodes=_nodes(4),
        node_metrics=_metrics(4),
        pods=running,
        pending_pods=pending,
        gangs={"g": GangSpec(name="g", min_member=3)},
        now=100.0,
    )
    out = PlacementModel().schedule(snap)
    assert out["default/p"] is not None


def test_quota_caps_through_model():
    pods = [
        PodSpec(name="a", quota="t", requests={R.CPU: 9000}, priority=9500),
        PodSpec(name="b", quota="t", requests={R.CPU: 1000}, priority=9400),
    ]
    snap = ClusterSnapshot(
        nodes=_nodes(3),
        node_metrics=_metrics(3),
        pending_pods=pods,
        quotas={"t": QuotaSpec(name="t", min={R.CPU: 2000}, max={R.CPU: 9000})},
        now=100.0,
    )
    out = PlacementModel().schedule(snap)
    assert out["default/a"] is not None
    assert out["default/b"] is None  # 9000 + 1000 > max 9000


class TestPodBucketing:
    def test_bucket_sizes(self):
        from koordinator_tpu.models.placement import PlacementModel

        b = PlacementModel.pod_bucket
        assert b(1) == 64 and b(64) == 64
        assert b(65) == 80          # steps of 16 below 128
        assert b(100) == 112
        assert b(1000) == 1024
        assert b(1025) == 1280      # steps of 256 below 2048
        for p in (1, 7, 65, 100, 999, 4097, 10000):
            assert b(p) >= p
            assert b(p) <= max(64, int(p * 1.25) + 1)

    def test_bucketed_schedule_identical(self):
        from koordinator_tpu.apis.extension import ResourceName as R
        from koordinator_tpu.apis.types import (
            ClusterSnapshot,
            NodeMetric,
            NodeSpec,
            PodSpec,
        )
        from koordinator_tpu.models.placement import PlacementModel

        def snap():
            return ClusterSnapshot(
                nodes=[
                    NodeSpec(name=f"n{i}",
                             allocatable={R.CPU: 16000, R.MEMORY: 32768})
                    for i in range(3)
                ],
                pending_pods=[
                    PodSpec(name=f"p{i}", requests={R.CPU: 1000 + 100 * i})
                    for i in range(7)
                ],
                node_metrics={
                    f"n{i}": NodeMetric(node_name=f"n{i}", node_usage={},
                                        update_time=99.0)
                    for i in range(3)
                },
                now=100.0,
            )

        bucketed = PlacementModel(pod_bucketing=True).schedule(snap())
        plain = PlacementModel(pod_bucketing=False).schedule(snap())
        assert dict(bucketed) == dict(plain)
        assert len(bucketed) == 7  # padding never leaks into results
