"""Differential sweep: vectorized host oracle == scalar oracle == device scan.

The vectorized oracle (oracle/vectorized.py) exists to prove device
correctness at FULL BASELINE shapes; its own authority comes from exact
agreement with the scalar transliteration (oracle/placement.py) across
randomized problems covering every semantic branch: stale metrics,
unschedulable nodes, prod thresholds + prod scoring mode, daemonset skip,
quota admission, and gang batch-end resolution.
"""

import numpy as np
import pytest

from koordinator_tpu.oracle.placement import (
    SequentialQuota,
    schedule_sequential,
    schedule_sequential_quota,
)
from koordinator_tpu.oracle.vectorized import (
    VectorQuota,
    gang_outcomes_np,
    schedule_vectorized,
)


def _rich_problem(n_nodes, n_pods, seed, prod_thresholds=False):
    """Numpy problem with every branch exercised (stale metrics, cordoned
    nodes, prod pods, daemonsets, near-full nodes)."""
    rng = np.random.default_rng(seed)
    r = 4
    alloc = np.zeros((n_nodes, r), np.int64)
    alloc[:, 0] = rng.choice([16000, 32000, 64000], n_nodes)
    alloc[:, 1] = rng.choice([32768, 65536], n_nodes)
    usage = (alloc * rng.uniform(0, 0.9, alloc.shape)).astype(np.int64)
    used_req = (alloc * rng.uniform(0, 0.6, alloc.shape)).astype(np.int64)
    prod_usage = (usage * rng.uniform(0, 1.0, usage.shape)).astype(np.int64)
    est_extra = (alloc * rng.uniform(0, 0.1, alloc.shape)).astype(np.int64)
    prod_base = prod_usage.copy()
    metric_fresh = rng.uniform(size=n_nodes) < 0.9
    schedulable = rng.uniform(size=n_nodes) < 0.95
    req = np.zeros((n_pods, r), np.int64)
    req[:, 0] = rng.choice([500, 1000, 2000, 4000], n_pods)
    req[:, 1] = rng.choice([1024, 2048, 8192], n_pods)
    est = (req * 85) // 100
    is_prod = rng.uniform(size=n_pods) < 0.5
    is_ds = rng.uniform(size=n_pods) < 0.05
    weights = np.array([1, 1, 0, 0], np.int64)
    thresholds = np.array([65, 95, 0, 0], np.int64)
    prod_thr = (
        np.array([55, 80, 0, 0], np.int64)
        if prod_thresholds
        else np.zeros(r, np.int64)
    )
    return (
        alloc, used_req, usage, prod_usage, est_extra, prod_base,
        metric_fresh, schedulable, req, est, is_prod, is_ds,
        weights, thresholds, prod_thr,
    )


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("prod_thr", [False, True])
def test_vectorized_matches_scalar(seed, prod_thr):
    args = _rich_problem(40, 120, seed, prod_thresholds=prod_thr)
    want = schedule_sequential(*args)
    got = schedule_vectorized(*args)
    np.testing.assert_array_equal(got, np.asarray(want))


@pytest.mark.parametrize("seed", [5, 6])
def test_vectorized_matches_scalar_prod_scoring(seed):
    args = _rich_problem(30, 80, seed, prod_thresholds=True)
    want = schedule_sequential(*args, score_according_prod=True)
    got = schedule_vectorized(*args, score_according_prod=True)
    np.testing.assert_array_equal(got, np.asarray(want))


@pytest.mark.parametrize("seed", [7, 8, 9])
def test_vectorized_quota_matches_scalar(seed):
    n_nodes, n_pods, n_q = 30, 150, 6
    args = _rich_problem(n_nodes, n_pods, seed)
    rng = np.random.default_rng(seed + 100)
    quota_id = rng.integers(-1, n_q, n_pods)
    non_pre = rng.uniform(size=n_pods) < 0.3
    total = args[0].sum(axis=0)
    r = 4
    mn = np.zeros((n_q, r), np.int64)
    mx = np.zeros((n_q, r), np.int64)
    mn[:, :2] = total[:2] // (3 * n_q)
    mx[:, :2] = total[:2] // 4
    qargs = (mn, mx, mn, mx, np.ones(n_q, bool), total)

    sq = SequentialQuota(*qargs)
    want = schedule_sequential_quota(
        *args[:12], quota_id, non_pre, sq, args[12], args[13], args[14]
    )
    vq = VectorQuota(*qargs)
    got = schedule_vectorized(
        *args, pod_quota_id=quota_id, pod_non_preemptible=non_pre, quota=vq
    )
    np.testing.assert_array_equal(got, np.asarray(want))
    np.testing.assert_array_equal(vq.used, sq.used)
    np.testing.assert_array_equal(vq.np_used, sq.np_used)


def test_zero_nodes_returns_all_unplaced():
    """Empty cluster mirrors solve_batch's shape early-out: all -1, no
    crash, quota requests still registered."""
    args = _rich_problem(0, 10, seed=99)
    out = schedule_vectorized(*args)
    np.testing.assert_array_equal(out, np.full(10, -1))
    vq = VectorQuota(
        np.zeros((2, 4), np.int64), np.full((2, 4), 100, np.int64),
        np.zeros((2, 4), np.int64), np.ones((2, 4), np.int64),
        np.ones(2, bool), np.full(4, 1000, np.int64),
    )
    out = schedule_vectorized(
        *args, pod_quota_id=np.zeros(10, np.int64),
        pod_non_preemptible=np.zeros(10, bool), quota=vq,
    )
    np.testing.assert_array_equal(out, np.full(10, -1))
    assert vq.child_request[0].sum() > 0  # requests registered anyway


def test_vectorized_matches_device_scan():
    """Anchor the vectorized oracle directly to the jitted scan."""
    import jax

    from __graft_entry__ import _example_problem
    from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch

    state, pods, params = _example_problem(80, 250, seed=11)
    solve = jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig()))
    _, assign = solve(state, pods, params)
    from koordinator_tpu.oracle.vectorized import oracle_args

    got = schedule_vectorized(*oracle_args(state, pods, params))
    np.testing.assert_array_equal(got, np.asarray(assign))


@pytest.mark.parametrize("seed", [13, 14])
def test_gang_outcomes_np_matches_device(seed):
    import jax.numpy as jnp

    from koordinator_tpu.ops.gang import GangState, gang_outcomes

    rng = np.random.default_rng(seed)
    g, p = 12, 200
    gang_id = rng.integers(-1, g, p).astype(np.int32)
    assignments = np.where(
        rng.uniform(size=p) < 0.7, rng.integers(0, 50, p), -1
    ).astype(np.int32)
    min_member = rng.integers(1, 20, g)
    bound = rng.integers(0, 3, g)
    strict = rng.uniform(size=g) < 0.5
    group = rng.integers(0, 5, g)
    gs = GangState.build(
        min_member=min_member, bound_count=bound, strict=strict, group_id=group
    )
    c, w, rj = gang_outcomes(jnp.asarray(assignments), jnp.asarray(gang_id), gs)
    # gang_outcomes_np takes the densified group ids GangState.build produced
    nc, nw, nrj = gang_outcomes_np(
        assignments, gang_id, min_member, bound, strict,
        np.asarray(gs.group_id),
    )
    np.testing.assert_array_equal(np.asarray(c), nc)
    np.testing.assert_array_equal(np.asarray(w), nw)
    np.testing.assert_array_equal(np.asarray(rj), nrj)
