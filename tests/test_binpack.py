"""Differential tests: batched scan solver == sequential oracle scheduler,
including BASELINE config #1 scale (100 pods / 20 nodes)."""

import numpy as np
import jax.numpy as jnp

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.oracle.placement import schedule_sequential

RNG = np.random.default_rng(42)


def _weights():
    w = np.zeros(NUM_RESOURCES, dtype=np.int64)
    w[ResourceName.CPU] = 1
    w[ResourceName.MEMORY] = 1
    return w


def _thresholds():
    t = np.zeros(NUM_RESOURCES, dtype=np.int64)
    t[ResourceName.CPU] = 65
    t[ResourceName.MEMORY] = 95
    return t


def _cluster(n, fresh_frac=0.9):
    alloc = np.zeros((n, NUM_RESOURCES), dtype=np.int64)
    alloc[:, ResourceName.CPU] = RNG.choice([16000, 32000, 64000, 96000], n)
    alloc[:, ResourceName.MEMORY] = RNG.choice([32768, 65536, 131072, 262144], n)
    used = (alloc * RNG.uniform(0, 0.6, (n, NUM_RESOURCES))).astype(np.int64)
    usage = (alloc * RNG.uniform(0, 0.7, (n, NUM_RESOURCES))).astype(np.int64)
    prod = (usage * RNG.uniform(0, 1.0, (n, NUM_RESOURCES))).astype(np.int64)
    extra = RNG.integers(0, 2000, (n, NUM_RESOURCES)).astype(np.int64)
    prod_base = (prod * RNG.uniform(0, 1.2, (n, NUM_RESOURCES))).astype(np.int64)
    fresh = RNG.uniform(size=n) < fresh_frac
    sched = RNG.uniform(size=n) < 0.95
    return alloc, used, usage, prod, extra, prod_base, fresh, sched


def _pods(p):
    req = np.zeros((p, NUM_RESOURCES), dtype=np.int64)
    req[:, ResourceName.CPU] = RNG.choice([500, 1000, 2000, 4000], p)
    req[:, ResourceName.MEMORY] = RNG.choice([1024, 2048, 4096, 8192], p)
    est = np.zeros_like(req)
    est[:, ResourceName.CPU] = np.floor(req[:, ResourceName.CPU] * 0.85 + 0.5)
    est[:, ResourceName.MEMORY] = np.floor(req[:, ResourceName.MEMORY] * 0.70 + 0.5)
    is_prod = RNG.uniform(size=p) < 0.5
    is_ds = RNG.uniform(size=p) < 0.05
    return req, est, is_prod, is_ds


def _run_both(n, p, config=SolverConfig()):
    alloc, used, usage, prod, extra, prod_base, fresh, sched = _cluster(n)
    req, est, is_prod, is_ds = _pods(p)
    w, thr = _weights(), _thresholds()
    prod_thr = np.zeros_like(thr)

    state = NodeState(
        alloc=jnp.asarray(alloc, jnp.int32),
        used_req=jnp.asarray(used, jnp.int32),
        usage=jnp.asarray(usage, jnp.int32),
        prod_usage=jnp.asarray(prod, jnp.int32),
        est_extra=jnp.asarray(extra, jnp.int32),
        prod_base=jnp.asarray(prod_base, jnp.int32),
        metric_fresh=jnp.asarray(fresh),
        schedulable=jnp.asarray(sched),
    )
    pods = PodBatch.build(
        req=jnp.asarray(req, jnp.int32),
        est=jnp.asarray(est, jnp.int32),
        is_prod=jnp.asarray(is_prod),
        is_daemonset=jnp.asarray(is_ds),
    )
    params = ScoreParams(
        weights=jnp.asarray(w, jnp.int32),
        thresholds=jnp.asarray(thr, jnp.int32),
        prod_thresholds=jnp.asarray(prod_thr, jnp.int32),
    )
    _, got = schedule_batch(state, pods, params, config)
    want = schedule_sequential(
        alloc, used, usage, prod, extra, prod_base, fresh, sched,
        req, est, is_prod, is_ds, w, thr, prod_thr,
        fit_weight=config.fit_weight,
        loadaware_weight=config.loadaware_weight,
        score_according_prod=config.score_according_prod,
    )
    return np.asarray(got), np.array(want)


def test_batched_solver_matches_sequential_oracle_small():
    got, want = _run_both(7, 23)
    np.testing.assert_array_equal(got, want)


def test_batched_solver_matches_sequential_oracle_config1():
    # BASELINE config #1: 100 pending pods, 20 nodes
    got, want = _run_both(20, 100)
    np.testing.assert_array_equal(got, want)
    assert (got >= 0).sum() > 0


def test_batched_solver_prod_scoring_mode():
    got, want = _run_both(11, 31, SolverConfig(score_according_prod=True))
    np.testing.assert_array_equal(got, want)


def test_unschedulable_when_no_capacity():
    # single tiny node, pod too big
    alloc = np.zeros((1, NUM_RESOURCES), dtype=np.int64)
    alloc[0, ResourceName.CPU] = 1000
    alloc[0, ResourceName.MEMORY] = 1024
    state = NodeState(
        alloc=jnp.asarray(alloc, jnp.int32),
        used_req=jnp.zeros((1, NUM_RESOURCES), jnp.int32),
        usage=jnp.zeros((1, NUM_RESOURCES), jnp.int32),
        prod_usage=jnp.zeros((1, NUM_RESOURCES), jnp.int32),
        est_extra=jnp.zeros((1, NUM_RESOURCES), jnp.int32),
        prod_base=jnp.zeros((1, NUM_RESOURCES), jnp.int32),
        metric_fresh=jnp.asarray(np.array([True])),
        schedulable=jnp.asarray(np.array([True])),
    )
    req = np.zeros((2, NUM_RESOURCES), dtype=np.int64)
    req[:, ResourceName.CPU] = 800  # first fits, second doesn't
    pods = PodBatch.build(
        req=jnp.asarray(req, jnp.int32),
        est=jnp.asarray(req, jnp.int32),
        is_prod=jnp.zeros(2, bool),
        is_daemonset=jnp.zeros(2, bool),
    )
    params = ScoreParams(
        weights=jnp.asarray(_weights(), jnp.int32),
        thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )
    _, got = schedule_batch(state, pods, params)
    assert got[0] == 0 and got[1] == -1
