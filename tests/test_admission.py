"""Admission gate (ISSUE 3 / DESIGN §12): QoS lanes, deadlines,
best-effort-first shedding, and same-base coalescing that is
bit-identical to sequential solves."""

import threading
import time

import numpy as np
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES, QoSClass
from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.service.admission import (
    LANE_BE,
    LANE_LS,
    LANE_SYSTEM,
    AdmissionConfig,
    AdmissionGate,
    coalesce_key,
    lane_for_qos,
    solve_coalesced,
)
from koordinator_tpu.service.client import (
    PlacementClient,
    SolverDeadlineExceeded,
    SolverShuttingDown,
)
from koordinator_tpu.service.codec import SolveRequest, SolveResponse
from koordinator_tpu.service.server import PlacementService, solve_from_request


def _base(n_nodes=6, seed=0):
    """Shared node/params groups (the coalescing base)."""
    rng = np.random.default_rng(seed)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, R.CPU] = 16000
    alloc[:, R.MEMORY] = 32768
    used = np.zeros_like(alloc)
    used[:, R.CPU] = rng.integers(0, 4000, n_nodes)
    node = {
        "alloc": alloc,
        "used_req": used,
        "usage": np.zeros_like(alloc),
        "prod_usage": np.zeros_like(alloc),
        "est_extra": np.zeros_like(alloc),
        "prod_base": np.zeros_like(alloc),
        "metric_fresh": np.ones(n_nodes, bool),
        "schedulable": np.ones(n_nodes, bool),
    }
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[R.CPU] = 1
    weights[R.MEMORY] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[R.CPU] = 65
    thresholds[R.MEMORY] = 95
    params = {
        "weights": weights,
        "thresholds": thresholds,
        "prod_thresholds": np.zeros(NUM_RESOURCES, np.int32),
    }
    return node, params


def _pods(n_pods, seed):
    rng = np.random.default_rng(seed)
    req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = rng.choice([500, 1000, 2000, 3000], n_pods)
    req[:, R.MEMORY] = rng.choice([256, 1024, 2048], n_pods)
    return {
        "req": req,
        "est": (req * 85) // 100,
        "is_prod": rng.uniform(size=n_pods) < 0.4,
        "is_daemonset": np.zeros(n_pods, bool),
    }


def _request(n_nodes=6, n_pods=5, seed=0, pod_seed=None, **over):
    node, params = _base(n_nodes, seed)
    req = SolveRequest(
        node=node, params=params,
        pods=_pods(n_pods, seed if pod_seed is None else pod_seed),
    )
    for k, v in over.items():
        setattr(req, k, v)
    return req


def _stub_response(request):
    n = int(np.asarray(request.pods["req"]).shape[0])
    return SolveResponse(assignments=np.zeros(n, np.int32))


class TestCoalescedBitIdentity:
    def test_property_coalesced_equals_sequential(self):
        """THE coalescing contract: K same-base requests merged into one
        device dispatch split back bit-identical to K solves run one by
        one against the same staged state — across random node counts,
        segment counts, and segment lengths."""
        rng = np.random.default_rng(42)
        for trial in range(6):
            n_nodes = int(rng.integers(3, 25))
            k = int(rng.integers(2, 6))
            requests = [
                _request(
                    n_nodes=n_nodes, seed=trial,
                    n_pods=int(rng.integers(1, 14)),
                    pod_seed=int(rng.integers(0, 2**31)),
                )
                for _ in range(k)
            ]
            keys = {coalesce_key(r) for r in requests}
            assert len(keys) == 1 and None not in keys
            sequential = [solve_from_request(r) for r in requests]
            # the gate's dispatch is assignments-only (satellite: the
            # [K,N,R] state carry is dead weight on the serving path) —
            # placements/commit must still match solo bit-for-bit, and
            # node_used_req comes back None by contract
            coalesced = solve_coalesced(requests)
            # want_state=True materializes the per-lane carries too
            # (the isolation property the pool leans on)
            full = solve_coalesced(requests, want_state=True)
            for i, (want, got, gotf) in enumerate(
                    zip(sequential, coalesced, full)):
                assert want.error == "" and got.error == ""
                assert got.node_used_req is None
                for field in ("assignments", "commit", "waiting",
                              "rejected", "raw_assign"):
                    np.testing.assert_array_equal(
                        getattr(want, field), getattr(got, field),
                        err_msg=f"trial {trial} segment {i} field {field}",
                    )
                    np.testing.assert_array_equal(
                        getattr(want, field), getattr(gotf, field),
                        err_msg=f"trial {trial} segment {i} field {field}"
                                " (want_state)",
                    )
                np.testing.assert_array_equal(
                    want.node_used_req, gotf.node_used_req,
                    err_msg=f"trial {trial} segment {i} node_used_req",
                )

class TestCoalesceKey:
    def test_same_base_same_key_different_pods(self):
        a = _request(n_pods=3, pod_seed=1)
        b = _request(n_pods=9, pod_seed=2)
        assert coalesce_key(a) == coalesce_key(b) is not None

    def test_node_bytes_differ_key_differs(self):
        a = _request(seed=0)
        b = _request(seed=0)
        b.node["used_req"] = np.array(b.node["used_req"], copy=True)
        b.node["used_req"][0, R.CPU] += 1
        assert coalesce_key(a) != coalesce_key(b)

    def test_feature_groups_ride_solo(self):
        assert coalesce_key(
            _request(quota={"used": np.zeros((1, NUM_RESOURCES))})
        ) is None
        assert coalesce_key(
            _request(node_delta={"epoch": np.asarray(1, np.int64)})
        ) is None

    def test_pod_dtype_schema_in_key(self):
        a = _request(pod_seed=1)
        b = _request(pod_seed=2)
        b.pods["req"] = b.pods["req"].astype(np.int64)
        assert coalesce_key(a) != coalesce_key(b)

    def test_lane_mapping(self):
        assert lane_for_qos(QoSClass.SYSTEM) == LANE_SYSTEM
        assert lane_for_qos(QoSClass.BE) == LANE_BE
        for q in (QoSClass.LS, QoSClass.LSR, QoSClass.LSE, QoSClass.NONE):
            assert lane_for_qos(q) == LANE_LS


class _BlockingSolve:
    """A solve_fn the test can hold closed to pin the executor."""

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()
        self.order = []

    def __call__(self, request, config, node_cache):
        self.order.append(request)
        self.entered.set()
        assert self.release.wait(10), "test forgot to release the solve"
        return _stub_response(request)


def _solo_request(tag: int, **over):
    """A request that can never coalesce (unique quota group) with a
    distinguishable pod count."""
    req = _request(n_pods=2 + tag % 3, pod_seed=tag)
    req.quota = {"tag": np.asarray([tag])}
    for k, v in over.items():
        setattr(req, k, v)
    return req


def _lane_group(lane, deadline_s=None):
    adm = {"lane": np.asarray(lane, np.int64)}
    if deadline_s is not None:
        adm["deadline_s"] = np.asarray(deadline_s, np.float64)
    return adm


class TestGateSemantics:
    def _gate(self, solve_fn, **cfg):
        return AdmissionGate(solve_fn, AdmissionConfig(**cfg))

    def test_lanes_drain_in_priority_order(self):
        solve = _BlockingSolve()
        gate = self._gate(solve, capacity=16)
        try:
            blocker = gate.submit(_solo_request(0), None)
            assert solve.entered.wait(5)
            entries = [
                gate.submit(
                    _solo_request(1, admission=_lane_group(LANE_BE)), None
                ),
                gate.submit(
                    _solo_request(2, admission=_lane_group(LANE_LS)), None
                ),
                gate.submit(
                    _solo_request(3, admission=_lane_group(LANE_SYSTEM)),
                    None,
                ),
            ]
            solve.release.set()
            for e in [blocker] + entries:
                assert e.wait(10).error == ""
            # order: blocker, then system > ls > be regardless of arrival
            tags = [
                int(np.asarray(r.quota["tag"]).item())
                for r in solve.order
            ]
            assert tags == [0, 3, 2, 1]
        finally:
            gate.shutdown(timeout=1)

    def test_deadline_expired_in_queue_typed_error(self):
        solve = _BlockingSolve()
        gate = self._gate(solve)
        try:
            blocker = gate.submit(_solo_request(0), None)
            assert solve.entered.wait(5)
            doomed = gate.submit(
                _solo_request(1, admission=_lane_group(LANE_LS, 0.02)),
                None,
            )
            time.sleep(0.05)  # expire while the executor is pinned
            solve.release.set()
            assert blocker.wait(10).error == ""
            resp = doomed.wait(10)
            assert resp.error.startswith("deadline-exceeded")
            assert gate.stats()["shed"]["deadline-exceeded"] == 1
        finally:
            gate.shutdown(timeout=1)

    def test_shed_best_effort_first(self):
        solve = _BlockingSolve()
        gate = self._gate(solve, capacity=2)
        try:
            blocker = gate.submit(_solo_request(0), None)
            assert solve.entered.wait(5)
            be_old = gate.submit(
                _solo_request(1, admission=_lane_group(LANE_BE)), None
            )
            be_new = gate.submit(
                _solo_request(2, admission=_lane_group(LANE_BE)), None
            )
            # queue full: an LS arrival evicts the NEWEST BE entry
            ls = gate.submit(
                _solo_request(3, admission=_lane_group(LANE_LS)), None
            )
            shed = be_new.wait(5)
            assert shed is not None and shed.error.startswith("overloaded")
            # ...but a BE arrival with nothing below it is itself refused
            be_refused = gate.submit(
                _solo_request(4, admission=_lane_group(LANE_BE)), None
            )
            # (be lane still has be_old; an equal-lane arrival outranks
            # nothing — shedding only reaches STRICTLY lower lanes)
            refused = be_refused.wait(5)
            assert refused.error.startswith("overloaded")
            solve.release.set()
            assert blocker.wait(10).error == ""
            assert ls.wait(10).error == ""
            assert be_old.wait(10).error == ""
            assert gate.stats()["shed"]["overloaded"] == 2
        finally:
            gate.shutdown(timeout=1)

    def test_shutdown_fails_queued_typed(self):
        solve = _BlockingSolve()
        gate = self._gate(solve)
        blocker = gate.submit(_solo_request(0), None)
        assert solve.entered.wait(5)
        queued = gate.submit(_solo_request(1), None)
        solve.release.set()
        gate.shutdown(timeout=5)
        assert blocker.wait(5).error == ""  # in-flight still answered
        assert queued.wait(5).error.startswith("shutting-down")
        late = gate.submit(_solo_request(2), None)
        assert late.wait(5).error.startswith("shutting-down")

    def test_coalesced_batch_one_dispatch(self):
        """K same-base requests queued behind a blocker drain as ONE
        batch: requests_total jumps by K while batches_total +1."""
        solve = _BlockingSolve()
        gate = self._gate(solve, capacity=32, max_coalesce=8)
        try:
            blocker = gate.submit(_solo_request(0), None)
            assert solve.entered.wait(5)
            same = [
                _request(n_nodes=5, seed=9, n_pods=3 + i, pod_seed=50 + i)
                for i in range(4)
            ]
            entries = [gate.submit(r, None) for r in same]
            solve.release.set()
            responses = [e.wait(20) for e in entries]
            for r, req in zip(responses, same):
                assert r.error == ""
                np.testing.assert_array_equal(
                    r.assignments, solve_from_request(req).assignments
                )
            st = gate.stats()
            assert st["requests_total"] == 5
            assert st["batches_total"] == 2  # blocker + one fused batch
            assert st["coalesced_requests_total"] == 4
            assert st["coalesce_ratio"] == pytest.approx(2.5)
        finally:
            gate.shutdown(timeout=1)

    def test_lone_client_skips_coalesce_window(self):
        """With <= 1 peer connected nobody can coalesce, so a solo
        coalescible request must dispatch immediately instead of
        lingering out the micro-batching window."""
        def instant(request, config, node_cache):
            return _stub_response(request)

        gate = AdmissionGate(
            instant,
            AdmissionConfig(coalesce_window_s=0.5),
            peer_count=lambda: 1,
        )
        try:
            t0 = time.monotonic()
            entry = gate.submit(_request(), None)
            resp = entry.wait(5)
            assert resp is not None and resp.error == ""
            assert time.monotonic() - t0 < 0.3  # no 0.5s window linger
        finally:
            gate.shutdown(timeout=1)

    def test_multi_peer_waits_the_window(self):
        def instant(request, config, node_cache):
            return _stub_response(request)

        gate = AdmissionGate(
            instant,
            AdmissionConfig(coalesce_window_s=0.3),
            peer_count=lambda: 2,
        )
        try:
            t0 = time.monotonic()
            entry = gate.submit(_request(), None)
            resp = entry.wait(5)
            assert resp is not None and resp.error == ""
            assert time.monotonic() - t0 >= 0.25  # window honored
        finally:
            gate.shutdown(timeout=1)

    def test_internal_error_is_typed_not_silence(self):
        def boom(request, config, node_cache):
            raise RuntimeError("staging exploded")

        gate = AdmissionGate(boom, AdmissionConfig())
        try:
            entry = gate.submit(_solo_request(0), None)
            resp = entry.wait(5)
            assert resp.error.startswith("internal")
            assert "staging exploded" in resp.error
        finally:
            gate.shutdown(timeout=1)


class TestServiceIntegration:
    def test_concurrent_identical_clients_bit_identical(self, tmp_path):
        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        want = solve_from_request(_request(n_pods=6, pod_seed=3))
        results, errors = {}, []
        barrier = threading.Barrier(6)

        def worker(i):
            try:
                with PlacementClient(addr, timeout=120.0) as client:
                    barrier.wait(timeout=30)
                    results[i] = client.solve(
                        _request(n_pods=6, pod_seed=3)
                    ).assignments
            except Exception as e:  # noqa: BLE001 — surface in main thread
                errors.append(e)

        try:
            threads = [
                threading.Thread(target=worker, args=(i,)) for i in range(6)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors
            assert len(results) == 6
            for got in results.values():
                np.testing.assert_array_equal(got, want.assignments)
            st = service.status()["admission"]
            assert st["requests_total"] == 6
            assert st["batches_total"] >= 1
            assert st["coalesce_ratio"] >= 1.0
        finally:
            service.stop()

    def test_deadline_exceeded_over_wire(self, tmp_path):
        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        inner = service.gate._solve_fn
        hold = threading.Event()

        def slow(request, config, node_cache):
            hold.wait(5)
            return inner(request, config, node_cache)

        service.gate._solve_fn = slow
        try:
            with PlacementClient(addr, timeout=60.0) as busy:
                t = threading.Thread(
                    target=busy.solve, args=(_request(seed=11),)
                )
                t.start()
                time.sleep(0.2)  # the slow solve now pins the executor
                with PlacementClient(addr, timeout=60.0) as client:
                    with pytest.raises(SolverDeadlineExceeded):
                        client.solve(_request(
                            admission=_lane_group(LANE_LS, 0.05)
                        ))
                hold.set()
                t.join(timeout=30)
        finally:
            hold.set()
            service.stop()

    def test_stop_delivers_shutting_down_frame(self, tmp_path):
        """Satellite 6: stop() drains queued requests into typed
        shutting-down error FRAMES before severing — a waiting client
        sees an error response, never a bare reset."""
        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        inner = service.gate._solve_fn
        hold = threading.Event()

        def slow(request, config, node_cache):
            hold.wait(10)
            return inner(request, config, node_cache)

        service.gate._solve_fn = slow
        outcome = {}

        def busy_worker():
            with PlacementClient(addr, timeout=60.0) as c:
                outcome["busy"] = c.solve(_request(seed=21))

        def queued_worker():
            try:
                with PlacementClient(addr, timeout=60.0) as c:
                    c.solve(_request(seed=22))
            except Exception as e:  # noqa: BLE001
                outcome["queued"] = e

        t1 = threading.Thread(target=busy_worker)
        t1.start()
        time.sleep(0.2)
        t2 = threading.Thread(target=queued_worker)
        t2.start()
        time.sleep(0.2)

        def release_soon():
            time.sleep(0.3)
            hold.set()  # let the in-flight solve finish during stop()

        threading.Thread(target=release_soon).start()
        service.stop()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert isinstance(outcome.get("queued"), SolverShuttingDown)
        # the in-flight request was still answered with a real solve
        assert outcome["busy"].error == ""

    def test_admission_metrics_on_debug_http(self, tmp_path):
        """Satellite 1: the gate's series ride the same /metrics
        surface as everything else, next to the kernel-breaker status
        in /apis/v1/plugins/solver."""
        import json
        import urllib.request

        from koordinator_tpu.metrics.components import SOLVER_METRICS
        from koordinator_tpu.scheduler.monitor import DebugServices
        from koordinator_tpu.utils.debug_http import DebugHTTPServer

        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        services = DebugServices()
        services.register("solver", service.status)
        debug = DebugHTTPServer(
            services=services, metrics=SOLVER_METRICS
        ).start()
        try:
            with PlacementClient(addr) as client:
                client.solve(_request())
            base = f"http://127.0.0.1:{debug.port}"
            text = urllib.request.urlopen(base + "/metrics").read().decode()
            assert "solver_admission_batches_total" in text
            assert "solver_admission_queue_depth" in text
            assert "solver_admission_wait_seconds_bucket" in text
            payload = json.loads(urllib.request.urlopen(
                base + "/apis/v1/plugins/solver"
            ).read().decode())
            assert payload["kernel_breaker"] is not None
            assert payload["admission"]["requests_total"] >= 1
        finally:
            debug.stop()
            service.stop()
