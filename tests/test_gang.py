"""Gang/coscheduling tests: segment feasibility, resource release, the
gang-gated batched solve (BASELINE config #4 shape), and the host
Permit-barrier state machine."""

import numpy as np
import jax.numpy as jnp

from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.apis.types import GangMode, GangSpec
from koordinator_tpu.gang.manager import GangManager, GangMatchPolicy, PermitResult
from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.ops.gang import GangState, gang_outcomes, release_rejected

CPU = ResourceName.CPU
MEM = ResourceName.MEMORY
RNG = np.random.default_rng(11)


def test_gang_outcomes_basic():
    # gang 0: 3 members all placed, min 3 -> commit
    # gang 1 (strict): 2 of 3 placed, min 3 -> rejected
    # gang 2 (non-strict): 1 of 2 placed, min 2 -> waiting
    # pod 8: no gang, placed -> commit; pod 9: no gang, unplaced
    assignments = jnp.asarray(
        np.array([0, 1, 2, 0, 1, -1, 2, -1, 3, -1], np.int32)
    )
    gang_id = jnp.asarray(np.array([0, 0, 0, 1, 1, 1, 2, 2, -1, -1], np.int32))
    gangs = GangState.build(
        min_member=[3, 3, 2],
        strict=[True, True, False],
    )
    commit, waiting, rejected = gang_outcomes(assignments, gang_id, gangs)
    np.testing.assert_array_equal(
        np.asarray(commit),
        [True, True, True, False, False, False, False, False, True, False],
    )
    np.testing.assert_array_equal(
        np.asarray(waiting),
        [False, False, False, False, False, False, True, False, False, False],
    )
    np.testing.assert_array_equal(
        np.asarray(rejected),
        [False, False, False, True, True, False, False, False, False, False],
    )


def test_gang_outcomes_bound_count():
    # gang with 2 already-bound members: one new placement reaches min 3
    assignments = jnp.asarray(np.array([5], np.int32))
    gang_id = jnp.asarray(np.array([0], np.int32))
    gangs = GangState.build(min_member=[3], bound_count=[2])
    commit, waiting, rejected = gang_outcomes(assignments, gang_id, gangs)
    assert bool(commit[0]) and not bool(waiting[0]) and not bool(rejected[0])


def test_gang_group_coupling():
    # two gangs in one gang-group: gang 0 satisfied, gang 1 not ->
    # NEITHER commits (all-or-nothing across the group)
    assignments = jnp.asarray(np.array([0, 1, 2, -1], np.int32))
    gang_id = jnp.asarray(np.array([0, 0, 1, 1], np.int32))
    gangs = GangState.build(
        min_member=[2, 2], strict=[True, True], group_id=[7, 7]
    )
    commit, waiting, rejected = gang_outcomes(assignments, gang_id, gangs)
    assert not np.asarray(commit).any()
    np.testing.assert_array_equal(
        np.asarray(rejected), [True, True, True, False]
    )


def test_release_rejected_restores_resources():
    n, p = 4, 3
    used = np.full((n, NUM_RESOURCES), 100, np.int32)
    extra = np.full((n, NUM_RESOURCES), 50, np.int32)
    prodb = np.full((n, NUM_RESOURCES), 30, np.int32)
    req = np.full((p, NUM_RESOURCES), 10, np.int32)
    est = np.full((p, NUM_RESOURCES), 7, np.int32)
    assignments = jnp.asarray(np.array([1, 1, 2], np.int32))
    rejected = jnp.asarray(np.array([True, True, False]))
    is_prod = jnp.asarray(np.array([True, False, True]))
    u, e, pb = release_rejected(
        jnp.asarray(used), jnp.asarray(extra), jnp.asarray(prodb),
        assignments, rejected, jnp.asarray(req), jnp.asarray(est), is_prod,
    )
    u, e, pb = np.asarray(u), np.asarray(e), np.asarray(pb)
    assert (u[1] == 80).all() and (u[2] == 100).all()  # two pods off node 1
    assert (e[1] == 36).all() and (e[0] == 50).all()
    assert (pb[1] == 23).all()  # only the prod pod's estimate


def _state(n, cpu=32000, mem=65536):
    alloc = np.zeros((n, NUM_RESOURCES), np.int64)
    alloc[:, CPU] = cpu
    alloc[:, MEM] = mem
    z = np.zeros((n, NUM_RESOURCES), np.int64)
    return NodeState(
        alloc=jnp.asarray(alloc, jnp.int32),
        used_req=jnp.asarray(z, jnp.int32),
        usage=jnp.asarray(z, jnp.int32),
        prod_usage=jnp.asarray(z, jnp.int32),
        est_extra=jnp.asarray(z, jnp.int32),
        prod_base=jnp.asarray(z, jnp.int32),
        metric_fresh=jnp.ones(n, bool),
        schedulable=jnp.ones(n, bool),
    )


def _params():
    w = np.zeros(NUM_RESOURCES, np.int64)
    w[CPU] = w[MEM] = 1
    return ScoreParams(
        weights=jnp.asarray(w, jnp.int32),
        thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
        prod_thresholds=jnp.zeros(NUM_RESOURCES, jnp.int32),
    )


def test_gang_gated_solve_all_or_nothing():
    # BASELINE config #4 shape at test scale: gangs of 4, tight capacity.
    # 2 nodes x 32 cores; gang pods want 8 cores each -> 8 fit total.
    # gang 0 (4 pods) fits, gang 1 (4 pods) fits, gang 2 (4 pods) does not
    # -> strict gang 2 fully rejected, its partial placements released.
    n_gangs, members = 3, 4
    p = n_gangs * members
    req = np.zeros((p, NUM_RESOURCES), np.int64)
    req[:, CPU] = 8000
    req[:, MEM] = 4096
    gang_id = np.repeat(np.arange(n_gangs), members).astype(np.int32)
    pods = PodBatch.build(
        req=jnp.asarray(req, jnp.int32),
        est=jnp.asarray((req * 85) // 100, jnp.int32),
        is_prod=jnp.zeros(p, bool),
        is_daemonset=jnp.zeros(p, bool),
        gang_id=jnp.asarray(gang_id),
    )
    gangs = GangState.build(min_member=[members] * n_gangs)
    state = _state(2)
    final_state, (assign, commit, waiting) = schedule_batch(
        state, pods, _params(), SolverConfig(), gang_state=gangs
    )
    assign = np.asarray(assign)
    commit = np.asarray(commit)
    # gangs 0 and 1 fully committed
    assert commit[: 2 * members].all()
    assert (assign[: 2 * members] >= 0).all()
    # gang 2 fully rejected (released)
    assert not commit[2 * members:].any()
    assert (assign[2 * members:] == -1).all()
    # released resources: node used_req equals exactly the committed pods
    used = np.asarray(final_state.used_req)
    assert used[:, CPU].sum() == 2 * members * 8000


def test_gang_nonstrict_waits_holding_resources():
    # NonStrict gang that can't fully place: placed members keep their nodes
    p = 3
    req = np.zeros((p, NUM_RESOURCES), np.int64)
    req[:, CPU] = 16000
    pods = PodBatch.build(
        req=jnp.asarray(req, jnp.int32),
        est=jnp.asarray(req, jnp.int32),
        is_prod=jnp.zeros(p, bool),
        is_daemonset=jnp.zeros(p, bool),
        gang_id=jnp.asarray(np.zeros(p, np.int32)),
    )
    gangs = GangState.build(min_member=[3], strict=[False])
    state = _state(1)  # one 32-core node: only 2 of 3 fit
    final_state, (assign, commit, waiting) = schedule_batch(
        state, pods, _params(), SolverConfig(), gang_state=gangs
    )
    assert not np.asarray(commit).any()
    np.testing.assert_array_equal(np.asarray(waiting), [True, True, False])
    np.testing.assert_array_equal(np.asarray(assign), [0, 0, -1])
    # resources still held
    assert np.asarray(final_state.used_req)[0, CPU] == 32000


# ---------------------------------------------------------------------------
# host state machine
# ---------------------------------------------------------------------------

def _mgr(min_member=2, mode=GangMode.STRICT, n_pods=3, name="g"):
    mgr = GangManager()
    mgr.update_gang(GangSpec(name=name, min_member=min_member, mode=mode))
    for i in range(n_pods):
        mgr.on_pod_add(f"{name}-p{i}", name)
    return mgr


def test_manager_prefilter_min_member_gate():
    mgr = GangManager()
    mgr.update_gang(GangSpec(name="g", min_member=3))
    mgr.on_pod_add("g-p0", "g")
    assert mgr.pre_filter("g-p0") is not None  # 1 < 3 children
    mgr.on_pod_add("g-p1", "g")
    mgr.on_pod_add("g-p2", "g")
    assert mgr.pre_filter("g-p0") is None


def test_manager_permit_barrier_then_allow():
    mgr = _mgr(min_member=2)
    assert mgr.pre_filter("g-p0") is None
    result, wait = mgr.permit("g-p0")
    assert result == PermitResult.WAIT and wait == 600.0
    result, _ = mgr.permit("g-p1")
    assert result == PermitResult.ALLOW
    released = mgr.allow_gang_group("g")
    assert set(released) == {"g-p0", "g-p1"}


def test_manager_strict_rejection_releases_waiting():
    mgr = _mgr(min_member=3)
    mgr.permit("g-p0")
    mgr.permit("g-p1")
    rejected = mgr.unreserve("g-p2")  # p2 failed filter after others assumed
    assert set(rejected) == {"g-p0", "g-p1"}
    # cycle now invalid: strict members fail PreFilter until all attempted
    assert mgr.pre_filter("g-p0") is not None


def test_manager_cycle_reopens_after_all_children_attempt():
    mgr = _mgr(min_member=3, n_pods=3)
    # p0 and p1 attempt cycle 1, then the group is rejected
    assert mgr.pre_filter("g-p0") is None
    assert mgr.pre_filter("g-p1") is None
    mgr.reject_gang_group("g")
    # cycle invalid and not all children have attempted yet: retries fail
    assert mgr.pre_filter("g-p0") is not None
    assert mgr.pre_filter("g-p1") is not None
    # p2's first attempt also fails (cycle invalid) but completes the
    # attempt set...
    assert mgr.pre_filter("g-p2") is not None
    # ...so the cycle reopens and retries pass again
    assert mgr.pre_filter("g-p0") is None


def test_manager_once_satisfied_short_circuits():
    mgr = _mgr(min_member=2)
    mgr.permit("g-p0")
    mgr.permit("g-p1")
    mgr.allow_gang_group("g")
    mgr.on_pod_bound("g-p0")
    mgr.on_pod_bound("g-p1")
    # a later member of a satisfied gang passes PreFilter unconditionally
    # and its failure doesn't reject the gang
    assert mgr.pre_filter("g-p2") is None
    assert mgr.unreserve("g-p2") == []


def test_manager_nonstrict_failure_keeps_waiting():
    mgr = _mgr(min_member=3, mode=GangMode.NON_STRICT)
    mgr.permit("g-p0")
    mgr.permit("g-p1")
    assert mgr.unreserve("g-p2") == []  # non-strict: no group rejection
