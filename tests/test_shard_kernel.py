"""Sharded pallas kernel == single-device solver, bit-for-bit.

VERDICT r4 #3: the multi-chip path previously lowered to the
HBM-streaming scan; the pallas kernel now composes under
``jax.shard_map`` — per-shard VMEM carry, per-pod cross-shard winner
merge over in-kernel remote DMAs (``parallel.mesh.shard_kernel_solver``,
``ops/pallas_binpack._make_kernel`` n_shards > 1). On the 8-device
virtual CPU mesh the kernels run under the TPU interpreter with
emulated remote DMAs — same program, same synchronization.

Identity bar: assignments AND every mutated carry equal the
single-device ``solve_batch`` exactly, cross-shard argmax tie-breaks
(smallest node index) included.
"""

import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from __graft_entry__ import _example_problem
from koordinator_tpu.apis.extension import NUM_RESOURCES, ResourceName
from koordinator_tpu.ops.binpack import NumaAux, SolverConfig, solve_batch
from koordinator_tpu.parallel.mesh import (
    distributed_kernel_supported,
    make_mesh,
    shard_kernel_solver,
)

#: the distributed kernel needs pltpu.CompilerParams + the TPU
#: interpreter's emulated remote DMAs (pltpu.InterpretParams off-TPU);
#: jax 0.4.x ships neither — the GSPMD path (test_parallel.py /
#: test_full_scale.py) carries the multichip identity contract there
pytestmark = pytest.mark.skipif(
    not distributed_kernel_supported(),
    reason="distributed pallas kernel APIs unavailable on this jax build",
)


def _single(state, pods, params, *args, **kw):
    return jax.jit(
        lambda s, p, pr: solve_batch(s, p, pr, SolverConfig(), *args, **kw)
    )(state, pods, params)


def _assert_result_equal(sharded, single, quota=False, numa=False):
    np.testing.assert_array_equal(
        np.asarray(sharded.assign), np.asarray(single.assign)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.commit), np.asarray(single.commit)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.node_state.used_req),
        np.asarray(single.node_state.used_req),
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.node_state.est_extra),
        np.asarray(single.node_state.est_extra),
    )
    if numa:
        np.testing.assert_array_equal(
            np.asarray(sharded.node_state.numa_free),
            np.asarray(single.node_state.numa_free),
        )
        np.testing.assert_array_equal(
            np.asarray(sharded.numa_consumed),
            np.asarray(single.numa_consumed),
        )
    if quota:
        np.testing.assert_array_equal(
            np.asarray(sharded.quota_state.used),
            np.asarray(single.quota_state.used),
        )
        np.testing.assert_array_equal(
            np.asarray(sharded.quota_state.np_used),
            np.asarray(single.quota_state.np_used),
        )


def test_two_device_plain_identity():
    state, pods, params = _example_problem(256, 96, seed=3)
    mesh = make_mesh(jax.devices()[:2])
    res = shard_kernel_solver(mesh)(state, pods, params)
    single = _single(state, pods, params)
    _assert_result_equal(res, single)
    assert int((np.asarray(res.assign) >= 0).sum()) > 0


def test_eight_device_unpadded_node_count():
    """327 nodes is not a multiple of 8 x 128: the global padding path
    (unschedulable zero rows) must keep indices and tie-breaks exact."""
    state, pods, params = _example_problem(327, 64, seed=7)
    mesh = make_mesh(jax.devices()[:8])
    res = shard_kernel_solver(mesh)(state, pods, params)
    single = _single(state, pods, params)
    _assert_result_equal(res, single)


def test_eight_device_full_features_identity():
    """Quota + strict gangs + NUMA through the sharded kernel: the
    replicated quota replay, local NUMA consumption with cross-shard
    consumed-OR, and the gang release epilogue must all match the
    single-device solve bit-for-bit. 1024 nodes keeps every shard
    tile-aligned with REAL rows (1024/8 = 128 lanes each — no
    padding-only shards); the pod count is what the interpret-mode
    emulation's wall time scales with, so 96 pods instead of the
    original 256 cuts this leg from 1769s to ~50s without narrowing
    feature coverage (the driver dryrun separately proves 1024x1536
    all-features via shard_full_solver)."""
    from koordinator_tpu.ops.gang import GangState
    from koordinator_tpu.ops.quota import QuotaState

    n_nodes, n_pods, n_quota, n_gangs = 1024, 96, 8, 8
    state, pods, params = _example_problem(n_nodes, n_pods, seed=11)
    rng = np.random.default_rng(11)
    cap = np.asarray(state.alloc)
    free = (cap * rng.uniform(0.3, 1.0, cap.shape)).astype(np.int32)
    state = state._replace(
        numa_cap=jnp.asarray(cap), numa_free=jnp.asarray(free)
    )
    gang_id = np.full(n_pods, -1, np.int32)
    gang_id[: n_gangs * 8] = np.repeat(
        np.arange(n_gangs, dtype=np.int32), 8
    )
    pods = pods._replace(
        quota_id=jnp.asarray(
            rng.integers(0, n_quota, n_pods).astype(np.int32)
        ),
        gang_id=jnp.asarray(gang_id),
        has_numa_policy=jnp.asarray(rng.uniform(size=n_pods) < 0.4),
        non_preemptible=jnp.asarray(rng.uniform(size=n_pods) < 0.3),
    )
    total = cap.astype(np.int64).sum(axis=0)
    mn = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    mx = np.zeros_like(mn)
    mn[:, ResourceName.CPU] = total[ResourceName.CPU] // (2 * n_quota)
    mn[:, ResourceName.MEMORY] = total[ResourceName.MEMORY] // (2 * n_quota)
    mx[:, ResourceName.CPU] = total[ResourceName.CPU] // 6
    mx[:, ResourceName.MEMORY] = total[ResourceName.MEMORY] // 6
    qid = np.asarray(pods.quota_id)
    child = np.zeros((n_quota, NUM_RESOURCES), np.int64)
    np.add.at(child, qid, np.asarray(pods.req).astype(np.int64))
    qstate = QuotaState.build(
        min=mn, max=mx, weight=mx, allow_lent=np.ones(n_quota, bool),
        total=total, child_request=child,
    )
    gstate = GangState.build(min_member=[8] * n_gangs)
    aux = NumaAux(node_policy=jnp.asarray(rng.uniform(size=n_nodes) < 0.5))

    single = jax.jit(
        lambda s, p, pr, q, g, n_: solve_batch(
            s, p, pr, SolverConfig(), q, g, numa=n_
        )
    )(state, pods, params, qstate, gstate, aux)
    mesh = make_mesh(jax.devices()[:8])
    sharded = shard_kernel_solver(mesh)(
        state, pods, params, qstate, gstate, aux
    )
    _assert_result_equal(sharded, single, quota=True, numa=True)
    assert int(np.asarray(sharded.numa_consumed).sum()) > 0


@pytest.mark.skipif(
    os.environ.get("KTPU_SLOW", "1") == "0",
    reason="interpret-mode remote DMA emulation at 5k nodes is slow",
)
def test_eight_device_5k_nodes_identity():
    """The VERDICT bar: sharded-kernel == single-device at >= 5k nodes
    on the 8-device virtual mesh (interpret-mode remote DMAs)."""
    state, pods, params = _example_problem(5120, 256, seed=5)
    mesh = make_mesh(jax.devices()[:8])
    t0 = time.time()
    res = shard_kernel_solver(mesh)(state, pods, params)
    np.asarray(res.assign)
    wall = time.time() - t0
    single = _single(state, pods, params)
    _assert_result_equal(res, single)
    assert int((np.asarray(res.assign) >= 0).sum()) > 0
    # emulated wall time recorded for visibility, not asserted
    print(f"5120-node 8-device interpret solve: {wall:.1f}s")


def test_four_device_resv_identity():
    """Reservation credit/consumption through the sharded kernel: the
    replicated rfree replay and the shard-offset one-hot credit matmul
    must match the single-device solve bit-for-bit, gang releases
    included. Shape kept small (4 devices x 256 nodes x 64 pods) so the
    interpret-mode remote-DMA emulation finishes in ordinary per-test
    budgets — cross-shard exchange is fully exercised at any K >= 2."""
    from koordinator_tpu.ops.gang import GangState
    from koordinator_tpu.testing import example_resv

    n_nodes, n_pods, n_resv, n_gangs = 256, 64, 9, 4
    state, pods, params = _example_problem(n_nodes, n_pods, seed=13)
    gang_id = np.full(n_pods, -1, np.int32)
    gang_id[: n_gangs * 8] = np.repeat(
        np.arange(n_gangs, dtype=np.int32), 8
    )
    pods = pods._replace(gang_id=jnp.asarray(gang_id))
    gstate = GangState.build(min_member=[8] * n_gangs)
    resv = example_resv(n_resv, n_nodes, n_pods, seed=13)
    single = jax.jit(
        lambda s, p, pr, g, r: solve_batch(
            s, p, pr, SolverConfig(), None, g, resv=r
        )
    )(state, pods, params, gstate, resv)
    mesh = make_mesh(jax.devices()[:4])
    sharded = shard_kernel_solver(mesh)(
        state, pods, params, None, gstate, resv=resv
    )
    _assert_result_equal(sharded, single)
    for field in ("resv_free", "resv_vstar", "resv_delta"):
        np.testing.assert_array_equal(
            np.asarray(getattr(sharded, field)),
            np.asarray(getattr(single, field)), err_msg=field)
    assert int((np.asarray(single.resv_vstar) >= 0).sum()) > 0
