"""The AOT warm pool (ISSUE 13, docs/DESIGN.md §21).

Contracts under test:

- store framing + provenance: every way an entry can be bad —
  truncated, bit-flipped, wrong magic, oversized, stale host
  fingerprint, version-skewed, torn by concurrent writers
  (``testing.chaos.WARM_POOL_FAULT_KINDS``) — is a TYPED
  ``WarmEntryError`` (mirroring tests/test_wire_hardening.py's
  typed-error discipline for the wire), a counted REJECT
  (``scheduler_warm_pool_rejects_total``), and a quarantine; never a
  crash, never a retry loop, never a stale-executable solve;
- persist → restore → serve: a fresh process (fresh pool + fresh jit
  binding) answers adopted calls from deserialized executables —
  bit-identical to the jit path, ZERO XLA recompiles (the
  ``xla_compiles`` fixture), and the warm path provably never donates
  its inputs (the §19.2 pin, same observable contract as
  ``test_sharded_scatter_never_donates``);
- the failover twin prewarms from signatures another BINDING persisted
  (program-identity sharing: the sidecar's store warms the scheduler's
  degraded path);
- the promotion sweep restores pool + staged world (``StateAuditor``
  with ``warm_pool``);
- graftcheck's donation rule refuses donating jits in the warm-pool
  module AND donating bindings at any adopt site.

The suite runs on the forced 8-virtual-device mesh, so pools here pass
``force_single_device=True`` — one physical host, and the §19.2 replay
bug needs donation, which the pool structurally lacks (that is the
point of the guard). Production keeps the conservative gate.
"""

import os

import jax
import numpy as np
import pytest

from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.ops.binpack import SolverConfig, solve_batch
from koordinator_tpu.service.warmpool import WarmPool
from koordinator_tpu.testing import example_problem
from koordinator_tpu.testing.chaos import (
    WARM_POOL_FAULT_KINDS,
    sabotage_store,
)
from koordinator_tpu.utils.compilation_cache import (
    ExecutableCache,
    WarmEntryCorrupt,
    WarmEntryError,
    WarmEntryFingerprintMismatch,
    WarmEntryOversized,
    WarmEntryTruncated,
    frame_payload,
    unframe_payload,
)


@pytest.fixture(autouse=True)
def _fresh_device_obs():
    """A fresh observatory slate per test: the process-global
    DEVICE_OBS accumulates warm-manifest avals across the whole suite,
    and persist() would otherwise AOT-compile every solve signature
    every other module ever recorded."""
    DEVICE_OBS.reset()
    yield


def _make_toy():
    """A tiny warm-poolable program shaped like the real solves: arrays
    around a static config (argpos 2). Compiles in milliseconds so the
    store-mechanics tests don't pay solve-sized compile times. A fresh
    CLOSURE per test: jax's pjit executable cache is shared per
    underlying function, so a fresh function object is what makes a
    fresh binding's first call a real, observable compile (the restart
    shape these tests simulate in-process)."""

    def toy_program(a, b, scale, c):
        return (a + b) * scale - c

    return toy_program


def _toy_binding(toy, name="toy_solve"):
    return DEVICE_OBS.jit(name, jax.jit(
        toy, static_argnums=(2,), donate_argnums=()
    ))


def _toy_args(n=8, scale=3):
    return (
        jax.numpy.arange(n, dtype=jax.numpy.int32),
        jax.numpy.ones(n, dtype=jax.numpy.int32),
        scale,
        jax.numpy.full(n, 2, dtype=jax.numpy.int32),
    )


def _pool(tmp_path, name="store"):
    return WarmPool().configure(
        str(tmp_path / name), force_single_device=True
    )


def _seed_toy(tmp_path, name="toy_solve"):
    """One warmed toy pool: binding called (signature recorded),
    persisted to disk. Returns (pool, binding, args, reference, toy)."""
    pool = _pool(tmp_path)
    toy = _make_toy()
    binding = _toy_binding(toy, name)
    pool.adopt(binding, toy, config_argpos=2)
    args = _toy_args()
    want = np.asarray(binding(*args))
    report = pool.persist()
    assert report["persisted"] == 1
    return pool, binding, args, want, toy


class TestStoreFraming:
    def test_round_trip(self):
        body = os.urandom(1024)
        assert unframe_payload(frame_payload(body)) == body

    def test_truncated(self):
        framed = frame_payload(b"x" * 100)
        with pytest.raises(WarmEntryTruncated):
            unframe_payload(framed[:16])
        with pytest.raises(WarmEntryTruncated):
            unframe_payload(framed[:-10])

    def test_wrong_magic(self):
        framed = bytearray(frame_payload(b"payload"))
        framed[:4] = b"EVIL"
        with pytest.raises(WarmEntryCorrupt):
            unframe_payload(bytes(framed))

    def test_bitflip_is_fingerprint_mismatch(self):
        framed = bytearray(frame_payload(b"p" * 256))
        framed[-5] ^= 0xFF
        with pytest.raises(WarmEntryFingerprintMismatch):
            unframe_payload(bytes(framed))

    def test_oversized_declared_length(self):
        import struct

        framed = bytearray(frame_payload(b"tiny"))
        framed[8:16] = struct.pack(">Q", 1 << 62)
        with pytest.raises(WarmEntryOversized):
            unframe_payload(bytes(framed))


class TestExecutableCacheHardening:
    """load_checked's typed errors + quarantine, against real entry
    files (a tiny jitted program, not the solve)."""

    def _seed(self, tmp_path):
        cache = ExecutableCache(str(tmp_path))
        fn = jax.jit(lambda x: x + 1)
        compiled = fn.lower(jax.numpy.arange(4)).compile()
        assert cache.store("k", compiled)
        return cache

    def _entry_path(self, cache):
        return cache._path("k")

    @pytest.mark.parametrize("kind", WARM_POOL_FAULT_KINDS)
    def test_fuzzed_entry_is_typed_never_a_crash(self, tmp_path, kind):
        cache = self._seed(tmp_path)
        assert sabotage_store(str(tmp_path), kind, seed=7) is not None
        with pytest.raises(WarmEntryError):
            cache.load_checked("k")
        # the silent form maps every typed failure to a plain miss
        assert cache.load("k") is None

    def test_quarantine_moves_aside_never_retries(self, tmp_path):
        cache = self._seed(tmp_path)
        sabotage_store(str(tmp_path), "bitflipped-entry", seed=7)
        with pytest.raises(WarmEntryFingerprintMismatch):
            cache.load_checked("k")
        moved = cache.quarantine("k")
        assert moved is not None and moved.endswith(".quarantined")
        assert os.path.exists(moved)
        # the poisoned entry is GONE from the load path: the next load
        # is a clean miss, not a crash loop
        assert cache.load_checked("k") is None
        assert cache.quarantine("k") is None  # nothing left to move

    def test_garbage_file_is_corrupt(self, tmp_path):
        cache = self._seed(tmp_path)
        with open(self._entry_path(cache), "wb") as f:
            f.write(os.urandom(64))
        with pytest.raises(WarmEntryCorrupt):
            cache.load_checked("k")

    def test_oversized_file_refused_before_read(self, tmp_path,
                                                monkeypatch):
        cache = self._seed(tmp_path)
        monkeypatch.setenv("KTPU_WARM_MAX_ENTRY_BYTES", "16")
        with pytest.raises(WarmEntryOversized):
            cache.load_checked("k")


class TestWarmPoolSmoke:
    def test_smoke_persist_restore_serve_identical(self, tmp_path):
        """The §21 round trip: a fresh pool + fresh binding (the
        restart shape) serves the call from the store, bit-identical,
        with hit/served counters moving."""
        _pool0, _b0, args, want, toy = _seed_toy(tmp_path)
        binding = _toy_binding(toy)
        pool = _pool(tmp_path)
        pool.adopt(binding, toy, config_argpos=2)
        report = pool.restore()
        assert report["restored"] == 1 and report["failed"] == 0
        got = np.asarray(binding(*args))
        np.testing.assert_array_equal(got, want)
        status = pool.status()
        assert status["hits"] == 1
        assert status["served"] == 1
        assert status["quarantined"] == 0
        assert status["misses"] == 0
        assert status["rejects"] == {}

    def test_smoke_zero_xla_recompiles_when_served(self, tmp_path,
                                                   xla_compiles):
        """The restored executable answers with ZERO XLA compilations
        — no trace, no lower, no backend compile (the restart-blackout
        criterion, quantitative)."""
        _pool0, _b0, args, want, toy = _seed_toy(tmp_path)
        binding = _toy_binding(toy)
        pool = _pool(tmp_path)
        pool.adopt(binding, toy, config_argpos=2)
        pool.restore()
        xla_compiles.clear()
        got = np.asarray(binding(*args))
        np.testing.assert_array_equal(got, want)
        assert xla_compiles == [], (
            "a warm-served call compiled — the pool is not serving"
        )

    def test_unknown_signature_falls_through_to_jit(self, tmp_path):
        _pool0, _b0, args, _want, toy = _seed_toy(tmp_path)
        binding = _toy_binding(toy)
        pool = _pool(tmp_path)
        pool.adopt(binding, toy, config_argpos=2)
        pool.restore()
        other = _toy_args(n=16)  # a shape the store never saw
        got = np.asarray(binding(*other))
        np.testing.assert_array_equal(got, (np.arange(16) + 1) * 3 - 2)
        assert pool.status()["served"] == 0

    def test_inert_pool_never_serves(self, tmp_path, monkeypatch):
        """The suite default (empty cache dir) keeps the singleton
        inert: adopted bindings run the plain jit path untouched."""
        monkeypatch.setenv("KTPU_COMPILATION_CACHE_DIR", "")
        pool = WarmPool().configure(None)
        toy = _make_toy()
        binding = _toy_binding(toy)
        pool.adopt(binding, toy, config_argpos=2)
        assert not pool.active
        assert not pool.serving
        args = _toy_args()
        np.testing.assert_array_equal(
            np.asarray(binding(*args)),
            (np.arange(8) + 1) * 3 - 2,
        )

    def test_poisoned_executable_ejected_not_fatal(self, tmp_path):
        """A restored executable that raises at call time is dropped
        (never re-served) and the call is answered by the jit path."""
        _pool0, _b0, args, want, toy = _seed_toy(tmp_path)
        binding = _toy_binding(toy)
        pool = _pool(tmp_path)
        pool.adopt(binding, toy, config_argpos=2)
        pool.restore()

        def boom(*_a):
            raise RuntimeError("poisoned executable")

        with pool._lock:
            key = next(iter(pool._execs))
            pool._execs[key] = boom
        got = np.asarray(binding(*args))  # jit fallback, not a crash
        np.testing.assert_array_equal(got, want)
        assert pool.status()["executables"] == 0  # ejected
        assert "poisoned" in pool.status()["last_error"]
        # and it stays ejected: the next call is plain jit, no retry
        np.testing.assert_array_equal(np.asarray(binding(*args)), want)


class TestCorruptStore:
    @pytest.mark.parametrize("kind", WARM_POOL_FAULT_KINDS)
    def test_corrupt_entry_typed_counted_quarantined(self, tmp_path, kind):
        """Satellite 1: every store corruption is a typed fallback —
        the restore reports the failure, counts the miss under its
        reason, quarantines the entry, and the scheduler-side outcome
        is COLD COMPILE, not a crash and not a skipped solve."""
        _pool0, _b0, args, want, toy = _seed_toy(tmp_path)
        assert sabotage_store(str(tmp_path / "store"), kind, seed=3)
        binding = _toy_binding(toy)
        pool = _pool(tmp_path)
        pool.adopt(binding, toy, config_argpos=2)
        report = pool.restore()  # loads only: the shape stays cold
        assert report["restored"] == 0 and report["failed"] == 1
        status = pool.status()
        assert status["misses"] == 0  # a reject is NOT a clean miss
        assert sum(status["rejects"].values()) == 1
        reason = next(iter(status["rejects"]))
        assert reason in ("truncated", "corrupt", "fingerprint",
                          "oversized", "stale-host", "version-skew")
        assert status["quarantined"] == 1
        assert status["last_error"] is not None
        # silent fallback to cold compile: the call still answers,
        # bit-identical, through the ordinary jit path
        np.testing.assert_array_equal(np.asarray(binding(*args)), want)

    def test_quarantined_entry_not_retried_in_a_loop(self, tmp_path):
        _pool0, _b0, _args, _want, toy = _seed_toy(tmp_path)
        sabotage_store(str(tmp_path / "store"), "bitflipped-entry", seed=3)
        pool = _pool(tmp_path)
        pool.adopt(_toy_binding(toy), toy, config_argpos=2)
        pool.restore()
        assert pool.status()["quarantined"] == 1
        # a second restore meets a MISSING entry (quarantined aside),
        # never the same poisoned bytes again
        pool2 = _pool(tmp_path)
        pool2.adopt(_toy_binding(toy), toy, config_argpos=2)
        pool2.restore()
        assert pool2.status()["quarantined"] == 0
        assert pool2.status()["rejects"] == {}
        assert pool2.status()["misses"] == 1  # clean absence, not a reject

    def test_corrupt_entry_recompiled_when_asked(self, tmp_path):
        """``compile_missing=True`` (the failover prewarm path): the
        quarantined entry is cold-compiled off-path and RE-STORED, so
        the store self-heals."""
        _pool0, _b0, args, want, toy = _seed_toy(tmp_path)
        sabotage_store(str(tmp_path / "store"), "bitflipped-entry", seed=3)
        binding = _toy_binding(toy)
        pool = _pool(tmp_path)
        pool.adopt(binding, toy, config_argpos=2)
        report = pool.restore(compile_missing=True)
        # a cold-compiled row counts ONLY under "compiled" — restored
        # means deserialized, the signal the supervisor's probe-budget
        # split keys its tight warm grace on
        assert report["compiled"] == 1 and report["restored"] == 0
        assert pool.status()["quarantined"] == 1
        np.testing.assert_array_equal(np.asarray(binding(*args)), want)
        # the store healed: a third pool loads clean
        pool3 = _pool(tmp_path)
        pool3.adopt(_toy_binding(toy), toy, config_argpos=2)
        assert pool3.restore()["restored"] == 1
        assert pool3.status()["hits"] == 1

    def test_corrupt_manifest_degrades_to_cold(self, tmp_path):
        _pool0, _b0, args, want, toy = _seed_toy(tmp_path)
        assert sabotage_store(str(tmp_path / "store"), "bitflipped-entry",
                              seed=3, manifest=True)
        binding = _toy_binding(toy)
        pool = _pool(tmp_path)
        pool.adopt(binding, toy, config_argpos=2)
        report = pool.restore()
        assert report["restored"] == 0 and report["rows"] == 0
        assert pool.status()["quarantined"] == 1
        assert pool.status()["rejects"] == {"fingerprint": 1}
        np.testing.assert_array_equal(np.asarray(binding(*args)), want)

    def test_metrics_series_move(self, tmp_path):
        from koordinator_tpu.metrics.components import (
            WARM_POOL_HITS,
            WARM_POOL_QUARANTINED,
            WARM_POOL_REJECTS,
        )

        h0 = WARM_POOL_HITS.value()
        q0 = WARM_POOL_QUARANTINED.value()
        m0 = WARM_POOL_REJECTS.value({"reason": "fingerprint"})
        _pool0, _b0, _args, _want, toy = _seed_toy(tmp_path)
        pool = _pool(tmp_path)
        pool.adopt(_toy_binding(toy), toy, config_argpos=2)
        pool.restore()
        assert WARM_POOL_HITS.value() == h0 + 1
        sabotage_store(str(tmp_path / "store"), "bitflipped-entry", seed=3)
        pool2 = _pool(tmp_path)
        pool2.adopt(_toy_binding(toy), toy, config_argpos=2)
        pool2.restore()
        assert WARM_POOL_REJECTS.value({"reason": "fingerprint"}) == m0 + 1
        assert WARM_POOL_QUARANTINED.value() == q0 + 1


@pytest.fixture(scope="module")
def solve_store(tmp_path_factory):
    """A store seeded with ONE real solve_batch signature (50 nodes ×
    64-bucket pods) — shared by the never-donate / failover / promotion
    tests so the suite pays the solve compile once."""
    store = tmp_path_factory.mktemp("solve-store")
    pool = WarmPool().configure(str(store), force_single_device=True)
    binding = DEVICE_OBS.jit("solve_batch", jax.jit(
        solve_batch, static_argnames=("config",), donate_argnums=()
    ))
    pool.adopt(binding, solve_batch, config_argpos=3)
    state, pods, params = example_problem(50, 60)
    cfg = SolverConfig()
    # the full positional convention every production caller uses
    # (placement model / failover twin / sidecar): feature states ride
    # as explicit Nones and are part of the signature
    args = (state, pods, params, cfg, None, None, None, None, None)
    want = binding(*args)
    report = pool.persist()
    assert report["persisted"] >= 1
    return {
        "dir": str(store),
        "args": args,
        "want_assign": np.asarray(want.assign),
    }


class TestNeverDonates:
    def test_warm_serve_never_donates_inputs(self, solve_store):
        """The §19.2 pin, runtime half (same observable contract as
        test_sharded_scatter_never_donates): a warm-served solve's
        inputs survive the call — a donated program would delete
        them — and the result is bit-identical to the jit path."""
        args = solve_store["args"]
        state = args[0]
        binding = DEVICE_OBS.jit("solve_batch", jax.jit(
            solve_batch, static_argnames=("config",), donate_argnums=()
        ))
        pool = WarmPool().configure(
            solve_store["dir"], force_single_device=True
        )
        pool.adopt(binding, solve_batch, config_argpos=3)
        assert pool.restore()["restored"] >= 1
        result = binding(*args)
        assert pool.status()["served"] == 1, "jit path answered, not warm"
        assert not state.alloc.is_deleted(), (
            "the warm path donated its input — the §19.2 replay bug "
            "is reachable again"
        )
        assert not state.used_req.is_deleted()
        np.testing.assert_array_equal(
            np.asarray(result.assign), solve_store["want_assign"]
        )

    def test_graftcheck_refuses_donating_jit_in_warm_module(self):
        """Static half of the pin: a donating (or undeclared) jit
        factory inside the warm-pool module is a donation-safety
        violation."""
        from koordinator_tpu.analysis.graftcheck.engine import load_module
        from koordinator_tpu.analysis.graftcheck.rules import DonationRule

        fixture = os.path.join(
            os.path.dirname(__file__), "fixtures", "graftcheck",
            "warm_donate.py",
        )
        module = load_module(
            __import__("pathlib").Path(fixture), "warm_pool_fixture.py"
        )
        rule = DonationRule(no_donate_globs=("warm_pool_fixture.py",))
        violations = rule.check(module)
        messages = [v.message for v in violations]
        assert any("donate_argnums=()" in m for m in messages), messages
        assert any("adopted into the warm pool" in m for m in messages), \
            messages

    def test_graftcheck_repo_warm_module_clean(self):
        """The real warm-pool module and every real adopt site pass
        the guard (the repo-wide run is also gated by check.sh; this
        pins the rule actually COVERS the production files)."""
        from pathlib import Path

        from koordinator_tpu.analysis.graftcheck.engine import load_module
        from koordinator_tpu.analysis.graftcheck.rules import (
            NO_DONATE_MODULES,
            DonationRule,
        )

        root = Path(__file__).resolve().parent.parent
        rule = DonationRule(no_donate_globs=NO_DONATE_MODULES)
        for rel in (
            "koordinator_tpu/service/warmpool.py",
            "koordinator_tpu/models/placement.py",
            "koordinator_tpu/service/failover.py",
            "koordinator_tpu/service/server.py",
        ):
            module = load_module(root / rel, rel)
            assert rule.check(module) == [], rel

    def test_stripped_donation_declaration_caught(self, tmp_path):
        """Teeth against the REAL module source: rewriting the warm
        pool's jit to donate must flag (the injected-violation pattern
        of test_graftcheck_v2)."""
        from pathlib import Path

        from koordinator_tpu.analysis.graftcheck.engine import load_module
        from koordinator_tpu.analysis.graftcheck.rules import DonationRule

        root = Path(__file__).resolve().parent.parent
        src = (root / "koordinator_tpu/service/warmpool.py").read_text()
        # target the CODE declaration, not the docstring mention
        evil = src.replace("static_argnums=(), donate_argnums=()",
                           "static_argnums=(), donate_argnums=(0,)", 1)
        assert evil != src
        bad = tmp_path / "warmpool.py"
        bad.write_text(evil)
        rule = DonationRule(
            no_donate_globs=("koordinator_tpu/service/warmpool.py",)
        )
        module = load_module(bad, "koordinator_tpu/service/warmpool.py")
        assert any(
            "warm-path jit factory" in v.message
            for v in rule.check(module)
        )


class TestFailoverPrewarm:
    def test_local_twin_prewarms_from_shared_program(self, solve_store,
                                                     xla_compiles):
        """The failover twin loads executables persisted under the
        ``solve_batch`` BINDING (program-identity sharing): its first
        degraded-mode solve is warm — zero XLA compiles — and
        bit-identical."""
        from koordinator_tpu.service import failover
        from koordinator_tpu.service.client import SolverUnavailable

        pool = WarmPool().configure(
            solve_store["dir"], force_single_device=True
        )
        # _local_solve is a MODULE-LEVEL binding: re-adopt for this
        # test, restore the singleton adoption afterwards so the rest
        # of the suite never consults this test's tmp-dir pool
        prev_warm = failover._local_solve._warm
        pool.adopt(failover._local_solve, solve_batch, config_argpos=3)

        class DeadRemote:
            address = "/nonexistent"
            supports_staging_delta = False

            def solve_result(self, *a, **k):
                raise SolverUnavailable("dead")

        try:
            fs = failover.FailoverSolver(
                DeadRemote(), failure_threshold=1,
                probe_fn=lambda: False, prewarm=False,
            )
            report = fs.prewarm(background=False)
            assert report["restored"] >= 1, report
            state, pods, params, cfg = solve_store["args"][:4]
            xla_compiles.clear()
            result = fs.solve_result(state, pods, params, cfg)
            assert fs.last_mode == "local-fallback"
            assert xla_compiles == [], (
                "the first degraded solve compiled — the prewarm did "
                "not cover the hot signature"
            )
            np.testing.assert_array_equal(
                np.asarray(result.assign), solve_store["want_assign"]
            )
            assert fs.status()["prewarm"]["restored"] >= 1
        finally:
            failover._local_solve._warm = prev_warm


class TestPromotionRestore:
    def _wired(self):
        from koordinator_tpu.apis.extension import ResourceName as R
        from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
        from koordinator_tpu.client.bus import APIServer, Kind
        from koordinator_tpu.client.wiring import wire_scheduler
        from koordinator_tpu.models.placement import PlacementModel
        from koordinator_tpu.scheduler import Scheduler

        bus = APIServer()
        sched = Scheduler(model=PlacementModel(use_pallas=False))
        wire_scheduler(bus, sched)
        for i in range(4):
            bus.apply(Kind.NODE, f"n{i}", NodeSpec(
                name=f"n{i}",
                allocatable={R.CPU: 64000, R.MEMORY: 131072}))
            bus.apply(Kind.NODE_METRIC, f"n{i}", NodeMetric(
                node_name=f"n{i}", node_usage={R.CPU: 100 * i},
                update_time=90.0))
        pod = PodSpec(name="p0", requests={R.CPU: 500, R.MEMORY: 256})
        bus.apply(Kind.POD, pod.uid, pod)
        return bus, sched

    def test_promotion_sweep_warm_restores(self, solve_store):
        """note_promotion → the promotion sweep's report carries the
        warm-restore section: pool executables loaded from disk AND
        the staged world eagerly prestaged — both BEFORE the first
        solve. Periodic sweeps never pay it."""
        from koordinator_tpu.scheduler.auditor import StateAuditor

        bus, sched = self._wired()
        pool = WarmPool().configure(
            solve_store["dir"], force_single_device=True
        )
        pool.adopt(sched.model._solve, solve_batch, config_argpos=3)
        auditor = StateAuditor(sched, bus, interval_rounds=1,
                               warm_pool=pool)
        auditor.note_promotion()
        report = auditor.on_round(now=100.0)
        assert report["kind"] == "promotion"
        warm = report["warm"]
        assert warm["pool"]["restored"] >= 1
        assert pool.status()["hits"] >= 1
        # the staged world was eagerly prestaged (full first staging)
        assert "prestage" in warm and "error" not in warm["prestage"]
        assert sched.model.staged_cache.state is not None
        # a periodic sweep does NOT re-run the warm restore
        report2 = auditor.on_round(now=101.0)
        assert report2 is not None and report2["kind"] == "periodic"
        assert "warm" not in report2

    def test_promotion_restore_never_raises(self, tmp_path):
        """A broken pool (store vanished mid-flight) costs latency,
        never the promotion round."""
        from koordinator_tpu.scheduler.auditor import StateAuditor

        bus, sched = self._wired()

        class ExplodingPool:
            def restore(self, **_k):
                raise RuntimeError("store on fire")

        auditor = StateAuditor(sched, bus, interval_rounds=0,
                               warm_pool=ExplodingPool())
        auditor.note_promotion()
        report = auditor.on_round(now=100.0)
        assert "error" in report["warm"]["pool"]


class TestObservabilitySurfaces:
    def test_placement_service_status_has_warm_pool_section(self, tmp_path):
        from koordinator_tpu.service.server import PlacementService

        service = PlacementService(str(tmp_path / "warm-status.sock"))
        service.start()  # stop() joins serve_forever — it must be running
        try:
            status = service.status()
            warm = status["warm_pool"]
            for key in ("active", "serving", "hits", "misses",
                        "quarantined", "executables"):
                assert key in warm
        finally:
            service.stop()

    def test_flight_dump_carries_cached_warm_section(self, tmp_path):
        import json

        from koordinator_tpu.obs.flight import FlightRecorder

        recorder = FlightRecorder(dump_dir=str(tmp_path),
                                  min_interval_s=0.0)
        path = recorder.trigger("manual", detail="warm-section-test")
        assert path is not None
        with open(path) as f:
            payload = json.load(f)
        warm = payload["warm"]
        for key in ("serving", "hits", "misses", "quarantined"):
            assert key in warm


class TestEntryProvenance:
    """The v2 record's embedded provenance (host fingerprint + jax
    version): path scoping can be bypassed by a copied/renamed store,
    the load-time checks cannot."""

    def _seed(self, tmp_path):
        cache = ExecutableCache(str(tmp_path))
        fn = jax.jit(lambda x: x * 2)
        compiled = fn.lower(jax.numpy.arange(4)).compile()
        assert cache.store("prov", compiled)
        return cache

    def _rewrite_record(self, cache, **overrides):
        """Re-frame the entry with provenance fields replaced — a
        VALID frame whose only defect is the embedded provenance."""
        import pickle

        path = cache._path("prov")
        with open(path, "rb") as f:
            body = unframe_payload(f.read())
        host, version, payload, trees = pickle.loads(body)
        record = {
            "host": host, "version": version,
            "payload": payload, "trees": trees, **overrides,
        }
        body = pickle.dumps((record["host"], record["version"],
                             record["payload"], record["trees"]))
        with open(path, "wb") as f:
            f.write(frame_payload(body))

    def test_stale_host_fingerprint_typed(self, tmp_path):
        from koordinator_tpu.utils.compilation_cache import (
            WarmEntryHostMismatch,
        )

        cache = self._seed(tmp_path)
        assert sabotage_store(str(tmp_path), "stale-host-fingerprint")
        with pytest.raises(WarmEntryHostMismatch) as e:
            cache.load_checked("prov")
        assert e.value.reason == "stale-host"
        assert cache.load("prov") is None  # silent form: a plain miss

    def test_version_skew_typed(self, tmp_path):
        from koordinator_tpu.utils.compilation_cache import (
            WarmEntryVersionSkew,
        )

        cache = self._seed(tmp_path)
        self._rewrite_record(cache, version="0.0.1-foreign")
        with pytest.raises(WarmEntryVersionSkew) as e:
            cache.load_checked("prov")
        assert e.value.reason == "version-skew"

    def test_torn_concurrent_write_typed(self, tmp_path):
        cache = self._seed(tmp_path)
        assert sabotage_store(str(tmp_path), "torn-concurrent-write")
        with pytest.raises(WarmEntryError) as e:
            cache.load_checked("prov")
        # an interleaved write surfaces through the integrity ladder
        assert e.value.reason in ("fingerprint", "truncated", "corrupt")


class TestPopulateCorruptRestart:
    def test_smoke_populate_corrupt_restart_one_reject_rest_hit(
            self, tmp_path):
        """The check.sh warm-pool smoke scenario (ISSUE 13): populate
        the store with N signatures, corrupt ONE entry, restart (fresh
        pool over the same store) — exactly 1 counted reject +
        quarantine, the other N-1 restore as hits, and the corrupted
        shape still answers bit-identical through the cold path."""
        pool = _pool(tmp_path)
        toy = _make_toy()
        binding = _toy_binding(toy)
        pool.adopt(binding, toy, config_argpos=2)
        shapes = (8, 12, 24)
        wants = {n: np.asarray(binding(*_toy_args(n=n))) for n in shapes}
        assert pool.persist()["persisted"] == len(shapes)
        assert sabotage_store(str(tmp_path / "store"),
                              "bitflipped-entry", seed=11)

        fresh_binding = _toy_binding(toy)
        fresh = _pool(tmp_path)
        fresh.adopt(fresh_binding, toy, config_argpos=2)
        report = fresh.restore()
        assert report["rows"] == len(shapes)
        assert report["restored"] == len(shapes) - 1
        assert report["failed"] == 1
        status = fresh.status()
        assert status["hits"] == len(shapes) - 1        # N-1 hits
        assert sum(status["rejects"].values()) == 1     # 1 typed reject
        assert status["quarantined"] == 1
        # every shape still answers, bit-identical — the corrupted one
        # through the cold jit path, the others warm-served
        for n in shapes:
            np.testing.assert_array_equal(
                np.asarray(fresh_binding(*_toy_args(n=n))), wants[n]
            )
        assert fresh.status()["served"] == len(shapes) - 1


class TestSupervisorProbeBudget:
    """The respawn probe-budget split (ISSUE 13 satellite): a
    warm-restored child is probed on the tight ``warm_ready_timeout_s``
    — a hung warm child dies in seconds — while a cold (or undecided)
    child keeps the generous cold-compile allowance."""

    def _supervisor(self, spawned, probe, clock, warm_flag, **kw):
        from koordinator_tpu.service.supervisor import SolverSupervisor

        class _Handle:
            def __init__(self):
                self.returncode = None
                self.killed = 0
                self.pid = 777
                self.warm_restored = warm_flag["value"]

            def poll(self):
                return self.returncode

            def kill(self):
                self.killed += 1
                self.returncode = -9

        def spawn():
            handle = _Handle()
            spawned.append(handle)
            return handle

        kw.setdefault("probe_interval_s", 0.01)
        kw.setdefault("backoff_base_s", 0.0)
        kw.setdefault("backoff_cap_s", 0.0)
        return SolverSupervisor(
            ("127.0.0.1", 1), spawn_fn=spawn, probe_fn=probe,
            sleep=lambda _s: None, clock=clock,
            probe_failure_threshold=3,
            ready_timeout_s=120.0, warm_ready_timeout_s=10.0, **kw,
        )

    def _respawn_cold_then(self, warm_value):
        """Boot healthy, crash, respawn with the child reporting
        ``warm_value`` as its restore outcome; probes keep failing.
        Returns (supervisor, now, spawned)."""
        now = [0.0]
        spawned = []
        alive = {"ok": True}
        warm_flag = {"value": warm_value}
        sup = self._supervisor(
            spawned, probe=lambda: alive["ok"], clock=lambda: now[0],
            warm_flag=warm_flag,
        )
        sup.start(wait_ready=True, monitor=False)
        alive["ok"] = False
        spawned[-1].returncode = 1
        assert sup.check_once() == "restarted"
        return sup, now, spawned

    def test_warm_respawn_probed_on_tight_grace(self):
        from koordinator_tpu.metrics.components import (
            SUPERVISOR_RESPAWN_WARM,
        )

        before = SUPERVISOR_RESPAWN_WARM.value()
        sup, now, spawned = self._respawn_cold_then(warm_value=True)
        try:
            assert sup.check_once() == "starting"  # inside warm grace
            status = sup.status()
            assert status["respawn_warm"] is True
            assert status["ready_grace_s"] == 10.0
            assert status["respawns_warm_total"] == 1
            assert SUPERVISOR_RESPAWN_WARM.value() == before + 1
            # past the WARM grace (nowhere near the 120s allowance):
            # failed probes now count — the hung warm child is killed
            # after the probe threshold, in seconds
            now[0] += 11.0
            assert sup.check_once() == "probe-failed"
            assert sup.check_once() == "probe-failed"
            assert sup.check_once() == "restarted"
            assert spawned[1].killed == 1
        finally:
            sup.stop()

    def test_cold_respawn_keeps_generous_grace(self):
        sup, now, spawned = self._respawn_cold_then(warm_value=False)
        try:
            now[0] += 11.0  # past warm grace — must NOT matter when cold
            for _ in range(5):
                assert sup.check_once() == "starting"
            assert sup.status()["ready_grace_s"] == 120.0
            assert sup.status()["respawns_warm_total"] == 0
            now[0] += 121.0  # past the cold allowance: now it is hung
            assert sup.check_once() == "probe-failed"
        finally:
            sup.stop()

    def test_undecided_outcome_stays_generous(self):
        """None (the child can't answer yet — boot restore in flight)
        must keep the cold allowance: infanticiding an undecided child
        on the tight clock would re-create the respawn loop the ready
        grace exists to prevent."""
        sup, now, spawned = self._respawn_cold_then(warm_value=None)
        try:
            now[0] += 30.0
            assert sup.check_once() == "starting"
            assert sup.status()["respawn_warm"] is None
            assert sup.status()["ready_grace_s"] == 120.0
            # the child resolves warm mid-wait: the grace TIGHTENS now
            spawned[-1].warm_restored = True
            assert sup.check_once() == "probe-failed"  # 30s > warm 10s
        finally:
            sup.stop()

    def test_debug_port_warm_outcome_reads_the_mux(self):
        from koordinator_tpu.scheduler.monitor import DebugServices
        from koordinator_tpu.service.supervisor import (
            debug_port_warm_outcome,
        )
        from koordinator_tpu.utils.debug_http import DebugHTTPServer

        payload = {"active": True, "executables": 0,
                   "last_restore": None}
        services = DebugServices()
        services.register("warm-pool", lambda: dict(payload))
        server = DebugHTTPServer(services=services, port=0).start()
        try:
            outcome = debug_port_warm_outcome(server.port)
            assert outcome() is None           # restore still in flight
            payload["executables"] = 3
            assert outcome() is True           # warm: tight grace
            payload.update(executables=0,
                           last_restore={"restored": 0, "failed": 1})
            assert outcome() is False          # cold restore: generous
            payload["active"] = False
            assert outcome() is False          # no pool: always cold
        finally:
            server.stop()
        assert outcome() is None               # mux gone: undecided


class TestDeviceObsManifest:
    def test_warm_manifest_snapshots_fn_aval_pairs(self):
        obs_entries_before = {
            fn for fn, _a, _k in DEVICE_OBS.warm_manifest()
        }
        binding = _toy_binding(_make_toy(), "toy_manifest_probe")
        binding(*_toy_args(n=32))
        entries = [
            (fn, aval_args) for fn, aval_args, _kw
            in DEVICE_OBS.warm_manifest()
            if fn == "toy_manifest_probe"
        ]
        assert "toy_manifest_probe" not in obs_entries_before
        assert len(entries) == 1
        fn, aval_args = entries[0]
        # arrays became ShapeDtypeStructs, the static rode by value
        assert aval_args[0].shape == (32,)
        assert aval_args[2] == 3


class TestPreemptVariantsAdoption:
    """ISSUE 16 warm-pool satellite: the joint place+evict solve
    variants — preempt_solve, preempt_solve_scan, defrag_repack — are
    ordinary (fn × aval-signature) pool citizens. A promoted replica's
    first eviction round must restore warm, not cold: the same
    adopt → persist → restore → serve contract the solve path pins."""

    def _storm_world(self):
        from koordinator_tpu.models.placement import PlacementModel
        from koordinator_tpu.scheduler.scheduler import Scheduler
        from koordinator_tpu.state.cluster import lower_nodes
        from koordinator_tpu.testing.chaos import preemption_storm

        nodes, residents, arrivals = preemption_storm(
            seed=7, n_nodes=6, residents_per_node=4, n_arrivals=3,
        )
        sched = Scheduler(model=PlacementModel(use_pallas=False))
        for node in nodes:
            sched.add_node(node)
        for pod in residents:
            sched.add_pod(pod)
        snapshot = sched.cache.snapshot(now=100.0)
        arrays = lower_nodes(snapshot, **sched.model.lowering_kwargs())
        resident = sched.model.lower_residents(snapshot, arrays)
        return sched.model, arrivals, arrays, resident

    def _adopt_all(self, pool, model):
        from koordinator_tpu.ops.preempt import (
            headroom_repack,
            preempt_scan,
            select_victims,
        )

        pool.adopt(model._preempt, select_victims, config_argpos=0)
        pool.adopt(model._preempt_scan, preempt_scan, config_argpos=0)
        pool.adopt(model._defrag, headroom_repack, config_argpos=0)

    def test_preempt_variants_restore_warm(self, tmp_path):
        from koordinator_tpu.apis.types import (
            ResourceName,
            resources_to_vector,
        )
        from koordinator_tpu.models.placement import PlacementModel

        model, arrivals, arrays, resident = self._storm_world()
        target = resources_to_vector({
            ResourceName.CPU: 8000, ResourceName.MEMORY: 16384,
        })
        pool = _pool(tmp_path, "preempt-store")
        self._adopt_all(pool, model)
        want_select = model.select_victims_device(
            arrays, resident, arrivals[0])
        want_scan = model.preempt_scan_device(
            arrays, resident, arrivals[:2])
        want_defrag = model.plan_defrag_device(
            arrays, resident, target, max_victim_priority=5000)
        report = pool.persist()
        assert report["persisted"] >= 3, (
            "preempt/scan/defrag signatures missing from the pooled "
            "manifest"
        )
        # the restart shape: a fresh model (fresh jit bindings) and a
        # fresh pool over the same store — every eviction-round entry
        # must come back warm and answer bit-identically
        model2 = PlacementModel(use_pallas=False)
        pool2 = _pool(tmp_path, "preempt-store")
        self._adopt_all(pool2, model2)
        assert pool2.restore()["restored"] >= 3
        got_select = model2.select_victims_device(
            arrays, resident, arrivals[0])
        got_scan = model2.preempt_scan_device(
            arrays, resident, arrivals[:2])
        got_defrag = model2.plan_defrag_device(
            arrays, resident, target, max_victim_priority=5000)
        assert pool2.status()["served"] >= 3, (
            "jit path answered an adopted eviction-round call"
        )
        assert got_select == want_select
        assert got_scan == want_scan
        assert got_defrag == want_defrag
