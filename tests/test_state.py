"""Array-substrate lowering tests: assigned-pod estimation correction,
schedule ordering, metric freshness."""

import numpy as np

from koordinator_tpu.apis.extension import ResourceName
from koordinator_tpu.apis.types import ClusterSnapshot, NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.state.cluster import (
    lower_nodes,
    lower_pending_pods,
    schedule_order,
)


def _node(name, cpu=32000, mem=65536):
    return NodeSpec(name=name, allocatable={ResourceName.CPU: cpu, ResourceName.MEMORY: mem})


def test_lower_nodes_basic():
    snap = ClusterSnapshot(
        nodes=[_node("n0"), _node("n1")],
        pods=[
            PodSpec(name="a", requests={ResourceName.CPU: 1000}, node_name="n0"),
            PodSpec(name="b", requests={ResourceName.CPU: 2000}, node_name="n0"),
            PodSpec(name="c", requests={ResourceName.CPU: 500}, node_name="n1"),
        ],
        node_metrics={
            "n0": NodeMetric(
                node_name="n0",
                node_usage={ResourceName.CPU: 10000},
                update_time=100.0,
            )
        },
        now=150.0,
    )
    arrays = lower_nodes(snap)
    assert arrays.n == 2
    assert arrays.used_req[0, ResourceName.CPU] == 3000
    assert arrays.used_req[1, ResourceName.CPU] == 500
    assert arrays.usage[0, ResourceName.CPU] == 10000
    assert arrays.metric_fresh[0] and not arrays.metric_fresh[1]
    assert arrays.schedulable.all()


def test_metric_expiration():
    snap = ClusterSnapshot(
        nodes=[_node("n0")],
        node_metrics={
            "n0": NodeMetric(node_name="n0", update_time=0.0),
        },
        now=200.0,  # > 180s default expiration
    )
    assert not lower_nodes(snap).metric_fresh[0]


def test_est_extra_unreported_pod_estimated():
    # Pod assigned after the metric update (no usage reported): its full
    # estimate enters est_extra, nothing subtracted (load_aware.go:337-376).
    snap = ClusterSnapshot(
        nodes=[_node("n0")],
        pods=[
            PodSpec(
                name="new",
                requests={ResourceName.CPU: 1000, ResourceName.MEMORY: 1024},
                node_name="n0",
                assign_time=150.0,
            )
        ],
        node_metrics={
            "n0": NodeMetric(
                node_name="n0",
                node_usage={ResourceName.CPU: 5000},
                update_time=100.0,
                report_interval=60.0,
            )
        },
        now=160.0,
    )
    arrays = lower_nodes(snap)
    assert arrays.est_extra[0, ResourceName.CPU] == 850    # round(1000*0.85)
    assert arrays.est_extra[0, ResourceName.MEMORY] == 717


def test_est_extra_reported_pod_outside_interval_not_estimated():
    # Pod assigned well before the metric update with reported usage: not
    # estimated at all -> est_extra == 0.
    snap = ClusterSnapshot(
        nodes=[_node("n0")],
        pods=[
            PodSpec(
                name="old",
                uid="default/old",
                requests={ResourceName.CPU: 1000},
                node_name="n0",
                assign_time=0.0,
            )
        ],
        node_metrics={
            "n0": NodeMetric(
                node_name="n0",
                node_usage={ResourceName.CPU: 5000},
                pod_usages={"default/old": {ResourceName.CPU: 700}},
                update_time=100.0,
                report_interval=60.0,
            )
        },
        now=160.0,
    )
    arrays = lower_nodes(snap)
    assert arrays.est_extra[0, ResourceName.CPU] == 0


def test_est_extra_max_of_estimate_and_reported_minus_covered():
    # Pod still within the report interval with reported usage: estimated
    # value is max(estimate, reported); its reported usage is subtracted
    # from node usage since node usage covers it.
    snap = ClusterSnapshot(
        nodes=[_node("n0")],
        pods=[
            PodSpec(
                name="warm",
                uid="default/warm",
                requests={ResourceName.CPU: 1000},
                node_name="n0",
                assign_time=90.0,  # update_time-assign < report_interval
            )
        ],
        node_metrics={
            "n0": NodeMetric(
                node_name="n0",
                node_usage={ResourceName.CPU: 5000},
                pod_usages={"default/warm": {ResourceName.CPU: 900}},
                update_time=100.0,
                report_interval=60.0,
            )
        },
        now=160.0,
    )
    arrays = lower_nodes(snap)
    # max(850, 900) - 900 = 0 ... estimate 850 < reported 900 -> use 900,
    # subtract the 900 reported (covered by node usage 5000) -> extra 0
    assert arrays.est_extra[0, ResourceName.CPU] == 0

    # bump the request so the estimate dominates: max(1700,900)-900 = 800
    snap.pods[0].requests[ResourceName.CPU] = 2000
    arrays = lower_nodes(snap)
    assert arrays.est_extra[0, ResourceName.CPU] == 800


def test_est_extra_subtract_guard_when_usage_does_not_cover():
    # Node usage below the estimated pods' reported sum: no subtraction
    # (reference guard quantity.Cmp(q) >= 0, load_aware.go:318-323).
    snap = ClusterSnapshot(
        nodes=[_node("n0")],
        pods=[
            PodSpec(
                name="warm",
                uid="default/warm",
                requests={ResourceName.CPU: 1000},
                node_name="n0",
                assign_time=90.0,
            )
        ],
        node_metrics={
            "n0": NodeMetric(
                node_name="n0",
                node_usage={ResourceName.CPU: 500},  # < reported 900
                pod_usages={"default/warm": {ResourceName.CPU: 900}},
                update_time=100.0,
                report_interval=60.0,
            )
        },
        now=160.0,
    )
    arrays = lower_nodes(snap)
    assert arrays.est_extra[0, ResourceName.CPU] == 900  # max(850,900), no sub


def test_prod_arrays_lowering():
    # Two assigned pods: one prod (reported, not estimated), one batch
    # (estimated). prod_usage (filter base) and prod_base (score base) must
    # only see the prod pod; est_extra sees both classes.
    snap = ClusterSnapshot(
        nodes=[_node("n0")],
        pods=[
            PodSpec(
                name="prod-old",
                uid="default/prod-old",
                requests={ResourceName.CPU: 1000},
                priority=9500,
                node_name="n0",
                assign_time=0.0,  # outside report interval -> not estimated
            ),
            PodSpec(
                name="be-new",
                requests={ResourceName.CPU: 2000},
                priority=5500,
                node_name="n0",
                assign_time=150.0,  # after metric update -> estimated
            ),
        ],
        node_metrics={
            "n0": NodeMetric(
                node_name="n0",
                node_usage={ResourceName.CPU: 5000},
                pod_usages={"default/prod-old": {ResourceName.CPU: 700}},
                update_time=100.0,
                report_interval=60.0,
            )
        },
        now=160.0,
    )
    arrays = lower_nodes(snap)
    # filter base: reported usage of the prod pod
    assert arrays.prod_usage[0, ResourceName.CPU] == 700
    # score base: non-estimated prod pod contributes reported usage only
    assert arrays.prod_base[0, ResourceName.CPU] == 700
    # non-prod correction: only the estimated BE pod (cpu est = 0, since a
    # batch-priority pod requesting plain CPU reads the BATCH_CPU column ->
    # zero quantity -> falls to the 250m default)
    assert arrays.est_extra[0, ResourceName.CPU] == 250


def test_schedule_order_priority_then_fifo():
    pods = [
        PodSpec(name="low", priority=3000),
        PodSpec(name="hi", priority=9500),
        PodSpec(name="hi2", priority=9500),
        PodSpec(name="mid", priority=7000),
    ]
    order = schedule_order(pods)
    assert [pods[i].name for i in order] == ["hi", "hi2", "mid", "low"]


def test_lower_pending_pods():
    pods = [
        PodSpec(name="b", priority=5500, requests={ResourceName.BATCH_CPU: 2000}),
        PodSpec(name="p", priority=9500, requests={ResourceName.CPU: 1000}, gang="g1"),
    ]
    arrays = lower_pending_pods(pods, gang_index={"g1": 0})
    # schedule order puts the prod pod first
    assert arrays.uids[0] == "default/p"
    assert arrays.is_prod[0] and not arrays.is_prod[1]
    assert arrays.gang_id[0] == 0 and arrays.gang_id[1] == -1
    assert arrays.req[1, ResourceName.BATCH_CPU] == 2000
    assert arrays.est[1, ResourceName.CPU] == 1700  # translated batch estimate
