"""Persistent compilation cache: a restarted solver warms from disk.

VERDICT r4 weak #5: every solver start paid the full compile warmup, so
leader failover meant a multi-second solver blackout. These tests run
the solver program in FRESH interpreters against a shared cache
directory: the second run must warm dramatically faster than the first
(deserialization, not compilation).
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = """
import time
from koordinator_tpu.utils.compilation_cache import enable_persistent_cache
assert enable_persistent_cache() is not None
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
from koordinator_tpu.testing import example_problem
state, pods, params = example_problem(400, 600)
solve = jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig()))
t0 = time.time()
out = solve(state, pods, params)
np.asarray(out[1])
print("WARMUP", time.time() - t0)
"""


def _clean_env(cache_dir):
    """Subprocess env: CPU, ONE device (the restart scenario is a
    single solver process — strip the suite's 8-device forcing)."""
    import re

    env = dict(os.environ)
    env["KTPU_COMPILATION_CACHE_DIR"] = str(cache_dir)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    ).strip()
    return env


def _run(cache_dir):
    env = _clean_env(cache_dir)
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE], capture_output=True, text=True,
        timeout=300, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    for line in proc.stdout.splitlines():
        if line.startswith("WARMUP"):
            return float(line.split()[1])
    raise AssertionError(f"no WARMUP line in: {proc.stdout!r}")


def test_second_process_warms_from_cache(tmp_path):
    cache = tmp_path / "xla-cache"
    cold = _run(cache)
    assert any(cache.iterdir()), "nothing persisted to the cache dir"
    warm = _run(cache)
    # deserialization must beat compilation decisively; the absolute
    # warm bound is the restart-blackout criterion (CPU compile of this
    # program is ~4-10 s cold)
    assert warm < cold / 2, (cold, warm)
    assert warm < 2.0, f"warm start took {warm:.2f}s"


_AOT_SEED = """
import os, time
import jax
jax.config.update("jax_platforms", "cpu")
from koordinator_tpu.utils.compilation_cache import ExecutableCache
from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
from koordinator_tpu.testing import example_problem
state, pods, params = example_problem(400, 600)
cfg = SolverConfig()
t0 = time.time()
ExecutableCache().get_or_compile(
    "test-aot", jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, cfg)),
    state, pods, params,
)
print("COLD", time.time() - t0)
"""

_AOT_LOAD = """
import os, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from koordinator_tpu.utils.compilation_cache import ExecutableCache
from koordinator_tpu.testing import example_problem
state, pods, params = example_problem(400, 600)
t0 = time.time()
fn = ExecutableCache().load("test-aot")
assert fn is not None, "cache miss"
out = fn(state, pods, params)
np.asarray(out[1])
print("WARM", time.time() - t0)
from koordinator_tpu.ops.binpack import SolverConfig, schedule_batch
want = jax.jit(lambda s, p, pr: schedule_batch(s, p, pr, SolverConfig()))(
    state, pods, params)
assert (np.asarray(out[1]) == np.asarray(want[1])).all(), "AOT diverged"
print("IDENTICAL")
"""


def _run_snippet(code, cache_dir, marker):
    env = _clean_env(cache_dir)
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    value = None
    for line in proc.stdout.splitlines():
        if line.startswith(marker):
            value = float(line.split()[1])
    return value, proc.stdout


def test_aot_executable_cache_restart(tmp_path):
    """The solver sidecar's restart path: a fresh interpreter loads the
    serialized COMPILED executable — no re-trace, no re-compile — and
    produces identical results."""
    cache = tmp_path / "xla-cache"
    cold, _ = _run_snippet(_AOT_SEED, cache, "COLD")
    warm, out = _run_snippet(_AOT_LOAD, cache, "WARM")
    assert "IDENTICAL" in out
    assert warm < cold / 3, (cold, warm)
    assert warm < 2.0, f"AOT warm start took {warm:.2f}s"


def test_cache_disabled_by_empty_env(tmp_path, monkeypatch):
    from koordinator_tpu.utils.compilation_cache import (
        enable_persistent_cache,
    )

    monkeypatch.setenv("KTPU_COMPILATION_CACHE_DIR", "")
    assert enable_persistent_cache() is None


class TestHostScopedCache:
    """The cache directory is keyed by a host CPU fingerprint so AOT
    results never replay across machines with different feature sets
    (SIGILL / 20-min-stall risk — the MULTICHIP_r05 rc=124 dryrun)."""

    def test_fingerprint_stable_and_shaped(self):
        from koordinator_tpu.utils.compilation_cache import host_fingerprint

        fp = host_fingerprint()
        assert fp == host_fingerprint()  # deterministic on one host
        machine, _, digest = fp.rpartition("-")
        assert machine and len(digest) == 12

    def test_executable_cache_dir_is_host_scoped(self, tmp_path):
        from koordinator_tpu.utils.compilation_cache import (
            ExecutableCache,
            host_fingerprint,
        )

        cache = ExecutableCache(str(tmp_path))
        assert f"host-{host_fingerprint()}" in cache.dir

    def test_enable_persistent_cache_scopes_dir(self, tmp_path, monkeypatch):
        import jax

        from koordinator_tpu.utils.compilation_cache import (
            enable_persistent_cache,
            host_fingerprint,
        )

        before = jax.config.jax_compilation_cache_dir
        try:
            out = enable_persistent_cache(str(tmp_path))
            assert out is not None
            assert f"host-{host_fingerprint()}" in out
            import os

            assert os.path.isdir(out)
        finally:
            jax.config.update("jax_compilation_cache_dir", before)

    def test_host_scope_opt_out(self, tmp_path, monkeypatch):
        from koordinator_tpu.utils import compilation_cache as cc

        monkeypatch.setenv("KTPU_CACHE_HOST_SCOPE", "0")
        cache = cc.ExecutableCache(str(tmp_path))
        assert "host-" not in cache.dir
