"""Debug/observability HTTP mux (SURVEY §5.1/§5.5 HTTP surface).

Oracle: cmd/koord-scheduler/app/server.go:293-303 (debug toggles +
services install), frameworkext/services/services.go (per-plugin REST),
/metrics + /healthz on every binary.
"""

import json
import urllib.request

import pytest

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import NodeMetric, NodeSpec, PodSpec
from koordinator_tpu.metrics.registry import Registry
from koordinator_tpu.scheduler import Scheduler
from koordinator_tpu.utils.debug_http import DebugHTTPServer


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read().decode()


def _put(port, path):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method="PUT")
    with urllib.request.urlopen(req) as r:
        return r.status, r.read().decode()


@pytest.fixture
def served_scheduler():
    s = Scheduler()
    s.add_node(NodeSpec(name="n0",
                        allocatable={R.CPU: 8000, R.MEMORY: 16384}))
    s.update_node_metric(NodeMetric(node_name="n0", update_time=99.0))
    registry = Registry("test")
    registry.counter("rounds_total", "rounds").inc()
    server = DebugHTTPServer(services=s.services, debug=s.debug,
                             metrics=registry).start()
    yield s, server
    server.stop()


def test_healthz_and_metrics(served_scheduler):
    _, server = served_scheduler
    assert _get(server.port, "/healthz") == (200, "ok")
    status, body = _get(server.port, "/metrics")
    assert status == 200 and "rounds_total" in body


def test_plugin_services(served_scheduler):
    s, server = served_scheduler
    status, body = _get(server.port, "/apis/v1/plugins")
    assert status == 200 and "Coscheduling" in json.loads(body)
    status, body = _get(server.port, "/apis/v1/plugins/Coscheduling")
    assert status == 200 and json.loads(body) == {}
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server.port, "/apis/v1/plugins/nope")
    assert e.value.code == 404


def test_debug_flag_toggles_collect_dumps(served_scheduler):
    """The reference's PUT /debug/flags/s runtime toggle: scores dumped
    only while enabled."""
    s, server = served_scheduler
    s.add_pod(PodSpec(name="p0", requests={R.CPU: 100}))
    s.schedule_pending(now=100.0)
    _, body = _get(server.port, "/debug/dumps")
    assert json.loads(body)["scores"] == []      # toggle off: no dumps

    assert _put(server.port, "/debug/flags/s")[0] == 200
    assert _put(server.port, "/debug/flags/f?value=1")[0] == 200
    s.add_pod(PodSpec(name="p1", requests={R.CPU: 100}))
    s.batched_placement = False                  # per-pod cycles record
    s.schedule_pending(now=101.0)
    _, body = _get(server.port, "/debug/dumps")
    assert json.loads(body)["scores"]            # dumped while on

    status, body = _put(server.port, "/debug/flags/s?value=0")
    assert json.loads(body) == {"enabled": False}
    assert s.debug.dump_scores is False


def test_audit_query_endpoint():
    """pkg/koordlet/audit's HTTP query: filters + limit round-trip."""
    from koordinator_tpu.koordlet.audit import Auditor

    auditor = Auditor(clock=lambda: 100.0)
    auditor.log("qosmanager/cpusuppress", "kubepods/besteffort",
                "suppress", "cpus=4")
    auditor.log("resourceexecutor", "kubepods/podx", "update", "cfs=200000")
    server = DebugHTTPServer(auditor=auditor).start()
    try:
        _, body = _get(server.port, "/audit")
        events = json.loads(body)
        assert len(events) == 2 and events[0]["operation"] == "update"
        _, body = _get(server.port,
                       "/audit?group=qosmanager/cpusuppress&limit=5")
        events = json.loads(body)
        assert len(events) == 1 and events[0]["detail"] == "cpus=4"
    finally:
        server.stop()


def test_handler_error_returns_500():
    class Boom:
        def names(self):
            raise RuntimeError("dictionary changed size during iteration")

    server = DebugHTTPServer(services=Boom()).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(server.port, "/apis/v1/plugins")
        assert e.value.code == 500
    finally:
        server.stop()
