"""Delta staging correctness: any tracked event sequence must yield a
staged NodeState bit-identical to a from-scratch lowering + staging of
the final snapshot, and solves through the delta path must match the
full-restage path and the host oracle.

This is the property the whole incremental layer rests on (parity is
asserted on the FINAL staged state, not per-delta — docs/PARITY.md):
``lower_nodes_delta`` shares its per-row helpers with ``lower_nodes``,
so equality here is by construction, and these tests guard the
construction (dirty-set bookkeeping, freshness drift, structure
fallbacks, the donated device scatter, bucket padding).
"""

import numpy as np
import pytest

from koordinator_tpu.apis.extension import PriorityClass, ResourceName
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    NodeSpec,
    PodSpec,
    ReservationSpec,
    ReservationState,
)
from koordinator_tpu.models.placement import PlacementModel, StagedStateCache
from koordinator_tpu.ops.binpack import STAGED_NODE_FIELDS
from koordinator_tpu.state.cluster import (
    ClusterDeltaTracker,
    lower_nodes,
    lower_nodes_delta,
)

CPU, MEM = ResourceName.CPU, ResourceName.MEMORY

ARRAY_FIELDS = STAGED_NODE_FIELDS  # the staged columns


def _node(i, rng):
    return NodeSpec(
        name=f"n{i}",
        allocatable={CPU: int(rng.integers(8000, 64000)),
                     MEM: int(rng.integers(8192, 131072))},
        unschedulable=bool(rng.random() < 0.05),
    )


def _metric(name, now, rng, pods=()):
    return NodeMetric(
        node_name=name,
        node_usage={CPU: int(rng.integers(0, 32000)),
                    MEM: int(rng.integers(0, 65536))},
        update_time=float(now - rng.integers(0, 300)),
        pod_usages={
            p.uid: {CPU: int(rng.integers(0, 2000)),
                    MEM: int(rng.integers(0, 2048))}
            for p in pods if rng.random() < 0.7
        },
    )


def _pod(j, rng, node_name=None):
    prod = rng.random() < 0.4
    return PodSpec(
        name=f"p{j}",
        node_name=node_name,
        requests={CPU: int(rng.integers(100, 4000)),
                  MEM: int(rng.integers(64, 4096))},
        limits={CPU: int(rng.integers(100, 5000))} if rng.random() < 0.3
        else {},
        priority_class=PriorityClass.PROD if prod else PriorityClass.NONE,
        assign_time=float(rng.integers(0, 400)) if node_name else 0.0,
    )


def _build(rng, n_nodes=24):
    nodes = [_node(i, rng) for i in range(n_nodes)]
    pods = []
    for j in range(3 * n_nodes):
        node = nodes[int(rng.integers(0, n_nodes))]
        pods.append(_pod(j, rng, node.name))
    metrics = {}
    for node in nodes:
        if rng.random() < 0.8:
            on_node = [p for p in pods if p.node_name == node.name]
            metrics[node.name] = _metric(node.name, 400.0, rng, on_node)
    resvs = []
    for k in range(6):
        node = nodes[int(rng.integers(0, n_nodes))]
        resvs.append(ReservationSpec(
            name=f"r{k}", node_name=node.name,
            requests={CPU: int(rng.integers(500, 4000)),
                      MEM: int(rng.integers(256, 4096))},
            state=ReservationState.AVAILABLE,
        ))
    tracker = ClusterDeltaTracker()
    return ClusterSnapshot(
        nodes=nodes, pods=pods, pending_pods=[], node_metrics=metrics,
        reservations=resvs, now=400.0, delta_tracker=tracker,
    ), tracker


def _mutate(snapshot, tracker, rng, counters):
    """Apply one random tracked event; returns nothing. Every mutation
    that can change a node row marks the tracker exactly as a correct
    producer (SchedulerCache) would."""
    kind = rng.choice([
        "node_spec", "node_add", "node_remove", "pod_assign",
        "pod_remove", "metric", "metric_drop", "resv_alloc",
        "resv_expire", "advance_now",
    ])
    nodes = snapshot.nodes
    if kind == "node_spec":
        i = int(rng.integers(0, len(nodes)))
        nodes[i] = _node_replacement(nodes[i], rng)
        tracker.mark_node(nodes[i].name)
    elif kind == "node_add":
        counters["next_node"] += 1
        nodes.append(_node(1000 + counters["next_node"], rng))
        tracker.mark_structure()
    elif kind == "node_remove" and len(nodes) > 4:
        i = int(rng.integers(0, len(nodes)))
        gone = nodes.pop(i)
        snapshot.pods = [p for p in snapshot.pods
                         if p.node_name != gone.name]
        snapshot.node_metrics.pop(gone.name, None)
        tracker.mark_structure()
    elif kind == "pod_assign":
        counters["next_pod"] += 1
        node = nodes[int(rng.integers(0, len(nodes)))]
        snapshot.pods.append(
            _pod(2000 + counters["next_pod"], rng, node.name)
        )
        tracker.mark_node(node.name)
    elif kind == "pod_remove" and snapshot.pods:
        i = int(rng.integers(0, len(snapshot.pods)))
        gone = snapshot.pods.pop(i)
        tracker.mark_node(gone.node_name)
    elif kind == "metric":
        node = nodes[int(rng.integers(0, len(nodes)))]
        on_node = [p for p in snapshot.pods if p.node_name == node.name]
        snapshot.node_metrics[node.name] = _metric(
            node.name, snapshot.now, rng, on_node
        )
        tracker.mark_node(node.name)
    elif kind == "metric_drop" and snapshot.node_metrics:
        name = list(snapshot.node_metrics)[
            int(rng.integers(0, len(snapshot.node_metrics)))
        ]
        del snapshot.node_metrics[name]
        tracker.mark_node(name)
    elif kind == "resv_alloc" and snapshot.reservations:
        resv = snapshot.reservations[
            int(rng.integers(0, len(snapshot.reservations)))
        ]
        resv.allocated = {CPU: int(rng.integers(0, 2000))}
        tracker.mark_node(resv.node_name)
    elif kind == "resv_expire" and snapshot.reservations:
        resv = snapshot.reservations[
            int(rng.integers(0, len(snapshot.reservations)))
        ]
        resv.state = ReservationState.EXPIRED
        tracker.mark_node(resv.node_name)
    elif kind == "advance_now":
        # freshness drift: NO mark — the delta path must catch expired
        # (and re-freshened) metrics from the cached update times alone
        snapshot.now += float(rng.integers(1, 120))


def _node_replacement(node, rng):
    return NodeSpec(
        name=node.name,
        allocatable={CPU: int(rng.integers(8000, 64000)),
                     MEM: int(rng.integers(8192, 131072))},
        unschedulable=bool(rng.random() < 0.2),
    )


def _assert_arrays_equal(got, want, context):
    assert got.names == want.names, context
    for f in ARRAY_FIELDS:
        np.testing.assert_array_equal(
            getattr(got, f), getattr(want, f), err_msg=f"{context}: {f}"
        )


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_delta_lowering_matches_full_property(seed):
    """Any tracked event sequence: patching the previous NodeArrays with
    lower_nodes_delta == a from-scratch lower_nodes, bit for bit."""
    rng = np.random.default_rng(seed)
    snapshot, tracker = _build(rng)
    counters = {"next_node": 0, "next_pod": 0}
    arrays = lower_nodes(snapshot)
    seen_epoch = tracker.epoch
    for round_i in range(30):
        for _ in range(int(rng.integers(1, 6))):
            _mutate(snapshot, tracker, rng, counters)
        dirty = tracker.dirty_since(seen_epoch)
        structure_changed = tracker.structure_epoch > seen_epoch
        idx = lower_nodes_delta(snapshot, arrays, dirty)
        if structure_changed:
            # the node set/order moved: the delta path must refuse
            assert idx is None, f"round {round_i}"
        if idx is None:
            arrays = lower_nodes(snapshot)
        seen_epoch = tracker.epoch
        _assert_arrays_equal(
            arrays, lower_nodes(snapshot), f"seed {seed} round {round_i}"
        )


def test_delta_refuses_stale_node_order():
    rng = np.random.default_rng(9)
    snapshot, tracker = _build(rng, n_nodes=6)
    arrays = lower_nodes(snapshot)
    snapshot.nodes.reverse()  # same set, different order
    assert lower_nodes_delta(snapshot, arrays, []) is None


def test_freshness_drift_without_marks():
    """now advancing past the expiration window must flip metric_fresh
    on UNMARKED rows (the tracker never sees time passing)."""
    rng = np.random.default_rng(4)
    snapshot, tracker = _build(rng, n_nodes=10)
    arrays = lower_nodes(snapshot)
    snapshot.now += 10_000.0  # everything expires
    idx = lower_nodes_delta(snapshot, arrays, [])
    assert idx is not None and idx.size > 0
    _assert_arrays_equal(arrays, lower_nodes(snapshot), "expired")
    assert not arrays.metric_fresh.any()
    snapshot.now -= 10_000.0  # ...and back inside the window
    idx = lower_nodes_delta(snapshot, arrays, [])
    assert idx is not None and idx.size > 0
    _assert_arrays_equal(arrays, lower_nodes(snapshot), "refreshed")


@pytest.mark.parametrize("seed", [11, 12])
def test_staged_cache_device_state_property(seed):
    """The STAGED device state after any tracked event sequence equals
    a from-scratch stage_nodes(lower_nodes(snapshot)) — the donated
    scatter (bucket padding included) is exact."""
    rng = np.random.default_rng(seed)
    snapshot, tracker = _build(rng)
    counters = {"next_node": 0, "next_pod": 0}
    model = PlacementModel(use_pallas=False)
    cache = StagedStateCache(model)
    paths = set()
    for round_i in range(12):
        for _ in range(int(rng.integers(1, 5))):
            _mutate(snapshot, tracker, rng, counters)
        arrays, state, _times, _staging = cache.ensure(snapshot)
        paths.add(cache.last_path)
        want = model.stage_nodes(lower_nodes(snapshot))
        for f in ARRAY_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(state, f)),
                np.asarray(getattr(want, f)),
                err_msg=f"seed {seed} round {round_i}: {f}",
            )
    assert "delta" in paths  # the incremental path actually ran


def test_schedule_delta_matches_full_and_oracle():
    """Solves THROUGH the delta path == the full-restage path == the
    sequential host oracle, over several churn rounds."""
    from koordinator_tpu.oracle.vectorized import (
        oracle_args,
        schedule_vectorized,
    )
    from koordinator_tpu.state.cluster import lower_pending_pods

    rng = np.random.default_rng(21)
    snapshot, tracker = _build(rng, n_nodes=16)
    counters = {"next_node": 0, "next_pod": 0}
    delta_model = PlacementModel(use_pallas=False)
    for round_i in range(6):
        for _ in range(3):
            _mutate(snapshot, tracker, rng, counters)
        snapshot.pending_pods = [
            _pod(5000 + 100 * round_i + j, rng) for j in range(12)
        ]
        got = delta_model.schedule(snapshot)

        fresh_snapshot = ClusterSnapshot(
            nodes=snapshot.nodes, pods=snapshot.pods,
            pending_pods=snapshot.pending_pods,
            node_metrics=snapshot.node_metrics,
            reservations=snapshot.reservations, now=snapshot.now,
        )
        full_model = PlacementModel(use_pallas=False)
        want = full_model.schedule(fresh_snapshot)
        assert dict(got) == dict(want), f"round {round_i}"
        assert got.waiting == want.waiting

        if not snapshot.reservations or all(
            getattr(r.state, "value", r.state) != "Available"
            for r in snapshot.reservations
        ):
            # plain shape: also pin against the sequential oracle
            arrays = lower_nodes(fresh_snapshot)
            pod_arrays = lower_pending_pods(fresh_snapshot.pending_pods)
            state = full_model.stage_nodes(arrays)
            batch = full_model.stage_pods(pod_arrays)
            assign = schedule_vectorized(
                *oracle_args(state, batch, full_model.params)
            )
            oracle_map = {
                uid: (arrays.names[a] if a >= 0 else None)
                for uid, a in zip(pod_arrays.uids, assign)
            }
            assert dict(got) == oracle_map, f"oracle round {round_i}"

        # bind this round's placements (tracked), as a scheduler would
        by_uid = {p.uid: p for p in snapshot.pending_pods}
        for uid, node in got.items():
            if node is not None:
                pod = by_uid[uid]
                pod.node_name = node
                pod.assign_time = snapshot.now
                snapshot.pods.append(pod)
                tracker.mark_node(node)
        snapshot.pending_pods = []
        snapshot.now += 30.0
    assert delta_model.staged_cache.last_path is not None


def test_tracker_semantics():
    t = ClusterDeltaTracker()
    e0 = t.epoch
    t.mark_node("a")
    t.mark_nodes(["b", "c"])
    assert set(t.dirty_since(e0)) == {"a", "b", "c"}
    mid = t.epoch
    t.mark_node("d")
    assert set(t.dirty_since(mid)) == {"d"}
    t.mark_structure()
    assert t.structure_epoch == t.epoch
    assert t.dirty_since(mid) == []  # structure reset the marks
    t.mark_node(None)  # no-op, never raises


def test_staged_cache_device_half_skip_and_reestablish():
    """want_device=False keeps only the host half fresh (NUMA callers
    restage anyway); the device half comes back bit-identical from the
    current host arrays when next wanted."""
    rng = np.random.default_rng(33)
    snapshot, tracker = _build(rng, n_nodes=8)
    model = PlacementModel(use_pallas=False)
    cache = StagedStateCache(model)
    arrays, state, _, _ = cache.ensure(snapshot, want_device=False)
    assert state is None and cache.last_path == "full"
    tracker.mark_node(snapshot.nodes[0].name)
    snapshot.nodes[0] = _node_replacement(snapshot.nodes[0], rng)
    arrays, state, _, _ = cache.ensure(snapshot, want_device=False)
    assert state is None and cache.last_path == "delta"
    # now the device half is wanted again: rebuilt from host arrays
    arrays, state, _, _ = cache.ensure(snapshot)
    assert state is not None
    want = model.stage_nodes(lower_nodes(snapshot))
    for f in ARRAY_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(state, f)), np.asarray(getattr(want, f)),
            err_msg=f,
        )


def test_snapshot_epoch_sync_point():
    """ensure() syncs to the snapshot-time epoch, so a mark landing
    AFTER the snapshot was taken (racing informer) is re-lowered next
    tick instead of silently lost."""
    rng = np.random.default_rng(55)
    snapshot, tracker = _build(rng, n_nodes=8)
    model = PlacementModel(use_pallas=False)
    cache = StagedStateCache(model)
    snapshot.delta_epoch = tracker.epoch
    cache.ensure(snapshot)
    # a mutation + mark races in after the snapshot's epoch capture
    snapshot.nodes[2] = _node_replacement(snapshot.nodes[2], rng)
    tracker.mark_node(snapshot.nodes[2].name)
    # the next tick's snapshot carries the new epoch: the row re-lowers
    snapshot.delta_epoch = tracker.epoch
    arrays, state, _, _ = cache.ensure(snapshot)
    assert cache.last_path == "delta"
    _assert_arrays_equal(arrays, lower_nodes(snapshot), "post-race")
