"""LoadAware aggregated (percentile) usage mode, end-to-end.

Reference semantics: pkg/scheduler/plugins/loadaware/load_aware.go:157-186
(filter substitutes percentile usage + the aggregated threshold set),
:310-311 (score substitutes the percentile base), helper.go:58-90
(getTargetAggregatedUsage window/percentile selection). The VERDICT r3
closure test: avg mode admits a node that p95 mode rejects.
"""

import numpy as np

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    NodeSpec,
    PodSpec,
)
from koordinator_tpu.models import PlacementModel
from koordinator_tpu.state.cluster import (
    AggregatedArgs,
    lower_nodes,
    target_aggregated_usage,
)


def _snap(avg_cpu=5000, p95_cpu=7000, agg_duration=300.0, n=1):
    nodes = [
        NodeSpec(name=f"n{i}", allocatable={R.CPU: 10000, R.MEMORY: 32768})
        for i in range(n)
    ]
    metrics = {
        f"n{i}": NodeMetric(
            node_name=f"n{i}",
            node_usage={R.CPU: avg_cpu},
            aggregated_usage={95: {R.CPU: p95_cpu}, 50: {R.CPU: avg_cpu // 2}},
            aggregated_duration=agg_duration,
            update_time=99.0,
        )
        for i in range(n)
    }
    pod = PodSpec(name="p", requests={R.CPU: 1000, R.MEMORY: 1024})
    return ClusterSnapshot(
        nodes=nodes, node_metrics=metrics, pending_pods=[pod], now=100.0
    )


AGG_FILTER = AggregatedArgs(usage_thresholds={R.CPU: 65}, usage_pct=95)


def test_avg_admits_p95_rejects():
    """The differential: 50% avg < 65% threshold admits; 70% p95 >= 65%
    aggregated threshold rejects — same snapshot, same pod."""
    snap = _snap(avg_cpu=5000, p95_cpu=7000)
    assert PlacementModel().schedule(snap)["default/p"] == "n0"
    out = PlacementModel(aggregated=AGG_FILTER).schedule(_snap())
    assert out["default/p"] is None


def test_p95_under_threshold_admits():
    out = PlacementModel(aggregated=AGG_FILTER).schedule(
        _snap(avg_cpu=5000, p95_cpu=6000)  # 60% < 65%
    )
    assert out["default/p"] == "n0"


def test_avg_rejects_while_p95_admits():
    """The aggregated threshold set REPLACES the avg set: a hot-avg node
    with a calm p95 is admitted in aggregated mode (and rejected in avg
    mode) — the substitution works both directions."""
    snap = _snap(avg_cpu=7000, p95_cpu=5000)  # avg 70% >= 65, p95 50%
    assert PlacementModel().schedule(snap)["default/p"] is None
    out = PlacementModel(aggregated=AGG_FILTER).schedule(
        _snap(avg_cpu=7000, p95_cpu=5000)
    )
    assert out["default/p"] == "n0"


def test_missing_percentile_skips_check():
    """No reported percentile -> the aggregated check is skipped and the
    node passes (helper.go returns nil -> filter continue)."""
    snap = _snap(avg_cpu=9900, p95_cpu=9900)
    for m in snap.node_metrics.values():
        m.aggregated_usage = {}
        m.aggregated_duration = None
    out = PlacementModel(aggregated=AGG_FILTER).schedule(snap)
    assert out["default/p"] == "n0"


def test_duration_mismatch_skips_check():
    """A requested window that no reported aggregation matches -> nil ->
    check skipped (helper.go:79-89 exact duration match)."""
    args = AggregatedArgs(
        usage_thresholds={R.CPU: 65}, usage_pct=95,
        usage_duration_seconds=600.0,  # metric reports 300s
    )
    out = PlacementModel(aggregated=args).schedule(_snap(p95_cpu=9000))
    assert out["default/p"] == "n0"
    # matching window enforces the threshold again
    args_match = AggregatedArgs(
        usage_thresholds={R.CPU: 65}, usage_pct=95,
        usage_duration_seconds=300.0,
    )
    out = PlacementModel(aggregated=args_match).schedule(_snap(p95_cpu=9000))
    assert out["default/p"] is None


def test_score_aggregated_prefers_calm_p95_node():
    """Two nodes, identical avg usage; n1 has the lower p95. Aggregated
    score mode places on n1; avg mode tie-breaks to n0."""
    def snap2():
        nodes = [
            NodeSpec(name=f"n{i}", allocatable={R.CPU: 10000, R.MEMORY: 32768})
            for i in range(2)
        ]
        metrics = {
            "n0": NodeMetric(
                node_name="n0", node_usage={R.CPU: 4000},
                aggregated_usage={95: {R.CPU: 8000}},
                aggregated_duration=300.0, update_time=99.0,
            ),
            "n1": NodeMetric(
                node_name="n1", node_usage={R.CPU: 4000},
                aggregated_usage={95: {R.CPU: 5000}},
                aggregated_duration=300.0, update_time=99.0,
            ),
        }
        pod = PodSpec(name="p", requests={R.CPU: 1000, R.MEMORY: 1024})
        return ClusterSnapshot(
            nodes=nodes, node_metrics=metrics, pending_pods=[pod], now=100.0
        )

    assert PlacementModel().schedule(snap2())["default/p"] == "n0"
    out = PlacementModel(
        aggregated=AggregatedArgs(score_pct=95)
    ).schedule(snap2())
    assert out["default/p"] == "n1"


def test_score_aggregated_nil_estimates_all_assigned():
    """Score-aggregated mode with no reported percentiles: the node usage
    base is dropped and every assigned pod becomes estimated
    (load_aware.go:357-358 OR clause) — visible as est_extra == the pod
    estimate with no node-usage term."""
    node = NodeSpec(name="n0", allocatable={R.CPU: 10000, R.MEMORY: 32768})
    assigned = PodSpec(
        name="a", node_name="n0", requests={R.CPU: 2000, R.MEMORY: 1024},
        assign_time=0.0,
    )
    metric = NodeMetric(
        node_name="n0", node_usage={R.CPU: 6000},
        pod_usages={"default/a": {R.CPU: 1000}},
        update_time=99.0, report_interval=10.0,
    )
    snap = ClusterSnapshot(
        nodes=[node], pods=[assigned], node_metrics={"n0": metric}, now=100.0
    )
    arrays = lower_nodes(snap, aggregated=AggregatedArgs(score_pct=95))
    # filter side untouched (filter mode off): usage stays the avg
    assert arrays.usage[0, R.CPU] == 6000
    # score base = usage + est_extra must equal the bare pod estimate:
    # max(est(2000*85%), reported 1000) = 1700, node usage dropped
    assert arrays.usage[0, R.CPU] + arrays.est_extra[0, R.CPU] == 1700


def test_target_aggregated_usage_selection():
    m = NodeMetric(
        node_name="n", aggregated_usage={95: {R.CPU: 5}},
        aggregated_duration=300.0,
    )
    assert target_aggregated_usage(m, None, 95) == {R.CPU: 5}
    assert target_aggregated_usage(m, 300.0, 95) == {R.CPU: 5}
    assert target_aggregated_usage(m, 600.0, 95) is None
    assert target_aggregated_usage(m, None, 90) is None
    assert target_aggregated_usage(NodeMetric(node_name="n"), None, 95) is None


def test_target_aggregated_usage_multi_window():
    """Multiple reported windows: exact duration match; no duration ->
    the LARGEST window (helper.go:65-78 default policy)."""
    m = NodeMetric(
        node_name="n", aggregated_usage={95: {R.CPU: 5}},
        aggregated_duration=300.0,
        aggregated_windows={
            900.0: {95: {R.CPU: 7}},
            1800.0: {95: {R.CPU: 9}, 50: {R.CPU: 2}},
        },
    )
    assert target_aggregated_usage(m, 300.0, 95) == {R.CPU: 5}
    assert target_aggregated_usage(m, 900.0, 95) == {R.CPU: 7}
    assert target_aggregated_usage(m, None, 95) == {R.CPU: 9}  # max window
    assert target_aggregated_usage(m, None, 50) == {R.CPU: 2}
    assert target_aggregated_usage(m, 1200.0, 95) is None


def test_reporter_fills_extra_windows():
    from koordinator_tpu.koordlet.metriccache import MetricCache, MetricKind
    from koordinator_tpu.koordlet.statesinformer import (
        NodeMetricReporter,
        StatesInformer,
    )
    from koordinator_tpu.manager.nodemetric import NodeMetricCollectPolicy

    mc = MetricCache()
    informer = StatesInformer()
    informer.set_node(
        NodeSpec("n0", allocatable={R.CPU: 8000, R.MEMORY: 16384})
    )
    informer.set_pods([])
    informer.set_collect_policy(NodeMetricCollectPolicy(300, 60))
    # a spike 10 min ago is visible in the 900/1800s windows' p99 but
    # not in the 300s window
    for t in range(0, 1200, 60):
        val = 7000.0 if t < 300 else 2000.0
        mc.append(MetricKind.NODE_CPU_USAGE, None, float(t), val)
    for t in range(0, 1200, 60):
        mc.append(MetricKind.SYS_CPU_USAGE, None, float(t), 300.0)
    m = NodeMetricReporter(mc, informer).report(now=1200.0)
    assert m.aggregated_duration == 300.0
    assert set(m.aggregated_windows) == {900.0, 1800.0}
    assert m.aggregated_windows[1800.0][99][R.CPU] > \
        m.aggregated_usage[99][R.CPU]
    # system-usage percentiles reported per window (AggregatedSystemUsages)
    assert m.aggregated_system_usage[300.0][95][R.CPU] == 300
    assert set(m.aggregated_system_usage) == {300.0, 900.0, 1800.0}


def test_incremental_path_applies_aggregated_mode():
    """BatchedPlacement=false must apply the same aggregated profile:
    the plugin-chain cycle lowers with the model's AggregatedArgs
    (cycle_seed -> node_view), so p95 rejects there too."""
    from koordinator_tpu.models import PlacementModel
    from koordinator_tpu.scheduler import Scheduler

    for batched, expected in ((True, None), (False, None)):
        s = Scheduler(model=PlacementModel(aggregated=AGG_FILTER))
        s.batched_placement = batched
        snap = _snap()  # avg 50% admits, p95 70% rejects at 65
        s.add_node(snap.nodes[0])
        s.update_node_metric(snap.node_metrics["n0"])
        s.update_pod(snap.pending_pods[0])
        out = s.schedule_pending(now=100.0)
        assert out["default/p"] is expected, f"batched={batched}"
    # control: without the profile both paths admit
    for batched in (True, False):
        s = Scheduler()
        s.batched_placement = batched
        snap = _snap()
        s.add_node(snap.nodes[0])
        s.update_node_metric(snap.node_metrics["n0"])
        s.update_pod(snap.pending_pods[0])
        out = s.schedule_pending(now=100.0)
        assert out["default/p"] == "n0", f"batched={batched}"


def test_incremental_lowering_uses_model_scaling_factors():
    """The plugin-chain cycle must lower assigned-pod estimation with
    the MODEL's scaling factors, not the defaults — otherwise the two
    paths score the same queue differently."""
    from koordinator_tpu.models import PlacementModel
    from koordinator_tpu.scheduler import Scheduler
    from koordinator_tpu.scheduler.framework import CycleState
    from koordinator_tpu.scheduler.plugins.lowering import node_view

    assigned = PodSpec(
        name="a", node_name="n0", requests={R.CPU: 2000}, assign_time=99.5,
    )
    node = NodeSpec(name="n0", allocatable={R.CPU: 10000, R.MEMORY: 32768})
    metric = NodeMetric(node_name="n0", node_usage={R.CPU: 100},
                        update_time=99.0, report_interval=10.0)
    snap = ClusterSnapshot(
        nodes=[node], pods=[assigned], node_metrics={"n0": metric},
        now=100.0,
    )
    s = Scheduler(model=PlacementModel(
        scaling_factors={R.CPU: 50, R.MEMORY: 70}
    ))
    state = CycleState(s.framework.cycle_seed)
    view = node_view(state, snap)
    # assigned pod estimated at 50% of its 2000m request (assign after
    # metric update -> should-estimate; no reported usage to subtract)
    assert view.arrays.est_extra[0, R.CPU] == 1000
    # a default-config scheduler estimates the same pod at 85%
    view2 = node_view(CycleState(Scheduler().framework.cycle_seed), snap)
    assert view2.arrays.est_extra[0, R.CPU] == 1700


def test_reporter_stamps_aggregated_duration():
    """The koordlet reporter records the aggregation window so the
    scheduler's duration selection has something to match against."""
    from koordinator_tpu.koordlet.metriccache import MetricCache, MetricKind
    from koordinator_tpu.koordlet.statesinformer import (
        NodeMetricReporter,
        StatesInformer,
    )
    from koordinator_tpu.manager.nodemetric import NodeMetricCollectPolicy

    mc = MetricCache()
    informer = StatesInformer()
    informer.set_node(
        NodeSpec("n0", allocatable={R.CPU: 8000, R.MEMORY: 16384})
    )
    informer.set_pods([])
    informer.set_collect_policy(NodeMetricCollectPolicy(300, 60))
    for t in range(10):
        mc.append(MetricKind.NODE_CPU_USAGE, None, float(t), 3000.0)
    m = NodeMetricReporter(mc, informer).report(now=10.0)
    assert m.aggregated_usage[95][R.CPU] == 3000
    assert m.aggregated_duration == 300.0
