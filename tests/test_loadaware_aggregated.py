"""LoadAware aggregated (percentile) usage mode, end-to-end.

Reference semantics: pkg/scheduler/plugins/loadaware/load_aware.go:157-186
(filter substitutes percentile usage + the aggregated threshold set),
:310-311 (score substitutes the percentile base), helper.go:58-90
(getTargetAggregatedUsage window/percentile selection). The VERDICT r3
closure test: avg mode admits a node that p95 mode rejects.
"""

import numpy as np

from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    NodeSpec,
    PodSpec,
)
from koordinator_tpu.models import PlacementModel
from koordinator_tpu.state.cluster import (
    AggregatedArgs,
    lower_nodes,
    target_aggregated_usage,
)


def _snap(avg_cpu=5000, p95_cpu=7000, agg_duration=300.0, n=1):
    nodes = [
        NodeSpec(name=f"n{i}", allocatable={R.CPU: 10000, R.MEMORY: 32768})
        for i in range(n)
    ]
    metrics = {
        f"n{i}": NodeMetric(
            node_name=f"n{i}",
            node_usage={R.CPU: avg_cpu},
            aggregated_usage={95: {R.CPU: p95_cpu}, 50: {R.CPU: avg_cpu // 2}},
            aggregated_duration=agg_duration,
            update_time=99.0,
        )
        for i in range(n)
    }
    pod = PodSpec(name="p", requests={R.CPU: 1000, R.MEMORY: 1024})
    return ClusterSnapshot(
        nodes=nodes, node_metrics=metrics, pending_pods=[pod], now=100.0
    )


AGG_FILTER = AggregatedArgs(usage_thresholds={R.CPU: 65}, usage_pct=95)


def test_avg_admits_p95_rejects():
    """The differential: 50% avg < 65% threshold admits; 70% p95 >= 65%
    aggregated threshold rejects — same snapshot, same pod."""
    snap = _snap(avg_cpu=5000, p95_cpu=7000)
    assert PlacementModel().schedule(snap)["default/p"] == "n0"
    out = PlacementModel(aggregated=AGG_FILTER).schedule(_snap())
    assert out["default/p"] is None


def test_p95_under_threshold_admits():
    out = PlacementModel(aggregated=AGG_FILTER).schedule(
        _snap(avg_cpu=5000, p95_cpu=6000)  # 60% < 65%
    )
    assert out["default/p"] == "n0"


def test_avg_rejects_while_p95_admits():
    """The aggregated threshold set REPLACES the avg set: a hot-avg node
    with a calm p95 is admitted in aggregated mode (and rejected in avg
    mode) — the substitution works both directions."""
    snap = _snap(avg_cpu=7000, p95_cpu=5000)  # avg 70% >= 65, p95 50%
    assert PlacementModel().schedule(snap)["default/p"] is None
    out = PlacementModel(aggregated=AGG_FILTER).schedule(
        _snap(avg_cpu=7000, p95_cpu=5000)
    )
    assert out["default/p"] == "n0"


def test_missing_percentile_skips_check():
    """No reported percentile -> the aggregated check is skipped and the
    node passes (helper.go returns nil -> filter continue)."""
    snap = _snap(avg_cpu=9900, p95_cpu=9900)
    for m in snap.node_metrics.values():
        m.aggregated_usage = {}
        m.aggregated_duration = None
    out = PlacementModel(aggregated=AGG_FILTER).schedule(snap)
    assert out["default/p"] == "n0"


def test_duration_mismatch_skips_check():
    """A requested window that no reported aggregation matches -> nil ->
    check skipped (helper.go:79-89 exact duration match)."""
    args = AggregatedArgs(
        usage_thresholds={R.CPU: 65}, usage_pct=95,
        usage_duration_seconds=600.0,  # metric reports 300s
    )
    out = PlacementModel(aggregated=args).schedule(_snap(p95_cpu=9000))
    assert out["default/p"] == "n0"
    # matching window enforces the threshold again
    args_match = AggregatedArgs(
        usage_thresholds={R.CPU: 65}, usage_pct=95,
        usage_duration_seconds=300.0,
    )
    out = PlacementModel(aggregated=args_match).schedule(_snap(p95_cpu=9000))
    assert out["default/p"] is None


def test_score_aggregated_prefers_calm_p95_node():
    """Two nodes, identical avg usage; n1 has the lower p95. Aggregated
    score mode places on n1; avg mode tie-breaks to n0."""
    def snap2():
        nodes = [
            NodeSpec(name=f"n{i}", allocatable={R.CPU: 10000, R.MEMORY: 32768})
            for i in range(2)
        ]
        metrics = {
            "n0": NodeMetric(
                node_name="n0", node_usage={R.CPU: 4000},
                aggregated_usage={95: {R.CPU: 8000}},
                aggregated_duration=300.0, update_time=99.0,
            ),
            "n1": NodeMetric(
                node_name="n1", node_usage={R.CPU: 4000},
                aggregated_usage={95: {R.CPU: 5000}},
                aggregated_duration=300.0, update_time=99.0,
            ),
        }
        pod = PodSpec(name="p", requests={R.CPU: 1000, R.MEMORY: 1024})
        return ClusterSnapshot(
            nodes=nodes, node_metrics=metrics, pending_pods=[pod], now=100.0
        )

    assert PlacementModel().schedule(snap2())["default/p"] == "n0"
    out = PlacementModel(
        aggregated=AggregatedArgs(score_pct=95)
    ).schedule(snap2())
    assert out["default/p"] == "n1"


def test_score_aggregated_nil_estimates_all_assigned():
    """Score-aggregated mode with no reported percentiles: the node usage
    base is dropped and every assigned pod becomes estimated
    (load_aware.go:357-358 OR clause) — visible as est_extra == the pod
    estimate with no node-usage term."""
    node = NodeSpec(name="n0", allocatable={R.CPU: 10000, R.MEMORY: 32768})
    assigned = PodSpec(
        name="a", node_name="n0", requests={R.CPU: 2000, R.MEMORY: 1024},
        assign_time=0.0,
    )
    metric = NodeMetric(
        node_name="n0", node_usage={R.CPU: 6000},
        pod_usages={"default/a": {R.CPU: 1000}},
        update_time=99.0, report_interval=10.0,
    )
    snap = ClusterSnapshot(
        nodes=[node], pods=[assigned], node_metrics={"n0": metric}, now=100.0
    )
    arrays = lower_nodes(snap, aggregated=AggregatedArgs(score_pct=95))
    # filter side untouched (filter mode off): usage stays the avg
    assert arrays.usage[0, R.CPU] == 6000
    # score base = usage + est_extra must equal the bare pod estimate:
    # max(est(2000*85%), reported 1000) = 1700, node usage dropped
    assert arrays.usage[0, R.CPU] + arrays.est_extra[0, R.CPU] == 1700


def test_target_aggregated_usage_selection():
    m = NodeMetric(
        node_name="n", aggregated_usage={95: {R.CPU: 5}},
        aggregated_duration=300.0,
    )
    assert target_aggregated_usage(m, None, 95) == {R.CPU: 5}
    assert target_aggregated_usage(m, 300.0, 95) == {R.CPU: 5}
    assert target_aggregated_usage(m, 600.0, 95) is None
    assert target_aggregated_usage(m, None, 90) is None
    assert target_aggregated_usage(NodeMetric(node_name="n"), None, 95) is None


def test_reporter_stamps_aggregated_duration():
    """The koordlet reporter records the aggregation window so the
    scheduler's duration selection has something to match against."""
    from koordinator_tpu.koordlet.metriccache import MetricCache, MetricKind
    from koordinator_tpu.koordlet.statesinformer import (
        NodeMetricReporter,
        StatesInformer,
    )
    from koordinator_tpu.manager.nodemetric import NodeMetricCollectPolicy

    mc = MetricCache()
    informer = StatesInformer()
    informer.set_node(
        NodeSpec("n0", allocatable={R.CPU: 8000, R.MEMORY: 16384})
    )
    informer.set_pods([])
    informer.set_collect_policy(NodeMetricCollectPolicy(300, 60))
    for t in range(10):
        mc.append(MetricKind.NODE_CPU_USAGE, None, float(t), 3000.0)
    m = NodeMetricReporter(mc, informer).report(now=10.0)
    assert m.aggregated_usage[95][R.CPU] == 3000
    assert m.aggregated_duration == 300.0
