"""Multi-tenant solver pool (ISSUE 11 / DESIGN §20): cross-tenant lane
batching that is bit-identical to every tenant solving solo, zero XLA
recompiles across tenant join/leave inside a shape bucket, per-tenant
epoch fencing, weighted-fair lane allocation, and fair-share shedding
isolation."""

import threading
import time

import numpy as np
import pytest

from koordinator_tpu.apis.extension import NUM_RESOURCES
from koordinator_tpu.apis.extension import ResourceName as R
from koordinator_tpu.service.admission import (
    LANE_BE,
    LANE_LS,
    AdmissionConfig,
    AdmissionGate,
    coalesce_key,
    solve_coalesced,
)
from koordinator_tpu.service.codec import SolveRequest
from koordinator_tpu.service.server import PlacementService, solve_from_request
from koordinator_tpu.service.tenancy import (
    DEFAULT_TENANT,
    TenantRegistry,
    allocate_fair_lanes,
    fair_share,
    lane_bucket,
    node_bucket,
    pod_bucket,
    request_tenant,
    shape_bucket_key,
    solve_tenant_lanes,
    tenant_wire_value,
)


def _world(n_nodes, seed):
    """One tenant's node/params groups — data differs per seed, schema
    (and node bucket, for nearby n_nodes) is shared."""
    rng = np.random.default_rng(seed)
    alloc = np.zeros((n_nodes, NUM_RESOURCES), np.int32)
    alloc[:, R.CPU] = 16000
    alloc[:, R.MEMORY] = 32768
    used = np.zeros_like(alloc)
    used[:, R.CPU] = rng.integers(0, 8000, n_nodes)
    used[:, R.MEMORY] = rng.integers(0, 16384, n_nodes)
    node = {
        "alloc": alloc,
        "used_req": used,
        "usage": np.zeros_like(alloc),
        "prod_usage": np.zeros_like(alloc),
        "est_extra": np.zeros_like(alloc),
        "prod_base": np.zeros_like(alloc),
        "metric_fresh": np.ones(n_nodes, bool),
        "schedulable": np.ones(n_nodes, bool),
    }
    weights = np.zeros(NUM_RESOURCES, np.int32)
    weights[R.CPU] = 1
    weights[R.MEMORY] = 1
    thresholds = np.zeros(NUM_RESOURCES, np.int32)
    thresholds[R.CPU] = 65
    thresholds[R.MEMORY] = 95
    params = {
        "weights": weights,
        "thresholds": thresholds,
        "prod_thresholds": np.zeros(NUM_RESOURCES, np.int32),
    }
    return node, params


def _pods(n_pods, seed):
    rng = np.random.default_rng(seed)
    req = np.zeros((n_pods, NUM_RESOURCES), np.int32)
    req[:, R.CPU] = rng.choice([500, 1000, 2000, 3000], n_pods)
    req[:, R.MEMORY] = rng.choice([256, 1024, 2048], n_pods)
    return {
        "req": req,
        "est": (req * 85) // 100,
        "is_prod": rng.uniform(size=n_pods) < 0.4,
        "is_daemonset": np.zeros(n_pods, bool),
    }


def _request(tenant=None, n_nodes=12, n_pods=5, seed=0, pod_seed=None,
             **over):
    node, params = _world(n_nodes, seed)
    req = SolveRequest(
        node=node, params=params,
        pods=_pods(n_pods, seed if pod_seed is None else pod_seed),
    )
    if tenant is not None:
        req.admission = dict(over.pop("admission", None) or {})
        req.admission["tenant"] = tenant_wire_value(tenant)
    for k, v in over.items():
        setattr(req, k, v)
    return req


def _stub_response(request):
    from koordinator_tpu.service.codec import SolveResponse

    n = int(np.asarray(request.pods["req"]).shape[0])
    return SolveResponse(assignments=np.zeros(n, np.int32))


class _BlockingSolve:
    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()
        self.order = []

    def __call__(self, request, config, node_cache):
        self.order.append(request)
        self.entered.set()
        assert self.release.wait(10), "test forgot to release the solve"
        return _stub_response(request)


def _solo_request(tag: int, tenant=None, **over):
    req = _request(tenant=tenant, n_pods=2 + tag % 3, pod_seed=tag, **over)
    req.quota = {"tag": np.asarray([tag])}
    return req


# -- identity / keys ---------------------------------------------------------

class TestTenantIdentity:
    def test_request_tenant_decode(self):
        assert request_tenant(_request()) == DEFAULT_TENANT
        assert request_tenant(_request(tenant="team-a")) == "team-a"
        # undecodable bytes fall back instead of raising
        req = _request()
        req.admission = {"tenant": np.asarray([0xFF, 0xFE], np.uint8)}
        assert request_tenant(req) == DEFAULT_TENANT
        # over-long ids are truncated, not refused
        long = _request(tenant="x" * 200)
        assert len(request_tenant(long)) == 64

    def test_tenant_id_sanitized_for_metric_labels(self):
        """Wire tenant ids land in Prometheus label values, and the
        exposition does no escaping — a quote/newline in a hostile id
        must be neutralized, never break the whole /metrics scrape."""
        evil = _request(tenant='a"} 1\nevil{x="y')
        got = request_tenant(evil)
        assert '"' not in got and "\n" not in got and "{" not in got
        assert got.startswith("a_")

    def test_tenant_cardinality_bounded(self):
        """A client cycling unique tenant ids cannot grow the gate's
        per-tenant accounting (stats rows, depth-gauge label sets)
        without bound: past the cap, unregistered newcomers fold into
        the overflow bucket."""
        from koordinator_tpu.service.tenancy import (
            MAX_TRACKED_TENANTS,
            OVERFLOW_TENANT,
        )

        def instant(request, config, node_cache):
            return _stub_response(request)

        gate = AdmissionGate(instant, AdmissionConfig(),
                             peer_count=lambda: 1)
        try:
            for i in range(MAX_TRACKED_TENANTS + 40):
                e = gate.submit(_solo_request(i, tenant=f"churner-{i}"),
                                None)
                assert e.wait(10).error == ""
            st = gate.stats()
            assert len(st["tenants"]) <= MAX_TRACKED_TENANTS + 1
            assert st["tenants"][OVERFLOW_TENANT]["requests"] >= 40
        finally:
            gate.shutdown(timeout=2)

    def test_cross_tenant_never_merges_bases(self):
        """THE isolation key property: byte-identical worlds from two
        tenants must NOT share a coalesce key (no cross-tenant base
        merge) while sharing a shape bucket (they may share a dispatch
        as separate lanes)."""
        a = _request(tenant="team-a", seed=3)
        b = _request(tenant="team-b", seed=3)
        assert coalesce_key(a) is not None
        assert coalesce_key(a) != coalesce_key(b)
        assert shape_bucket_key(a) == shape_bucket_key(b) is not None

    def test_shape_bucket_key_data_blind(self):
        # different data, same schema/buckets -> same key
        a = _request(tenant="a", n_nodes=9, seed=1)
        b = _request(tenant="b", n_nodes=10, seed=2)  # both in the 10-bucket
        assert node_bucket(9) == node_bucket(10)
        assert shape_bucket_key(a) == shape_bucket_key(b)
        # a different node bucket -> different key
        c = _request(tenant="c", n_nodes=200, seed=1)
        assert shape_bucket_key(a) != shape_bucket_key(c)
        # feature groups / delta never batch
        assert shape_bucket_key(_solo_request(1)) is None
        assert shape_bucket_key(
            _request(node_delta={"epoch": np.asarray(1, np.int64)})
        ) is None

    def test_shape_bucket_key_config_values(self):
        a = _request(seed=1)
        b = _request(seed=1)
        b.config = {"unroll": np.asarray(8, np.int64)}
        assert shape_bucket_key(a) != shape_bucket_key(b)

    def test_malformed_delta_rides_solo(self):
        """A delta patch missing row columns (or with mismatched row
        lengths) must never join a batch: batched, its staging failure
        would poison co-batched tenants' responses."""
        from koordinator_tpu.service.tenancy import delta_request

        node, params = _world(8, seed=1)
        good = {
            "idx": np.asarray([0], np.int32),
            "base_epoch": np.asarray(0, np.int64),
            "epoch": np.asarray(1, np.int64),
            **{f: np.asarray(node[f][:1]) for f in node},
        }
        req = SolveRequest(node={}, params=params, pods=_pods(3, 1),
                           node_delta=dict(good))
        assert delta_request(req)
        missing = dict(good)
        del missing["used_req"]
        req.node_delta = missing
        assert not delta_request(req)
        short = dict(good)
        short["alloc"] = np.asarray(node["alloc"][:0])
        req.node_delta = short
        assert not delta_request(req)


# -- the lane dispatch -------------------------------------------------------

class TestLaneDispatchIdentity:
    def test_smoke_lanes_bit_identical_to_solo(self):
        """THE pool contract: K tenants' plain requests — separate
        worlds, separate params, one shape bucket — solved as lanes of
        one dispatch split back bit-identical to each tenant solving
        alone (mixed node counts inside the bucket included)."""
        requests = [
            _request(tenant=f"t{i}", n_nodes=9 + (i % 2), n_pods=3 + i,
                     seed=10 + i, pod_seed=100 + i)
            for i in range(3)
        ]
        keys = {shape_bucket_key(r) for r in requests}
        assert len(keys) == 1 and None not in keys
        solo = [solve_from_request(r) for r in requests]
        lanes = solve_tenant_lanes(requests)
        full = solve_tenant_lanes(requests, want_state=True)
        for i, (want, got, gotf) in enumerate(zip(solo, lanes, full)):
            assert want.error == "" and got.error == ""
            assert got.node_used_req is None
            for field in ("assignments", "commit", "waiting", "rejected",
                          "raw_assign"):
                np.testing.assert_array_equal(
                    getattr(want, field), getattr(got, field),
                    err_msg=f"lane {i} field {field}",
                )
            np.testing.assert_array_equal(
                want.node_used_req, gotf.node_used_req,
                err_msg=f"lane {i} node_used_req",
            )

    def test_property_lanes_identical_under_mixed_churn(self):
        """Property sweep: random tenant counts, node counts (within
        and across buckets handled by the caller grouping), pod
        counts, and per-tick world mutation — every lane always equals
        its solo twin, tick after tick."""
        rng = np.random.default_rng(7)
        n_base = int(rng.integers(8, 14))
        worlds = {}
        for t in range(4):
            node, params = _world(n_base + int(rng.integers(0, 3)),
                                  seed=40 + t)
            worlds[f"t{t}"] = (node, params)
        for tick in range(4):
            requests = []
            for t, (node, params) in sorted(worlds.items()):
                # churn: mutate a couple of node rows in place, like a
                # front-end folding binds between ticks
                idx = rng.integers(0, node["alloc"].shape[0], 2)
                node["used_req"][idx, R.CPU] += int(rng.integers(0, 500))
                req = SolveRequest(
                    node={k: v.copy() for k, v in node.items()},
                    params=params,
                    pods=_pods(int(rng.integers(1, 9)),
                               seed=tick * 10 + int(t[1])),
                )
                req.admission = {"tenant": tenant_wire_value(t)}
                requests.append(req)
            want_state = tick % 2 == 0
            got = solve_tenant_lanes(requests, want_state=want_state)
            for i, r in enumerate(requests):
                want = solve_from_request(r)
                np.testing.assert_array_equal(
                    want.assignments, got[i].assignments,
                    err_msg=f"tick {tick} tenant {i}",
                )
                if want_state:
                    np.testing.assert_array_equal(
                        want.node_used_req, got[i].node_used_req,
                        err_msg=f"tick {tick} tenant {i} used_req",
                    )

    def test_zero_recompiles_on_join_leave_within_bucket(self, xla_compiles):
        """Satellite: a warmed multi-tenant dispatch performs ZERO XLA
        recompiles across tenant join/leave within a shape bucket —
        the lane count pads to its bucket, worlds to the node bucket,
        pods to the pod bucket, so K drifting inside the bucket reuses
        one compiled program."""
        from koordinator_tpu.service.tenancy import lane_shard_count

        shards = lane_shard_count()

        def reqs(k):
            return [
                _request(tenant=f"t{i}", n_nodes=9 + (i % 2),
                         n_pods=3 + (i % 4), seed=60 + i, pod_seed=i)
                for i in range(k)
            ]

        # warm at k=2: the lane bucket covers every k up to its width
        kb = lane_bucket(2, shards)
        solve_tenant_lanes(reqs(2))
        xla_compiles.clear()
        for k in (3, min(kb, 4), 2, min(kb, 5)):
            out = solve_tenant_lanes(reqs(k))
            assert len(out) == k
        assert xla_compiles == [], (
            "tenant join/leave inside the bucket recompiled: "
            + "; ".join(xla_compiles)
        )

    def test_lane_bucket_family(self):
        assert lane_bucket(1, 1) == 1
        assert lane_bucket(3, 1) == 4
        assert lane_bucket(5, 8) == 8
        assert lane_bucket(9, 8) == 16
        assert pod_bucket(5) == 8
        assert node_bucket(9) == 10

    def test_sixteen_plus_tenants_chunked_per_shard(self):
        """ROADMAP 2a / ISSUE 12 satellite: tenant counts past the
        lane-shard count are dispatched as per-shard-sized CHUNKS
        (one lane per device each) instead of one oversized stacked
        program — the shape that segfaulted the 8-virtual-device
        child under XLA:CPU mapping pressure. 18 tenants on 8 shards
        must split into 3 dispatches of <= 8 lanes, and every tenant
        stays bit-identical to its solo solve."""
        import jax

        from koordinator_tpu.service import tenancy
        from koordinator_tpu.service.tenancy import lane_shard_count

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-virtual-device mesh")
        shards = lane_shard_count()
        assert shards > 1, "pool mesh did not shard on this host"
        k = 2 * shards + 2  # strictly past the shard count, non-pow2
        requests = [
            _request(tenant=f"t{i}", n_nodes=9 + (i % 2), n_pods=3 + i % 4,
                     seed=200 + i, pod_seed=300 + i)
            for i in range(k)
        ]
        chunks = []
        real_chunk = tenancy._solve_lane_chunk

        def spy(pairs, config, want_state, shards_):
            chunks.append(len(pairs))
            return real_chunk(pairs, config, want_state, shards_)

        tenancy._solve_lane_chunk, saved = spy, real_chunk
        try:
            lanes = solve_tenant_lanes(requests)
        finally:
            tenancy._solve_lane_chunk = saved
        assert len(lanes) == k
        # split per shape bucket: every dispatch bounded by the shard
        # count (never one [18, N, ...] stack), FIFO order preserved
        assert len(chunks) == -(-k // shards)
        assert max(chunks) <= shards and sum(chunks) == k
        for i, r in enumerate(requests):
            want = solve_from_request(r)
            np.testing.assert_array_equal(
                want.assignments, lanes[i].assignments,
                err_msg=f"tenant {i} diverged under chunked dispatch",
            )


# -- weighted-fair allocation ------------------------------------------------

class TestFairness:
    def test_fair_share_proportional(self):
        shares = fair_share(100, {"a": 1.0, "b": 1.0, "c": 2.0})
        assert shares == {"a": 25, "b": 25, "c": 50}
        assert fair_share(2, {"a": 1.0, "b": 1.0, "c": 1.0})["a"] == 1

    def test_allocate_fair_lanes_weighted(self):
        cands = {
            "a": [("a", i) for i in range(8)],
            "b": [("b", i) for i in range(8)],
            "c": [("c", i) for i in range(8)],
        }
        weights = {"a": 1.0, "b": 1.0, "c": 2.0}
        take = allocate_fair_lanes(
            cands, weights.__getitem__, budget=8, room=10**9,
            pods_of=lambda e: 1,
        )
        by_tenant = {t: sum(1 for e in take if e[0] == t)
                     for t in ("a", "b", "c")}
        assert by_tenant == {"a": 2, "b": 2, "c": 4}
        # FIFO preserved inside each tenant
        assert [e[1] for e in take if e[0] == "c"] == [0, 1, 2, 3]

    def test_allocate_fair_lanes_respects_room(self):
        cands = {"a": [4, 4, 4], "b": [2, 2, 2]}
        take = allocate_fair_lanes(
            cands, lambda t: 1.0, budget=10, room=8,
            pods_of=lambda e: e,
        )
        assert sum(take) <= 8

    def test_allocate_preloaded_counts(self):
        # a batch head already granted to "a" shifts the next grants
        cands = {"a": ["a1"], "b": ["b1"]}
        take = allocate_fair_lanes(
            cands, lambda t: 1.0, budget=1, room=10,
            pods_of=lambda e: 1, preloaded={"a": 1},
        )
        assert take == ["b1"]

    def test_smoke_fair_share_shed_protects_other_tenant(self):
        """Isolation under overload: tenant B's queued work, within its
        fair share, can NOT be evicted by tenant A's higher-lane
        arrival — A is refused instead (pre-tenancy policy would have
        evicted B)."""
        solve = _BlockingSolve()
        gate = AdmissionGate(solve, AdmissionConfig(capacity=2))
        try:
            blocker = gate.submit(_solo_request(0, tenant="a"), None)
            assert solve.entered.wait(5)
            b_be = gate.submit(
                _solo_request(1, tenant="b",
                              admission={"lane": np.asarray(LANE_BE)}),
                None,
            )
            a_ls = gate.submit(
                _solo_request(2, tenant="a",
                              admission={"lane": np.asarray(LANE_LS)}),
                None,
            )
            # queue full (b_be + a_ls); A's LS arrival outranks B's BE
            # entry, but B (queued 1 = its share of 2) is protected
            a_more = gate.submit(
                _solo_request(3, tenant="a",
                              admission={"lane": np.asarray(LANE_LS)}),
                None,
            )
            refused = a_more.wait(5)
            assert refused.error.startswith("overloaded")
            solve.release.set()
            assert b_be.wait(10).error == ""
            assert a_ls.wait(10).error == ""
            st = gate.stats()
            assert st["tenants"]["a"]["shed_overloaded"] == 1
            assert st["tenants"]["b"]["shed_overloaded"] == 0
        finally:
            solve.release.set()
            gate.shutdown(timeout=2)

    def test_own_tenant_burst_sheds_itself(self):
        """A tenant flooding BE work sheds its OWN newest entries when
        a higher lane of the same tenant arrives — single-tenant
        behavior is unchanged by the fair-share rule."""
        solve = _BlockingSolve()
        gate = AdmissionGate(solve, AdmissionConfig(capacity=2))
        try:
            blocker = gate.submit(_solo_request(0, tenant="a"), None)
            assert solve.entered.wait(5)
            old = gate.submit(
                _solo_request(1, tenant="a",
                              admission={"lane": np.asarray(LANE_BE)}),
                None,
            )
            new = gate.submit(
                _solo_request(2, tenant="a",
                              admission={"lane": np.asarray(LANE_BE)}),
                None,
            )
            ls = gate.submit(
                _solo_request(3, tenant="a",
                              admission={"lane": np.asarray(LANE_LS)}),
                None,
            )
            shed = new.wait(5)
            assert shed is not None and shed.error.startswith("overloaded")
            solve.release.set()
            assert old.wait(10).error == ""
            assert ls.wait(10).error == ""
        finally:
            solve.release.set()
            gate.shutdown(timeout=2)


# -- the gate's cross-tenant batching ---------------------------------------

class TestGateLaneBatching:
    def test_smoke_cross_tenant_one_dispatch(self):
        """K tenants' same-bucket plain requests queued behind a
        blocker drain as ONE multi-base lane dispatch, each response
        bit-identical to that tenant solving solo."""
        solve = _BlockingSolve()
        gate = AdmissionGate(
            solve, AdmissionConfig(capacity=32, max_coalesce=8)
        )
        try:
            blocker = gate.submit(_solo_request(0), None)
            assert solve.entered.wait(5)
            requests = [
                _request(tenant=f"t{i}", n_nodes=9 + (i % 2), n_pods=3 + i,
                         seed=20 + i, pod_seed=70 + i)
                for i in range(4)
            ]
            entries = [gate.submit(r, None) for r in requests]
            solve.release.set()
            responses = [e.wait(30) for e in entries]
            for r, req in zip(responses, requests):
                assert r.error == ""
                np.testing.assert_array_equal(
                    r.assignments, solve_from_request(req).assignments
                )
            st = gate.stats()
            assert st["requests_total"] == 5
            assert st["batches_total"] == 2  # blocker + one lane batch
            assert st["lane_batches_total"] == 1
            assert st["lane_requests_total"] == 4
            for i in range(4):
                assert st["tenants"][f"t{i}"]["lane_batched"] == 1
        finally:
            solve.release.set()
            gate.shutdown(timeout=2)

    def test_tenant_lanes_off_no_cross_tenant_batch(self):
        solve = _BlockingSolve()
        gate = AdmissionGate(
            solve,
            AdmissionConfig(capacity=32, max_coalesce=8,
                            tenant_lanes=False, coalesce_window_s=0.0),
        )
        try:
            blocker = gate.submit(_solo_request(0), None)
            assert solve.entered.wait(5)
            entries = [
                gate.submit(_request(tenant=f"t{i}", seed=30 + i), None)
                for i in range(3)
            ]
            solve.release.set()
            for e in entries:
                assert e.wait(30).error == ""
            st = gate.stats()
            assert st["lane_batches_total"] == 0
            # 3 different tenants -> 3 separate dispatches
            assert st["batches_total"] == 4
        finally:
            solve.release.set()
            gate.shutdown(timeout=2)

    def test_same_tenant_still_coalesces_same_base(self):
        """Within one tenant, byte-identical bases keep the cheaper
        shared-base coalesce path (one staged world, K pod lanes)."""
        solve = _BlockingSolve()
        gate = AdmissionGate(
            solve, AdmissionConfig(capacity=32, max_coalesce=8)
        )
        try:
            blocker = gate.submit(_solo_request(0), None)
            assert solve.entered.wait(5)
            same = [
                _request(tenant="team-a", n_nodes=8, seed=9,
                         n_pods=3 + i, pod_seed=50 + i)
                for i in range(3)
            ]
            entries = [gate.submit(r, None) for r in same]
            solve.release.set()
            for e, req in zip(entries, same):
                got = e.wait(30)
                assert got.error == ""
                np.testing.assert_array_equal(
                    got.assignments, solve_from_request(req).assignments
                )
            st = gate.stats()
            assert st["coalesced_requests_total"] == 3
            assert st["lane_batches_total"] == 0
        finally:
            solve.release.set()
            gate.shutdown(timeout=2)


# -- per-tenant epoch fencing over the wire ---------------------------------

class TestPerTenantEpochs:
    def _full_request(self, tenant, node, params, pods, epoch):
        req = SolveRequest(
            node=node, params=params, pods=pods,
            node_delta={"epoch": np.asarray(epoch, np.int64)},
        )
        req.admission = {"tenant": tenant_wire_value(tenant)}
        return req

    def _delta_request(self, tenant, pods, idx, rows, base, epoch):
        delta = {
            "idx": np.asarray(idx, np.int32),
            "base_epoch": np.asarray(base, np.int64),
            "epoch": np.asarray(epoch, np.int64),
        }
        delta.update(rows)
        req = SolveRequest(node={}, params=self._params, pods=pods,
                           node_delta=delta)
        req.admission = {"tenant": tenant_wire_value(tenant)}
        return req

    def test_epoch_chains_independent_per_tenant(self, tmp_path):
        """Two tenants multiplexed over ONE connection keep independent
        delta bases: establishing/advancing tenant A's epoch chain
        neither advances nor invalidates tenant B's, mismatches are
        per-tenant, and every delta solve equals the equivalent full
        solve."""
        from koordinator_tpu.service.client import PlacementClient

        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        try:
            worlds = {
                "a": _world(10, seed=1),
                "b": _world(10, seed=2),
            }
            pods = _pods(4, seed=5)
            self._params = worlds["a"][1]
            with PlacementClient(addr, timeout=60.0) as client:
                # establish both tenants' bases at different epochs
                for tenant, epoch in (("a", 100), ("b", 200)):
                    node, params = worlds[tenant]
                    self._params = params
                    resp = client.solve(self._full_request(
                        tenant, node, params, pods, epoch
                    ))
                    assert resp.error == ""
                # tenant a advances 100 -> 101 with a row patch; b's
                # chain (still at 200) must be untouched
                node_a, params_a = worlds["a"]
                rows = {
                    f: np.asarray(node_a[f][:1])
                    for f in node_a
                }
                rows["used_req"] = rows["used_req"].copy()
                rows["used_req"][0, R.CPU] += 1000
                self._params = params_a
                resp = client.solve(self._delta_request(
                    "a", pods, [0], rows, base=100, epoch=101
                ))
                assert resp.error == ""
                # the delta solve equals the full solve of the patched
                # world (bit-identity of the per-tenant chain)
                node_patched = {k: v.copy() for k, v in node_a.items()}
                node_patched["used_req"][0, R.CPU] += 1000
                want = solve_from_request(SolveRequest(
                    node=node_patched, params=params_a, pods=pods
                ))
                np.testing.assert_array_equal(
                    resp.assignments, want.assignments
                )
                # a delta against tenant b's OLD epoch under tenant a's
                # id is a per-tenant mismatch (a holds 101, not 200)
                with pytest.raises(RuntimeError, match="delta-base-mismatch"):
                    client.solve(self._delta_request(
                        "a", pods, [0], rows, base=200, epoch=201
                    ))
                # tenant b's chain is still alive at 200
                node_b, params_b = worlds["b"]
                self._params = params_b
                resp_b = client.solve(self._delta_request(
                    "b", pods, [], {
                        f: np.asarray(node_b[f][:0]) for f in node_b
                    }, base=200, epoch=201
                ))
                assert resp_b.error == ""
        finally:
            service.stop()


class TestDeltaLaneBatching:
    """The steady-state serving shape: per-tick DELTA requests from K
    tenants — kilobytes of wire against per-tenant staged bases —
    batched as lanes of one dispatch."""

    def _establish(self, client, tenant, node, params, pods, epoch):
        req = SolveRequest(
            node=node, params=params, pods=pods,
            node_delta={"epoch": np.asarray(epoch, np.int64)},
        )
        req.admission = {"tenant": tenant_wire_value(tenant)}
        resp = client.solve(req)
        assert resp.error == ""

    def _delta(self, tenant, node, params, pods, idx, base, epoch):
        rows = {f: np.asarray(node[f][idx]) for f in node}
        delta = {
            "idx": np.asarray(idx, np.int32),
            "base_epoch": np.asarray(base, np.int64),
            "epoch": np.asarray(epoch, np.int64),
        }
        delta.update(rows)
        req = SolveRequest(node={}, params=params, pods=pods,
                           node_delta=delta)
        req.admission = {"tenant": tenant_wire_value(tenant)}
        return req

    def test_smoke_delta_ticks_batch_as_lanes(self, tmp_path, xla_compiles):
        """Three tenants' concurrent delta ticks — separate
        connections, separate staged bases, one shape bucket — drain as
        ONE lane batch, each lane bit-identical to the equivalent full
        solve of that tenant's patched world, with ZERO XLA recompiles
        on the steady-state rounds."""
        from koordinator_tpu.service.client import PlacementClient

        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        n_tenants = 3
        try:
            worlds = {i: _world(9 + (i % 2), seed=80 + i)
                      for i in range(n_tenants)}
            pods = _pods(4, seed=9)
            clients = [
                PlacementClient(addr, timeout=60.0)
                for _ in range(n_tenants)
            ]
            for i, c in enumerate(clients):
                node, params = worlds[i]
                self._establish(c, f"t{i}", node, params, pods, epoch=0)

            def tick(r):
                """One concurrent delta round; returns per-tenant
                responses (executor pinned so the ticks queue and
                batch)."""
                inner = service.gate._solve_fn
                hold = threading.Event()

                def slow(request, config, node_cache):
                    hold.wait(10)
                    return inner(request, config, node_cache)

                service.gate._solve_fn = slow
                try:
                    with PlacementClient(addr, timeout=60.0) as blocker:
                        result = {}

                        def block():
                            # an establish request rides solo (it is
                            # not a pure delta) yet real-solves cleanly
                            result["b"] = blocker.solve(_request(
                                tenant="blocker", seed=123,
                                node_delta={
                                    "epoch": np.asarray(0, np.int64)
                                },
                            ))

                        bt = threading.Thread(target=block)
                        bt.start()
                        time.sleep(0.2)  # the blocker pins the executor
                        responses = {}
                        errors = []

                        def send(i):
                            node, params = worlds[i]
                            idx = np.asarray([r % node["alloc"].shape[0]])
                            node["used_req"][idx, R.CPU] += 100 * (r + 1)
                            try:
                                responses[i] = clients[i].solve(self._delta(
                                    f"t{i}", node, params, pods, idx,
                                    base=r, epoch=r + 1,
                                ))
                            except Exception as e:  # noqa: BLE001
                                errors.append(e)

                        threads = [
                            threading.Thread(target=send, args=(i,))
                            for i in range(n_tenants)
                        ]
                        for t in threads:
                            t.start()
                        time.sleep(0.3)  # let every tick queue
                        hold.set()
                        for t in threads:
                            t.join(timeout=30)
                        bt.join(timeout=30)
                        assert not errors, errors
                        assert result["b"].error == ""
                        return responses
                finally:
                    service.gate._solve_fn = inner
                    hold.set()

            before = service.gate.stats()["lane_batches_total"]
            first = tick(0)
            # round 1 solved from freshly-established single-device
            # bases; its output hands every cache a mesh-resident lane
            # slice, so round 2 compiles the staging ops once more for
            # the settled sharding layout — rounds 3+ are the steady
            # state the zero-recompile contract covers
            tick(1)
            xla_compiles.clear()
            second = tick(2)  # steady state: zero recompiles
            assert xla_compiles == [], xla_compiles
            st = service.gate.stats()
            assert st["lane_batches_total"] >= before + 3
            # bit-identity: each batched delta tick equals the full
            # solve of that tenant's patched world
            for i in range(n_tenants):
                node, params = worlds[i]
                want = solve_from_request(SolveRequest(
                    node=node, params=params, pods=pods
                ))
                got = second[i]
                assert got.error == ""
                np.testing.assert_array_equal(
                    got.assignments, want.assignments, err_msg=f"tenant {i}"
                )
            # epochs advanced independently: a solo delta against the
            # latest epoch succeeds per tenant
            for i, c in enumerate(clients):
                node, params = worlds[i]
                resp = c.solve(self._delta(
                    f"t{i}", node, params, pods,
                    np.asarray([0]), base=3, epoch=4,
                ))
                assert resp.error == ""
            for c in clients:
                c.close()
        finally:
            service.stop()


class TestConnectionCacheBound:
    def test_connection_tenant_caches_lru_bounded(self, tmp_path):
        """One connection cycling tenant ids cannot pin unbounded
        staged worlds: past the per-connection cap the LRU tenant's
        base is evicted, and its next delta self-heals through the
        typed ``delta-base-mismatch`` re-establish path."""
        from koordinator_tpu.service.client import PlacementClient

        addr = str(tmp_path / "solver.sock")
        service = PlacementService(addr)
        service.start()
        try:
            node, params = _world(8, seed=5)
            pods = _pods(3, seed=5)

            def establish(client, tenant):
                req = SolveRequest(
                    node={k: v.copy() for k, v in node.items()},
                    params=params, pods=pods,
                    node_delta={"epoch": np.asarray(7, np.int64)},
                )
                req.admission = {"tenant": tenant_wire_value(tenant)}
                assert client.solve(req).error == ""

            def empty_delta(client, tenant):
                delta = {
                    "idx": np.asarray([], np.int32),
                    "base_epoch": np.asarray(7, np.int64),
                    "epoch": np.asarray(8, np.int64),
                    **{f: np.asarray(node[f][:0]) for f in node},
                }
                req = SolveRequest(node={}, params=params, pods=pods,
                                   node_delta=delta)
                req.admission = {"tenant": tenant_wire_value(tenant)}
                return client.solve(req)

            with PlacementClient(addr, timeout=60.0) as c:
                establish(c, "keeper")
                # churn far past the 32-tenant per-connection cap
                for i in range(40):
                    establish(c, f"churn-{i}")
                # the LRU victim ("keeper") lost its base: typed
                # mismatch, not silence and not someone else's state
                with pytest.raises(RuntimeError,
                                   match="delta-base-mismatch"):
                    empty_delta(c, "keeper")
                # a recent tenant's chain is intact
                assert empty_delta(c, "churn-39").error == ""
                # and keeper re-establishes cleanly (the self-heal)
                establish(c, "keeper")
                assert empty_delta(c, "keeper").error == ""
        finally:
            service.stop()


# -- status / metrics --------------------------------------------------------

class TestObservability:
    def test_status_and_metrics_keyed_by_tenant(self, tmp_path):
        from koordinator_tpu.metrics.components import SOLVER_METRICS
        from koordinator_tpu.service.client import PlacementClient

        addr = str(tmp_path / "solver.sock")
        registry = TenantRegistry({"team-a": 2.0})
        service = PlacementService(addr, tenants=registry)
        service.start()
        try:
            with PlacementClient(addr, timeout=60.0) as client:
                for tenant in ("team-a", "team-b"):
                    resp = client.solve(_request(tenant=tenant, seed=4))
                    assert resp.error == ""
            st = service.status()["admission"]
            assert set(st["tenants"]) >= {"team-a", "team-b"}
            assert st["tenants"]["team-a"]["dispatched"] == 1
            assert st["tenants"]["team-a"]["weight"] == 2.0
            assert st["tenants"]["team-b"]["weight"] == 1.0
            text = SOLVER_METRICS.gather()
            assert 'tenant="team-a"' in text
            assert 'tenant="team-b"' in text
        finally:
            service.stop()


class TestTenantWarmManifest:
    """ROADMAP 2b (ISSUE 14 satellite): the warm pool's keys for the
    lane dispatch are tenant SHAPE-BUCKET signatures — bucketed
    [K*,N*,...] axes, zero tenant data — so a persisted pool program
    warms tenants the sidecar has NEVER seen."""

    def test_new_tenant_first_bucket_restores_warm(self, tmp_path,
                                                   xla_compiles):
        from koordinator_tpu.obs.device import DEVICE_OBS
        from koordinator_tpu.service import tenancy
        from koordinator_tpu.service.warmpool import WARM_POOL, WarmPool

        # a fresh manifest slate: the process-global observatory's
        # bounded warm-aval ring may be full from earlier suites
        DEVICE_OBS.reset()
        store = str(tmp_path / "store")
        pool = WarmPool().configure(store, force_single_device=True)
        # the suite's forced 8-virtual-device mesh routes lane
        # dispatches through the SHARDED solver; the warm pool serves
        # single-device processes (the pooled-sidecar shape), so pin
        # the plain-vmap path for this test
        prev_mesh = tenancy._lane_mesh[0]
        tenancy._lane_mesh[0] = None
        try:
            pool.adopt(tenancy._jit_tenant,
                       tenancy._vmapped_tenant_solve, config_argpos=3)
            # tenants a/b: distinct worlds, one shape bucket
            # (node bucket 80, pod bucket 8, lane bucket 2)
            req_a = _request(tenant="a", n_nodes=70, n_pods=5, seed=1,
                             pod_seed=11)
            req_b = _request(tenant="b", n_nodes=75, n_pods=6, seed=2,
                             pod_seed=22)
            solve_tenant_lanes([req_a, req_b])  # cold: records the sig
            report = pool.persist()
            assert report["persisted"] >= 1
            assert pool.status()["manifest_programs"], report

            # "fresh process": a new pool over the same store — only
            # the program-keyed manifest connects the two
            pool2 = WarmPool().configure(store, force_single_device=True)
            pool2.adopt(tenancy._jit_tenant,
                        tenancy._vmapped_tenant_solve, config_argpos=3)
            restored = pool2.restore()
            assert restored["restored"] >= 1

            # tenants c/d: NEVER seen by any store writer, different
            # node counts — but inside the same shape bucket, so their
            # FIRST pooled solve must serve from the restored
            # executable with zero XLA compiles
            req_c = _request(tenant="c", n_nodes=66, n_pods=5, seed=3,
                             pod_seed=33)
            req_d = _request(tenant="d", n_nodes=80, n_pods=7, seed=4,
                             pod_seed=44)
            served_before = pool2.status()["served"]
            xla_compiles.clear()
            warm_out = solve_tenant_lanes([req_c, req_d])
            assert pool2.status()["served"] == served_before + 1
            assert xla_compiles == [], (
                "a new tenant's first bucket cold-compiled: "
                + "; ".join(xla_compiles)
            )

            # warm-served answers are bit-identical to the jit path
            tenancy._jit_tenant._warm = None
            ref_out = solve_tenant_lanes([req_c, req_d])
            for warm_r, ref_r in zip(warm_out, ref_out):
                np.testing.assert_array_equal(
                    warm_r.assignments, ref_r.assignments)
                np.testing.assert_array_equal(warm_r.commit, ref_r.commit)
        finally:
            tenancy._lane_mesh[0] = prev_mesh
            tenancy._jit_tenant._warm = WARM_POOL
